"""Interval abstract interpretation over jaxprs — the rangelint core.

The limb kernels emulate 381-bit field arithmetic in u64 lanes, and their
soundness rests on hand-reasoned magnitude bounds ("a column of 13 such
products plus carries stays under 2^64", ops/field_limbs.py). This module
machine-checks those bounds: every jaxpr variable gets an integer interval
``[lo, hi]`` (exact python-int arithmetic — never numpy wraparound),
seeded from the input domains the kernel registry declares, and propagated
through transfer functions for every primitive the registered kernels
emit. Intervals are ELEMENTWISE where it matters (lo/hi are object-dtype
numpy arrays broadcast to the aval shape) because limb arrays have
per-limb bounds — the top limb of a value < 2p is ~2^22, not 2^30, and
several proofs (the lazy ``sub`` lend path) need that precision.

Loops:

* ``lax.scan``/``while`` bodies are checked for an INDUCTIVE carry
  interval: seed with the init interval, run the body, require
  out ⊆ in; otherwise join-and-retry up to
  ``ETH_SPECS_ANALYSIS_RANGE_WIDEN_STEPS`` times (converging carry
  recurrences like ``carry = (col + carry) >> 30`` stabilize in 2-4
  joins).
* A scan whose carries will not stabilize but whose xs are CONCRETE
  (e.g. the Montgomery reduction's ``scan(red_step, t, arange(13))``) is
  UNROLLED with per-iteration concrete indices, making every
  dynamic_slice position static — this is what lets the analyzer
  reproduce the schoolbook-column proof exactly.
* Anything else widens the unstable carries to dtype-top, emits a
  ``widened`` event (a lane-overflow finding: the loop is unproven), and
  continues.

Sanctioned wraparound is declared per primitive site (``Wrap``): an
arithmetic result exceeding the dtype at a matched site is clamped into
``[0, min(hi, bound, dtype_max)]`` with no event — sha256's mod-2^32
adds, the borrow-chain subtractions whose transient underflow is
restored two ops later, and the lazy ``sub`` lend path are the sanctioned
sites. Everything else that can exceed the lane fires an ``overflow``
event and the value becomes TAINTED dtype-top; masking a tainted value
with a low-bit mask fires ``masked-taint`` (the mask-consistency rule:
masks may truncate only bits the interval proves are separately-carried
high bits, never an unproven magnitude).

No execution, no compile: the interpreter walks ``jax.make_jaxpr``
output only. Wrap sites are matched on ``file.py::function`` substrings
from each eqn's user traceback — line-free, stable across edits.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass

import numpy as np

__all__ = [
    "Domain",
    "Wrap",
    "Ival",
    "Event",
    "RangeInterp",
    "ival_binop",
    "widen_steps_default",
    "range_timeout_s",
]


def widen_steps_default() -> int:
    # 12, not a tight 4-6: sha256's compress rotates its 8 registers, so
    # a widened interval takes up to 8 joins to propagate around the
    # a..h ring before the carry tuple stabilizes (measured: the mesh
    # merkle body stabilizes at join 9; plain carry recurrences at 2-4)
    raw = os.environ.get("ETH_SPECS_ANALYSIS_RANGE_WIDEN_STEPS", "")
    try:
        return max(1, int(raw)) if raw else 12
    except ValueError:
        return 12


def range_timeout_s() -> float:
    raw = os.environ.get("ETH_SPECS_ANALYSIS_RANGE_TIMEOUT_S", "")
    try:
        return float(raw) if raw else 300.0
    except ValueError:
        return 300.0


# Scans that fail to stabilize are unrolled only up to this trip count
# (the Montgomery reductions are 9-15 steps; nothing legitimate is big).
UNROLL_MAX = 128
# concrete constants above this element count are not tracked (memory)
_CONC_MAX_ELEMS = 1 << 16


# ------------------------------------------------------------ declarations --


@dataclass(frozen=True)
class Domain:
    """Declared input domain for one argument (or one pytree leaf).

    ``hi`` is an inclusive elementwise bound: an int, or an ndarray
    broadcastable against the trailing axes of the leaf (a per-limb cap
    for limb arrays). ``corners`` are VALID concrete boundary members of
    the domain — ``(label, array-or-scalar)`` pairs broadcastable the
    same way — so the declaration that seeds the prover also generates
    the runtime boundary-value tests (tests/test_range_domains.py)."""

    name: str
    hi: object
    lo: object = 0
    corners: tuple = ()


@dataclass(frozen=True)
class Wrap:
    """One sanctioned-wraparound (or trusted-bound) primitive site.

    ``site`` is a ``"file.py::function"`` substring matched against the
    eqn's user traceback frames. On an out-of-dtype interval at a
    matched eqn the result is clamped to ``[0, min(hi, bound,
    dtype_max)]`` instead of firing lane-overflow — per-site, reviewed,
    never blanket. ``bound`` (optional) additionally caps the clamped
    hi: a declared invariant for sites whose true bound the interval
    cannot derive relationally (the borrow-restore add)."""

    prim: str
    site: str
    bound: int | None = None


# ------------------------------------------------------------------ domain --


def _is_arr(x) -> bool:
    return isinstance(x, np.ndarray)


def _amin(x) -> int:
    return int(x.min()) if _is_arr(x) else int(x)


def _amax(x) -> int:
    return int(x.max()) if _is_arr(x) else int(x)


def _obj(x, shape):
    """Broadcast an int or array bound to ``shape`` as an object ndarray
    of python ints (NEVER numpy scalars — they wrap)."""
    if _is_arr(x):
        a = x if x.dtype == object else x.astype(object)
    else:
        a = np.asarray(int(x), dtype=object)
    return np.broadcast_to(a, shape)


class Ival:
    """Interval [lo, hi] of python ints; lo/hi are an int (uniform over
    the array) or an object ndarray broadcast to the var's shape.
    ``tainted`` marks values whose magnitude the analysis does NOT know
    (widened loops, unhandled primitives, unsanctioned overflow)."""

    __slots__ = ("lo", "hi", "tainted")

    def __init__(self, lo, hi, tainted: bool = False):
        # 0-d arrays collapse to ints (uniform): scalar vars stay cheap
        # and never hit array-vs-scalar broadcast mismatches
        if _is_arr(lo) and lo.ndim == 0:
            lo = int(lo[()])
        if _is_arr(hi) and hi.ndim == 0:
            hi = int(hi[()])
        self.lo = lo
        self.hi = hi
        self.tainted = tainted

    def __repr__(self):
        return f"Ival[{_amin(self.lo)}, {_amax(self.hi)}{'T' if self.tainted else ''}]"

    def broadcast(self, shape) -> "Ival":
        try:
            lo = _obj(self.lo, shape) if _is_arr(self.lo) else self.lo
            hi = _obj(self.hi, shape) if _is_arr(self.hi) else self.hi
            return Ival(lo, hi, self.tainted)
        except ValueError:
            # shape mismatch (e.g. per-shard vs global): collapse, stay sound
            return Ival(_amin(self.lo), _amax(self.hi), self.tainted)


def _binmap(a, b, f):
    """Elementwise f over int-or-ndarray bounds (object arrays hold
    python ints, so arithmetic is exact arbitrary precision)."""
    if not _is_arr(a) and not _is_arr(b):
        return f(int(a), int(b))
    return f(np.asarray(a, dtype=object), np.asarray(b, dtype=object))


def _unimap(a, f):
    if not _is_arr(a):
        return f(int(a))
    return np.frompyfunc(f, 1, 1)(np.asarray(a, dtype=object))


def ival_join(a: Ival, b: Ival) -> Ival:
    return Ival(
        _binmap(a.lo, b.lo, lambda x, y: np.minimum(x, y) if _is_arr(x) else min(x, y)),
        _binmap(a.hi, b.hi, lambda x, y: np.maximum(x, y) if _is_arr(x) else max(x, y)),
        a.tainted or b.tainted,
    )


def ival_leq(a: Ival, b: Ival) -> bool:
    """a ⊆ b (a contained in b) — the inductiveness check."""
    if a.tainted and not b.tainted:
        return False
    lo_ok = _binmap(a.lo, b.lo, lambda x, y: x >= y)
    hi_ok = _binmap(a.hi, b.hi, lambda x, y: x <= y)
    lo_ok = bool(np.all(lo_ok)) if _is_arr(lo_ok) else bool(lo_ok)
    hi_ok = bool(np.all(hi_ok)) if _is_arr(hi_ok) else bool(hi_ok)
    return lo_ok and hi_ok


def _dtype_range(dtype) -> tuple[int, int] | None:
    """(min, max) for integer/bool dtypes; None for floats (unchecked)."""
    kind = dtype.kind
    if kind == "b":
        return (0, 1)
    if kind == "u":
        return (0, (1 << (dtype.itemsize * 8)) - 1)
    if kind == "i":
        bits = dtype.itemsize * 8
        return (-(1 << (bits - 1)), (1 << (bits - 1)) - 1)
    return None


def _top(dtype, tainted: bool = False) -> Ival:
    rng = _dtype_range(np.dtype(dtype))
    if rng is None:
        return Ival(0, 0, tainted)  # floats: not range-checked
    return Ival(rng[0], rng[1], tainted)


def _conc_to_obj(arr: np.ndarray) -> np.ndarray:
    """Concrete numpy values -> object array of python ints (bool->int)."""
    if arr.dtype.kind == "b":
        arr = arr.astype(np.int64)
    if arr.dtype.kind == "f":
        # float constants are not range-relevant; track magnitude 0
        return np.zeros(arr.shape, dtype=object)
    return np.frompyfunc(int, 1, 1)(arr) if arr.ndim else np.asarray(int(arr), object)


def ival_binop(prim: str, a: Ival, b: Ival, dtype=None):
    """The pure add/sub/mul/shift/and/or/xor transfer functions, exposed
    for unit tests. Returns the RAW (unclamped) interval — overflow
    classification against ``dtype`` happens in the interpreter."""
    if prim == "add":
        return Ival(_binmap(a.lo, b.lo, lambda x, y: x + y),
                    _binmap(a.hi, b.hi, lambda x, y: x + y),
                    a.tainted or b.tainted)
    if prim == "sub":
        return Ival(_binmap(a.lo, b.hi, lambda x, y: x - y),
                    _binmap(a.hi, b.lo, lambda x, y: x - y),
                    a.tainted or b.tainted)
    if prim == "mul":
        if _amin(a.lo) >= 0 and _amin(b.lo) >= 0:
            return Ival(_binmap(a.lo, b.lo, lambda x, y: x * y),
                        _binmap(a.hi, b.hi, lambda x, y: x * y),
                        a.tainted or b.tainted)
        cs = [_binmap(x, y, lambda p, q: p * q)
              for x in (a.lo, a.hi) for y in (b.lo, b.hi)]
        lo = cs[0]
        hi = cs[0]
        for c in cs[1:]:
            lo = _binmap(lo, c, lambda x, y: np.minimum(x, y) if _is_arr(x) else min(x, y))
            hi = _binmap(hi, c, lambda x, y: np.maximum(x, y) if _is_arr(x) else max(x, y))
        return Ival(lo, hi, a.tainted or b.tainted)
    if prim == "and":
        if _amin(a.lo) >= 0 and _amin(b.lo) >= 0:
            return Ival(0, _binmap(a.hi, b.hi, lambda x, y: np.minimum(x, y) if _is_arr(x) else min(x, y)),
                        a.tainted or b.tainted)
        return _top(np.dtype(dtype) if dtype is not None else np.dtype(np.int64),
                    a.tainted or b.tainted)
    if prim in ("or", "xor"):
        if _amin(a.lo) >= 0 and _amin(b.lo) >= 0:
            # x|y <= x+y and x^y <= x+y for nonneg; never exceeds dtype
            hi = _binmap(a.hi, b.hi, lambda x, y: x + y)
            if dtype is not None:
                rng = _dtype_range(np.dtype(dtype))
                if rng is not None:
                    hi = _binmap(hi, rng[1], lambda x, y: np.minimum(x, y) if _is_arr(x) else min(x, y))
            lo = 0 if prim == "xor" else _binmap(
                a.lo, b.lo, lambda x, y: np.maximum(x, y) if _is_arr(x) else max(x, y))
            return Ival(lo, hi, a.tainted or b.tainted)
        return _top(np.dtype(dtype) if dtype is not None else np.dtype(np.int64),
                    a.tainted or b.tainted)
    if prim == "shift_right_logical":
        smin, smax = max(_amin(b.lo), 0), max(_amax(b.hi), 0)
        if _amin(a.lo) < 0:
            # logical shift reinterprets the bit pattern: a negative input
            # becomes (x mod 2^bits) >> s, a huge positive — cover it
            bits = 8 * np.dtype(dtype).itemsize if dtype is not None else 64
            return Ival(0, ((1 << bits) - 1) >> smin, a.tainted)
        return Ival(_unimap(a.lo, lambda x: x >> smax),
                    _unimap(a.hi, lambda x: x >> smin),
                    a.tainted)
    if prim == "shift_right_arithmetic":
        smin, smax = max(_amin(b.lo), 0), max(_amax(b.hi), 0)
        # negative values move TOWARD zero as the shift grows, so the
        # extreme shift amount flips with the operand's sign
        return Ival(_unimap(a.lo, lambda x: x >> (smin if x < 0 else smax)),
                    _unimap(a.hi, lambda x: x >> (smax if x < 0 else smin)),
                    a.tainted)
    if prim == "shift_left":
        bits = 8 * np.dtype(dtype).itemsize if dtype is not None else 64
        smin = min(max(_amin(b.lo), 0), bits + 8)
        smax = min(max(_amax(b.hi), 0), bits + 8)
        # negative values move AWAY from zero as the shift grows
        return Ival(_unimap(a.lo, lambda x: x << (smax if x < 0 else smin)),
                    _unimap(a.hi, lambda x: x << (smin if x < 0 else smax)),
                    a.tainted or b.tainted)
    if prim == "max":
        return Ival(_binmap(a.lo, b.lo, lambda x, y: np.maximum(x, y) if _is_arr(x) else max(x, y)),
                    _binmap(a.hi, b.hi, lambda x, y: np.maximum(x, y) if _is_arr(x) else max(x, y)),
                    a.tainted or b.tainted)
    if prim == "min":
        return Ival(_binmap(a.lo, b.lo, lambda x, y: np.minimum(x, y) if _is_arr(x) else min(x, y)),
                    _binmap(a.hi, b.hi, lambda x, y: np.minimum(x, y) if _is_arr(x) else min(x, y)),
                    a.tainted or b.tainted)
    if prim == "div":
        amag = max(abs(_amin(a.lo)), abs(_amax(a.hi)))
        if _amin(b.lo) < 0 or _amin(a.lo) < 0:
            # a possibly-negative divisor flips the quotient's sign
            # (x // -1 = -x); |b| >= 1 bounds the magnitude by |a|
            return Ival(-amag, amag, a.tainted or b.tainted)
        dlo = max(_amin(b.lo), 1)
        dhi = max(_amax(b.hi), 1)
        return Ival(_unimap(a.lo, lambda x: x // dhi),
                    _unimap(a.hi, lambda x: x // dlo), a.tainted or b.tainted)
    if prim == "rem":
        if _amin(a.lo) >= 0 and _amin(b.lo) >= 0:
            dhi = max(_amax(b.hi), 1)
            hi = _binmap(a.hi, dhi - 1, lambda x, y: np.minimum(x, y) if _is_arr(x) else min(x, y))
            return Ival(0, hi, a.tainted or b.tainted)
        # |rem| < |divisor| (sign follows the dividend) and |rem| <= |a|
        dmag = max(abs(_amin(b.lo)), abs(_amax(b.hi)), 1)
        amag = max(abs(_amin(a.lo)), abs(_amax(a.hi)))
        m = min(dmag - 1, amag)
        return Ival(-m, m, a.tainted or b.tainted)
    raise KeyError(prim)


# ------------------------------------------------------------------ events --


@dataclass(frozen=True)
class Event:
    kind: str  # "overflow" | "masked-taint" | "widened" | "unhandled"
    prim: str
    site: str  # innermost project frame "file.py::function"
    message: str

    @property
    def detail(self) -> str:
        return f"{self.prim}@{self.site}"


class AnalysisTimeout(Exception):
    """Per-variant budget exhausted — the kernel remains unproven."""


# ------------------------------------------------------------- interpreter --


class RangeInterp:
    """One interpreter per (kernel family, variant): carries the wrap
    declarations, the widening budget and the deadline."""

    def __init__(self, wraps: tuple = (), widen_steps: int | None = None,
                 deadline: float | None = None):
        self.wraps = tuple(wraps)
        self.widen_steps = widen_steps or widen_steps_default()
        self.deadline = deadline
        self.events: list[Event] = []
        self._muted = 0
        self.stats = {"eqns": 0, "unrolled_scans": 0, "widened_loops": 0,
                      "wrap_hits": 0, "unhandled": {}}
        self._frame_cache: dict[int, tuple[str, ...]] = {}

    # -- events ------------------------------------------------------------

    def _emit(self, kind, prim, frames, message):
        if self._muted:
            return
        site = frames[0] if frames else "?"
        self.events.append(Event(kind, prim, site, message))

    class _Mute:
        def __init__(self, interp):
            self.interp = interp

        def __enter__(self):
            self.interp._muted += 1

        def __exit__(self, *exc):
            self.interp._muted -= 1

    def _mute(self):
        return RangeInterp._Mute(self)

    # -- source info -------------------------------------------------------

    def _frames(self, eqn) -> tuple[str, ...]:
        si = eqn.source_info
        tb = getattr(si, "traceback", None)
        key = id(tb)
        hit = self._frame_cache.get(key)
        if hit is not None:
            return hit
        frames: list[str] = []
        try:
            from jax._src import source_info_util

            for fr in source_info_util.user_frames(si):
                base = os.path.basename(fr.file_name)
                frames.append(f"{base}::{fr.function_name}")
        except Exception:
            pass
        out = tuple(frames)
        self._frame_cache[key] = out
        return out

    def _wrap_for(self, prim: str, frames) -> Wrap | None:
        for w in self.wraps:
            if w.prim != prim:
                continue
            for fr in frames:
                if w.site in fr:
                    return w
        return None

    # -- entry -------------------------------------------------------------

    def run(self, closed, in_ivals: list[Ival]) -> list[Ival]:
        """Analyze a ClosedJaxpr given intervals for its flat invars."""
        jaxpr = closed.jaxpr
        env: dict = {}
        conc: dict = {}
        for cv, cval in zip(jaxpr.constvars, closed.consts):
            arr = np.asarray(cval)
            if arr.dtype.kind in "iub" and arr.size <= _CONC_MAX_ELEMS:
                o = _conc_to_obj(arr)
                env[cv] = Ival(o, o)
                conc[cv] = arr
            else:
                env[cv] = self._const_ival(arr)
        if len(in_ivals) != len(jaxpr.invars):
            raise ValueError(
                f"domain seed mismatch: {len(in_ivals)} intervals for "
                f"{len(jaxpr.invars)} jaxpr inputs"
            )
        for v, iv in zip(jaxpr.invars, in_ivals):
            env[v] = self._fit(iv, v)
        self._run_eqns(jaxpr, env, conc)
        return [self._read(env, conc, v) for v in jaxpr.outvars]

    def _const_ival(self, arr: np.ndarray) -> Ival:
        if arr.dtype.kind in "iub":
            if arr.size <= _CONC_MAX_ELEMS:
                o = _conc_to_obj(arr)
                return Ival(o, o)
            return Ival(int(arr.min()), int(arr.max()))
        return Ival(0, 0)

    def _read(self, env, conc, v) -> Ival:
        from jax._src.core import Literal

        if isinstance(v, Literal):
            arr = np.asarray(v.val)
            return self._const_ival(arr)
        iv = env.get(v)
        if iv is None:
            return _top(v.aval.dtype, tainted=True)
        return iv

    def _read_conc(self, env, conc, v):
        from jax._src.core import Literal

        if isinstance(v, Literal):
            arr = np.asarray(v.val)
            if arr.dtype.kind in "iub":
                return arr
            return None
        hit = conc.get(v)
        if hit is not None:
            return hit
        # an EXACT interval (lo == hi elementwise) IS a concrete value —
        # this is how arange/iota constants survive pjit/scan/while
        # boundaries and let failed-widening scans unroll precisely
        iv = env.get(v)
        if iv is None or iv.tainted:
            return None
        dt = np.dtype(v.aval.dtype)
        if dt.kind not in "iub":
            return None
        shape = tuple(v.aval.shape)
        if math.prod(shape) > _CONC_MAX_ELEMS:
            return None
        lo, hi = iv.lo, iv.hi
        if not _is_arr(lo) and not _is_arr(hi):
            if int(lo) != int(hi):
                return None
            vals = _obj(lo, shape)
        else:
            lo_b, hi_b = _obj(lo, shape), _obj(hi, shape)
            if lo_b is not hi_b and not np.array_equal(lo_b, hi_b):
                return None
            vals = lo_b
        try:
            out = vals.astype(dt) if shape else np.asarray(int(_amin(lo)), dtype=dt)
        except (OverflowError, TypeError, ValueError):
            return None
        conc[v] = out
        return out

    # -- main eqn loop -----------------------------------------------------

    def _run_eqns(self, jaxpr, env, conc):
        for eqn in jaxpr.eqns:
            self.stats["eqns"] += 1
            if self.deadline is not None and self.stats["eqns"] % 256 == 0:
                if time.monotonic() > self.deadline:
                    raise AnalysisTimeout()
            self._eval_eqn(eqn, env, conc)

    def _eval_eqn(self, eqn, env, conc):
        prim = eqn.primitive.name
        ins = [self._read(env, conc, v) for v in eqn.invars]
        cins = [self._read_conc(env, conc, v) for v in eqn.invars]
        handler = _HANDLERS.get(prim)
        if handler is None:
            for ov in eqn.outvars:
                env[ov] = _top(ov.aval.dtype, tainted=True)
            self.stats["unhandled"][prim] = self.stats["unhandled"].get(prim, 0) + 1
            self._emit("unhandled", prim, self._frames(eqn),
                       f"no transfer function for primitive {prim}")
            return
        outs, couts = handler(self, eqn, ins, cins)
        for i, ov in enumerate(eqn.outvars):
            iv = outs[i] if i < len(outs) else _top(ov.aval.dtype, tainted=True)
            env[ov] = self._fit(iv, ov)
        if couts:
            for i, ov in enumerate(eqn.outvars):
                c = couts[i] if i < len(couts) else None
                if c is not None and c.size <= _CONC_MAX_ELEMS:
                    conc[ov] = c

    @staticmethod
    def _fit(iv: Ival, var) -> Ival:
        """Every env entry's bound arrays must broadcast against the
        var's aval shape; anything else collapses to its uniform bounds
        (always sound — at worst elementwise precision is lost)."""
        if not _is_arr(iv.lo) and not _is_arr(iv.hi):
            return iv
        shape = tuple(var.aval.shape)
        try:
            if _is_arr(iv.lo):
                np.broadcast_to(iv.lo, shape)
            if _is_arr(iv.hi):
                np.broadcast_to(iv.hi, shape)
            return iv
        except ValueError:
            return Ival(_amin(iv.lo), _amax(iv.hi), iv.tainted)

    # -- overflow classification -------------------------------------------

    def _finish_arith(self, eqn, iv: Ival, *, prim=None, aval=None) -> Ival:
        """Classify an arithmetic result against the output dtype:
        in-range passes through; out-of-range at a declared Wrap site is
        clamped (sanctioned); anything else fires ``overflow`` and the
        value becomes tainted dtype-top. Signed counters widen silently
        (lane-overflow is an unsigned-lane rule; i32 loop counters are
        jaxlint's x64-drift territory)."""
        aval = eqn.outvars[0].aval if aval is None else aval
        dt = np.dtype(aval.dtype)
        rng = _dtype_range(dt)
        if rng is None:
            return iv
        dmin, dmax = rng
        lo_min, hi_max = _amin(iv.lo), _amax(iv.hi)
        if lo_min >= dmin and hi_max <= dmax:
            return iv
        if dt.kind == "i" or dt.kind == "b":
            return Ival(dmin, dmax, iv.tainted)
        prim = prim or eqn.primitive.name
        frames = self._frames(eqn)
        w = self._wrap_for(prim, frames)
        if w is not None:
            self.stats["wrap_hits"] += 1
            cap = dmax if w.bound is None else min(w.bound, dmax)
            if _is_arr(iv.lo) or _is_arr(iv.hi):
                shape = tuple(aval.shape)
                lo_b, hi_b = _obj(iv.lo, shape), _obj(iv.hi, shape)
                inr = np.frompyfunc(lambda l, h: 0 <= l and h <= cap, 2, 1)(
                    lo_b, hi_b
                ).astype(bool)
                return Ival(np.where(inr, lo_b, 0), np.where(inr, hi_b, cap),
                            iv.tainted)
            if 0 <= lo_min and hi_max <= cap:
                return Ival(iv.lo, iv.hi, iv.tainted)
            return Ival(0, cap, iv.tainted)
        kindmsg = []
        if hi_max > dmax:
            kindmsg.append(f"hi {hi_max} > {dt.name} max {dmax}")
        if lo_min < dmin:
            kindmsg.append(f"lo {lo_min} underflows {dt.name}")
        self._emit(
            "overflow", prim, frames,
            f"{prim} interval [{lo_min}, {hi_max}] exceeds {dt.name} "
            f"({'; '.join(kindmsg)}) — a silent lane wraparound, not an "
            "exception; annotate the site `wraps` only if the wrap is the "
            "algorithm (sha256 mod-2^32) or declare a tighter input domain",
        )
        return Ival(dmin, dmax, True)


# ----------------------------------------------------------- prim handlers --
# Each handler returns (out_ivals, out_concs | None). ``self`` is the
# interpreter (handlers are plain functions registered in _HANDLERS).


def _shape_of(v):
    return tuple(v.aval.shape)


def _h_arith(self: RangeInterp, eqn, ins, cins):
    prim = eqn.primitive.name
    if prim == "add_any":  # transpose-of-fan-out accumulation IS an add
        prim = "add"
    out_dt = eqn.outvars[0].aval.dtype
    iv = ival_binop(prim, ins[0], ins[1], dtype=out_dt)
    iv = self._finish_arith(eqn, iv)
    c = None
    if prim in ("add", "sub", "mul") and cins[0] is not None and cins[1] is not None:
        with np.errstate(over="ignore"):
            c = {"add": np.add, "sub": np.subtract, "mul": np.multiply}[prim](
                cins[0], cins[1]
            )
        # only keep concrete results that the interval confirms exact
        if _amin(iv.lo) < 0 or iv.tainted:
            c = None
    return [iv], [c]


def _h_and(self: RangeInterp, eqn, ins, cins):
    out_dt = eqn.outvars[0].aval.dtype
    iv = ival_binop("and", ins[0], ins[1], dtype=out_dt)
    # mask-consistency: AND-ing a TAINTED value with a low-bit constant
    # mask pretends to "extract a limb" of a magnitude nothing proved
    for side, other in ((0, 1), (1, 0)):
        mask_iv = ins[side]
        if _is_arr(mask_iv.lo) or _is_arr(mask_iv.hi):
            # a broadcast constant mask reaches the eqn with an exact
            # elementwise interval — a uniform one is still THE mask,
            # and skipping it would let the taint hide under it
            lo_a = np.asarray(mask_iv.lo, dtype=object).ravel()
            hi_a = np.asarray(mask_iv.hi, dtype=object).ravel()
            if lo_a.size == 0 or hi_a.size == 0:
                continue
            if not (np.all(lo_a == lo_a[0]) and np.all(hi_a == hi_a[0])):
                continue
            mlo, mhi = int(lo_a[0]), int(hi_a[0])
        else:
            mlo, mhi = int(mask_iv.lo), int(mask_iv.hi)
        if mlo != mhi:
            continue
        m = mhi
        if m <= 0 or (m & (m + 1)) != 0:
            continue  # not a low-bit mask 2^k - 1
        if ins[other].tainted:
            self._emit(
                "masked-taint", "and", self._frames(eqn),
                f"AND with mask {hex(m)} applied to a value whose interval "
                "was widened to dtype-top — the mask truncates bits the "
                "analysis cannot prove are separately-propagated carries "
                "(an overflow upstream may be hiding under this mask)",
            )
    return [iv], None


def _h_shift(self: RangeInterp, eqn, ins, cins):
    prim = eqn.primitive.name
    out_dt = eqn.outvars[0].aval.dtype
    iv = ival_binop(prim, ins[0], ins[1], dtype=out_dt)
    if prim == "shift_left":
        iv = self._finish_arith(eqn, iv)
    return [iv], None


def _h_pass_binop(self: RangeInterp, eqn, ins, cins):
    prim = eqn.primitive.name
    out_dt = eqn.outvars[0].aval.dtype
    return [ival_binop(prim, ins[0], ins[1], dtype=out_dt)], None


def _h_cmp(self: RangeInterp, eqn, ins, cins):
    """Comparisons fold to an exact 0/1 when the intervals decide them —
    jax's negative-index normalization (``i if i >= 0 else i + n``)
    routes dynamic_slice starts through lt/select_n, and folding the
    predicate is what keeps concrete scan indices concrete."""
    if cins[0] is not None and cins[1] is not None:
        op = {"eq": np.equal, "ne": np.not_equal, "lt": np.less,
              "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal}
        c = op[eqn.primitive.name](cins[0], cins[1])
        o = _conc_to_obj(c)
        return [Ival(o, o)], [c]
    a, b = ins
    alo, ahi, blo, bhi = _amin(a.lo), _amax(a.hi), _amin(b.lo), _amax(b.hi)
    prim = eqn.primitive.name
    verdict = None
    if prim == "lt":
        verdict = 1 if ahi < blo else (0 if alo >= bhi else None)
    elif prim == "le":
        verdict = 1 if ahi <= blo else (0 if alo > bhi else None)
    elif prim == "gt":
        verdict = 1 if alo > bhi else (0 if ahi <= blo else None)
    elif prim == "ge":
        verdict = 1 if alo >= bhi else (0 if ahi < blo else None)
    elif prim == "eq":
        verdict = 0 if (ahi < blo or alo > bhi) else (
            1 if alo == ahi == blo == bhi else None
        )
    elif prim == "ne":
        verdict = 1 if (ahi < blo or alo > bhi) else (
            0 if alo == ahi == blo == bhi else None
        )
    if verdict is not None:
        return [Ival(verdict, verdict)], None
    return [Ival(0, 1)], None


def _h_not(self: RangeInterp, eqn, ins, cins):
    dt = np.dtype(eqn.outvars[0].aval.dtype)
    rng = _dtype_range(dt)
    if rng is None:
        return [Ival(0, 0)], None
    dmin, dmax = rng
    a = ins[0]
    if dt.kind in "ub" and _amin(a.lo) >= 0:
        return [Ival(_binmap(a.hi, dmax, lambda x, y: y - x),
                     _binmap(a.lo, dmax, lambda x, y: y - x), a.tainted)], None
    return [Ival(dmin, dmax, a.tainted)], None


def _h_neg(self: RangeInterp, eqn, ins, cins):
    a = ins[0]
    iv = Ival(_unimap(a.hi, lambda x: -x), _unimap(a.lo, lambda x: -x), a.tainted)
    return [self._finish_arith(eqn, iv)], None


def _h_clamp(self: RangeInterp, eqn, ins, cins):
    mn, x, mx = ins
    lo = min(max(_amin(x.lo), _amin(mn.lo)), _amin(mx.lo))
    hi = min(max(_amax(x.hi), _amax(mn.hi)), _amax(mx.hi))
    return [Ival(lo, hi, x.tainted)], None


def _h_sign(self: RangeInterp, eqn, ins, cins):
    dt = np.dtype(eqn.outvars[0].aval.dtype)
    if dt.kind == "u" or _amin(ins[0].lo) >= 0:
        return [Ival(0, 1, ins[0].tainted)], None
    return [Ival(-1, 1, ins[0].tainted)], None


def _h_select(self: RangeInterp, eqn, ins, cins):
    pred = ins[0]
    cases = ins[1:]
    # an exact uniform predicate picks ONE case — interval AND concrete
    # survive (the folded negative-index select around dynamic_slice)
    if (
        not _is_arr(pred.lo)
        and not _is_arr(pred.hi)
        and not pred.tainted
        and int(pred.lo) == int(pred.hi)
        and 0 <= int(pred.lo) < len(cases)
    ):
        k = int(pred.lo)
        return [cases[k]], [cins[1 + k]]
    out = cases[0].broadcast(_shape_of(eqn.outvars[0])) if _is_arr(cases[0].lo) else cases[0]
    for c in cases[1:]:
        out = ival_join(out, c)
    return [out], None


def _h_identity(self: RangeInterp, eqn, ins, cins):
    return [ins[0]], [cins[0]]


def _h_convert(self: RangeInterp, eqn, ins, cins):
    dt = np.dtype(eqn.outvars[0].aval.dtype)
    rng = _dtype_range(dt)
    a = ins[0]
    if rng is None:
        return [Ival(0, 0)], None
    dmin, dmax = rng
    if dt.kind == "b":
        return [Ival(0, 1, a.tainted)], None
    src_dt = np.dtype(eqn.invars[0].aval.dtype)
    if src_dt.kind == "f":
        return [Ival(dmin, dmax, a.tainted)], None
    lo_min, hi_max = _amin(a.lo), _amax(a.hi)
    if lo_min >= dmin and hi_max <= dmax:
        c = None
        if cins[0] is not None and dt.kind in "iub":
            c = cins[0].astype(dt)
        return [Ival(a.lo, a.hi, a.tainted)], [c]
    if src_dt.kind == "i" and dt.kind == "u" and lo_min < 0 and hi_max <= dmax:
        # signed->unsigned reinterpretation of a possibly-negative value
        # (two's complement, defined) — signed values are outside the
        # unsigned-lane overflow rule, so widen silently
        return [Ival(0, dmax, a.tainted)], None
    # narrowing that can truncate: a mod-2^k wrap in disguise
    return [self._finish_arith(eqn, a, prim="convert_element_type")], None


def _h_bitcast(self: RangeInterp, eqn, ins, cins):
    return [_top(eqn.outvars[0].aval.dtype)], None


def _h_iota(self: RangeInterp, eqn, ins, cins):
    aval = eqn.outvars[0].aval
    dim = eqn.params.get("dimension", 0)
    shape = tuple(aval.shape)
    n = shape[dim] if shape else 1
    if np.dtype(aval.dtype).kind in "iu" and math.prod(shape) <= _CONC_MAX_ELEMS:
        idx = np.arange(n, dtype=np.int64)
        view = idx.reshape([n if i == dim else 1 for i in range(len(shape))])
        c = np.broadcast_to(view, shape).astype(aval.dtype)
        o = _conc_to_obj(c)
        return [Ival(o, o)], [np.ascontiguousarray(c)]
    return [Ival(0, max(n - 1, 0))], None


def _materialize(iv: Ival, shape) -> tuple:
    return _obj(iv.lo, shape), _obj(iv.hi, shape)


def _h_shape_op(self: RangeInterp, eqn, ins, cins):
    """Pure layout ops: uniform intervals pass through; elementwise
    intervals are transformed positionally with numpy."""
    prim = eqn.primitive.name
    a = ins[0]
    out_shape = _shape_of(eqn.outvars[0])
    in_shape = _shape_of(eqn.invars[0])

    def xform(arr):
        p = eqn.params
        if prim == "broadcast_in_dim":
            bdims = p["broadcast_dimensions"]
            view_shape = [1] * len(out_shape)
            for i, d in enumerate(bdims):
                view_shape[d] = arr.shape[i]
            return np.broadcast_to(arr.reshape(view_shape), out_shape)
        if prim == "reshape":
            return np.reshape(np.ascontiguousarray(arr), out_shape)
        if prim == "transpose":
            return np.transpose(arr, p["permutation"])
        if prim == "squeeze":
            return np.squeeze(arr, axis=tuple(p["dimensions"]))
        if prim == "rev":
            return np.flip(arr, axis=tuple(p["dimensions"]))
        if prim == "slice":
            idx = tuple(
                slice(s, l, (st or 1))
                for s, l, st in zip(
                    p["start_indices"], p["limit_indices"],
                    p.get("strides") or [1] * len(p["start_indices"]),
                )
            )
            return arr[idx]
        if prim == "expand_dims":
            return np.reshape(np.ascontiguousarray(arr), out_shape)
        raise KeyError(prim)

    c = None
    if cins[0] is not None:
        try:
            c = np.ascontiguousarray(xform(cins[0]))
        except Exception:
            c = None
    if not _is_arr(a.lo) and not _is_arr(a.hi):
        return [a], [c]
    lo, hi = _materialize(a, in_shape)
    try:
        return [Ival(xform(lo), xform(hi), a.tainted)], [c]
    except Exception:
        return [Ival(_amin(a.lo), _amax(a.hi), a.tainted)], [c]


def _h_concat(self: RangeInterp, eqn, ins, cins):
    dim = eqn.params["dimension"]
    any_arr = any(_is_arr(i.lo) or _is_arr(i.hi) for i in ins)
    tainted = any(i.tainted for i in ins)
    c = None
    if all(x is not None for x in cins):
        try:
            c = np.concatenate(cins, axis=dim)
        except Exception:
            c = None
    if not any_arr:
        lo = min(_amin(i.lo) for i in ins)
        hi = max(_amax(i.hi) for i in ins)
        if all(_amin(i.lo) == lo and _amax(i.hi) == hi for i in ins):
            return [Ival(lo, hi, tainted)], [c]
        # differing uniform ranges: keep positional structure
        los = [np.full(_shape_of(eqn.invars[i]), _amin(v.lo), object)
               for i, v in enumerate(ins)]
        his = [np.full(_shape_of(eqn.invars[i]), _amax(v.hi), object)
               for i, v in enumerate(ins)]
        return [Ival(np.concatenate(los, axis=dim),
                     np.concatenate(his, axis=dim), tainted)], [c]
    los, his = [], []
    for i, v in enumerate(ins):
        shp = _shape_of(eqn.invars[i])
        lo, hi = _materialize(v, shp)
        los.append(lo)
        his.append(hi)
    return [Ival(np.concatenate(los, axis=dim), np.concatenate(his, axis=dim),
                 tainted)], [c]


def _h_pad(self: RangeInterp, eqn, ins, cins):
    a, padval = ins
    out_shape = _shape_of(eqn.outvars[0])
    in_shape = _shape_of(eqn.invars[0])
    config = eqn.params["padding_config"]
    tainted = a.tainted or padval.tainted
    simple = all(lo_p >= 0 and hi_p >= 0 and interior == 0
                 for lo_p, hi_p, interior in config)
    if not simple:
        lo = min(_amin(a.lo), _amin(padval.lo))
        hi = max(_amax(a.hi), _amax(padval.hi))
        return [Ival(lo, hi, tainted)], None
    if not _is_arr(a.lo) and not _is_arr(a.hi):
        if _amin(a.lo) == _amin(padval.lo) and _amax(a.hi) == _amax(padval.hi):
            return [Ival(a.lo, a.hi, tainted)], None
    lo_in, hi_in = _materialize(a, in_shape)
    target = tuple(
        slice(lo_p, lo_p + n) for (lo_p, _, _), n in zip(config, in_shape)
    )

    def build(val_arr, fill):
        arr = np.full(out_shape, fill, dtype=object)
        arr[target] = val_arr
        return arr

    return [Ival(build(lo_in, _amin(padval.lo)),
                 build(hi_in, _amax(padval.hi)), tainted)], None


def _h_gather(self: RangeInterp, eqn, ins, cins):
    op = ins[0]
    return [Ival(_amin(op.lo), _amax(op.hi), op.tainted)], None


def _h_dynamic_slice(self: RangeInterp, eqn, ins, cins):
    op = ins[0]
    out_shape = _shape_of(eqn.outvars[0])
    in_shape = _shape_of(eqn.invars[0])
    starts = cins[1:]
    if all(s is not None for s in starts) and (_is_arr(op.lo) or _is_arr(op.hi)):
        idx = []
        for i, s in enumerate(starts):
            st = int(np.asarray(s).reshape(()))
            st = max(0, min(st, in_shape[i] - out_shape[i]))
            idx.append(slice(st, st + out_shape[i]))
        lo, hi = _materialize(op, in_shape)
        c = None
        if cins[0] is not None:
            c = np.ascontiguousarray(cins[0][tuple(idx)])
        return [Ival(lo[tuple(idx)], hi[tuple(idx)], op.tainted)], [c]
    return [Ival(_amin(op.lo), _amax(op.hi), op.tainted)], None


def _h_dynamic_update_slice(self: RangeInterp, eqn, ins, cins):
    op, upd = ins[0], ins[1]
    out_shape = _shape_of(eqn.outvars[0])
    upd_shape = _shape_of(eqn.invars[1])
    starts = cins[2:]
    tainted = op.tainted or upd.tainted
    if all(s is not None for s in starts):
        idx = []
        for i, s in enumerate(starts):
            st = int(np.asarray(s).reshape(()))
            st = max(0, min(st, out_shape[i] - upd_shape[i]))
            idx.append(slice(st, st + upd_shape[i]))
        lo, hi = _materialize(op, out_shape)
        lo = np.array(lo, dtype=object)
        hi = np.array(hi, dtype=object)
        ulo, uhi = _materialize(upd, upd_shape)
        lo[tuple(idx)] = ulo
        hi[tuple(idx)] = uhi
        return [Ival(lo, hi, tainted)], None
    # unknown position: every element is either old or SOME update value
    joined = ival_join(
        op.broadcast(out_shape) if _is_arr(op.lo) else op,
        Ival(_amin(upd.lo), _amax(upd.hi), upd.tainted),
    )
    return [joined], None


def _h_scatter(self: RangeInterp, eqn, ins, cins):
    op, _idx, upd = ins[0], ins[1], ins[2]
    out_shape = _shape_of(eqn.outvars[0])
    joined = ival_join(
        op.broadcast(out_shape) if _is_arr(op.lo) else op,
        Ival(_amin(upd.lo), _amax(upd.hi), upd.tainted),
    )
    return [joined], None


def _h_scatter_add(self: RangeInterp, eqn, ins, cins):
    op, _idx, upd = ins[0], ins[1], ins[2]
    n = max(math.prod(_shape_of(eqn.invars[2])), 1)
    iv = Ival(
        _binmap(op.lo, min(_amin(upd.lo), 0) * n, lambda x, y: x + y),
        _binmap(op.hi, max(_amax(upd.hi), 0) * n, lambda x, y: x + y),
        op.tainted or upd.tainted,
    )
    return [self._finish_arith(eqn, iv, prim="add")], None


def _reduce_axes(eqn):
    return tuple(eqn.params.get("axes", ()))


def _h_reduce_minmax_like(self: RangeInterp, eqn, ins, cins):
    a = ins[0]
    axes = _reduce_axes(eqn)
    prim = eqn.primitive.name
    if not _is_arr(a.lo) and not _is_arr(a.hi):
        return [a], None
    in_shape = _shape_of(eqn.invars[0])
    lo, hi = _materialize(a, in_shape)
    if prim in ("reduce_max", "reduce_or"):
        return [Ival(np.max(lo, axis=axes), np.max(hi, axis=axes), a.tainted)], None
    return [Ival(np.min(lo, axis=axes), np.min(hi, axis=axes), a.tainted)], None


def _h_reduce_bitwise(self: RangeInterp, eqn, ins, cins):
    """reduce_or / reduce_and over INTEGER lanes: bitwise, not order —
    1|2 = 3 exceeds the elementwise max and 1&2 = 0 undershoots the
    elementwise min, so min/max transfer is unsound here. For nonneg
    values: OR only sets bits (result >= every element, bits bounded by
    the union cover 2^bits(max hi) - 1), AND only clears them
    (0 <= result <= every element)."""
    a = ins[0]
    prim = eqn.primitive.name
    dt = np.dtype(eqn.outvars[0].aval.dtype)
    if dt.kind == "b":
        # 0/1 lanes: or == max, and == min — the elementwise transfer
        # is exact
        return _h_reduce_minmax_like(self, eqn, ins, cins)
    if _amin(a.lo) < 0:
        return [_top(dt, a.tainted)], None
    axes = _reduce_axes(eqn)
    cover = lambda x: (1 << int(x).bit_length()) - 1
    if _is_arr(a.lo) or _is_arr(a.hi):
        in_shape = _shape_of(eqn.invars[0])
        lo, hi = _materialize(a, in_shape)
        if prim == "reduce_or":
            return [Ival(np.max(lo, axis=axes),
                         _unimap(np.max(hi, axis=axes), cover),
                         a.tainted)], None
        return [Ival(0, np.min(hi, axis=axes), a.tainted)], None
    if prim == "reduce_or":
        return [Ival(int(a.lo), cover(a.hi), a.tainted)], None
    return [Ival(0, int(a.hi), a.tainted)], None


def _h_reduce_sum(self: RangeInterp, eqn, ins, cins):
    a = ins[0]
    axes = _reduce_axes(eqn)
    in_shape = _shape_of(eqn.invars[0])
    if _is_arr(a.lo) or _is_arr(a.hi):
        lo, hi = _materialize(a, in_shape)
        iv = Ival(np.sum(lo, axis=axes), np.sum(hi, axis=axes), a.tainted)
    else:
        n = math.prod(in_shape[ax] for ax in axes) if axes else 1
        iv = Ival(int(a.lo) * n, int(a.hi) * n, a.tainted)
    return [self._finish_arith(eqn, iv, prim="add")], None


def _h_argminmax(self: RangeInterp, eqn, ins, cins):
    axes = tuple(eqn.params.get("axes", ()))
    in_shape = _shape_of(eqn.invars[0])
    n = max((in_shape[ax] for ax in axes), default=1)
    return [Ival(0, max(n - 1, 0))], None


def _h_pjit(self: RangeInterp, eqn, ins, cins):
    sub = eqn.params["jaxpr"]
    outs = self.run(sub, [iv for iv in ins])
    return outs, None


def _h_closed_call(self: RangeInterp, eqn, ins, cins):
    sub = eqn.params.get("call_jaxpr") or eqn.params.get("jaxpr")
    outs = self.run(sub, [iv for iv in ins])
    return outs, None


def _h_custom_call(self: RangeInterp, eqn, ins, cins):
    sub = eqn.params.get("call_jaxpr")
    if sub is None:
        return [
            _top(ov.aval.dtype, tainted=True) for ov in eqn.outvars
        ], None
    n = len(sub.jaxpr.invars)
    outs = self.run(sub, [iv for iv in ins[:n]])
    return outs, None


def _h_shard_map(self: RangeInterp, eqn, ins, cins):
    """Enter the per-shard body. The shard split changes LEADING axes
    only, so elementwise bounds broadcastable against the per-shard aval
    (per-limb caps on the trailing limb axis — the precision the fat-p
    lend proof needs) carry straight across; anything else collapses to
    its uniform bounds. The mesh is stashed for collective axis sizes."""
    sub = eqn.params["jaxpr"]  # open Jaxpr
    mesh = eqn.params.get("mesh")
    env: dict = {}
    conc: dict = {}
    for v, iv in zip(sub.invars, ins):
        env[v] = self._fit(iv, v)
    prev_mesh = getattr(self, "_mesh", None)
    self._mesh = mesh
    try:
        self._run_eqns(sub, env, conc)
    finally:
        self._mesh = prev_mesh
    return [self._read(env, conc, v) for v in sub.outvars], None


def _mesh_axis_size(self: RangeInterp, eqn) -> int:
    mesh = getattr(self, "_mesh", None)
    names = eqn.params.get("axes") or eqn.params.get("axis_name") or ()
    if isinstance(names, str):
        names = (names,)
    total = 1
    if mesh is not None:
        shape = dict(getattr(mesh, "shape", {}))
        for n in names:
            total *= int(shape.get(n, 1))
    else:
        total = 8  # conservative default when the mesh is unknown
    return max(total, 1)


def _h_psum(self: RangeInterp, eqn, ins, cins):
    n = _mesh_axis_size(self, eqn)
    outs = []
    for i, iv in enumerate(ins):
        s = Ival(_amin(iv.lo) * n, _amax(iv.hi) * n, iv.tainted)
        # classify EVERY operand of a tuple psum against its own output
        # aval — a scaled-but-unchecked second operand would leak an
        # out-of-dtype interval downstream unproven
        outs.append(
            self._finish_arith(eqn, s, prim="add", aval=eqn.outvars[i].aval)
        )
    return outs, None


def _h_all_gather(self: RangeInterp, eqn, ins, cins):
    iv = ins[0]
    return [Ival(_amin(iv.lo), _amax(iv.hi), iv.tainted)], None


def _h_axis_index(self: RangeInterp, eqn, ins, cins):
    return [Ival(0, _mesh_axis_size(self, eqn) - 1)], None


# -- loops ------------------------------------------------------------------


def _reduce_leading(iv: Ival, shape) -> Ival:
    """Join an xs interval over the scan axis (axis 0)."""
    if not _is_arr(iv.lo) and not _is_arr(iv.hi):
        return iv
    lo, hi = _materialize(iv, shape)
    if lo.ndim == 0:
        return Ival(int(lo), int(hi), iv.tainted)
    return Ival(np.min(lo, axis=0), np.max(hi, axis=0), iv.tainted)


def _h_scan(self: RangeInterp, eqn, ins, cins):
    p = eqn.params
    body = p["jaxpr"]  # ClosedJaxpr
    nc, ncar = p["num_consts"], p["num_carry"]
    length = int(p["length"])
    consts_iv = ins[:nc]
    init_iv = ins[nc : nc + ncar]
    xs_iv = ins[nc + ncar :]
    xs_shapes = [_shape_of(v) for v in eqn.invars[nc + ncar :]]
    xs_step = [_reduce_leading(iv, shp) for iv, shp in zip(xs_iv, xs_shapes)]
    n_out = len(eqn.outvars)

    def run_body(carry_ivs):
        return self.run(body, list(consts_iv) + list(carry_ivs) + list(xs_step))

    # 1) inductive / widening pass (muted: transient joins must not emit)
    carry = list(init_iv)
    stable = False
    with self._mute():
        for _ in range(self.widen_steps):
            outs = run_body(carry)
            new_carry = outs[:ncar]
            if all(ival_leq(n_, c_) for n_, c_ in zip(new_carry, carry)):
                stable = True
                break
            carry = [ival_join(c_, n_) for c_, n_ in zip(carry, new_carry)]

    if stable:
        outs = run_body(carry)  # authoritative, unmuted
        # a length-0 scan never runs its body — the carry output IS
        # init, so join it in (mirrors _h_while's zero-iteration join)
        final = [
            ival_join(i_, o_) for i_, o_ in zip(init_iv, outs[:ncar])
        ] + list(outs[ncar:])
        return _scan_outs(eqn, final, ncar, n_out, length), None

    # 2) concrete unroll: per-iteration xs values make dynamic slice
    #    positions static (the Montgomery red_step proof)
    xs_conc = cins[nc + ncar :]
    if length <= UNROLL_MAX and xs_iv and all(c is not None for c in xs_conc):
        self.stats["unrolled_scans"] += 1
        carry = list(init_iv)
        ys_join: list[Ival] | None = None
        reverse = bool(p.get("reverse", False))
        order = range(length - 1, -1, -1) if reverse else range(length)
        for it in order:
            step_ins = []
            for c, shp in zip(xs_conc, xs_shapes):
                row = np.ascontiguousarray(c[it])
                o = _conc_to_obj(row)
                step_ins.append(Ival(o, o))
            # concrete xs also flow as concrete values into the body
            outs = self._run_with_conc(
                body, list(consts_iv) + list(carry) + step_ins,
                conc_tail=[np.ascontiguousarray(c[it]) for c in xs_conc],
                n_tail=len(xs_conc),
            )
            carry = outs[:ncar]
            ys = outs[ncar:]
            if ys_join is None:
                ys_join = list(ys)
            else:
                ys_join = [ival_join(a, b) for a, b in zip(ys_join, ys)]
        final = list(carry) + (ys_join or [])
        return _scan_outs(eqn, final, ncar, n_out, length), None

    # 3) widen-to-top: only the carries that failed to stabilize
    self.stats["widened_loops"] += 1
    widened = _widen_fixpoint(
        self, lambda w: run_body(w)[:ncar], carry, eqn, "scan",
        lambda i, c_: (
            f"scan carry {i} has no inductive interval within "
            f"{self.widen_steps} widening steps (init "
            f"[{_amin(init_iv[i].lo)}, {_amax(init_iv[i].hi)}] grew to "
            f"[{_amin(c_.lo)}, {_amax(c_.hi)}]) and the xs are not "
            "concrete — carry widened to dtype-top; the loop body is "
            "UNPROVEN against lane overflow"
        ),
    )
    outs = run_body(widened)  # authoritative, unmuted
    final = list(widened) + list(outs[ncar:])
    return _scan_outs(eqn, final, ncar, n_out, length), None


def _widen_fixpoint(self: RangeInterp, probe_body, carry, eqn, kind, msg):
    """Top the non-inductive carries, re-checking the survivors against
    the WIDENED environment until a fixpoint: widening one carry can
    un-stabilize a dependent one (c0 = f(c1)) that looked inductive
    before the top. Each pass tops >= 1 new carry, so <= len(carry)
    passes. Emits a 'widened' event per topped UNSIGNED carry (the
    range-checked lanes)."""
    widened = list(carry)
    topped: set = set()
    while True:
        with self._mute():
            probe = probe_body(widened)
        changed = False
        for i, (c_, n_) in enumerate(zip(widened, probe)):
            if i in topped or ival_leq(n_, c_):
                continue
            dt = np.dtype(eqn.outvars[i].aval.dtype)
            widened[i] = _top(dt, tainted=dt.kind == "u")
            topped.add(i)
            changed = True
            if dt.kind == "u":
                self._emit("widened", kind, self._frames(eqn), msg(i, c_))
        if not changed:
            break
    return widened


def _scan_outs(eqn, outs, ncar, n_out, length):
    """Map body-shaped output intervals onto the scan eqn's outvars
    (ys gain the leading length axis)."""
    result = []
    for i in range(n_out):
        iv = outs[i] if i < len(outs) else None
        ov = eqn.outvars[i]
        if iv is None:
            result.append(_top(ov.aval.dtype, tainted=True))
            continue
        if i >= ncar and (_is_arr(iv.lo) or _is_arr(iv.hi)):
            shp = _shape_of(ov)
            try:
                lo = np.broadcast_to(_obj(iv.lo, shp[1:]), shp)
                hi = np.broadcast_to(_obj(iv.hi, shp[1:]), shp)
                result.append(Ival(lo, hi, iv.tainted))
                continue
            except Exception:
                result.append(Ival(_amin(iv.lo), _amax(iv.hi), iv.tainted))
                continue
        result.append(iv)
    return result


def _h_while(self: RangeInterp, eqn, ins, cins):
    p = eqn.params
    cond_n, body_n = p["cond_nconsts"], p["body_nconsts"]
    body = p["body_jaxpr"]
    cond_consts = ins[:cond_n]
    body_consts = ins[cond_n : cond_n + body_n]
    init = ins[cond_n + body_n :]

    def run_body(carry_ivs):
        return self.run(body, list(body_consts) + list(carry_ivs))

    def run_cond(carry_ivs):
        # the condition's arithmetic runs once per iteration on device:
        # it must be checked against the SAME carry cover as the body
        self.run(p["cond_jaxpr"], list(cond_consts) + list(carry_ivs))

    carry = list(init)
    stable = False
    with self._mute():
        for _ in range(self.widen_steps):
            new_carry = run_body(carry)
            if all(ival_leq(n_, c_) for n_, c_ in zip(new_carry, carry)):
                stable = True
                break
            carry = [ival_join(c_, n_) for c_, n_ in zip(carry, new_carry)]
    if stable:
        final = run_body(carry)  # authoritative, unmuted
        run_cond(carry)  # cond arithmetic checked over the fixpoint
        joined = [ival_join(i_, f_) for i_, f_ in zip(init, final)]
        return joined, None
    self.stats["widened_loops"] += 1
    widened = _widen_fixpoint(
        self, run_body, carry, eqn, "while",
        lambda i, c_: (
            f"while carry {i} has no inductive interval within "
            f"{self.widen_steps} widening steps — widened to dtype-top; "
            "the loop body is UNPROVEN against lane overflow"
        ),
    )
    run_body(widened)  # authoritative pass for body-internal events
    run_cond(widened)
    return widened, None


def _h_cond(self: RangeInterp, eqn, ins, cins):
    branches = eqn.params["branches"]
    op_ins = ins[1:]
    joined: list[Ival] | None = None
    for br in branches:
        outs = self.run(br, list(op_ins))
        if joined is None:
            joined = list(outs)
        else:
            joined = [ival_join(a, b) for a, b in zip(joined, outs)]
    return joined or [], None


def _run_with_conc(self: RangeInterp, closed, in_ivals, conc_tail, n_tail):
    """run() but seeding concrete values for the LAST n_tail inputs
    (unrolled scan iterations)."""
    jaxpr = closed.jaxpr
    env: dict = {}
    conc: dict = {}
    for cv, cval in zip(jaxpr.constvars, closed.consts):
        arr = np.asarray(cval)
        if arr.dtype.kind in "iub" and arr.size <= _CONC_MAX_ELEMS:
            o = _conc_to_obj(arr)
            env[cv] = Ival(o, o)
            conc[cv] = arr
        else:
            env[cv] = self._const_ival(arr)
    for v, iv in zip(jaxpr.invars, in_ivals):
        env[v] = iv
    if n_tail:
        for v, c in zip(jaxpr.invars[-n_tail:], conc_tail):
            if c is not None:
                conc[v] = c
    self._run_eqns(jaxpr, env, conc)
    return [self._read(env, conc, v) for v in jaxpr.outvars]


RangeInterp._run_with_conc = _run_with_conc


_HANDLERS = {
    "add": _h_arith,
    "sub": _h_arith,
    "mul": _h_arith,
    "add_any": _h_arith,
    "and": _h_and,
    "or": _h_pass_binop,
    "xor": _h_pass_binop,
    "min": _h_pass_binop,
    "max": _h_pass_binop,
    "div": _h_pass_binop,
    "rem": _h_pass_binop,
    "shift_left": _h_shift,
    "shift_right_logical": _h_shift,
    "shift_right_arithmetic": _h_shift,
    "eq": _h_cmp,
    "ne": _h_cmp,
    "lt": _h_cmp,
    "le": _h_cmp,
    "gt": _h_cmp,
    "ge": _h_cmp,
    "not": _h_not,
    "neg": _h_neg,
    "sign": _h_sign,
    "clamp": _h_clamp,
    "select_n": _h_select,
    "select": _h_select,
    "device_put": _h_identity,
    "copy": _h_identity,
    "optimization_barrier": _h_identity,
    "stop_gradient": _h_identity,
    "convert_element_type": _h_convert,
    "bitcast_convert_type": _h_bitcast,
    "iota": _h_iota,
    "broadcast_in_dim": _h_shape_op,
    "reshape": _h_shape_op,
    "transpose": _h_shape_op,
    "squeeze": _h_shape_op,
    "expand_dims": _h_shape_op,
    "rev": _h_shape_op,
    "slice": _h_shape_op,
    "concatenate": _h_concat,
    "pad": _h_pad,
    "gather": _h_gather,
    "dynamic_slice": _h_dynamic_slice,
    "dynamic_update_slice": _h_dynamic_update_slice,
    "scatter": _h_scatter,
    "scatter-add": _h_scatter_add,
    "reduce_and": _h_reduce_bitwise,
    "reduce_or": _h_reduce_bitwise,
    "reduce_max": _h_reduce_minmax_like,
    "reduce_min": _h_reduce_minmax_like,
    "reduce_sum": _h_reduce_sum,
    "argmax": _h_argminmax,
    "argmin": _h_argminmax,
    "pjit": _h_pjit,
    "closed_call": _h_closed_call,
    "core_call": _h_closed_call,
    "custom_jvp_call": _h_custom_call,
    "custom_vjp_call": _h_custom_call,
    "remat_call": _h_custom_call,
    "checkpoint": _h_custom_call,
    "shard_map": _h_shard_map,
    "psum": _h_psum,
    "psum2": _h_psum,
    "all_gather": _h_all_gather,
    "axis_index": _h_axis_index,
    "scan": _h_scan,
    "while": _h_while,
    "cond": _h_cond,
}
