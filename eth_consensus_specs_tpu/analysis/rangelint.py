"""rangelint — value-level static analysis: no limb kernel wraps a lane.

The third rung of the analysis ladder. speclint (analysis/lint.py) reads
source; jaxlint (analysis/jaxlint.py) reads trace structure; rangelint
reads trace VALUES: an interval abstract interpreter (analysis/ranges.py)
walks every registered kernel's jaxpr with exact python-int bounds seeded
from the input domains the registry declares (``Variant.domains``) and
proves, per intermediate, that no unsigned lane can silently wrap. The
hand-reasoned comments this machine-checks are load-bearing: a u64
wraparound in the Montgomery column sums is a wrong pairing verdict, not
an exception.

``lane-overflow``
    Any intermediate whose interval can exceed its dtype max (or
    underflow below zero on an unsigned lane) in a kernel not annotated
    ``wraps`` at that primitive site. sha256's mod-2^32 adds are the
    sanctioned wrap — declared per primitive site (``Wrap``), never
    blanket. Widened loops (no inductive carry interval), analysis
    timeouts, and unhandled primitives also land here: an UNPROVEN
    kernel is indistinguishable from an overflowing one. NEVER
    baselined (HARD_RULES).
``mask-consistency``
    A value AND-ed with a low-bit mask ``2^k - 1`` must be provably
    bounded — masks may truncate only bits the interval proves are
    separately-propagated carries. Masking a widened/unproven value is
    how an upstream overflow hides.
``lazy-bound-audit``
    Every ``lazy_limbs.LF`` static ``max_limb`` claim (``add``/``dbl``
    growth, ``sub``'s ``_fat_p`` lend path, ``mul``'s normalized
    output) is cross-checked against the interval the analyzer infers
    for the same chain: a claim TIGHTER than inferred is a soundness
    bug (downstream preconditions trust it), a claim LOOSER than
    inferred is waste (it forces premature norm/shrink sweeps).

Findings reuse the speclint/jaxlint machinery end to end: line-free
``kernel::rule::detail`` fingerprints, the ratcheting EMPTY baseline
(``rangelint_baseline.json``), the shared CLI front end
(analysis/cli.py), ``scripts/rangelint.py`` / ``make rangelint``.
Abstract interpretation only — no execution, no XLA compile.
"""

from __future__ import annotations

import time

from . import kernels as kernels_mod
from .lint import Finding
from .ranges import (
    AnalysisTimeout,
    Ival,
    RangeInterp,
    range_timeout_s,
    widen_steps_default,
    _obj,
)

ALL_RULES = (
    "lane-overflow",
    "mask-consistency",
    "lazy-bound-audit",
)

# lane-overflow may NEVER be baselined (CI asserts this): a possible
# silent wraparound in consensus-critical arithmetic is a bug, not debt
HARD_RULES = ("lane-overflow",)

_EVENT_RULE = {
    "overflow": "lane-overflow",
    "widened": "lane-overflow",
    "unhandled": "lane-overflow",
    "masked-taint": "mask-consistency",
}


def _f(name: str, rule: str, detail: str, message: str) -> Finding:
    # path = kernel family: fingerprint kernel::rule::detail, line-free
    return Finding(rule, name, 0, detail, message)


def seed_ivals(variant) -> list[Ival]:
    """The flat input intervals for one variant, from its declared
    domains (one Domain per traced-arg pytree leaf, in flatten order)."""
    import jax
    import numpy as np

    traced = [
        a
        for i, a in enumerate(variant.args)
        if i not in (variant.static_argnums or ())
    ]
    leaves = jax.tree_util.tree_leaves(traced)
    if len(variant.domains) != len(leaves):
        raise ValueError(
            f"variant {variant.label!r} declares {len(variant.domains)} "
            f"domains for {len(leaves)} traced input leaves"
        )
    out = []
    for dom, leaf in zip(variant.domains, leaves):
        shape = tuple(leaf.shape)
        lo = _obj(np.asarray(dom.lo, object), shape) if np.ndim(dom.lo) else int(dom.lo)
        hi = _obj(np.asarray(dom.hi, object), shape) if np.ndim(dom.hi) else int(dom.hi)
        out.append(Ival(lo, hi))
    return out


def analyze_variant(spec, variant, *, widen_steps=None, deadline=None):
    """(findings, interp) for one registry variant."""
    from .jaxlint import trace_variant

    findings: list[Finding] = []
    closed = trace_variant(variant)
    interp = RangeInterp(
        wraps=spec.wraps, widen_steps=widen_steps, deadline=deadline
    )
    try:
        interp.run(closed, seed_ivals(variant))
    except AnalysisTimeout:
        findings.append(
            _f(
                spec.name,
                "lane-overflow",
                f"{variant.label}:timeout",
                f"{spec.name}/{variant.label}: interval analysis exceeded "
                "ETH_SPECS_ANALYSIS_RANGE_TIMEOUT_S — the kernel is UNPROVEN "
                "against lane overflow (raise the budget or shrink the "
                "representative shapes)",
            )
        )
        return findings, interp
    for ev in interp.events:
        rule = _EVENT_RULE.get(ev.kind)
        if rule is None:
            continue
        findings.append(
            _f(
                spec.name,
                rule,
                f"{variant.label}:{ev.detail}",
                f"{spec.name}/{variant.label}: {ev.message}",
            )
        )
    return findings, interp


# ------------------------------------------------------- lazy-bound-audit --


def _lf_chain_cases():
    """The audited LF chains: (label, n_inputs, fn(LF...) -> LF).
    Kept below the shrink/norm thresholds so the claims under audit are
    the RAW growth formulas, not post-sweep resets."""
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    def add2(a, b):
        return lz.add(a, b)

    def dbl1(a):
        return lz.dbl(a)

    def add_chain4(a, b, c, d):
        return lz.add(lz.add(a, b), lz.add(c, d))

    def dbl_chain3(a):
        return lz.dbl(lz.dbl(lz.dbl(a)))

    def sub2(a, b):
        return lz.sub(a, b)

    def sub_of_sum(a, b, c):
        # the lend path under a GROWN subtrahend: _fat_p must re-cover
        return lz.sub(a, lz.add(b, c))

    def mul2(a, b):
        return lz.mul(a, b)

    return [
        ("add", 2, add2),
        ("dbl", 1, dbl1),
        ("add_chain4", 4, add_chain4),
        ("dbl_chain3", 1, dbl_chain3),
        ("sub", 2, sub2),
        ("sub_fat_lend", 3, sub_of_sum),
        ("mul", 2, mul2),
    ]


def audit_lazy_bounds(*, widen_steps=None, deadline=None):
    """Cross-check LF ``max_limb`` claims against inferred intervals.

    Each chain is traced over normalized inputs (limb-wise
    ``[0, NORM_MAX]``, value < 2p — exactly what ``lf()`` claims); the
    trace ITSELF computes the static claim (the LF bound algebra runs at
    trace time), and the interpreter infers the true reachable interval
    of the output array. claim < inferred -> soundness finding;
    claim > inferred -> waste finding. Returns (findings, stats)."""
    import jax
    import numpy as np

    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    findings: list[Finding] = []
    stats = {"chains": 0, "events": 0}
    shape = (2, lz.N_LIMBS)
    sds = jax.ShapeDtypeStruct(shape, jax.numpy.uint64)
    # the SAME digit-cap formula the registry domains seed the family
    # sweep with — the audit must prove against the identical input set
    hi = kernels_mod.limb_caps(2 * lz.P_INT - 1, lz.LIMB_BITS, lz.N_LIMBS)
    for label, n_in, chain in _lf_chain_cases():
        stats["chains"] += 1
        claims: list[tuple[int, int]] = []

        def run(*arrs, _chain=chain):
            out = _chain(*(lz.lf(a) for a in arrs))
            claims.append((out.max, out.val))
            return out.v

        closed = jax.make_jaxpr(run)(*([sds] * n_in))
        claimed_max, claimed_val = claims[0]
        interp = RangeInterp(
            wraps=_lazy_wraps(), widen_steps=widen_steps, deadline=deadline
        )
        try:
            [out] = interp.run(
                closed, [Ival(0, np.broadcast_to(hi, shape))] * n_in
            )
        except AnalysisTimeout:
            # unproven == indistinguishable from overflowing: file under
            # the never-baselined rule, same as the family sweep
            findings.append(
                _f("lazy_limbs", "lane-overflow", f"{label}:timeout",
                   f"lazy_limbs {label}: bound audit timed out — unproven")
            )
            continue
        stats["events"] += len(interp.events)
        for ev in interp.events:
            # overflow/widened/unhandled inside a chain is a LANE bug the
            # audit happened to surface — it must keep the lane-overflow
            # fingerprint so it can never be baselined away as audit debt
            findings.append(
                _f(
                    "lazy_limbs",
                    _EVENT_RULE.get(ev.kind, "lane-overflow"),
                    f"{label}:{ev.detail}",
                    f"lazy_limbs {label}: {ev.message}",
                )
            )
        inferred = _ival_max(out)
        if claimed_max < inferred:
            findings.append(
                _f(
                    "lazy_limbs",
                    "lazy-bound-audit",
                    f"{label}:claim-tight",
                    f"lazy_limbs {label}: claimed max_limb {claimed_max} is "
                    f"TIGHTER than the inferred reachable bound {inferred} — "
                    "every downstream norm/shrink/mul precondition trusting "
                    "the claim is unsound (a lane can wrap where the static "
                    "bookkeeping says it cannot)",
                )
            )
        elif claimed_max > max(inferred, lz.NORM_MAX):
            # claims never need to dip below NORM_MAX (inputs are allowed
            # to BE normalized); above that, looseness costs real sweeps
            findings.append(
                _f(
                    "lazy_limbs",
                    "lazy-bound-audit",
                    f"{label}:claim-loose",
                    f"lazy_limbs {label}: claimed max_limb {claimed_max} is "
                    f"LOOSER than the inferred bound {inferred} — the slack "
                    "forces premature norm/shrink sweeps (each one a ~15-op "
                    "carry subgraph the lazy design exists to avoid)",
                )
            )
    return findings, stats


def _ival_max(iv: Ival) -> int:
    import numpy as np

    return int(np.max(iv.hi)) if isinstance(iv.hi, np.ndarray) else int(iv.hi)


def _lazy_wraps():
    """The reviewed wrap sites for lazy_limbs chains — the SAME set the
    pairing registry entry declares, so the audit and the family sweep
    trust identical sites."""
    return kernels_mod.lazy_lend_wraps()


# ------------------------------------------------------------------ engine --


def analyze(
    mesh=None,
    rules: set[str] | None = None,
    registry: tuple | None = None,
    only: set[str] | None = None,
    widen_steps: int | None = None,
    timeout_s: float | None = None,
) -> tuple[list[Finding], dict]:
    """Run the selected value-range rules over the kernel registry.
    Returns (findings, stats). Same contract as jaxlint.analyze:
    ``mesh=None`` analyzes single-device variants only, ``only`` narrows
    to a family subset. The per-FAMILY deadline comes from
    ``ETH_SPECS_ANALYSIS_RANGE_TIMEOUT_S`` unless ``timeout_s`` is
    given."""
    rules = set(rules) if rules is not None else set(ALL_RULES)
    registry = kernels_mod.REGISTRY if registry is None else registry
    widen_steps = widen_steps or widen_steps_default()
    budget = range_timeout_s() if timeout_s is None else timeout_s
    findings: list[Finding] = []
    stats = {
        "kernels": 0,
        "variants": 0,
        "mesh_variants": 0,
        "eqns": 0,
        "unrolled_scans": 0,
        "widened_loops": 0,
        "wrap_hits": 0,
    }
    if rules & {"lane-overflow", "mask-consistency"}:
        for spec in registry:
            if only is not None and spec.name not in only:
                continue
            stats["kernels"] += 1
            deadline = time.monotonic() + budget
            for variant in spec.build_variants(mesh):
                stats["variants"] += 1
                if variant.mesh is not None:
                    stats["mesh_variants"] += 1
                fs, interp = analyze_variant(
                    spec, variant, widen_steps=widen_steps, deadline=deadline
                )
                # hard-rule findings always ship, even when the caller
                # narrowed --rules: lane-overflow has no opt-out
                findings.extend(
                    f for f in fs if f.rule in rules or f.rule in HARD_RULES
                )
                for k in ("eqns", "unrolled_scans", "widened_loops", "wrap_hits"):
                    stats[k] += interp.stats[k]
            if spec.suppress:
                findings = [
                    f
                    for f in findings
                    if not (
                        f.path == spec.name
                        and f.rule in spec.suppress
                        and f.rule not in HARD_RULES
                    )
                ]
    if "lazy-bound-audit" in rules and (
        only is None or {"lazy_limbs", "pairing"} & only
    ):
        deadline = time.monotonic() + budget
        audit_findings, audit_stats = audit_lazy_bounds(
            widen_steps=widen_steps, deadline=deadline
        )
        # audit-surfaced hard-rule findings always ship, even when the
        # caller narrowed --rules: lane-overflow has no opt-out
        findings.extend(
            f for f in audit_findings if f.rule in rules or f.rule in HARD_RULES
        )
        stats["lf_chains"] = audit_stats["chains"]
    # one finding per fingerprint (several variants repeating the same
    # defect collapse), like jaxlint
    seen: set[str] = set()
    unique = []
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.symbol)):
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        unique.append(f)
    return unique, stats
