"""The kernel registry: every registered device kernel, declaratively.

jaxlint (analysis/jaxlint.py) abstract-evals each entry here — no
execution, no XLA compile — and runs its trace-level rules over the
jaxprs. The registry is therefore the place where a kernel family makes
its accelerator contract EXPLICIT:

  * ``dtypes`` — the aval dtypes the kernel is allowed to contain
    (x64-drift: an i64 counter inside a uint32 hash kernel doubles its
    register/HBM footprint silently);
  * ``donate`` / ``donation_waiver`` — every family must either declare
    the flat argnums its jit actually donates, or carry a reviewed
    waiver string saying why no donation opportunity is taken
    (donation-audit; the ROADMAP item-2 device-resident state work
    lands behind this seam). The registry refuses entries that declare
    neither — silence is not a donation policy;
  * ``variants`` — the representative traced shapes, including the
    mesh-sharded variant where one exists (collective-audit needs the
    real shard_map mesh to bind axis names against);
  * ``key_grid`` — for kernels the serve layer buckets, the LIVE
    compile-key function (serve/buckets.merkle_many_key / bls_msm_key,
    ops/state_root.state_root_compile_key — the same callables the
    dispatch sites use, not copies) evaluated over the bucket grid so
    the recompile-surface rule can prove key -> traced-signature
    injectivity.

Representative shapes are small on purpose: ``jax.make_jaxpr`` cost is
graph-size-bound, not data-bound, so a depth-10 tree over 8 trees
exercises exactly the primitives the depth-12x64 production bucket
compiles. The bucket GRIDS (key_grid) do cover the production range —
key computation is pure python.

``suppress`` mirrors speclint's inline ``# speclint: disable=`` escape
hatch at registry granularity: a reviewed, diff-visible waiver of one
rule for one kernel. The baseline (jaxlint_baseline.json) ships EMPTY.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from .ranges import Domain, Wrap

# ---------------------------------------------------------------- specs --


@dataclass(frozen=True)
class Variant:
    """One traceable entry point of a kernel family: the callable plus
    the abstract args (ShapeDtypeStruct pytrees) to trace it with.

    ``domains`` seed rangelint's interval analysis: one
    :class:`~eth_consensus_specs_tpu.analysis.ranges.Domain` per TRACED
    input pytree leaf (flatten order, static argnums excluded), each
    carrying the inclusive elementwise bound the kernel assumes of that
    argument ("Montgomery limbs in [0, 2p) limb-wise", "scalar bits in
    {0, 1}") plus the concrete boundary members
    (tests/test_range_domains.py executes every family at these corners
    against its host oracle, so a stale domain fails at runtime too,
    not just in the prover)."""

    label: str  # "single" | "mesh"
    fn: Callable
    args: tuple
    static_argnums: tuple[int, ...] = ()
    mesh: object = None  # jax Mesh for mesh variants (axis-name binding)
    domains: tuple = ()  # one ranges.Domain per traced input leaf


@dataclass(frozen=True)
class KernelSpec:
    name: str
    help: str
    # aval dtypes the kernel's jaxpr may contain (0-d weak-typed scalars
    # — literal-derived trace constants — are exempt in the rule)
    dtypes: frozenset
    # flat positional argnums the kernel's jit declares donated
    donate: tuple[int, ...] = ()
    # reviewed reason why donation opportunities are NOT taken (required
    # when donate is empty — the registry refuses silent entries)
    donation_waiver: str | None = None
    # registry-level rule suppressions (reviewed escape hatch)
    suppress: tuple[str, ...] = ()
    # sanctioned-wraparound primitive sites for rangelint: each Wrap
    # names ONE primitive at ONE ``file.py::function`` site where
    # exceeding the lane is the algorithm (sha256's mod-2^32 adds, the
    # borrow-chain transient underflow) — reviewed per site, never
    # blanket
    wraps: tuple = ()
    # (mesh | None) -> list[Variant]; mesh variants only when mesh given
    # — whether a family HAS a mesh variant is determined here and only
    # here (callers inspect Variant.mesh; no duplicate flag to drift)
    build_variants: Callable = None
    # (mesh | None) -> list[(key tuple, signature tuple)] over the
    # serve bucket grid; None = the serve layer never keys this kernel
    key_grid: Callable | None = None


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _default_buckets() -> tuple[int, ...]:
    from eth_consensus_specs_tpu.serve.config import ServeConfig

    return ServeConfig().buckets


# ------------------------------------------------------- range domains --


def limb_caps(value_max: int, limb_bits: int, n_limbs: int):
    """Inclusive per-limb bound for base-2^limb_bits digit vectors of
    values <= value_max. Elementwise on purpose: the top limb of a
    value < 2p is ~2^22, not the limb mask, and several overflow proofs
    (fat-p lend cover, column sums) need exactly that precision."""
    import numpy as np

    mask = (1 << limb_bits) - 1
    return np.array(
        [min(mask, value_max >> (limb_bits * i)) for i in range(n_limbs)],
        dtype=object,
    )


def limb_digits(x: int, limb_bits: int, n_limbs: int):
    """Concrete digit vector of x (a VALID domain member — corner data)."""
    import numpy as np

    mask = (1 << limb_bits) - 1
    return np.array(
        [(x >> (limb_bits * i)) & mask for i in range(n_limbs)], dtype=np.uint64
    )


def mont_domain(
    name: str, modulus: int, limb_bits: int, n_limbs: int, *, canonical: bool = False
) -> Domain:
    """Montgomery limb vectors, limb-wise. The default is the REDUNDANT
    range [0, 2p) every reduced device field element satisfies; pass
    ``canonical=True`` for boundaries that require host-converted
    elements < p (the pairing's prepared inputs: ``_fat_p``'s top-limb
    lend cover is sized from ``val=p-1``, and rangelint proves a
    [p, 2p) input would underflow it — the declaration IS the
    precondition). Corners are the boundary members of the range."""
    vmax = (modulus - 1) if canonical else (2 * modulus - 1)
    corners = (
        ("zero", 0),
        ("p-1", limb_digits(modulus - 1, limb_bits, n_limbs)),
    )
    if not canonical:
        corners += (("2p-1", limb_digits(2 * modulus - 1, limb_bits, n_limbs)),)
    return Domain(name, hi=limb_caps(vmax, limb_bits, n_limbs), corners=corners)


def limb_borrow_wraps(file: str, mask: int) -> tuple:
    """The reviewed wrap pair for a borrow-chain subtraction: the
    ``x - y - borrow`` step transiently underflows (two's complement, by
    design) and the restore add ``cur + (under << LIMB_BITS)`` provably
    lands back under the limb ``mask`` — the carry-separation argument
    the mask-consistency rule checks."""
    return (
        Wrap("sub", f"{file}::_sub_limbs"),
        Wrap("add", f"{file}::_sub_limbs", bound=mask),
    )


def lazy_lend_wraps() -> tuple:
    """lazy_limbs sanctioned sites: the borrow chain (shrink's cond-sub)
    plus the ``sub`` lend path. ``fat - y`` is sound because a
    NORMALIZED y's top digit is bounded by ``y.val >> 364`` — a
    value-level fact the interval domain cannot represent after norm's
    re-masking — so the site is declared trusted with the bound
    ``lazy_limbs._LEND_LIMB_CAP`` (1 << 30) that ``sub`` now enforces at
    trace time on every call — auto-shrinking a subtrahend whose fat
    cover would exceed it (tests pin the two constants equal)."""
    return limb_borrow_wraps("lazy_limbs.py", _MASK26) + (
        Wrap("sub", "lazy_limbs.py::sub", bound=1 << 30),
    )


# u32 hash words: the full lane is the domain (message/chunk words)
_WORDS32 = Domain(
    "hash words (full u32 lane)",
    hi=0xFFFFFFFF,
    corners=(("zero", 0), ("all-ones", 0xFFFFFFFF)),
)

# sha256 wraps BY DESIGN: every add is mod 2^32 (the algorithm), and
# _rotr's left shift drops high bits that the or re-introduces rotated.
# Declared per primitive site; families that hash (merkle, state_root)
# reach these frames through their call stacks.
_SHA_WRAPS = (
    Wrap("add", "sha256.py::_compress"),
    Wrap("add", "sha256.py::rnd"),
    Wrap("add", "sha256.py::_compress_scan"),
    Wrap("add", "sha256.py::sha256_pair_words_scan"),
    Wrap("add", "sha256.py::sha256_pair_words_unrolled"),
    Wrap("add", "sha256.py::sha256_single_block"),
    Wrap("shift_left", "sha256.py::_rotr"),
)


# ------------------------------------------------------------- builders --


def _sha256_variants(mesh):
    from eth_consensus_specs_tpu.ops import sha256

    return [
        Variant(
            f"single:tile{t}",
            sha256._kernel,
            (_sds((t, 16), "uint32"),),
            domains=(_WORDS32,),
        )
        for t in sha256.TILES
    ]


def _merkle_variants(mesh):
    from eth_consensus_specs_tpu.ops import merkle

    return [
        Variant(
            f"single:d{d}",
            merkle._tree_root_fused,
            (_sds((1 << d, 8), "uint32"), d),
            static_argnums=(1,),
            domains=(_WORDS32,),
        )
        for d in (6, 10)
    ]


def _merkle_many_args(batch: int, depth: int):
    return (_sds((batch, 1 << depth, 8), "uint32"),)


def _merkle_many_variants(mesh):
    from eth_consensus_specs_tpu.ops import merkle
    from eth_consensus_specs_tpu.parallel import mesh_ops

    depth = 10
    out = [
        Variant(
            "single",
            merkle._many_tree_root_fused,
            (*_merkle_many_args(8, depth), depth),
            static_argnums=(1,),
            domains=(_WORDS32,),
        )
    ]
    if mesh is not None:
        batch = mesh_ops.pad_to_shards(8, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                merkle._many_tree_root_sharded(mesh, depth),
                _merkle_many_args(batch, depth),
                mesh=mesh,
                domains=(_WORDS32,),
            )
        )
    return out


def _merkle_many_key_grid(mesh):
    """LIVE serve key fn (buckets.merkle_many_key) over the bucket grid
    vs the traced signature the dispatch actually compiles under."""
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    cfg = _default_buckets()
    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        shards = mesh_ops.shard_count(m)
        for depth in (4, 10, 12):
            for n in (1, 2, 3, 5, 8, 13, 16, 33, 64):
                key = buckets.merkle_many_key(n, depth, cfg, mesh=m)
                pad = key[1]
                batch = mesh_ops.pad_to_shards(pad, shards) if m is not None else pad
                sig = (
                    _canon_args(_merkle_many_args(batch, depth)),
                    depth,
                    mesh_ops.mesh_signature(m),
                )
                out.append((key, sig))
                # the router's profile-form of the SAME key fn (the
                # front door predicts siblings' compile keys from
                # (shards, signature) — serve/buckets): a divergence
                # between the two forms is an `aliased` finding here,
                # not a silent cold compile in production
                out.append((
                    buckets.merkle_many_key_from_profile(
                        n, depth, cfg, shards, mesh_ops.mesh_signature(m)
                    ),
                    sig,
                ))
    return out


def _merkle_inc_args(shards: int, depth_local: int):
    m = (1 << (depth_local + 1)) - 1
    ll = 1 << depth_local
    return (
        _sds((shards, m, 8), "uint32"),
        _sds((shards, ll), "bool_"),
        _sds((shards, ll, 8), "uint32"),
    )


def _merkle_inc_variants(mesh):
    from eth_consensus_specs_tpu.ops import merkle_inc
    from eth_consensus_specs_tpu.serve import buckets

    depth, cap = 10, 8
    doms = (_WORDS32, _BOOL_DOMAIN, _WORDS32)
    out = [
        Variant(
            "single",
            merkle_inc._apply_kernel(depth, cap, buckets.inc_dense_count(depth, cap)),
            _merkle_inc_args(1, depth),
            domains=doms,
        )
    ]
    if mesh is not None:
        shards = merkle_inc.forest_shards(depth, mesh)
        if shards > 1:
            dl = depth - (shards - 1).bit_length()
            out.append(
                Variant(
                    "mesh",
                    merkle_inc._apply_kernel_mesh(
                        mesh, depth, cap, buckets.inc_dense_count(dl, cap)
                    ),
                    _merkle_inc_args(shards, dl),
                    mesh=mesh,
                    domains=doms,
                )
            )
    return out


def _merkle_inc_key_grid(mesh):
    """LIVE serve key fn (buckets.merkle_inc_key) over the dirty-bucket
    grid vs the traced forest-update signature the dispatch compiles
    under (every static knob — capacity, dense threshold, depth, mesh
    signature — discriminates)."""
    from eth_consensus_specs_tpu.ops import merkle_inc
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        for depth in (8, 10, 12):
            shards = merkle_inc.forest_shards(depth, m) if m is not None else 1
            dl = depth - (shards - 1).bit_length()
            for hint in (1, 5, 8, 64, 200):
                cap = min(buckets.inc_dirty_bucket(hint), 1 << dl)
                dense = buckets.inc_dense_count(dl, cap)
                key = buckets.merkle_inc_key(cap, dense, depth, mesh=m)
                sig = (
                    _canon_args(_merkle_inc_args(shards, dl)),
                    cap,
                    dense,
                    mesh_ops.mesh_signature(m),
                )
                out.append((key, sig))
    return out


def _shuffle_variants(mesh):
    from eth_consensus_specs_tpu.ops import shuffle

    n, rounds = 512, 90
    num_chunks = (n + 255) // 256
    return [
        Variant(
            "single",
            shuffle._device_shuffle_kernel(n, rounds, num_chunks),
            (_sds((rounds * num_chunks, 16), "uint32"), _sds((rounds,), "int32")),
            domains=(
                _WORDS32,
                Domain(
                    "round pivots in [0, n)",
                    hi=n - 1,
                    corners=(("zero", 0), ("n-1", n - 1)),
                ),
            ),
        )
    ]


def _fr_fft_args(batch: int, n: int, stages: int):
    from eth_consensus_specs_tpu.ops import fr_fft

    fr = fr_fft.FR
    return (
        _sds((batch, n, fr.n_limbs), "uint64"),
        *(_sds((1 << i, fr.n_limbs), "uint64") for i in range(stages)),
    )


def _fr_fft_variants(mesh):
    from eth_consensus_specs_tpu.ops import fr_fft
    from eth_consensus_specs_tpu.parallel import mesh_ops

    n, stages = 256, 8
    fr = fr_fft.FR
    # twiddle tables are CANONICAL Montgomery (< r, built by to_mont);
    # no corners — the runtime corner test needs the real tables (a
    # boundary "twiddle" would just be a different polynomial basis)
    tw_dom = Domain(
        "twiddles: canonical Montgomery Fr (< r limb-wise)",
        hi=limb_caps(fr.modulus - 1, 30, fr.n_limbs),
    )
    doms = (
        mont_domain("values: Montgomery Fr in [0, 2r)", fr.modulus, 30, fr.n_limbs),
        *([tw_dom] * stages),
    )
    out = [
        Variant(
            "single",
            fr_fft._compiled_fft(n, stages),
            _fr_fft_args(4, n, stages),
            domains=doms,
        )
    ]
    if mesh is not None:
        batch = mesh_ops.pad_to_shards(4, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                fr_fft._sharded_fft(mesh, n, stages),
                _fr_fft_args(batch, n, stages),
                mesh=mesh,
                domains=doms,
            )
        )
    return out


def _fr_fft_key_grid(mesh):
    """LIVE serve key fn (buckets.fr_fft_key) over the blob-flush grid
    vs the batch-padded avals the FFT dispatch compiles under — the
    bucket discipline the FFT never had before the DAS workload."""
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        shards = mesh_ops.shard_count(m)
        for n in (256, 4096):
            stages = n.bit_length() - 1
            for b in (1, 2, 3, 5, 8, 16, 33):
                key = buckets.fr_fft_key(b, n, mesh=m)
                sig = (
                    _canon_args(_fr_fft_args(key[1], n, stages)),
                    mesh_ops.mesh_signature(m),
                )
                out.append((key, sig))
                # profile-form agreement (see _merkle_many_key_grid)
                out.append((
                    buckets.fr_fft_key_from_profile(
                        b, n, shards, mesh_ops.mesh_signature(m)
                    ),
                    sig,
                ))
    return out


def _fq_jacobian_domains() -> tuple:
    from eth_consensus_specs_tpu.crypto.fields import P

    return tuple(
        mont_domain(f"Jacobian {c}: Montgomery Fq in [0, 2p)", P, 30, 13)
        for c in ("X", "Y", "Z")
    )


_SCALAR_BITS_DOMAIN = Domain(
    "scalar bits in {0, 1}",
    hi=1,
    corners=(("zero", 0), ("one", 1)),
)


def _g1_msm_variants(mesh):
    from eth_consensus_specs_tpu.ops import g1_msm
    from eth_consensus_specs_tpu.parallel import mesh_ops

    def args(lanes):
        return (
            _sds((lanes, 256), "uint64"),
            *[_sds((lanes, 13), "uint64")] * 3,
        )

    doms = (_SCALAR_BITS_DOMAIN, *_fq_jacobian_domains())
    out = [Variant("single", g1_msm.msm_kernel, args(8), domains=doms)]
    if mesh is not None:
        lanes = g1_msm.mesh_lane_pad(8, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                g1_msm._sharded_fn(mesh, "msm"),
                args(lanes),
                mesh=mesh,
                domains=doms,
            )
        )
    return out


def _bls_msm_args(items: int, lanes: int):
    return tuple([_sds((items, lanes, 13), "uint64")] * 3)


def _bls_msm_variants(mesh):
    from eth_consensus_specs_tpu.ops import g1_msm
    from eth_consensus_specs_tpu.parallel import mesh_ops

    doms = _fq_jacobian_domains()
    out = [
        Variant(
            "single", g1_msm.sum_many_kernel, _bls_msm_args(4, 8), domains=doms
        )
    ]
    if mesh is not None:
        items = mesh_ops.pad_to_shards(4, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                g1_msm._sharded_fn(mesh, "sum_many"),
                _bls_msm_args(items, 8),
                mesh=mesh,
                domains=doms,
            )
        )
    return out


def _bls_msm_key_grid(mesh):
    """LIVE serve key fn (buckets.bls_msm_key) over the committee grid
    vs the many_sum_shape padded avals the dispatch compiles under."""
    from eth_consensus_specs_tpu.ops.g1_msm import many_sum_shape
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        shards = mesh_ops.shard_count(m)
        for items in (1, 2, 3, 5, 9, 16, 33):
            for lanes in (1, 3, 8, 64, 100):
                key = buckets.bls_msm_key(items, lanes, mesh=m)
                item_pad, lane_pad = many_sum_shape(items, lanes, shards)
                sig = (
                    _canon_args(_bls_msm_args(item_pad, lane_pad)),
                    mesh_ops.mesh_signature(m),
                )
                out.append((key, sig))
                # profile-form agreement (see _merkle_many_key_grid)
                out.append((
                    buckets.bls_msm_key_from_profile(
                        items, lanes, shards, mesh_ops.mesh_signature(m)
                    ),
                    sig,
                ))
    return out


def _kzg_msm_args(items: int, lanes: int):
    return (
        _sds((items, lanes, 256), "uint64"),
        *[_sds((items, lanes, 13), "uint64")] * 3,
    )


def _kzg_msm_variants(mesh):
    from eth_consensus_specs_tpu.ops import g1_msm

    doms = (_SCALAR_BITS_DOMAIN, *_fq_jacobian_domains())
    out = [
        Variant(
            "single", g1_msm.msm_many_kernel, _kzg_msm_args(2, 4), domains=doms
        )
    ]
    if mesh is not None:
        from eth_consensus_specs_tpu.parallel import mesh_ops

        lanes = g1_msm.mesh_lane_pad(4, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                g1_msm._sharded_fn(mesh, "msm_many"),
                _kzg_msm_args(2, lanes),
                mesh=mesh,
                domains=doms,
            )
        )
    return out


def _kzg_msm_key_grid(mesh):
    """LIVE serve key fn (buckets.kzg_msm_key) over the blob-flush grid
    vs the 2-item lane-padded avals the RLC fold compiles under (the
    lane axis is the mesh-sharded one, like g2_agg)."""
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        shards = mesh_ops.shard_count(m)
        for n in (1, 2, 3, 5, 9, 16, 33, 64):
            key = buckets.kzg_msm_key(n, mesh=m)
            sig = (
                _canon_args(_kzg_msm_args(2, buckets.kzg_lane_bucket(n, shards))),
                mesh_ops.mesh_signature(m),
            )
            out.append((key, sig))
            # profile-form agreement (see _merkle_many_key_grid)
            out.append((
                buckets.kzg_msm_key_from_profile(
                    n, shards, mesh_ops.mesh_signature(m)
                ),
                sig,
            ))
    return out


def _g2_agg_args(items: int, lanes: int):
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    return tuple([_sds((items, lanes, 2, lz.N_LIMBS), "uint64")] * 3)


def _g2_agg_domains() -> tuple:
    from eth_consensus_specs_tpu.crypto.fields import P
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    # REDUNDANT [0, 2p): host conversion feeds canonical (< p) limbs,
    # but the butterfly scan's canonical carry is < 2p and the declared
    # domain must cover what actually crosses the boundary
    return tuple(
        mont_domain(
            f"G2 Jacobian {c}: Montgomery Fq2 in [0, 2p) limb-wise",
            P, lz.LIMB_BITS, lz.N_LIMBS,
        )
        for c in ("X", "Y", "Z")
    )


def _g2_agg_variants(mesh):
    from eth_consensus_specs_tpu.ops import g2_aggregate as ga
    from eth_consensus_specs_tpu.serve import buckets

    doms = _g2_agg_domains()
    out = [
        Variant("single", ga.g2_sum_many_kernel, _g2_agg_args(2, 4), domains=doms)
    ]
    if mesh is not None:
        from eth_consensus_specs_tpu.parallel import mesh_ops

        lanes = buckets.agg_lane_bucket(4, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                ga._sharded_fn(mesh),
                _g2_agg_args(2, lanes),
                mesh=mesh,
                domains=doms,
            )
        )
    return out


def _g2_agg_key_grid(mesh):
    """LIVE serve key fn (buckets.g2_agg_key) over the committee grid
    vs the g2_many_sum_shape padded avals the dispatch compiles under
    (the lane axis is the mesh-sharded one here)."""
    from eth_consensus_specs_tpu.ops.g2_aggregate import g2_many_sum_shape
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        shards = mesh_ops.shard_count(m)
        for items in (1, 2, 3, 5, 9, 16, 33):
            for lanes in (1, 3, 8, 64, 100):
                key = buckets.g2_agg_key(items, lanes, mesh=m)
                item_pad, lane_pad = g2_many_sum_shape(items, lanes, shards)
                sig = (
                    _canon_args(_g2_agg_args(item_pad, lane_pad)),
                    mesh_ops.mesh_signature(m),
                )
                out.append((key, sig))
                # profile-form agreement (see _merkle_many_key_grid)
                out.append((
                    buckets.g2_agg_key_from_profile(
                        items, lanes, shards, mesh_ops.mesh_signature(m)
                    ),
                    sig,
                ))
    return out


def _pairing_domains() -> tuple:
    from eth_consensus_specs_tpu.crypto.fields import P
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    # CANONICAL (< p): miller_from_coeffs claims val=P-1 for the
    # prepared inputs, and _fat_p's lend cover is sized from that claim
    # — a [p, 2p) input would underflow the borrow-free sub
    lazy = lambda name: mont_domain(name, P, lz.LIMB_BITS, lz.N_LIMBS, canonical=True)
    return (
        lazy("prepared coefficients: canonical Montgomery Fq (< p)"),
        lazy("G1 x: canonical Montgomery Fq (< p)"),
        lazy("G1 y: canonical Montgomery Fq (< p)"),
        Domain("active mask", hi=1, corners=(("inactive", 0), ("active", 1))),
    )


def _pairing_variants(mesh):
    from eth_consensus_specs_tpu.ops import pairing_device as pd

    def chunk_args(n_chunks):
        lead = (n_chunks,) if n_chunks else ()
        return (
            _sds((*lead, pd._CHUNK, pd.N_STEPS, 2, 2, pd.N_LIMBS), "uint64"),
            _sds((*lead, pd._CHUNK, pd.N_LIMBS), "uint64"),
            _sds((*lead, pd._CHUNK, pd.N_LIMBS), "uint64"),
            _sds((*lead, pd._CHUNK), "bool"),
        )

    doms = _pairing_domains()
    out = [Variant("single", pd._miller_chunk_fold, chunk_args(0), domains=doms)]
    if mesh is not None:
        from eth_consensus_specs_tpu.parallel import mesh_ops

        shards = mesh_ops.shard_count(mesh)
        out.append(
            Variant(
                "mesh",
                pd._miller_sharded_fn(mesh, 1),
                chunk_args(shards),
                mesh=mesh,
                domains=doms,
            )
        )
    return out


def synthetic_state_root_meta(n: int = 64, extra_static: int = 0):
    """A StateRootMeta with every dynamic slot the altair+ impl resolves,
    without building a spec/object state. ``extra_static`` grows the
    top-level container (and so top_depth) — the key grid uses it to
    prove the compile key discriminates container shapes."""
    from eth_consensus_specs_tpu.ops.state_root import StateRootMeta

    dynamic = (
        "validators",
        "balances",
        "inactivity_scores",
        "previous_epoch_participation",
        "current_epoch_participation",
        "justification_bits",
        "previous_justified_checkpoint",
        "current_justified_checkpoint",
        "finalized_checkpoint",
    )
    n_fields = len(dynamic) + 16 + extra_static
    top_depth = max(n_fields - 1, 0).bit_length()
    return StateRootMeta(
        dynamic_slots=tuple(enumerate(dynamic)),
        n_validators=n,
        top_depth=top_depth,
    )


def _state_root_args(meta):
    from eth_consensus_specs_tpu.ops.state_root import StateRootArrays
    from eth_consensus_specs_tpu.ops.state_columns import JustificationState

    n = meta.n_validators
    arrays = StateRootArrays(
        val_node_a=_sds((n, 8), "uint32"),
        val_node_f=_sds((n, 8), "uint32"),
        slashed_chunk=_sds((n, 8), "uint32"),
        prev_part_flags=_sds((n,), "uint8"),
        top_chunks=_sds((1 << meta.top_depth, 8), "uint32"),
        zerohashes=_sds((41, 8), "uint32"),
    )
    just = JustificationState(
        current_epoch=_sds((), "uint64"),
        justification_bits=_sds((4,), "bool_"),
        prev_justified_epoch=_sds((), "uint64"),
        prev_justified_root=_sds((32,), "uint8"),
        cur_justified_epoch=_sds((), "uint64"),
        cur_justified_root=_sds((32,), "uint8"),
        finalized_epoch=_sds((), "uint64"),
        finalized_root=_sds((32,), "uint8"),
        block_root_prev=_sds((32,), "uint8"),
        block_root_cur=_sds((32,), "uint8"),
        slashings_sum=_sds((), "uint64"),
    )
    cols = (_sds((n,), "uint64"), _sds((n,), "uint64"), _sds((n,), "uint64"))
    return arrays, cols, just


_U64_FULL = Domain(
    "u64 SSZ value (full lane)",
    hi=(1 << 64) - 1,
    corners=(("zero", 0), ("max", (1 << 64) - 1)),
)
_BYTES_FULL = Domain(
    "opaque bytes (full u8 lane)",
    hi=255,
    corners=(("zero", 0), ("max", 255)),
)
_BOOL_DOMAIN = Domain("bit", hi=1, corners=(("false", 0), ("true", 1)))


def _state_root_domains() -> tuple:
    """One Domain per flat leaf of (arrays, bal, eff, inact, just) — the
    kernel only HASHES these (byte-swap + sha256 wraps), so every leaf's
    domain is its full lane; a future arithmetic epoch-accounting step
    would have to tighten these to survive rangelint."""
    return (
        # StateRootArrays: val_node_a, val_node_f, slashed_chunk,
        # prev_part_flags, top_chunks, zerohashes
        _WORDS32,
        _WORDS32,
        _WORDS32,
        _BYTES_FULL,
        _WORDS32,
        _WORDS32,
        # balances / effective_balance / inactivity_scores columns
        _U64_FULL,
        _U64_FULL,
        _U64_FULL,
        # JustificationState: current_epoch, justification_bits,
        # prev_justified_epoch, prev_justified_root, cur_justified_epoch,
        # cur_justified_root, finalized_epoch, finalized_root,
        # block_root_prev, block_root_cur, slashings_sum
        _U64_FULL,
        _BOOL_DOMAIN,
        _U64_FULL,
        _BYTES_FULL,
        _U64_FULL,
        _BYTES_FULL,
        _U64_FULL,
        _BYTES_FULL,
        _BYTES_FULL,
        _BYTES_FULL,
        _U64_FULL,
    )


def _state_root_variants(mesh):
    from eth_consensus_specs_tpu.ops import state_root as sr

    meta = synthetic_state_root_meta(64)
    arrays, (bal, eff, inact), just = _state_root_args(meta)

    def run(arrays, balances, effective_balance, inactivity_scores, just):
        return sr._post_epoch_state_root_impl(
            arrays, meta, balances, effective_balance, inactivity_scores, just
        )

    return [
        Variant(
            "single",
            run,
            (arrays, bal, eff, inact, just),
            domains=_state_root_domains(),
        )
    ]


def _state_root_key_grid(mesh):
    """LIVE ops/state_root.state_root_compile_key over registry shapes
    vs the flattened input avals the graph traces under."""
    from eth_consensus_specs_tpu.ops.state_root import state_root_compile_key

    out = []
    for n in (64, 128, 256):
        for extra in (0, 40):  # two container widths -> two top_depths
            meta = synthetic_state_root_meta(n, extra_static=extra)
            key = state_root_compile_key(meta)
            sig = (
                _canon_args(_state_root_args(meta)),
                meta.top_depth,
                meta.dynamic_slots,
            )
            out.append((key, sig))
    return out


def _resident_scrub_shapes(shards: int, depth: int, sub_depth: int, k: int):
    m = (1 << (depth + 1)) - 1
    return (
        _sds((shards, m, 8), "uint32"),
        _sds((k,), "int32"),
        _sds((k,), "int32"),
    )


def _resident_scrub_domains(shards: int, depth: int, sub_depth: int):
    per_shard = 1 << (depth - sub_depth)
    return (
        _WORDS32,
        Domain(
            "shard index in [0, shards)",
            hi=shards - 1,
            corners=(("zero", 0), ("last", shards - 1)),
        ),
        Domain(
            "subtree position in [0, per_shard)",
            hi=per_shard - 1,
            corners=(("zero", 0), ("last", per_shard - 1)),
        ),
    )


def _resident_scrub_variants(mesh):
    from eth_consensus_specs_tpu.ops import snapshot

    depth, sub_depth, k = 10, snapshot.SCRUB_SUBTREE_DEPTH, 4
    m = (1 << (depth + 1)) - 1
    return [
        Variant(
            "single",
            snapshot._scrub_kernel(m, sub_depth, k),
            _resident_scrub_shapes(1, depth, sub_depth, k),
            domains=_resident_scrub_domains(1, depth, sub_depth),
        )
    ]


def _resident_scrub_key_grid(mesh):
    """LIVE first_dispatch key of ops/snapshot.scrub_forest —
    ("resident_scrub", shards, n_nodes, sub_depth, k) — over registry
    shapes vs the traced (nodes, sidx, pos) signature."""
    from eth_consensus_specs_tpu.ops import snapshot

    out = []
    for depth in (8, 10):
        sd = min(snapshot.SCRUB_SUBTREE_DEPTH, depth)
        m = (1 << (depth + 1)) - 1
        for k in (4, 8):
            kk = min(k, 1 << (depth - sd))
            key = ("resident_scrub", 1, m, sd, kk)
            sig = (_canon_args(_resident_scrub_shapes(1, depth, sd, kk)), sd, kk)
            out.append((key, sig))
    return out


def _forest_args(plan):
    """ShapeDtypeStruct pytree of a StateForest under this plan — the
    donated argument of the slot_apply family (run_epochs shares the
    same layout)."""
    from eth_consensus_specs_tpu.ops.state_root import StateForest

    mv = (1 << (plan.depth_val + 1)) - 1
    mb = (1 << (plan.depth_bal + 1)) - 1
    return StateForest(
        val_nodes=_sds((plan.shards, mv, 8), "uint32"),
        bal_nodes=_sds((plan.shards, mb, 8), "uint32"),
        inact_nodes=_sds((plan.shards, mb, 8), "uint32") if plan.has_inact else None,
        part_root=_sds((8,), "uint32"),
    )


def _slot_apply_args(meta, plan, p_flags: int, p_rewards: int):
    arrays, (bal, eff, inact), just = _state_root_args(meta)
    n = meta.n_validators
    return (
        arrays,
        _forest_args(plan),
        bal,
        eff,
        inact,
        _sds((n,), "uint8"),  # prev_flags participation column
        _sds((n,), "bool_"),  # cur_tgt_att column
        just,
        _sds((p_flags,), "int32"),  # flag scatter indices (pad lanes -> 0)
        _sds((p_flags,), "uint8"),  # flag_on hit bits (pad lanes -> 0)
        _sds((p_rewards,), "int32"),  # reward scatter indices
        _sds((p_rewards,), "uint64"),  # reward amounts (pad lanes -> 0)
    )


_BALANCE_GWEI = Domain(
    "balance gwei < 2^63 (headroom for the slot's reward adds)",
    hi=(1 << 63) - 1,
    corners=(("zero", 0), ("max", (1 << 63) - 1)),
)
_REWARD_GWEI = Domain(
    "per-validator sync reward gwei < 2^32",
    hi=(1 << 32) - 1,
    corners=(("zero", 0), ("max", (1 << 32) - 1)),
)


def _slot_apply_domains(meta, plan, p_flags: int, p_rewards: int) -> tuple:
    n = meta.n_validators
    idx = Domain(
        "validator index in [0, n)",
        hi=n - 1,
        corners=(("zero", 0), ("last", n - 1)),
    )
    forest_words = (_WORDS32,) * (4 if plan.has_inact else 3)
    return (
        # StateRootArrays (same order as the state_root family)
        _WORDS32,
        _WORDS32,
        _WORDS32,
        _BYTES_FULL,
        _WORDS32,
        _WORDS32,
        # StateForest: val_nodes, bal_nodes, [inact_nodes,] part_root
        *forest_words,
        # balance is ADDED to (bounded), eff/inact are only hashed
        _BALANCE_GWEI,
        _U64_FULL,
        _U64_FULL,
        _BYTES_FULL,  # prev_flags participation byte
        _BOOL_DOMAIN,  # cur_tgt_att
        # JustificationState (same 11 as the state_root family)
        _U64_FULL,
        _BOOL_DOMAIN,
        _U64_FULL,
        _BYTES_FULL,
        _U64_FULL,
        _BYTES_FULL,
        _U64_FULL,
        _BYTES_FULL,
        _BYTES_FULL,
        _BYTES_FULL,
        _U64_FULL,
        # scatter plan lanes
        idx,
        _BOOL_DOMAIN,  # flag_on hit bit (uint8 {0, 1})
        idx,
        _REWARD_GWEI,
    )


def _slot_apply_variants(mesh):
    from eth_consensus_specs_tpu.ops import slot_pipeline
    from eth_consensus_specs_tpu.ops.state_root import forest_plan

    meta = synthetic_state_root_meta(64)
    plan = forest_plan(meta)
    p_flags, p_rewards = 8, 8
    return [
        Variant(
            "single",
            slot_pipeline._compiled_slot_apply(meta, plan, None, p_flags, p_rewards),
            _slot_apply_args(meta, plan, p_flags, p_rewards),
            domains=_slot_apply_domains(meta, plan, p_flags, p_rewards),
        )
    ]


def _slot_apply_key_grid(mesh):
    """LIVE serve/buckets.slot_key over the request-capacity grid
    (registry size x flag/reward capacities — capacities are derived
    from the request ALONE, so the router and the dispatch share this
    exact surface) vs the flat traced arg shapes the jit caches on."""
    from eth_consensus_specs_tpu.ops.state_root import forest_plan
    from eth_consensus_specs_tpu.serve import buckets

    out = []
    for n in (64, 128):
        meta = synthetic_state_root_meta(n)
        plan = forest_plan(meta)
        for flags in (1, 5, 8, 64):
            for rewards in (1, 16):
                key = buckets.slot_key(n, flags, rewards, plan)
                args = _slot_apply_args(meta, plan, key[2], key[3])
                sig = (_canon_args(args), tuple(plan))
                out.append((key, sig))
    return out


def _canon_args(args) -> tuple:
    """Canonical hashable form of a ShapeDtypeStruct pytree — the part
    of the jit cache key the shape grid varies."""
    import jax

    return tuple(
        (tuple(leaf.shape), str(leaf.dtype)) for leaf in jax.tree_util.tree_leaves(args)
    )


# ------------------------------------------------------------- registry --

_LIMB_DTYPES = frozenset({"uint64", "uint32", "int32", "bool"})

_MASK30 = (1 << 30) - 1  # field_limbs / limb_field limb mask
_MASK26 = (1 << 26) - 1  # lazy_limbs limb mask

REGISTRY: tuple[KernelSpec, ...] = (
    KernelSpec(
        name="sha256",
        help="tiled vectorized SHA-256 (ops/sha256.sha256_tiled)",
        dtypes=frozenset({"uint32"}),
        donation_waiver="message (N,16) and digest (N,8) avals never alias; "
        "tiles are transient host uploads reused across levels",
        wraps=_SHA_WRAPS,
        build_variants=_sha256_variants,
    ),
    KernelSpec(
        name="merkle",
        help="single-subtree device merkleization (ops/merkle)",
        # bool: the fori_loop predicate scalar; int32: its counter
        dtypes=frozenset({"uint32", "int32", "bool"}),
        donation_waiver="leaf buffer (2^d,8) vs root (8,) never alias; the "
        "resident-state seam (ROADMAP item 2) donates at the column level, "
        "not here",
        wraps=_SHA_WRAPS,
        build_variants=_merkle_variants,
    ),
    KernelSpec(
        name="merkle_many",
        help="vmapped multi-tree merkleization, mesh tree-axis sharded",
        dtypes=frozenset({"uint32", "int32", "bool"}),
        donation_waiver="batched leaves (B,2^d,8) vs roots (B,8) never alias",
        wraps=_SHA_WRAPS,
        build_variants=_merkle_many_variants,
        key_grid=_merkle_many_key_grid,
    ),
    KernelSpec(
        name="merkle_inc",
        help="incremental dirty-subtree forest update (ops/merkle_inc), "
        "mesh leaf-axis sharded",
        dtypes=frozenset({"uint32", "int32", "bool"}),
        # the forest node buffer: every epoch's update lands in place —
        # this donation IS the resident-footprint claim the ROADMAP
        # item-1 rework makes, proven per kernel by the audit
        donate=(0,),
        wraps=_SHA_WRAPS,
        build_variants=_merkle_inc_variants,
        key_grid=_merkle_inc_key_grid,
    ),
    KernelSpec(
        name="shuffle",
        help="whole-permutation swap-or-not shuffle (ops/shuffle)",
        dtypes=frozenset({"uint32", "int32", "bool"}),
        donation_waiver="decision blocks and pivots are read-only; the index "
        "plane lives in the loop carry, not an argument buffer",
        wraps=_SHA_WRAPS,
        build_variants=_shuffle_variants,
    ),
    KernelSpec(
        name="fr_fft",
        help="batched BLS-scalar-field FFT (ops/fr_fft), mesh "
        "batch-axis sharded",
        dtypes=_LIMB_DTYPES,
        donate=(0,),  # vals: private bit-reversed copy, aval == output
        wraps=limb_borrow_wraps("limb_field.py", _MASK30),
        build_variants=_fr_fft_variants,
        key_grid=_fr_fft_key_grid,
    ),
    KernelSpec(
        name="g1_msm",
        help="G1 multi-scalar multiplication, mesh lane-axis sharded",
        dtypes=_LIMB_DTYPES,
        donation_waiver="lane arrays (N,13)x3 + bits (N,256) vs one Jacobian "
        "point (13,)x3 — no aval ever aliases an output",
        wraps=limb_borrow_wraps("field_limbs.py", _MASK30),
        build_variants=_g1_msm_variants,
    ),
    KernelSpec(
        name="bls_msm",
        help="batched per-item G1 committee sums (the serve RLC seam), "
        "mesh item-axis sharded",
        dtypes=_LIMB_DTYPES,
        donation_waiver="committee lanes (I,L,13)x3 vs per-item points "
        "(I,13)x3 — shapes never alias",
        wraps=limb_borrow_wraps("field_limbs.py", _MASK30),
        build_variants=_bls_msm_variants,
        key_grid=_bls_msm_key_grid,
    ),
    KernelSpec(
        name="kzg_msm",
        help="batched per-item full-scalar G1 MSMs (the KZG blob RLC "
        "fold — ops/g1_msm.msm_many_kernel), mesh lane-axis sharded",
        dtypes=_LIMB_DTYPES,
        donation_waiver="MSM lanes (I,L,13)x3 + bits (I,L,256) vs "
        "per-item Jacobian points (I,13)x3 — no aval ever aliases an "
        "output",
        wraps=limb_borrow_wraps("field_limbs.py", _MASK30),
        build_variants=_kzg_msm_variants,
        key_grid=_kzg_msm_key_grid,
    ),
    KernelSpec(
        name="g2_aggregate",
        help="batched ragged-committee G2 signature sums (the aggregation "
        "pipeline seam), mesh lane-axis sharded",
        dtypes=_LIMB_DTYPES,
        donation_waiver="committee lanes (I,L,2,15)x3 vs per-item Jacobian "
        "points (I,2,15)x3 — shapes never alias",
        wraps=lazy_lend_wraps(),
        build_variants=_g2_agg_variants,
        key_grid=_g2_agg_key_grid,
    ),
    KernelSpec(
        name="pairing",
        help="chunked Miller accumulation + fold, mesh chunk-axis sharded",
        dtypes=frozenset({"uint64", "uint32", "uint8", "int32", "bool"}),
        donation_waiver="prepared coefficients are cached host constants "
        "(_PREP_CACHE) reused across batches — donating them would corrupt "
        "the cache",
        wraps=lazy_lend_wraps(),
        build_variants=_pairing_variants,
    ),
    KernelSpec(
        name="state_root",
        help="post-accounting-epoch BeaconState root (ops/state_root)",
        dtypes=frozenset({"uint32", "uint64", "uint8", "int32", "bool"}),
        donation_waiver="static tree arrays are reused every epoch "
        "(device-resident by design); donation lands with the in-place "
        "per-slot updates of ROADMAP item 2",
        wraps=_SHA_WRAPS,
        build_variants=_state_root_variants,
        key_grid=_state_root_key_grid,
    ),
    KernelSpec(
        name="resident_scrub",
        help="salted-subtree resident forest integrity scrub "
        "(ops/snapshot._scrub_kernel): K subtrees re-hashed from their "
        "resident leaves + the full upper region, compared against the "
        "stored rows",
        dtypes=frozenset({"uint32", "int32", "bool"}),
        donation_waiver="read-only verification pass: the resident node "
        "buffer must SURVIVE the scrub (a donated forest could not be "
        "quarantine-rebuilt from its own leaves afterwards)",
        wraps=_SHA_WRAPS,
        build_variants=_resident_scrub_variants,
        key_grid=_resident_scrub_key_grid,
    ),
    KernelSpec(
        name="slot_apply",
        help="whole-slot fused apply (ops/slot_pipeline._compiled_slot_apply): "
        "duplicate-safe participation scatter + sync-reward balance adds + "
        "incremental re-root against the resident forest, one donated dispatch",
        dtypes=frozenset({"uint32", "uint64", "uint8", "int32", "bool"}),
        # the resident forest (flat invars 6..9 after the 6 StateRootArrays
        # leaves): slot N+1 updates slot N's tree levels in place — the
        # run_epochs lifecycle, same buffers
        donate=(6, 7, 8, 9),
        wraps=_SHA_WRAPS,
        build_variants=_slot_apply_variants,
        key_grid=_slot_apply_key_grid,
    ),
)

for _spec in REGISTRY:
    if not _spec.donate and not _spec.donation_waiver:
        raise AssertionError(
            f"kernel registry entry {_spec.name!r} declares neither donated "
            "argnums nor a donation waiver — silence is not a donation policy"
        )


def by_name() -> dict[str, KernelSpec]:
    return {s.name: s for s in REGISTRY}


def mesh_families(mesh) -> set[str]:
    """Families whose builders emit a mesh variant on this mesh —
    derived from the builders themselves (the authoritative source),
    not a hand-maintained list."""
    if mesh is None:
        return set()
    return {
        s.name
        for s in REGISTRY
        if any(v.mesh is not None for v in s.build_variants(mesh))
    }
