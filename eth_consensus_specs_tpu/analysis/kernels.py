"""The kernel registry: every registered device kernel, declaratively.

jaxlint (analysis/jaxlint.py) abstract-evals each entry here — no
execution, no XLA compile — and runs its trace-level rules over the
jaxprs. The registry is therefore the place where a kernel family makes
its accelerator contract EXPLICIT:

  * ``dtypes`` — the aval dtypes the kernel is allowed to contain
    (x64-drift: an i64 counter inside a uint32 hash kernel doubles its
    register/HBM footprint silently);
  * ``donate`` / ``donation_waiver`` — every family must either declare
    the flat argnums its jit actually donates, or carry a reviewed
    waiver string saying why no donation opportunity is taken
    (donation-audit; the ROADMAP item-2 device-resident state work
    lands behind this seam). The registry refuses entries that declare
    neither — silence is not a donation policy;
  * ``variants`` — the representative traced shapes, including the
    mesh-sharded variant where one exists (collective-audit needs the
    real shard_map mesh to bind axis names against);
  * ``key_grid`` — for kernels the serve layer buckets, the LIVE
    compile-key function (serve/buckets.merkle_many_key / bls_msm_key,
    ops/state_root.state_root_compile_key — the same callables the
    dispatch sites use, not copies) evaluated over the bucket grid so
    the recompile-surface rule can prove key -> traced-signature
    injectivity.

Representative shapes are small on purpose: ``jax.make_jaxpr`` cost is
graph-size-bound, not data-bound, so a depth-10 tree over 8 trees
exercises exactly the primitives the depth-12x64 production bucket
compiles. The bucket GRIDS (key_grid) do cover the production range —
key computation is pure python.

``suppress`` mirrors speclint's inline ``# speclint: disable=`` escape
hatch at registry granularity: a reviewed, diff-visible waiver of one
rule for one kernel. The baseline (jaxlint_baseline.json) ships EMPTY.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

# ---------------------------------------------------------------- specs --


@dataclass(frozen=True)
class Variant:
    """One traceable entry point of a kernel family: the callable plus
    the abstract args (ShapeDtypeStruct pytrees) to trace it with."""

    label: str  # "single" | "mesh"
    fn: Callable
    args: tuple
    static_argnums: tuple[int, ...] = ()
    mesh: object = None  # jax Mesh for mesh variants (axis-name binding)


@dataclass(frozen=True)
class KernelSpec:
    name: str
    help: str
    # aval dtypes the kernel's jaxpr may contain (0-d weak-typed scalars
    # — literal-derived trace constants — are exempt in the rule)
    dtypes: frozenset
    # flat positional argnums the kernel's jit declares donated
    donate: tuple[int, ...] = ()
    # reviewed reason why donation opportunities are NOT taken (required
    # when donate is empty — the registry refuses silent entries)
    donation_waiver: str | None = None
    # registry-level rule suppressions (reviewed escape hatch)
    suppress: tuple[str, ...] = ()
    # (mesh | None) -> list[Variant]; mesh variants only when mesh given
    # — whether a family HAS a mesh variant is determined here and only
    # here (callers inspect Variant.mesh; no duplicate flag to drift)
    build_variants: Callable = None
    # (mesh | None) -> list[(key tuple, signature tuple)] over the
    # serve bucket grid; None = the serve layer never keys this kernel
    key_grid: Callable | None = None


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _default_buckets() -> tuple[int, ...]:
    from eth_consensus_specs_tpu.serve.config import ServeConfig

    return ServeConfig().buckets


# ------------------------------------------------------------- builders --


def _sha256_variants(mesh):
    from eth_consensus_specs_tpu.ops import sha256

    return [
        Variant(f"single:tile{t}", sha256._kernel, (_sds((t, 16), "uint32"),))
        for t in sha256.TILES
    ]


def _merkle_variants(mesh):
    from eth_consensus_specs_tpu.ops import merkle

    return [
        Variant(
            f"single:d{d}",
            merkle._tree_root_fused,
            (_sds((1 << d, 8), "uint32"), d),
            static_argnums=(1,),
        )
        for d in (6, 10)
    ]


def _merkle_many_args(batch: int, depth: int):
    return (_sds((batch, 1 << depth, 8), "uint32"),)


def _merkle_many_variants(mesh):
    from eth_consensus_specs_tpu.ops import merkle
    from eth_consensus_specs_tpu.parallel import mesh_ops

    depth = 10
    out = [
        Variant(
            "single",
            merkle._many_tree_root_fused,
            (*_merkle_many_args(8, depth), depth),
            static_argnums=(1,),
        )
    ]
    if mesh is not None:
        batch = mesh_ops.pad_to_shards(8, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                merkle._many_tree_root_sharded(mesh, depth),
                _merkle_many_args(batch, depth),
                mesh=mesh,
            )
        )
    return out


def _merkle_many_key_grid(mesh):
    """LIVE serve key fn (buckets.merkle_many_key) over the bucket grid
    vs the traced signature the dispatch actually compiles under."""
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    cfg = _default_buckets()
    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        shards = mesh_ops.shard_count(m)
        for depth in (4, 10, 12):
            for n in (1, 2, 3, 5, 8, 13, 16, 33, 64):
                key = buckets.merkle_many_key(n, depth, cfg, mesh=m)
                pad = key[1]
                batch = mesh_ops.pad_to_shards(pad, shards) if m is not None else pad
                sig = (
                    _canon_args(_merkle_many_args(batch, depth)),
                    depth,
                    mesh_ops.mesh_signature(m),
                )
                out.append((key, sig))
    return out


def _shuffle_variants(mesh):
    from eth_consensus_specs_tpu.ops import shuffle

    n, rounds = 512, 90
    num_chunks = (n + 255) // 256
    return [
        Variant(
            "single",
            shuffle._device_shuffle_kernel(n, rounds, num_chunks),
            (_sds((rounds * num_chunks, 16), "uint32"), _sds((rounds,), "int32")),
        )
    ]


def _fr_fft_variants(mesh):
    from eth_consensus_specs_tpu.ops import fr_fft

    n, stages = 256, 8
    tw = tuple(
        _sds((1 << i, fr_fft.FR.n_limbs), "uint64") for i in range(stages)
    )
    return [
        Variant(
            "single",
            fr_fft._compiled_fft(n, stages),
            (_sds((4, n, fr_fft.FR.n_limbs), "uint64"), *tw),
        )
    ]


def _g1_msm_variants(mesh):
    from eth_consensus_specs_tpu.ops import g1_msm
    from eth_consensus_specs_tpu.parallel import mesh_ops

    def args(lanes):
        return (
            _sds((lanes, 256), "uint64"),
            *[_sds((lanes, 13), "uint64")] * 3,
        )

    out = [Variant("single", g1_msm.msm_kernel, args(8))]
    if mesh is not None:
        lanes = g1_msm.mesh_lane_pad(8, mesh_ops.shard_count(mesh))
        out.append(
            Variant("mesh", g1_msm._sharded_fn(mesh, "msm"), args(lanes), mesh=mesh)
        )
    return out


def _bls_msm_args(items: int, lanes: int):
    return tuple([_sds((items, lanes, 13), "uint64")] * 3)


def _bls_msm_variants(mesh):
    from eth_consensus_specs_tpu.ops import g1_msm
    from eth_consensus_specs_tpu.parallel import mesh_ops

    out = [Variant("single", g1_msm.sum_many_kernel, _bls_msm_args(4, 8))]
    if mesh is not None:
        items = mesh_ops.pad_to_shards(4, mesh_ops.shard_count(mesh))
        out.append(
            Variant(
                "mesh",
                g1_msm._sharded_fn(mesh, "sum_many"),
                _bls_msm_args(items, 8),
                mesh=mesh,
            )
        )
    return out


def _bls_msm_key_grid(mesh):
    """LIVE serve key fn (buckets.bls_msm_key) over the committee grid
    vs the many_sum_shape padded avals the dispatch compiles under."""
    from eth_consensus_specs_tpu.ops.g1_msm import many_sum_shape
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    out = []
    for m in (None, mesh) if mesh is not None else (None,):
        shards = mesh_ops.shard_count(m)
        for items in (1, 2, 3, 5, 9, 16, 33):
            for lanes in (1, 3, 8, 64, 100):
                key = buckets.bls_msm_key(items, lanes, mesh=m)
                item_pad, lane_pad = many_sum_shape(items, lanes, shards)
                sig = (
                    _canon_args(_bls_msm_args(item_pad, lane_pad)),
                    mesh_ops.mesh_signature(m),
                )
                out.append((key, sig))
    return out


def _pairing_variants(mesh):
    from eth_consensus_specs_tpu.ops import pairing_device as pd

    def chunk_args(n_chunks):
        lead = (n_chunks,) if n_chunks else ()
        return (
            _sds((*lead, pd._CHUNK, pd.N_STEPS, 2, 2, pd.N_LIMBS), "uint64"),
            _sds((*lead, pd._CHUNK, pd.N_LIMBS), "uint64"),
            _sds((*lead, pd._CHUNK, pd.N_LIMBS), "uint64"),
            _sds((*lead, pd._CHUNK), "bool"),
        )

    out = [Variant("single", pd._miller_chunk_fold, chunk_args(0))]
    if mesh is not None:
        from eth_consensus_specs_tpu.parallel import mesh_ops

        shards = mesh_ops.shard_count(mesh)
        out.append(
            Variant(
                "mesh",
                pd._miller_sharded_fn(mesh, 1),
                chunk_args(shards),
                mesh=mesh,
            )
        )
    return out


def synthetic_state_root_meta(n: int = 64, extra_static: int = 0):
    """A StateRootMeta with every dynamic slot the altair+ impl resolves,
    without building a spec/object state. ``extra_static`` grows the
    top-level container (and so top_depth) — the key grid uses it to
    prove the compile key discriminates container shapes."""
    from eth_consensus_specs_tpu.ops.state_root import StateRootMeta

    dynamic = (
        "validators",
        "balances",
        "inactivity_scores",
        "previous_epoch_participation",
        "current_epoch_participation",
        "justification_bits",
        "previous_justified_checkpoint",
        "current_justified_checkpoint",
        "finalized_checkpoint",
    )
    n_fields = len(dynamic) + 16 + extra_static
    top_depth = max(n_fields - 1, 0).bit_length()
    return StateRootMeta(
        dynamic_slots=tuple(enumerate(dynamic)),
        n_validators=n,
        top_depth=top_depth,
    )


def _state_root_args(meta):
    from eth_consensus_specs_tpu.ops.state_root import StateRootArrays
    from eth_consensus_specs_tpu.ops.state_columns import JustificationState

    n = meta.n_validators
    arrays = StateRootArrays(
        val_node_a=_sds((n, 8), "uint32"),
        val_node_f=_sds((n, 8), "uint32"),
        slashed_chunk=_sds((n, 8), "uint32"),
        prev_part_flags=_sds((n,), "uint8"),
        top_chunks=_sds((1 << meta.top_depth, 8), "uint32"),
        zerohashes=_sds((41, 8), "uint32"),
    )
    just = JustificationState(
        current_epoch=_sds((), "uint64"),
        justification_bits=_sds((4,), "bool_"),
        prev_justified_epoch=_sds((), "uint64"),
        prev_justified_root=_sds((32,), "uint8"),
        cur_justified_epoch=_sds((), "uint64"),
        cur_justified_root=_sds((32,), "uint8"),
        finalized_epoch=_sds((), "uint64"),
        finalized_root=_sds((32,), "uint8"),
        block_root_prev=_sds((32,), "uint8"),
        block_root_cur=_sds((32,), "uint8"),
        slashings_sum=_sds((), "uint64"),
    )
    cols = (_sds((n,), "uint64"), _sds((n,), "uint64"), _sds((n,), "uint64"))
    return arrays, cols, just


def _state_root_variants(mesh):
    from eth_consensus_specs_tpu.ops import state_root as sr

    meta = synthetic_state_root_meta(64)
    arrays, (bal, eff, inact), just = _state_root_args(meta)

    def run(arrays, balances, effective_balance, inactivity_scores, just):
        return sr._post_epoch_state_root_impl(
            arrays, meta, balances, effective_balance, inactivity_scores, just
        )

    return [Variant("single", run, (arrays, bal, eff, inact, just))]


def _state_root_key_grid(mesh):
    """LIVE ops/state_root.state_root_compile_key over registry shapes
    vs the flattened input avals the graph traces under."""
    from eth_consensus_specs_tpu.ops.state_root import state_root_compile_key

    out = []
    for n in (64, 128, 256):
        for extra in (0, 40):  # two container widths -> two top_depths
            meta = synthetic_state_root_meta(n, extra_static=extra)
            key = state_root_compile_key(meta)
            sig = (
                _canon_args(_state_root_args(meta)),
                meta.top_depth,
                meta.dynamic_slots,
            )
            out.append((key, sig))
    return out


def _canon_args(args) -> tuple:
    """Canonical hashable form of a ShapeDtypeStruct pytree — the part
    of the jit cache key the shape grid varies."""
    import jax

    return tuple(
        (tuple(leaf.shape), str(leaf.dtype)) for leaf in jax.tree_util.tree_leaves(args)
    )


# ------------------------------------------------------------- registry --

_LIMB_DTYPES = frozenset({"uint64", "uint32", "int32", "bool"})

REGISTRY: tuple[KernelSpec, ...] = (
    KernelSpec(
        name="sha256",
        help="tiled vectorized SHA-256 (ops/sha256.sha256_tiled)",
        dtypes=frozenset({"uint32"}),
        donation_waiver="message (N,16) and digest (N,8) avals never alias; "
        "tiles are transient host uploads reused across levels",
        build_variants=_sha256_variants,
    ),
    KernelSpec(
        name="merkle",
        help="single-subtree device merkleization (ops/merkle)",
        # bool: the fori_loop predicate scalar; int32: its counter
        dtypes=frozenset({"uint32", "int32", "bool"}),
        donation_waiver="leaf buffer (2^d,8) vs root (8,) never alias; the "
        "resident-state seam (ROADMAP item 2) donates at the column level, "
        "not here",
        build_variants=_merkle_variants,
    ),
    KernelSpec(
        name="merkle_many",
        help="vmapped multi-tree merkleization, mesh tree-axis sharded",
        dtypes=frozenset({"uint32", "int32", "bool"}),
        donation_waiver="batched leaves (B,2^d,8) vs roots (B,8) never alias",
        build_variants=_merkle_many_variants,
        key_grid=_merkle_many_key_grid,
    ),
    KernelSpec(
        name="shuffle",
        help="whole-permutation swap-or-not shuffle (ops/shuffle)",
        dtypes=frozenset({"uint32", "int32", "bool"}),
        donation_waiver="decision blocks and pivots are read-only; the index "
        "plane lives in the loop carry, not an argument buffer",
        build_variants=_shuffle_variants,
    ),
    KernelSpec(
        name="fr_fft",
        help="batched BLS-scalar-field FFT (ops/fr_fft)",
        dtypes=_LIMB_DTYPES,
        donate=(0,),  # vals: private bit-reversed copy, aval == output
        build_variants=_fr_fft_variants,
    ),
    KernelSpec(
        name="g1_msm",
        help="G1 multi-scalar multiplication, mesh lane-axis sharded",
        dtypes=_LIMB_DTYPES,
        donation_waiver="lane arrays (N,13)x3 + bits (N,256) vs one Jacobian "
        "point (13,)x3 — no aval ever aliases an output",
        build_variants=_g1_msm_variants,
    ),
    KernelSpec(
        name="bls_msm",
        help="batched per-item G1 committee sums (the serve RLC seam), "
        "mesh item-axis sharded",
        dtypes=_LIMB_DTYPES,
        donation_waiver="committee lanes (I,L,13)x3 vs per-item points "
        "(I,13)x3 — shapes never alias",
        build_variants=_bls_msm_variants,
        key_grid=_bls_msm_key_grid,
    ),
    KernelSpec(
        name="pairing",
        help="chunked Miller accumulation + fold, mesh chunk-axis sharded",
        dtypes=frozenset({"uint64", "uint32", "uint8", "int32", "bool"}),
        donation_waiver="prepared coefficients are cached host constants "
        "(_PREP_CACHE) reused across batches — donating them would corrupt "
        "the cache",
        build_variants=_pairing_variants,
    ),
    KernelSpec(
        name="state_root",
        help="post-accounting-epoch BeaconState root (ops/state_root)",
        dtypes=frozenset({"uint32", "uint64", "uint8", "int32", "bool"}),
        donation_waiver="static tree arrays are reused every epoch "
        "(device-resident by design); donation lands with the in-place "
        "per-slot updates of ROADMAP item 2",
        build_variants=_state_root_variants,
        key_grid=_state_root_key_grid,
    ),
)

for _spec in REGISTRY:
    if not _spec.donate and not _spec.donation_waiver:
        raise AssertionError(
            f"kernel registry entry {_spec.name!r} declares neither donated "
            "argnums nor a donation waiver — silence is not a donation policy"
        )


def by_name() -> dict[str, KernelSpec]:
    return {s.name: s for s in REGISTRY}


def mesh_families(mesh) -> set[str]:
    """Families whose builders emit a mesh variant on this mesh —
    derived from the builders themselves (the authoritative source),
    not a hand-maintained list."""
    if mesh is None:
        return set()
    return {
        s.name
        for s in REGISTRY
        if any(v.mesh is not None for v in s.build_variants(mesh))
    }
