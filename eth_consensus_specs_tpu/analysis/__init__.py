"""analysis — project-native static analysis (speclint) + runtime lock watch.

Every scale PR so far shipped a review-hardening paragraph fixing the
same bug classes by hand: PR 6 needed ``os.register_at_fork`` hooks
because the gen pool forked children holding supervisor locks, PR 3
moved ``_H2G2_CACHE`` mutations under a lock, PR 4 deleted a deque
reservoir that mutated under a lock. This package machine-checks those
invariants so the next subsystem inherits them instead of re-learning
them:

  * ``analysis.lint`` — an AST lint engine with project-native rules
    (fork-safety, blocking-under-lock, lock-order, jit-purity,
    obs-discipline, env-registry, fault-site-registry), inline
    ``# speclint: disable=<rule>`` suppressions, and a ratcheting
    baseline. ``scripts/speclint.py`` is the CLI; CI gates zero
    non-baselined findings.
  * ``analysis.lockwatch`` — the runtime counterpart of the static
    lock-order rule: an opt-in (``ETH_SPECS_ANALYSIS_LOCKWATCH=1``)
    instrumented-lock wrapper that records per-thread acquisition
    orders and flags inversions observed live, cross-checking the
    static graph during tier-1 and serve_bench.
  * ``analysis.jaxlint`` + ``analysis.kernels`` — trace-level analysis
    BELOW the AST: every registered kernel family is abstract-evaled
    (``jax.make_jaxpr``, no execution) and its jaxprs checked for
    host transfers, missed/undeclared buffer donation, compile-key
    injectivity over the serve bucket grid, mesh-collective axis
    binding, constant bloat, and 64-bit dtype drift.
    ``scripts/jaxlint.py`` is the CLI; it shares speclint's baseline
    machinery and argparse front end (``analysis.cli``).

See docs/analysis.md for the rule tables and the PR-history bug each
rule encodes.
"""

from __future__ import annotations

from . import lockwatch  # noqa: F401  (public submodule; import-light)
