"""Executable model of the production deposit contract.

Behavioral parity target: solidity_deposit_contract/deposit_contract.sol —
the incremental Merkle tree (branch/zero_hashes update :69-79, :101-140),
`get_deposit_root` with the little-endian count mix-in (:80-96), and the
DepositEvent data layout checks (pubkey/credential/amount/signature
lengths, :104-117). The spec-side `deposit-contract.md` constants
(DEPOSIT_CONTRACT_TREE_DEPTH = 32) apply.

The hot loops (branch insert, root fold) run in the native C layer
(native/sha256_merkle.c) when a compiler is available, with a pure-Python
hashlib fallback — the same layering the reference gets from its C-backed
hashlib. The key cross-check (tested): the contract root equals
`hash_tree_root(List[DepositData, 2**32](deposits))`, which is how the
consensus spec consumes `state.eth1_data.deposit_root`.
"""

from __future__ import annotations

import ctypes
import hashlib

from eth_consensus_specs_tpu import native
from eth_consensus_specs_tpu.ssz.merkle import zerohashes

DEPOSIT_CONTRACT_TREE_DEPTH = 32
MAX_DEPOSIT_COUNT = 2**DEPOSIT_CONTRACT_TREE_DEPTH - 1


def _sha(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


_ZEROHASHES = [bytes(h) for h in zerohashes[:DEPOSIT_CONTRACT_TREE_DEPTH]]
_ZEROHASHES_FLAT = b"".join(_ZEROHASHES)


class DepositContract:
    """Incremental-Merkle deposit accumulator (deposit_contract.sol:64-141)."""

    def __init__(self):
        self.branch = [b"\x00" * 32] * DEPOSIT_CONTRACT_TREE_DEPTH
        self.deposit_count = 0

    # == views =============================================================

    def get_deposit_count(self) -> bytes:
        """uint64 little-endian, as the contract returns it (:97-99)."""
        return self.deposit_count.to_bytes(8, "little")

    def get_deposit_root(self) -> bytes:
        lib = native.get_lib()
        if lib is not None:
            out = (ctypes.c_uint8 * 32)()
            branch = (ctypes.c_uint8 * (32 * DEPOSIT_CONTRACT_TREE_DEPTH)).from_buffer_copy(
                b"".join(self.branch)
            )
            zeros = (ctypes.c_uint8 * len(_ZEROHASHES_FLAT)).from_buffer_copy(
                _ZEROHASHES_FLAT
            )
            lib.deposit_tree_root(
                branch, zeros, self.deposit_count, DEPOSIT_CONTRACT_TREE_DEPTH, out
            )
            return bytes(out)
        node = b"\x00" * 32
        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1:
                node = _sha(self.branch[height] + node)
            else:
                node = _sha(node + _ZEROHASHES[height])
            size >>= 1
        return _sha(node + self.get_deposit_count() + b"\x00" * 24)

    # == mutation ==========================================================

    def deposit(
        self,
        pubkey: bytes,
        withdrawal_credentials: bytes,
        amount_gwei: int,
        signature: bytes,
    ) -> bytes:
        """Insert a deposit; returns its leaf (DepositData root). Mirrors
        the contract's input checks and leaf construction (:101-140)."""
        assert len(pubkey) == 48, "invalid pubkey length"
        assert len(withdrawal_credentials) == 32, "invalid credentials length"
        assert len(signature) == 96, "invalid signature length"
        assert amount_gwei >= 1_000_000_000, "deposit value too low"
        assert self.deposit_count < MAX_DEPOSIT_COUNT, "merkle tree full"

        amount = int(amount_gwei).to_bytes(8, "little")
        pubkey_root = _sha(pubkey + b"\x00" * 16)
        signature_root = _sha(
            _sha(signature[:64]) + _sha(signature[64:] + b"\x00" * 32)
        )
        node = _sha(
            _sha(pubkey_root + withdrawal_credentials)
            + _sha(amount + b"\x00" * 24 + signature_root)
        )
        self._insert(node)
        return node

    def insert_leaf(self, leaf: bytes) -> None:
        """Insert a precomputed DepositData root (test/vector ingestion)."""
        assert len(leaf) == 32
        assert self.deposit_count < MAX_DEPOSIT_COUNT, "merkle tree full"
        self._insert(bytes(leaf))

    def _insert(self, node: bytes) -> None:
        lib = native.get_lib()
        if lib is not None:
            branch = bytearray(b"".join(self.branch))
            buf = (ctypes.c_uint8 * len(branch)).from_buffer(branch)
            leaf = (ctypes.c_uint8 * 32).from_buffer_copy(node)
            lib.deposit_tree_insert(
                buf, self.deposit_count, leaf, DEPOSIT_CONTRACT_TREE_DEPTH
            )
            self.branch = [
                bytes(branch[32 * i : 32 * (i + 1)])
                for i in range(DEPOSIT_CONTRACT_TREE_DEPTH)
            ]
            self.deposit_count += 1
            return
        size = self.deposit_count + 1
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size & 1:
                self.branch[height] = node
                break
            node = _sha(self.branch[height] + node)
            size >>= 1
        self.deposit_count += 1
