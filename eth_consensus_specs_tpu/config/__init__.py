"""Two-tier configuration system.

* **Presets** (compile-time): structural sizes and limits. They fix every
  SSZ shape and every jit-time constant — on TPU this is a feature: all
  shapes are static at trace time. One preset = one merged dict over the
  per-fork preset files (duplicate keys across files are an error, matching
  the reference loader's strictness, cf. pysetup/generate_specs.py:66-82).

* **Configs** (runtime): fork schedule, network params, churn — a frozen
  namespace; changing it never changes compiled shapes (reference analogue:
  the Configuration NamedTuple, pysetup/helpers.py:128-138).

Value parsing: ints stay ints (arbitrary precision), 0x-prefixed strings
become `bytes`, names stay strings, lists of mappings (BLOB_SCHEDULE) are
tuples of frozen namespaces.
"""

from __future__ import annotations

import os
from functools import lru_cache
from types import MappingProxyType
from typing import Any, Mapping

import yaml

_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

# Fork lineage: each fork inherits every ancestor's preset constants.
FORK_ORDER = [
    "phase0",
    "altair",
    "bellatrix",
    "capella",
    "deneb",
    "electra",
    "fulu",
    "gloas",
]


def previous_fork(fork: str) -> str | None:
    i = FORK_ORDER.index(fork)
    return FORK_ORDER[i - 1] if i > 0 else None


def fork_lineage(fork_name: str) -> str:
    """Mainline fork a spec's semantics sit on: itself for mainline forks,
    the registered base fork for features. Unknown names are a hard error
    (a feature module missing its FEATURE_BASE_FORK entry must not be
    silently treated as phase0)."""
    if fork_name in FORK_ORDER:
        return fork_name
    from eth_consensus_specs_tpu.forks.features import FEATURE_BASE_FORK

    try:
        return FEATURE_BASE_FORK[fork_name]
    except KeyError:
        raise KeyError(
            f"{fork_name!r} is neither a mainline fork nor a registered feature"
        ) from None


def is_post_fork(fork_name: str, target: str) -> bool:
    """True when `fork_name`'s lineage is at or after `target`."""
    return FORK_ORDER.index(fork_lineage(fork_name)) >= FORK_ORDER.index(target)


def _parse_value(v: Any) -> Any:
    if isinstance(v, str):
        if v.startswith("0x"):
            return bytes.fromhex(v[2:])
        if v.isdigit():
            return int(v)
        if v in ("true", "True", "false", "False"):
            return v in ("true", "True")
        return v
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        return v
    if isinstance(v, list):
        return tuple(_parse_value(e) for e in v)
    if isinstance(v, dict):
        return FrozenNamespace({k: _parse_value(x) for k, x in v.items()})
    return v


class FrozenNamespace:
    """Immutable attribute+mapping view over parsed config values."""

    def __init__(self, values: Mapping[str, Any]):
        object.__setattr__(self, "_values", MappingProxyType(dict(values)))

    def __getattr__(self, name: str) -> Any:
        try:
            return object.__getattribute__(self, "_values")[name]
        except KeyError:
            raise AttributeError(name) from None

    def __getitem__(self, name: str) -> Any:
        return self._values[name]

    def __contains__(self, name: str) -> bool:
        return name in self._values

    def __iter__(self):
        return iter(self._values)

    def __setattr__(self, name, value):
        raise AttributeError("configuration is immutable; derive a new one with replace()")

    def keys(self):
        return self._values.keys()

    def items(self):
        return self._values.items()

    def as_dict(self) -> dict:
        return dict(self._values)

    def replace(self, **overrides) -> "FrozenNamespace":
        d = dict(self._values)
        d.update(overrides)
        return FrozenNamespace(d)

    def __repr__(self):
        return f"FrozenNamespace({dict(self._values)!r})"

    def __eq__(self, other):
        return isinstance(other, FrozenNamespace) and other.as_dict() == self.as_dict()

    def __hash__(self):
        # immutable by construction; hashable so override sets containing
        # nested namespaces (e.g. BLOB_SCHEDULE entries) can key lru caches
        return hash(tuple(sorted(self._values.items())))


def _load_yaml(path: str) -> dict:
    # BaseLoader keeps every scalar a string so unquoted 0x-hex survives
    # (safe_load would parse it to int, silently destroying byte values —
    # the same strictness the reference loader applies).
    with open(path) as f:
        raw = yaml.load(f, Loader=yaml.BaseLoader) or {}
    return {k: _parse_value(v) for k, v in raw.items()}


@lru_cache(maxsize=None)
def load_preset(preset_name: str, fork: str = FORK_ORDER[-1]) -> FrozenNamespace:
    """Merged preset constants for `fork` and all its ancestors.

    Duplicate keys across fork files are an error (a fork renames rather
    than redefines, e.g. INACTIVITY_PENALTY_QUOTIENT_ALTAIR).
    """
    merged: dict[str, Any] = {}
    lineage = FORK_ORDER[: FORK_ORDER.index(fork) + 1]
    for f in lineage:
        path = os.path.join(_DATA_DIR, "presets", preset_name, f"{f}.yaml")
        if not os.path.exists(path):
            continue  # fork preset not yet defined
        values = _load_yaml(path)
        dup = merged.keys() & values.keys()
        if dup:
            raise ValueError(f"duplicate preset keys across forks: {sorted(dup)}")
        merged.update(values)
    if not merged:
        raise FileNotFoundError(f"no preset files for preset={preset_name}")
    return FrozenNamespace(merged)


@lru_cache(maxsize=None)
def load_config(config_name: str) -> FrozenNamespace:
    path = os.path.join(_DATA_DIR, "configs", f"{config_name}.yaml")
    return FrozenNamespace(_load_yaml(path))
