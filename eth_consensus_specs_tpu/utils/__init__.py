"""Runtime support layer: crypto backend switch, hashing, kzg setup tooling,
merkle helpers, compilation cache. The seam the spec modules import
(mirrors reference tests/core/pyspec/eth2spec/utils/)."""
