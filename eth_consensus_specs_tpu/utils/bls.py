"""BLS backend switch — the crypto seam of the framework.

Mirrors the reference's multi-backend switch surface (reference:
tests/core/pyspec/eth2spec/utils/bls.py:57-296): capitalized verb API used
verbatim by spec code and tests, a `bls_active` kill-switch that replaces
signature checks with stub-byte equality for fast non-crypto tests, and
selectable backends. Backends here:

  * "pyspec": the first-party pure-Python oracle (crypto/).
  * "tpu":    batched device verification (ops/bls_batch) with host fallback
              for single operations — the reference's milagro/arkworks slot.

Stub mode semantics match the reference's bls_active=False behavior
(utils/bls.py:71-138): Sign returns a deterministic stub, Verify accepts
anything shaped right.
"""

from __future__ import annotations

from functools import wraps

from eth_consensus_specs_tpu.crypto import signature as _sig
from eth_consensus_specs_tpu.crypto import curve as _curve
from eth_consensus_specs_tpu.crypto import pairing as _pairing
from eth_consensus_specs_tpu.crypto.fields import R as CURVE_ORDER
from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2 as _hash_to_g2

bls_active = True
_backend = "pyspec"

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
G2_POINT_AT_INFINITY = bytes([0xC0]) + b"\x00" * 95
G1_POINT_AT_INFINITY = bytes([0xC0]) + b"\x00" * 47


def use_pyspec() -> None:
    global _backend
    _backend = "pyspec"


def use_tpu() -> None:
    """Route batchable verification through the device backend."""
    global _backend
    _backend = "tpu"


def use_fastest() -> None:
    use_pyspec()


def backend_name() -> str:
    return _backend


from contextlib import contextmanager


@contextmanager
def inactive():
    """Stub-signature mode for the enclosed block (save/restore of the
    bls_active kill-switch — the shared form of the toggle the scenario
    drivers need; reference analogue: utils/bls.py bls_active handling)."""
    global bls_active
    prev = bls_active
    bls_active = False
    try:
        yield
    finally:
        bls_active = prev


def only_with_bls(alt_return=None):
    """Decorator: run the wrapped check only when bls_active (reference
    analogue: utils/bls.py:124-138)."""

    def deco(fn):
        @wraps(fn)
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)

        return wrapper

    return deco


# --- high-level verbs (spec API) ------------------------------------------


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(sk: int, message: bytes) -> bytes:
    return _sig.sign(int(sk), bytes(message))


@only_with_bls(alt_return=True)
def Verify(pk: bytes, message: bytes, sig: bytes) -> bool:
    return _sig.verify(bytes(pk), bytes(message), bytes(sig))


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures: list) -> bytes:
    return _sig.aggregate([bytes(s) for s in signatures])


@only_with_bls(alt_return=True)
def AggregateVerify(pks: list, messages: list, sig: bytes) -> bool:
    return _sig.aggregate_verify([bytes(p) for p in pks], [bytes(m) for m in messages], bytes(sig))


@only_with_bls(alt_return=True)
def FastAggregateVerify(pks: list, message: bytes, sig: bytes) -> bool:
    # routed service (serve/): concurrent callers coalesce into one RLC
    # pairing per flush. routed() is None on the service's own threads,
    # so the service's internal verification never re-enters here.
    from eth_consensus_specs_tpu import serve

    svc = serve.routed()
    if svc is not None:
        # a typed shed (queue caps, or every front-door replica
        # overloaded) is flow control, not an answer: honor the
        # retry-after hint and resubmit — a synchronous spec-code caller
        # has nothing better to do with its slot than wait its turn
        import time as _time

        while True:
            try:
                return svc.submit_bls_aggregate(
                    [bytes(p) for p in pks], bytes(message), bytes(sig)
                ).result()
            except serve.Overloaded as exc:
                _time.sleep(min(exc.retry_after_s, 5.0))
    if _backend == "tpu":
        from eth_consensus_specs_tpu.ops import bls_batch

        return bls_batch.fast_aggregate_verify_device(
            [bytes(p) for p in pks], bytes(message), bytes(sig)
        )
    return _sig.fast_aggregate_verify([bytes(p) for p in pks], bytes(message), bytes(sig))


@only_with_bls(alt_return=STUB_PUBKEY)
def AggregatePKs(pubkeys: list) -> bytes:
    return _sig.aggregate_pks([bytes(p) for p in pubkeys])


@only_with_bls(alt_return=True)
def KeyValidate(pk: bytes) -> bool:
    return _sig.key_validate(bytes(pk))


@only_with_bls(alt_return=STUB_PUBKEY)
def SkToPk(sk: int) -> bytes:
    return _sig.sk_to_pk(int(sk))


# --- low-level group API (reference utils/bls.py:224-296) -----------------


def add(a, b):
    return a + b


def multiply(p, k: int):
    return p.mul(int(k))


def neg(p):
    return -p


def multi_exp(points: list, scalars: list):
    """Sum of scalar*point (native Pippenger MSM when available; the batched
    device MSM lives in ops/bls_batch)."""
    if len(points) == 0 or len(points) != len(scalars):
        raise ValueError("multi_exp: mismatched inputs")
    from eth_consensus_specs_tpu.crypto import native_bridge as nb
    from eth_consensus_specs_tpu.crypto.fields import Fq, Fq2

    # Only take the native MSM when every scalar is already < r: for points
    # with a cofactor component [s]P != [s mod r]P, so reducing here would
    # diverge from the pure path's unreduced p.mul(s). Out-of-range scalars
    # (never produced by spec code) fall through to the bit-exact pure path.
    lifted = [int(s) for s in scalars]
    if nb.enabled() and all(0 <= s < CURVE_ORDER for s in lifted):
        reduced = lifted
        if all(p.is_infinity() or isinstance(p.x, Fq) for p in points):
            raw = nb.g1_msm(
                [None if p.is_infinity() else (p.x.n, p.y.n) for p in points], reduced
            )
            if raw is None:
                return _curve.g1_infinity()
            return _curve.Point(Fq(raw[0]), Fq(raw[1]), _curve.B1)
        if all(p.is_infinity() or isinstance(p.x, Fq2) for p in points):
            raw = nb.g2_msm(
                [
                    None
                    if p.is_infinity()
                    else ((p.x.c0.n, p.x.c1.n), (p.y.c0.n, p.y.c1.n))
                    for p in points
                ],
                reduced,
            )
            if raw is None:
                return _curve.g2_infinity()
            (x0, x1), (y0, y1) = raw
            return _curve.Point(Fq2(Fq(x0), Fq(x1)), Fq2(Fq(y0), Fq(y1)), _curve.B2)
    acc = None
    for p, s in zip(points, scalars):
        term = p.mul(int(s))
        acc = term if acc is None else acc + term
    return acc


def pairing_check(pairs: list) -> bool:
    return _pairing.pairing_check(pairs)


def hash_to_G2(message: bytes):
    return _hash_to_g2(bytes(message))


def signature_to_G2(sig: bytes):
    return _curve.g2_from_bytes(bytes(sig))


def pubkey_to_G1(pk: bytes):
    return _curve.g1_from_bytes(bytes(pk))


def G1_to_pubkey(p) -> bytes:
    return _curve.g1_to_bytes(p)


def G2_to_signature(p) -> bytes:
    return _curve.g2_to_bytes(p)


class Scalar:
    """Field element mod the curve order r — the reference's `bls.Scalar`
    surface (reference utils/bls.py:35-54 py_ecc_Scalar / arkworks Scalar)
    that the deneb/fulu polynomial markdown builds `BLSFieldElement` on.
    Arithmetic reduces mod r; int operands coerce."""

    field_modulus = CURVE_ORDER
    __slots__ = ("n",)

    def __init__(self, value):
        self.n = int(value) % CURVE_ORDER

    def _coerce(self, o):
        if isinstance(o, Scalar):
            return o.n
        return int(o)

    def __add__(self, o):
        return type(self)(self.n + self._coerce(o))

    __radd__ = __add__

    def __sub__(self, o):
        return type(self)(self.n - self._coerce(o))

    def __rsub__(self, o):
        return type(self)(self._coerce(o) - self.n)

    def __mul__(self, o):
        return type(self)(self.n * self._coerce(o))

    __rmul__ = __mul__

    def __neg__(self):
        return type(self)(-self.n)

    def __pow__(self, e):
        return type(self)(pow(self.n, int(e), CURVE_ORDER))

    def pow(self, exp):
        return self ** int(exp)

    def inverse(self):
        return type(self)(pow(self.n, CURVE_ORDER - 2, CURVE_ORDER))

    def __truediv__(self, o):
        return self * type(self)(self._coerce(o)).inverse()

    def __eq__(self, o):
        if isinstance(o, (Scalar, int)):
            return self.n == self._coerce(o) % CURVE_ORDER
        return NotImplemented

    def __hash__(self):
        return hash(("Scalar", self.n))

    def __int__(self):
        return self.n

    def __index__(self):
        return self.n

    def __repr__(self):
        return f"Scalar({self.n})"


# Serialization aliases under the reference's KZG-facing names (reference
# utils/bls.py:345-392; the deneb polynomial-commitments markdown calls
# bls.G1_to_bytes48 / bls.bytes48_to_G1 / bls.bytes96_to_G2 directly).
def G1_to_bytes48(p) -> bytes:
    return _curve.g1_to_bytes(p)


def bytes48_to_G1(b: bytes):
    return _curve.g1_from_bytes(bytes(b))


def G2_to_bytes96(p) -> bytes:
    return _curve.g2_to_bytes(p)


def bytes96_to_G2(b: bytes):
    return _curve.g2_from_bytes(bytes(b))


def Z1():
    return _curve.g1_infinity()


def Z2():
    return _curve.g2_infinity()


def G1():
    return _curve.g1_generator()


def G2():
    return _curve.g2_generator()
