"""First-party hexary Merkle-Patricia trie root computation.

The reference repo computes EL transaction/receipt/withdrawal roots with
the ``trie`` pip package's ``HexaryTrie`` (reference: tests/core/pyspec/
eth2spec/test/helpers/execution_payload.py:6, 100-110); this is a
self-contained equivalent that builds the trie functionally from the full
key set and returns the root hash, which is all the EL fakes need (no
incremental updates, no proofs, no deletions).

Node model per the Ethereum yellow paper, appendix D:
- leaf:      [hex-prefix(remaining-nibbles, t=1), value]
- extension: [hex-prefix(shared-nibbles,    t=0), ref(child)]
- branch:    [ref(child_0) ... ref(child_15), value]
- ref(node): rlp(node) if len(rlp(node)) < 32 else keccak256(rlp(node)),
  except the root, which is always hashed.
Empty trie root: keccak256(rlp(b'')).
"""

from __future__ import annotations

from .keccak import keccak_256
from .rlp import rlp_encode

EMPTY_TRIE_ROOT = keccak_256(rlp_encode(b""))


def _nibbles(key: bytes) -> tuple[int, ...]:
    out = []
    for byte in key:
        out.append(byte >> 4)
        out.append(byte & 0x0F)
    return tuple(out)


def _hex_prefix(nibbles: tuple[int, ...], is_leaf: bool) -> bytes:
    """Yellow-paper hex-prefix encoding: flag nibble carries parity + leaf bit."""
    flag = 2 * int(is_leaf)
    if len(nibbles) % 2 == 1:
        packed = [(flag + 1) << 4 | nibbles[0]]
        rest = nibbles[1:]
    else:
        packed = [flag << 4]
        rest = nibbles
    for i in range(0, len(rest), 2):
        packed.append(rest[i] << 4 | rest[i + 1])
    return bytes(packed)


def _node_ref(node) -> bytes | list:
    encoded = rlp_encode(node)
    if len(encoded) < 32:
        return node
    return keccak_256(encoded)


def _build(items: list[tuple[tuple[int, ...], bytes]], depth: int):
    """Structural node for the given (nibble-key, value) set; keys distinct."""
    if not items:
        return b""
    if len(items) == 1:
        key, value = items[0]
        return [_hex_prefix(key[depth:], True), value]

    # Longest common prefix below `depth` across all keys → extension node.
    first_key = items[0][0]
    common = 0
    while all(
        len(key) > depth + common and key[depth + common] == first_key[depth + common]
        for key, _ in items
    ):
        common += 1
    if common > 0:
        child = _build(items, depth + common)
        return [_hex_prefix(first_key[depth : depth + common], False), _node_ref(child)]

    # Branch node: split on the nibble at `depth`.
    buckets: list[list] = [[] for _ in range(16)]
    branch_value = b""
    for key, value in items:
        if len(key) == depth:
            branch_value = value
        else:
            buckets[key[depth]].append((key, value))
    slots = []
    for bucket in buckets:
        if not bucket:
            slots.append(b"")
        else:
            slots.append(_node_ref(_build(bucket, depth + 1)))
    return slots + [branch_value]


def trie_root(entries: dict[bytes, bytes]) -> bytes:
    """Root hash of the MPT mapping each key to its value.

    Empty values are skipped, matching HexaryTrie.set semantics where
    setting b'' deletes the key (reference: execution_payload.py:105-106).
    """
    items = sorted(
        (_nibbles(key), value) for key, value in entries.items() if value != b""
    )
    if not items:
        return EMPTY_TRIE_ROOT
    return keccak_256(rlp_encode(_build(items, 0)))


def indexed_trie_root(values: list[bytes]) -> bytes:
    """Root of patriciaTrie(rlp(index) => value), the EIP-2718 shape used
    for transaction/receipt/withdrawal roots (reference:
    execution_payload.py:100-110)."""
    return trie_root({rlp_encode(i): value for i, value in enumerate(values)})
