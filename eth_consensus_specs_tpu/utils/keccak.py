"""First-party Keccak-256 (the pre-NIST-padding SHA-3 variant Ethereum's
execution layer uses for every hash: block hashes, trie node refs, RLP
commitment roots).

The reference repo pulls this from the ``eth-hash`` pip package
(reference: tests/core/pyspec/eth2spec/test/helpers/execution_payload.py:3);
that package is not in this environment, and the EL-fake machinery
(RLP header hashing, Merkle-Patricia trie roots) needs it, so this is a
self-contained implementation of Keccak-f[1600] with rate 1088 / capacity
512 and the legacy 0x01 domain padding.

Host-side only: these hashes run a handful of times per test to fake EL
data structures — never in the TPU compute path (the consensus layer's
hash is SHA-256, see ssz/hashing.py).
"""

from __future__ import annotations

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# Rotation offsets r[x][y] from the Keccak specification.
_ROTATIONS = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

_MASK = (1 << 64) - 1
_RATE_BYTES = 136  # 1088-bit rate for Keccak-256


def _rotl(value: int, shift: int) -> int:
    return ((value << shift) | (value >> (64 - shift))) & _MASK


def _keccak_f(lanes: list[list[int]]) -> None:
    """In-place Keccak-f[1600] permutation over a 5x5 lane state."""
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [lanes[x][0] ^ lanes[x][1] ^ lanes[x][2] ^ lanes[x][3] ^ lanes[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                lanes[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(lanes[x][y], _ROTATIONS[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                lanes[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y] & _MASK) & b[(x + 2) % 5][y])
        # iota
        lanes[0][0] ^= rc


def keccak_256(data: bytes) -> bytes:
    """Keccak-256 digest with the legacy 0x01 multi-rate padding
    (NOT the NIST SHA3-256 0x06 padding)."""
    lanes = [[0] * 5 for _ in range(5)]

    # Absorb full rate-sized blocks of the padded message.
    padded = bytearray(data)
    pad_len = _RATE_BYTES - (len(padded) % _RATE_BYTES)
    padded += b"\x01" + b"\x00" * (pad_len - 2) + b"\x80" if pad_len >= 2 else b"\x81"

    for block_start in range(0, len(padded), _RATE_BYTES):
        block = padded[block_start : block_start + _RATE_BYTES]
        for i in range(_RATE_BYTES // 8):
            lane = int.from_bytes(block[8 * i : 8 * i + 8], "little")
            x, y = i % 5, i // 5
            lanes[x][y] ^= lane
        _keccak_f(lanes)

    # Squeeze 32 bytes (fits inside one rate block).
    out = bytearray()
    for i in range(4):
        x, y = i % 5, i // 5
        out += lanes[x][y].to_bytes(8, "little")
    return bytes(out)
