"""First-party RLP (Recursive Length Prefix) encoding.

The reference repo uses the ``rlp`` pip package with explicit sedes
schemas (reference: tests/core/pyspec/eth2spec/test/helpers/
execution_payload.py:4-5, 134-190); here the helpers pass plain Python
values — ``bytes`` and non-negative ``int`` (big-endian minimal) and
(nested) lists thereof — which covers every EL structure the test fakes
build: block headers, withdrawals, EIP-7685 request payloads, and trie
keys. Encoding only: the consensus layer never decodes RLP.
"""

from __future__ import annotations


def encode_int(value: int) -> bytes:
    """Big-endian minimal integer payload (0 encodes as the empty string)."""
    if value < 0:
        raise ValueError("RLP integers are non-negative")
    if value == 0:
        return b""
    return value.to_bytes((value.bit_length() + 7) // 8, "big")


def _length_prefix(length: int, short_offset: int) -> bytes:
    if length < 56:
        return bytes([short_offset + length])
    length_bytes = encode_int(length)
    return bytes([short_offset + 55 + len(length_bytes)]) + length_bytes


def rlp_encode(item) -> bytes:
    """RLP-encode bytes / int / (nested) list-or-tuple of the same."""
    if isinstance(item, int) and not isinstance(item, bool):
        item = encode_int(item)
    if isinstance(item, (bytes, bytearray, memoryview)):
        payload = bytes(item)
        if len(payload) == 1 and payload[0] < 0x80:
            return payload
        return _length_prefix(len(payload), 0x80) + payload
    if isinstance(item, (list, tuple)):
        payload = b"".join(rlp_encode(sub) for sub in item)
        return _length_prefix(len(payload), 0xC0) + payload
    raise TypeError(f"cannot RLP-encode {type(item).__name__}")
