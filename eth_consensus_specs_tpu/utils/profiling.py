"""Tracing/profiling helpers (SURVEY §5: the reference has none — the TPU
build adds real tracing via the jax profiler).

`trace(logdir)` wraps a region in a jax profiler trace viewable in
TensorBoard/Perfetto; `timed(fn)` gives quick wall-clock numbers with
`block_until_ready` so async dispatch doesn't lie."""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Profile a region: `with trace("/tmp/jax-trace"): step(...)`."""
    import jax

    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def timed(fn, *args, repeats: int = 3, warmup: int = 1, **kwargs):
    """(best_seconds, result) over `repeats` runs after `warmup` calls;
    blocks on device results so dispatch isn't measured as compute."""
    import jax

    result = None
    for _ in range(max(warmup, 0)):
        result = jax.block_until_ready(fn(*args, **kwargs))
    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args, **kwargs))
        best = min(best, time.perf_counter() - t0)
    return best, result


def annotate(name: str):
    """Named sub-region inside a trace (shows as a span in the viewer)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
