"""Persistent XLA compilation cache.

The unrolled SHA-256/limb kernels trade compile time for runtime; caching
compiled executables across processes makes that cost one-time per machine
instead of one-time per run (bench and test drivers call this first)."""

from __future__ import annotations

import os

_enabled = False
_enabled_dir: str | None = None


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Accelerator backends only. XLA:CPU cache entries are AOT executables
    pinned to the compiling host's machine features (avx512 etc.); loading
    one on a different CPU is accepted with a warning and then executes
    garbage (observed: infinite hang). TPU executables are
    topology-portable, and that's also where recompiles actually hurt."""
    global _enabled
    import jax

    try:
        backend = jax.default_backend()
    except Exception:
        # Backend init failure (e.g. TPU tunnel down) — the caller decides
        # how to fall back; cache setup must never be the crash site.
        return None
    if backend == "cpu":
        return None
    if cache_dir is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        cache_dir = os.path.join(repo_root, ".jax_cache")
    global _enabled_dir
    if not _enabled:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
        _enabled_dir = cache_dir
    return _enabled_dir


def cache_dir_path() -> str:
    """The cache directory actually enabled this process, falling back to
    the default location — keeps the warm sentinel co-located with the
    executables it vouches for even under a custom cache_dir."""
    if _enabled_dir is not None:
        return _enabled_dir
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, ".jax_cache")


def warm_sentinel(stage: str, backend: str) -> str:
    """Marker file recording that a device chain (`pairing`, `h2c`, ...)
    compiled AND executed to completion for `backend` with the entries
    persisted in the cache.  Lets the bench attempt a device stage only
    when a warm start is plausible — a cold compile of these chains can
    exceed a whole section budget (round-3 lesson: never let one slow
    compile strand a measurement).  The filename is built HERE only, so
    producers (the kernels' mark_warm) and consumers (bench) can never
    drift apart."""
    return os.path.join(cache_dir_path(), f"device_{stage}_warm.{backend}")


def pairing_warm_sentinel(backend: str) -> str:
    return warm_sentinel("pairing", backend)


def mark_warm(stage: str) -> None:
    """Write the warm sentinel for `stage` — call strictly AFTER the
    chain's results have been materialized on host (a sentinel written
    before a runtime failure would keep steering later runs into the
    broken path).  No-op without the persistent cache or on cpu."""
    try:
        if not _enabled:
            return
        import jax

        backend = jax.default_backend()
        if backend == "cpu":
            return
        with open(warm_sentinel(stage, backend), "w") as fh:
            fh.write("ok\n")
    except Exception:
        pass
