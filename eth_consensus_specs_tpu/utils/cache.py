"""Persistent XLA compilation cache.

The unrolled SHA-256/limb kernels trade compile time for runtime; caching
compiled executables across processes makes that cost one-time per machine
instead of one-time per run (bench and test drivers call this first)."""

from __future__ import annotations

import os

_enabled = False


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    global _enabled
    import jax

    if cache_dir is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        cache_dir = os.path.join(repo_root, ".jax_cache")
    if not _enabled:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    return cache_dir
