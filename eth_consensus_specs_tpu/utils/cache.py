"""Persistent XLA compilation cache.

The unrolled SHA-256/limb kernels trade compile time for runtime; caching
compiled executables across processes makes that cost one-time per machine
instead of one-time per run (bench and test drivers call this first)."""

from __future__ import annotations

import os

_enabled = False
_enabled_dir: str | None = None


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Accelerator backends only. XLA:CPU cache entries are AOT executables
    pinned to the compiling host's machine features (avx512 etc.); loading
    one on a different CPU is accepted with a warning and then executes
    garbage (observed: infinite hang). TPU executables are
    topology-portable, and that's also where recompiles actually hurt."""
    global _enabled
    import jax

    try:
        backend = jax.default_backend()
    except Exception:
        # Backend init failure (e.g. TPU tunnel down) — the caller decides
        # how to fall back; cache setup must never be the crash site.
        return None
    if backend == "cpu":
        return None
    if cache_dir is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        cache_dir = os.path.join(repo_root, ".jax_cache")
    global _enabled_dir
    if not _enabled:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
        _enabled_dir = cache_dir
    return _enabled_dir


def cache_dir_path() -> str:
    """The cache directory actually enabled this process, falling back to
    the default location — keeps the warm sentinel co-located with the
    executables it vouches for even under a custom cache_dir."""
    if _enabled_dir is not None:
        return _enabled_dir
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, ".jax_cache")


def pairing_warm_sentinel(backend: str) -> str:
    """Marker file recording that the device pairing chain compiled to
    completion for `backend` with the entries persisted in the cache.
    Lets the bench attempt the device pairing only when a warm start is
    plausible — a cold compile of the Miller/final-exp chain can exceed
    the whole section budget (round-3 lesson: never let one slow compile
    strand a measurement)."""
    return os.path.join(cache_dir_path(), f"device_pairing_warm.{backend}")
