"""Model-based fork-choice compliance generation.

Reference analogue: tests/generators/compliance_runners/fork_choice/ —
there, MiniZinc constraint models enumerate block-tree shapes and
justification links, instantiators realize them into on_tick/on_block/
on_attestation event sequences, and mutation operators reorder/duplicate
events. MiniZinc is an external C++ solver; here the (small) constraint
space is enumerated directly in Python — same artifact, no solver
dependency:

  1. `enumerate_block_trees` — all parent-vector trees of n blocks under
     branching constraints (the "block tree shape" model, block_tree.mzn).
  2. `instantiate_scenario` — realize a tree into slots/blocks/attestation
     events against a real genesis state.
  3. mutations — event reordering (parent-after-child redelivery) and
     attestation duplication (mutation_operators.py analogue).
  4. `run_scenario` — replay events into a fresh Store, asserting the
     universal invariants (head known, head descends from justified root,
     store time monotone).

The produced step sequences follow the fork-choice vector format
(tests/formats/fork_choice/README.md:28-80): a list of dicts with `tick`/
`block`/`attestation`/`checks` entries, consumable by the dumper."""

from __future__ import annotations

import random
from typing import Iterator

from eth_consensus_specs_tpu.ssz import hash_tree_root


def enumerate_block_trees(n_blocks: int, max_children: int = 2) -> Iterator[tuple[int, ...]]:
    """All rooted trees over n_blocks nodes as parent vectors
    (parents[i] < i; node 0 is the anchor/genesis). The branching bound
    mirrors the compliance model's shape constraints."""
    assert n_blocks >= 1

    def rec(parents: list[int]) -> Iterator[tuple[int, ...]]:
        i = len(parents)
        if i == n_blocks:
            yield tuple(parents)
            return
        for p in range(i):
            # parents[0] is node 0's placeholder, not a child edge
            if parents[1:].count(p) < max_children:
                yield from rec(parents + [p])

    # node 0 has no parent; start enumeration at node 1
    if n_blocks == 1:
        yield (0,)
        return
    for tree in rec([0]):
        yield tree


def instantiate_scenario(spec, genesis_state, tree: tuple[int, ...], *, attest: bool = True,
                         rng: random.Random | None = None) -> list[dict]:
    """Realize a parent-vector tree into an ordered event sequence.

    Returns fork-choice steps: [{"tick": t}, {"block": signed_block},
    {"attestation": att}, ..., {"checks": {...}}]."""
    from eth_consensus_specs_tpu.test_infra.attestations import get_valid_attestation
    from eth_consensus_specs_tpu.test_infra.block import (
        build_empty_block,
        state_transition_and_sign_block,
    )

    rng = rng or random.Random(1)
    n = len(tree)
    # assign slots: each child lands 1 slot after its parent, with sibling
    # separation so proposers differ (branch i gets +index skew)
    states = {0: genesis_state.copy()}
    slots = {0: int(genesis_state.slot)}
    steps: list[dict] = []
    blocks = {}
    sibling_rank: dict[int, int] = {}
    for i in range(1, n):
        parent = tree[i]
        rank = sibling_rank.get(parent, 0)
        sibling_rank[parent] = rank + 1
        slot = slots[parent] + 1 + rank  # siblings at distinct slots
        parent_state = states[parent].copy()
        block = build_empty_block(spec, parent_state, slot=slot)
        if attest and rng.random() < 0.3 and slot >= 2:
            # embedded attestation (carried in the block body)
            probe = parent_state.copy()
            att_slot = slot - 1
            if att_slot > int(probe.slot):
                spec.process_slots(probe, att_slot)
            try:
                att = get_valid_attestation(spec, probe, slot=att_slot)
                block.body.attestations.append(att)
            except (AssertionError, IndexError, ValueError):
                pass
        signed = state_transition_and_sign_block(spec, parent_state, block)
        states[i] = parent_state
        slots[i] = slot
        blocks[i] = signed
        steps.append({"tick": slot})
        steps.append({"block": signed})
        if attest and rng.random() < 0.5:
            # standalone on-the-wire attestation for this block (valid for
            # the store from the NEXT slot)
            try:
                att = get_valid_attestation(
                    spec, parent_state, slot=slot, signed=True
                )
                steps.append({"tick": slot + 1})
                steps.append({"attestation": att})
            except (AssertionError, IndexError, ValueError):
                pass
    steps.append({"checks": {"head_known": True, "descends_from_justified": True}})
    return steps


def mutate_reorder_parent_after_child(steps: list[dict], rng: random.Random) -> list[dict]:
    """Deliver one block before its parent, then redeliver in order — the
    'unknown parent' delay path (mutation_operators.py analogue). The
    moved block must be a LEAF (moving an inner block would orphan its
    children) with a non-genesis parent (so the early delivery genuinely
    fails); trees with no such block return unmutated."""
    block_idx = [i for i, s in enumerate(steps) if "block" in s]
    if len(block_idx) < 2:
        return list(steps)
    roots = {i: bytes(hash_tree_root(steps[i]["block"].message)) for i in block_idx}
    parents = {i: bytes(steps[i]["block"].message.parent_root) for i in block_idx}
    genesis_root = parents[block_idx[0]]
    candidates = [
        i
        for i in block_idx
        if parents[i] != genesis_root and roots[i] not in parents.values()
    ]
    if not candidates:
        return list(steps)
    j = rng.choice(candidates)
    moved_root = roots[j]
    out = []
    early = dict(steps[j])
    early["expect_invalid"] = True
    inserted = False
    deferred: list[dict] = [{k: v for k, v in steps[j].items()}]
    for i, s in enumerate(steps):
        if i == j:
            continue
        if "attestation" in s and bytes(s["attestation"].data.beacon_block_root) == moved_root:
            # votes for the delayed block only land once it is known
            deferred.append(dict(s))
            continue
        if not inserted and "block" in s:
            out.append(early)
            inserted = True
        out.append(s)
    for s in deferred:
        out.insert(len(out) - 1, s)
    return out


def mutate_duplicate_attestations(steps: list[dict], rng: random.Random) -> list[dict]:
    """Duplicate on_attestation deliveries (idempotence probe)."""
    out = []
    for s in steps:
        out.append(s)
        if "attestation" in s:
            out.append(dict(s))
    return out


MUTATIONS = (mutate_reorder_parent_after_child, mutate_duplicate_attestations)


# ------------------------------------------------------------- SM links --


def enumerate_sm_links(anchor_epoch: int = 0, n_epochs: int = 5, max_links: int = 4):
    """Super-majority-link sets per the reference constraint model
    (compliance_runners/fork_choice/model/SM_links.mzn): sources < targets,
    every source is the anchor or an earlier target, targets strictly
    increase, no surround votes, and the Gasper-unreachable (1, 2) link is
    excluded.  Enumeration is restricted to SINGLE-CHAIN-realizable sets —
    each link's source is the highest justification VISIBLE when its
    target epoch is being attested (epoch u's justification lands at the
    end of u for u >= 2 but only at the end of u+1 for u == 1, the
    weigh_justification genesis guard) — so every yielded set is directly
    instantiable by filling its target epochs with attestations.  Yields
    tuples of (source, target) links."""
    from itertools import combinations

    epochs = range(anchor_epoch + 1, anchor_epoch + n_epochs)
    for k in range(1, max_links + 1):
        for targets in combinations(epochs, k):
            links = []
            for t in targets:
                visible = [
                    u for u in targets if u < t and (u >= 2 or t >= 3)
                ]
                src = max(visible) if visible else anchor_epoch
                links.append((src, t))
            assert all(s < t for s, t in links)
            assert (1, 2) not in links  # Gasper-unreachable by construction
            yield tuple(links)


def expected_justification(links, last_epoch: int, anchor_epoch: int = 0):
    """The abstract finality automaton
    (specs/phase0/beacon-chain.md weigh_justification_and_finalization)
    applied to a link pattern whose target epochs reach the 2/3 target
    supermajority: returns the (justified_epoch, finalized_epoch) a chain
    realizing the pattern must reach by the end of `last_epoch`."""
    filled = {t for _, t in links}
    pj = cj = fin = anchor_epoch
    bits = [0, 0, 0, 0]
    for e in range(anchor_epoch, last_epoch + 1):
        if e <= 1:  # current_epoch <= GENESIS_EPOCH + 1 guard
            continue
        old_pj, old_cj = pj, cj
        pj = cj
        bits = [0] + bits[:3]
        if (e - 1) in filled:
            cj = e - 1
            bits[1] = 1
        if e in filled:
            cj = e
            bits[0] = 1
        if all(bits[1:4]) and old_pj + 3 == e:
            fin = old_pj
        if all(bits[1:3]) and old_pj + 2 == e:
            fin = old_pj
        if all(bits[0:3]) and old_cj + 2 == e:
            fin = old_cj
        if all(bits[0:2]) and old_cj + 1 == e:
            fin = old_cj
    return cj, fin


def instantiate_sm_links(spec, state, links, extra_epochs: int = 1):
    """Realize a link pattern on one chain: fill each target epoch with
    full attestations (next_epoch_with_attestations), leave the others
    empty.  `state` must sit on an epoch boundary; it is advanced in
    place.  Returns (signed_blocks, last_epoch)."""
    from eth_consensus_specs_tpu.test_infra.attestations import (
        next_epoch_with_attestations,
    )
    from eth_consensus_specs_tpu.test_infra.state import next_epoch

    targets = {t for _, t in links}
    last = max(targets) + extra_epochs
    blocks = []
    epoch = int(spec.get_current_epoch(state))
    while epoch <= last:
        if epoch in targets:
            # fills the CURRENT epoch's slots with target-epoch == `epoch`
            # attestations and advances to the next boundary
            _, bs, _ = next_epoch_with_attestations(
                spec, state, fill_cur_epoch=True, fill_prev_epoch=False
            )
            blocks.extend(bs)
        else:
            next_epoch(spec, state)
        epoch += 1
    return blocks, last


def replay_blocks_into_store(spec, anchor_state, signed_blocks, tick_to_epoch=None):
    """Deliver blocks in order with slot-accurate ticks; returns the
    store."""
    from eth_consensus_specs_tpu.test_infra.fork_choice import (
        get_genesis_forkchoice_store,
    )

    store, _anchor = get_genesis_forkchoice_store(spec, anchor_state)
    for signed in signed_blocks:
        time = (
            store.genesis_time
            + int(signed.message.slot) * spec.config.SECONDS_PER_SLOT
        )
        if time > store.time:
            spec.on_tick(store, time)
        spec.on_block(store, signed)
    if tick_to_epoch is not None:
        time = (
            store.genesis_time
            + tick_to_epoch * spec.SLOTS_PER_EPOCH * spec.config.SECONDS_PER_SLOT
        )
        if time > store.time:
            spec.on_tick(store, time)
    # get_weight reads checkpoint_states[justified]; in production the
    # entry appears with the first on_attestation for that target — warm
    # it through the same spec function a block-only replay never calls
    spec.store_target_checkpoint_state(store, store.justified_checkpoint)
    return store


# ----------------------------------------------------------- block cover --


def block_cover_scenarios(spec, genesis_state):
    """Store states covering the reference block-cover predicate space
    (compliance_runners/fork_choice/model/Block_cover.mzn): every
    satisfiable combination of

      store_je_eq_zero            store justified epoch == 0
      block_vse_eq_store_je       target block's voting source == store JE
      block_vse_plus_two_ge_curr  the filter_block_tree clock window
      block_is_leaf               target has no children in the store

    (je == 0 forces vse == je, so 12 of the 16 combinations are
    satisfiable — the same exclusions the reference's solver finds).
    Yields dicts {name, blocks, target_root, tick_to_epoch, expect}."""
    from eth_consensus_specs_tpu.ssz import hash_tree_root
    from eth_consensus_specs_tpu.test_infra.attestations import (
        next_epoch_with_attestations,
    )
    from eth_consensus_specs_tpu.test_infra.block import (
        build_empty_block,
        build_empty_block_for_next_slot,
        state_transition_and_sign_block,
    )

    # --- group A: unjustified store (je == 0): a 2-block epoch-0 chain
    base = genesis_state.copy()
    a_blocks = []
    for _ in range(2):
        block = build_empty_block_for_next_slot(spec, base)
        a_blocks.append(state_transition_and_sign_block(spec, base, block))
    inner_root = bytes(hash_tree_root(a_blocks[0].message))
    leaf_root = bytes(hash_tree_root(a_blocks[1].message))
    for clock_epoch, near in ((1, True), (5, False)):
        for root, is_leaf in ((leaf_root, True), (inner_root, False)):
            yield {
                "name": f"je0_leaf{is_leaf}_near{near}",
                "blocks": list(a_blocks),
                "target_root": root,
                "tick_to_epoch": clock_epoch,
                "expect": {
                    "store_je_eq_zero": True,
                    "block_vse_eq_store_je": True,
                    "block_vse_plus_two_ge_curr_e": 0 + 2 >= clock_epoch,
                    "block_is_leaf": is_leaf,
                },
            }

    # --- group B: justified store (je == 2) + a fork stuck on je == 1.
    # Two consecutive justified epochs on the canonical chain; the fork
    # branches after epoch 1's fill, so its blocks carry voting source 1
    # while the store advances to 2 — the only satisfiable shape for
    # (je != 0, vse != je, vse + 2 >= curr_e): a stale-but-in-window
    # branch.  Epoch-N blocks only SEE epoch N-1's supermajority from
    # epoch N+1 states (weigh_justification's genesis guard pins epoch-1
    # states to 0), so every target is one epoch past its fill.
    just = genesis_state.copy()
    b_blocks = []
    for _ in range(int(spec.SLOTS_PER_EPOCH)):
        block = build_empty_block_for_next_slot(spec, just)
        b_blocks.append(state_transition_and_sign_block(spec, just, block))
    _, filled1, _ = next_epoch_with_attestations(
        spec, just, fill_cur_epoch=True, fill_prev_epoch=False
    )
    b_blocks.extend(filled1)
    fork_base = just.copy()
    _, filled2, _ = next_epoch_with_attestations(
        spec, just, fill_cur_epoch=True, fill_prev_epoch=False
    )
    b_blocks.extend(filled2)
    tail_block = build_empty_block_for_next_slot(spec, just)
    b_blocks.append(state_transition_and_sign_block(spec, just, tail_block))
    canon_leaf = bytes(hash_tree_root(b_blocks[-1].message))  # epoch-3 tail
    canon_inner = bytes(hash_tree_root(filled2[-1].message))  # boundary block
    # the fork: two unattested epoch-2 blocks from the post-epoch-1 state
    fork_blocks = []
    fstate = fork_base.copy()
    for _ in range(2):
        block = build_empty_block_for_next_slot(spec, fstate)
        fork_blocks.append(state_transition_and_sign_block(spec, fstate, block))
    fork_inner = bytes(hash_tree_root(fork_blocks[0].message))
    fork_leaf = bytes(hash_tree_root(fork_blocks[1].message))

    all_blocks = b_blocks + fork_blocks
    for root, is_leaf, on_canon in (
        (canon_leaf, True, True),
        (canon_inner, False, True),
        (fork_leaf, True, False),
        (fork_inner, False, False),
    ):
        vse = 2 if on_canon else 1
        for clock_epoch, near in ((3, True), (6, False)):
            yield {
                "name": f"je2_canon{on_canon}_leaf{is_leaf}_near{near}",
                "blocks": list(all_blocks),
                "target_root": root,
                "tick_to_epoch": clock_epoch,
                "expect": {
                    "store_je_eq_zero": False,
                    "block_vse_eq_store_je": on_canon,
                    "block_vse_plus_two_ge_curr_e": vse + 2 >= clock_epoch,
                    "block_is_leaf": is_leaf,
                },
            }


def evaluate_block_cover_predicates(spec, store, target_root: bytes) -> dict:
    """The actual predicate values a store realizes for a target block —
    compared against a scenario's `expect` by the compliance tests."""
    current_epoch = spec.compute_epoch_at_slot(
        spec.get_current_slot(store)
    )
    vse = int(spec.get_voting_source(store, target_root).epoch)
    je = int(store.justified_checkpoint.epoch)
    children = [
        r
        for r, b in store.blocks.items()
        if bytes(b.parent_root) == bytes(target_root)
    ]
    return {
        "store_je_eq_zero": je == 0,
        "block_vse_eq_store_je": vse == je,
        "block_vse_plus_two_ge_curr_e": vse + 2 >= int(current_epoch),
        "block_is_leaf": not children,
    }


def run_scenario(spec, genesis_state, steps: list[dict]) -> dict:
    """Replay a step sequence into a fresh store, asserting the universal
    invariants. Returns {'head': root, 'applied': n, 'rejected': n}."""
    from eth_consensus_specs_tpu.test_infra.fork_choice import (
        get_genesis_forkchoice_store,
    )

    store, _anchor = get_genesis_forkchoice_store(spec, genesis_state)
    applied = rejected = 0
    for step in steps:
        if "tick" in step:
            time = store.genesis_time + int(step["tick"]) * spec.config.SECONDS_PER_SLOT
            if time > store.time:
                spec.on_tick(store, time)
        elif "block" in step:
            try:
                spec.on_block(store, step["block"])
                applied += 1
            except (AssertionError, KeyError, IndexError):
                rejected += 1
                assert step.get("expect_invalid"), "valid block rejected"
        elif "attestation" in step:
            try:
                spec.on_attestation(store, step["attestation"])
            except AssertionError:
                if not step.get("expect_invalid"):
                    raise
        elif "checks" in step:
            head = spec.get_head_root(store)
            if step["checks"].get("head_known"):
                assert head in store.blocks
            if step["checks"].get("descends_from_justified"):
                justified_root = bytes(store.justified_checkpoint.root)
                root = head
                while root != justified_root:
                    block = store.blocks[root]
                    parent = bytes(block.parent_root)
                    if parent == root or parent not in store.blocks:
                        raise AssertionError("head does not descend from justified root")
                    root = parent
    return {"head": spec.get_head_root(store), "applied": applied, "rejected": rejected}
