"""Model-based fork-choice compliance generation.

Reference analogue: tests/generators/compliance_runners/fork_choice/ —
there, MiniZinc constraint models enumerate block-tree shapes and
justification links, instantiators realize them into on_tick/on_block/
on_attestation event sequences, and mutation operators reorder/duplicate
events. MiniZinc is an external C++ solver; here the (small) constraint
space is enumerated directly in Python — same artifact, no solver
dependency:

  1. `enumerate_block_trees` — all parent-vector trees of n blocks under
     branching constraints (the "block tree shape" model, block_tree.mzn).
  2. `instantiate_scenario` — realize a tree into slots/blocks/attestation
     events against a real genesis state.
  3. mutations — event reordering (parent-after-child redelivery) and
     attestation duplication (mutation_operators.py analogue).
  4. `run_scenario` — replay events into a fresh Store, asserting the
     universal invariants (head known, head descends from justified root,
     store time monotone).

The produced step sequences follow the fork-choice vector format
(tests/formats/fork_choice/README.md:28-80): a list of dicts with `tick`/
`block`/`attestation`/`checks` entries, consumable by the dumper."""

from __future__ import annotations

import random
from typing import Iterator

from eth_consensus_specs_tpu.ssz import hash_tree_root


def enumerate_block_trees(n_blocks: int, max_children: int = 2) -> Iterator[tuple[int, ...]]:
    """All rooted trees over n_blocks nodes as parent vectors
    (parents[i] < i; node 0 is the anchor/genesis). The branching bound
    mirrors the compliance model's shape constraints."""
    assert n_blocks >= 1

    def rec(parents: list[int]) -> Iterator[tuple[int, ...]]:
        i = len(parents)
        if i == n_blocks:
            yield tuple(parents)
            return
        for p in range(i):
            # parents[0] is node 0's placeholder, not a child edge
            if parents[1:].count(p) < max_children:
                yield from rec(parents + [p])

    # node 0 has no parent; start enumeration at node 1
    if n_blocks == 1:
        yield (0,)
        return
    for tree in rec([0]):
        yield tree


def instantiate_scenario(spec, genesis_state, tree: tuple[int, ...], *, attest: bool = True,
                         rng: random.Random | None = None) -> list[dict]:
    """Realize a parent-vector tree into an ordered event sequence.

    Returns fork-choice steps: [{"tick": t}, {"block": signed_block},
    {"attestation": att}, ..., {"checks": {...}}]."""
    from eth_consensus_specs_tpu.test_infra.attestations import get_valid_attestation
    from eth_consensus_specs_tpu.test_infra.block import (
        build_empty_block,
        state_transition_and_sign_block,
    )

    rng = rng or random.Random(1)
    n = len(tree)
    # assign slots: each child lands 1 slot after its parent, with sibling
    # separation so proposers differ (branch i gets +index skew)
    states = {0: genesis_state.copy()}
    slots = {0: int(genesis_state.slot)}
    steps: list[dict] = []
    blocks = {}
    sibling_rank: dict[int, int] = {}
    for i in range(1, n):
        parent = tree[i]
        rank = sibling_rank.get(parent, 0)
        sibling_rank[parent] = rank + 1
        slot = slots[parent] + 1 + rank  # siblings at distinct slots
        parent_state = states[parent].copy()
        block = build_empty_block(spec, parent_state, slot=slot)
        if attest and rng.random() < 0.3 and slot >= 2:
            # embedded attestation (carried in the block body)
            probe = parent_state.copy()
            att_slot = slot - 1
            if att_slot > int(probe.slot):
                spec.process_slots(probe, att_slot)
            try:
                att = get_valid_attestation(spec, probe, slot=att_slot)
                block.body.attestations.append(att)
            except (AssertionError, IndexError, ValueError):
                pass
        signed = state_transition_and_sign_block(spec, parent_state, block)
        states[i] = parent_state
        slots[i] = slot
        blocks[i] = signed
        steps.append({"tick": slot})
        steps.append({"block": signed})
        if attest and rng.random() < 0.5:
            # standalone on-the-wire attestation for this block (valid for
            # the store from the NEXT slot)
            try:
                att = get_valid_attestation(
                    spec, parent_state, slot=slot, signed=True
                )
                steps.append({"tick": slot + 1})
                steps.append({"attestation": att})
            except (AssertionError, IndexError, ValueError):
                pass
    steps.append({"checks": {"head_known": True, "descends_from_justified": True}})
    return steps


def mutate_reorder_parent_after_child(steps: list[dict], rng: random.Random) -> list[dict]:
    """Deliver one block before its parent, then redeliver in order — the
    'unknown parent' delay path (mutation_operators.py analogue). The
    moved block must be a LEAF (moving an inner block would orphan its
    children) with a non-genesis parent (so the early delivery genuinely
    fails); trees with no such block return unmutated."""
    block_idx = [i for i, s in enumerate(steps) if "block" in s]
    if len(block_idx) < 2:
        return list(steps)
    roots = {i: bytes(hash_tree_root(steps[i]["block"].message)) for i in block_idx}
    parents = {i: bytes(steps[i]["block"].message.parent_root) for i in block_idx}
    genesis_root = parents[block_idx[0]]
    candidates = [
        i
        for i in block_idx
        if parents[i] != genesis_root and roots[i] not in parents.values()
    ]
    if not candidates:
        return list(steps)
    j = rng.choice(candidates)
    moved_root = roots[j]
    out = []
    early = dict(steps[j])
    early["expect_invalid"] = True
    inserted = False
    deferred: list[dict] = [{k: v for k, v in steps[j].items()}]
    for i, s in enumerate(steps):
        if i == j:
            continue
        if "attestation" in s and bytes(s["attestation"].data.beacon_block_root) == moved_root:
            # votes for the delayed block only land once it is known
            deferred.append(dict(s))
            continue
        if not inserted and "block" in s:
            out.append(early)
            inserted = True
        out.append(s)
    for s in deferred:
        out.insert(len(out) - 1, s)
    return out


def mutate_duplicate_attestations(steps: list[dict], rng: random.Random) -> list[dict]:
    """Duplicate on_attestation deliveries (idempotence probe)."""
    out = []
    for s in steps:
        out.append(s)
        if "attestation" in s:
            out.append(dict(s))
    return out


MUTATIONS = (mutate_reorder_parent_after_child, mutate_duplicate_attestations)


def run_scenario(spec, genesis_state, steps: list[dict]) -> dict:
    """Replay a step sequence into a fresh store, asserting the universal
    invariants. Returns {'head': root, 'applied': n, 'rejected': n}."""
    from eth_consensus_specs_tpu.test_infra.fork_choice import (
        get_genesis_forkchoice_store,
    )

    store, _anchor = get_genesis_forkchoice_store(spec, genesis_state)
    applied = rejected = 0
    for step in steps:
        if "tick" in step:
            time = store.genesis_time + int(step["tick"]) * spec.config.SECONDS_PER_SLOT
            if time > store.time:
                spec.on_tick(store, time)
        elif "block" in step:
            try:
                spec.on_block(store, step["block"])
                applied += 1
            except (AssertionError, KeyError, IndexError):
                rejected += 1
                assert step.get("expect_invalid"), "valid block rejected"
        elif "attestation" in step:
            try:
                spec.on_attestation(store, step["attestation"])
            except AssertionError:
                if not step.get("expect_invalid"):
                    raise
        elif "checks" in step:
            head = spec.get_head_root(store)
            if step["checks"].get("head_known"):
                assert head in store.blocks
            if step["checks"].get("descends_from_justified"):
                justified_root = bytes(store.justified_checkpoint.root)
                root = head
                while root != justified_root:
                    block = store.blocks[root]
                    parent = bytes(block.parent_root)
                    if parent == root or parent not in store.blocks:
                        raise AssertionError("head does not descend from justified root")
                    root = parent
    return {"head": spec.get_head_root(store), "applied": applied, "rejected": rejected}
