"""Vector part writer (reference analogue: gen_base/dumper.py:48-78 +
the type-tagging of tests/infra/yield_generator.py:10-43).

A test case's yielded parts land in one case directory:

  * SSZ views / bytes  -> `<name>.ssz_snappy`
  * lists of views     -> `<name>_<i>.ssz_snappy` + meta `<name>_count`
  * plain values       -> collected into `meta.yaml`
  * `post` = None      -> omitted (the invalid-case convention, reference
                          tests/formats/operations/README.md:24-28)

Crash safety: every part is written into a ``<case_dir>.__tmp<pid>``
staging dir and each `.ssz_snappy` write is verified by read-back
(snappy-decode must round-trip to the input bytes — a fault-injected or
disk-level corruption is caught and retried through fault.retrying
before it can become a torn vector). The case directory itself is
committed LAST via `os.replace`, so a SIGKILL at any point leaves either
no case dir or a complete one — never a partial tree. The per-part
sha256 digests collected during the write feed the run manifest
(gen/manifest.py) and the obs `gen.part` events.
"""

from __future__ import annotations

import os
import shutil

import yaml

from eth_consensus_specs_tpu import fault, obs
from eth_consensus_specs_tpu.obs import gates
from eth_consensus_specs_tpu.ssz import serialize
from eth_consensus_specs_tpu.ssz.types import View

from .snappy_codec import frame_compress, frame_decompress


def _is_view(value) -> bool:
    return isinstance(value, View)


class TornWriteError(OSError):
    """A written `.ssz_snappy` failed its read-back verification."""


# suffix of the stash a committed case dir is moved to during an
# overwrite commit; manifest.clean_stale_tmp knows to restore it
OLD_SUFFIX = ".__old"


class Dumper:
    def __init__(self, output_dir: str):
        self.output_dir = output_dir
        self._digests: dict[str, str] = {}

    def pop_digests(self) -> dict[str, str]:
        """{part name: digest} of the most recent dump_case (consumed)."""
        digests, self._digests = self._digests, {}
        return digests

    def case_dir(self, case) -> str:
        return os.path.join(
            self.output_dir,
            case.preset,
            case.fork,
            case.runner,
            case.handler,
            case.suite,
            case.case_name,
        )

    def dump_ssz(self, case_dir: str, name: str, encoded: bytes) -> None:
        digest = gates.digest(encoded)
        self._digests[name] = digest
        if obs.obs_enabled():
            # fingerprint through the shared gate digest so a cross-generator
            # byte-diff can compare runs from the observability stream alone
            obs.count("gen.parts", 1)
            obs.count("gen.bytes_serialized", len(encoded))
            obs.event("gen.part", part=name, digest=digest, nbytes=len(encoded))
        # writes land inside the staging dir (dump_case), which only
        # becomes visible via the final atomic commit — no per-file tmp
        # dance needed, just the read-back verification
        path = os.path.join(case_dir, f"{name}.ssz_snappy")

        def _write_verified():
            frame = fault.corrupt("gen.dump_bytes", frame_compress(encoded))
            with open(path, "wb") as f:
                f.write(frame)
            with open(path, "rb") as f:
                written = f.read()
            try:
                intact = frame_decompress(written) == encoded
            except Exception:
                intact = False
            if not intact:
                os.unlink(path)  # never leave torn bytes behind
                obs.count("gen.torn_writes", 1)
                raise TornWriteError(f"read-back mismatch writing {path}")

        fault.retrying(
            _write_verified,
            name=f"gen.dump:{name}",
            attempts=3,
            retry_on=(TornWriteError, OSError),
            base_delay=0.01,
        )

    def dump_meta(self, case_dir: str, meta: dict) -> None:
        if not meta:
            return
        with open(os.path.join(case_dir, "meta.yaml"), "w") as f:
            yaml.safe_dump(meta, f, default_flow_style=None)

    def dump_case(self, case, parts) -> str:
        """Write all (name, value) parts of one executed case into a
        staging dir, then commit the case dir atomically; returns the
        final case directory."""
        final_dir = self.case_dir(case)
        os.makedirs(os.path.dirname(final_dir), exist_ok=True)
        tmp_dir = final_dir + f".__tmp{os.getpid()}"
        if os.path.isdir(tmp_dir):
            shutil.rmtree(tmp_dir)
        os.makedirs(tmp_dir)
        self._digests = {}
        meta: dict = {}
        for name, value in parts:
            if value is None:
                continue  # invalid-case convention: no post state emitted
            if name.endswith(".yaml"):
                # standalone yaml part (the bls/shuffling/ssz_generic
                # format families dump `data.yaml` per case, reference
                # tests/formats/{bls,shuffling}/README.md)
                with open(os.path.join(tmp_dir, name), "w") as f:
                    yaml.safe_dump(_yamlable(value), f, default_flow_style=None)
                continue
            if _is_view(value):
                self.dump_ssz(tmp_dir, name, serialize(value))
            elif isinstance(value, (bytes, bytearray)):
                self.dump_ssz(tmp_dir, name, bytes(value))
            elif isinstance(value, (list, tuple)) and (not value or _is_view(value[0])):
                # view lists (incl. empty: the zero-block sanity convention
                # still needs `<name>_count: 0` in meta)
                meta[f"{name}_count"] = len(value)
                for i, item in enumerate(value):
                    self.dump_ssz(tmp_dir, f"{name}_{i}", serialize(item))
            else:
                meta[name] = _yamlable(value)
        self.dump_meta(tmp_dir, meta)
        # commit LAST: the case dir appears fully-formed or not at all.
        # Overwrites move the old dir aside FIRST (atomic rename) so a
        # committed case is never destroyed before its replacement is in
        # place; a kill between the two renames leaves the stash, which
        # clean_stale_tmp RESTORES (not deletes) when the final dir is
        # missing — a durable vector can only be superseded, never lost
        old_dir = None
        if os.path.isdir(final_dir):
            old_dir = final_dir + OLD_SUFFIX
            if os.path.isdir(old_dir):
                shutil.rmtree(old_dir)
            os.replace(final_dir, old_dir)
        os.replace(tmp_dir, final_dir)
        if old_dir is not None:
            shutil.rmtree(old_dir, ignore_errors=True)
        return final_dir


def _yamlable(value):
    if isinstance(value, dict):
        return {k: _yamlable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_yamlable(v) for v in value]
    if isinstance(value, bool):
        return value
    if isinstance(value, int):
        return int(value)
    return str(value)
