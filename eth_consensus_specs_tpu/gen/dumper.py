"""Vector part writer (reference analogue: gen_base/dumper.py:48-78 +
the type-tagging of tests/infra/yield_generator.py:10-43).

A test case's yielded parts land in one case directory:

  * SSZ views / bytes  -> `<name>.ssz_snappy`
  * lists of views     -> `<name>_<i>.ssz_snappy` + meta `<name>_count`
  * plain values       -> collected into `meta.yaml`
  * `post` = None      -> omitted (the invalid-case convention, reference
                          tests/formats/operations/README.md:24-28)
"""

from __future__ import annotations

import os

import yaml

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import gates
from eth_consensus_specs_tpu.ssz import serialize
from eth_consensus_specs_tpu.ssz.types import View

from .snappy_codec import frame_compress


def _is_view(value) -> bool:
    return isinstance(value, View)


class Dumper:
    def __init__(self, output_dir: str):
        self.output_dir = output_dir

    def case_dir(self, case) -> str:
        return os.path.join(
            self.output_dir,
            case.preset,
            case.fork,
            case.runner,
            case.handler,
            case.suite,
            case.case_name,
        )

    def dump_ssz(self, case_dir: str, name: str, encoded: bytes) -> None:
        if obs.obs_enabled():
            # fingerprint through the shared gate digest so a cross-generator
            # byte-diff can compare runs from the observability stream alone
            obs.count("gen.parts", 1)
            obs.count("gen.bytes_serialized", len(encoded))
            obs.event("gen.part", part=name, digest=gates.digest(encoded), nbytes=len(encoded))
        with open(os.path.join(case_dir, f"{name}.ssz_snappy"), "wb") as f:
            f.write(frame_compress(encoded))

    def dump_meta(self, case_dir: str, meta: dict) -> None:
        if not meta:
            return
        with open(os.path.join(case_dir, "meta.yaml"), "w") as f:
            yaml.safe_dump(meta, f, default_flow_style=None)

    def dump_case(self, case, parts) -> str:
        """Write all (name, value) parts of one executed case; returns the
        case directory."""
        case_dir = self.case_dir(case)
        os.makedirs(case_dir, exist_ok=True)
        meta: dict = {}
        for name, value in parts:
            if value is None:
                continue  # invalid-case convention: no post state emitted
            if name.endswith(".yaml"):
                # standalone yaml part (the bls/shuffling/ssz_generic
                # format families dump `data.yaml` per case, reference
                # tests/formats/{bls,shuffling}/README.md)
                with open(os.path.join(case_dir, name), "w") as f:
                    yaml.safe_dump(_yamlable(value), f, default_flow_style=None)
                continue
            if _is_view(value):
                self.dump_ssz(case_dir, name, serialize(value))
            elif isinstance(value, (bytes, bytearray)):
                self.dump_ssz(case_dir, name, bytes(value))
            elif isinstance(value, (list, tuple)) and (not value or _is_view(value[0])):
                # view lists (incl. empty: the zero-block sanity convention
                # still needs `<name>_count: 0` in meta)
                meta[f"{name}_count"] = len(value)
                for i, item in enumerate(value):
                    self.dump_ssz(case_dir, f"{name}_{i}", serialize(item))
            else:
                meta[name] = _yamlable(value)
        self.dump_meta(case_dir, meta)
        return case_dir


def _yamlable(value):
    if isinstance(value, dict):
        return {k: _yamlable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_yamlable(v) for v in value]
    if isinstance(value, bool):
        return value
    if isinstance(value, int):
        return int(value)
    return str(value)
