"""Durable run manifest for the vector generator.

One JSONL line per COMPLETED case (written or skipped), appended by the
parent only after the case directory is durably committed (atomic
rename, see dumper.py) — so `--resume` can trust every entry::

    {"key": [preset, fork, runner, handler, case_name],
     "status": "written" | "skipped",
     "dir": "<case dir relative to output_dir>" | null,
     "parts": {"<part name>": "<sha256[:32] of the raw SSZ bytes>"}}

The part digests are the same fingerprints the obs `gen.part` events
carry (obs/gates.digest), which is what lets CI byte-diff a
fault-injected run against a clean one from the manifests alone. A
crash mid-append leaves at most one torn tail line; `load_manifest`
skips unparseable lines, which only means the interrupted case is
regenerated on resume — never that a torn entry is trusted.
"""

from __future__ import annotations

import json
import os
import shutil

from eth_consensus_specs_tpu import fault, obs

MANIFEST_NAME = "gen_manifest.jsonl"


def manifest_path(output_dir: str) -> str:
    return os.path.join(output_dir, MANIFEST_NAME)


def load_manifest(path: str) -> dict[tuple, dict]:
    """{case key tuple: record} of every well-formed line (later lines
    win, matching append order)."""
    out: dict[tuple, dict] = {}
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                key = tuple(rec["key"])
            except (json.JSONDecodeError, KeyError, TypeError):
                continue  # torn tail line from a crash mid-append
            out[key] = rec
    return out


def clean_stale_tmp(output_dir: str) -> int:
    """Remove uncommitted ``*.__tmp*`` staging dirs a killed worker left
    behind (never renamed into place — nothing durable is touched), and
    handle ``*.__old`` overwrite stashes: RESTORED when the final dir is
    missing (the writer died between its two commit renames — the stash
    is the only surviving copy of a durable vector), deleted otherwise."""
    from .dumper import OLD_SUFFIX

    removed = restored = 0
    for root, dirs, _files in os.walk(output_dir):
        for d in list(dirs):
            path = os.path.join(root, d)
            if d.endswith(OLD_SUFFIX):
                target = path[: -len(OLD_SUFFIX)]
                if not os.path.isdir(target):
                    os.replace(path, target)
                    restored += 1
                else:
                    shutil.rmtree(path, ignore_errors=True)
                    removed += 1
                dirs.remove(d)
            elif ".__tmp" in d:
                shutil.rmtree(path, ignore_errors=True)
                dirs.remove(d)
                removed += 1
    if removed or restored:
        obs.event("gen.tmp_cleaned", dirs=removed, restored=restored)
    return removed


class RunManifest:
    """Append-side handle held by the generation parent process."""

    def __init__(self, output_dir: str, resume: bool = False):
        os.makedirs(output_dir, exist_ok=True)
        self.output_dir = output_dir
        self.path = manifest_path(output_dir)
        self.completed: dict[tuple, dict] = {}
        if resume:
            self.completed = load_manifest(self.path)
            clean_stale_tmp(output_dir)
        # non-resume runs start a fresh manifest: stale entries from an
        # older tree must not leak into a later --resume
        self._fh = open(self.path, "a" if resume else "w")

    def record(self, key: tuple, status: str, digests: dict, rel_dir: str | None = None):
        rec = {"key": list(key), "status": status, "dir": rel_dir, "parts": digests}
        line = json.dumps(rec, sort_keys=True) + "\n"

        def _append():
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())

        fault.retrying(_append, name="gen.manifest_append", attempts=3, retry_on=OSError)
        self.completed[tuple(key)] = rec

    def close(self):
        try:
            self._fh.close()
        except OSError:
            pass
