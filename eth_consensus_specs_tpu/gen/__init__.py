"""Test-vector generation (L5 of the reference's layer map).

The reference's conformance machine (reference:
eth2spec/gen_helpers/gen_base/gen_runner.py:113-320, gen_from_tests/
gen.py:19-71, gen_base/dumper.py:48-78) re-runs the decorated test
functions in generator mode and serializes their yielded parts into the
canonical `config/fork/runner/handler/suite/case` tree of
`.ssz_snappy` + `.yaml` files (format: reference tests/formats/README.md).

This package is the tpu-native equivalent: `discover` walks the repo's
test modules, `run_generator` executes cases (the same decorated callables
pytest runs, with ``generator_mode=True``), and `Dumper` writes the tree.
Snappy framing is first-party (gen/snappy_codec.py) since no snappy
binding is baked into the image.
"""

from .dumper import Dumper
from .gen_from_tests import discover_test_cases
from .gen_runner import run_generator
from .manifest import RunManifest, load_manifest, manifest_path

__all__ = [
    "Dumper",
    "RunManifest",
    "discover_test_cases",
    "load_manifest",
    "manifest_path",
    "run_generator",
]
