"""CLI vector generator: `python -m eth_consensus_specs_tpu.gen`
(reference analogue: `make reftests` -> tests/generators/main.py)."""

from __future__ import annotations

import argparse
import json

from eth_consensus_specs_tpu import fault, obs

from .gen_from_tests import discover_test_cases
from .gen_runner import run_generator


def main() -> None:
    parser = argparse.ArgumentParser(description="generate consensus test vectors")
    parser.add_argument("--output", "-o", default="test_vectors", help="output directory")
    parser.add_argument("--presets", nargs="*", default=["minimal"])
    parser.add_argument("--forks", nargs="*", default=None)
    parser.add_argument("--runners", nargs="*", default=None)
    parser.add_argument("--verbose", "-v", action="store_true")
    parser.add_argument(
        "--workers",
        default=None,
        help='process-pool size or "auto" (reference: pathos pool, '
        "gen_base/gen_runner.py:288-302); default sequential",
    )
    parser.add_argument(
        "--resume",
        action="store_true",
        help="skip cases already durable in the output dir's run manifest "
        "(gen_manifest.jsonl) from a previous, possibly interrupted run",
    )
    parser.add_argument(
        "--case-timeout",
        type=float,
        default=None,
        help="pool-mode wall-clock deadline per case (seconds); a hung case "
        "gets its worker killed and is re-dispatched",
    )
    parser.add_argument(
        "--case-retries",
        type=int,
        default=1,
        help="extra attempts for a failed/lost/hung case (default 1)",
    )
    parser.add_argument(
        "--fault",
        default=None,
        help="fault-injection spec (overrides ETH_SPECS_FAULT; grammar in "
        "docs/robustness.md) — chaos/CI use",
    )
    args = parser.parse_args()

    if args.fault is not None:
        fault.install(args.fault)

    runners = tuple(args.runners) if args.runners else None
    cases = discover_test_cases(
        presets=tuple(args.presets),
        forks=tuple(args.forks) if args.forks else None,
        runners=runners,
    )
    # dedicated direct-computation runners (bls/kzg/shuffling/ssz_generic);
    # the --forks filter applies to their cases like any other
    from .runners import get_runner_cases

    runner_cases = get_runner_cases(presets=tuple(args.presets), runners=runners)
    if args.forks:
        runner_cases = [c for c in runner_cases if c.fork in args.forks]
    cases = list(cases) + runner_cases
    workers = args.workers
    if workers is not None and workers != "auto":
        workers = int(workers)
    obs.export.maybe_serve_http()  # scrapeable during a long run (env-gated)
    stats = run_generator(
        cases,
        args.output,
        verbose=args.verbose,
        workers=workers,
        case_timeout=args.case_timeout,
        case_retries=args.case_retries,
        resume=args.resume,
    )
    # recovery + serving + flight-recorder counters ride along so CI
    # chaos jobs can assert on them (serve.* arrives from pool workers
    # via the per-case counter shipping when ETH_SPECS_SERVE=1;
    # frontdoor.* covers the replicated fleet when
    # ETH_SPECS_SERVE_REPLICAS is set; flight.dumps says how many
    # postmortem bundles the run left)
    counters = {
        k: v
        for k, v in obs.snapshot()["counters"].items()
        if k.startswith(("gen.", "fault.", "serve.", "frontdoor.", "flight."))
    }
    print(json.dumps({"cases": len(cases), **stats, "counters": counters}))


if __name__ == "__main__":
    main()
