"""CLI vector generator: `python -m eth_consensus_specs_tpu.gen`
(reference analogue: `make reftests` -> tests/generators/main.py)."""

from __future__ import annotations

import argparse
import json

from .gen_from_tests import discover_test_cases
from .gen_runner import run_generator


def main() -> None:
    parser = argparse.ArgumentParser(description="generate consensus test vectors")
    parser.add_argument("--output", "-o", default="test_vectors", help="output directory")
    parser.add_argument("--presets", nargs="*", default=["minimal"])
    parser.add_argument("--forks", nargs="*", default=None)
    parser.add_argument("--runners", nargs="*", default=None)
    parser.add_argument("--verbose", "-v", action="store_true")
    parser.add_argument(
        "--workers",
        default=None,
        help='process-pool size or "auto" (reference: pathos pool, '
        "gen_base/gen_runner.py:288-302); default sequential",
    )
    args = parser.parse_args()

    runners = tuple(args.runners) if args.runners else None
    cases = discover_test_cases(
        presets=tuple(args.presets),
        forks=tuple(args.forks) if args.forks else None,
        runners=runners,
    )
    # dedicated direct-computation runners (bls/kzg/shuffling/ssz_generic);
    # the --forks filter applies to their cases like any other
    from .runners import get_runner_cases

    runner_cases = get_runner_cases(presets=tuple(args.presets), runners=runners)
    if args.forks:
        runner_cases = [c for c in runner_cases if c.fork in args.forks]
    cases = list(cases) + runner_cases
    workers = args.workers
    if workers is not None and workers != "auto":
        workers = int(workers)
    stats = run_generator(cases, args.output, verbose=args.verbose, workers=workers)
    print(json.dumps({"cases": len(cases), **stats}))


if __name__ == "__main__":
    main()
