"""First-party snappy FRAMING format codec (no external binding).

Writer emits uncompressed chunks (type 0x01) — every conforming snappy
decoder must accept them, so the produced ``.ssz_snappy`` files are valid
for any consensus-layer client. Reader handles both uncompressed and
compressed (type 0x00) chunks, with a full snappy BLOCK format
decompressor, so upstream-released vectors (which use compressed chunks)
can be ingested too.

Framing format: stream identifier "sNaPpY", per-chunk masked CRC-32C of
the uncompressed data. Reference consumer: gen_base/dumper.py:66-71
(python-snappy `compress`).
"""

from __future__ import annotations

_STREAM_IDENTIFIER = b"\xff\x06\x00\x00sNaPpY"
_MAX_UNCOMPRESSED_CHUNK = 65536

# CRC-32C (Castagnoli), reflected polynomial 0x82F63B78
_CRC_TABLE = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def frame_compress(data: bytes) -> bytes:
    """Snappy frame stream holding `data` in uncompressed chunks."""
    out = [_STREAM_IDENTIFIER]
    starts = range(0, len(data), _MAX_UNCOMPRESSED_CHUNK) if data else [0]
    for i in starts:
        chunk = data[i : i + _MAX_UNCOMPRESSED_CHUNK]
        body = _masked_crc(chunk).to_bytes(4, "little") + chunk
        out.append(b"\x01" + len(body).to_bytes(3, "little") + body)
    return b"".join(out)


def _uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def block_decompress(data: bytes) -> bytes:
    """Snappy BLOCK format decompressor (tag-stream parser)."""
    expected_len, pos = _uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0b11
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            out += data[pos : pos + length]
            pos += length
        else:
            if kind == 1:  # copy, 1-byte offset
                length = ((tag >> 2) & 0b111) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:  # copy, 2-byte offset
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:  # copy, 4-byte offset
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("snappy: invalid copy offset")
            # overlapping copies are byte-at-a-time semantics
            start = len(out) - offset
            for i in range(length):
                out.append(out[start + i])
    if len(out) != expected_len:
        raise ValueError(f"snappy: expected {expected_len} bytes, got {len(out)}")
    return bytes(out)


def frame_decompress(data: bytes) -> bytes:
    if not data.startswith(_STREAM_IDENTIFIER):
        raise ValueError("snappy: missing stream identifier")
    pos = len(_STREAM_IDENTIFIER)
    out = bytearray()
    n = len(data)
    while pos < n:
        chunk_type = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        body = data[pos + 4 : pos + 4 + length]
        pos += 4 + length
        if chunk_type == 0x00:  # compressed
            crc = int.from_bytes(body[:4], "little")
            chunk = block_decompress(body[4:])
            if _masked_crc(chunk) != crc:
                raise ValueError("snappy: chunk checksum mismatch")
            out += chunk
        elif chunk_type == 0x01:  # uncompressed
            crc = int.from_bytes(body[:4], "little")
            chunk = body[4:]
            if _masked_crc(chunk) != crc:
                raise ValueError("snappy: chunk checksum mismatch")
            out += chunk
        elif chunk_type == 0xFF:  # repeated stream identifier
            continue
        elif 0x80 <= chunk_type <= 0xFE:  # padding/skippable
            continue
        else:
            raise ValueError(f"snappy: unknown chunk type {chunk_type:#x}")
    return bytes(out)
