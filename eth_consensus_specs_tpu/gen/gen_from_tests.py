"""Discover decorated test functions and wrap them as generator cases
(reference analogue: gen_from_tests/gen.py:19-71, 77-134)."""

from __future__ import annotations

import importlib
import inspect
import pkgutil
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class TestCase:
    preset: str
    fork: str
    runner: str
    handler: str
    suite: str
    case_name: str
    case_fn: Callable  # () -> iterator of (name, value) parts
    # reference meta convention (tests/formats/README.md): 1 = signatures
    # must be checked, 2 = checks must be skipped, 0/absent = optional
    bls_setting: int = 0


# FALLBACK module-basename -> (runner, handler) taxonomy. The primary
# source of coordinates is a test's @manifest vector location
# (test_infra/manifest.py, mirrored from reference tests/infra/
# manifest.py:7-73); this map only fills what a test leaves unpinned.
_RUNNER_MAP = {
    "test_process_attestation": ("operations", "attestation"),
    "test_withdrawals": ("operations", "withdrawals"),
    "test_bls_to_execution_change": ("operations", "bls_to_execution_change"),
    "test_execution_payload": ("operations", "execution_payload"),
    "test_blob_processing": ("operations", "execution_payload"),
    "test_execution_requests": ("operations", "execution_requests"),
    "test_pending_deposits": ("epoch_processing", "pending_deposits"),
    "test_epoch_processing": ("epoch_processing", "epoch_processing"),
    "test_sanity": ("sanity", "blocks"),
    "test_sync_aggregate": ("operations", "sync_aggregate"),
    "test_fork_choice": ("fork_choice", "on_block"),
    "test_fork_choice_gloas": ("fork_choice", "on_block"),
    "test_finality": ("finality", "finality"),
    "test_genesis": ("genesis", "initialization"),
    "test_rewards": ("rewards", "basic"),
    "test_reward_components": ("rewards", "components"),
    "test_light_client": ("light_client", "sync"),
    "test_random_blocks": ("random", "random"),
}

# basename-prefix fallbacks applied before the catch-all "tests" runner
_RUNNER_PREFIX_MAP = [
    ("test_upgrade_to_", ("transition", "core")),
]


def _iter_test_modules(package_name: str = "tests"):
    pkg = importlib.import_module(package_name)
    for modinfo in pkgutil.walk_packages(pkg.__path__, prefix=f"{package_name}."):
        basename = modinfo.name.rsplit(".", 1)[-1]
        if not basename.startswith("test_"):
            continue
        yield importlib.import_module(modinfo.name)


def discover_test_cases(
    presets=("minimal",),
    forks=None,
    runners=None,
    package: str = "tests",
):
    """Walk the repo's test package; every fork-matrixed test function
    becomes one TestCase per (preset, fork) it supports."""
    from eth_consensus_specs_tpu.forks import available_forks

    all_forks = available_forks()
    # key -> (module_fork_segment, TestCase); same-named tests in a fork's
    # own module dir (tests/<fork>/...) override fork-generic ones so a
    # fork's vector comes from its most specific test definition
    selected: dict[tuple, tuple[str | None, TestCase]] = {}
    for module in _iter_test_modules(package):
        parts = module.__name__.split(".")
        basename = parts[-1]
        module_fork = parts[-2] if len(parts) >= 2 and parts[-2] in all_forks else None
        mapped = _RUNNER_MAP.get(basename)
        if mapped is None:
            for prefix, target in _RUNNER_PREFIX_MAP:
                if basename.startswith(prefix):
                    mapped = target
                    break
        runner, handler = mapped or ("tests", basename.removeprefix("test_"))
        if runners is not None and runner not in runners:
            continue
        for name, fn in inspect.getmembers(module, callable):
            if not name.startswith("test_"):
                continue
            phases = getattr(fn, "phases", None)
            if phases is None:
                continue  # not a fork-matrixed spec test
            # explicit @manifest coordinates win over the module-map
            # fallback (the seam the reference's Manifest provides)
            from eth_consensus_specs_tpu.test_infra.manifest import vector_location_of

            loc = vector_location_of(fn)
            for preset in presets:
                if loc.preset is not None and preset != loc.preset:
                    continue
                for fork in phases:
                    if fork not in all_forks:
                        continue
                    if forks is not None and fork not in forks:
                        continue
                    if loc.fork is not None and fork != loc.fork:
                        continue
                    case_name = loc.case or name.removeprefix("test_")
                    case_handler = loc.handler or handler
                    case_runner = loc.runner or runner
                    if (
                        loc.handler is None
                        and case_runner == "sanity"
                        and case_name.startswith("slots")
                    ):
                        # slot-advance cases have their own format
                        # (reference tests/formats/sanity/slots.md)
                        case_handler = "slots"
                    bls_marker = getattr(fn, "bls", None)
                    bls_setting = {"always": 1, "never": 2}.get(bls_marker, 0)
                    case = TestCase(
                        preset=preset,
                        fork=fork,
                        runner=case_runner,
                        handler=case_handler,
                        suite=loc.suite or "pyspec_tests",
                        case_name=case_name,
                        case_fn=(
                            lambda fn=fn, fork=fork, preset=preset: fn(
                                generator_mode=True, phase=fork, preset=preset
                            )
                        ),
                        bls_setting=bls_setting,
                    )
                    key = (preset, fork, case_runner, case_handler, case_name)
                    prev = selected.get(key)
                    if prev is not None:
                        prev_fork_seg = prev[0]
                        if prev_fork_seg == fork:
                            continue  # keep the fork-specific definition
                        if module_fork != fork:
                            continue  # neither specific: keep the first
                    selected[key] = (module_fork, case)
    return [case for _, case in selected.values()]
