"""Vector-generation executor (reference analogue:
gen_base/gen_runner.py:113-320 — ours is sequential; the reference's
pathos process pool parallelizes python-process-bound crypto that is not
this framework's bottleneck)."""

from __future__ import annotations

import sys
import traceback

from .dumper import Dumper
from .gen_from_tests import TestCase


class SkippedCase(Exception):
    pass


def execute_case(case: TestCase, dumper: Dumper) -> str | None:
    """Run one case in generator mode and dump its parts. Returns the case
    dir, or None if the case was skipped."""
    from eth_consensus_specs_tpu.test_infra.context import SkippedTest

    try:
        gen = case.case_fn()
        if gen is None:
            return None  # test yielded nothing (pure-assertion case)
        # snapshot each part AT YIELD TIME: tests yield live state objects
        # ("pre" and "post" are often the same mutated instance), so views
        # must be copied before the generator advances (the reference
        # serializes eagerly for the same reason, yield_generator.py:10-43)
        parts = []
        for name, value in gen:
            parts.append((name, _snapshot(value)))
    except SkippedTest:
        return None
    if not parts:
        # plain-assertion test (no yielded vector parts): nothing to emit
        return None
    if case.bls_setting:
        parts.insert(0, ("bls_setting", case.bls_setting))
    return dumper.dump_case(case, parts)


def _snapshot(value):
    # deep-copy view lists BEFORE the generic .copy() check — list.copy()
    # is shallow and would alias the contained views
    if isinstance(value, (list, tuple)):
        return [_snapshot(v) for v in value]
    if hasattr(value, "copy") and callable(value.copy):
        return value.copy()
    return value


def run_generator(cases, output_dir: str, verbose: bool = False) -> dict:
    """Execute all cases; returns {written, skipped, failed} counts."""
    dumper = Dumper(output_dir)
    written = skipped = failed = 0
    for case in cases:
        try:
            out = execute_case(case, dumper)
        except Exception:
            failed += 1
            if verbose:
                print(f"[gen] FAILED {case.runner}/{case.handler}/{case.case_name}",
                      file=sys.stderr)
                traceback.print_exc()
            continue
        if out is None:
            skipped += 1
        else:
            written += 1
            if verbose:
                print(f"[gen] wrote {out}", file=sys.stderr)
    return {"written": written, "skipped": skipped, "failed": failed}
