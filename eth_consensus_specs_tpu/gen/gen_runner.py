"""Vector-generation executor (reference analogue:
gen_base/gen_runner.py:113-320).

Two modes:

* sequential (default) — simple, in-process;
* process pool (``workers=N`` or ``"auto"``) — mirrors the reference's
  pathos pool with ``maxtasksperchild`` recycling, live progress and
  per-worker RSS telemetry (reference gen_runner.py:183-302). Cases are
  addressed by coordinate key and re-discovered inside each worker (the
  case closures themselves don't pickle, exactly why the reference uses
  a dill-based pool; re-discovery is one import pass per worker)."""

from __future__ import annotations

import os
import sys
import time
import traceback

from eth_consensus_specs_tpu import obs

from .dumper import Dumper
from .gen_from_tests import TestCase


class SkippedCase(Exception):
    pass


def execute_case(case: TestCase, dumper: Dumper) -> str | None:
    """Run one case in generator mode and dump its parts. Returns the case
    dir, or None if the case was skipped."""
    from eth_consensus_specs_tpu.test_infra.context import SkippedTest

    with obs.span("gen.case", runner=case.runner, handler=case.handler):
        try:
            gen = case.case_fn()
            if gen is None:
                obs.count("gen.cases_skipped", 1)
                return None  # test yielded nothing (pure-assertion case)
            # snapshot each part AT YIELD TIME: tests yield live state objects
            # ("pre" and "post" are often the same mutated instance), so views
            # must be copied before the generator advances (the reference
            # serializes eagerly for the same reason, yield_generator.py:10-43)
            parts = []
            for name, value in gen:
                parts.append((name, _snapshot(value)))
        except SkippedTest:
            obs.count("gen.cases_skipped", 1)
            return None
        if not parts:
            # plain-assertion test (no yielded vector parts): nothing to emit
            obs.count("gen.cases_skipped", 1)
            return None
        if case.bls_setting:
            parts.insert(0, ("bls_setting", case.bls_setting))
        out = dumper.dump_case(case, parts)
    obs.count("gen.cases_written", 1)
    return out


def _snapshot(value):
    # deep-copy view lists BEFORE the generic .copy() check — list.copy()
    # is shallow and would alias the contained views
    if isinstance(value, (list, tuple)):
        return [_snapshot(v) for v in value]
    if hasattr(value, "copy") and callable(value.copy):
        return value.copy()
    return value


def run_generator(
    cases, output_dir: str, verbose: bool = False, workers: int | str | None = None
) -> dict:
    """Execute all cases; returns {written, skipped, failed} counts.

    ``workers``: None/0/1 = sequential; an int or "auto" = process pool."""
    if workers in (None, 0, 1):
        return _run_sequential(cases, output_dir, verbose)
    n_workers = os.cpu_count() - 1 if workers == "auto" else int(workers)
    return _run_pool(cases, output_dir, verbose, max(n_workers, 1))


def _run_sequential(cases, output_dir: str, verbose: bool) -> dict:
    dumper = Dumper(output_dir)
    written = skipped = failed = 0
    for case in cases:
        try:
            out = execute_case(case, dumper)
        except Exception:
            failed += 1
            obs.count("gen.cases_failed", 1)
            if verbose:
                print(f"[gen] FAILED {case.runner}/{case.handler}/{case.case_name}",
                      file=sys.stderr)
                traceback.print_exc()
            continue
        if out is None:
            skipped += 1
        else:
            written += 1
            if verbose:
                print(f"[gen] wrote {out}", file=sys.stderr)
    return {"written": written, "skipped": skipped, "failed": failed}


def case_key(case: TestCase) -> tuple:
    return (case.preset, case.fork, case.runner, case.handler, case.case_name)


_WORKER_CASES: dict | None = None
_WORKER_DUMPER: Dumper | None = None


def _pool_init(output_dir: str, presets: tuple, forks: tuple | None, package: str):
    """Worker initializer: rebuild the case index once per worker
    process (closures don't pickle; coordinates do)."""
    global _WORKER_CASES, _WORKER_DUMPER
    from .gen_from_tests import discover_test_cases
    from .runners import get_runner_cases

    found = discover_test_cases(
        presets=presets, forks=list(forks) if forks else None, package=package
    )
    found += get_runner_cases(presets=presets)
    _WORKER_CASES = {case_key(c): c for c in found}
    _WORKER_DUMPER = Dumper(output_dir)


_WORKER_OBS_BASE: dict = {}


def _worker_obs_delta() -> dict:
    """Delta of ALL this worker's obs counters since the previous case —
    shipped with each result so pool mode reports what sequential mode
    does: dumper totals (gen.parts, gen.bytes_serialized), kernel
    counters, and above all watchdog.checks/.divergences (a divergence
    detected inside a worker MUST reach the parent registry). Only
    gen.cases_* stay out: the parent mirrors those from its own
    authoritative status counts."""
    global _WORKER_OBS_BASE
    now = {
        k: v
        for k, v in obs.snapshot()["counters"].items()
        if not k.startswith("gen.cases_")
    }
    delta = {k: v - _WORKER_OBS_BASE.get(k, 0) for k, v in now.items()}
    _WORKER_OBS_BASE = now
    return {k: v for k, v in delta.items() if v}


def _pool_exec(key: tuple) -> tuple:
    """Run one case in the worker; returns (key, status, rss_mb, obs_delta)."""
    import resource

    case = _WORKER_CASES.get(key)
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    if case is None:
        return key, "failed", rss, _worker_obs_delta()
    try:
        out = execute_case(case, _WORKER_DUMPER)
    except Exception:
        traceback.print_exc()
        return key, "failed", rss, _worker_obs_delta()
    return key, ("written" if out is not None else "skipped"), rss, _worker_obs_delta()


def _run_pool(cases, output_dir: str, verbose: bool, n_workers: int) -> dict:
    """Process-parallel execution with progress + RSS telemetry. Workers
    recycle after 100 cases (the reference's maxtasksperchild leak guard,
    gen_runner.py:288)."""
    import multiprocessing as mp

    presets = tuple(sorted({c.preset for c in cases}))
    forks = tuple(sorted({c.fork for c in cases}))
    ctx = mp.get_context("fork")
    counts = {"written": 0, "skipped": 0, "failed": 0}
    keys = [case_key(c) for c in cases]
    t0 = time.monotonic()
    last_print = 0.0
    max_rss = 0
    with ctx.Pool(
        processes=n_workers,
        initializer=_pool_init,
        initargs=(output_dir, presets, forks, "tests"),
        maxtasksperchild=100,
    ) as pool:
        for i, (key, status, rss, obs_delta) in enumerate(
            pool.imap_unordered(_pool_exec, keys, chunksize=4), start=1
        ):
            counts[status] += 1
            max_rss = max(max_rss, rss)
            for cname, n in obs_delta.items():
                obs.count(cname, n)
            if status == "failed" and verbose:
                print(f"[gen] FAILED {'/'.join(map(str, key))}", file=sys.stderr)
            now = time.monotonic()
            if verbose and (now - last_print > 2 or i == len(keys)):
                last_print = now
                rate = i / max(now - t0, 1e-9)
                print(
                    f"[gen] {i}/{len(keys)} ({rate:.1f} case/s, "
                    f"worker rss {max_rss} MB, "
                    f"w={counts['written']} s={counts['skipped']} f={counts['failed']})",
                    file=sys.stderr,
                )
    # dumper counters were shipped per-result above; per-part digest
    # events reach the shared JSONL sink directly from each worker.
    # gen.cases_* mirror the parent's authoritative status counts.
    for status, n in counts.items():
        obs.count(f"gen.cases_{status}", n)
    obs.event("gen.pool_summary", workers=n_workers, max_rss_mb=max_rss, **counts)
    return counts
