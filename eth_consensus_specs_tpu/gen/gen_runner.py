"""Vector-generation executor (reference analogue:
gen_base/gen_runner.py:113-320).

Two modes:

* sequential (default) — simple, in-process;
* process pool (``workers=N`` or ``"auto"``) — a crash-safe pool built
  on raw worker processes with per-worker task queues and async result
  collection. Unlike the reference's pathos pool (which loses in-flight
  work when a worker hard-crashes), the parent here runs a deadline
  sweep: a SIGKILLed/OOM-killed worker is detected via its exitcode, a
  case that blows its wall-clock deadline gets its worker killed, and in
  both paths the lost case is re-dispatched up to a retry budget while a
  replacement worker spawns. Cases are addressed by coordinate key and
  re-discovered inside each worker (the case closures themselves don't
  pickle, exactly why the reference uses a dill-based pool; re-discovery
  is one import pass per worker).

Durability: the parent appends every completed case (key + part
digests) to a JSONL run manifest (gen/manifest.py) AFTER its case dir
is atomically committed by the dumper, so ``resume=True`` (CLI
``--resume``) skips already-durable cases and a re-run after a crash
regenerates only what is missing. Fault-injection sites (``gen.case``,
``gen.dump_bytes`` — see fault/) make all of this rehearsable in tests.
"""

from __future__ import annotations

import os
import sys
import time
import traceback
from collections import deque

from eth_consensus_specs_tpu import fault, obs
from eth_consensus_specs_tpu.obs import flight, trace
from eth_consensus_specs_tpu.obs.delta import DeltaShipper, merge_delta

from .dumper import Dumper
from .gen_from_tests import TestCase
from .manifest import RunManifest

# workers recycle after this many cases (the reference's maxtasksperchild
# leak guard, gen_runner.py:288)
_MAX_TASKS_PER_WORKER = 100

# extra deadline slack for a case dispatched to a worker that is still
# starting up (_pool_init's discovery pass + first-call compiles must not
# count against the case's own wall-clock budget); the deadline tightens
# to `case_timeout` when the worker's "started" message arrives
_STARTUP_GRACE_S = 120.0


class SkippedCase(Exception):
    pass


def execute_case(case: TestCase, dumper: Dumper) -> str | None:
    """Run one case in generator mode and dump its parts. Returns the case
    dir, or None if the case was skipped."""
    from eth_consensus_specs_tpu.test_infra.context import SkippedTest

    fault.check("gen.case", tag=f"{case.runner}/{case.handler}/{case.case_name}")
    dumper.pop_digests()  # drop stale digests a mid-dump failure left behind
    with obs.span("gen.case", runner=case.runner, handler=case.handler):
        try:
            gen = case.case_fn()
            if gen is None:
                obs.count("gen.cases_skipped", 1)
                return None  # test yielded nothing (pure-assertion case)
            # snapshot each part AT YIELD TIME: tests yield live state objects
            # ("pre" and "post" are often the same mutated instance), so views
            # must be copied before the generator advances (the reference
            # serializes eagerly for the same reason, yield_generator.py:10-43)
            parts = []
            for name, value in gen:
                parts.append((name, _snapshot(value)))
        except SkippedTest:
            obs.count("gen.cases_skipped", 1)
            return None
        if not parts:
            # plain-assertion test (no yielded vector parts): nothing to emit
            obs.count("gen.cases_skipped", 1)
            return None
        if case.bls_setting:
            parts.insert(0, ("bls_setting", case.bls_setting))
        out = dumper.dump_case(case, parts)
    obs.count("gen.cases_written", 1)
    return out


def _snapshot(value):
    # deep-copy view lists BEFORE the generic .copy() check — list.copy()
    # is shallow and would alias the contained views
    if isinstance(value, (list, tuple)):
        return [_snapshot(v) for v in value]
    if hasattr(value, "copy") and callable(value.copy):
        return value.copy()
    return value


def run_generator(
    cases,
    output_dir: str,
    verbose: bool = False,
    workers: int | str | None = None,
    *,
    case_timeout: float | None = None,
    case_retries: int = 1,
    resume: bool = False,
) -> dict:
    """Execute all cases; returns {written, skipped, failed, resumed}.

    ``workers``: None/0/1 = sequential; an int or "auto" = process pool.
    ``case_timeout``: pool-mode wall-clock deadline per case (seconds);
    a case past it gets its worker killed and is re-dispatched.
    ``case_retries``: extra attempts for a failed/lost/hung case.
    ``resume``: skip cases already recorded in the output dir's run
    manifest (gen/manifest.py) from a previous, possibly interrupted run."""
    cases = list(cases)
    case_retries = max(case_retries, 0)
    manifest = RunManifest(output_dir, resume=resume)
    if resume:
        pending_cases = [c for c in cases if case_key(c) not in manifest.completed]
    else:
        pending_cases = cases
    resumed = len(cases) - len(pending_cases)
    if resumed:
        obs.count("gen.cases_resumed", resumed)
        obs.event("gen.resume", resumed=resumed, pending=len(pending_cases))
    # one trace per run: every case (sequential or in a pool worker)
    # runs under a child context, so all gen.case spans — including
    # those recorded in worker processes and shipped via the shared
    # JSONL sink — stitch back to this root
    run_ctx = trace.new_trace()
    obs.event("gen.run", cases=len(pending_cases), **trace.event_fields(run_ctx))
    try:
        if workers in (None, 0, 1):
            with trace.activate(run_ctx):
                stats = _run_sequential(
                    pending_cases, output_dir, verbose, case_retries, manifest
                )
        else:
            # os.cpu_count() may return None (unknown topology): default to
            # one worker rather than crashing on None - 1
            n_workers = ((os.cpu_count() or 2) - 1) if workers == "auto" else int(workers)
            stats = _run_pool(
                pending_cases,
                output_dir,
                verbose,
                max(n_workers, 1),
                case_timeout,
                case_retries,
                manifest,
                run_ctx,
            )
    finally:
        manifest.close()
        # a worker killed mid-dump leaves an uncommitted staging dir; the
        # final tree must hold only fully-committed case dirs
        from .manifest import clean_stale_tmp

        clean_stale_tmp(output_dir)
    stats["resumed"] = resumed
    return stats


def _run_sequential(
    cases, output_dir: str, verbose: bool, case_retries: int, manifest: RunManifest
) -> dict:
    dumper = Dumper(output_dir)
    written = skipped = failed = 0
    for case in cases:
        attempts_used = 0

        def _attempt(case=case):
            nonlocal attempts_used
            attempts_used += 1
            # per-case trace span: child of the run root active here
            with trace.activate(trace.child()):
                return execute_case(case, dumper)

        try:
            out = fault.retrying(
                _attempt, name="gen.case_retry", attempts=case_retries + 1, base_delay=0.02
            )
        except Exception:
            failed += 1
            obs.count("gen.cases_failed", 1)
            flight.trigger_dump(
                "gen.case_failed",
                detail=f"{case.runner}/{case.handler}/{case.case_name}",
                extra={"traceback": traceback.format_exc()[-4000:]},
            )
            if verbose:
                print(f"[gen] FAILED {case.runner}/{case.handler}/{case.case_name}",
                      file=sys.stderr)
                traceback.print_exc()
            continue
        if attempts_used > 1:
            obs.count("gen.cases_retried", 1)
        digests = dumper.pop_digests()
        if out is None:
            skipped += 1
            manifest.record(case_key(case), "skipped", {})
        else:
            written += 1
            manifest.record(
                case_key(case), "written", digests, os.path.relpath(out, output_dir)
            )
            if verbose:
                print(f"[gen] wrote {out}", file=sys.stderr)
    return {"written": written, "skipped": skipped, "failed": failed}


def case_key(case: TestCase) -> tuple:
    return (case.preset, case.fork, case.runner, case.handler, case.case_name)


_WORKER_CASES: dict | None = None
_WORKER_DUMPER: Dumper | None = None
_WORKER_SERVICE = None


def _pool_init(output_dir: str, presets: tuple, forks: tuple | None, package: str):
    """Worker initializer: rebuild the case index once per worker
    process (closures don't pickle; coordinates do)."""
    global _WORKER_CASES, _WORKER_DUMPER, _WORKER_SERVICE
    from .gen_from_tests import discover_test_cases
    from .runners import get_runner_cases

    found = discover_test_cases(
        presets=presets, forks=list(forks) if forks else None, package=package
    )
    found += get_runner_cases(presets=presets)
    _WORKER_CASES = {case_key(c): c for c in found}
    _WORKER_DUMPER = Dumper(output_dir)
    from eth_consensus_specs_tpu import serve

    if serve.serve_enabled():
        # With a replicated front door running (the parent exported
        # ETH_SPECS_SERVE_FRONTDOOR before forking), this worker routes
        # its BLS verifies ACROSS the process boundary: shape-affine,
        # failure-aware, hedged — one shared fleet instead of one
        # private service per worker. Otherwise, the per-worker
        # in-process service as before. idle_flush because a pool
        # worker is a SINGLE synchronous submitter — without it every
        # verify would pay the full deadline wait for co-riders that
        # cannot exist. serve.*/frontdoor.* counters land in the
        # worker's obs registry and ship to the parent with every case
        # result via the existing _worker_obs_delta shipping.
        _WORKER_SERVICE = serve.maybe_frontdoor_client(
            name=f"gen-worker-fd-{os.getpid()}"
        ) or serve.VerifyService(
            serve.ServeConfig.from_env(idle_flush=True),
            name=f"gen-worker-{os.getpid()}",
        )
        serve.install_routing(_WORKER_SERVICE)


def _pool_shutdown():
    """Worker teardown: drain + close the per-worker service (emits its
    serve.stats event) before the process exits or recycles."""
    global _WORKER_SERVICE
    if _WORKER_SERVICE is not None:
        from eth_consensus_specs_tpu import serve

        serve.uninstall_routing()
        _WORKER_SERVICE.close()
        _WORKER_SERVICE = None


_WORKER_SHIPPER: DeltaShipper | None = None


def _worker_obs_delta() -> dict:
    """Delta of ALL this worker's obs state since the previous case —
    shipped with each result so pool mode reports what sequential mode
    does. The four sections (counters / gauges / histograms / flight)
    and their merge semantics live in obs/delta.py, shared with the
    serving front door's replica health probes; only ``gen.cases_*``
    counters stay out of the ship — the parent mirrors those from its
    own authoritative status counts. The shipper swallows fork-inherited
    registry state at init, so the first delta covers THIS worker's
    work only and a stale forked gauge can't overwrite the parent's."""
    global _WORKER_SHIPPER
    if _WORKER_SHIPPER is None:
        _WORKER_SHIPPER = DeltaShipper(
            skip_counter_prefixes=("gen.cases_",), swallow_initial=False
        )
    return _WORKER_SHIPPER.delta()


def _merge_worker_obs(delta: dict, worker_ring: deque | None = None) -> None:
    """Fold one worker result's obs delta into the parent registry; the
    worker's shipped flight entries append to the parent's bounded
    per-worker ring copy (the crash black box)."""
    merge_delta(delta, worker_ring)


def _pool_exec(key: tuple) -> tuple:
    """Run one case in the worker; returns
    (key, status, rss_mb, obs_delta, part_digests, case_dir|None)."""
    import resource

    case = _WORKER_CASES.get(key)
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    if case is None:
        return key, "failed", rss, _worker_obs_delta(), {}, None
    try:
        out = execute_case(case, _WORKER_DUMPER)
    except Exception:
        traceback.print_exc()
        # the worker survived the exception, so it writes its own black
        # box (a SIGKILLed worker can't — the parent dumps for it from
        # the ring entries shipped with previous results)
        flight.trigger_dump(
            "gen.worker_exception",
            detail="/".join(map(str, key)),
            extra={"traceback": traceback.format_exc()[-4000:]},
        )
        return key, "failed", rss, _worker_obs_delta(), {}, None
    digests = _WORKER_DUMPER.pop_digests()
    status = "written" if out is not None else "skipped"
    return key, status, rss, _worker_obs_delta(), digests, out


def _worker_main(task_q, result_q, output_dir: str, presets: tuple, forks: tuple, package: str):
    """Crash-safe pool worker loop: serve case keys one at a time until
    the sentinel or the recycling point."""
    _pool_init(output_dir, presets, forks, package)
    # swallow counters inherited from the parent across fork: the first
    # shipped delta must cover THIS worker's work only
    _worker_obs_delta()
    done = 0
    try:
        while True:
            task = task_q.get()
            if task is None:
                break
            # tasks ship as (key, trace-wire): the parent's per-case
            # context crosses the process boundary in the payload, so
            # worker-side gen.case spans (shared JSONL sink) stitch
            # into the parent's trace tree
            key, wire = task
            try:
                # the case's wall clock starts HERE, not at dispatch: init and
                # queue latency must not eat the case's deadline budget
                result_q.put(("started", os.getpid(), key))
            except Exception:
                break
            try:
                with trace.activate(trace.from_wire(wire)):
                    res = _pool_exec(key)
            except BaseException:
                # _pool_exec already catches case errors; this guards the
                # machinery itself — report and keep serving
                traceback.print_exc()
                res = (key, "failed", 0, {}, {}, None)
            try:
                result_q.put(("done", os.getpid(), res))
            except Exception:
                break
            done += 1
            if done >= _MAX_TASKS_PER_WORKER:
                result_q.put(("recycle", os.getpid(), None))
                break
    finally:
        _pool_shutdown()


class _Worker:
    __slots__ = ("proc", "task_q", "res_q", "busy_key", "deadline", "dead_since")

    def __init__(self, proc, task_q, res_q):
        self.proc = proc
        self.task_q = task_q
        self.res_q = res_q
        self.busy_key = None
        self.deadline = None
        self.dead_since = None


def _run_pool(
    cases,
    output_dir: str,
    verbose: bool,
    n_workers: int,
    case_timeout: float | None,
    case_retries: int,
    manifest: RunManifest,
    run_ctx=None,
) -> dict:
    """Process-parallel execution with crash/hang recovery, progress and
    RSS telemetry. The parent collects results asynchronously and sweeps
    for dead (exitcode != 0) and hung (past `case_timeout`) workers;
    their in-flight case re-dispatches up to `case_retries` times."""
    import multiprocessing as mp
    from queue import Empty

    presets = tuple(sorted({c.preset for c in cases}))
    forks = tuple(sorted({c.fork for c in cases}))
    from eth_consensus_specs_tpu import serve

    # ETH_SPECS_SERVE=1 + ETH_SPECS_SERVE_REPLICAS=R: the parent boots
    # ONE replicated front door and exports its addresses before forking
    # workers — every worker routes verifies through the shared,
    # supervised fleet instead of a private per-worker service
    frontdoor = None
    n_replicas = serve.FrontDoorConfig.from_env().replicas if serve.serve_enabled() else 0
    if n_replicas > 0 and not serve.frontdoor_addrs():
        frontdoor = serve.FrontDoor(replicas=n_replicas, name="gen-frontdoor")
        os.environ.update(frontdoor.export_env())
        obs.event("gen.frontdoor", replicas=n_replicas,
                  addrs=",".join(frontdoor.addresses()))
    ctx = mp.get_context("fork")
    counts = {"written": 0, "skipped": 0, "failed": 0}
    # dedup while preserving order: the resolved SET is compared against
    # len(keys), so a duplicate key could otherwise never terminate
    keys = list(dict.fromkeys(case_key(c) for c in cases))
    pending: deque = deque(keys)
    attempts: dict[tuple, int] = dict.fromkeys(keys, 0)
    resolved: set[tuple] = set()
    workers: dict[int, _Worker] = {}
    # each worker's last-shipped flight ring (bounded like the ring
    # itself): the black box the parent dumps when the worker dies
    worker_rings: dict[int, deque] = {}
    t0 = time.monotonic()
    last_print = 0.0
    max_rss = 0
    replaced = retried = timeouts = 0
    # circuit breaker: worker losses with NO completed case in between.
    # A systemic startup failure (broken import in the discovery pass,
    # fork-time resource exhaustion) would otherwise respawn forever.
    losses_since_progress = 0
    max_consecutive_losses = max(3 * n_workers, 6)

    def spawn():
        # one PRIVATE result queue per worker: a worker killed mid-write
        # can desync a queue's byte stream permanently, and on a shared
        # queue that would poison every other worker's results too
        task_q = ctx.Queue()
        res_q = ctx.Queue()
        proc = ctx.Process(
            target=_worker_main,
            args=(task_q, res_q, output_dir, presets, forks, "tests"),
            daemon=True,
        )
        fault.retrying(proc.start, name="gen.worker_spawn", attempts=3)
        workers[proc.pid] = _Worker(proc, task_q, res_q)
        worker_rings[proc.pid] = deque(maxlen=max(flight.capacity(), 1))

    def requeue_or_fail(key: tuple):
        nonlocal retried
        attempts[key] += 1
        if attempts[key] <= case_retries:
            retried += 1
            obs.count("gen.cases_retried", 1)
            pending.appendleft(key)
        else:
            resolved.add(key)
            counts["failed"] += 1
            if verbose:
                print(f"[gen] FAILED {'/'.join(map(str, key))}", file=sys.stderr)

    for _ in range(min(n_workers, len(pending))):
        spawn()

    try:
        while len(resolved) < len(keys):
            # 1. dispatch: one in-flight case per idle LIVE worker (a dead
            # worker would charge the case a retry attempt it never used)
            for w in workers.values():
                if w.busy_key is not None or not w.proc.is_alive():
                    continue
                while pending and pending[0] in resolved:
                    pending.popleft()  # late duplicate of a re-dispatched case
                if not pending:
                    break
                key = pending.popleft()
                # ship the per-case trace context with the task: the
                # worker activates it around the case execution
                w.task_q.put((key, trace.to_wire(trace.child(run_ctx))))
                w.busy_key = key
                w.deadline = (
                    time.monotonic() + case_timeout + _STARTUP_GRACE_S
                    if case_timeout
                    else None
                )
            # 2. collect: drain every worker's private result queue — a
            # dead worker's already-delivered result must resolve its case
            # before the sweep below can requeue (and re-run) it, and a
            # torn stream from a mid-write kill only ever loses that
            # worker's own messages
            got_any = False
            for pid, w in list(workers.items()):
                while True:
                    try:
                        msg, _pid, payload = w.res_q.get_nowait()
                    except Empty:
                        break
                    except Exception:
                        # truncated pickle frame from a killed writer
                        # (UnpicklingError/EOFError/OSError): the stream is
                        # dead; the sweep re-dispatches its in-flight case
                        obs.count("gen.result_stream_errors", 1)
                        break
                    got_any = True
                    if msg == "started":
                        # the worker began executing: tighten the dispatch-
                        # time deadline (startup grace) to the case's budget
                        if w.busy_key == payload and case_timeout:
                            w.deadline = time.monotonic() + case_timeout
                    elif msg == "done":
                        key, status, rss, obs_delta, digests, case_dir = payload
                        if w.busy_key == key:
                            w.busy_key = None
                            w.deadline = None
                        losses_since_progress = 0
                        max_rss = max(max_rss, rss)
                        _merge_worker_obs(obs_delta, worker_rings.get(pid))
                        if key in resolved:
                            pass  # late duplicate of a re-dispatched case
                        elif status == "failed":
                            requeue_or_fail(key)
                        else:
                            resolved.add(key)
                            counts[status] += 1
                            rel = (
                                os.path.relpath(case_dir, output_dir)
                                if case_dir
                                else None
                            )
                            manifest.record(key, status, digests, rel)
                    elif msg == "recycle":
                        workers.pop(pid, None)
                        worker_rings.pop(pid, None)  # clean exit: no black box
                        w.proc.join(timeout=10)
                        obs.count("gen.workers_recycled", 1)
                        if w.busy_key is not None and w.busy_key not in resolved:
                            # dispatched between the worker's last result and
                            # its recycle notice: the case never ran — requeue
                            pending.appendleft(w.busy_key)
                        if len(resolved) < len(keys):
                            spawn()
                        break  # worker gone; nothing more on its queue
            if not got_any:
                time.sleep(0.05)
            # 3. sweep: dead workers (crash/OOM/SIGKILL) and hung cases
            now = time.monotonic()
            for pid, w in list(workers.items()):
                alive = w.proc.is_alive()
                hung = (
                    alive
                    and w.busy_key is not None
                    and w.deadline is not None
                    and now > w.deadline
                )
                if alive and not hung:
                    continue
                if hung:
                    timeouts += 1
                    obs.count("gen.cases_timeout", 1)
                    obs.event(
                        "gen.case_timeout",
                        case="/".join(map(str, w.busy_key)),
                        timeout_s=case_timeout,
                    )
                    w.proc.kill()
                elif w.proc.exitcode == 0:
                    # clean exit: give its recycle message a grace window to
                    # arrive; past that, treat it as lost (a worker that died
                    # after a failed result_q.put must not hang the run)
                    if w.dead_since is None:
                        w.dead_since = now
                        continue
                    if now - w.dead_since < 5.0:
                        continue
                w.proc.join(timeout=10)
                workers.pop(pid)
                replaced += 1
                losses_since_progress += 1
                obs.count("gen.workers_replaced", 1)
                obs.event(
                    "gen.worker_lost",
                    exitcode=w.proc.exitcode,
                    case="/".join(map(str, w.busy_key or ())),
                    hung=hung,
                )
                # the dead worker's black box: it can't dump its own ring
                # any more, so the parent dumps the copy shipped with its
                # results (plus the parent's own ring for pool context)
                flight.trigger_dump(
                    "gen.worker_lost",
                    detail="/".join(map(str, w.busy_key or ())) or "idle",
                    extra={
                        "worker_pid": pid,
                        "exitcode": w.proc.exitcode,
                        "hung": hung,
                        "in_flight_case": list(w.busy_key) if w.busy_key else None,
                        "worker_ring": list(worker_rings.get(pid, ())),
                    },
                )
                worker_rings.pop(pid, None)
                if w.busy_key is not None and w.busy_key not in resolved:
                    requeue_or_fail(w.busy_key)
                if losses_since_progress > max_consecutive_losses:
                    # systemic failure (every replacement dies before
                    # completing anything): abort loudly instead of
                    # respawning forever
                    obs.event(
                        "gen.pool_aborted", consecutive_losses=losses_since_progress
                    )
                    raise RuntimeError(
                        f"generation pool aborted: {losses_since_progress} worker "
                        "losses without a completed case — workers are failing "
                        "systematically (startup/import error or resource "
                        "exhaustion), see stderr for worker tracebacks"
                    )
                if len(resolved) < len(keys):
                    spawn()
            if verbose and (now - last_print > 2):
                last_print = now
                done_n = len(resolved)
                rate = done_n / max(now - t0, 1e-9)
                print(
                    f"[gen] {done_n}/{len(keys)} ({rate:.1f} case/s, "
                    f"worker rss {max_rss} MB, "
                    f"w={counts['written']} s={counts['skipped']} f={counts['failed']})",
                    file=sys.stderr,
                )
    finally:
        for w in workers.values():
            try:
                w.task_q.put(None)
            except Exception:
                pass
        for w in workers.values():
            w.proc.join(timeout=5)
            if w.proc.is_alive():
                w.proc.kill()
                w.proc.join(timeout=5)
        if frontdoor is not None:
            os.environ.pop("ETH_SPECS_SERVE_FRONTDOOR", None)
            frontdoor.close()
    # dumper counters were shipped per-result above; per-part digest
    # events reach the shared JSONL sink directly from each worker.
    # gen.cases_* mirror the parent's authoritative status counts.
    for status, nv in counts.items():
        obs.count(f"gen.cases_{status}", nv)
    obs.event(
        "gen.pool_summary",
        workers=n_workers,
        max_rss_mb=max_rss,
        replaced=replaced,
        retried=retried,
        timeouts=timeouts,
        **counts,
    )
    return counts
