"""KZG runner: blob commitment / proof vectors computed directly
(reference: tests/generators/runners/kzg.py; formats:
tests/formats/kzg_4844/README.md — data.yaml with {input, output}).

Uses whichever trusted setup the framework has active (the ceremony
setup when loaded, else the insecure testing setup) — vectors are
self-consistent either way."""

from __future__ import annotations

import hashlib
from functools import lru_cache as _lru_cache

from ..gen_from_tests import TestCase


def _make_blob(tag: bytes):
    from eth_consensus_specs_tpu.crypto import kzg

    out = []
    for i in range(kzg.FIELD_ELEMENTS_PER_BLOB):
        h = hashlib.sha256(tag + i.to_bytes(4, "big")).digest()
        out.append((int.from_bytes(h, "big") % kzg.BLS_MODULUS).to_bytes(32, "big"))
    return b"".join(out)


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _active_setup_suite() -> str:
    """Name the suite after the setup that produced the vectors — insecure-
    setup vectors must never masquerade as mainnet-setup ones."""
    import os

    from eth_consensus_specs_tpu.crypto.kzg import _setup_override, _UNSET

    override = _setup_override[0]
    if override is _UNSET:
        override = os.environ.get("ETH_CONSENSUS_TRUSTED_SETUP")
    return "kzg-mainnet" if override else "kzg-insecure-setup"


@_lru_cache(maxsize=1)
def _shared_artifacts():
    """Blob/commitment/proofs computed ONCE, lazily at case execution (not
    at discovery — a --forks-filtered run must not pay the KZG cost, and a
    setup failure must fail cases, not the CLI)."""
    from eth_consensus_specs_tpu.crypto import kzg

    blob = _make_blob(b"kzg-runner")
    commitment = kzg.blob_to_kzg_commitment(blob)
    z = (7).to_bytes(32, "big")
    proof, y = kzg.compute_kzg_proof(blob, z)
    blob_proof = kzg.compute_blob_kzg_proof(blob, commitment)
    return blob, commitment, z, proof, y, blob_proof


def _build_cases():
    blob, commitment, z, proof, y, blob_proof = _shared_artifacts()

    yield (
        "blob_to_kzg_commitment",
        "blob_to_kzg_commitment_case_0",
        {"input": {"blob": _hex(blob)}, "output": _hex(commitment)},
    )
    yield (
        "compute_kzg_proof",
        "compute_kzg_proof_case_0",
        {
            "input": {"blob": _hex(blob), "z": _hex(z)},
            "output": [_hex(proof), _hex(y)],
        },
    )
    yield (
        "verify_kzg_proof",
        "verify_kzg_proof_valid",
        {
            "input": {
                "commitment": _hex(commitment),
                "z": _hex(z),
                "y": _hex(y),
                "proof": _hex(proof),
            },
            "output": True,
        },
    )
    wrong_y = (int.from_bytes(y, "big") + 1).to_bytes(32, "big")
    yield (
        "verify_kzg_proof",
        "verify_kzg_proof_wrong_y",
        {
            "input": {
                "commitment": _hex(commitment),
                "z": _hex(z),
                "y": _hex(wrong_y),
                "proof": _hex(proof),
            },
            "output": False,
        },
    )
    yield (
        "verify_blob_kzg_proof",
        "verify_blob_kzg_proof_valid",
        {
            "input": {
                "blob": _hex(blob),
                "commitment": _hex(commitment),
                "proof": _hex(blob_proof),
            },
            "output": True,
        },
    )
    yield (
        "verify_blob_kzg_proof_batch",
        "verify_blob_kzg_proof_batch_valid",
        {
            "input": {
                "blobs": [_hex(blob)],
                "commitments": [_hex(commitment)],
                "proofs": [_hex(blob_proof)],
            },
            "output": True,
        },
    )


# (handler, case_name) index — enumerable WITHOUT computing any crypto
_CASE_INDEX = [
    ("blob_to_kzg_commitment", "blob_to_kzg_commitment_case_0"),
    ("compute_kzg_proof", "compute_kzg_proof_case_0"),
    ("verify_kzg_proof", "verify_kzg_proof_valid"),
    ("verify_kzg_proof", "verify_kzg_proof_wrong_y"),
    ("verify_blob_kzg_proof", "verify_blob_kzg_proof_valid"),
    ("verify_blob_kzg_proof_batch", "verify_blob_kzg_proof_batch_valid"),
]


def _case_payload(case_name: str):
    for _handler, name, payload in _build_cases():
        if name == case_name:
            return payload
    raise KeyError(case_name)


def get_test_cases(presets=("minimal",)) -> list[TestCase]:
    suite = _active_setup_suite()
    out = []
    for handler, name in _CASE_INDEX:
        out.append(
            TestCase(
                preset="general",
                fork="deneb",
                runner="kzg",
                handler=handler,
                suite=suite,
                case_name=name,
                # computed lazily at EXECUTION, inside run_generator's
                # per-case error handling; artifacts shared via lru_cache
                case_fn=(lambda name=name: iter([("data.yaml", _case_payload(name))])),
            )
        )
    return out
