"""BLS runner: sign/verify/aggregate vectors computed directly against the
framework's BLS core (reference: tests/generators/runners/bls.py; format:
tests/formats/bls/README.md — one data.yaml with {input, output} per case).

Fork/preset-independent crypto; emitted once under the phase0/general
coordinates like the reference's `general` config runners."""

from __future__ import annotations

from eth_consensus_specs_tpu.utils import bls

from ..gen_from_tests import TestCase

_PRIVKEYS = [1, 2, 3, 12345, 2**200 + 7]
_MESSAGES = [b"\x00" * 32, b"\xab" * 32, b"\x56" * 32]


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _sign_cases():
    for i, sk in enumerate(_PRIVKEYS):
        for j, msg in enumerate(_MESSAGES):
            sig = bls.Sign(sk, msg)
            yield (
                f"sign_case_{i}_{j}",
                {
                    "input": {
                        "privkey": _hex(sk.to_bytes(32, "big")),
                        "message": _hex(msg),
                    },
                    "output": _hex(sig),
                },
            )


def _verify_cases():
    sk, other = _PRIVKEYS[0], _PRIVKEYS[1]
    msg = _MESSAGES[1]
    pk = bls.SkToPk(sk)
    sig = bls.Sign(sk, msg)
    yield (
        "verify_valid",
        {
            "input": {"pubkey": _hex(pk), "message": _hex(msg), "signature": _hex(sig)},
            "output": True,
        },
    )
    yield (
        "verify_wrong_pubkey",
        {
            "input": {
                "pubkey": _hex(bls.SkToPk(other)),
                "message": _hex(msg),
                "signature": _hex(sig),
            },
            "output": False,
        },
    )
    yield (
        "verify_tampered_signature",
        {
            "input": {
                "pubkey": _hex(pk),
                "message": _hex(msg),
                "signature": _hex(b"\x01" + bytes(sig)[1:]),
            },
            "output": False,
        },
    )
    yield (
        "verify_infinity_pubkey",
        {
            "input": {
                "pubkey": _hex(bls.G1_POINT_AT_INFINITY),
                "message": _hex(msg),
                "signature": _hex(bls.G2_POINT_AT_INFINITY),
            },
            "output": False,
        },
    )


def _aggregate_cases():
    msg = _MESSAGES[0]
    sigs = [bls.Sign(sk, msg) for sk in _PRIVKEYS[:3]]
    yield (
        "aggregate_3",
        {"input": [_hex(s) for s in sigs], "output": _hex(bls.Aggregate(sigs))},
    )
    pks = [bls.SkToPk(sk) for sk in _PRIVKEYS[:3]]
    agg_sig = bls.Aggregate(sigs)
    yield (
        "fast_aggregate_verify_valid",
        {
            "input": {
                "pubkeys": [_hex(p) for p in pks],
                "message": _hex(msg),
                "signature": _hex(agg_sig),
            },
            "output": True,
        },
    )
    yield (
        "fast_aggregate_verify_extra_pubkey",
        {
            "input": {
                "pubkeys": [_hex(p) for p in pks + [bls.SkToPk(_PRIVKEYS[3])]],
                "message": _hex(msg),
                "signature": _hex(agg_sig),
            },
            "output": False,
        },
    )
    msgs = [bytes([i]) * 32 for i in range(3)]
    per_msg_sigs = [bls.Sign(sk, m) for sk, m in zip(_PRIVKEYS[:3], msgs)]
    yield (
        "aggregate_verify_valid",
        {
            "input": {
                "pubkeys": [_hex(p) for p in pks],
                "messages": [_hex(m) for m in msgs],
                "signature": _hex(bls.Aggregate(per_msg_sigs)),
            },
            "output": True,
        },
    )


from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=1)
def _all_payloads() -> dict:
    """All verb payloads, computed once and LAZILY (at case execution, not
    discovery — see kzg.py for the rationale)."""
    prev = bls.bls_active
    bls.bls_active = True
    try:
        return dict(
            list(_sign_cases()) + list(_verify_cases()) + list(_aggregate_cases())
        )
    finally:
        bls.bls_active = prev


# enumerable without signing anything
_CASE_NAMES = (
    [f"sign_case_{i}_{j}" for i in range(len(_PRIVKEYS)) for j in range(len(_MESSAGES))]
    + [
        "verify_valid",
        "verify_wrong_pubkey",
        "verify_tampered_signature",
        "verify_infinity_pubkey",
    ]
    + [
        "aggregate_3",
        "fast_aggregate_verify_valid",
        "fast_aggregate_verify_extra_pubkey",
        "aggregate_verify_valid",
    ]
)

_HANDLERS = (
    "fast_aggregate_verify",
    "aggregate_verify",
    "aggregate",
    "verify",
    "sign",
)


def get_test_cases(presets=("minimal",)) -> list[TestCase]:
    out = []
    for name in _CASE_NAMES:
        handler = next(h for h in _HANDLERS if name.startswith(h))
        out.append(
            TestCase(
                preset="general",
                fork="phase0",
                runner="bls",
                handler=handler,
                suite="bls",
                case_name=name,
                case_fn=(
                    lambda name=name: iter([("data.yaml", _all_payloads()[name])])
                ),
            )
        )
    return out
