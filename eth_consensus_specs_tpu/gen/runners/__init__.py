"""Dedicated vector runners — vectors computed directly, not replayed from
decorated tests (reference analogue: tests/generators/runners/{bls,kzg,
shuffling,ssz_generic}.py; tests/generators/main.py:6-20 loads 19 such
runner modules and merges their cases with the from-tests bridge).

Each module exposes ``get_test_cases(presets) -> list[TestCase]``; the
CLI merges them with the from-tests discovery under --runners filtering.
Formats are documented per runner in docs/formats.md.
"""

from __future__ import annotations

from . import bls as bls_runner
from . import kzg as kzg_runner
from . import shuffling as shuffling_runner
from . import ssz_generic as ssz_generic_runner

RUNNER_MODULES = {
    "bls": bls_runner,
    "kzg": kzg_runner,
    "shuffling": shuffling_runner,
    "ssz_generic": ssz_generic_runner,
}


def get_runner_cases(presets=("minimal",), runners=None) -> list:
    cases = []
    for name, mod in RUNNER_MODULES.items():
        if runners is not None and name not in runners:
            continue
        cases.extend(mod.get_test_cases(presets))
    return cases
