"""Shuffling runner: whole-permutation vectors computed directly
(reference: tests/generators/runners/shuffling.py:16-58; format:
tests/formats/shuffling/README.md — {seed, count, mapping} in mapping.yaml)."""

from __future__ import annotations

from eth_consensus_specs_tpu.ops.shuffle import shuffle_permutation

from ..gen_from_tests import TestCase

_COUNTS = [0, 1, 2, 3, 5, 16, 100, 333]


def _case_fn(seed: bytes, count: int, rounds: int):
    def run():
        perm = shuffle_permutation(count, seed, rounds)
        yield (
            "mapping.yaml",
            {
                "seed": "0x" + seed.hex(),
                "count": count,
                "mapping": [int(x) for x in perm],
            },
        )

    return run


def get_test_cases(presets=("minimal",)) -> list[TestCase]:
    from eth_consensus_specs_tpu.forks import get_spec

    cases = []
    for preset in presets:
        spec = get_spec("phase0", preset)
        rounds = int(spec.SHUFFLE_ROUND_COUNT)
        for seed_i in range(4):
            seed = bytes([seed_i]) * 32
            for count in _COUNTS:
                cases.append(
                    TestCase(
                        preset=preset,
                        fork="phase0",
                        runner="shuffling",
                        handler="core",
                        suite="shuffle",
                        case_name=f"shuffle_0x{seed[:2].hex()}_{count}",
                        case_fn=_case_fn(seed, count, rounds),
                    )
                )
    return cases
