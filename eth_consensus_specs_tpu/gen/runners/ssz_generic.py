"""ssz_generic runner: serialization vectors for the base type system
(reference: tests/generators/runners/ssz_generic.py; formats:
tests/formats/ssz_generic/README.md — serialized.ssz_snappy + value/meta
for valid cases, lone serialized bytes for invalid ones).

NOTE: no `from __future__ import annotations` here — the Container
definitions below need eagerly-evaluated field annotations."""

from eth_consensus_specs_tpu import ssz

from ..gen_from_tests import TestCase


class _SingleFieldContainer(ssz.Container):
    A: ssz.uint64


class _FixedContainer(ssz.Container):
    A: ssz.uint8
    B: ssz.uint64
    C: ssz.uint32


class _VarContainer(ssz.Container):
    A: ssz.uint16
    B: ssz.List[ssz.uint16, 1024]


def _valid_cases():
    yield "uints", "uint64_max", ssz.uint64(2**64 - 1)
    yield "uints", "uint64_zero", ssz.uint64(0)
    yield "uints", "uint256_big", ssz.uint256(2**255 + 12345)
    yield "boolean", "true", ssz.boolean(True)
    yield "boolean", "false", ssz.boolean(False)
    yield "basic_vector", "vec_uint64_4", ssz.Vector[ssz.uint64, 4]([1, 2, 3, 2**63])
    yield "bitvector", "bitvec_9", ssz.Bitvector[9]([True, False] * 4 + [True])
    yield "bitlist", "bitlist_7_of_16", ssz.Bitlist[16]([True] * 7)
    yield "bitlist", "bitlist_empty", ssz.Bitlist[16]([])
    yield "containers", "single_field", _SingleFieldContainer(A=7)
    yield "containers", "fixed", _FixedContainer(A=1, B=2**40, C=3)
    yield "containers", "variable", _VarContainer(A=9, B=[1, 2, 3])


def _invalid_cases():
    # (handler, name, raw serialized bytes that must FAIL deserialization)
    yield "uints", "uint64_too_short", ssz.uint64, b"\x01" * 7
    yield "uints", "uint64_too_long", ssz.uint64, b"\x01" * 9
    yield "bitvector", "bitvec_9_high_padding_bits", ssz.Bitvector[9], b"\xff\xff"
    yield "bitlist", "bitlist_no_delimiter", ssz.Bitlist[16], b"\x00\x00\x00"
    yield "containers", "fixed_truncated", _FixedContainer, b"\x01\x02"


def _valid_fn(value):
    def run():
        from eth_consensus_specs_tpu.debug.encode import encode

        # reference part names (tests/formats/ssz_generic/README.md):
        # serialized bytes + meta.yaml root + value.yaml object form
        yield "serialized", bytes(ssz.serialize(value))
        yield "root", "0x" + bytes(ssz.hash_tree_root(value)).hex()
        yield "value.yaml", encode(value)

    return run


def _invalid_fn(typ, raw):
    def run():
        try:
            ssz.deserialize(typ, raw)
        except (ssz.DeserializationError, ValueError, IndexError):
            pass
        else:  # pragma: no cover - generator sanity
            raise AssertionError("invalid-case bytes unexpectedly deserialized")
        yield "serialized", raw

    return run


def get_test_cases(presets=("minimal",)) -> list[TestCase]:
    out = []
    for handler, name, value in _valid_cases():
        out.append(
            TestCase(
                preset="general",
                fork="phase0",
                runner="ssz_generic",
                handler=handler,
                suite="valid",
                case_name=name,
                case_fn=_valid_fn(value),
            )
        )
    for handler, name, typ, raw in _invalid_cases():
        out.append(
            TestCase(
                preset="general",
                fork="phase0",
                runner="ssz_generic",
                handler=handler,
                suite="invalid",
                case_name=name,
                case_fn=_invalid_fn(typ, raw),
            )
        )
    return out
