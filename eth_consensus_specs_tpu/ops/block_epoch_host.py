"""Numpy host oracle for ops/block_epoch.py — the independent leg of the
block-epoch bench's correctness coupling (same contract as
ops/state_root_host.py: no XLA in the replay, native-SHA trees), and a
third implementation corner for tests (object path <-> device kernel <->
this oracle)."""

from __future__ import annotations

import numpy as np

from eth_consensus_specs_tpu.ops.block_epoch import BlockEpochParams


def _isqrt(v: int) -> int:
    import math

    return math.isqrt(int(v))


def base_reward_column_np(params: BlockEpochParams, eff: np.ndarray, total: int):
    per_inc = (
        params.effective_balance_increment * params.base_reward_factor
    ) // _isqrt(total)
    return (eff // np.uint64(params.effective_balance_increment)) * np.uint64(per_inc)


def sync_rewards_np(params: BlockEpochParams, total: int):
    per_inc = (
        params.effective_balance_increment * params.base_reward_factor
    ) // _isqrt(total)
    total_increments = total // params.effective_balance_increment
    total_base = per_inc * total_increments
    max_part = (
        total_base
        * params.sync_reward_weight
        // params.weight_denominator
        // params.slots_per_epoch
    )
    part = max_part // params.sync_committee_size
    prop = part * params.proposer_weight // (
        params.weight_denominator - params.proposer_weight
    )
    return int(part), int(prop)


def replay_block_epoch_np(
    params: BlockEpochParams,
    n: int,
    st0,
    blocks,
    eff: np.ndarray,
    withdrawable_epoch: np.ndarray,
    has_eth1_cred: np.ndarray,
    epoch: int,
    with_withdrawals: bool = True,
    root_fn=None,
):
    """Sequential numpy replay of block_epoch_chain.  `st0`/`blocks` are
    the same (numpy-converted) structures the kernel consumes.  With
    `root_fn(balance, cur_part, prev_part, slot_no) -> u32[8]` the
    per-slot root xor-chain is accumulated too.  Returns
    (balance, cur_part, prev_part, next_wd_index, next_wd_validator,
    root_acc)."""
    bal = np.array(np.asarray(st0.balance), np.uint64, copy=True)
    cur = np.array(np.asarray(st0.cur_part), np.uint8, copy=True)
    prev = np.array(np.asarray(st0.prev_part), np.uint8, copy=True)
    wd_index = int(np.asarray(st0.next_wd_index))
    wd_validator = int(np.asarray(st0.next_wd_validator))
    total = max(int(eff.sum()), params.effective_balance_increment)
    base_reward = base_reward_column_np(params, eff, total)
    part_r, prop_r = sync_rewards_np(params, total)
    denom = (
        (params.weight_denominator - params.proposer_weight)
        * params.weight_denominator
        // params.proposer_weight
    )
    acc = np.zeros(8, np.uint32)

    # one conversion per tensor — inside the loops these would re-copy
    # multi-MB arrays thousands of times
    b_att_idx = np.asarray(blocks.att_idx)
    b_att_bits = np.asarray(blocks.att_bits)
    b_att_flags = np.asarray(blocks.att_flags)
    b_att_is_current = np.asarray(blocks.att_is_current)
    b_att_pay = np.asarray(blocks.att_pay)
    b_proposer = np.asarray(blocks.proposer)
    b_sync_idx = np.asarray(blocks.sync_idx)
    b_sync_bits = np.asarray(blocks.sync_bits)
    b_dep_idx = np.asarray(blocks.dep_idx)
    b_dep_amt = np.asarray(blocks.dep_amt)

    S = b_proposer.shape[0]
    slot_no = epoch * params.slots_per_epoch + 1
    for s in range(S):
        # withdrawals sweep (forks/capella.py:223-281)
        if with_withdrawals:
            bound = min(n, params.max_validators_per_withdrawals_sweep)
            window = (wd_validator + np.arange(bound)) % n
            wbal = bal[window]
            full = (
                has_eth1_cred[window]
                & (withdrawable_epoch[window] <= np.uint64(epoch))
                & (wbal > 0)
            )
            partial = (
                has_eth1_cred[window]
                & (eff[window] == np.uint64(params.max_effective_balance))
                & (wbal > np.uint64(params.max_effective_balance))
            )
            elig = full | partial
            rank = np.cumsum(elig)
            take = elig & (rank <= params.max_withdrawals_per_payload)
            amount = np.where(full, wbal, wbal - np.uint64(params.max_effective_balance))
            bal[window[take]] = wbal[take] - amount[take]
            n_taken = int(min(rank[-1] if bound else 0, params.max_withdrawals_per_payload))
            if n_taken == params.max_withdrawals_per_payload:
                last_pos = int(np.max(np.nonzero(take)[0]))
                wd_validator = (wd_validator + last_pos + 1) % n
            else:
                wd_validator = (
                    wd_validator + params.max_validators_per_withdrawals_sweep
                ) % n
            wd_index += n_taken

        # attestations, in block order; the proposer numerator carries
        # across an aggregate's per-committee rows and divides once at
        # the pay boundary (electra EIP-7549 shape)
        A = b_att_idx.shape[1]
        proposer = int(b_proposer[s])
        carry_num = 0
        for a in range(A):
            idx = b_att_idx[s, a]
            bits = b_att_bits[s, a]
            flags = int(b_att_flags[s, a])
            pay = bool(b_att_pay[s, a])
            if flags != 0:
                live = (idx < n) & bits
                part = cur if bool(b_att_is_current[s, a]) else prev
                li = idx[live].astype(np.int64)
                pre = part[li]
                new_bits = np.uint8(flags) & ~pre
                part[li] = pre | new_bits
                weight_sum = np.zeros(li.shape[0], np.uint64)
                for b, w in enumerate(params.weights):
                    weight_sum += np.where((new_bits >> b) & 1, np.uint64(w), np.uint64(0))
                carry_num += int((weight_sum * base_reward[li]).sum())
            if pay:
                bal[proposer] += np.uint64(carry_num // denom)
                carry_num = 0

        # deposits (existing-key top-ups)
        didx = b_dep_idx[s]
        damt = b_dep_amt[s]
        for j in range(didx.shape[0]):
            if didx[j] < n:
                bal[int(didx[j])] += np.uint64(damt[j])

        # sync aggregate — spec order: one op per committee position
        sidx = b_sync_idx[s].astype(np.int64)
        sbits = b_sync_bits[s]
        for pos in range(sidx.shape[0]):
            i = int(sidx[pos])
            if sbits[pos]:
                bal[i] += np.uint64(part_r)
                bal[proposer] += np.uint64(prop_r)
            else:
                bal[i] = bal[i] - np.uint64(part_r) if bal[i] >= part_r else np.uint64(0)

        if root_fn is not None:
            acc = acc ^ root_fn(bal, cur, prev, slot_no)
        slot_no += 1

    return bal, cur, prev, wd_index, wd_validator, acc


def slot_root_fn_from_ctx(ctx):
    """Host slot-root fn straight from a device SlotRootCtx
    (block_epoch.make_root_ctx output): the per-epoch-constant top chunks
    are already filled on the ctx, so the host leg only re-reduces the
    dirty columns — this is fault.degrade's fallback, which must work
    from exactly the inputs the device path had."""
    from eth_consensus_specs_tpu.ops.state_root import (
        BALANCE_LIMIT_CHUNKS_LOG2,
        PARTICIPATION_LIMIT_CHUNKS_LOG2,
    )
    from eth_consensus_specs_tpu.ops.state_root_host import (
        tree_root_np,
        u8_list_root_np,
        u64_chunk_words_np,
        u64_list_root_np,
        zerohash_words,
    )

    n = ctx.n
    zh = zerohash_words(41)
    chunks = np.array(np.asarray(ctx.top_chunks), np.uint32, copy=True)

    def root_fn(bal, cur, prev, slot_no):
        c = chunks.copy()
        c[ctx.slot_field_index] = u64_chunk_words_np(int(slot_no))
        c[ctx.balances_slot] = u64_list_root_np(
            np.asarray(bal), n, BALANCE_LIMIT_CHUNKS_LOG2, zh
        )
        c[ctx.cur_part_slot] = u8_list_root_np(
            np.asarray(cur), n, PARTICIPATION_LIMIT_CHUNKS_LOG2, zh
        )
        c[ctx.prev_part_slot] = u8_list_root_np(
            np.asarray(prev), n, PARTICIPATION_LIMIT_CHUNKS_LOG2, zh
        )
        return tree_root_np(c, ctx.top_depth)

    return root_fn


def slot_root_fn_np(spec, arrays, meta, static, scores, just):
    """Host mirror of block_epoch.make_root_ctx + _slot_root: fill the
    per-epoch-constant top chunks once, then per-slot reduce only the
    dirty columns through the native-SHA trees."""
    from eth_consensus_specs_tpu.ops.state_root import (
        BALANCE_LIMIT_CHUNKS_LOG2,
        PARTICIPATION_LIMIT_CHUNKS_LOG2,
    )
    from eth_consensus_specs_tpu.ops.state_root_host import (
        bitvector4_chunk_np,
        checkpoint_root_np,
        tree_root_np,
        u8_list_root_np,
        u64_chunk_words_np,
        u64_list_root_np,
        validator_registry_root_np,
        zerohash_words,
    )

    n = meta.n_validators
    zh = zerohash_words(41)
    slot_of = {name: i for i, name in meta.dynamic_slots}
    chunks = np.array(np.asarray(arrays.top_chunks), np.uint32, copy=True)
    chunks[slot_of["validators"]] = validator_registry_root_np(
        np.asarray(arrays.val_node_a),
        np.asarray(arrays.val_node_f),
        np.asarray(arrays.slashed_chunk),
        np.asarray(static.eff_balance),
        zh,
    )
    if "inactivity_scores" in slot_of:
        chunks[slot_of["inactivity_scores"]] = u64_list_root_np(
            np.asarray(scores), n, BALANCE_LIMIT_CHUNKS_LOG2, zh
        )
    chunks[slot_of["justification_bits"]] = bitvector4_chunk_np(
        np.asarray(just.justification_bits).astype(bool)
    )
    chunks[slot_of["previous_justified_checkpoint"]] = checkpoint_root_np(
        int(just.prev_justified_epoch), np.asarray(just.prev_justified_root)
    )
    chunks[slot_of["current_justified_checkpoint"]] = checkpoint_root_np(
        int(just.cur_justified_epoch), np.asarray(just.cur_justified_root)
    )
    chunks[slot_of["finalized_checkpoint"]] = checkpoint_root_np(
        int(just.finalized_epoch), np.asarray(just.finalized_root)
    )
    fields = list(spec.BeaconState.fields())
    slot_field = fields.index("slot")

    def root_fn(bal, cur, prev, slot_no):
        c = chunks.copy()
        c[slot_field] = u64_chunk_words_np(int(slot_no))
        c[slot_of["balances"]] = u64_list_root_np(bal, n, BALANCE_LIMIT_CHUNKS_LOG2, zh)
        c[slot_of["current_epoch_participation"]] = u8_list_root_np(
            cur, n, PARTICIPATION_LIMIT_CHUNKS_LOG2, zh
        )
        c[slot_of["previous_epoch_participation"]] = u8_list_root_np(
            prev, n, PARTICIPATION_LIMIT_CHUNKS_LOG2, zh
        )
        return tree_root_np(c, meta.top_depth)

    return root_fn
