"""Incremental dirty-subtree merkleization — a device-resident tree forest.

ROADMAP item 1's standing perf debt: `run_epochs(with_root="state")`
re-merkleizes the ENTIRE state tree every epoch (r04's +1463%
`resident_epoch_plus_root_ms` advisory), even though an accounting epoch
dirties only the balance column, a handful of u64 epoch fields, and the
justification bits. The Verkle/binary-Merkle benchmarking literature
(PAPERS.md, arXiv:2504.14069) quantifies the asymmetry this module
exploits: an UPDATE should cost O(changed leaves x tree depth), not
O(tree size). The fastest hash is the one you don't recompute.

Layout — every tree keeps ALL internal levels resident in HBM as one
flat buffer, leaves first, root last::

    nodes: u32[2^(d+1)-1, 8]      level k at offset 2^(d+1) - 2^(d-k+1)

so `nodes[-1]` is the root and a parent at level k+1 sits at a shift of
its children's indices — no pointer chasing, pure index arithmetic.

Update path (:func:`path_update`): scatter the K dirty leaves, then per
level gather the 2K children, hash, scatter the K parents — ONE
fixed-shape [K, 16] compression body reused by a `fori_loop` over the
levels (dynamic offsets, static shapes), so the graph stays one sha body
+ the loop regardless of depth. Duplicate ancestors (two dirty siblings)
are rehashed redundantly rather than deduplicated: the scatter is
idempotent (same parent -> same hash) and static shapes beat a compacted
but dynamic index set.

Dirty capacity K is a COMPILE key, pow2-bucketed through
serve/buckets.inc_dirty_bucket (the serve-buckets idiom: a small set of
capacities ever compiles; `buckets.merkle_inc_key` is the LIVE key fn
jaxlint proves injective). The live dirty count is data: when it exceeds
the capacity — or the measured crossover where K x depth path work loses
to one vectorized rebuild (`buckets.inc_dense_count`) — `apply_dirty`'s
`lax.cond` takes the DENSE branch, an exact-shrinking-width rebuild of
every level. Both branches produce identical buffers for the same leaf
content; the root is bit-identical to `ops/merkle.tree_root_words` over
the same leaves on every path (tests/test_merkle_inc.py).

Mesh (the PR 8 seams): a forest shards its LEAF axis over the (dp, sp)
serve mesh — `nodes: u32[S, 2^(dl+1)-1, 8]` holds S local trees of depth
dl = d - log2(S), sharded on axis 0 via shard_map. Per-shard path
updates need NO collectives below the shard boundary (each shard owns
its subtree); the log-depth combine above it runs on the gathered
per-shard roots (`forest_root`), S-1 hashes on [S, 8] — tiny. Sharded
roots are bit-identical to the single-device forest because the level
structure is the same tree. Non-pow2 shard counts don't align with
binary tree levels, so `forest_shards` falls back to 1 for them.

Donation: the jitted kernels donate the node buffer (`donate_argnums=
(0,)`) — the forest is updated in place, never copied; jaxlint's
donation-audit PROVES the alias per kernel (the registry family
`merkle_inc` declares `donate=(0,)`, analysis/kernels.py), and rangelint
proves the hash-word/index lanes from the declared domains. The
dirty-index extraction is i32-pure on purpose (an `associative_scan`
prefix sum + drop-mode scatter instead of `jnp.nonzero`/`cumsum`, whose
i64 avals under the package x64 flag would both trip x64-drift and land
outside the range interpreter's proven primitive set).
"""

from __future__ import annotations

import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.ops.sha256 import sha256_pair_words

__all__ = [
    "apply_dirty",
    "build_forest",
    "build_levels",
    "dirty_indices",
    "forest_apply",
    "forest_root",
    "forest_shards",
    "inc_update_hashes",
    "path_update",
    "tree_depth",
    "tree_nodes",
    "update_forest_device",
]


def tree_nodes(depth: int) -> int:
    """Rows of the flat node buffer of a depth-`depth` tree."""
    return (1 << (depth + 1)) - 1


def tree_depth(n_nodes: int) -> int:
    """Inverse of :func:`tree_nodes` (n_nodes = 2^(d+1)-1)."""
    return (n_nodes + 1).bit_length() - 2


def inc_update_hashes(depth: int, cap: int, leaf_hashes: int = 0) -> int:
    """Compressions ONE sparse path update executes at capacity `cap`:
    the kernel hashes exactly cap rows per level (padding duplicates
    included — static shapes) plus `leaf_hashes` per dirty leaf to
    derive the leaf chunk itself. This is the honest work count the
    resident roofline accounting uses (capacity-based: the dispatch
    does this work whether 1 or cap leaves are really dirty)."""
    return cap * (depth + leaf_hashes)


def build_levels(leaves: jnp.ndarray) -> jnp.ndarray:
    """u32[..., 2^d, 8] leaves -> u32[..., 2^(d+1)-1, 8] all levels,
    leaves first, root last — exact shrinking widths (traceable,
    batched over leading dims; the dense-rebuild branch and the forest
    builder share it)."""
    parts = [leaves]
    buf = leaves
    lead = leaves.shape[:-2]
    while buf.shape[-2] > 1:
        w = buf.shape[-2] // 2
        # flatten leading dims: the compression body is 2D [rows, 16]
        buf = sha256_pair_words(buf.reshape(-1, 16)).reshape(*lead, w, 8)
        parts.append(buf)
    return jnp.concatenate(parts, axis=-2)


def dirty_indices(mask: jnp.ndarray, cap: int) -> jnp.ndarray:
    """bool[L] -> i32[cap] packed indices of the True entries
    (ascending), padded with 0. Entries past `cap` are dropped — the
    caller's crossover cond must have routed such masks to the dense
    rebuild. i32-pure: an associative-scan prefix sum + drop-mode
    scatter (no `nonzero`/`cumsum` — their i64 avals under the package
    x64 flag would drift the kernel's dtype set)."""
    n = mask.shape[-1]
    pos = lax.associative_scan(jnp.add, mask.astype(jnp.int32)) - 1
    pos = jnp.where(mask, pos, jnp.int32(cap))
    return jnp.zeros(cap, jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop"
    )


def path_update(nodes: jnp.ndarray, idx: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Re-hash the ancestor paths of K dirty leaves.

    nodes: u32[2^(d+1)-1, 8] flat forest tree; idx: i32[K] leaf indices
    (duplicates allowed — idempotent); vals: u32[K, 8] new leaf chunk
    words. Exactly K compressions per level through ONE [K, 16] body in
    a fori_loop with dynamic level offsets (static shapes: the graph
    never grows with depth)."""
    depth = tree_depth(nodes.shape[-2])
    if depth == 0:
        return nodes.at[jnp.zeros((), jnp.int32)].set(vals[0])
    cap2 = nodes.shape[-2] + 1  # 2^(d+1); level k offset = cap2 - (cap2 >> k)

    def level(k, carry):
        nodes, idx = carry
        parent = idx >> 1
        off_c = jnp.int32(cap2) - (jnp.int32(cap2) >> k)
        off_p = jnp.int32(cap2) - (jnp.int32(cap2) >> (k + jnp.int32(1)))
        left = nodes[off_c + 2 * parent]
        right = nodes[off_c + 2 * parent + 1]
        h = sha256_pair_words(jnp.concatenate([left, right], axis=-1))
        return nodes.at[off_p + parent].set(h), parent

    nodes = nodes.at[idx].set(vals)
    nodes, _ = lax.fori_loop(
        jnp.int32(0), jnp.int32(depth), level, (nodes, idx)
    )
    return nodes


def apply_dirty(
    nodes: jnp.ndarray, mask: jnp.ndarray, leaf_fn, cap: int, dense_count: int
) -> jnp.ndarray:
    """One tree's epoch update: sparse path rehash or dense rebuild.

    `leaf_fn(idx: i32[J]) -> u32[J, 8]` derives leaf chunk words at the
    given leaf indices (vectorized — called with the cap dirty indices
    on the sparse branch, `arange(L)` on the dense one; it must return
    the SSZ zero chunk for padding indices beyond the live leaf count).
    The `lax.cond` routes on the LIVE dirty count: <= `dense_count`
    takes the O(dirty x depth) path update, above it (capacity overflow
    or the measured crossover — serve/buckets.inc_dense_count) the
    exact-width dense rebuild. Both produce identical buffers for the
    same leaf content."""
    n_leaves = (nodes.shape[-2] + 1) // 2
    count = jnp.sum(mask.astype(jnp.int32), dtype=jnp.int32)

    def sparse(nodes):
        idx = dirty_indices(mask, cap)
        return path_update(nodes, idx, leaf_fn(idx))

    def dense(nodes):
        del nodes  # fully rebuilt from the leaf source
        return build_levels(leaf_fn(jnp.arange(n_leaves, dtype=jnp.int32)))

    return lax.cond(count > jnp.int32(dense_count), dense, sparse, nodes)


# ------------------------------------------------------------- forests --
#
# A forest tree is nodes[S, 2^(dl+1)-1, 8]: S local trees over the
# leaf-axis shards (S=1 without a mesh). The top log2(S) levels are not
# stored — they are S-1 hashes over the gathered shard roots, recomputed
# per root read (forest_root).


def forest_shards(depth: int, mesh=None) -> int:
    """Shards a depth-`depth` forest tree splits into on `mesh` — the
    mesh's device count when it is a power of two that divides the leaf
    level, else 1 (binary tree levels cannot split across a non-pow2
    grid; the single-device forest stays bit-identical)."""
    from eth_consensus_specs_tpu.parallel.mesh_ops import shard_count

    s = shard_count(mesh)
    if s <= 1 or s & (s - 1) or (1 << depth) % s or s > (1 << depth):
        return 1
    return s


def build_forest(leaves: jnp.ndarray, shards: int) -> jnp.ndarray:
    """u32[2^d, 8] global leaves -> u32[S, 2^(dl+1)-1, 8] local trees
    (traceable; batched build_levels over the shard axis)."""
    n = leaves.shape[-2]
    return build_levels(leaves.reshape(shards, n // shards, 8))


def forest_root(nodes: jnp.ndarray) -> jnp.ndarray:
    """u32[S, M, 8] forest tree -> u32[8] root: the per-shard roots
    reduced through the log-depth top combine (S=1: the local root IS
    the tree root). Bit-identical to the unsharded tree — the top
    levels are the same tree, just not stored.

    For live MESH-sharded buffers prefer the root `forest_apply`
    returns: it is combined INSIDE the shard_map via an explicit
    all-gather, replicated on every shard, rather than leaving the
    S-way resharding of an [S, 8] array to the SPMD partitioner."""
    # static slices only (a mixed-int index like nodes[0, -1, :] lowers
    # through i64 index normalization — x64-drift in a u32 kernel)
    shard_roots = nodes[:, -1:, :].reshape(nodes.shape[0], 8)
    if nodes.shape[0] == 1:
        return shard_roots.reshape(8)
    return build_levels(shard_roots)[-1:, :].reshape(8)


def forest_apply(
    nodes: jnp.ndarray,
    mask: jnp.ndarray,
    leaf_inputs: tuple,
    leaf_fn,
    cap: int,
    dense_count: int,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply one epoch's dirty set to a forest tree (traceable).

    nodes: u32[S, M, 8]; mask: bool[S, Ll] per-shard dirty leaves;
    leaf_inputs: tuple of arrays with leading [S, Ll] — the per-leaf
    source data; `leaf_fn(inputs, idx)` gets the shard-local input
    tuple (leading [Ll]) and i32[J] local indices and returns u32[J, 8]
    leaf chunk words. With a mesh the S axis shards over (dp, sp):
    per-shard path updates run without collectives (each shard owns its
    subtree and takes its OWN sparse/dense cond on its local count);
    above the shard boundary ONE log-depth all-gather hands every shard
    the S shard roots and each computes the replicated top combine —
    the only collective in the kernel. Returns (nodes, root)."""

    def local_update(nodes1, mask1, *inputs1):
        # one [1, M, 8] shard block (or the whole S=1 forest)
        fn = lambda idx: leaf_fn(tuple(a[0] for a in inputs1), idx)
        return apply_dirty(nodes1[0], mask1[0], fn, cap, dense_count)[None]

    if mesh is None or nodes.shape[0] == 1:
        assert nodes.shape[0] == 1, "multi-shard forest needs its mesh"
        nodes = local_update(nodes, mask, *leaf_inputs)
        return nodes, nodes[:, -1:, :].reshape(8)

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from eth_consensus_specs_tpu.parallel.mesh_ops import BATCH_AXES

    def local(nodes1, mask1, *inputs1):
        out = local_update(nodes1, mask1, *inputs1)
        # log-depth combine above the shard boundary: every shard
        # gathers the S local roots and reduces the (tiny) top tree
        # itself — replicated output, no partitioner-driven resharding
        local_root = out[:, -1:, :].reshape(8)
        shard_roots = lax.all_gather(local_root, BATCH_AXES, tiled=False)
        return out, build_levels(shard_roots)[-1:, :].reshape(8)

    spec = P(BATCH_AXES)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec) + (spec,) * len(leaf_inputs),
        out_specs=(spec, P()),
        check_rep=False,
    )
    return fn(nodes, mask, *leaf_inputs)


# ------------------------------------------------- jitted entry points --
#
# One compiled executable per (depth, capacity, dense threshold[, mesh])
# — the capacity is the pow2 compile bucket (serve/buckets
# .inc_dirty_bucket), exactly the serve-buckets idiom. The node buffer
# is DONATED: updates are in place, jaxlint's donation-audit proves it.


@lru_cache(maxsize=None)
def _apply_kernel(depth: int, cap: int, dense_count: int):
    """Single-device identity-leaf forest update: (nodes[1, M, 8],
    mask[1, L], leaves[1, L, 8]) -> (nodes, root), leaves gathered
    straight from the provided leaf level."""

    @partial(jax.jit, donate_argnums=(0,))
    def run(nodes, mask, leaves):
        fn = lambda inputs, idx: inputs[0][idx]
        return forest_apply(
            nodes, mask, (leaves,), fn, cap, dense_count, mesh=None
        )

    return run


@lru_cache(maxsize=None)
def _apply_kernel_mesh(mesh, depth: int, cap: int, dense_count: int):
    """Mesh variant of :func:`_apply_kernel`: the shard axis of
    (nodes[S, Ml, 8], mask[S, Ll], leaves[S, Ll, 8]) splits over the
    (dp, sp) grid; capacity and crossover apply PER SHARD."""

    @partial(jax.jit, donate_argnums=(0,))
    def run(nodes, mask, leaves):
        fn = lambda inputs, idx: inputs[0][idx]
        return forest_apply(
            nodes, mask, (leaves,), fn, cap, dense_count, mesh=mesh
        )

    return run


def _clear_mesh_kernels_after_fork_in_child() -> None:
    # fork-safety: compiled executables reference the parent's devices
    _apply_kernel_mesh.cache_clear()


os.register_at_fork(after_in_child=_clear_mesh_kernels_after_fork_in_child)


def update_forest_device(
    nodes, mask, leaves, mesh=None, cap: int | None = None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Non-traced convenience entry: one forest-tree update dispatch.

    Buckets the LIVE dirty count into a pow2 capacity
    (serve/buckets.inc_dirty_bucket), notes the compile key through the
    live `buckets.merkle_inc_key` fn (`serve.compiles` accounting — the
    resident smoke's zero-cold-compile gate rides this), and records an
    honest capacity-based work span. The resident loop does NOT go
    through here (its updates fuse into the epoch jit); tests, the
    smoke bench, and standalone callers do. Returns (nodes, root)."""
    import numpy as np

    from eth_consensus_specs_tpu.serve import buckets

    shards, n_local = mask.shape
    depth = tree_depth(nodes.shape[-2]) + (shards - 1).bit_length()
    live = int(np.asarray(jnp.sum(mask, dtype=jnp.int32)))
    if cap is None:
        cap = buckets.inc_dirty_bucket(max(live, 1))
    cap = min(cap, n_local)
    dense_count = buckets.inc_dense_count(tree_depth(nodes.shape[-2]), cap)
    key = buckets.merkle_inc_key(cap, dense_count, depth, mesh=mesh)
    if shards > 1:
        fn = _apply_kernel_mesh(mesh, depth, cap, dense_count)
    else:
        fn = _apply_kernel(depth, cap, dense_count)
    real = shards * inc_update_hashes(tree_depth(nodes.shape[-2]), cap)
    with obs.span(
        "merkle_inc.update",
        work_bytes=96 * real,
        tree_depth=depth,
        dirty=live,
        capacity=cap,
        shards=shards,
    ) as sp:
        with buckets.first_dispatch(*key):
            nodes, root = fn(nodes, mask, leaves)
        sp.result = root
    obs.count("merkle_inc.updates", 1)
    obs.count("merkle_inc.dirty_leaves", live)
    obs.count("merkle_inc.real_hashes", real)
    return nodes, root
