"""Columnar phase0 epoch accounting — ONE fused XLA computation.

The reference computes epoch rewards with Python loops over the validator
registry (reference: specs/phase0/beacon-chain.md:1466-1846 — five delta
components, each an O(validators) pass, plus slashings and the
effective-balance hysteresis sweep).  Here the whole accounting epoch is a
single jitted function over a *columnar* state: one uint64/bool array per
validator field, participation pre-reduced to per-component bit masks.  All
control flow is `jnp.where` on masks; there is no data-dependent branching,
so XLA fuses the entire epoch into a few elementwise kernels + reductions +
one scatter-add (proposer micro-rewards).

Fusion boundary (proved safe, see forks/phase0.py:process_epoch ordering):
the kernel runs justification/finalization -> rewards&penalties ->
slashings -> effective-balance updates.  `process_registry_updates` sits
between rewards and slashings in the spec, but it only mutates epochs of
*unslashed* validators to values in the future (> current_epoch + lookahead),
none of which feed the slashing predicate (requires `slashed`), the active
set at current_epoch, or the balance columns — so hoisting it out of the
fused region is bit-exact.  The host wrapper
(forks/phase0.py:process_epoch_columnar) runs it after the kernel.

All arithmetic is uint64 with floor division, matching the spec's
overflow-as-invalid integer semantics (reference:
specs/phase0/beacon-chain.md:1339-1344); x64 mode is enabled at import.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax

import eth_consensus_specs_tpu  # noqa: F401  (package import enables x64)
import jax.numpy as jnp

U64 = jnp.uint64


@dataclass(frozen=True)
class EpochParams:
    """Compile-time preset constants (static under jit; one compiled
    executable per preset). Values per presets/<p>/phase0.yaml."""

    effective_balance_increment: int
    base_reward_factor: int
    base_rewards_per_epoch: int
    proposer_reward_quotient: int
    min_epochs_to_inactivity_penalty: int
    inactivity_penalty_quotient: int
    proportional_slashing_multiplier: int
    epochs_per_slashings_vector: int
    hysteresis_quotient: int
    hysteresis_downward_multiplier: int
    hysteresis_upward_multiplier: int
    max_effective_balance: int

    @classmethod
    def from_spec(cls, spec) -> "EpochParams":
        return cls(
            effective_balance_increment=spec.EFFECTIVE_BALANCE_INCREMENT,
            base_reward_factor=spec.BASE_REWARD_FACTOR,
            base_rewards_per_epoch=spec.BASE_REWARDS_PER_EPOCH,
            proposer_reward_quotient=spec.PROPOSER_REWARD_QUOTIENT,
            min_epochs_to_inactivity_penalty=spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY,
            inactivity_penalty_quotient=spec.INACTIVITY_PENALTY_QUOTIENT,
            proportional_slashing_multiplier=spec.PROPORTIONAL_SLASHING_MULTIPLIER,
            epochs_per_slashings_vector=spec.EPOCHS_PER_SLASHINGS_VECTOR,
            hysteresis_quotient=spec.HYSTERESIS_QUOTIENT,
            hysteresis_downward_multiplier=spec.HYSTERESIS_DOWNWARD_MULTIPLIER,
            hysteresis_upward_multiplier=spec.HYSTERESIS_UPWARD_MULTIPLIER,
            max_effective_balance=spec.MAX_EFFECTIVE_BALANCE,
        )


class EpochColumns(NamedTuple):
    """Columnar validator registry + previous-epoch participation.

    Per-validator arrays (length N). Participation masks are raw "attested
    for component X" bits; the kernel applies the unslashed filter itself.
    `incl_delay`/`incl_proposer` describe the earliest-included source
    attestation per attester (delay >= 1 everywhere; garbage where
    src_att is False — masked out).
    """

    effective_balance: jnp.ndarray  # u64[N]
    balance: jnp.ndarray  # u64[N]
    slashed: jnp.ndarray  # bool[N]
    activation_epoch: jnp.ndarray  # u64[N]
    exit_epoch: jnp.ndarray  # u64[N]
    withdrawable_epoch: jnp.ndarray  # u64[N]
    src_att: jnp.ndarray  # bool[N] prev-epoch matching-source attester
    tgt_att: jnp.ndarray  # bool[N] prev-epoch matching-target attester
    head_att: jnp.ndarray  # bool[N] prev-epoch matching-head attester
    cur_tgt_att: jnp.ndarray  # bool[N] current-epoch matching-target attester
    incl_delay: jnp.ndarray  # u64[N]
    incl_proposer: jnp.ndarray  # i64[N]


class JustificationState(NamedTuple):
    """Scalar fork-accounting state threaded through the kernel."""

    current_epoch: jnp.ndarray  # u64 scalar
    justification_bits: jnp.ndarray  # bool[4]
    prev_justified_epoch: jnp.ndarray  # u64
    prev_justified_root: jnp.ndarray  # u8[32]
    cur_justified_epoch: jnp.ndarray  # u64
    cur_justified_root: jnp.ndarray  # u8[32]
    finalized_epoch: jnp.ndarray  # u64
    finalized_root: jnp.ndarray  # u8[32]
    block_root_prev: jnp.ndarray  # u8[32] get_block_root(state, prev_epoch)
    block_root_cur: jnp.ndarray  # u8[32] get_block_root(state, cur_epoch)
    slashings_sum: jnp.ndarray  # u64 sum(state.slashings)


class EpochResult(NamedTuple):
    balance: jnp.ndarray
    effective_balance: jnp.ndarray
    justification_bits: jnp.ndarray
    prev_justified_epoch: jnp.ndarray
    prev_justified_root: jnp.ndarray
    cur_justified_epoch: jnp.ndarray
    cur_justified_root: jnp.ndarray
    finalized_epoch: jnp.ndarray
    finalized_root: jnp.ndarray
    rewards: jnp.ndarray  # attestation-delta rewards (parity debugging)
    penalties: jnp.ndarray  # attestation-delta penalties


def isqrt_u64(x: jnp.ndarray) -> jnp.ndarray:
    """Largest r with r*r <= x, for uint64 x (spec integer_squareroot,
    reference: specs/phase0/beacon-chain.md:799-807). Float64 seed gives r
    within +-1 of exact for all x < 2**64; two correction passes each way."""
    r = jnp.minimum(
        jnp.sqrt(x.astype(jnp.float64)).astype(U64), jnp.asarray(0xFFFFFFFF, U64)
    )
    for _ in range(2):
        r = jnp.where((r > 0) & (r * r > x), r - 1, r)
    for _ in range(2):
        rp = r + 1
        ok = (rp <= jnp.asarray(0xFFFFFFFF, U64)) & (rp * rp <= x)
        r = jnp.where(ok, rp, r)
    return r


class LocalReductions:
    """Single-device reduction/scatter primitives. The sharded epoch path
    (parallel/epoch.py) swaps in psum-backed equivalents — the kernel body
    is identical on one chip and on a mesh; only these two ops change."""

    def sum(self, x: jnp.ndarray) -> jnp.ndarray:
        return jnp.sum(x)

    def scatter_add(self, idx: jnp.ndarray, amounts: jnp.ndarray, local_n: int) -> jnp.ndarray:
        """Sum `amounts` into a (globally sized) zero vector at global
        indices `idx`; return this shard's slice of the result."""
        return jnp.zeros(local_n, amounts.dtype).at[jnp.clip(idx, 0, local_n - 1)].add(amounts)


_LOCAL = LocalReductions()


def _total_balance(mask, eff, increment, red) -> jnp.ndarray:
    """max(EFFECTIVE_BALANCE_INCREMENT, sum of effective balances in mask)
    (reference: specs/phase0/beacon-chain.md get_total_balance)."""
    s = red.sum(jnp.where(mask, eff, jnp.zeros_like(eff)))
    return jnp.maximum(s, increment)


def justification_update(just, prev_tgt_bal, cur_tgt_bal, total_active):
    """Branch-free weigh_justification_and_finalization (reference:
    specs/phase0/beacon-chain.md:1466-1525) — identical scalar machine for
    every fork; only the target-balance inputs are fork-specific.

    Returns (bits, prev_je, prev_jr, cur_je, cur_jr, fin_e, fin_r) with the
    genesis guard (epoch <= 1 leaves everything unchanged) applied.
    """
    one = jnp.asarray(1, U64)
    cur_epoch = just.current_epoch
    prev_epoch = jnp.where(cur_epoch > 0, cur_epoch - one, jnp.asarray(0, U64))
    do_justif = cur_epoch > one

    old_bits = just.justification_bits
    old_prev_je, old_prev_jr = just.prev_justified_epoch, just.prev_justified_root
    old_cur_je, old_cur_jr = just.cur_justified_epoch, just.cur_justified_root

    just_prev = prev_tgt_bal * jnp.asarray(3, U64) >= total_active * jnp.asarray(2, U64)
    just_cur = cur_tgt_bal * jnp.asarray(3, U64) >= total_active * jnp.asarray(2, U64)

    # bits shift in one, newest first; then the two justification sets
    b0 = just_cur
    b1 = old_bits[0] | just_prev
    b2, b3 = old_bits[1], old_bits[2]
    new_bits = jnp.stack([b0, b1, b2, b3])

    new_cur_je = jnp.where(just_cur, cur_epoch, jnp.where(just_prev, prev_epoch, old_cur_je))
    new_cur_jr = jnp.where(
        just_cur,
        just.block_root_cur,
        jnp.where(just_prev, just.block_root_prev, old_cur_jr),
    )

    # finalization ladder — later (shorter-span) rules override earlier ones,
    # matching the sequential-if structure of weigh_justification_and_finalization
    fin_e, fin_r = just.finalized_epoch, just.finalized_root
    c234 = b1 & b2 & b3 & (old_prev_je + jnp.asarray(3, U64) == cur_epoch)
    fin_e = jnp.where(c234, old_prev_je, fin_e)
    fin_r = jnp.where(c234, old_prev_jr, fin_r)
    c23 = b1 & b2 & (old_prev_je + jnp.asarray(2, U64) == cur_epoch)
    fin_e = jnp.where(c23, old_prev_je, fin_e)
    fin_r = jnp.where(c23, old_prev_jr, fin_r)
    c123 = b0 & b1 & b2 & (old_cur_je + jnp.asarray(2, U64) == cur_epoch)
    fin_e = jnp.where(c123, old_cur_je, fin_e)
    fin_r = jnp.where(c123, old_cur_jr, fin_r)
    c12 = b0 & b1 & (old_cur_je + one == cur_epoch)
    fin_e = jnp.where(c12, old_cur_je, fin_e)
    fin_r = jnp.where(c12, old_cur_jr, fin_r)

    return (
        jnp.where(do_justif, new_bits, old_bits),
        jnp.where(do_justif, old_cur_je, old_prev_je),
        jnp.where(do_justif, old_cur_jr, old_prev_jr),
        jnp.where(do_justif, new_cur_je, old_cur_je),
        jnp.where(do_justif, new_cur_jr, old_cur_jr),
        jnp.where(do_justif, fin_e, just.finalized_epoch),
        jnp.where(do_justif, fin_r, just.finalized_root),
    )


def epoch_accounting_impl(
    params: EpochParams,
    cols: EpochColumns,
    just: JustificationState,
    red: LocalReductions = _LOCAL,
) -> EpochResult:
    """The fused accounting epoch: justification/finalization, attestation
    rewards & penalties, slashing penalties, effective-balance hysteresis.

    Everything is branch-free; genesis-epoch guards are `where` masks so a
    single compiled executable serves every epoch.
    """
    p = params
    n = cols.balance.shape[0]
    one = jnp.asarray(1, U64)
    zero = jnp.asarray(0, U64)
    incr = jnp.asarray(p.effective_balance_increment, U64)

    cur_epoch = just.current_epoch
    prev_epoch = jnp.where(cur_epoch > 0, cur_epoch - one, zero)

    eff = cols.effective_balance
    not_slashed = ~cols.slashed
    active_cur = (cols.activation_epoch <= cur_epoch) & (cur_epoch < cols.exit_epoch)
    active_prev = (cols.activation_epoch <= prev_epoch) & (prev_epoch < cols.exit_epoch)
    eligible = active_prev | (cols.slashed & (prev_epoch + one < cols.withdrawable_epoch))

    total_active = _total_balance(active_cur, eff, incr, red)

    # -- justification & finalization (scalar; skipped for epochs 0,1) ----
    prev_tgt_bal = _total_balance(cols.tgt_att & not_slashed, eff, incr, red)
    cur_tgt_bal = _total_balance(cols.cur_tgt_att & not_slashed, eff, incr, red)
    (
        out_bits,
        out_prev_je,
        out_prev_jr,
        out_cur_je,
        out_cur_jr,
        out_fin_e,
        out_fin_r,
    ) = justification_update(just, prev_tgt_bal, cur_tgt_bal, total_active)

    # -- rewards & penalties (uses the POST-justification finalized epoch) --
    sqrt_total = isqrt_u64(total_active)
    base_reward = (
        eff
        * jnp.asarray(p.base_reward_factor, U64)
        // sqrt_total
        // jnp.asarray(p.base_rewards_per_epoch, U64)
    )
    proposer_reward = base_reward // jnp.asarray(p.proposer_reward_quotient, U64)

    finality_delay = prev_epoch - out_fin_e
    in_leak = finality_delay > jnp.asarray(p.min_epochs_to_inactivity_penalty, U64)

    rewards = jnp.zeros(n, U64)
    penalties = jnp.zeros(n, U64)
    total_units = total_active // incr
    for mask in (cols.src_att, cols.tgt_att, cols.head_att):
        att = mask & not_slashed
        att_bal = _total_balance(att, eff, incr, red)
        # during leaks attesters are credited as if participation were optimal
        full = jnp.where(in_leak, base_reward, base_reward * (att_bal // incr) // total_units)
        rewards = rewards + jnp.where(eligible & att, full, zero)
        penalties = penalties + jnp.where(eligible & ~att, base_reward, zero)

    # inclusion-delay micro-rewards: attester share decays with delay,
    # proposer share scatter-added at the earliest includer
    src_unslashed = cols.src_att & not_slashed
    att_share = jnp.where(
        src_unslashed, (base_reward - proposer_reward) // jnp.maximum(cols.incl_delay, one), zero
    )
    rewards = rewards + att_share
    prop_amount = jnp.where(src_unslashed, proposer_reward, zero)
    rewards = rewards + red.scatter_add(cols.incl_proposer, prop_amount, n)

    # inactivity leak: quadratic drain on non-target-attesting eligibles
    leak_base = jnp.where(
        eligible & in_leak,
        jnp.asarray(p.base_rewards_per_epoch, U64) * base_reward - proposer_reward,
        zero,
    )
    tgt_unslashed = cols.tgt_att & not_slashed
    leak_extra = jnp.where(
        eligible & in_leak & ~tgt_unslashed,
        eff * finality_delay // jnp.asarray(p.inactivity_penalty_quotient, U64),
        zero,
    )
    penalties = penalties + leak_base + leak_extra

    do_rewards = cur_epoch > zero
    rewards = jnp.where(do_rewards, rewards, jnp.zeros_like(rewards))
    penalties = jnp.where(do_rewards, penalties, jnp.zeros_like(penalties))

    bal = cols.balance + rewards
    bal = bal - jnp.minimum(bal, penalties)

    # -- slashings sweep (runs every epoch, no genesis guard) -------------
    adj_slash = jnp.minimum(
        just.slashings_sum * jnp.asarray(p.proportional_slashing_multiplier, U64),
        total_active,
    )
    half_vec = jnp.asarray(p.epochs_per_slashings_vector // 2, U64)
    slash_now = cols.slashed & (cur_epoch + half_vec == cols.withdrawable_epoch)
    slash_penalty = (eff // incr) * adj_slash // total_active * incr
    bal = bal - jnp.minimum(bal, jnp.where(slash_now, slash_penalty, zero))

    # -- effective-balance hysteresis -------------------------------------
    hyst = incr // jnp.asarray(p.hysteresis_quotient, U64)
    down = hyst * jnp.asarray(p.hysteresis_downward_multiplier, U64)
    up = hyst * jnp.asarray(p.hysteresis_upward_multiplier, U64)
    crossed = (bal + down < eff) | (eff + up < bal)
    new_eff = jnp.where(
        crossed,
        jnp.minimum(bal - bal % incr, jnp.asarray(p.max_effective_balance, U64)),
        eff,
    )

    return EpochResult(
        balance=bal,
        effective_balance=new_eff,
        justification_bits=out_bits,
        prev_justified_epoch=out_prev_je,
        prev_justified_root=out_prev_jr,
        cur_justified_epoch=out_cur_je,
        cur_justified_root=out_cur_jr,
        finalized_epoch=out_fin_e,
        finalized_root=out_fin_r,
        rewards=rewards,
        penalties=penalties,
    )


epoch_accounting = partial(jax.jit, static_argnums=(0,))(epoch_accounting_impl)
