"""Batched Fq2/Fq6/Fq12 tower arithmetic on the device (u64 limb lanes).

Extends the proven 13x30-bit Montgomery Fq kernel (ops/field_limbs.py) up
the BLS12-381 tower: Fq2 = Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3 - xi),
Fq12 = Fq6[w]/(w^2 - v), xi = 1 + u — the exact formula set of the host
oracle (crypto/fields.py), so device values are bit-identical after
canonicalization.

Array layouts (leading axes are free batch dims):

    Fq   [..., 13]          Montgomery limbs
    Fq2  [..., 2, 13]       (c0, c1)
    Fq6  [..., 3, 2, 13]    (c0, c1, c2) Fq2 coefficients
    Fq12 [..., 2, 3, 2, 13] (c0, c1) Fq6 halves

Inversion is Fermat (fixed p-2 square-and-multiply as a lax.scan — no
data-dependent control flow), so everything here jits with static shapes.
Frobenius constants are computed at import from the host tower (no
hardcoded magic numbers to mistype), then converted to Montgomery limbs.

Reference seam: this is the arithmetic behind the device pairing
(ops/pairing_device.py) replacing what the reference delegates to
milagro/arkworks (reference: utils/bls.py:224-296).
"""

from __future__ import annotations

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu.crypto.fields import (
    BLS_X,
    P as P_INT,
    XI,
    Fq,
    Fq2,
    Fq6,
    Fq12,
)
from eth_consensus_specs_tpu.ops.field_limbs import (
    N_LIMBS,
    ONE_MONT,
    add_mod,
    from_mont_int,
    is_zero as fq_is_zero,
    mont_mul,
    sub_mod,
    to_mont,
)

# ---------------------------------------------------------------- host <-> --


def fq2_to_limbs(a: Fq2) -> np.ndarray:
    return np.stack([to_mont(a.c0.n), to_mont(a.c1.n)])


def fq12_to_limbs(f: Fq12) -> np.ndarray:
    return np.stack(
        [
            np.stack([fq2_to_limbs(c) for c in (half.c0, half.c1, half.c2)])
            for half in (f.c0, f.c1)
        ]
    )


def limbs_to_fq2(arr) -> Fq2:
    a = np.asarray(arr)
    return Fq2(Fq(from_mont_int(a[0])), Fq(from_mont_int(a[1])))


def limbs_to_fq12(arr) -> Fq12:
    a = np.asarray(arr)
    halves = [Fq6(*[limbs_to_fq2(a[h, v]) for v in range(3)]) for h in range(2)]
    return Fq12(halves[0], halves[1])


# ------------------------------------------------------------ Fq helpers --

_ZERO = np.zeros(N_LIMBS, np.uint64)


def _const(x) -> jnp.ndarray:
    return jnp.asarray(np.asarray(x, np.uint64))


def fq_neg(a):
    return sub_mod(jnp.broadcast_to(_const(_ZERO), a.shape), a)


def _bits_msb_first(e: int) -> np.ndarray:
    return np.array([int(b) for b in bin(e)[2:]], np.uint8)


_P_MINUS_2_BITS = _bits_msb_first(P_INT - 2)


def fq_pow_const(a, bits: np.ndarray):
    """a^e for a FIXED public exponent (bits MSB-first), batched. Scan body
    is one square + one (selected) multiply — ~constant graph size."""
    xs = jnp.asarray(bits[1:])  # leading 1: start from acc = a

    def step(acc, bit):
        acc = mont_mul(acc, acc)
        withm = mont_mul(acc, a)
        return jnp.where(bit != 0, withm, acc), None

    out, _ = lax.scan(step, a, xs)
    return out


def fq_inv(a):
    """Fermat inverse a^(p-2); returns 0 for 0 (callers mask)."""
    return fq_pow_const(a, _P_MINUS_2_BITS)


# ------------------------------------------------------------------- Fq2 --


def fq2_add(a, b):
    return add_mod(a, b)


def fq2_sub(a, b):
    return sub_mod(a, b)


def fq2_neg(a):
    return fq_neg(a)


def fq2_mul(a, b):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    t0 = mont_mul(a0, b0)
    t1 = mont_mul(a1, b1)
    cross = sub_mod(
        sub_mod(mont_mul(add_mod(a0, a1), add_mod(b0, b1)), t0), t1
    )
    return jnp.stack([sub_mod(t0, t1), cross], axis=-2)


def fq2_sqr(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    t = mont_mul(add_mod(a0, a1), sub_mod(a0, a1))
    b = mont_mul(a0, a1)
    return jnp.stack([t, add_mod(b, b)], axis=-2)


def fq2_mul_fp(a, s):
    """Fq2 [..., 2, 13] times Fq [..., 13]."""
    return jnp.stack(
        [mont_mul(a[..., 0, :], s), mont_mul(a[..., 1, :], s)], axis=-2
    )


def fq2_mul_xi(a):
    """Multiply by xi = 1 + u: (c0 - c1, c0 + c1)."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([sub_mod(a0, a1), add_mod(a0, a1)], axis=-2)


def fq2_conj(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([a0, fq_neg(a1)], axis=-2)


def fq2_inv(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    norm = add_mod(mont_mul(a0, a0), mont_mul(a1, a1))
    ninv = fq_inv(norm)
    return jnp.stack(
        [mont_mul(a0, ninv), fq_neg(mont_mul(a1, ninv))], axis=-2
    )


def fq2_is_zero(a):
    return fq_is_zero(a[..., 0, :]) & fq_is_zero(a[..., 1, :])


# ------------------------------------------------------------------- Fq6 --


def fq6_add(a, b):
    return add_mod(a, b)


def fq6_sub(a, b):
    return sub_mod(a, b)


def fq6_neg(a):
    return fq_neg(a)


def fq6_mul(a, b):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    b0, b1, b2 = b[..., 0, :, :], b[..., 1, :, :], b[..., 2, :, :]
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    c0 = fq2_add(
        t0,
        fq2_mul_xi(
            fq2_sub(fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), t1), t2)
        ),
    )
    c1 = fq2_add(
        fq2_sub(
            fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), t0), t1
        ),
        fq2_mul_xi(t2),
    )
    c2 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), t0), t2), t1
    )
    return jnp.stack([c0, c1, c2], axis=-3)


def fq6_sqr(a):
    return fq6_mul(a, a)


def fq6_mul_v(a):
    """Multiply by v: (c0, c1, c2) -> (c2*xi, c0, c1)."""
    return jnp.stack(
        [fq2_mul_xi(a[..., 2, :, :]), a[..., 0, :, :], a[..., 1, :, :]], axis=-3
    )


def fq6_inv(a):
    av, b, c = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    t0 = fq2_sub(fq2_sqr(av), fq2_mul_xi(fq2_mul(b, c)))
    t1 = fq2_sub(fq2_mul_xi(fq2_sqr(c)), fq2_mul(av, b))
    t2 = fq2_sub(fq2_sqr(b), fq2_mul(av, c))
    denom = fq2_inv(
        fq2_add(
            fq2_mul(av, t0),
            fq2_mul_xi(fq2_add(fq2_mul(c, t1), fq2_mul(b, t2))),
        )
    )
    return jnp.stack(
        [fq2_mul(t0, denom), fq2_mul(t1, denom), fq2_mul(t2, denom)], axis=-3
    )


# ------------------------------------------------------------------ Fq12 --


def fq12_add(a, b):
    return add_mod(a, b)


def fq12_mul(a, b):
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    b0, b1 = b[..., 0, :, :, :], b[..., 1, :, :, :]
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    cross = fq6_sub(
        fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), t0), t1
    )
    return jnp.stack([fq6_add(t0, fq6_mul_v(t1)), cross], axis=-4)


def fq12_sqr(a):
    return fq12_mul(a, a)


def fq12_conj(a):
    return jnp.stack(
        [a[..., 0, :, :, :], fq6_neg(a[..., 1, :, :, :])], axis=-4
    )


def fq12_inv(a):
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    t = fq6_inv(fq6_sub(fq6_sqr(a0), fq6_mul_v(fq6_sqr(a1))))
    return jnp.stack([fq6_mul(a0, t), fq6_neg(fq6_mul(a1, t))], axis=-4)


_FQ12_ONE = fq12_to_limbs(Fq12.one())


def fq12_one(batch_shape: tuple[int, ...] = ()) -> jnp.ndarray:
    one = _const(_FQ12_ONE)
    return jnp.broadcast_to(one, (*batch_shape, *one.shape))


def fq12_is_one(a):
    """True iff the element equals 1 mod p (handles the redundant range)."""
    one = jnp.broadcast_to(_const(_FQ12_ONE), a.shape)
    diff = sub_mod(a, one)
    flat_zero = fq_is_zero(diff)  # [..., 2, 3, 2] per-Fq verdicts
    return jnp.all(flat_zero, axis=(-3, -2, -1))


# coefficient view: f = sum a_i w^i, a_i = f[half=i%2, v=i//2] (fields.py
# Fq12.coeffs ordering)
def _coeff(a, i: int):
    return a[..., i % 2, i // 2, :, :]


def _from_coeffs(cs):
    c0 = jnp.stack([cs[0], cs[2], cs[4]], axis=-3)
    c1 = jnp.stack([cs[1], cs[3], cs[5]], axis=-3)
    return jnp.stack([c0, c1], axis=-4)


_FROB1_G = np.stack([fq2_to_limbs(XI.pow(i * (P_INT - 1) // 6)) for i in range(6)])
_FROB2_G = np.stack(
    [fq2_to_limbs(XI.pow(i * (P_INT * P_INT - 1) // 6)) for i in range(6)]
)


def fq12_frobenius(a):
    """f -> f^p (conjugate each Fq2 coefficient, times gamma1_i)."""
    cs = [
        fq2_mul(fq2_conj(_coeff(a, i)), jnp.broadcast_to(_const(_FROB1_G[i]), _coeff(a, i).shape))
        for i in range(6)
    ]
    return _from_coeffs(cs)


def fq12_frobenius2(a):
    """f -> f^(p^2) (gamma2_i lie in Fq: no conjugation)."""
    cs = [
        fq2_mul(_coeff(a, i), jnp.broadcast_to(_const(_FROB2_G[i]), _coeff(a, i).shape))
        for i in range(6)
    ]
    return _from_coeffs(cs)


# ------------------------------------------------------------- exponents --

_BLS_X_ABS_BITS = _bits_msb_first(-BLS_X)


def fq12_powx(a):
    """a^x for the (negative) BLS parameter x — square-and-multiply over
    the fixed |x| bits, then conjugate (valid in the cyclotomic subgroup
    where inversion is conjugation; mirrors native/bls12_381.c:1098)."""
    xs = jnp.asarray(_BLS_X_ABS_BITS[1:])

    def step(acc, bit):
        acc = fq12_sqr(acc)
        withm = fq12_mul(acc, a)
        return jnp.where(bit != 0, withm, acc), None

    out, _ = lax.scan(step, a, xs)
    return fq12_conj(out)


def fq12_pow_const(a, e: int):
    """a^e for a fixed public exponent (exact final-exp hard part)."""
    bits = _bits_msb_first(e)
    xs = jnp.asarray(bits[1:])

    def step(acc, bit):
        acc = fq12_sqr(acc)
        withm = fq12_mul(acc, a)
        return jnp.where(bit != 0, withm, acc), None

    out, _ = lax.scan(step, a, xs)
    return out
