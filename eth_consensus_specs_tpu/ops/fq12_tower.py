"""Batched Fq2/Fq6/Fq12 tower arithmetic on the device (lazy u64 limbs).

Built on ops/lazy_limbs.py (15x26-bit Montgomery limbs, static bound
tracking): adds/subs are one or two vector ops, and every multiply level
STACKS its independent base-field products into one Montgomery instance
(3 lanes per Fq2 product, 6 per Fq6, 3 per Fq12 — 54 u64 lanes per Fq12
multiply in a single subgraph). The combination keeps pairing-sized XLA
graphs small enough to compile in seconds where the first-generation
normalize-everything kernel took minutes.

Formulas mirror the host oracle (crypto/fields.py) exactly: Fq2 =
Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3 - xi), Fq12 = Fq6[w]/(w^2 - v),
xi = 1 + u — device results are bit-identical after canonicalization.

Array layouts (leading axes free; elements are LF wrappers carrying
static bounds, see lazy_limbs.LF):

    Fq   [..., 15]           Montgomery limbs
    Fq2  [..., 2, 15]        (c0, c1)
    Fq6  [..., 3, 2, 15]     (c0, c1, c2)
    Fq12 [..., 2, 3, 2, 15]  (c0, c1) Fq6 halves

All ops take and return LF; at jit boundaries pass `.v` of a normalized
element and re-wrap with `lz.lf(...)`.

Reference seam: the arithmetic behind the device pairing
(ops/pairing_device.py), replacing what the reference delegates to
milagro/arkworks (reference: utils/bls.py:224-296).
"""

from __future__ import annotations

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu.crypto.fields import (
    BLS_X,
    P as P_INT,
    XI,
    Fq,
    Fq2,
    Fq6,
    Fq12,
)
from eth_consensus_specs_tpu.ops import lazy_limbs as lz
from eth_consensus_specs_tpu.ops.lazy_limbs import LF, lf

N_LIMBS = lz.N_LIMBS

# ---------------------------------------------------------------- host <-> --


def fq2_to_limbs(a: Fq2) -> np.ndarray:
    return np.stack([lz.to_mont(a.c0.n), lz.to_mont(a.c1.n)])


def fq12_to_limbs(f: Fq12) -> np.ndarray:
    return np.stack(
        [
            np.stack([fq2_to_limbs(c) for c in (half.c0, half.c1, half.c2)])
            for half in (f.c0, f.c1)
        ]
    )


def limbs_to_fq2(arr) -> Fq2:
    a = np.asarray(arr)
    return Fq2(Fq(lz.from_mont_int(a[0])), Fq(lz.from_mont_int(a[1])))


def limbs_to_fq12(arr) -> Fq12:
    a = np.asarray(arr)
    halves = [Fq6(*[limbs_to_fq2(a[h, v]) for v in range(3)]) for h in range(2)]
    return Fq12(halves[0], halves[1])


# ------------------------------------------------------------ Fq helpers --


def _bits_msb_first(e: int) -> np.ndarray:
    return np.array([int(b) for b in bin(e)[2:]], np.uint8)


_P_MINUS_2_BITS = _bits_msb_first(P_INT - 2)


def fq_pow_const(a: LF, bits: np.ndarray) -> LF:
    """a^e for a FIXED public exponent (bits MSB-first), batched. The scan
    carry is a raw normalized array (LF wraps inside the body)."""
    a = lz.norm(a)
    xs = jnp.asarray(bits[1:])  # leading 1: start from acc = a

    def step(acc_v, bit):
        acc = lf(acc_v)
        sq = lz.mul(acc, acc)
        withm = lz.mul(sq, lf(a.v))
        return jnp.where(bit != 0, withm.v, sq.v), None

    out, _ = lax.scan(step, a.v, xs)
    return lf(out)


def fq_inv(a: LF) -> LF:
    """Fermat inverse a^(p-2); returns 0 for 0 (callers mask)."""
    return fq_pow_const(a, _P_MINUS_2_BITS)


# ------------------------------------------------------------------- Fq2 --
# component helpers: LF wrapping sub-arrays shares the parent's bounds


def _part(a: LF, i: int, ndim_tail: int) -> LF:
    """Select component i on the axis `ndim_tail` levels above the limbs."""
    idx = (Ellipsis, i) + (slice(None),) * ndim_tail
    return LF(a.v[idx], a.max, a.val)


def _stack(parts: list[LF], axis: int) -> LF:
    return LF(
        jnp.stack([p.v for p in parts], axis=axis),
        max(p.max for p in parts),
        max(p.val for p in parts),
    )


def _lane_stack(parts: list[LF]) -> LF:
    """Stack onto a NEW leading lane axis for batched multiplies."""
    return _stack(parts, 0)


def _unstack(a: LF, n: int) -> list[LF]:
    return [LF(a.v[i], a.max, a.val) for i in range(n)]


def fq2_add(a: LF, b: LF) -> LF:
    return lz.add(a, b)


def fq2_sub(a: LF, b: LF) -> LF:
    return lz.sub(a, b)


def fq2_neg(a: LF) -> LF:
    return lz.sub(lz.zero_like(a), a)


def fq2_mul(a: LF, b: LF) -> LF:
    """Karatsuba; the three Fq products ride one stacked mont instance."""
    a0, a1 = _part(a, 0, 1), _part(a, 1, 1)
    b0, b1 = _part(b, 0, 1), _part(b, 1, 1)
    lhs = _lane_stack([a0, a1, lz.add(a0, a1)])
    rhs = _lane_stack([b0, b1, lz.add(b0, b1)])
    t0, t1, full = _unstack(lz.mul(lhs, rhs), 3)
    cross = lz.sub(lz.sub(full, t0), t1)
    return _stack([lz.sub(t0, t1), cross], axis=-2)


def fq2_sqr(a: LF) -> LF:
    a0, a1 = _part(a, 0, 1), _part(a, 1, 1)
    t, b = _unstack(
        lz.mul(_lane_stack([lz.add(a0, a1), a0]), _lane_stack([lz.sub(a0, a1), a1])),
        2,
    )
    return _stack([t, lz.dbl(b)], axis=-2)


def fq2_mul_fp(a: LF, s: LF) -> LF:
    a0, a1 = _part(a, 0, 1), _part(a, 1, 1)
    r0, r1 = _unstack(
        lz.mul(_lane_stack([a0, a1]), _lane_stack([s, s])), 2
    )
    return _stack([r0, r1], axis=-2)


def fq2_mul_xi(a: LF) -> LF:
    """Multiply by xi = 1 + u: (c0 - c1, c0 + c1)."""
    a0, a1 = _part(a, 0, 1), _part(a, 1, 1)
    return _stack([lz.sub(a0, a1), lz.add(a0, a1)], axis=-2)


def fq2_conj(a: LF) -> LF:
    a0, a1 = _part(a, 0, 1), _part(a, 1, 1)
    return _stack([a0, lz.sub(lz.zero_like(a1), a1)], axis=-2)


def fq2_inv(a: LF) -> LF:
    a0, a1 = _part(a, 0, 1), _part(a, 1, 1)
    s0, s1 = _unstack(lz.mul(_lane_stack([a0, a1]), _lane_stack([a0, a1])), 2)
    ninv = fq_inv(lz.add(s0, s1))
    r0, r1 = _unstack(lz.mul(_lane_stack([a0, a1]), _lane_stack([ninv, ninv])), 2)
    return _stack([r0, lz.sub(lz.zero_like(r1), r1)], axis=-2)


def fq2_is_zero(a: LF):
    red = a if a.val <= 2 * P_INT - 1 else lz.shrink(a)
    return lz.is_zero(_part(red, 0, 1)) & lz.is_zero(_part(red, 1, 1))


# ------------------------------------------------------------------- Fq6 --


def fq6_add(a: LF, b: LF) -> LF:
    return lz.add(a, b)


def fq6_sub(a: LF, b: LF) -> LF:
    return lz.sub(a, b)


def fq6_neg(a: LF) -> LF:
    return lz.sub(lz.zero_like(a), a)


def fq6_mul(a: LF, b: LF) -> LF:
    """Toom-style; all SIX Fq2 products in one stacked fq2_mul."""
    a0, a1, a2 = (_part(a, i, 2) for i in range(3))
    b0, b1, b2 = (_part(b, i, 2) for i in range(3))
    lhs = _lane_stack([a0, a1, a2, lz.add(a1, a2), lz.add(a0, a1), lz.add(a0, a2)])
    rhs = _lane_stack([b0, b1, b2, lz.add(b1, b2), lz.add(b0, b1), lz.add(b0, b2)])
    t0, t1, t2, u12, u01, u02 = _unstack(fq2_mul(lhs, rhs), 6)
    c0 = lz.add(t0, fq2_mul_xi(lz.sub(lz.sub(u12, t1), t2)))
    c1 = lz.add(lz.sub(lz.sub(u01, t0), t1), fq2_mul_xi(t2))
    c2 = lz.add(lz.sub(lz.sub(u02, t0), t2), t1)
    return _stack([c0, c1, c2], axis=-3)


def fq6_sqr(a: LF) -> LF:
    return fq6_mul(a, a)


def fq6_mul_v(a: LF) -> LF:
    """Multiply by v: (c0, c1, c2) -> (c2*xi, c0, c1)."""
    a0, a1, a2 = (_part(a, i, 2) for i in range(3))
    return _stack([fq2_mul_xi(a2), a0, a1], axis=-3)


def fq6_inv(a: LF) -> LF:
    av, b, c = (_part(a, i, 2) for i in range(3))
    sq_av, sq_c, sq_b, bc, avb, avc = _unstack(
        fq2_mul(_lane_stack([av, c, b, b, av, av]), _lane_stack([av, c, b, c, b, c])),
        6,
    )
    t0 = lz.sub(sq_av, fq2_mul_xi(bc))
    t1 = lz.sub(fq2_mul_xi(sq_c), avb)
    t2 = lz.sub(sq_b, avc)
    d0, d1, d2 = _unstack(
        fq2_mul(_lane_stack([av, c, b]), _lane_stack([t0, t1, t2])), 3
    )
    denom = fq2_inv(lz.add(d0, fq2_mul_xi(lz.add(d1, d2))))
    r0, r1, r2 = _unstack(
        fq2_mul(_lane_stack([t0, t1, t2]), _lane_stack([denom, denom, denom])), 3
    )
    return _stack([r0, r1, r2], axis=-3)


# ------------------------------------------------------------------ Fq12 --


def fq12_add(a: LF, b: LF) -> LF:
    return lz.add(a, b)


def fq12_mul(a: LF, b: LF) -> LF:
    """Karatsuba over Fq6 halves; ONE mont instance (54 lanes) total."""
    a0, a1 = _part(a, 0, 3), _part(a, 1, 3)
    b0, b1 = _part(b, 0, 3), _part(b, 1, 3)
    lhs = _lane_stack([a0, a1, lz.add(a0, a1)])
    rhs = _lane_stack([b0, b1, lz.add(b0, b1)])
    t0, t1, full = _unstack(fq6_mul(lhs, rhs), 3)
    cross = lz.sub(lz.sub(full, t0), t1)
    return _stack([lz.add(t0, fq6_mul_v(t1)), cross], axis=-4)


def fq12_sqr(a: LF) -> LF:
    return fq12_mul(a, a)


def fq12_conj(a: LF) -> LF:
    a0, a1 = _part(a, 0, 3), _part(a, 1, 3)
    return _stack([a0, fq6_neg(a1)], axis=-4)


def fq12_inv(a: LF) -> LF:
    a0, a1 = _part(a, 0, 3), _part(a, 1, 3)
    s0, s1 = _unstack(
        fq6_mul(_lane_stack([a0, a1]), _lane_stack([a0, a1])), 2
    )
    t = fq6_inv(lz.sub(s0, fq6_mul_v(s1)))
    r0, r1 = _unstack(
        fq6_mul(_lane_stack([a0, a1]), _lane_stack([t, t])), 2
    )
    return _stack([r0, fq6_neg(r1)], axis=-4)


_FQ12_ONE = fq12_to_limbs(Fq12.one())


def fq12_one(batch_shape: tuple[int, ...] = ()) -> LF:
    one = jnp.asarray(np.asarray(_FQ12_ONE, np.uint64))
    return lf(jnp.broadcast_to(one, (*batch_shape, *one.shape)), val=P_INT)


def fq12_is_one(a: LF):
    """True iff the element equals 1 mod p (redundant range handled)."""
    one = fq12_one(a.v.shape[: a.v.ndim - 4])
    diff = lz.sub(a if a.val <= 2 * P_INT else lz.shrink(a), one)
    red = lz.shrink(diff)
    flat_zero = lz.is_zero(red)  # [..., 2, 3, 2] per-Fq verdicts
    return jnp.all(flat_zero, axis=(-3, -2, -1))


# coefficient view: f = sum a_i w^i, a_i = f[half=i%2, v=i//2] (fields.py
# Fq12.coeffs ordering)
def _coeff(a: LF, i: int) -> LF:
    return LF(a.v[..., i % 2, i // 2, :, :], a.max, a.val)


def _from_coeffs(cs: list[LF]) -> LF:
    c0 = _stack([cs[0], cs[2], cs[4]], axis=-3)
    c1 = _stack([cs[1], cs[3], cs[5]], axis=-3)
    return _stack([c0, c1], axis=-4)


_FROB1_G = np.stack([fq2_to_limbs(XI.pow(i * (P_INT - 1) // 6)) for i in range(6)])
_FROB2_G = np.stack(
    [fq2_to_limbs(XI.pow(i * (P_INT * P_INT - 1) // 6)) for i in range(6)]
)


def _stacked_gammas(g: np.ndarray, like: LF) -> LF:
    """[6, 2, 15] constants broadcast against [6, *batch, 2, 15]."""
    n_batch = like.v.ndim - 3
    shaped = jnp.asarray(g).reshape(6, *(1,) * n_batch, 2, N_LIMBS)
    return LF(jnp.broadcast_to(shaped, like.v.shape), lz.NORM_MAX, P_INT - 1)


def fq12_frobenius(a: LF) -> LF:
    """f -> f^p: conjugate each Fq2 coefficient, times gamma1_i — six
    products in one stacked fq2_mul instance."""
    coeffs = _lane_stack([_coeff(a, i) for i in range(6)])
    out = fq2_mul(fq2_conj(coeffs), _stacked_gammas(_FROB1_G, coeffs))
    return _from_coeffs(_unstack(out, 6))


def fq12_frobenius2(a: LF) -> LF:
    """f -> f^(p^2) (gamma2_i lie in Fq: no conjugation)."""
    coeffs = _lane_stack([_coeff(a, i) for i in range(6)])
    out = fq2_mul(coeffs, _stacked_gammas(_FROB2_G, coeffs))
    return _from_coeffs(_unstack(out, 6))


# ------------------------------------------------------------- exponents --

_BLS_X_ABS_BITS = _bits_msb_first(-BLS_X)


def _norm12(a: LF) -> LF:
    """Normalize an Fq12 for a scan carry (limbs < 2^26, value < 2p)."""
    return lz.shrink(a) if a.val > 2 * P_INT - 1 else lz.norm(a)


def _fq12_pow_bits(a: LF, bits: np.ndarray) -> LF:
    """Shared square-and-multiply scan over fixed MSB-first bits. The scan
    carry is a NORMALIZED array (limbs < 2^26, value < 2p) so the static
    bounds are identical on every iteration."""
    a = _norm12(a)
    xs = jnp.asarray(bits[1:])  # leading 1: start from acc = a

    def step(acc_v, bit):
        acc = lf(acc_v)
        sq = fq12_sqr(acc)
        withm = fq12_mul(sq, lf(a.v))
        sel = LF(
            jnp.where(bit != 0, withm.v, sq.v),
            max(withm.max, sq.max),
            max(withm.val, sq.val),
        )
        return _norm12(sel).v, None

    out, _ = lax.scan(step, a.v, xs)
    return lf(out)


def fq12_powx(a: LF) -> LF:
    """a^x for the (negative) BLS parameter x — |x|-bit pow then
    conjugate (valid in the cyclotomic subgroup where inversion is
    conjugation; mirrors native/bls12_381.c:1098)."""
    return fq12_conj(_fq12_pow_bits(a, _BLS_X_ABS_BITS))


def fq12_pow_const(a: LF, e: int) -> LF:
    """a^e for a fixed public exponent (exact final-exp hard part)."""
    return _fq12_pow_bits(a, _bits_msb_first(e))
