"""The whole-slot state-transition pipeline — five benches as ONE op.

The paper's headline scenario (SNIPPETS.md header; ROADMAP item 3) is
``state_transition`` over a full registry served as ONE request, yet
every ingredient landed in isolation: resident state + incremental
root (PR 11/16), committee aggregation (PR 13), batched BLS + KZG
verification (PRs 8/14). This module composes them. One
:class:`SlotRequest` carries a block's attestations (ragged
committees), its sync aggregate, and its blob sidecars; the device
pipeline chains

  * **slot.verify** — every attestation's claimed aggregate signature,
    the sync aggregate, and every blob's KZG proof through the batched
    RLC paths (``ops/bls_batch.verify_many`` — ONE pairing for an
    all-valid slot, bisection isolates the invalid — and
    ``ops/kzg_batch.verify_many_blobs``);
  * **slot.aggregate** — the VALID attestations' signatures folded into
    per-subnet aggregates through the ONE batched G2 many-sum
    (``ops/g2_aggregate.sum_g2_many_device``, the PR 13 kernel, same
    live ``g2_agg`` compile key);
  * **column update + slot.reroot** — the participation/balance
    scatter (the 14th kernel family, ``slot_apply``) fused with the
    incremental state re-root against the RESIDENT forest
    (``ops/state_root.post_epoch_state_root_inc`` — the forest is
    DONATED in place, the PR 11/16 lifecycle); an epoch-boundary slot
    additionally runs one accounting epoch through
    ``parallel/resident.run_epochs(with_root="state_inc")``.

Every leg is bit-identical to the sequential host fold of the same
ops (:func:`host_slot_fold`) — the parity gate every tier and the
slot-machine bench (scripts/slot_bench.py) REFUSE to violate.

Semantics (honest about what the resident world models): a VALID
attestation sets its participating members' previous-epoch
participation flags (source|target|head) and the TIMELY_TARGET column
the epoch accounting reads; a VALID sync aggregate credits each sync
participant a fixed ``ETH_SPECS_SLOT_SYNC_REWARD`` gwei (the per-slot
balance mutation — process_sync_aggregate's shape). The state root
follows the resident convention (parallel/resident.py): balances /
effective balances / inactivity scores re-root incrementally; the
participation LIST root in the forest is the static stand-in, so flag
writes update the accounting columns but not the root — the same
documented caveat the resident loop carries.

Invalid inputs degrade the ITEM, never the slot: a bad attestation is
a ``False`` verdict excluded from aggregation and participation; a bad
blob is a ``False`` verdict; the rest of the slot lands normally.

Fault sites (fault/sites.py): ``slot.verify`` fires before any state
read, ``slot.reroot`` before the donating dispatch — both BEFORE any
mutation of the committed carry, so the degrade ladder (serve/slot.py)
re-runs the WHOLE slot as the host fold from the pre-slot columns and
commits all-or-nothing; a half-applied slot is unrepresentable.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache, partial

import numpy as np

from eth_consensus_specs_tpu import obs

# altair participation bits: TIMELY_SOURCE | TIMELY_TARGET | TIMELY_HEAD
FLAG_MASK = 0b111


def sync_reward_gwei() -> int:
    """Per-participant balance credit of a valid sync aggregate (the
    slot-level balance mutation; deterministic, env-snapshotted)."""
    raw = os.environ.get("ETH_SPECS_SLOT_SYNC_REWARD", "")
    try:
        return max(int(raw), 0) if raw else 1024
    except ValueError:
        return 1024


# ------------------------------------------------------------ wire types --


@dataclass(frozen=True)
class SlotAttestation:
    """One aggregated attestation as a block carries it: the claimed
    aggregate signature over the participating committee members."""

    subnet: int
    root: bytes  # attestation data root — the signed message
    committee: tuple  # validator indices of the FULL committee
    bits: tuple  # participation bits over the full committee
    pubkeys: tuple  # participating members' compressed pubkeys (48B)
    sig: bytes  # claimed aggregate signature (96B)


@dataclass(frozen=True)
class SlotRequest:
    """Everything one block submits: attestations, sync aggregate, blob
    sidecars. ``slot`` is the idempotency key — a retried slot that
    already committed replays its recorded verdicts instead of
    double-applying. ``epoch_boundary`` marks the slot that closes an
    epoch (one resident accounting epoch runs after the column
    updates). Plain data end to end — pickles across the replica wire
    unchanged."""

    slot: int
    attestations: tuple = ()
    sync_pubkeys: tuple = ()  # compressed pubkeys of sync participants
    sync_message: bytes = b""
    sync_sig: bytes = b""
    sync_indices: tuple = ()  # validator indices credited when valid
    blobs: tuple = ()  # (blob, commitment, proof) byte triples
    epoch_boundary: bool = False


@dataclass(frozen=True)
class SlotResult:
    """What ``submit_slot`` resolves to: the verdicts, the aggregation
    leg's per-subnet aggregates, and the canonical post-slot state root
    — every field bit-comparable against the sequential host fold."""

    slot: int
    att_verdicts: tuple  # bool per attestation
    sync_verdict: bool
    blob_verdicts: tuple  # bool per blob sidecar
    subnet_aggregates: tuple  # ((subnet, 96B aggregate sig) ...) valid atts
    state_root: bytes  # canonical combined root AFTER this slot
    epoch: int  # accounting epoch after this slot
    replayed: bool = False  # True: idempotent replay of a committed slot


@dataclass
class SlotPrep:
    """Host prep of one slot request (service ``_prep`` — overlapped
    with the previous flush's device work): decompressed signature
    points for the aggregation leg and parsed blob items for the KZG
    leg. Pure host work, no device touch."""

    sig_points: tuple = ()  # G2 Point | None per attestation
    blob_parsed: tuple = ()  # kzg_batch.parse_item output per blob


def prep_request(req: SlotRequest) -> SlotPrep:
    """Decompress/parse everything the device legs will need — the
    per-slot fixed host cost, paid off the dispatch thread."""
    from eth_consensus_specs_tpu.crypto.signature import _load_pk, _load_sig
    from eth_consensus_specs_tpu.ops.kzg_batch import parse_item

    for att in req.attestations:
        for pk in att.pubkeys:
            _load_pk(pk)  # warms the bounded decompression cache
    for pk in req.sync_pubkeys:
        _load_pk(pk)
    sig_points = tuple(_load_sig(att.sig) for att in req.attestations)
    blob_parsed = tuple(parse_item(b) for b in req.blobs)
    return SlotPrep(sig_points=sig_points, blob_parsed=blob_parsed)


# -------------------------------------------------------- update planning --


def request_capacity(req: SlotRequest) -> tuple[int, int]:
    """(flag capacity, reward capacity) of a request BEFORE any verdict
    exists: every set committee bit and every sync index, valid or not.
    The compile key buckets THIS — a shape derivable from the request
    alone, so the front door's router and the dispatch can never
    disagree — and invalid items simply leave no-op pad lanes."""
    flags = sum(1 for att in req.attestations for bit in att.bits if bit)
    return flags, len(req.sync_indices)


def plan_updates(
    req: SlotRequest, att_verdicts: list, sync_verdict: bool, n_validators: int
):
    """The deterministic scatter plan both legs share: which validators
    get participation flags and which get balance credits, from the
    VALID items only. Returns (flag_idx i32[], reward_idx i32[],
    reward_amt u64[]) — unpadded; the dispatch pads to the bucketed
    kernel shape. Out-of-range indices are dropped (a malformed request
    must not scatter outside the registry)."""
    flag_idx: list[int] = []
    for att, ok in zip(req.attestations, att_verdicts):
        if not ok:
            continue
        for vi, bit in zip(att.committee, att.bits):
            if bit and 0 <= int(vi) < n_validators:
                flag_idx.append(int(vi))
    reward_idx: list[int] = []
    if sync_verdict:
        reward = sync_reward_gwei()
        for vi in req.sync_indices:
            if 0 <= int(vi) < n_validators and reward > 0:
                reward_idx.append(int(vi))
    return (
        np.asarray(flag_idx, np.int32),
        np.asarray(reward_idx, np.int32),
        np.full(len(reward_idx), sync_reward_gwei(), np.uint64),
    )


# ------------------------------------------------------- the fused kernel --


@lru_cache(maxsize=None)
def _compiled_slot_apply(meta, plan, mesh, p_flags: int, p_rewards: int):
    """One executable per (registry shape, forest plan, mesh, padded
    update counts) — the 14th kernel family. The forest is DONATED:
    the slot chain updates the resident tree levels in place, exactly
    the run_epochs lifecycle (jaxlint's donation-audit proves the
    alias on the registered entry)."""
    import jax
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops.state_root import post_epoch_state_root_inc

    @partial(jax.jit, donate_argnums=(1,))
    def run(
        arrays,
        forest,
        balance,
        effective_balance,
        inactivity_scores,
        prev_flags,
        cur_tgt_att,
        just,
        flag_idx,
        flag_on,
        reward_idx,
        reward_amt,
    ):
        # duplicate-safe participation scatter: a dense hit COUNT via
        # scatter-add (pad lanes carry index 0 with flag_on=0 — no-op;
        # u32 lane bounds the count at the padded lane count), then OR
        # the flag mask where hit > 0 — a validator two committees both
        # include gets the same flags either way.
        hit = (
            jnp.zeros(balance.shape[0], jnp.uint32)
            .at[flag_idx]
            .add(flag_on.astype(jnp.uint32))
        )
        new_flags = jnp.where(hit > 0, prev_flags | jnp.uint8(FLAG_MASK), prev_flags)
        new_tgt = cur_tgt_att | (hit > 0)
        # sync rewards legitimately accumulate across duplicate lanes
        # (pad lanes add 0 at index 0)
        new_balance = balance.at[reward_idx].add(reward_amt)
        forest, root = post_epoch_state_root_inc(
            arrays,
            meta,
            plan,
            forest,
            balance,
            effective_balance,
            inactivity_scores,
            new_balance,
            effective_balance,
            inactivity_scores,
            just,
            mesh=mesh,
        )
        return new_balance, new_flags, new_tgt, forest, root

    return run


def slot_apply_device(
    static,
    plan,
    forest,
    cols,
    just,
    flag_idx,
    reward_idx,
    reward_amt,
    mesh=None,
    cap_flags: int | None = None,
    cap_rewards: int | None = None,
):
    """Apply one slot's scatter plan and incrementally re-root: ONE
    donated dispatch. Returns (new_cols, new_forest, root_bytes).
    Compile-keyed by the LIVE ``serve/buckets.slot_key`` fn — pad
    shapes come from the key, so the dispatch and the analyzer always
    agree on the recompile surface. ``cap_flags``/``cap_rewards`` are
    the request-derived capacities (:func:`request_capacity`): bucketing
    the capacity instead of the post-verdict count keeps the key a pure
    function of the request shape."""
    import jax
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.obs import devprof
    from eth_consensus_specs_tpu.serve import buckets

    arrays, meta = static
    n = int(cols.balance.shape[0])
    key = buckets.slot_key(
        n,
        max(cap_flags if cap_flags is not None else len(flag_idx), len(flag_idx)),
        max(
            cap_rewards if cap_rewards is not None else len(reward_idx),
            len(reward_idx),
        ),
        plan,
        mesh=mesh,
    )
    p_flags, p_rewards = key[2], key[3]
    f_idx = np.zeros(p_flags, np.int32)
    f_on = np.zeros(p_flags, np.uint8)
    f_idx[: len(flag_idx)] = flag_idx
    f_on[: len(flag_idx)] = 1
    r_idx = np.zeros(p_rewards, np.int32)
    r_amt = np.zeros(p_rewards, np.uint64)
    r_idx[: len(reward_idx)] = reward_idx
    r_amt[: len(reward_amt)] = reward_amt
    run = _compiled_slot_apply(meta, plan, mesh, p_flags, p_rewards)
    work = 2 * sum(
        int(a.nbytes) for a in (cols.balance, cols.prev_flags, cols.cur_tgt_att)
    )
    with buckets.first_dispatch(*key):
        with devprof.measure("slot_apply", work_bytes=work):
            new_balance, new_flags, new_tgt, forest, root = run(
                jax.device_put(arrays),
                forest,
                cols.balance,
                cols.effective_balance,
                cols.inactivity_scores,
                cols.prev_flags,
                cols.cur_tgt_att,
                just,
                jnp.asarray(f_idx),
                jnp.asarray(f_on),
                jnp.asarray(r_idx),
                jnp.asarray(r_amt),
            )
    new_cols = cols._replace(
        balance=new_balance, prev_flags=new_flags, cur_tgt_att=new_tgt
    )
    return new_cols, forest, _root_bytes(root)


def _root_bytes(words) -> bytes:
    """u32[8] root words -> the canonical 32 big-endian bytes (the same
    encoding ops/snapshot.state_root_bytes commits to manifests)."""
    return np.asarray(words, np.uint32).astype(">u4").tobytes()


# -------------------------------------------------------- host twin legs --


def host_verify(req: SlotRequest) -> tuple[list, bool, list]:
    """The verify leg's host oracle: per-item
    ``crypto.signature.fast_aggregate_verify`` +
    ``ops.kzg_batch.verify_blob_host`` — exactly what the batched
    device paths are test-pinned against."""
    from eth_consensus_specs_tpu.crypto.signature import fast_aggregate_verify
    from eth_consensus_specs_tpu.ops.kzg_batch import verify_blob_host

    att = [
        bool(fast_aggregate_verify(list(a.pubkeys), a.root, a.sig))
        for a in req.attestations
    ]
    sync = bool(req.sync_pubkeys) and bool(
        fast_aggregate_verify(list(req.sync_pubkeys), req.sync_message, req.sync_sig)
    )
    blobs = [bool(verify_blob_host(*b)) for b in req.blobs]
    return att, sync, blobs


def device_verify(req: SlotRequest, prep: SlotPrep | None, mesh=None):
    """The verify leg on device: ONE RLC-batched BLS pass over every
    attestation + the sync aggregate (bisection isolates invalid
    items) and ONE batched KZG pass over the blob sidecars."""
    from eth_consensus_specs_tpu.ops.bls_batch import verify_many
    from eth_consensus_specs_tpu.ops.kzg_batch import verify_many_blobs

    items = [(list(a.pubkeys), a.root, a.sig) for a in req.attestations]
    n_att = len(items)
    if req.sync_pubkeys:
        items.append((list(req.sync_pubkeys), req.sync_message, req.sync_sig))
    verdicts = verify_many(items, mesh=mesh) if items else []
    att = [bool(v) for v in verdicts[:n_att]]
    sync = bool(verdicts[n_att]) if req.sync_pubkeys else False
    blobs = []
    if req.blobs:
        parsed = list(prep.blob_parsed) if prep is not None else None
        blobs = [
            bool(v)
            for v in verify_many_blobs(list(req.blobs), mesh=mesh, parsed=parsed)
        ]
    return att, sync, blobs


def _valid_by_subnet(req: SlotRequest, att_verdicts) -> list[tuple[int, list[int]]]:
    """(subnet, [attestation index...]) groups of the VALID attestations,
    subnet-sorted — the deterministic aggregation order both legs share."""
    groups: dict[int, list[int]] = {}
    for i, (att, ok) in enumerate(zip(req.attestations, att_verdicts)):
        if ok:
            groups.setdefault(int(att.subnet), []).append(i)
    return sorted(groups.items())


def host_aggregate(req: SlotRequest, att_verdicts) -> tuple:
    """The aggregation leg's host oracle: the ``crypto/signature``
    fold of each subnet's valid aggregate signatures."""
    from eth_consensus_specs_tpu.crypto.signature import aggregate

    out = []
    for subnet, idxs in _valid_by_subnet(req, att_verdicts):
        out.append((subnet, aggregate([req.attestations[i].sig for i in idxs])))
    return tuple(out)


def device_aggregate(
    req: SlotRequest, att_verdicts, prep: SlotPrep | None, mesh=None
) -> tuple:
    """The aggregation leg on device: every subnet's valid signatures
    in ONE batched G2 many-sum dispatch (the PR 13 kernel, the same
    LIVE ``g2_agg`` compile key the serve tier buckets by)."""
    from eth_consensus_specs_tpu.crypto.curve import g2_to_bytes
    from eth_consensus_specs_tpu.crypto.signature import _load_sig
    from eth_consensus_specs_tpu.ops.g2_aggregate import sum_g2_many_device
    from eth_consensus_specs_tpu.serve import buckets

    groups = _valid_by_subnet(req, att_verdicts)
    if not groups:
        return ()
    pts = list(prep.sig_points) if prep is not None else None
    lists = []
    for _, idxs in groups:
        row = []
        for i in idxs:
            p = pts[i] if pts is not None else _load_sig(req.attestations[i].sig)
            if p is None:  # unreachable for a True verdict; belt and braces
                p = _load_sig(req.attestations[i].sig)
            row.append(p)
        lists.append(row)
    max_lanes = max(len(row) for row in lists)
    sharded = mesh is not None and buckets.route_wide(
        "agg", buckets.pow2_bucket(max_lanes), len(lists)
    )
    key = buckets.g2_agg_key(len(lists), max_lanes, mesh=mesh if sharded else None)
    with buckets.first_dispatch(*key):
        sums = sum_g2_many_device(
            lists, mesh=mesh if sharded else None, pad_shape=(key[1], key[2])
        )
    return tuple(
        (subnet, g2_to_bytes(p)) for (subnet, _), p in zip(groups, sums)
    )


def advance_epoch(spec, cols, just):
    """One accounting epoch, the resident convention: the altair fused
    kernel advances balances/scores/justification, the epoch counter
    increments — the exact ``_advance`` body
    ``parallel/resident.run_epochs`` chains (integer arithmetic:
    eager and jitted execution are bit-identical)."""
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops.altair_epoch import (
        AltairEpochParams,
        altair_epoch_accounting_impl,
    )

    params = AltairEpochParams.from_spec(spec)
    res = altair_epoch_accounting_impl(params, cols, just)
    cols = cols._replace(
        balance=res.balance,
        effective_balance=res.effective_balance,
        inactivity_scores=res.inactivity_scores,
    )
    just = just._replace(
        current_epoch=just.current_epoch + jnp.uint64(1),
        justification_bits=res.justification_bits,
        prev_justified_epoch=res.prev_justified_epoch,
        prev_justified_root=res.prev_justified_root,
        cur_justified_epoch=res.cur_justified_epoch,
        cur_justified_root=res.cur_justified_root,
        finalized_epoch=res.finalized_epoch,
        finalized_root=res.finalized_root,
    )
    return cols, just


def host_slot_fold(spec, static, cols, just, req: SlotRequest, epoch: int):
    """The WHOLE slot as a sequential host fold of the existing ops —
    the parity oracle every tier gates against and the degrade ladder's
    fallback. Verdicts via the per-item host oracles, aggregation via
    the ``crypto/signature`` fold, column updates as plain numpy
    scatters, the post-slot root via the full (non-incremental) host
    state-root oracle. Returns (SlotResult, new_cols, new_just) — the
    caller commits all-or-nothing."""
    import jax
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops.state_root import post_epoch_state_root_host

    arrays, meta = static
    att_v, sync_v, blob_v = host_verify(req)
    subnet_aggs = host_aggregate(req, att_v)

    host = jax.tree_util.tree_map(np.asarray, cols)
    n = int(host.balance.shape[0])
    flag_idx, reward_idx, reward_amt = plan_updates(req, att_v, sync_v, n)
    new_flags = host.prev_flags.copy()
    new_flags[flag_idx] |= FLAG_MASK
    new_tgt = host.cur_tgt_att.copy()
    new_tgt[flag_idx] = True
    new_balance = host.balance.copy()
    np.add.at(new_balance, reward_idx, reward_amt)
    new_cols = cols._replace(
        balance=jnp.asarray(new_balance),
        prev_flags=jnp.asarray(new_flags),
        cur_tgt_att=jnp.asarray(new_tgt),
    )
    new_just = just
    new_epoch = int(epoch)
    if req.epoch_boundary:
        new_cols, new_just = advance_epoch(spec, new_cols, new_just)
        new_epoch += 1
    root = _root_bytes(
        np.asarray(
            post_epoch_state_root_host(
                arrays,
                meta,
                np.asarray(new_cols.balance),
                np.asarray(new_cols.effective_balance),
                np.asarray(new_cols.inactivity_scores),
                jax.tree_util.tree_map(np.asarray, new_just),
            )
        )
    )
    result = SlotResult(
        slot=int(req.slot),
        att_verdicts=tuple(att_v),
        sync_verdict=bool(sync_v),
        blob_verdicts=tuple(blob_v),
        subnet_aggregates=subnet_aggs,
        state_root=root,
        epoch=new_epoch,
    )
    obs.count("slot.host_folds", 1)
    return result, new_cols, new_just


# --------------------------------------------------------------- metrics --


def count_slot(req: SlotRequest) -> None:
    obs.count("slot.slots", 1)
    obs.count("slot.attestations", len(req.attestations))
    obs.count("slot.blobs", len(req.blobs))
