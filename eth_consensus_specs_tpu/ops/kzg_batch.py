"""Device-batched KZG blob verification — the DAS workload's hot path.

An entire flush of (blob, commitment, proof) triples verifies through
exactly TWO device dispatches plus one pairing membership check:

  1. **Batched Lagrange -> monomial conversion** (``ops/fr_fft``): every
     blob polynomial of the flush rides ONE batched inverse FFT (the
     batch axis is blobs-per-flush, bucketed through the live
     ``serve/buckets.fr_fft_key``), and the challenge evaluation
     ``y_i = f_i(z_i)`` finishes as a host Horner walk over the
     coefficients. Exact modular arithmetic: the value equals the host
     oracle's barycentric ``evaluate_polynomial_in_evaluation_form``
     bit for bit, including challenges that land on a root of unity
     (no special case needed in coefficient form).
  2. **One RLC-combined G1 multi-MSM** (``ops/g1_msm.msm_many_kernel``):
     the spec's batch check needs two G1 points —
     ``A = sum r_i * proof_i`` and
     ``B = sum r_i * C_i + (-sum r_i y_i) * G + sum (z_i r_i) * proof_i``
     (the commitment-minus-y and proof-z lincombs folded into one MSM by
     linearity) — and both run as the two items of a single batched
     multi-MSM dispatch, lane-bucketed through the live
     ``serve/buckets.kzg_msm_key`` (2n+1 lanes for n blobs; the lane
     axis shards over the mesh past the crossover).
  3. **One pairing check** (``ops/pairing_device`` via the same routing
     policy the BLS batch uses): ``e(A, -tau G2) * e(B, G2) == 1`` —
     both G2 points are fixed setup points, so the prepared-coefficient
     cache makes the Miller input preparation free.

Verdict parity is a hard invariant: every verdict equals what
``crypto/kzg.py`` returns on the same inputs (the RLC singleton check is
deterministic — ``X^r == 1`` in a prime-order group with ``r != 0 mod
R`` iff ``X == 1`` — so bisection leaves equal per-blob direct calls),
and a sampled divergence watchdog recomputes one item per flush through
the pure host oracle.

Invalid items isolate through the same RLC bisection discipline
``ops/bls_batch.verify_many`` uses: one check settles an all-valid
flush; a reject bisects, recomputing only the Fiat-Shamir fold + MSM +
pairing per subset (the per-item FFT evaluations are computed ONCE).
"""

from __future__ import annotations

import os

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.crypto import kzg
from eth_consensus_specs_tpu.crypto.curve import g1_generator, g2_generator
from eth_consensus_specs_tpu.crypto.fields import R as BLS_MODULUS
from eth_consensus_specs_tpu.obs import watchdog

BYTES_PER_BLOB = kzg.BYTES_PER_BLOB
BYTES_PER_COMMITMENT = kzg.BYTES_PER_COMMITMENT
BYTES_PER_PROOF = kzg.BYTES_PER_PROOF
N_BLOB = kzg.FIELD_ELEMENTS_PER_BLOB


# ------------------------------------------------------------- parsing --


def parse_item(item: tuple[bytes, bytes, bytes]):
    """(blob, commitment, proof) -> (blob, commitment_bytes, C_point,
    polynomial, challenge, proof_bytes, proof_point) or None on ANY
    input the host oracle would reject with an assertion — the exact
    accept/reject surface of ``crypto/kzg.verify_blob_kzg_proof``, so
    per-item verdicts match :func:`verify_blob_host`."""
    blob, commitment_bytes, proof_bytes = item
    blob = bytes(blob)
    commitment_bytes = bytes(commitment_bytes)
    proof_bytes = bytes(proof_bytes)
    if (
        len(blob) != BYTES_PER_BLOB
        or len(commitment_bytes) != BYTES_PER_COMMITMENT
        or len(proof_bytes) != BYTES_PER_PROOF
    ):
        return None
    try:
        kzg.bytes_to_kzg_commitment(commitment_bytes)
        polynomial = kzg.blob_to_polynomial(blob)
        kzg.bytes_to_kzg_proof(proof_bytes)
    except AssertionError:
        return None
    challenge = kzg.compute_challenge(blob, commitment_bytes)
    return (
        blob,
        commitment_bytes,
        kzg._g1_point(commitment_bytes),
        polynomial,
        challenge,
        proof_bytes,
        kzg._g1_point(proof_bytes),
    )


def verify_blob_host(blob: bytes, commitment_bytes: bytes, proof_bytes: bytes) -> bool:
    """The per-item host oracle with the serve layer's verdict semantic:
    malformed inputs (wrong lengths, invalid G1 encodings, field
    elements >= the modulus) are ``False`` verdicts, not exceptions —
    exactly the items :func:`parse_item` rejects."""
    try:
        return bool(kzg.verify_blob_kzg_proof(bytes(blob), bytes(commitment_bytes),
                                              bytes(proof_bytes)))
    except AssertionError:
        return False


# ------------------------------------------------- challenge evaluation --


def _eval_coeffs(coeffs: list[int], z: int) -> int:
    """Horner over monomial coefficients — exact mod-R arithmetic, so it
    equals the barycentric host evaluation of the same polynomial."""
    y = 0
    for c in reversed(coeffs):
        y = (y * z + c) % BLS_MODULUS
    return y


def challenge_evaluations(parsed: list, mesh=None) -> list[int]:
    """``y_i = f_i(z_i)`` for every parsed item, with the Lagrange ->
    monomial conversion of the WHOLE flush in one batched device inverse
    FFT (``ETH_SPECS_KZG_HOST_EVAL=1`` forces the host barycentric path
    instead — bit-identical values, no device dispatch)."""
    if not parsed:
        return []
    if os.environ.get("ETH_SPECS_KZG_HOST_EVAL", "0") not in ("", "0"):
        return [
            kzg.evaluate_polynomial_in_evaluation_form(poly, z)
            for _, _, _, poly, z, _, _ in parsed
        ]
    from eth_consensus_specs_tpu.ops.fr_fft import batch_fft_field
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    # blobs carry brp(evaluation) order; natural-order rows IFFT to the
    # monomial coefficients (brp is an involution)
    rows = [kzg.bit_reversal_permutation(poly) for _, _, _, poly, z, _, _ in parsed]
    roots = kzg.compute_roots_of_unity(N_BLOB)
    shards = mesh_ops.shard_count(mesh)
    use_mesh = mesh if shards > 1 and len(rows) >= mesh_ops.min_items() else None
    key = buckets.fr_fft_key(len(rows), N_BLOB, mesh=use_mesh)
    obs.count("kzg.fft_rows", len(rows))
    with buckets.first_dispatch(*key):
        coeff_rows = batch_fft_field(
            rows, roots, inv=True, mesh=use_mesh, pad_batch=key[1]
        )
    return [
        _eval_coeffs(coeffs, z)
        for coeffs, (_, _, _, _, z, _, _) in zip(coeff_rows, parsed)
    ]


# ------------------------------------------------------------- RLC fold --


def _rlc_check(parsed: list, ys: list[int], mesh=None) -> bool:
    """One batch verdict for a subset: the spec's Fiat-Shamir RLC
    (``crypto/kzg.verify_kzg_proof_batch`` :412) with its three G1
    lincombs folded by linearity into the two items of ONE batched
    multi-MSM dispatch, then one pairing check."""
    from eth_consensus_specs_tpu.ops.bls_batch import _pairing_check_routed
    from eth_consensus_specs_tpu.ops.g1_msm import msm_g1_many_device
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    n = len(parsed)
    degree_poly = N_BLOB.to_bytes(8, kzg.KZG_ENDIANNESS)
    data = kzg.RANDOM_CHALLENGE_KZG_BATCH_DOMAIN + degree_poly + n.to_bytes(
        8, kzg.KZG_ENDIANNESS
    )
    for (_, commitment_bytes, _, _, z, proof_bytes, _), y in zip(parsed, ys):
        data += (
            commitment_bytes
            + kzg.bls_field_to_bytes(z)
            + kzg.bls_field_to_bytes(y)
            + proof_bytes
        )
    r_powers = kzg.compute_powers(kzg.hash_to_bls_field(data), n)

    proof_pts = [p for _, _, _, _, _, _, p in parsed]
    c_pts = [c for _, _, c, _, _, _, _ in parsed]
    zs = [z for _, _, _, _, z, _, _ in parsed]
    neg_ry = (-sum(rp * y for rp, y in zip(r_powers, ys))) % BLS_MODULUS
    a_lanes = (proof_pts, list(r_powers))
    b_lanes = (
        c_pts + proof_pts + [g1_generator()],
        list(r_powers)
        + [z * rp % BLS_MODULUS for z, rp in zip(zs, r_powers)]
        + [neg_ry],
    )

    shards = mesh_ops.shard_count(mesh)
    wide = shards > 1 and buckets.route_wide("kzg", buckets.kzg_lane_bucket(n, 1), n)
    use_mesh = mesh if wide else None
    key = buckets.kzg_msm_key(n, mesh=use_mesh)
    obs.count("kzg.batches", 1)
    with buckets.first_dispatch(*key):
        a_pt, b_pt = msm_g1_many_device(
            [a_lanes[0], b_lanes[0]], [a_lanes[1], b_lanes[1]],
            mesh=use_mesh, pad_shape=(2, key[1]),
        )
    setup = kzg.get_setup()
    return _pairing_check_routed(
        [(a_pt, -setup.g2_monomial[1]), (b_pt, g2_generator())], mesh=use_mesh
    )


def verify_blob_kzg_proof_batch_device(
    blobs, commitments_bytes, proofs_bytes, mesh=None
) -> bool:
    """Device twin of ``crypto/kzg.verify_blob_kzg_proof_batch``: same
    assertion surface for malformed inputs, bit-identical verdict for
    well-formed ones."""
    assert len(blobs) == len(commitments_bytes) == len(proofs_bytes)
    if not blobs:
        return True
    parsed = [
        parse_item(item) for item in zip(blobs, commitments_bytes, proofs_bytes)
    ]
    assert all(p is not None for p in parsed), "malformed blob/commitment/proof"
    with obs.span("kzg.verify_many", items=len(parsed)):
        obs.count("kzg.blobs_verified", len(parsed))
        ys = challenge_evaluations(parsed, mesh=mesh)
        return _rlc_check(parsed, ys, mesh=mesh)


# ------------------------------------------------------------ bisection --


def _bisect(parsed: list, ys: list[int], mesh=None) -> list[bool]:
    if _rlc_check(parsed, ys, mesh=mesh):
        return [True] * len(parsed)
    if len(parsed) == 1:
        obs.count("kzg.isolated_invalid", 1)
        return [False]
    mid = len(parsed) // 2
    return _bisect(parsed[:mid], ys[:mid], mesh=mesh) + _bisect(
        parsed[mid:], ys[mid:], mesh=mesh
    )


def verify_many_blobs(
    items: list[tuple[bytes, bytes, bytes]], mesh=None, parsed: list | None = None
) -> list[bool]:
    """Per-item verdicts for many (blob, commitment, proof) triples —
    the serving layer's batch entry point. Parsing and the per-item
    challenge evaluations are computed ONCE; one RLC check settles an
    all-valid flush, and a reject bisects with only the Fiat-Shamir fold
    + MSM + pairing per subset. Malformed items are ``False`` without
    poisoning the rest (the :func:`verify_blob_host` semantic).

    ``parsed`` lets the serve batch thread hand over work it already did
    off the dispatch thread (one entry per item, ``None`` for malformed
    ones — exactly :func:`parse_item`'s output)."""
    if not items:
        return []
    if parsed is None:
        parsed = [parse_item(it) for it in items]
    assert len(parsed) == len(items)
    out = [False] * len(items)
    live = [i for i, p in enumerate(parsed) if p is not None]
    if not live:
        return out
    with obs.span("kzg.verify_many", items=len(live)):
        obs.count("kzg.blobs_verified", len(live))
        sub = [parsed[i] for i in live]
        ys = challenge_evaluations(sub, mesh=mesh)
        for i, v in zip(live, _bisect(sub, ys, mesh=mesh)):
            out[i] = v
    # sampled device/host coupling (outside the span, like bls_batch):
    # one item's verdict must reproduce through the pure host oracle —
    # barycentric evaluation, Pippenger MSM, host pairing, no fr_fft
    if watchdog.should_check("kzg_batch"):
        k = live[watchdog.call_salt("kzg_batch") % len(live)]
        blob, commitment_bytes, _, _, _, proof_bytes, _ = parsed[k]
        host = verify_blob_host(blob, commitment_bytes, proof_bytes)
        watchdog.record(
            "kzg_batch", host == out[k],
            {"device": out[k], "host": host, "item": k},
        )
    return out
