"""Vectorized SHA-256 for SSZ merkleization.

The merkle workload is millions of *independent* 64-byte messages
(left||right child pairs), each hashed with the same fixed schedule: one
compression over the data block + one over the constant padding block. That
is a pure SIMD problem — no data-dependent control flow — so the kernel is
written with the 128 rounds fully UNROLLED over a batch axis: XLA fuses the
whole round chain into one VPU kernel that reads each message once from HBM
and writes each digest once (measured ~2.9 Ghash/s on v5e at 256k batch,
~3000x hashlib's per-node loop). A scan-based variant was tried first and
ran *slower than hashlib* on TPU because the carry round-tripped HBM every
round — unrolling is what makes this kernel a kernel.

Compile cost of the unrolled graph (~10s) is contained by dispatching in
FIXED tile sizes (two shapes process-wide), not per-batch-size buckets.

Replaces the reference's per-node `hashlib.sha256` C calls
(reference: tests/core/pyspec/eth2spec/utils/hash_function.py:8-9).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import watchdog, xprof

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_IV = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)

# Message-schedule words of the constant second block for a 64-byte message:
# 0x80 delimiter, zeros, bit-length 512 in the last word.
_PAD_BLOCK = np.zeros(16, dtype=np.uint32)
_PAD_BLOCK[0] = 0x80000000
_PAD_BLOCK[15] = 512


def _rotr(x, n: int):
    return (x >> n) | (x << (32 - n))


def _compress(state: list, w: list) -> list:
    """One SHA-256 compression, rounds unrolled.

    state: 8 uint32 arrays, w: 16 uint32 arrays, all sharing a batch shape.
    """
    ws = list(w)
    for t in range(16, 64):
        s0 = _rotr(ws[t - 15], 7) ^ _rotr(ws[t - 15], 18) ^ (ws[t - 15] >> 3)
        s1 = _rotr(ws[t - 2], 17) ^ _rotr(ws[t - 2], 19) ^ (ws[t - 2] >> 10)
        ws.append(ws[t - 16] + s0 + ws[t - 7] + s1)
    a, b, c, d, e, f, g, h = state
    for t in range(64):
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + jnp.uint32(_K[t]) + ws[t]
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + S0 + maj
    return [s + o for s, o in zip(state, [a, b, c, d, e, f, g, h])]


def _compress_scan(state8: jnp.ndarray, w16: jnp.ndarray) -> jnp.ndarray:
    """One compression as a lax.scan over the 64 rounds.

    state8: uint32[8, N], w16: uint32[16, N]. The rolling 16-word message-
    schedule window rides in the carry: W[t+16] = W[t] + s0(W[t+1]) +
    W[t+9] + s1(W[t+14]). Semantically identical to the unrolled form; the
    graph is ~100x smaller. XLA:CPU chokes for minutes on the unrolled
    graph, so this is the CPU (test/virtual-mesh) form — TPU keeps the
    unrolled one, where the fused round chain is the whole point.
    """

    def rnd(carry, k):
        a, b, c, d, e, f, g, h, win = carry
        wt = win[0]
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + k + wt
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        s0 = _rotr(win[1], 7) ^ _rotr(win[1], 18) ^ (win[1] >> 3)
        s1 = _rotr(win[14], 17) ^ _rotr(win[14], 19) ^ (win[14] >> 10)
        wnext = win[0] + s0 + win[9] + s1
        win = jnp.concatenate([win[1:], wnext[None]], axis=0)
        return (t1 + S0 + maj, a, b, c, d + t1, e, f, g, win), None

    init = tuple(state8[i] for i in range(8)) + (w16,)
    (a, b, c, d, e, f, g, h, _), _ = jax.lax.scan(rnd, init, jnp.asarray(_K))
    out = jnp.stack([a, b, c, d, e, f, g, h])
    return state8 + out


def sha256_pair_words_scan(words: jnp.ndarray) -> jnp.ndarray:
    """Scan-form batch hash: uint32[N, 16] -> uint32[N, 8]."""
    n = words.shape[0]
    state = jnp.broadcast_to(jnp.asarray(_IV)[:, None], (8, n))
    state = _compress_scan(state, words.T)
    pad = jnp.broadcast_to(jnp.asarray(_PAD_BLOCK)[:, None], (16, n))
    state = _compress_scan(state, pad)
    return state.T


def sha256_pair_words_unrolled(words: jnp.ndarray) -> jnp.ndarray:
    """Unrolled batch hash: uint32[N, 16] -> uint32[N, 8]."""
    n = words.shape[0]
    w = [words[:, i] for i in range(16)]
    state = [jnp.broadcast_to(jnp.uint32(_IV[i]), (n,)) for i in range(8)]
    state = _compress(state, w)
    pad = [jnp.broadcast_to(jnp.uint32(_PAD_BLOCK[i]), (n,)) for i in range(16)]
    state = _compress(state, pad)
    return jnp.stack(state, axis=-1)


def sha256_single_block(words: jnp.ndarray) -> jnp.ndarray:
    """Hash a batch of messages that fit one fully-padded block.

    words: uint32[N, 16] (padding already applied by the caller) ->
    uint32[N, 8]. One compression instead of sha256_pair_words' two —
    the shape of the shuffle's decision-bit hashes (33/37-byte messages,
    specs/phase0/beacon-chain.md:816-836)."""
    n = words.shape[0]
    if jax.default_backend() == "cpu":
        state = jnp.broadcast_to(jnp.asarray(_IV)[:, None], (8, n))
        return _compress_scan(state, words.T).T
    w = [words[:, i] for i in range(16)]
    state = [jnp.broadcast_to(jnp.uint32(_IV[i]), (n,)) for i in range(8)]
    return jnp.stack(_compress(state, w), axis=-1)


def sha256_pair_words(words: jnp.ndarray) -> jnp.ndarray:
    """Hash a batch of 64-byte messages given as big-endian words.

    words: uint32[N, 16] -> uint32[N, 8]. Jit-traceable (inline this into
    larger fused kernels; for standalone use go through sha256_tiled).
    Picks the graph shape per backend: fully unrolled rounds on
    accelerators (XLA fuses the whole chain; scan carries round-trip HBM),
    round-scan on CPU (the unrolled graph takes minutes in XLA:CPU).
    """
    if jax.default_backend() == "cpu":
        return sha256_pair_words_scan(words)
    return sha256_pair_words_unrolled(words)


_kernel = jax.jit(sha256_pair_words)

# Fixed dispatch tiles: exactly these shapes ever compile (one-time ~10s
# each on TPU). Large tile amortizes dispatch; small tile bounds padding
# waste on shallow tree levels.
TILES = (65536, 2048)


def sha256_tiled(pairs: jnp.ndarray) -> jnp.ndarray:
    """Hash M pairs on device. pairs: uint32[M, 16] -> uint32[M, 8].

    Host-side greedy tiling over the fixed shapes; data stays on device.
    """
    m = pairs.shape[0]
    used_tiles: set[int] = set()
    # 64B message read + 32B digest write per hash: the traffic the span's
    # roofline verdict is judged against
    with obs.span("sha256.tiled", work_bytes=96 * m, messages=m) as sp:
        outs = []
        dispatches = 0
        pos = 0
        while pos < m:
            rest = m - pos
            tile = next((t for t in TILES if rest >= t), None)
            if tile is None:
                tile = TILES[-1]
                pad = jnp.zeros((tile - rest, 16), dtype=jnp.uint32)
                outs.append(_kernel(jnp.concatenate([pairs[pos:], pad], axis=0))[:rest])
                pos = m
            else:
                outs.append(_kernel(pairs[pos : pos + tile]))
                pos += tile
            used_tiles.add(tile)
            dispatches += 1
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
        sp.result = out
    if xprof.enabled():
        # XLA-derived attribution once per tile shape: compile timing,
        # flops/bytes/memory gauges, and the bytes floor cross-check
        # against the same 96 B/hash model the span above declared
        for t in sorted(used_tiles):
            xprof.analyze(
                "sha256",
                _kernel,
                (jax.ShapeDtypeStruct((t, 16), jnp.uint32),),
                hand_bytes=96 * t,
                dims=(t,),
            )
    obs.count("sha256.compressions", 2 * m)  # data block + constant padding block
    obs.count("sha256.messages", m)
    obs.count("sha256.dispatches", dispatches)
    if watchdog.should_check("sha256"):
        watchdog.check_sha256_slice(pairs, out)
    return out


def sha256_64B_batch_np(pairs: np.ndarray) -> np.ndarray:
    """Host-convenience entry: uint8[N, 64] -> uint8[N, 32]."""
    n = pairs.shape[0]
    words = np.ascontiguousarray(pairs).view(">u4").astype(np.uint32).reshape(n, 16)
    digest_words = np.asarray(sha256_tiled(jnp.asarray(words)))
    return digest_words.astype(">u4", order="C").view(np.uint8).reshape(n, 32)


def sha256_oracle(msg: bytes) -> bytes:
    """Single-message oracle path through the kernel (64-byte messages only),
    for correctness tests against hashlib."""
    assert len(msg) == 64
    out = sha256_64B_batch_np(np.frombuffer(msg, dtype=np.uint8).reshape(1, 64))
    return out[0].tobytes()
