"""Parameterized fixed-limb modular arithmetic for the device.

Generalizes the proven Fq kernel structure (ops/field_limbs.py — see that
module's docstring for the no-dot-general / redundant-range rationale) to
any odd modulus: 30-bit limbs in uint64 lanes, Montgomery (SOS) multiply
with a lax.scan reduction, values kept in [0, 2p).  The BLS *scalar*
field instance (9x30-bit limbs for the 255-bit r) backs the DAS FFT
kernel (ops/fr_fft.py); Fq keeps its dedicated module.
"""

from __future__ import annotations

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp
from jax import lax

LIMB_BITS = 30
MASK = (1 << LIMB_BITS) - 1
U64 = jnp.uint64


class LimbField:
    """Montgomery limb arithmetic mod an odd `modulus` with the smallest
    limb count whose radix R = 2^(30*k) exceeds 4*modulus."""

    def __init__(self, modulus: int):
        assert modulus % 2 == 1
        n_limbs = (modulus.bit_length() + LIMB_BITS) // LIMB_BITS
        while (1 << (LIMB_BITS * n_limbs)) <= 4 * modulus:
            n_limbs += 1
        self.modulus = modulus
        self.n_limbs = n_limbs
        self.r_int = 1 << (LIMB_BITS * n_limbs)
        self.n0_inv = (-pow(modulus, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)
        self.p_limbs = self.int_to_limbs(modulus)
        self.p2_limbs = self.int_to_limbs(2 * modulus)
        self.one_mont = self.to_mont(1)

    # -- host conversions --------------------------------------------------

    def int_to_limbs(self, x: int) -> np.ndarray:
        out = np.zeros(self.n_limbs, np.uint64)
        for i in range(self.n_limbs):
            out[i] = x & MASK
            x >>= LIMB_BITS
        assert x == 0
        return out

    def limbs_to_int(self, arr) -> int:
        x = 0
        for i in reversed(range(self.n_limbs)):
            x = (x << LIMB_BITS) | int(arr[i])
        return x

    def to_mont(self, x: int) -> np.ndarray:
        return self.int_to_limbs((x * self.r_int) % self.modulus)

    def from_mont_int(self, limbs) -> int:
        raw = self.limbs_to_int(np.asarray(limbs))
        return (raw * pow(self.r_int, -1, self.modulus)) % self.modulus

    def ints_to_mont_batch(self, values) -> np.ndarray:
        """[...,] python ints -> [..., n_limbs] Montgomery u64 limbs."""
        flat = [self.to_mont(int(v) % self.modulus) for v in np.ravel(np.asarray(values, dtype=object))]
        out = np.stack(flat).reshape((*np.shape(values), self.n_limbs))
        return out

    def mont_batch_to_ints(self, limbs) -> list[int]:
        arr = np.asarray(limbs)
        flat = arr.reshape(-1, self.n_limbs)
        return [self.from_mont_int(row) for row in flat]

    # -- device ops (shape-generic over leading axes) ----------------------

    def _limb_product(self, a, b):
        partials = a[..., :, None] * b[..., None, :]
        batch_pad = [(0, 0)] * (partials.ndim - 2)
        out = None
        for i in range(self.n_limbs):
            row = jnp.pad(partials[..., i, :], batch_pad + [(i, self.n_limbs - 1 - i)])
            out = row if out is None else out + row
        return out

    @staticmethod
    def _carry_sweep(t):
        tT = jnp.moveaxis(t, -1, 0)

        def step(carry, col):
            cur = col + carry
            return cur >> jnp.uint64(LIMB_BITS), cur & jnp.uint64(MASK)

        carry, cols = lax.scan(step, jnp.zeros_like(tT[0]), tT)
        return jnp.moveaxis(cols, 0, -1), carry

    @staticmethod
    def _geq(a, b):
        aT = jnp.moveaxis(a, -1, 0)
        bT = jnp.moveaxis(b, -1, 0)

        def step(acc, ab):
            x, y = ab
            acc = jnp.where(x == y, acc, x > y)
            return acc, None

        acc, _ = lax.scan(step, jnp.ones_like(aT[0], dtype=bool), (aT, bT))
        return acc

    @staticmethod
    def _sub_limbs(a, b):
        aT = jnp.moveaxis(a, -1, 0)
        bT = jnp.moveaxis(b, -1, 0)

        def step(borrow, ab):
            x, y = ab
            cur = x - y - borrow
            under = cur >> jnp.uint64(63)
            return under, cur + (under << jnp.uint64(LIMB_BITS))

        _, cols = lax.scan(step, jnp.zeros_like(aT[0]), (aT, bT))
        return jnp.moveaxis(cols, 0, -1)

    def _cond_sub(self, t, bound_limbs):
        bound = jnp.asarray(bound_limbs)
        b = jnp.broadcast_to(bound, t.shape)
        need = self._geq(t, b)
        sub = self._sub_limbs(t, b)
        return jnp.where(need[..., None], sub, t)

    def mont_mul(self, a, b):
        """abR^-1 mod p for a, b in [0, 2p); result in [0, 2p)."""
        n = self.n_limbs
        mask = jnp.uint64(MASK)
        shift = jnp.uint64(LIMB_BITS)
        n0 = jnp.uint64(self.n0_inv)
        p_vec = jnp.asarray(self.p_limbs)

        prod = self._limb_product(a, b)
        t, carry = self._carry_sweep(prod)
        t = jnp.concatenate(
            [t, carry[..., None], jnp.zeros_like(carry)[..., None]], axis=-1
        )

        def red_step(t, i):
            ti = lax.dynamic_slice_in_dim(t, i, 1, axis=-1)[..., 0]
            m = ((ti & mask) * n0) & mask
            window = lax.dynamic_slice_in_dim(t, i, n, axis=-1)
            window = window + m[..., None] * p_vec
            t = lax.dynamic_update_slice_in_dim(t, window, i, axis=-1)
            pair = lax.dynamic_slice_in_dim(t, i, 2, axis=-1)
            folded = jnp.stack(
                [pair[..., 0] & mask, pair[..., 1] + (pair[..., 0] >> shift)], axis=-1
            )
            return lax.dynamic_update_slice_in_dim(t, folded, i, axis=-1), None

        t, _ = lax.scan(red_step, t, jnp.arange(n, dtype=jnp.int32))
        res, _carry = self._carry_sweep(t[..., n : 2 * n + 1])
        return res[..., :n]

    def add_mod(self, a, b):
        t, _carry = self._carry_sweep(a + b)
        return self._cond_sub(t, self.p2_limbs)

    def sub_mod(self, a, b):
        p2 = jnp.broadcast_to(jnp.asarray(self.p2_limbs), b.shape)
        t, _ = self._carry_sweep(a + self._sub_limbs(p2, b))
        return self._cond_sub(t, self.p2_limbs)
