"""Columnar per-slot BLOCK processing — the device path for a full epoch
of `state_transition` at registry scale (BASELINE config #4: 128
attestations/slot x 32 slots @ 1M validators, < 1 s).

What the reference does per block (and where):
  * process_attestation — committee bit-accumulation into participation
    flags + proposer-reward attribution
    (reference: specs/altair/beacon-chain.md:509-556 equivalent,
    specs/phase0/beacon-chain.md:1980-2006);
  * process_sync_aggregate — per-slot sync-committee rewards
    (specs/altair/beacon-chain.md:575-650);
  * process_deposit (existing-key top-up path,
    specs/phase0/beacon-chain.md:1852-1905);
  * get_expected_withdrawals / process_withdrawals — bounded circular
    sweep (specs/capella/beacon-chain.md:286-345).

TPU-first design: block bodies for a whole epoch are ingested ONCE into
fixed-shape index/bit/flag tensors (`BlockColumns`), then the epoch runs
as one jit — `lax.scan` over slots, an inner `lax.scan` over the slot's
attestations (the spec's "already set?" semantics make attestations
order-dependent WITHIN a block, so they form a scan, not a reduction;
every per-attestation step is itself fully vectorized over the committee
axis).  Gathers/scatters ride XLA's native dynamic-(update-)slice path;
no Python-level loop survives into the graph.

The per-slot dirty state root reuses ops/state_root.py subtrees: per
slot only balances + the two participation columns (+ the slot chunk)
move, so the validator-registry/scores/checkpoint subtree roots are
computed once per epoch and the slot root re-reduces just the dirty
columns and the ~32-chunk top combine.  Slot-cadence history vectors
(block_roots/state_roots/randao mixes/latest header) are modeled as
static top chunks — registry-scale hash work is the target here; their
13-hash incremental paths are noise at 1M validators.

Not modeled (rare-path, host/spec-level): proposer/attester slashings,
voluntary exits, BLS-to-execution changes, new-validator deposits
(registry growth changes array shapes — host ingest concern).  The
object path remains authoritative for those; tests/test_block_epoch.py
proves this kernel bit-exact against it for the dense plane.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (package import enables x64)
import jax
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu import fault, obs

U64 = jnp.uint64


@dataclass(frozen=True)
class BlockEpochParams:
    """Static (trace-time) preset constants."""

    slots_per_epoch: int
    effective_balance_increment: int
    base_reward_factor: int
    weights: tuple  # PARTICIPATION_FLAG_WEIGHTS (source, target, head)
    weight_denominator: int
    proposer_weight: int
    sync_reward_weight: int
    sync_committee_size: int
    max_effective_balance: int
    max_withdrawals_per_payload: int
    max_validators_per_withdrawals_sweep: int

    @classmethod
    def from_spec(cls, spec) -> "BlockEpochParams":
        return cls(
            slots_per_epoch=int(spec.SLOTS_PER_EPOCH),
            effective_balance_increment=int(spec.EFFECTIVE_BALANCE_INCREMENT),
            base_reward_factor=int(spec.BASE_REWARD_FACTOR),
            weights=tuple(int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS),
            weight_denominator=int(spec.WEIGHT_DENOMINATOR),
            proposer_weight=int(spec.PROPOSER_WEIGHT),
            sync_reward_weight=int(spec.SYNC_REWARD_WEIGHT),
            sync_committee_size=int(spec.SYNC_COMMITTEE_SIZE),
            max_effective_balance=int(spec.MAX_EFFECTIVE_BALANCE),
            # pre-capella specs have no withdrawal sweep
            max_withdrawals_per_payload=int(
                getattr(spec, "MAX_WITHDRAWALS_PER_PAYLOAD", 0)
            ),
            max_validators_per_withdrawals_sweep=int(
                getattr(spec, "MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP", 0)
            ),
        )


class BlockColumns(NamedTuple):
    """One epoch of block bodies as fixed-shape tensors.  PAD convention:
    validator-index lanes use index n (one past the registry) for absent
    entries; whole absent attestations/deposits have flags/amount 0."""

    att_idx: jnp.ndarray  # u32[S, A, C] committee member validator indices
    att_bits: jnp.ndarray  # bool[S, A, C] aggregation bits
    att_flags: jnp.ndarray  # u8[S, A] participation flag bits conferred
    att_is_current: jnp.ndarray  # bool[S, A] target epoch == current epoch
    # True on the LAST row of an on-chain attestation: electra aggregates
    # expand into one row per committee, and the spec divides the
    # proposer-reward numerator ONCE per attestation — rows accumulate
    # until the pay boundary (pre-electra: every row pays)
    att_pay: jnp.ndarray  # bool[S, A]
    proposer: jnp.ndarray  # u32[S]
    sync_idx: jnp.ndarray  # u32[S, SYNC] sync-committee validator indices
    sync_bits: jnp.ndarray  # bool[S, SYNC]
    dep_idx: jnp.ndarray  # u32[S, D] deposit target (existing validator)
    dep_amt: jnp.ndarray  # u64[S, D]


class BlockState(NamedTuple):
    """The dense mutable plane threaded through the slot scan."""

    balance: jnp.ndarray  # u64[N]
    cur_part: jnp.ndarray  # u8[N] current_epoch_participation
    prev_part: jnp.ndarray  # u8[N] previous_epoch_participation
    next_wd_index: jnp.ndarray  # u64 scalar
    next_wd_validator: jnp.ndarray  # u64 scalar


def base_reward_per_validator(params: BlockEpochParams, effective_balance, total_active):
    """get_base_reward as a column (specs/altair/beacon-chain.md:388-397):
    increments * (increment * factor // isqrt(total_active_balance))."""
    from eth_consensus_specs_tpu.ops.state_columns import isqrt_u64

    per_increment = (
        U64(params.effective_balance_increment) * U64(params.base_reward_factor)
    ) // isqrt_u64(total_active)
    return (effective_balance // U64(params.effective_balance_increment)) * per_increment


def sync_rewards(params: BlockEpochParams, total_active):
    """(participant_reward, proposer_reward) scalars for the epoch
    (specs/altair/beacon-chain.md:591-605)."""
    from eth_consensus_specs_tpu.ops.state_columns import isqrt_u64

    total_increments = total_active // U64(params.effective_balance_increment)
    per_increment = (
        U64(params.effective_balance_increment) * U64(params.base_reward_factor)
    ) // isqrt_u64(total_active)
    total_base_rewards = per_increment * total_increments
    max_participant_rewards = (
        total_base_rewards
        * U64(params.sync_reward_weight)
        // U64(params.weight_denominator)
        // U64(params.slots_per_epoch)
    )
    participant_reward = max_participant_rewards // U64(params.sync_committee_size)
    proposer_reward = (
        participant_reward
        * U64(params.proposer_weight)
        // U64(params.weight_denominator - params.proposer_weight)
    )
    return participant_reward, proposer_reward


def _apply_attestation(params, n, base_reward, part, balance, proposer, att, carry_num):
    """One attestation row against one participation column: set
    newly-earned flags for attesting committee members, accumulate the
    proposer-reward numerator, and pay (one floor division per on-chain
    attestation) at the row group's pay boundary.  Committee indices are
    unique within a row, so the scatter is write-once; pad lanes
    (idx == n) add zero."""
    idx, bits, flags, pay = att
    safe = jnp.minimum(idx, jnp.uint32(n - 1))
    live = (idx < jnp.uint32(n)) & bits & (flags != jnp.uint8(0))
    pre = part[safe]
    new_bits = jnp.where(live, flags & ~pre, jnp.uint8(0))
    # scatter-ADD, not set: pad lanes alias index n-1, and duplicate-index
    # scatter-set order is unspecified — adds commute, pad lanes add 0,
    # and new_bits is disjoint from pre so add == bitwise-or here
    part = part.at[safe].add(new_bits)
    weight_sum = jnp.zeros_like(new_bits, dtype=U64)
    for b, w in enumerate(params.weights):
        weight_sum = weight_sum + jnp.where(
            (new_bits >> b) & 1, U64(w), U64(0)
        )
    carry_num = carry_num + jnp.sum(weight_sum * base_reward[safe])
    denominator = U64(
        (params.weight_denominator - params.proposer_weight)
        * params.weight_denominator
        // params.proposer_weight
    )
    balance = balance.at[proposer].add(jnp.where(pay, carry_num // denominator, U64(0)))
    carry_num = jnp.where(pay, U64(0), carry_num)
    return part, balance, carry_num


def _apply_sync(params, st: BlockState, proposer, sync_idx, sync_bits, part_r, prop_r, n):
    """process_sync_aggregate balance plane in EXACT spec order: a scan
    over committee positions (increase participant + proposer per set
    bit, clamped decrease per unset bit).  Sync committees sample WITH
    replacement and decrease_balance clamps per OPERATION, so the
    position walk is genuinely sequential for a validator whose balance
    can cross zero mid-committee — a summed-then-clamped shortcut
    diverges there.  512 scan steps/slot is noise against the slot's
    tree work."""

    def step(bal, x):
        i, bit = x
        cur = bal[i]
        dec = jnp.where(cur >= part_r, cur - part_r, U64(0))
        bal = bal.at[i].set(jnp.where(bit, cur + part_r, dec))
        bal = bal.at[proposer].add(jnp.where(bit, prop_r, U64(0)))
        return bal, None

    bal, _ = lax.scan(step, st.balance, (sync_idx, sync_bits))
    return st._replace(balance=bal)


def _apply_deposits(st: BlockState, dep_idx, dep_amt, n):
    safe = jnp.minimum(dep_idx, jnp.uint32(n - 1))
    amt = jnp.where(dep_idx < jnp.uint32(n), dep_amt, U64(0))
    return st._replace(balance=st.balance.at[safe].add(amt))


def _apply_withdrawals(
    params, st: BlockState, epoch, eff_balance, withdrawable_epoch, has_eth1_cred, n
):
    """The capella sweep as one vectorized window: gather `bound`
    validators from the circular pointer, rank the eligible ones by
    cumulative count, pay the first MAX_WITHDRAWALS, advance pointers by
    the spec's two rules (forks/capella.py:223-281)."""
    bound = min(n, params.max_validators_per_withdrawals_sweep)
    max_w = params.max_withdrawals_per_payload
    start = st.next_wd_validator
    window = ((start + jnp.arange(bound, dtype=U64)) % U64(n)).astype(jnp.uint32)
    bal = st.balance[window]
    full = has_eth1_cred[window] & (withdrawable_epoch[window] <= epoch) & (bal > U64(0))
    partial = (
        has_eth1_cred[window]
        & (eff_balance[window] == U64(params.max_effective_balance))
        & (bal > U64(params.max_effective_balance))
    )
    elig = full | partial
    rank = jnp.cumsum(elig.astype(jnp.uint32))
    take = elig & (rank <= jnp.uint32(max_w))
    amount = jnp.where(full, bal, bal - U64(params.max_effective_balance))
    new_bal = st.balance.at[window].set(jnp.where(take, bal - amount, bal))
    n_taken = jnp.minimum(rank[-1], jnp.uint32(max_w)).astype(U64)
    # pointer advance: full payload resumes after the last paid validator,
    # otherwise the whole sweep window is skipped
    positions = jnp.arange(bound, dtype=jnp.uint32)
    last_pos = jnp.max(jnp.where(take, positions, jnp.uint32(0)))
    full_payload = n_taken == U64(max_w)
    next_validator = jnp.where(
        full_payload,
        (start + last_pos.astype(U64) + U64(1)) % U64(n),
        (start + U64(params.max_validators_per_withdrawals_sweep)) % U64(n),
    )
    return st._replace(
        balance=new_bal,
        next_wd_index=st.next_wd_index + n_taken,
        next_wd_validator=next_validator,
    )


def process_slot_columnar(
    params: BlockEpochParams,
    n: int,
    st: BlockState,
    slot_blk,
    base_reward,
    eff_balance,
    withdrawable_epoch,
    has_eth1_cred,
    epoch,
    part_reward,
    prop_reward,
    with_withdrawals: bool = True,
) -> BlockState:
    """One slot's block against the dense plane, in spec order:
    withdrawals -> (randao/eth1: no dense effect) -> operations
    (attestations, deposits) -> sync aggregate."""
    (att_idx, att_bits, att_flags, att_is_current, att_pay, proposer, sync_idx,
     sync_bits, dep_idx, dep_amt) = slot_blk
    if with_withdrawals:
        st = _apply_withdrawals(
            params, st, epoch, eff_balance, withdrawable_epoch, has_eth1_cred, n
        )

    def att_step(carry, att):
        cur, prev, bal, num = carry
        idx, bits, flags, is_cur, pay = att

        def on_cur(args):
            cur, prev, bal, num = args
            cur, bal, num = _apply_attestation(
                params, n, base_reward, cur, bal, proposer, (idx, bits, flags, pay), num
            )
            return cur, prev, bal, num

        def on_prev(args):
            cur, prev, bal, num = args
            prev, bal, num = _apply_attestation(
                params, n, base_reward, prev, bal, proposer, (idx, bits, flags, pay), num
            )
            return cur, prev, bal, num

        return lax.cond(is_cur, on_cur, on_prev, (cur, prev, bal, num)), None

    (cur, prev, bal, _num), _ = lax.scan(
        att_step,
        (st.cur_part, st.prev_part, st.balance, U64(0)),
        (att_idx, att_bits, att_flags, att_is_current, att_pay),
    )
    st = st._replace(cur_part=cur, prev_part=prev, balance=bal)
    st = _apply_deposits(st, dep_idx, dep_amt, n)
    st = _apply_sync(params, st, proposer, sync_idx, sync_bits, part_reward, prop_reward, n)
    return st


# ----------------------------------------------------------- epoch chain --


class BlockEpochStatic(NamedTuple):
    """Per-epoch constants the slot scan closes over."""

    base_reward: jnp.ndarray  # u64[N]
    eff_balance: jnp.ndarray  # u64[N]
    withdrawable_epoch: jnp.ndarray  # u64[N]
    has_eth1_cred: jnp.ndarray  # bool[N]
    epoch: jnp.ndarray  # u64
    part_reward: jnp.ndarray  # u64
    prop_reward: jnp.ndarray  # u64


def make_epoch_static(params, eff_balance, withdrawable_epoch, has_eth1_cred, epoch):
    active = eff_balance  # bench model: all validators active
    total = jnp.maximum(
        jnp.sum(active), U64(params.effective_balance_increment)
    )
    part_r, prop_r = sync_rewards(params, total)
    return BlockEpochStatic(
        base_reward=base_reward_per_validator(params, eff_balance, total),
        eff_balance=eff_balance,
        withdrawable_epoch=withdrawable_epoch,
        has_eth1_cred=has_eth1_cred,
        epoch=jnp.asarray(epoch, U64),
        part_reward=part_r,
        prop_reward=prop_r,
    )


def block_epoch_chain(
    params: BlockEpochParams,
    n: int,
    st: BlockState,
    blocks: BlockColumns,
    static: BlockEpochStatic,
    root_ctx=None,
    with_withdrawals: bool = True,
):
    """Scan an epoch of blocks over the dense plane inside one jit.  With
    `root_ctx` (see `make_root_ctx`) each slot also recomputes the dirty
    state-root subtrees (balances + both participation columns + the slot
    chunk over the cached static tree) and xor-chains the root — the
    chained-dependency shape bench.py times.  Returns (BlockState,
    root_acc u32[8])."""
    if obs.tracing(st.balance):
        obs.count("block_epoch.traces", 1)
        return _block_epoch_chain_impl(
            params, n, st, blocks, static, root_ctx, with_withdrawals
        )
    slots = params.slots_per_epoch
    work_bytes = slots * 2 * sum(
        int(getattr(a, "nbytes", 0)) for a in (st.balance, st.cur_part, st.prev_part)
    )
    if root_ctx is not None:
        from eth_consensus_specs_tpu.ops.state_root import slot_root_real_hashes

        work_bytes += slots * 96 * slot_root_real_hashes(n, root_ctx.top_depth)
    def _device():
        fault.check("block_epoch.device")
        with obs.span(
            "block_epoch.chain", work_bytes=work_bytes, n_validators=n, slots=slots
        ) as sp:
            out = _block_epoch_chain_impl(
                params, n, st, blocks, static, root_ctx, with_withdrawals
            )
            sp.result = out
        return out

    # device-side death (compile/OOM/injected) degrades to the numpy
    # replay + native-sha slot roots (ops/block_epoch_host.py)
    out = fault.degrade(
        "block_epoch.device",
        _device,
        lambda: _block_epoch_chain_host(
            params, n, st, blocks, static, root_ctx, with_withdrawals
        ),
    )
    obs.count("block_epoch.epochs", 1)
    obs.count("block_epoch.slots", slots)
    obs.count("block_epoch.validator_slots", n * slots)
    return out


def _block_epoch_chain_impl(
    params: BlockEpochParams,
    n: int,
    st: BlockState,
    blocks: BlockColumns,
    static: BlockEpochStatic,
    root_ctx,
    with_withdrawals: bool,
):
    def slot_step(carry, xs):
        st, acc, slot_no = carry
        st = process_slot_columnar(
            params,
            n,
            st,
            xs,
            static.base_reward,
            static.eff_balance,
            static.withdrawable_epoch,
            static.has_eth1_cred,
            static.epoch,
            static.part_reward,
            static.prop_reward,
            with_withdrawals=with_withdrawals,
        )
        if root_ctx is not None:
            root = _slot_root(root_ctx, st, slot_no)
            acc = acc ^ root
        return (st, acc, slot_no + U64(1)), None

    acc0 = jnp.zeros(8, jnp.uint32)
    slot0 = static.epoch * U64(params.slots_per_epoch) + U64(1)
    (st, acc, _), _ = lax.scan(slot_step, (st, acc0, slot0), blocks)
    return st, acc


def _block_epoch_chain_host(
    params: BlockEpochParams,
    n: int,
    st: BlockState,
    blocks: BlockColumns,
    static: BlockEpochStatic,
    root_ctx,
    with_withdrawals: bool,
):
    """fault.degrade fallback for block_epoch_chain: the sequential numpy
    replay + native-sha slot roots (ops/block_epoch_host.py) — the same
    independent leg the bench correctness coupling uses, repackaged into
    the kernel's (BlockState, root_acc) contract."""
    from eth_consensus_specs_tpu.ops.block_epoch_host import (
        replay_block_epoch_np,
        slot_root_fn_from_ctx,
    )

    root_fn = slot_root_fn_from_ctx(root_ctx) if root_ctx is not None else None
    with obs.span("block_epoch.chain_host", n_validators=n):
        bal, cur, prev, wd_index, wd_validator, acc = replay_block_epoch_np(
            params,
            n,
            st,
            blocks,
            np.asarray(static.eff_balance),
            np.asarray(static.withdrawable_epoch),
            np.asarray(static.has_eth1_cred),
            int(np.asarray(static.epoch)),
            with_withdrawals=with_withdrawals,
            root_fn=root_fn,
        )
    new_st = BlockState(
        balance=jnp.asarray(bal),
        cur_part=jnp.asarray(cur),
        prev_part=jnp.asarray(prev),
        next_wd_index=U64(wd_index),
        next_wd_validator=U64(wd_validator),
    )
    return new_st, jnp.asarray(acc)


# ------------------------------------------------------- per-slot rooting --


class SlotRootCtx(NamedTuple):
    """Static tree content for mid-epoch dirty roots: everything but
    balances/participation/slot reduced once per epoch."""

    top_chunks: jnp.ndarray  # u32[P, 8] with static + per-epoch roots filled
    zerohashes: jnp.ndarray
    top_depth: int
    n: int
    slot_field_index: int
    balances_slot: int
    cur_part_slot: int
    prev_part_slot: int


def make_root_ctx(spec, arrays, meta, static: BlockEpochStatic, scores, just) -> SlotRootCtx:
    """Fill every slow-moving top chunk once: validator registry root (eff
    balances are epoch-constant), inactivity scores, checkpoints — then
    per-slot work is just the three dirty columns + top combine."""
    from eth_consensus_specs_tpu.ops.state_root import (
        BALANCE_LIMIT_CHUNKS_LOG2,
        bitvector4_chunk,
        checkpoint_root,
        u64_list_root,
        validator_registry_root,
    )

    n = meta.n_validators
    slot_of = {name: i for i, name in meta.dynamic_slots}
    chunks = arrays.top_chunks
    chunks = chunks.at[slot_of["validators"]].set(
        validator_registry_root(arrays, n, static.eff_balance)
    )
    if "inactivity_scores" in slot_of:
        chunks = chunks.at[slot_of["inactivity_scores"]].set(
            u64_list_root(scores, n, BALANCE_LIMIT_CHUNKS_LOG2, arrays.zerohashes)
        )
    chunks = chunks.at[slot_of["justification_bits"]].set(
        bitvector4_chunk(just.justification_bits.astype(bool))
    )
    chunks = chunks.at[slot_of["previous_justified_checkpoint"]].set(
        checkpoint_root(just.prev_justified_epoch, just.prev_justified_root)
    )
    chunks = chunks.at[slot_of["current_justified_checkpoint"]].set(
        checkpoint_root(just.cur_justified_epoch, just.cur_justified_root)
    )
    chunks = chunks.at[slot_of["finalized_checkpoint"]].set(
        checkpoint_root(just.finalized_epoch, just.finalized_root)
    )
    fields = list(spec.BeaconState.fields())
    return SlotRootCtx(
        top_chunks=chunks,
        zerohashes=arrays.zerohashes,
        top_depth=meta.top_depth,
        n=n,
        slot_field_index=fields.index("slot"),
        balances_slot=slot_of["balances"],
        cur_part_slot=slot_of["current_epoch_participation"],
        prev_part_slot=slot_of["previous_epoch_participation"],
    )


def _u64_chunk(v) -> jnp.ndarray:
    from eth_consensus_specs_tpu.ops.state_root import _u64_chunk_words

    return _u64_chunk_words(jnp.asarray(v, U64).reshape(1))[0]


def _slot_root(ctx: SlotRootCtx, st: BlockState, slot_no) -> jnp.ndarray:
    from eth_consensus_specs_tpu.ops.merkle import tree_root_words
    from eth_consensus_specs_tpu.ops.state_root import (
        BALANCE_LIMIT_CHUNKS_LOG2,
        PARTICIPATION_LIMIT_CHUNKS_LOG2,
        u8_list_root,
        u64_list_root,
    )

    n = ctx.n
    chunks = ctx.top_chunks
    chunks = chunks.at[ctx.slot_field_index].set(_u64_chunk(slot_no))
    chunks = chunks.at[ctx.balances_slot].set(
        u64_list_root(st.balance, n, BALANCE_LIMIT_CHUNKS_LOG2, ctx.zerohashes)
    )
    chunks = chunks.at[ctx.cur_part_slot].set(
        u8_list_root(st.cur_part, n, PARTICIPATION_LIMIT_CHUNKS_LOG2, ctx.zerohashes)
    )
    chunks = chunks.at[ctx.prev_part_slot].set(
        u8_list_root(st.prev_part, n, PARTICIPATION_LIMIT_CHUNKS_LOG2, ctx.zerohashes)
    )
    return tree_root_words(chunks, ctx.top_depth)


# ------------------------------------------------------------- ingest -----


def extract_block_columns(spec, pre_state, signed_blocks):
    """Harvest an epoch of object blocks into BlockColumns + the initial
    BlockState, replaying the object path for state-dependent context
    (committees, participation-flag indices, proposer/sync membership).
    Electra's committee-bit on-chain aggregates (EIP-7549) expand into
    one ROW per named committee, sharing a proposer-reward numerator up
    to the aggregate's pay boundary — beacon committees partition a
    slot's attesters, so the per-committee rows reproduce the spec's
    union exactly."""
    from eth_consensus_specs_tpu.config import is_post_fork

    post_electra = is_post_fork(spec.fork_name, "electra")
    state = pre_state.copy()
    n = len(state.validators)
    S = len(signed_blocks)
    obs.count("block_epoch.ingests", 1)
    obs.count("block_epoch.blocks_ingested", S)

    def _rows_of(state_now, att):
        """[(committee, bits_slice)] — one row per committee."""
        if not post_electra:
            committee = spec.get_beacon_committee(state_now, att.data.slot, att.data.index)
            return [(committee, [bool(b) for b in att.aggregation_bits])]
        rows = []
        offset = 0
        for ci in spec.get_committee_indices(att.committee_bits):
            committee = spec.get_beacon_committee(state_now, att.data.slot, ci)
            rows.append(
                (
                    committee,
                    [bool(att.aggregation_bits[offset + i]) for i in range(len(committee))],
                )
            )
            offset += len(committee)
        return rows or [([], [])]

    if post_electra:
        # the columnar plane models deneb-shaped deposit/withdrawal
        # semantics; electra's EIP-7251 queues change both — guard the
        # parts this ingest does NOT yet cover instead of mis-modeling
        # them silently (attestation semantics ARE fully covered)
        assert all(
            len(b.message.body.deposits) == 0 for b in signed_blocks
        ), "electra deposits route through pending_deposits — not columnar yet"
        assert len(getattr(pre_state, "pending_partial_withdrawals", [])) == 0, (
            "electra pending partial withdrawals not modeled in the sweep"
        )
        assert all(
            bytes(v.withdrawal_credentials)[:1] != b"\x02" for v in pre_state.validators
        ), "compounding (0x02) credentials not modeled in the sweep"

    SY = int(spec.SYNC_COMMITTEE_SIZE) if hasattr(spec, "SYNC_COMMITTEE_SIZE") else 0
    pk_to_index = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}

    # ONE replay pass: buffer ragged per-slot rows, then pad into the
    # fixed-shape tensors (sizing needs no second pyspec replay)
    slot_rows: list[list[tuple]] = []  # (committee, bits, flags, is_cur, pay)
    slot_deps: list[list[tuple[int, int]]] = []
    proposer_l: list[int] = []
    sync_rows: list[tuple[list[int], list[bool]]] = []
    for signed in signed_blocks:
        block = signed.message
        if int(block.slot) > int(state.slot):
            spec.process_slots(state, int(block.slot))
        proposer_l.append(int(block.proposer_index))
        cur_epoch = spec.get_current_epoch(state)
        rows_here: list[tuple] = []
        for att in block.body.attestations:
            data = att.data
            flag_indices = spec.get_attestation_participation_flag_indices(
                state, data, int(state.slot) - int(data.slot)
            )
            flags = 0
            for fi in flag_indices:
                flags |= 1 << fi
            rows = _rows_of(state, att)
            is_cur = int(data.target.epoch) == int(cur_epoch)
            for r, (committee, bits) in enumerate(rows):
                rows_here.append(
                    ([int(v) for v in committee], bits, flags, is_cur, r == len(rows) - 1)
                )
        slot_rows.append(rows_here)
        if SY:
            agg = block.body.sync_aggregate
            sync_rows.append(
                (
                    [pk_to_index[bytes(pk)] for pk in state.current_sync_committee.pubkeys],
                    [bool(b) for b in agg.sync_committee_bits],
                )
            )
        deps_here = []
        for dep in block.body.deposits:
            idx = pk_to_index.get(bytes(dep.data.pubkey))
            assert idx is not None, "columnar ingest covers existing-key deposits"
            deps_here.append((idx, int(dep.data.amount)))
        slot_deps.append(deps_here)
        spec.process_block(state, block)

    A = max((len(rows) for rows in slot_rows), default=1) or 1
    C = max(
        (len(cm) for rows in slot_rows for cm, *_ in rows), default=1
    ) or 1
    D = max((len(d) for d in slot_deps), default=0) or 1

    att_idx = np.full((S, A, C), n, np.uint32)
    att_bits = np.zeros((S, A, C), bool)
    att_flags = np.zeros((S, A), np.uint8)
    att_is_current = np.zeros((S, A), bool)
    att_pay = np.ones((S, A), bool)
    proposer = np.asarray(proposer_l, np.uint32)
    sync_idx = np.zeros((S, max(SY, 1)), np.uint32)
    sync_bits = np.zeros((S, max(SY, 1)), bool)
    dep_idx = np.full((S, D), n, np.uint32)
    dep_amt = np.zeros((S, D), np.uint64)
    for s in range(S):
        for a, (committee, bits, flags, is_cur, pay) in enumerate(slot_rows[s]):
            att_flags[s, a] = flags
            att_is_current[s, a] = is_cur
            att_pay[s, a] = pay
            if committee:
                att_idx[s, a, : len(committee)] = committee
                att_bits[s, a, : len(bits)] = bits
        if SY:
            sync_idx[s] = sync_rows[s][0]
            sync_bits[s] = sync_rows[s][1]
        for d, (idx, amt) in enumerate(slot_deps[s]):
            dep_idx[s, d] = idx
            dep_amt[s, d] = amt

    cols = BlockColumns(
        att_idx=jnp.asarray(att_idx),
        att_bits=jnp.asarray(att_bits),
        att_flags=jnp.asarray(att_flags),
        att_is_current=jnp.asarray(att_is_current),
        att_pay=jnp.asarray(att_pay),
        proposer=jnp.asarray(proposer),
        sync_idx=jnp.asarray(sync_idx),
        sync_bits=jnp.asarray(sync_bits),
        dep_idx=jnp.asarray(dep_idx),
        dep_amt=jnp.asarray(dep_amt),
    )
    st0 = BlockState(
        balance=jnp.asarray(np.array([int(b) for b in pre_state.balances], np.uint64)),
        cur_part=jnp.asarray(
            np.array([int(f) for f in pre_state.current_epoch_participation], np.uint8)
        ),
        prev_part=jnp.asarray(
            np.array([int(f) for f in pre_state.previous_epoch_participation], np.uint8)
        ),
        next_wd_index=U64(int(getattr(pre_state, "next_withdrawal_index", 0))),
        next_wd_validator=U64(
            int(getattr(pre_state, "next_withdrawal_validator_index", 0))
        ),
    )
    return cols, st0


def synthetic_block_columns(
    spec, n: int, seed: int = 0, atts_per_slot: int = 128, committee_cap: int | None = None
) -> tuple[BlockColumns, BlockState, BlockEpochStatic]:
    """Bench-scale inputs without an object state: every slot carries
    `atts_per_slot` full attestations over disjoint committees (the
    mainnet shape: 64 committees x 2 slots of lookback coverage), a full
    sync aggregate, a few deposits.  Deterministic in `seed`."""
    params = BlockEpochParams.from_spec(spec)
    S = params.slots_per_epoch
    rng = np.random.default_rng(seed)
    if committee_cap is None:
        committee_cap = max(8, int(np.ceil(n / (S * max(atts_per_slot // 2, 1)))))
        committee_cap = 1 << (committee_cap - 1).bit_length()
    A, C = atts_per_slot, committee_cap

    att_idx = np.full((S, A, C), n, np.uint32)
    att_bits = np.zeros((S, A, C), bool)
    for s in range(S):
        perm = rng.permutation(n).astype(np.uint32)
        rows = max(min(A, n // C), 1)
        flat = perm[: rows * C]
        committees = np.full((rows, C), n, np.uint32)
        committees.ravel()[: flat.shape[0]] = flat
        reps = -(-A // rows)  # re-vote committees until A attestations exist
        att_idx[s] = np.tile(committees, (reps, 1))[:A]
        att_bits[s] = rng.random((A, C)) < 0.9
    att_flags = np.full((S, A), 0b111, np.uint8)
    att_is_current = rng.random((S, A)) < 0.7
    # ~1/4 of rows continue into the next row's aggregate (the electra
    # multi-committee shape), exercising the carried numerator; rows of
    # one aggregate share their attestation data's target epoch
    att_pay = rng.random((S, A)) < 0.75
    att_pay[:, -1] = True
    for a in range(1, A):
        cont = ~att_pay[:, a - 1]
        att_is_current[cont, a] = att_is_current[cont, a - 1]

    SY = params.sync_committee_size
    cols = BlockColumns(
        att_idx=jnp.asarray(att_idx),
        att_bits=jnp.asarray(att_bits),
        att_flags=jnp.asarray(att_flags),
        att_is_current=jnp.asarray(att_is_current),
        att_pay=jnp.asarray(att_pay),
        proposer=jnp.asarray(rng.integers(0, n, S, dtype=np.int64).astype(np.uint32)),
        sync_idx=jnp.asarray(rng.integers(0, n, (S, SY), dtype=np.int64).astype(np.uint32)),
        sync_bits=jnp.asarray(rng.random((S, SY)) < 0.95),
        dep_idx=jnp.asarray(rng.integers(0, n, (S, 16), dtype=np.int64).astype(np.uint32)),
        dep_amt=jnp.asarray(
            rng.integers(1, 32_000_000_000, (S, 16), dtype=np.int64).astype(np.uint64)
        ),
    )
    balance = rng.integers(31_000_000_000, 33_000_000_000, n, dtype=np.int64).astype(
        np.uint64
    )
    # a stripe of near-zero balances so the sync penalty's per-operation
    # clamp (and its order sensitivity under duplicates) is exercised
    balance[:: max(n // 17, 1)] = rng.integers(
        0, 3, balance[:: max(n // 17, 1)].shape[0], dtype=np.int64
    ).astype(np.uint64)
    st0 = BlockState(
        balance=jnp.asarray(balance),
        cur_part=jnp.asarray(np.zeros(n, np.uint8)),
        prev_part=jnp.asarray(
            rng.integers(0, 8, n, dtype=np.int64).astype(np.uint8)
        ),
        next_wd_index=U64(0),
        next_wd_validator=U64(0),
    )
    eff = (balance // 1_000_000_000 * 1_000_000_000).astype(np.uint64)
    eff = np.minimum(eff, np.uint64(params.max_effective_balance))
    wd_epoch = np.full(n, 2**64 - 1, np.uint64)
    wd_epoch[rng.random(n) < 0.001] = 1  # a few fully-withdrawable
    static = make_epoch_static(
        params,
        jnp.asarray(eff),
        jnp.asarray(wd_epoch),
        jnp.asarray(np.ones(n, bool)),
        10,
    )
    return cols, st0, static
