"""Batched G2 many-sum — the aggregation pipeline's device kernel.

The serve layer has always *verified* pre-aggregated signatures; a
consensus node spends its slot budget *building* them from the
million-validator attestation fan-in. Signatures live in G2 (minimal-
pubkey-size BLS), so the hot operation is a ragged segment sum over
``g2_jacobian.g2_add`` lanes: every committee of a flush sums in ONE
dispatch, mirroring ``g1_msm.sum_many_kernel``'s one-dispatch-per-flush
discipline on the pubkey side.

Kernel shape: X/Y/Z are uint64[I, L, 2, 15] Montgomery lazy-limb arrays
(ops/lazy_limbs; infinity encoded as Z == 0, exactly the convention
``crypto/curve`` converts 1:1). Ragged committees pad their lane axis
with infinity lanes. The reduction is a LOG-DEPTH pairwise tree in
butterfly form, run as ONE ``lax.scan`` over the log2(L) levels: step s
adds every lane to its ``lane XOR 2^s`` partner, so after the scan lane
0 holds the committee sum and — crucially — the expensive complete-add
graph compiles ONCE per shape instead of once per tree level (measured
on XLA:CPU: ~45 s for the scan body vs ~50 s PER unrolled level). The
carry crosses the scan boundary canonical (limbs < 2^26, value < 2p),
the same bound discipline as ``g2_jacobian.g2_mul_z``.

Mesh variant: the LANE axis shards over the (dp, sp) mesh — each shard
folds its lane slice locally, then the per-shard Jacobian partials
all-gather and fold again on every device (the replicated-top combine
idiom of ``merkle_inc``/``msm_g1_device``). Jacobian addition is exact
group math and the final affine conversion is canonical, so any shard
count returns byte-identical points.

Conversion boundary: affine ``crypto/curve.Point`` <-> Montgomery limb
arrays on host; the final Jacobian->affine Fq2 inversion also stays
host-side (one inverse per committee, not worth a device Fermat chain
at flush sizes).
"""

from __future__ import annotations

import os

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.crypto.curve import B2, Point, g2_infinity
from eth_consensus_specs_tpu.crypto.fields import Fq, Fq2
from eth_consensus_specs_tpu.ops import fq12_tower as tw
from eth_consensus_specs_tpu.ops import g2_jacobian as gj
from eth_consensus_specs_tpu.ops import lazy_limbs as lz
from eth_consensus_specs_tpu.ops.lazy_limbs import lf


def _butterfly_partners(lanes: int) -> np.ndarray:
    """Per-level partner indices of the log-depth pairwise reduction in
    butterfly form: ``partners[s, j] = j XOR 2^s``. int32 on purpose —
    a python-int iota would widen to i64 under the package-wide x64
    flag (jaxlint x64-drift)."""
    steps = max(lanes - 1, 0).bit_length()
    if steps == 0:
        return np.zeros((0, lanes), np.int32)
    idx = np.arange(lanes, dtype=np.int32)
    return np.stack([idx ^ (1 << s) for s in range(steps)]).astype(np.int32)


def _lane_fold(X, Y, Z, axis: int = 1):
    """Sum the ``axis`` lanes of Jacobian [.., L, .., 2, 15] coordinate
    arrays via the butterfly tree; returns the [.., 2, 15] sums (lane 0
    of the folded axis). Lane count must be a power of two; infinity
    lanes (Z == 0) are absorbed by the complete add."""
    if axis != 1:
        X, Y, Z = (jnp.moveaxis(a, axis, 1) for a in (X, Y, Z))
    lanes = X.shape[1]
    # XOR partners index past the edge on a non-pow2 width, and
    # jnp.take would CLIP them silently — wrong sums, not an error
    assert lanes & (lanes - 1) == 0, f"lane fold needs pow2 lanes, got {lanes}"
    partners = _butterfly_partners(lanes)
    if partners.shape[0] == 0:
        return X[:, 0], Y[:, 0], Z[:, 0]

    def step(carry, idx):
        cX, cY, cZ = carry
        p = gj.G2J(lf(cX), lf(cY), lf(cZ))
        q = gj.G2J(
            lf(jnp.take(cX, idx, axis=1)),
            lf(jnp.take(cY, idx, axis=1)),
            lf(jnp.take(cZ, idx, axis=1)),
        )
        s = gj.g2_add(p, q)
        # canonical across the scan boundary: the re-wrap on entry
        # (lf = limbs < 2^26, value < 2p) must tell the truth
        return (gj._canon(s.x).v, gj._canon(s.y).v, gj._canon(s.z).v), None

    (oX, oY, oZ), _ = lax.scan(step, (X, Y, Z), jnp.asarray(partners))
    return oX[:, 0], oY[:, 0], oZ[:, 0]


@jax.jit
def g2_sum_many_kernel(X, Y, Z):
    """Per-item G2 point sums over [I, L, 2, 15] lane arrays (L a power
    of two): the batched committee-aggregate kernel — one dispatch sums
    every committee of a flush instead of one dispatch per committee."""
    return _lane_fold(X, Y, Z)


# == mesh-sharded kernel ===================================================
#
# The LANE axis shards over the (dp, sp) mesh: each shard's committees
# are the same (the item axis replicates), its lane slice folds locally,
# and the per-shard [I, 2, 15] Jacobian partials all-gather + fold again
# on every device — the replicated-top combine of merkle_inc and
# msm_g1_device. Per-shard lane counts stay a power of two by the
# agg_lane_bucket padding model (serve/buckets.py).


def _cross_shard_fold(rX, rY, rZ, axes):
    """all_gather per-shard Jacobian partials ([I, 2, 15] each) and fold
    the shard axis; non-pow2 shard counts pad with infinity lanes."""
    gX = lax.all_gather(rX, axes)
    gY = lax.all_gather(rY, axes)
    gZ = lax.all_gather(rZ, axes)
    s = gX.shape[0]
    cap = 1 << max(s - 1, 0).bit_length()
    if cap != s:
        pad = ((0, cap - s),) + ((0, 0),) * (gX.ndim - 1)
        gX = jnp.pad(gX, pad)
        gY = jnp.pad(gY, pad)
        gZ = jnp.pad(gZ, pad)
    return _lane_fold(gX, gY, gZ, axis=0)


_SHARDED_FNS: dict[Mesh, object] = {}


def _sharded_fn(mesh: Mesh):
    """Per-mesh jitted shard_map entry (cached: the jit cache then
    dedupes per input shape)."""
    fn = _SHARDED_FNS.get(mesh)
    if fn is not None:
        return fn
    from eth_consensus_specs_tpu.parallel.mesh_ops import BATCH_AXES

    spec = P(None, BATCH_AXES)

    def local(X, Y, Z):
        return _cross_shard_fold(*_lane_fold(X, Y, Z), BATCH_AXES)

    fn = jax.jit(
        shard_map(local, mesh=mesh, in_specs=spec, out_specs=P(), check_rep=False)
    )
    _SHARDED_FNS[mesh] = fn
    return fn


def _clear_sharded_after_fork_in_child() -> None:
    # fork-safety: compiled executables reference the parent's devices
    _SHARDED_FNS.clear()


os.register_at_fork(after_in_child=_clear_sharded_after_fork_in_child)


def g2_many_sum_shape(n_items: int, max_lanes: int, shards: int = 1) -> tuple[int, int]:
    """(item_pad, lane_pad) the batched committee-sum kernel compiles
    at: items pad to pow2 (the item axis replicates across shards),
    lanes to the mesh-aware ragged-committee bucket — ONE shared shape
    model for the ops entry point and the serve layer's compile
    accounting (serve/buckets.agg_lane_bucket), so they can never
    disagree."""
    from eth_consensus_specs_tpu.serve.buckets import agg_lane_bucket, pow2_bucket

    return pow2_bucket(max(n_items, 1)), agg_lane_bucket(max_lanes, shards)


# == host conversion boundary ==============================================


def _points_to_lanes(
    point_lists: list[list], item_pad: int, lane_pad: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    X = np.zeros((item_pad, lane_pad, 2, lz.N_LIMBS), np.uint64)
    Y = np.zeros_like(X)
    Z = np.zeros_like(X)
    one = tw.fq2_to_limbs(Fq2.one())
    for i, points in enumerate(point_lists):
        for j, p in enumerate(points):
            if p.is_infinity():
                continue  # Z stays zero
            X[i, j] = tw.fq2_to_limbs(p.x)
            Y[i, j] = tw.fq2_to_limbs(p.y)
            Z[i, j] = one
    return X, Y, Z


def _jacobian_to_point(X, Y, Z) -> Point:
    z = Fq2(Fq(lz.from_mont_int(Z[0])), Fq(lz.from_mont_int(Z[1])))
    if z == Fq2.zero():
        return g2_infinity()
    x = Fq2(Fq(lz.from_mont_int(X[0])), Fq(lz.from_mont_int(X[1])))
    y = Fq2(Fq(lz.from_mont_int(Y[0])), Fq(lz.from_mont_int(Y[1])))
    zinv = z.inv()
    zinv2 = zinv * zinv
    return Point(x * zinv2, y * zinv2 * zinv, B2)


def sum_g2_many_device(
    point_lists: list[list], mesh: Mesh | None = None, pad_shape: tuple | None = None
) -> list[Point]:
    """Per-committee G2 point sums for many committees in ONE dispatch:
    ``[sum(points) for points in point_lists]``. Ragged lanes pad with
    infinity to the :func:`g2_many_sum_shape` bucket (``pad_shape``
    overrides — the serve layer passes its own bucket so accounting and
    dispatch agree); a multi-device ``mesh`` shards the LANE axis. Each
    result is byte-identical to the host fold
    ``crypto.signature._sum_g2(points)``."""
    n = len(point_lists)
    if n == 0:
        return []
    from eth_consensus_specs_tpu.parallel.mesh_ops import shard_count

    shards = shard_count(mesh)
    if shards <= 1:
        mesh = None
    max_lanes = max((len(p) for p in point_lists), default=1)
    item_pad, lane_pad = pad_shape or g2_many_sum_shape(n, max_lanes, shards)
    assert item_pad >= n and lane_pad >= max_lanes
    X, Y, Z = _points_to_lanes(point_lists, item_pad, lane_pad)
    args = (jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
    if mesh is not None:
        obs.count("mesh.dispatches", 1)
        obs.count("mesh.sharded_items", n)
        rX, rY, rZ = _sharded_fn(mesh)(*args)
    else:
        rX, rY, rZ = g2_sum_many_kernel(*args)
    rX, rY, rZ = np.asarray(rX), np.asarray(rY), np.asarray(rZ)
    return [_jacobian_to_point(rX[i], rY[i], rZ[i]) for i in range(n)]


def sum_g2_device(points: list, mesh: Mesh | None = None) -> Point:
    """Device G2 point sum of one committee: ``sum(points)``."""
    return sum_g2_many_device([points], mesh=mesh)[0]
