"""Batched hash-to-G2 on device (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_).

The signature hot path hashes one fresh message to G2 per distinct
attestation — on the host that costs ~0.75 ms each (two Fq2 square roots
plus the cofactor ladder dominate, crypto/hash_to_curve.py).  Here the
whole field-to-curve pipeline runs as ONE jitted device program over a
fixed batch of messages:

  * hash_to_field stays host-side (SHA-256 via hashlib — cheap, and
    sha256 of short inputs is not the device's comparative advantage);
  * simplified SWU on E2' with the norm-method Fq2 square root, evaluated
    BRANCHLESSLY: both gx1/gx2 candidates, both ±sn half-branches, and
    the b==0 special case are computed for every lane and lane-selected
    to exactly the value the host oracle picks
    (crypto/fields.Fq2.sqrt + crypto/hash_to_curve.map_to_curve_sswu_g2);
  * all Fq exponentiations are packed into FOUR fixed scans (381 steps of
    square + conditional multiply each) over stacked lanes — per level
    every lane shares the same public exponent ((p+1)/4 or p-2);
  * the 3-isogeny evaluates into JACOBIAN coordinates (Z = x_den * y_den)
    so no inversion is spent before the group stage;
  * point addition of the two mapped points and the Budroni-Pintore
    cofactor ladder run in ops/g2_jacobian (bit-equal to the native C
    walk), and one final batched Fq2 inversion converts to affine.

Bit-exactness: hash_to_g2_device(msgs) == [hash_to_g2(m) for m in msgs]
exactly (tests/test_h2c_device.py), so the device path can substitute the
host/native one anywhere (reference seam: the message-side pairing input
of every verification, utils/bls.py:141-221).
"""

from __future__ import annotations

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu.crypto.fields import Fq, Fq2, P as P_INT
from eth_consensus_specs_tpu.crypto import hash_to_curve as h2c
from eth_consensus_specs_tpu.ops import fq12_tower as tw
from eth_consensus_specs_tpu.ops import g2_jacobian as gj
from eth_consensus_specs_tpu.ops import lazy_limbs as lz
from eth_consensus_specs_tpu.ops.lazy_limbs import LF, lf

# ---------------------------------------------------------------- consts --

_E_SQRT = np.array([int(b) for b in bin((P_INT + 1) // 4)[2:]], np.uint8)
_E_INV = np.array([int(b) for b in bin(P_INT - 2)[2:]], np.uint8)

_A_L = tw.fq2_to_limbs(h2c.A_PRIME)
_B_L = tw.fq2_to_limbs(h2c.B_PRIME)
_Z_L = tw.fq2_to_limbs(h2c.Z_SSWU)
# x1 coefficient for the regular branch: (-B) * A^-1 (a fixed Fq2 value)
_NEG_B_OVER_A_L = tw.fq2_to_limbs(-h2c.B_PRIME * h2c.A_PRIME.inv())
# x1 for the exceptional branch: B / (Z * A)
_B_OVER_ZA_L = tw.fq2_to_limbs(
    h2c.B_PRIME * (h2c.Z_SSWU * h2c.A_PRIME).inv()
)
_ONE2_L = tw.fq2_to_limbs(Fq2.one())
_INV2_L = lz.to_mont(pow(2, P_INT - 2, P_INT))
# (-1)^((p+1)/4): turns sqrt-candidate of a into the candidate of -a
_ZETA_L = lz.to_mont(pow(P_INT - 1, (P_INT + 1) // 4, P_INT))

_K_LIMBS = [
    np.stack([tw.fq2_to_limbs(c) for c in ks])
    for ks in (h2c._K1, h2c._K2, h2c._K3, h2c._K4)
]

_P_LIMBS_ARR = lz.int_to_limbs(P_INT)


# ------------------------------------------------------------- primitives --


def _pow_lanes(x: LF, bits: np.ndarray) -> LF:
    """x^e over any batch shape in ONE scan; bits MSB-first (the leading
    bit may be 0 — the accumulator starts at one)."""
    xc = lz.shrink(x)
    one = lf(jnp.broadcast_to(jnp.asarray(lz.ONE_MONT), xc.v.shape), val=P_INT - 1)

    def step(acc_v, bit):
        acc = lf(acc_v)
        sq = lz.mul(acc, acc)
        wm = lz.mul(sq, lf(xc.v))
        return jnp.where(bit != 0, wm.v, sq.v), None

    out, _ = lax.scan(step, one.v, jnp.asarray(bits))
    return lf(out)


def _canon_fq(x: LF) -> jnp.ndarray:
    """Exact canonical residue (< p) as normalized limbs — needed for
    parity (sgn0) and equality against host values."""
    s = lz.shrink(x)  # < 2p
    pv = jnp.broadcast_to(jnp.asarray(_P_LIMBS_ARR), s.v.shape)
    ge = lz._geq(s.v, pv)
    return jnp.where(ge[..., None], lz._sub_limbs(s.v, pv), s.v)


def _fq_eq(a: LF, b: LF) -> jnp.ndarray:
    return jnp.all(_canon_fq(a) == _canon_fq(b), axis=-1)


def _fq_is_zero(a: LF) -> jnp.ndarray:
    return jnp.all(_canon_fq(a) == 0, axis=-1)


def _fq2_eq(a: LF, b: LF) -> jnp.ndarray:
    return jnp.all(
        _canon_fq(LF(a.v, a.max, a.val)) == _canon_fq(LF(b.v, b.max, b.val)),
        axis=(-1, -2),
    )


def _c0(a: LF) -> LF:
    return tw._part(a, 0, 1)


def _c1(a: LF) -> LF:
    return tw._part(a, 1, 1)


def _mk2(c0: LF, c1: LF) -> LF:
    return tw._stack([c0, c1], axis=-2)


def _self_fq(mask, a: LF, b: LF) -> LF:
    return LF(
        jnp.where(mask[..., None], a.v, b.v),
        max(a.max, b.max),
        max(a.val, b.val),
    )


_ONE_PLAIN = lz.int_to_limbs(1)


def _from_mont(x: LF) -> jnp.ndarray:
    """Canonical PLAIN residue (< p): one Montgomery multiply by the
    plain constant 1 strips the 2^390 factor (mul(x, 1) = v)."""
    one = lf(jnp.broadcast_to(jnp.asarray(_ONE_PLAIN), x.v.shape), val=1)
    return _canon_fq(lz.mul(x, one))


def _sgn0(x: LF) -> jnp.ndarray:
    """RFC 9380 sgn0 for m=2 — parity is of the PLAIN value, so the
    Montgomery factor must come off first (limbs are base-2^26: bit 0 of
    limb 0 is the value's parity)."""
    c0 = _from_mont(_c0(x))
    c1 = _from_mont(_c1(x))
    sign_0 = c0[..., 0] & 1
    zero_0 = jnp.all(c0 == 0, axis=-1)
    sign_1 = c1[..., 0] & 1
    return sign_0 | (zero_0.astype(jnp.uint64) & sign_1)


# ------------------------------------------------------------ Fq2 sqrt ----
# The norm method exactly as crypto/fields.Fq2.sqrt, with every branch
# computed and lane-selected.  Returns (root, ok_mask).


def _fq2_sqrt_batch(v: LF) -> tuple[LF, jnp.ndarray]:
    a, b = _c0(v), _c1(v)
    b_zero = _fq_is_zero(b)
    v_zero = _fq_is_zero(a) & b_zero

    norm = lz.add(lz.mul(a, a), lz.mul(b, b))  # N(a+bu) = a^2 + b^2

    # L1: stacked (p+1)/4 lanes: [sn(norm), s_bz(a)]
    l1_in = tw._lane_stack([norm, a])
    l1 = _pow_lanes(l1_in, _E_SQRT)
    sn, s_bz = tw._unstack(l1, 2)

    # b==0 resolution: s_bz if s_bz^2 == a else zeta * s_bz (root of -a)
    bz_ok = _fq_eq(lz.mul(s_bz, s_bz), a)
    zeta = lf(jnp.broadcast_to(jnp.asarray(_ZETA_L), s_bz.v.shape), val=P_INT - 1)
    s_alt = lz.mul(zeta, s_bz)
    bz_root = _self_fq(bz_ok, s_bz, LF(jnp.zeros_like(s_bz.v), 0, 0))
    bz_root_c1 = _self_fq(bz_ok, LF(jnp.zeros_like(s_bz.v), 0, 0), s_alt)
    out_bz = _mk2(bz_root, bz_root_c1)

    # --- general branch ------------------------------------------------
    sn_ok = _fq_eq(lz.mul(sn, sn), norm)
    inv2 = lf(jnp.broadcast_to(jnp.asarray(_INV2_L), a.v.shape), val=P_INT - 1)
    half_p = lz.mul(lz.add(a, sn), inv2)
    half_m = lz.mul(lz.sub(a, sn), inv2)

    # L2: stacked (p+1)/4 lanes: [x_p, x_m]
    l2 = _pow_lanes(tw._lane_stack([half_p, half_m]), _E_SQRT)
    x_p, x_m = tw._unstack(l2, 2)
    xp_ok = _fq_eq(lz.mul(x_p, x_p), half_p) & ~_fq_is_zero(x_p)
    xm_ok = _fq_eq(lz.mul(x_m, x_m), half_m) & ~_fq_is_zero(x_m)

    # L3: stacked p-2 lanes: [inv(2x_p), inv(2x_m)]
    l3 = _pow_lanes(tw._lane_stack([lz.dbl(x_p), lz.dbl(x_m)]), _E_INV)
    ixp, ixm = tw._unstack(l3, 2)
    y_p = lz.mul(b, ixp)
    y_m = lz.mul(b, ixm)

    cand_p = _mk2(x_p, y_p)
    cand_m = _mk2(x_m, y_m)
    cp_ok = xp_ok & _fq2_eq(tw.fq2_sqr(cand_p), v)
    cm_ok = xm_ok & _fq2_eq(tw.fq2_sqr(cand_m), v)
    gen_root = gj._sel(cp_ok, cand_p, cand_m)
    gen_ok = sn_ok & (cp_ok | cm_ok)

    root = gj._sel(b_zero, out_bz, gen_root)
    ok = jnp.where(b_zero, True, gen_ok)
    # v == 0: root 0, ok
    zero2 = LF(jnp.zeros_like(root.v), 0, 0)
    root = gj._sel(v_zero, zero2, root)
    return root, ok


# ------------------------------------------------------------- SSWU ------


def _bc2(arr: np.ndarray, like: LF) -> LF:
    return lf(jnp.broadcast_to(jnp.asarray(arr), like.v.shape), val=P_INT - 1)


def _map_to_curve_sswu(u: LF) -> tuple[LF, LF]:
    """Affine (x', y') on E2' for a batch of field elements — the exact
    branch structure of map_to_curve_sswu_g2, lane-selected."""
    A = _bc2(_A_L, u)
    B = _bc2(_B_L, u)
    Z = _bc2(_Z_L, u)
    one = _bc2(_ONE2_L, u)

    u2 = tw.fq2_sqr(u)
    tv1 = tw.fq2_mul(Z, u2)
    tv2 = tw.fq2_add(tw.fq2_sqr(tv1), tv1)
    tv2_zero = tw.fq2_is_zero(tv2)

    # regular x1 = (-B/A) * (1 + tv2^-1); tv2^-1 via conj/norm with one
    # Fq exponent lane (p-2)
    t_a, t_b = _c0(tv2), _c1(tv2)
    tnorm = lz.add(lz.mul(t_a, t_a), lz.mul(t_b, t_b))
    # guard the zero lane so pow doesn't see 0 (its result is discarded)
    one_fq = lf(jnp.broadcast_to(jnp.asarray(lz.ONE_MONT), tnorm.v.shape), val=P_INT - 1)
    tnorm_safe = _self_fq(tv2_zero, one_fq, tnorm)
    tni = _pow_lanes(tnorm_safe, _E_INV)
    tv2_inv = _mk2(lz.mul(t_a, tni), lz.mul(lz.sub(LF(jnp.zeros_like(t_b.v), 0, 0), t_b), tni))
    x1_reg = tw.fq2_mul(_bc2(_NEG_B_OVER_A_L, u), tw.fq2_add(one, tv2_inv))
    x1_exc = _bc2(_B_OVER_ZA_L, u)
    x1 = gj._sel(tv2_zero, x1_exc, x1_reg)

    def gx(x: LF) -> LF:
        return tw.fq2_add(
            tw.fq2_mul(tw.fq2_add(tw.fq2_sqr(x), A), x), B
        )

    gx1 = gx(x1)
    x2 = tw.fq2_mul(tv1, x1)
    gx2 = gx(x2)

    y1, ok1 = _fq2_sqrt_batch(gx1)
    y2, _ok2 = _fq2_sqrt_batch(gx2)  # one of the two always succeeds

    x = gj._sel(ok1, x1, x2)
    y = gj._sel(ok1, y1, y2)

    flip = _sgn0(u) != _sgn0(y)
    y = gj._sel(flip, tw.fq2_neg(y), y)
    return x, y


def _iso_map_jacobian(x: LF, y: LF) -> gj.G2J:
    """3-isogeny E2' -> E2 into Jacobian coordinates without inversions:
    Z = xd*yd, X = xn*xd*yd^2, Y = y*yn*xd^3*yd^2.  Poles (xd or yd == 0)
    land on Z == 0 = infinity, matching the host's kernel convention."""
    def horner(karr: np.ndarray, xx: LF) -> LF:
        acc = _bc2(karr[-1], xx)
        for i in range(karr.shape[0] - 2, -1, -1):
            acc = tw.fq2_add(tw.fq2_mul(acc, xx), _bc2(karr[i], xx))
        return acc

    xn = horner(_K_LIMBS[0], x)
    xd = horner(_K_LIMBS[1], x)
    yn = horner(_K_LIMBS[2], x)
    yd = horner(_K_LIMBS[3], x)

    z = tw.fq2_mul(xd, yd)
    yd2 = tw.fq2_sqr(yd)
    X = tw.fq2_mul(tw.fq2_mul(xn, xd), yd2)
    xd2 = tw.fq2_sqr(xd)
    Y = tw.fq2_mul(
        tw.fq2_mul(tw.fq2_mul(y, yn), tw.fq2_mul(xd2, xd)), yd2
    )
    return gj.G2J(X, Y, z)


# ------------------------------------------------------------ public API --


# The pipeline is split into TWO jits on purpose: one monolithic graph
# (sswu x2 + cofactor ladder) was measured to blow XLA's optimization
# passes past 20 GB on CPU.  Stage 1 evaluates BOTH field elements of
# every message through a single SSWU/isogeny body (stacked lanes) and
# adds the pair; stage 2 runs the cofactor ladder and converts to
# affine.  Two device dispatches per batch — tunnel-friendly.


@jax.jit
def _h2c_map(u_limbs: jnp.ndarray):
    """[B, 2, 2, 15] field elements (two per message) -> Jacobian sum
    arrays for the B messages."""
    n = u_limbs.shape[0]
    stacked = jnp.concatenate([u_limbs[:, 0], u_limbs[:, 1]], axis=0)
    x, y = _map_to_curve_sswu(lf(stacked))
    pj = _iso_map_jacobian(x, y)
    p0 = gj.G2J(
        LF(pj.x.v[:n], pj.x.max, pj.x.val),
        LF(pj.y.v[:n], pj.y.max, pj.y.val),
        LF(pj.z.v[:n], pj.z.max, pj.z.val),
    )
    p1 = gj.G2J(
        LF(pj.x.v[n:], pj.x.max, pj.x.val),
        LF(pj.y.v[n:], pj.y.max, pj.y.val),
        LF(pj.z.v[n:], pj.z.max, pj.z.val),
    )
    summed = gj.g2_add(p0, p1)
    return (
        gj._canon(summed.x).v,
        gj._canon(summed.y).v,
        gj._canon(summed.z).v,
    )


@jax.jit
def _h2c_finish(xj: jnp.ndarray, yj: jnp.ndarray, zj: jnp.ndarray):
    """Jacobian sums -> cofactor-cleared affine limbs + infinity mask."""
    p = gj.G2J(lf(xj), lf(yj), lf(zj))
    cleared = gj.g2_clear_cofactor(p)
    ax, ay, inf = gj.g2_to_affine(cleared)
    return _canon_fq(ax), _canon_fq(ay), inf


def _h2c_core(u_limbs: jnp.ndarray):
    return _h2c_finish(*_h2c_map(u_limbs))


def hash_to_g2_device(msgs: list[bytes], dst: bytes = h2c.DST_G2):
    """Batched device hash-to-G2 — value-equal to the host hash_to_g2 for
    every message.  Returns a list of crypto.curve.Point.

    The batch is padded to the next power of two (extra lanes hash a
    fixed dummy message) so the compile-heavy jits serve every batch size
    from a handful of executables — the same same-pow2 sharing the G1 MSM
    kernel uses — instead of retracing per distinct message count."""
    from eth_consensus_specs_tpu.crypto.curve import B2, Point

    if not msgs:
        return []
    padded = 1 << (len(msgs) - 1).bit_length()
    rows = np.zeros((padded, 2, 2, lz.N_LIMBS), np.uint64)
    for i in range(padded):
        m = msgs[i] if i < len(msgs) else b"\x00pad"
        u0, u1 = h2c.hash_to_field_fq2(bytes(m), 2, dst)
        rows[i] = np.stack([tw.fq2_to_limbs(u0), tw.fq2_to_limbs(u1)])
    ax, ay, inf = _h2c_core(jnp.asarray(rows))
    ax_h, ay_h, inf_h = np.asarray(ax), np.asarray(ay), np.asarray(inf)
    # results are materialized on host — only now is "warm" true
    from eth_consensus_specs_tpu.utils.cache import mark_warm

    mark_warm("h2c")
    out = []
    for i in range(len(msgs)):
        if inf_h[i]:
            out.append(Point.infinity(B2))
            continue
        out.append(
            Point(tw.limbs_to_fq2(ax_h[i]), tw.limbs_to_fq2(ay_h[i]), B2)
        )
    return out
