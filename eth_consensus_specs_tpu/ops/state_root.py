"""Full BeaconState merkleization on device with dirty-path rehash.

SURVEY hard part 3: at 1M validators the reference's (cached) behavior —
full-state rehash per slot through remerkleable — is the top cost of
`state_transition` (reference: specs/phase0/beacon-chain.md:1383-1393 via
utils/hash_function.py). This module keeps the STATE TREE's big regions
device-resident and re-hashes only the paths the accounting epoch
actually dirties:

* per-validator subtrees: of the 8 Validator fields only
  effective_balance changes during accounting, so the static 2/3 of each
  validator's 15-node tree (pubkey root + withdrawal_credentials node;
  the four epoch fields' node) is precomputed ONCE at ingest via the
  native C sha core, and each epoch recomputes just 3 hashes/validator
  on device (B = H(eff_balance, slashed), E = H(A, B), root = H(E, F));
* the big flat columns (balances, inactivity scores, participation) are
  chunked and tree-reduced on device (ops/merkle.tree_root_words), then
  zero-hash-folded to their SSZ limit depth and length-mixed;
* every OTHER state field's root is harvested once at ingest from the
  object tree's cached roots and sits as a static chunk; the top-level
  container combine (~32 chunks) runs on device each epoch.

The result is `hash_tree_root(state)` for the post-accounting state as
PURE device work after one ingest — the north-star shape (BASELINE.json:
epoch-boundary state_transition incl. full state root < 1s @ 1M).

Bit-exactness: tests/test_state_root_device.py compares against
ssz.hash_tree_root on the equivalently-updated object state.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu import fault, obs
from eth_consensus_specs_tpu.ops.merkle import tree_root_words
from eth_consensus_specs_tpu.ops.sha256 import sha256_pair_words

VALIDATOR_REGISTRY_LIMIT_LOG2 = 40  # List[Validator, 2**40]
BALANCE_LIMIT_CHUNKS_LOG2 = 38  # 2**40 u64 -> 2**38 chunks
PARTICIPATION_LIMIT_CHUNKS_LOG2 = 35  # 2**40 bytes -> 2**35 chunks


def _bytes_to_words(b: bytes) -> np.ndarray:
    return np.frombuffer(b, dtype=">u4").astype(np.uint32)


def zerohash_words(max_depth: int) -> np.ndarray:
    """[max_depth+1, 8] u32 — zerohashes[d] as BE words."""
    from eth_consensus_specs_tpu.ssz.merkle import zerohashes

    return np.stack([_bytes_to_words(zerohashes[d]) for d in range(max_depth + 1)])


class StateRootArrays(NamedTuple):
    """Device-resident static tree content (a pure-array pytree, safe to
    pass through jit)."""

    val_node_a: jnp.ndarray  # u32[N, 8]  H(pubkey_root, withdrawal_credentials)
    val_node_f: jnp.ndarray  # u32[N, 8]  H(H(aee, ae), H(exit, withdrawable))
    slashed_chunk: jnp.ndarray  # u32[N, 8] SSZ chunk of `slashed`
    prev_part_flags: jnp.ndarray  # u8[N] participation bytes rotated into prev
    top_chunks: jnp.ndarray  # u32[P, 8] all field roots (static slots filled)
    zerohashes: jnp.ndarray  # u32[41, 8]


class StateRootMeta(NamedTuple):
    """Hashable host-side layout data (closure/static side of the jit)."""

    dynamic_slots: tuple  # ((field index, field name), ...)
    n_validators: int
    top_depth: int


def _u64_chunk_words(vals: jnp.ndarray) -> jnp.ndarray:
    """u64[N] -> SSZ 32-byte chunks as u32[N, 8] BE words (value LE in the
    first 8 bytes of the chunk)."""
    lo = (vals & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (vals >> jnp.uint64(32)).astype(jnp.uint32)

    def bswap(w):
        return (
            ((w & jnp.uint32(0xFF)) << 24)
            | ((w & jnp.uint32(0xFF00)) << 8)
            | ((w >> 8) & jnp.uint32(0xFF00))
            | ((w >> 24) & jnp.uint32(0xFF))
        )

    z = jnp.zeros_like(lo)
    return jnp.stack([bswap(lo), bswap(hi), z, z, z, z, z, z], axis=-1)


def _hash_rows(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """H(a || b) rowwise for u32[..., 8] word chunks."""
    return sha256_pair_words(jnp.concatenate([a, b], axis=-1))


def packed_u64_leaves(vals: jnp.ndarray, n: int) -> jnp.ndarray:
    """u64[n] (n % 4 == 0) -> u32[n//4, 8] SSZ packed chunk words."""
    w = lax.bitcast_convert_type(vals, jnp.uint32).reshape(n // 4, 8)
    return (
        ((w & 0xFF) << 24)
        | ((w & 0xFF00) << 8)
        | ((w >> 8) & 0xFF00)
        | ((w >> 24) & 0xFF)
    )


def packed_u8_leaves(vals: jnp.ndarray, n: int) -> jnp.ndarray:
    """u8[n] (n % 32 == 0) -> u32[n//32, 8] SSZ packed chunk words."""
    w = vals.reshape(n // 32, 8, 4).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) | w[..., 3]


def fold_to_limit(root: jnp.ndarray, depth: int, limit_log2: int, zh: jnp.ndarray):
    """Chain a subtree root up to the SSZ limit depth with zero-hash
    siblings (right sibling = zerohashes[d] at each level)."""
    for d in range(depth, limit_log2):
        root = _hash_rows(root[None, :], zh[d][None, :])[0]
    return root


def mix_length(root: jnp.ndarray, length: int) -> jnp.ndarray:
    len_chunk = _u64_chunk_words(jnp.full((1,), np.uint64(length), jnp.uint64))[0]
    return _hash_rows(root[None, :], len_chunk[None, :])[0]


def validator_registry_root(
    arrays: StateRootArrays, n: int, effective_balance: jnp.ndarray
) -> jnp.ndarray:
    """List[Validator] root from the static nodes + the dynamic
    effective-balance column: 3 hashes per validator + the leaf tree."""
    eb_chunk = _u64_chunk_words(effective_balance)
    node_b = _hash_rows(eb_chunk, arrays.slashed_chunk)
    node_e = _hash_rows(arrays.val_node_a, node_b)
    roots = _hash_rows(node_e, arrays.val_node_f)  # [N, 8] validator roots
    depth = max(n - 1, 0).bit_length()
    sub = tree_root_words(_pad_pow2(roots, depth), depth)
    full = fold_to_limit(sub, depth, VALIDATOR_REGISTRY_LIMIT_LOG2, arrays.zerohashes)
    return mix_length(full, n)


def _pad_pow2(leaves: jnp.ndarray, depth: int) -> jnp.ndarray:
    pad = (1 << depth) - leaves.shape[0]
    if pad:
        leaves = jnp.concatenate([leaves, jnp.zeros((pad, 8), jnp.uint32)], axis=0)
    return leaves


def u64_list_root(
    vals: jnp.ndarray, n: int, limit_chunks_log2: int, zh: jnp.ndarray
) -> jnp.ndarray:
    if n % 4:
        vals = jnp.concatenate([vals, jnp.zeros(4 - n % 4, jnp.uint64)])
    chunks = (n + 3) // 4
    leaves = packed_u64_leaves(vals, vals.shape[0])
    depth = max(chunks - 1, 0).bit_length() if n else 0
    sub = tree_root_words(_pad_pow2(leaves, depth), depth)
    return mix_length(fold_to_limit(sub, depth, limit_chunks_log2, zh), n)


def u8_list_root(
    vals: jnp.ndarray, n: int, limit_chunks_log2: int, zh: jnp.ndarray
) -> jnp.ndarray:
    if n % 32:
        vals = jnp.concatenate([vals, jnp.zeros(32 - n % 32, jnp.uint8)])
    chunks = (n + 31) // 32
    leaves = packed_u8_leaves(vals, vals.shape[0])
    depth = max(chunks - 1, 0).bit_length() if n else 0
    sub = tree_root_words(_pad_pow2(leaves, depth), depth)
    return mix_length(fold_to_limit(sub, depth, limit_chunks_log2, zh), n)


def _zero_u8_list_root_words(n: int) -> np.ndarray:
    """Host-computed root words of an all-zero List[uint8-ish, 2**40] of
    length n (the rotated-in current participation): zero subtree =
    zerohashes[depth], folded to the limit depth, length-mixed."""
    from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
    from eth_consensus_specs_tpu.ssz.merkle import zerohashes

    chunks = (n + 31) // 32
    depth = max(chunks - 1, 0).bit_length() if n else 0
    root = zerohashes[depth]
    for d in range(depth, PARTICIPATION_LIMIT_CHUNKS_LOG2):
        root = hash_bytes(root + zerohashes[d])
    root = hash_bytes(root + int(n).to_bytes(8, "little") + b"\x00" * 24)
    return _bytes_to_words(root)


def checkpoint_root(epoch: jnp.ndarray, root_bytes: jnp.ndarray) -> jnp.ndarray:
    """Checkpoint container root: H(chunk(epoch), root). `root_bytes` is
    u8[32]."""
    e_chunk = _u64_chunk_words(epoch.reshape(1).astype(jnp.uint64))[0]
    r_words = root_bytes.reshape(8, 4).astype(jnp.uint32)
    r_chunk = (
        (r_words[:, 0] << 24) | (r_words[:, 1] << 16) | (r_words[:, 2] << 8) | r_words[:, 3]
    )
    return _hash_rows(e_chunk[None, :], r_chunk[None, :])[0]


def bitvector4_chunk(bits: jnp.ndarray) -> jnp.ndarray:
    """Bitvector[4] (bool[4]) -> its single SSZ chunk as u32[8] words."""
    byte = (
        bits[0].astype(jnp.uint32)
        | (bits[1].astype(jnp.uint32) << 1)
        | (bits[2].astype(jnp.uint32) << 2)
        | (bits[3].astype(jnp.uint32) << 3)
    )
    chunk = jnp.zeros(8, jnp.uint32)
    return chunk.at[0].set(byte << 24)


def combine_state_root(
    arrays: StateRootArrays, meta: StateRootMeta, dynamic_roots: dict[int, jnp.ndarray]
) -> jnp.ndarray:
    """Write the dynamic roots into their top-level slots and reduce the
    container tree on device."""
    chunks = arrays.top_chunks
    for slot, root in dynamic_roots.items():
        chunks = chunks.at[slot].set(root)
    return tree_root_words(chunks, meta.top_depth)


# ------------------------------------------------------------------ ingest --


def build_static(
    spec, state, prev_part_from_current: bool = True
) -> tuple[StateRootArrays, StateRootMeta]:
    """Harvest the static tree content from an object state (one-time,
    host; per-validator static nodes go through the native C sha core)."""
    import jax

    from eth_consensus_specs_tpu import ssz
    from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
    from eth_consensus_specs_tpu.native import available as native_available, sha256_pairs

    n = len(state.validators)

    def pair_hash_many(data: bytes) -> bytes:
        if native_available():
            return sha256_pairs(data)
        out = []
        for i in range(0, len(data), 64):
            out.append(hash_bytes(data[i : i + 64]))
        return b"".join(out)

    # pubkey roots: H(pk[0:32], pk[32:48] || zeros)
    pk_stream = b"".join(
        bytes(v.pubkey)[:32] + bytes(v.pubkey)[32:48] + b"\x00" * 16
        for v in state.validators
    )
    pk_roots = pair_hash_many(pk_stream)
    # A = H(pubkey_root, withdrawal_credentials)
    a_stream = b"".join(
        pk_roots[i * 32 : (i + 1) * 32] + bytes(v.withdrawal_credentials)
        for i, v in enumerate(state.validators)
    )
    node_a = pair_hash_many(a_stream)

    def epoch_chunk(e: int) -> bytes:
        return int(e).to_bytes(8, "little") + b"\x00" * 24

    c_stream = b"".join(
        epoch_chunk(v.activation_eligibility_epoch) + epoch_chunk(v.activation_epoch)
        for v in state.validators
    )
    d_stream = b"".join(
        epoch_chunk(v.exit_epoch) + epoch_chunk(v.withdrawable_epoch)
        for v in state.validators
    )
    node_c = pair_hash_many(c_stream)
    node_d = pair_hash_many(d_stream)
    f_stream = b"".join(
        node_c[i * 32 : (i + 1) * 32] + node_d[i * 32 : (i + 1) * 32] for i in range(n)
    )
    node_f = pair_hash_many(f_stream)

    slashed_chunks = np.zeros((n, 8), np.uint32)
    for i, v in enumerate(state.validators):
        if v.slashed:
            slashed_chunks[i, 0] = 0x01000000

    fields = list(type(state).fields())
    top_depth = max(len(fields) - 1, 0).bit_length()
    top_chunks = np.zeros((1 << top_depth, 8), np.uint32)
    dynamic_names = {
        "validators",
        "balances",
        "inactivity_scores",
        "previous_epoch_participation",
        "current_epoch_participation",
        "justification_bits",
        "previous_justified_checkpoint",
        "current_justified_checkpoint",
        "finalized_checkpoint",
    }
    dynamic_slots = []
    for i, name in enumerate(fields):
        if name in dynamic_names:
            dynamic_slots.append((i, name))
        else:
            top_chunks[i] = _bytes_to_words(bytes(ssz.hash_tree_root(getattr(state, name))))

    prev_flags = np.array(
        [int(b) for b in state.current_epoch_participation]
        if prev_part_from_current
        else [int(b) for b in state.previous_epoch_participation],
        np.uint8,
    )

    def words(b: bytes, rows: int) -> np.ndarray:
        return np.frombuffer(b, dtype=">u4").astype(np.uint32).reshape(rows, 8)

    arrays = StateRootArrays(
        val_node_a=jax.device_put(jnp.asarray(words(node_a, n))),
        val_node_f=jax.device_put(jnp.asarray(words(node_f, n))),
        slashed_chunk=jax.device_put(jnp.asarray(slashed_chunks)),
        prev_part_flags=jax.device_put(jnp.asarray(prev_flags)),
        top_chunks=jax.device_put(jnp.asarray(top_chunks)),
        zerohashes=jax.device_put(jnp.asarray(zerohash_words(41))),
    )
    meta = StateRootMeta(
        dynamic_slots=tuple(dynamic_slots), n_validators=n, top_depth=top_depth
    )
    return arrays, meta


def synthetic_static(spec, n: int, seed: int = 0) -> tuple[StateRootArrays, StateRootMeta]:
    """Bench/demo static content WITHOUT building an n-validator object
    state: random static nodes, zero small-field chunks — the exact same
    device hash count and tree shape as build_static, minus the one-time
    host harvest. Roots are not meaningful; timings are."""
    import jax

    rng = np.random.default_rng(seed)
    fields = list(spec.BeaconState.fields())
    top_depth = max(len(fields) - 1, 0).bit_length()
    dynamic_names = {
        "validators",
        "balances",
        "inactivity_scores",
        "previous_epoch_participation",
        "current_epoch_participation",
        "justification_bits",
        "previous_justified_checkpoint",
        "current_justified_checkpoint",
        "finalized_checkpoint",
    }
    dynamic_slots = tuple(
        (i, name) for i, name in enumerate(fields) if name in dynamic_names
    )

    def rnd(shape):
        return jax.device_put(
            jnp.asarray(rng.integers(0, 2**32, size=shape, dtype=np.uint64).astype(np.uint32))
        )

    arrays = StateRootArrays(
        val_node_a=rnd((n, 8)),
        val_node_f=rnd((n, 8)),
        slashed_chunk=jax.device_put(jnp.zeros((n, 8), jnp.uint32)),
        prev_part_flags=jax.device_put(
            jnp.asarray(rng.integers(0, 8, size=n, dtype=np.int64).astype(np.uint8))
        ),
        top_chunks=rnd((1 << top_depth, 8)),
        zerohashes=jax.device_put(jnp.asarray(zerohash_words(41))),
    )
    return arrays, StateRootMeta(
        dynamic_slots=dynamic_slots, n_validators=n, top_depth=top_depth
    )


def state_root_real_hashes(meta: StateRootMeta) -> int:
    """Compressions one post_epoch_state_root evaluation executes — the
    honest work count for the span's roofline verdict (mirrors bench.py's
    resident accounting: validator nodes + full-width column trees)."""
    from eth_consensus_specs_tpu.ops.merkle import tree_real_hashes as fullwidth

    n = meta.n_validators
    names = {name for _, name in meta.dynamic_slots}
    hashes = 3 * n + fullwidth(max(n - 1, 0).bit_length())  # validator subtrees + registry
    d_bal = (max(n // 4, 1) - 1).bit_length()
    hashes += fullwidth(d_bal)  # balances
    if "inactivity_scores" in names:
        hashes += fullwidth(d_bal)
    if "previous_epoch_participation" in names:
        hashes += fullwidth((max(n // 32, 1) - 1).bit_length())
    return hashes + (1 << meta.top_depth)


def slot_root_real_hashes(n: int, top_depth: int) -> int:
    """Compressions of one per-slot dirty-path root (balances + both
    participation columns + the top tree) — ONE accounting shared by the
    block_epoch span instrumentation and bench.py's block_epoch section,
    so their roofline verdicts can never disagree on the same timing."""
    from eth_consensus_specs_tpu.ops.merkle import tree_real_hashes as fullwidth

    return (
        fullwidth((max(n // 4, 1) - 1).bit_length())
        + 2 * fullwidth((max(n // 32, 1) - 1).bit_length())
        + (1 << top_depth)
    )


def post_epoch_state_root(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances: jnp.ndarray,
    effective_balance: jnp.ndarray,
    inactivity_scores: jnp.ndarray,
    just,  # JustificationState-like with post-epoch values
) -> jnp.ndarray:
    """The full post-accounting-epoch state root as one device graph."""
    if obs.tracing(balances):
        # composed under an outer jit (parallel/resident.py): the trace
        # runs once per compile — count it, but never clock it as a run
        obs.count("state_root.traces", 1)
        return _post_epoch_state_root_impl(
            arrays, meta, balances, effective_balance, inactivity_scores, just
        )
    real = state_root_real_hashes(meta)

    def _device():
        fault.check("state_root.device")
        with obs.span(
            "state_root.post_epoch", work_bytes=96 * real, n_validators=meta.n_validators
        ) as sp:
            sp.result = out = _post_epoch_state_root_impl(
                arrays, meta, balances, effective_balance, inactivity_scores, just
            )
        return out

    # device-side death (compile/OOM/injected) degrades to the host
    # oracle: the run completes slower rather than not at all
    out = fault.degrade(
        "state_root.device",
        _device,
        lambda: _post_epoch_state_root_host(
            arrays, meta, balances, effective_balance, inactivity_scores, just
        ),
    )
    obs.count("state_root.roots", 1)
    obs.count("state_root.real_hashes", real)
    return out


def state_root_compile_key(meta: StateRootMeta) -> tuple:
    """Shape key the jitted state-root graph compiles under. The serving
    layer groups queued state-root requests by this key so every request
    for the same registry shape hits the same compiled executable, and
    counts first sightings as `serve.compiles` (serve/buckets.py)."""
    return ("state_root", meta.n_validators, meta.top_depth, len(meta.dynamic_slots))


def post_epoch_state_root_host(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances,
    effective_balance,
    inactivity_scores,
    just,
) -> jnp.ndarray:
    """Public host-oracle entry (no XLA anywhere) — what the serving
    layer's whole-batch degradation falls back to on device death."""
    return _post_epoch_state_root_host(
        arrays, meta, balances, effective_balance, inactivity_scores, just
    )


def _post_epoch_state_root_host(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances,
    effective_balance,
    inactivity_scores,
    just,
) -> jnp.ndarray:
    """fault.degrade fallback: the SAME tree through the host oracle's
    native-sha path (ops/state_root_host.py) — no XLA anywhere."""
    import jax

    from eth_consensus_specs_tpu.ops.state_root_host import post_epoch_state_root_np

    arrays_np = jax.tree_util.tree_map(np.asarray, arrays)
    just_np = jax.tree_util.tree_map(np.asarray, just)
    with obs.span("state_root.post_epoch_host", n_validators=meta.n_validators):
        out = post_epoch_state_root_np(
            arrays_np,
            meta,
            np.asarray(balances),
            np.asarray(effective_balance),
            np.asarray(inactivity_scores),
            just_np,
        )
    return jnp.asarray(out)


def _post_epoch_state_root_impl(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances: jnp.ndarray,
    effective_balance: jnp.ndarray,
    inactivity_scores: jnp.ndarray,
    just,
) -> jnp.ndarray:
    n = meta.n_validators
    zh = arrays.zerohashes
    slot_of = {name: i for i, name in meta.dynamic_slots}
    dyn: dict[int, jnp.ndarray] = {}
    dyn[slot_of["validators"]] = validator_registry_root(arrays, n, effective_balance)
    dyn[slot_of["balances"]] = u64_list_root(balances, n, BALANCE_LIMIT_CHUNKS_LOG2, zh)
    if "inactivity_scores" in slot_of:
        dyn[slot_of["inactivity_scores"]] = u64_list_root(
            inactivity_scores, n, BALANCE_LIMIT_CHUNKS_LOG2, zh
        )
    if "previous_epoch_participation" in slot_of:
        dyn[slot_of["previous_epoch_participation"]] = u8_list_root(
            arrays.prev_part_flags, n, PARTICIPATION_LIMIT_CHUNKS_LOG2, zh
        )
        # rotated-in current participation: all zero, length n — a
        # CONSTANT for fixed n, folded at trace time (host hashes), not
        # recomputed as an O(n/32) device tree every epoch
        dyn[slot_of["current_epoch_participation"]] = jnp.asarray(
            _zero_u8_list_root_words(n)
        )
    dyn[slot_of["justification_bits"]] = (
        bitvector4_chunk(just.justification_bits)
        if just.justification_bits.dtype == jnp.bool_
        else bitvector4_chunk(just.justification_bits.astype(bool))
    )
    dyn[slot_of["previous_justified_checkpoint"]] = checkpoint_root(
        just.prev_justified_epoch, just.prev_justified_root
    )
    dyn[slot_of["current_justified_checkpoint"]] = checkpoint_root(
        just.cur_justified_epoch, just.cur_justified_root
    )
    dyn[slot_of["finalized_checkpoint"]] = checkpoint_root(
        just.finalized_epoch, just.finalized_root
    )
    return combine_state_root(arrays, meta, dyn)
