"""Full BeaconState merkleization on device with dirty-path rehash.

SURVEY hard part 3: at 1M validators the reference's (cached) behavior —
full-state rehash per slot through remerkleable — is the top cost of
`state_transition` (reference: specs/phase0/beacon-chain.md:1383-1393 via
utils/hash_function.py). This module keeps the STATE TREE's big regions
device-resident and re-hashes only the paths the accounting epoch
actually dirties:

* per-validator subtrees: of the 8 Validator fields only
  effective_balance changes during accounting, so the static 2/3 of each
  validator's 15-node tree (pubkey root + withdrawal_credentials node;
  the four epoch fields' node) is precomputed ONCE at ingest via the
  native C sha core, and each epoch recomputes just 3 hashes/validator
  on device (B = H(eff_balance, slashed), E = H(A, B), root = H(E, F));
* the big flat columns (balances, inactivity scores, participation) are
  chunked and tree-reduced on device (ops/merkle.tree_root_words), then
  zero-hash-folded to their SSZ limit depth and length-mixed;
* every OTHER state field's root is harvested once at ingest from the
  object tree's cached roots and sits as a static chunk; the top-level
  container combine (~32 chunks) runs on device each epoch.

The result is `hash_tree_root(state)` for the post-accounting state as
PURE device work after one ingest — the north-star shape (BASELINE.json:
epoch-boundary state_transition incl. full state root < 1s @ 1M).

Bit-exactness: tests/test_state_root_device.py compares against
ssz.hash_tree_root on the equivalently-updated object state.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu import fault, obs
from eth_consensus_specs_tpu.ops.merkle import tree_root_words
from eth_consensus_specs_tpu.ops.sha256 import sha256_pair_words

VALIDATOR_REGISTRY_LIMIT_LOG2 = 40  # List[Validator, 2**40]
BALANCE_LIMIT_CHUNKS_LOG2 = 38  # 2**40 u64 -> 2**38 chunks
PARTICIPATION_LIMIT_CHUNKS_LOG2 = 35  # 2**40 bytes -> 2**35 chunks


def _bytes_to_words(b: bytes) -> np.ndarray:
    return np.frombuffer(b, dtype=">u4").astype(np.uint32)


def zerohash_words(max_depth: int) -> np.ndarray:
    """[max_depth+1, 8] u32 — zerohashes[d] as BE words."""
    from eth_consensus_specs_tpu.ssz.merkle import zerohashes

    return np.stack([_bytes_to_words(zerohashes[d]) for d in range(max_depth + 1)])


class StateRootArrays(NamedTuple):
    """Device-resident static tree content (a pure-array pytree, safe to
    pass through jit)."""

    val_node_a: jnp.ndarray  # u32[N, 8]  H(pubkey_root, withdrawal_credentials)
    val_node_f: jnp.ndarray  # u32[N, 8]  H(H(aee, ae), H(exit, withdrawable))
    slashed_chunk: jnp.ndarray  # u32[N, 8] SSZ chunk of `slashed`
    prev_part_flags: jnp.ndarray  # u8[N] participation bytes rotated into prev
    top_chunks: jnp.ndarray  # u32[P, 8] all field roots (static slots filled)
    zerohashes: jnp.ndarray  # u32[41, 8]


class StateRootMeta(NamedTuple):
    """Hashable host-side layout data (closure/static side of the jit)."""

    dynamic_slots: tuple  # ((field index, field name), ...)
    n_validators: int
    top_depth: int


def _u64_chunk_words(vals: jnp.ndarray) -> jnp.ndarray:
    """u64[N] -> SSZ 32-byte chunks as u32[N, 8] BE words (value LE in the
    first 8 bytes of the chunk)."""
    lo = (vals & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (vals >> jnp.uint64(32)).astype(jnp.uint32)

    def bswap(w):
        return (
            ((w & jnp.uint32(0xFF)) << 24)
            | ((w & jnp.uint32(0xFF00)) << 8)
            | ((w >> 8) & jnp.uint32(0xFF00))
            | ((w >> 24) & jnp.uint32(0xFF))
        )

    z = jnp.zeros_like(lo)
    return jnp.stack([bswap(lo), bswap(hi), z, z, z, z, z, z], axis=-1)


def _hash_rows(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """H(a || b) rowwise for u32[..., 8] word chunks."""
    return sha256_pair_words(jnp.concatenate([a, b], axis=-1))


def packed_u64_leaves(vals: jnp.ndarray, n: int) -> jnp.ndarray:
    """u64[n] (n % 4 == 0) -> u32[n//4, 8] SSZ packed chunk words."""
    w = lax.bitcast_convert_type(vals, jnp.uint32).reshape(n // 4, 8)
    return (
        ((w & 0xFF) << 24)
        | ((w & 0xFF00) << 8)
        | ((w >> 8) & 0xFF00)
        | ((w >> 24) & 0xFF)
    )


def packed_u8_leaves(vals: jnp.ndarray, n: int) -> jnp.ndarray:
    """u8[n] (n % 32 == 0) -> u32[n//32, 8] SSZ packed chunk words."""
    w = vals.reshape(n // 32, 8, 4).astype(jnp.uint32)
    return (w[..., 0] << 24) | (w[..., 1] << 16) | (w[..., 2] << 8) | w[..., 3]


def fold_to_limit(root: jnp.ndarray, depth: int, limit_log2: int, zh: jnp.ndarray):
    """Chain a subtree root up to the SSZ limit depth with zero-hash
    siblings (right sibling = zerohashes[d] at each level). One scan
    body instead of limit-depth unrolled compression instances — the
    fold is sequential either way, and the state-root graph carries
    several of these chains (a python loop here put ~25 sha bodies PER
    CHAIN into the jaxpr, the bulk of the full-state compile wall)."""
    if depth >= limit_log2:
        return root

    def step(r, z):
        return _hash_rows(r[None, :], z[None, :])[0], None

    root, _ = lax.scan(step, root, zh[depth:limit_log2])
    return root


def mix_length(root: jnp.ndarray, length: int) -> jnp.ndarray:
    len_chunk = _u64_chunk_words(jnp.full((1,), np.uint64(length), jnp.uint64))[0]
    return _hash_rows(root[None, :], len_chunk[None, :])[0]


def _validator_leaf_rows(
    effective_balance: jnp.ndarray,
    slashed_chunk: jnp.ndarray,
    node_a: jnp.ndarray,
    node_f: jnp.ndarray,
) -> jnp.ndarray:
    """The per-validator root from its static nodes + the dynamic
    effective balance — the 3-hash chain (B = H(eff_chunk, slashed),
    E = H(A, B), root = H(E, F)). ONE implementation: the full path
    applies it to whole columns, the incremental path to the gathered
    dirty rows — editing the Validator leaf derivation in one place
    cannot break full-vs-incremental root parity."""
    eb_chunk = _u64_chunk_words(effective_balance)
    node_b = _hash_rows(eb_chunk, slashed_chunk)
    node_e = _hash_rows(node_a, node_b)
    return _hash_rows(node_e, node_f)


def validator_registry_root(
    arrays: StateRootArrays, n: int, effective_balance: jnp.ndarray
) -> jnp.ndarray:
    """List[Validator] root from the static nodes + the dynamic
    effective-balance column: 3 hashes per validator + the leaf tree."""
    roots = _validator_leaf_rows(
        effective_balance, arrays.slashed_chunk, arrays.val_node_a, arrays.val_node_f
    )  # [N, 8] validator roots
    depth = max(n - 1, 0).bit_length()
    sub = tree_root_words(_pad_pow2(roots, depth), depth)
    full = fold_to_limit(sub, depth, VALIDATOR_REGISTRY_LIMIT_LOG2, arrays.zerohashes)
    return mix_length(full, n)


def _pad_pow2(leaves: jnp.ndarray, depth: int) -> jnp.ndarray:
    pad = (1 << depth) - leaves.shape[0]
    if pad:
        leaves = jnp.concatenate([leaves, jnp.zeros((pad, 8), jnp.uint32)], axis=0)
    return leaves


def u64_list_root(
    vals: jnp.ndarray, n: int, limit_chunks_log2: int, zh: jnp.ndarray
) -> jnp.ndarray:
    if n % 4:
        vals = jnp.concatenate([vals, jnp.zeros(4 - n % 4, jnp.uint64)])
    chunks = (n + 3) // 4
    leaves = packed_u64_leaves(vals, vals.shape[0])
    depth = max(chunks - 1, 0).bit_length() if n else 0
    sub = tree_root_words(_pad_pow2(leaves, depth), depth)
    return mix_length(fold_to_limit(sub, depth, limit_chunks_log2, zh), n)


def u8_list_root(
    vals: jnp.ndarray, n: int, limit_chunks_log2: int, zh: jnp.ndarray
) -> jnp.ndarray:
    if n % 32:
        vals = jnp.concatenate([vals, jnp.zeros(32 - n % 32, jnp.uint8)])
    chunks = (n + 31) // 32
    leaves = packed_u8_leaves(vals, vals.shape[0])
    depth = max(chunks - 1, 0).bit_length() if n else 0
    sub = tree_root_words(_pad_pow2(leaves, depth), depth)
    return mix_length(fold_to_limit(sub, depth, limit_chunks_log2, zh), n)


def _zero_u8_list_root_words(n: int) -> np.ndarray:
    """Host-computed root words of an all-zero List[uint8-ish, 2**40] of
    length n (the rotated-in current participation): zero subtree =
    zerohashes[depth], folded to the limit depth, length-mixed."""
    from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
    from eth_consensus_specs_tpu.ssz.merkle import zerohashes

    chunks = (n + 31) // 32
    depth = max(chunks - 1, 0).bit_length() if n else 0
    root = zerohashes[depth]
    for d in range(depth, PARTICIPATION_LIMIT_CHUNKS_LOG2):
        root = hash_bytes(root + zerohashes[d])
    root = hash_bytes(root + int(n).to_bytes(8, "little") + b"\x00" * 24)
    return _bytes_to_words(root)


def checkpoint_root(epoch: jnp.ndarray, root_bytes: jnp.ndarray) -> jnp.ndarray:
    """Checkpoint container root: H(chunk(epoch), root). `root_bytes` is
    u8[32]."""
    e_chunk = _u64_chunk_words(epoch.reshape(1).astype(jnp.uint64))[0]
    r_words = root_bytes.reshape(8, 4).astype(jnp.uint32)
    r_chunk = (
        (r_words[:, 0] << 24) | (r_words[:, 1] << 16) | (r_words[:, 2] << 8) | r_words[:, 3]
    )
    return _hash_rows(e_chunk[None, :], r_chunk[None, :])[0]


def bitvector4_chunk(bits: jnp.ndarray) -> jnp.ndarray:
    """Bitvector[4] (bool[4]) -> its single SSZ chunk as u32[8] words."""
    byte = (
        bits[0].astype(jnp.uint32)
        | (bits[1].astype(jnp.uint32) << 1)
        | (bits[2].astype(jnp.uint32) << 2)
        | (bits[3].astype(jnp.uint32) << 3)
    )
    chunk = jnp.zeros(8, jnp.uint32)
    return chunk.at[0].set(byte << 24)


def combine_state_root(
    arrays: StateRootArrays, meta: StateRootMeta, dynamic_roots: dict[int, jnp.ndarray]
) -> jnp.ndarray:
    """Write the dynamic roots into their top-level slots and reduce the
    container tree on device."""
    chunks = arrays.top_chunks
    for slot, root in dynamic_roots.items():
        chunks = chunks.at[slot].set(root)
    return tree_root_words(chunks, meta.top_depth)


# ------------------------------------------------------------------ ingest --


def build_static(
    spec, state, prev_part_from_current: bool = True
) -> tuple[StateRootArrays, StateRootMeta]:
    """Harvest the static tree content from an object state (one-time,
    host; per-validator static nodes go through the native C sha core)."""
    import jax

    from eth_consensus_specs_tpu import ssz
    from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
    from eth_consensus_specs_tpu.native import available as native_available, sha256_pairs

    n = len(state.validators)

    def pair_hash_many(data: bytes) -> bytes:
        if native_available():
            return sha256_pairs(data)
        out = []
        for i in range(0, len(data), 64):
            out.append(hash_bytes(data[i : i + 64]))
        return b"".join(out)

    # pubkey roots: H(pk[0:32], pk[32:48] || zeros)
    pk_stream = b"".join(
        bytes(v.pubkey)[:32] + bytes(v.pubkey)[32:48] + b"\x00" * 16
        for v in state.validators
    )
    pk_roots = pair_hash_many(pk_stream)
    # A = H(pubkey_root, withdrawal_credentials)
    a_stream = b"".join(
        pk_roots[i * 32 : (i + 1) * 32] + bytes(v.withdrawal_credentials)
        for i, v in enumerate(state.validators)
    )
    node_a = pair_hash_many(a_stream)

    def epoch_chunk(e: int) -> bytes:
        return int(e).to_bytes(8, "little") + b"\x00" * 24

    c_stream = b"".join(
        epoch_chunk(v.activation_eligibility_epoch) + epoch_chunk(v.activation_epoch)
        for v in state.validators
    )
    d_stream = b"".join(
        epoch_chunk(v.exit_epoch) + epoch_chunk(v.withdrawable_epoch)
        for v in state.validators
    )
    node_c = pair_hash_many(c_stream)
    node_d = pair_hash_many(d_stream)
    f_stream = b"".join(
        node_c[i * 32 : (i + 1) * 32] + node_d[i * 32 : (i + 1) * 32] for i in range(n)
    )
    node_f = pair_hash_many(f_stream)

    slashed_chunks = np.zeros((n, 8), np.uint32)
    for i, v in enumerate(state.validators):
        if v.slashed:
            slashed_chunks[i, 0] = 0x01000000

    fields = list(type(state).fields())
    top_depth = max(len(fields) - 1, 0).bit_length()
    top_chunks = np.zeros((1 << top_depth, 8), np.uint32)
    dynamic_names = {
        "validators",
        "balances",
        "inactivity_scores",
        "previous_epoch_participation",
        "current_epoch_participation",
        "justification_bits",
        "previous_justified_checkpoint",
        "current_justified_checkpoint",
        "finalized_checkpoint",
    }
    dynamic_slots = []
    for i, name in enumerate(fields):
        if name in dynamic_names:
            dynamic_slots.append((i, name))
        else:
            top_chunks[i] = _bytes_to_words(bytes(ssz.hash_tree_root(getattr(state, name))))

    prev_flags = np.array(
        [int(b) for b in state.current_epoch_participation]
        if prev_part_from_current
        else [int(b) for b in state.previous_epoch_participation],
        np.uint8,
    )

    def words(b: bytes, rows: int) -> np.ndarray:
        return np.frombuffer(b, dtype=">u4").astype(np.uint32).reshape(rows, 8)

    arrays = StateRootArrays(
        val_node_a=jax.device_put(jnp.asarray(words(node_a, n))),
        val_node_f=jax.device_put(jnp.asarray(words(node_f, n))),
        slashed_chunk=jax.device_put(jnp.asarray(slashed_chunks)),
        prev_part_flags=jax.device_put(jnp.asarray(prev_flags)),
        top_chunks=jax.device_put(jnp.asarray(top_chunks)),
        zerohashes=jax.device_put(jnp.asarray(zerohash_words(41))),
    )
    try:
        from eth_consensus_specs_tpu.obs import ledger

        ledger.register(
            "resident_state",
            f"static_tree-{n}",
            sum(int(a.nbytes) for a in jax.tree_util.tree_leaves(arrays)),
        )
    except Exception:
        pass
    meta = StateRootMeta(
        dynamic_slots=tuple(dynamic_slots), n_validators=n, top_depth=top_depth
    )
    return arrays, meta


def synthetic_static(spec, n: int, seed: int = 0) -> tuple[StateRootArrays, StateRootMeta]:
    """Bench/demo static content WITHOUT building an n-validator object
    state: random static nodes, zero small-field chunks — the exact same
    device hash count and tree shape as build_static, minus the one-time
    host harvest. Roots are not meaningful; timings are."""
    import jax

    rng = np.random.default_rng(seed)
    fields = list(spec.BeaconState.fields())
    top_depth = max(len(fields) - 1, 0).bit_length()
    dynamic_names = {
        "validators",
        "balances",
        "inactivity_scores",
        "previous_epoch_participation",
        "current_epoch_participation",
        "justification_bits",
        "previous_justified_checkpoint",
        "current_justified_checkpoint",
        "finalized_checkpoint",
    }
    dynamic_slots = tuple(
        (i, name) for i, name in enumerate(fields) if name in dynamic_names
    )

    def rnd(shape):
        return jax.device_put(
            jnp.asarray(rng.integers(0, 2**32, size=shape, dtype=np.uint64).astype(np.uint32))
        )

    arrays = StateRootArrays(
        val_node_a=rnd((n, 8)),
        val_node_f=rnd((n, 8)),
        slashed_chunk=jax.device_put(jnp.zeros((n, 8), jnp.uint32)),
        prev_part_flags=jax.device_put(
            jnp.asarray(rng.integers(0, 8, size=n, dtype=np.int64).astype(np.uint8))
        ),
        top_chunks=rnd((1 << top_depth, 8)),
        zerohashes=jax.device_put(jnp.asarray(zerohash_words(41))),
    )
    try:
        # creation-site HBM booking (obs/ledger.py): this static tree is
        # resident for as long as the caller keeps it — bench processes
        # hold it across the whole run
        from eth_consensus_specs_tpu.obs import ledger

        ledger.register(
            "resident_state",
            f"static_tree_synthetic-{n}",
            sum(int(a.nbytes) for a in jax.tree_util.tree_leaves(arrays)),
        )
    except Exception:
        pass
    return arrays, StateRootMeta(
        dynamic_slots=dynamic_slots, n_validators=n, top_depth=top_depth
    )


def state_root_real_hashes(meta: StateRootMeta) -> int:
    """Compressions one post_epoch_state_root evaluation executes — the
    honest work count for the span's roofline verdict (mirrors bench.py's
    resident accounting: validator nodes + full-width column trees)."""
    from eth_consensus_specs_tpu.ops.merkle import tree_real_hashes as fullwidth

    n = meta.n_validators
    names = {name for _, name in meta.dynamic_slots}
    hashes = 3 * n + fullwidth(max(n - 1, 0).bit_length())  # validator subtrees + registry
    d_bal = (max(n // 4, 1) - 1).bit_length()
    hashes += fullwidth(d_bal)  # balances
    if "inactivity_scores" in names:
        hashes += fullwidth(d_bal)
    if "previous_epoch_participation" in names:
        hashes += fullwidth((max(n // 32, 1) - 1).bit_length())
    return hashes + (1 << meta.top_depth)


def slot_root_real_hashes(n: int, top_depth: int) -> int:
    """Compressions of one per-slot dirty-path root (balances + both
    participation columns + the top tree) — ONE accounting shared by the
    block_epoch span instrumentation and bench.py's block_epoch section,
    so their roofline verdicts can never disagree on the same timing."""
    from eth_consensus_specs_tpu.ops.merkle import tree_real_hashes as fullwidth

    return (
        fullwidth((max(n // 4, 1) - 1).bit_length())
        + 2 * fullwidth((max(n // 32, 1) - 1).bit_length())
        + (1 << top_depth)
    )


def post_epoch_state_root(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances: jnp.ndarray,
    effective_balance: jnp.ndarray,
    inactivity_scores: jnp.ndarray,
    just,  # JustificationState-like with post-epoch values
) -> jnp.ndarray:
    """The full post-accounting-epoch state root as one device graph."""
    if obs.tracing(balances):
        # composed under an outer jit (parallel/resident.py): the trace
        # runs once per compile — count it, but never clock it as a run
        obs.count("state_root.traces", 1)
        return _post_epoch_state_root_impl(
            arrays, meta, balances, effective_balance, inactivity_scores, just
        )
    real = state_root_real_hashes(meta)

    def _device():
        fault.check("state_root.device")
        with obs.span(
            "state_root.post_epoch", work_bytes=96 * real, n_validators=meta.n_validators
        ) as sp:
            sp.result = out = _post_epoch_state_root_impl(
                arrays, meta, balances, effective_balance, inactivity_scores, just
            )
        return out

    # device-side death (compile/OOM/injected) degrades to the host
    # oracle: the run completes slower rather than not at all
    out = fault.degrade(
        "state_root.device",
        _device,
        lambda: _post_epoch_state_root_host(
            arrays, meta, balances, effective_balance, inactivity_scores, just
        ),
    )
    obs.count("state_root.roots", 1)
    obs.count("state_root.real_hashes", real)
    return out


def state_root_compile_key(meta: StateRootMeta) -> tuple:
    """Shape key the jitted state-root graph compiles under. The serving
    layer groups queued state-root requests by this key so every request
    for the same registry shape hits the same compiled executable, and
    counts first sightings as `serve.compiles` (serve/buckets.py)."""
    return ("state_root", meta.n_validators, meta.top_depth, len(meta.dynamic_slots))


def post_epoch_state_root_host(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances,
    effective_balance,
    inactivity_scores,
    just,
) -> jnp.ndarray:
    """Public host-oracle entry (no XLA anywhere) — what the serving
    layer's whole-batch degradation falls back to on device death."""
    return _post_epoch_state_root_host(
        arrays, meta, balances, effective_balance, inactivity_scores, just
    )


def _post_epoch_state_root_host(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances,
    effective_balance,
    inactivity_scores,
    just,
) -> jnp.ndarray:
    """fault.degrade fallback: the SAME tree through the host oracle's
    native-sha path (ops/state_root_host.py) — no XLA anywhere."""
    import jax

    from eth_consensus_specs_tpu.ops.state_root_host import post_epoch_state_root_np

    arrays_np = jax.tree_util.tree_map(np.asarray, arrays)
    just_np = jax.tree_util.tree_map(np.asarray, just)
    with obs.span("state_root.post_epoch_host", n_validators=meta.n_validators):
        out = post_epoch_state_root_np(
            arrays_np,
            meta,
            np.asarray(balances),
            np.asarray(effective_balance),
            np.asarray(inactivity_scores),
            just_np,
        )
    return jnp.asarray(out)


def _post_epoch_state_root_impl(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    balances: jnp.ndarray,
    effective_balance: jnp.ndarray,
    inactivity_scores: jnp.ndarray,
    just,
) -> jnp.ndarray:
    n = meta.n_validators
    zh = arrays.zerohashes
    slot_of = {name: i for i, name in meta.dynamic_slots}
    dyn: dict[int, jnp.ndarray] = {}
    dyn[slot_of["validators"]] = validator_registry_root(arrays, n, effective_balance)
    dyn[slot_of["balances"]] = u64_list_root(balances, n, BALANCE_LIMIT_CHUNKS_LOG2, zh)
    if "inactivity_scores" in slot_of:
        dyn[slot_of["inactivity_scores"]] = u64_list_root(
            inactivity_scores, n, BALANCE_LIMIT_CHUNKS_LOG2, zh
        )
    if "previous_epoch_participation" in slot_of:
        dyn[slot_of["previous_epoch_participation"]] = u8_list_root(
            arrays.prev_part_flags, n, PARTICIPATION_LIMIT_CHUNKS_LOG2, zh
        )
        # rotated-in current participation: all zero, length n — a
        # CONSTANT for fixed n, folded at trace time (host hashes), not
        # recomputed as an O(n/32) device tree every epoch
        dyn[slot_of["current_epoch_participation"]] = jnp.asarray(
            _zero_u8_list_root_words(n)
        )
    dyn.update(_small_dynamic_roots(slot_of, just))
    return combine_state_root(arrays, meta, dyn)


def _small_dynamic_roots(slot_of: dict, just) -> dict:
    """The O(1)-sized dynamic roots (justification bits + the three
    checkpoints) — ONE implementation shared by the full recompute and
    the incremental path, so the two can never disagree on the cheap
    fields while differing on the trees."""
    dyn = {
        slot_of["justification_bits"]: (
            bitvector4_chunk(just.justification_bits)
            if just.justification_bits.dtype == jnp.bool_
            else bitvector4_chunk(just.justification_bits.astype(bool))
        ),
        slot_of["previous_justified_checkpoint"]: checkpoint_root(
            just.prev_justified_epoch, just.prev_justified_root
        ),
        slot_of["current_justified_checkpoint"]: checkpoint_root(
            just.cur_justified_epoch, just.cur_justified_root
        ),
        slot_of["finalized_checkpoint"]: checkpoint_root(
            just.finalized_epoch, just.finalized_root
        ),
    }
    return dyn


# --------------------------------------------- incremental (forest) path --
#
# The full path above re-hashes every tree each epoch. The incremental
# path keeps the three big subtrees resident as merkle_inc forests (ALL
# internal levels in HBM, donated buffers) and re-hashes only the
# O(dirty x depth) ancestor paths the accounting epoch actually
# dirtied: effective balances move only on hysteresis crossings, the
# balance/score columns diff chunk-wise, and the participation list is
# STATIC inside the resident loop (its list root is computed once at
# forest build and reused — the full path re-treed it every epoch for
# the same value). Roots are bit-identical to the full recompute by
# construction: same tree shapes, same pads, same folds, the shared
# _small_dynamic_roots, the shared combine.


class StateForest(NamedTuple):
    """Device-resident incremental tree state (a pure-array pytree; the
    resident runner donates every leaf so epoch N+1 updates epoch N's
    buffers in place)."""

    val_nodes: jnp.ndarray  # u32[S, 2^(dvl+1)-1, 8] validator-root forest
    bal_nodes: jnp.ndarray  # u32[S, 2^(dbl+1)-1, 8] balance-chunk forest
    inact_nodes: jnp.ndarray | None  # scores forest (None pre-altair)
    part_root: jnp.ndarray  # u32[8] previous-participation LIST root (static)


class ForestPlan(NamedTuple):
    """Hashable static plan of an incremental forest — part of the
    resident compile key. Capacities/thresholds are PER SHARD."""

    depth_val: int  # validator-leaf tree depth (global)
    depth_bal: int  # u64-chunk tree depth (global; scores share it)
    shards: int  # pow2 leaf-axis shard count (1 = single device)
    cap_val: int  # dirty-capacity compile bucket, validator leaves
    cap_bal: int  # dirty-capacity compile bucket, chunk leaves
    dense_val: int  # dirty count past which the dense rebuild wins
    dense_bal: int
    has_inact: bool  # spec has inactivity_scores (altair+)


def forest_plan(meta: StateRootMeta, mesh=None, dirty_cap: int | None = None) -> ForestPlan:
    """Plan an incremental forest for this registry shape: tree depths
    from the leaf counts, shard count from the mesh (pow2-dividing or
    1), dirty capacities from the serve bucket grid
    (serve/buckets.inc_dirty_buckets — env-snapshotted HERE, never
    inside a trace), dense-fallback thresholds from the measured
    crossover model (buckets.inc_dense_count). `dirty_cap` overrides
    the default per-epoch dirty-leaf hint (n/256)."""
    from eth_consensus_specs_tpu.ops import merkle_inc
    from eth_consensus_specs_tpu.serve import buckets

    n = meta.n_validators
    depth_val = max(n - 1, 0).bit_length()
    chunks = (n + 3) // 4
    depth_bal = max(chunks - 1, 0).bit_length()
    shards = merkle_inc.forest_shards(min(depth_val, depth_bal), mesh)
    slog2 = (shards - 1).bit_length()
    hint = int(dirty_cap) if dirty_cap else max(n >> 8, 8)
    cap_val = min(buckets.inc_dirty_bucket(-(-hint // shards)), (1 << depth_val) // shards)
    cap_bal = min(
        buckets.inc_dirty_bucket(-(-max(hint // 4, 1) // shards)),
        (1 << depth_bal) // shards,
    )
    names = {name for _, name in meta.dynamic_slots}
    return ForestPlan(
        depth_val=depth_val,
        depth_bal=depth_bal,
        shards=shards,
        cap_val=cap_val,
        cap_bal=cap_bal,
        dense_val=buckets.inc_dense_count(depth_val - slog2, cap_val, leaf_hashes=3),
        dense_bal=buckets.inc_dense_count(depth_bal - slog2, cap_bal),
        has_inact="inactivity_scores" in names,
    )


def _u64_chunk_leaves(vals: jnp.ndarray, n: int, depth: int) -> jnp.ndarray:
    """u64[n] column -> u32[2^depth, 8] packed SSZ chunk leaf level
    (zero pads past the live chunks — the same virtual padding the full
    path's _pad_pow2 applies)."""
    if n % 4:
        vals = jnp.concatenate([vals, jnp.zeros(4 - n % 4, jnp.uint64)])
    leaves = packed_u64_leaves(vals, vals.shape[0])
    return _pad_pow2(leaves, depth)


def _pad_col(vals: jnp.ndarray, cap: int) -> jnp.ndarray:
    pad = cap - vals.shape[0]
    if pad:
        vals = jnp.concatenate([vals, jnp.zeros((pad, *vals.shape[1:]), vals.dtype)])
    return vals


def _validator_leaf_inputs(
    arrays: StateRootArrays, n: int, effective_balance: jnp.ndarray, plan: ForestPlan
) -> tuple:
    """The sharded per-leaf sources of the validator-root leaves: the
    new effective balances plus the static nodes, padded to the leaf
    level and reshaped [S, Ll, ...]."""
    lv = 1 << plan.depth_val
    s = plan.shards
    live = jnp.arange(lv, dtype=jnp.int32) < jnp.int32(n)
    return (
        _pad_col(effective_balance, lv).reshape(s, lv // s),
        _pad_col(arrays.slashed_chunk, lv).reshape(s, lv // s, 8),
        _pad_col(arrays.val_node_a, lv).reshape(s, lv // s, 8),
        _pad_col(arrays.val_node_f, lv).reshape(s, lv // s, 8),
        live.reshape(s, lv // s),
    )


def _validator_leaf_fn(inputs: tuple, idx: jnp.ndarray) -> jnp.ndarray:
    """Validator-root leaves at the given (shard-local) indices — the
    SHARED _validator_leaf_rows chain on the gathered rows; pad indices
    past the registry produce the SSZ zero chunk, matching the full
    path's _pad_pow2."""
    eff_l, slashed_l, a_l, f_l, live_l = inputs
    leaf = _validator_leaf_rows(eff_l[idx], slashed_l[idx], a_l[idx], f_l[idx])
    return jnp.where(live_l[idx][:, None], leaf, jnp.zeros_like(leaf))


def build_state_forest(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    plan: ForestPlan,
    balances: jnp.ndarray,
    effective_balance: jnp.ndarray,
    inactivity_scores: jnp.ndarray,
) -> StateForest:
    """One-time forest ingest (traceable; jit it once per shape): every
    validator root + all internal levels of the three big trees, plus
    the static previous-participation list root."""
    from eth_consensus_specs_tpu.ops import merkle_inc

    n = meta.n_validators
    s = plan.shards
    lv = 1 << plan.depth_val
    inputs = _validator_leaf_inputs(arrays, n, effective_balance, plan)
    flat = tuple(a.reshape(-1, *a.shape[2:]) for a in inputs)
    val_leaves = _validator_leaf_fn(flat, jnp.arange(lv, dtype=jnp.int32))
    val_nodes = merkle_inc.build_forest(val_leaves, s)
    bal_nodes = merkle_inc.build_forest(
        _u64_chunk_leaves(balances, n, plan.depth_bal), s
    )
    inact_nodes = None
    if plan.has_inact:
        inact_nodes = merkle_inc.build_forest(
            _u64_chunk_leaves(inactivity_scores, n, plan.depth_bal), s
        )
    part_root = u8_list_root(
        arrays.prev_part_flags, n, PARTICIPATION_LIMIT_CHUNKS_LOG2, arrays.zerohashes
    )
    return StateForest(
        val_nodes=val_nodes,
        bal_nodes=bal_nodes,
        inact_nodes=inact_nodes,
        part_root=part_root,
    )


def state_root_inc_real_hashes(meta: StateRootMeta, plan: ForestPlan) -> int:
    """Compressions one INCREMENTAL post-epoch root executes under the
    capacity model — the honest dirty-path node count for roofline /
    work-bytes accounting. Per tree the kernel runs either the sparse
    path (exactly cap x (depth + leaf hashes) compressions, padding
    duplicates included) or the dense rebuild; the static model takes
    the MINIMUM of the two, so implied traffic is never overstated (a
    dense epoch does more work than claimed, never less roofline-legal
    work). Folds, length mixes, checkpoints, and the top combine are
    counted exactly like state_root_real_hashes."""
    from eth_consensus_specs_tpu.ops import merkle_inc
    from eth_consensus_specs_tpu.ops.merkle import tree_real_hashes as fullwidth

    n = meta.n_validators
    s = plan.shards
    slog2 = (s - 1).bit_length()

    def tree_cost(depth: int, cap: int, leaf_hashes: int, dense_leaf_total: int) -> int:
        sparse = s * merkle_inc.inc_update_hashes(depth - slog2, cap, leaf_hashes)
        dense = fullwidth(depth - slog2) * s + dense_leaf_total
        return min(sparse, dense) + max(s - 1, 0)  # + the top combine

    hashes = tree_cost(plan.depth_val, plan.cap_val, 3, 3 * n)
    hashes += tree_cost(plan.depth_bal, plan.cap_bal, 0, 0)
    folds = (VALIDATOR_REGISTRY_LIMIT_LOG2 - plan.depth_val) + (
        BALANCE_LIMIT_CHUNKS_LOG2 - plan.depth_bal
    )
    mixes = 2
    if plan.has_inact:
        hashes += tree_cost(plan.depth_bal, plan.cap_bal, 0, 0)
        folds += BALANCE_LIMIT_CHUNKS_LOG2 - plan.depth_bal
        mixes += 1
    return hashes + folds + mixes + 3 + (1 << meta.top_depth)


def post_epoch_state_root_inc(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    plan: ForestPlan,
    forest: StateForest,
    old_balances: jnp.ndarray,
    old_effective_balance: jnp.ndarray,
    old_inactivity_scores: jnp.ndarray,
    balances: jnp.ndarray,
    effective_balance: jnp.ndarray,
    inactivity_scores: jnp.ndarray,
    just,
    mesh=None,
) -> tuple[StateForest, jnp.ndarray]:
    """The incremental full post-epoch state root (traceable; composes
    under the resident epoch jit). Diffs old vs new columns into
    per-tree dirty masks, applies them through the forest kernels
    (sparse path rehash or dense rebuild, per shard), and combines the
    same top-level container the full path does. Returns (forest, root)
    with root bit-identical to post_epoch_state_root on the same
    columns."""
    from eth_consensus_specs_tpu.ops import merkle_inc

    n = meta.n_validators
    s = plan.shards
    zh = arrays.zerohashes
    slot_of = {name: i for i, name in meta.dynamic_slots}
    dyn: dict[int, jnp.ndarray] = {}

    # -- validator registry: dirty = hysteresis crossings --------------
    lv = 1 << plan.depth_val
    mask_val = _pad_col(old_effective_balance != effective_balance, lv)
    inputs = _validator_leaf_inputs(arrays, n, effective_balance, plan)
    val_nodes, sub_val = merkle_inc.forest_apply(
        forest.val_nodes,
        mask_val.reshape(s, lv // s),
        inputs,
        _validator_leaf_fn,
        plan.cap_val,
        plan.dense_val,
        mesh=mesh if s > 1 else None,
    )
    full = fold_to_limit(sub_val, plan.depth_val, VALIDATOR_REGISTRY_LIMIT_LOG2, zh)
    dyn[slot_of["validators"]] = mix_length(full, n)

    # -- u64 list columns: chunk-wise diff ------------------------------
    def u64_tree(nodes, old_vals, new_vals):
        old_leaves = _u64_chunk_leaves(old_vals, n, plan.depth_bal)
        new_leaves = _u64_chunk_leaves(new_vals, n, plan.depth_bal)
        mask = jnp.any(old_leaves != new_leaves, axis=-1)
        lb = 1 << plan.depth_bal
        nodes, sub = merkle_inc.forest_apply(
            nodes,
            mask.reshape(s, lb // s),
            (new_leaves.reshape(s, lb // s, 8),),
            lambda inputs, idx: inputs[0][idx],
            plan.cap_bal,
            plan.dense_bal,
            mesh=mesh if s > 1 else None,
        )
        full = fold_to_limit(sub, plan.depth_bal, BALANCE_LIMIT_CHUNKS_LOG2, zh)
        return nodes, mix_length(full, n)

    bal_nodes, dyn[slot_of["balances"]] = u64_tree(
        forest.bal_nodes, old_balances, balances
    )
    inact_nodes = forest.inact_nodes
    if plan.has_inact and "inactivity_scores" in slot_of:
        inact_nodes, dyn[slot_of["inactivity_scores"]] = u64_tree(
            forest.inact_nodes, old_inactivity_scores, inactivity_scores
        )

    # -- static-in-the-loop participation lists -------------------------
    if "previous_epoch_participation" in slot_of:
        dyn[slot_of["previous_epoch_participation"]] = forest.part_root
        dyn[slot_of["current_epoch_participation"]] = jnp.asarray(
            _zero_u8_list_root_words(n)
        )

    dyn.update(_small_dynamic_roots(slot_of, just))
    forest = StateForest(
        val_nodes=val_nodes,
        bal_nodes=bal_nodes,
        inact_nodes=inact_nodes,
        part_root=forest.part_root,
    )
    return forest, combine_state_root(arrays, meta, dyn)


def state_root_from_forest(
    arrays: StateRootArrays,
    meta: StateRootMeta,
    plan: ForestPlan,
    forest: StateForest,
    just,
) -> jnp.ndarray:
    """The full post-epoch state root recomputed from a RESIDENT forest
    with ZERO dirty work (traceable) — the digest gate checkpoint
    manifests and restore verification share with the incremental epoch
    path. Same folds, same length mixes, same _small_dynamic_roots,
    same top combine as post_epoch_state_root_inc, so a root computed
    here bit-matches the one the resident chain would have produced on
    the same forest — which is exactly what lets a restore REFUSE to
    serve a forest whose recomputed root disagrees with its manifest."""
    from eth_consensus_specs_tpu.ops import merkle_inc

    n = meta.n_validators
    zh = arrays.zerohashes
    slot_of = {name: i for i, name in meta.dynamic_slots}
    dyn: dict[int, jnp.ndarray] = {}

    sub_val = merkle_inc.forest_root(forest.val_nodes)
    full = fold_to_limit(sub_val, plan.depth_val, VALIDATOR_REGISTRY_LIMIT_LOG2, zh)
    dyn[slot_of["validators"]] = mix_length(full, n)

    sub_bal = merkle_inc.forest_root(forest.bal_nodes)
    dyn[slot_of["balances"]] = mix_length(
        fold_to_limit(sub_bal, plan.depth_bal, BALANCE_LIMIT_CHUNKS_LOG2, zh), n
    )
    if plan.has_inact and "inactivity_scores" in slot_of:
        sub_in = merkle_inc.forest_root(forest.inact_nodes)
        dyn[slot_of["inactivity_scores"]] = mix_length(
            fold_to_limit(sub_in, plan.depth_bal, BALANCE_LIMIT_CHUNKS_LOG2, zh), n
        )
    if "previous_epoch_participation" in slot_of:
        dyn[slot_of["previous_epoch_participation"]] = forest.part_root
        dyn[slot_of["current_epoch_participation"]] = jnp.asarray(
            _zero_u8_list_root_words(n)
        )
    dyn.update(_small_dynamic_roots(slot_of, just))
    return combine_state_root(arrays, meta, dyn)
