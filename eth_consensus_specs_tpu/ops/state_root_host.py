"""Host oracle for the device state-root pipeline (ops/state_root.py).

Same tree, different engine: numpy word-wrangling + the native C SHA-256
core (SHA-NI when the host has it), no XLA anywhere in the hash path.
Purpose is CORRECTNESS-COUPLED benchmark timing (round-4 verdict weak #1:
device numbers were published without any check that the device actually
did the work) and an independent leg for tests: device result ==
host-oracle result on the SAME inputs, or the number is not published.

The reference's equivalent of this oracle is its per-node hashlib path
(reference: tests/core/pyspec/eth2spec/utils/merkle_minimal.py:47-91 and
hash_function.py:8-9); the functions here mirror ops/state_root.py
one-for-one so a disagreement localizes the divergent subtree.
"""

from __future__ import annotations

import numpy as np

from eth_consensus_specs_tpu.ops.state_root import (
    BALANCE_LIMIT_CHUNKS_LOG2,
    PARTICIPATION_LIMIT_CHUNKS_LOG2,
    VALIDATOR_REGISTRY_LIMIT_LOG2,
    StateRootMeta,
    _zero_u8_list_root_words,
    zerohash_words,
)


def _hash_pairs_np(msgs_words: np.ndarray) -> np.ndarray:
    """u32[N, 16] word rows (one 64-byte message per row, BE words) ->
    u32[N, 8] digest word rows, through the native sha core with a
    hashlib fallback."""
    import hashlib

    from eth_consensus_specs_tpu import native

    data = np.ascontiguousarray(msgs_words.astype(">u4")).tobytes()
    if native.available():
        out = native.sha256_pairs(data)
    else:
        out = b"".join(
            hashlib.sha256(data[i : i + 64]).digest() for i in range(0, len(data), 64)
        )
    return np.frombuffer(out, dtype=">u4").astype(np.uint32).reshape(-1, 8)


def hash_rows_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """H(a || b) rowwise for u32[N, 8] chunks."""
    return _hash_pairs_np(np.concatenate([a, b], axis=-1).reshape(-1, 16))


def tree_root_np(leaves: np.ndarray, depth: int) -> np.ndarray:
    """Exact logical Merkle reduction of u32[2**depth, 8] -> u32[8]."""
    assert leaves.shape[0] == 1 << depth
    buf = leaves
    for _ in range(depth):
        buf = hash_rows_np(buf[0::2], buf[1::2])
    return buf[0]


def tree_root_chain_np(
    base: np.ndarray, depth: int, chain: int, salt: np.ndarray
) -> np.ndarray:
    """Host recompute of the bench's chained device tree (bench.py tree
    section): `chain` iterations of root = tree(base ^ root), starting
    from the salt words.  Only the LOGICAL nodes are hashed — the device
    kernel's full-width overhead never reaches the root value."""
    acc = salt.astype(np.uint32)
    for _ in range(chain):
        acc = tree_root_np(base ^ acc[None, :], depth)
    return acc


def pad_pow2_np(leaves: np.ndarray, depth: int) -> np.ndarray:
    pad = (1 << depth) - leaves.shape[0]
    if pad:
        leaves = np.concatenate([leaves, np.zeros((pad, 8), np.uint32)], axis=0)
    return leaves


def u64_chunk_words_np(val: int) -> np.ndarray:
    b = int(val).to_bytes(8, "little") + b"\x00" * 24
    return np.frombuffer(b, dtype=">u4").astype(np.uint32)


def packed_u64_leaves_np(vals: np.ndarray) -> np.ndarray:
    """u64[n] -> u32[ceil(n/4), 8] SSZ packed chunk words (BE)."""
    n = vals.shape[0]
    if n % 4:
        vals = np.concatenate([vals, np.zeros(4 - n % 4, np.uint64)])
    raw = vals.astype("<u8").tobytes()
    return np.frombuffer(raw, dtype=">u4").astype(np.uint32).reshape(-1, 8)


def packed_u8_leaves_np(vals: np.ndarray) -> np.ndarray:
    n = vals.shape[0]
    if n % 32:
        vals = np.concatenate([vals, np.zeros(32 - n % 32, np.uint8)])
    raw = vals.astype(np.uint8).tobytes()
    return np.frombuffer(raw, dtype=">u4").astype(np.uint32).reshape(-1, 8)


def fold_to_limit_np(
    root: np.ndarray, depth: int, limit_log2: int, zh: np.ndarray
) -> np.ndarray:
    for d in range(depth, limit_log2):
        root = hash_rows_np(root[None, :], zh[d][None, :])[0]
    return root


def mix_length_np(root: np.ndarray, length: int) -> np.ndarray:
    return hash_rows_np(root[None, :], u64_chunk_words_np(length)[None, :])[0]


def u64_list_root_np(vals: np.ndarray, n: int, limit_log2: int, zh) -> np.ndarray:
    leaves = packed_u64_leaves_np(vals)
    chunks = (n + 3) // 4
    depth = max(chunks - 1, 0).bit_length() if n else 0
    sub = tree_root_np(pad_pow2_np(leaves, depth), depth)
    return mix_length_np(fold_to_limit_np(sub, depth, limit_log2, zh), n)


def u8_list_root_np(vals: np.ndarray, n: int, limit_log2: int, zh) -> np.ndarray:
    leaves = packed_u8_leaves_np(vals)
    chunks = (n + 31) // 32
    depth = max(chunks - 1, 0).bit_length() if n else 0
    sub = tree_root_np(pad_pow2_np(leaves, depth), depth)
    return mix_length_np(fold_to_limit_np(sub, depth, limit_log2, zh), n)


def checkpoint_root_np(epoch: int, root_bytes: np.ndarray) -> np.ndarray:
    r_words = np.frombuffer(
        np.ascontiguousarray(root_bytes, np.uint8).tobytes(), dtype=">u4"
    ).astype(np.uint32)
    return hash_rows_np(u64_chunk_words_np(epoch)[None, :], r_words[None, :])[0]


def bitvector4_chunk_np(bits: np.ndarray) -> np.ndarray:
    byte = int(bits[0]) | (int(bits[1]) << 1) | (int(bits[2]) << 2) | (int(bits[3]) << 3)
    chunk = np.zeros(8, np.uint32)
    chunk[0] = np.uint32(byte << 24)
    return chunk


def validator_registry_root_np(
    val_node_a: np.ndarray,
    val_node_f: np.ndarray,
    slashed_chunk: np.ndarray,
    effective_balance: np.ndarray,
    zh: np.ndarray,
) -> np.ndarray:
    n = effective_balance.shape[0]
    node_b = hash_rows_np(_eb_chunks_fast(effective_balance), slashed_chunk)
    node_e = hash_rows_np(val_node_a, node_b)
    roots = hash_rows_np(node_e, val_node_f)
    depth = max(n - 1, 0).bit_length()
    sub = tree_root_np(pad_pow2_np(roots, depth), depth)
    full = fold_to_limit_np(sub, depth, VALIDATOR_REGISTRY_LIMIT_LOG2, zh)
    return mix_length_np(full, n)


def _eb_chunks_fast(vals: np.ndarray) -> np.ndarray:
    """u64[n] -> per-VALIDATOR chunk words (one u64 in a 32-byte chunk)."""
    n = vals.shape[0]
    out = np.zeros((n, 32), np.uint8)
    out[:, :8] = np.frombuffer(vals.astype("<u8").tobytes(), np.uint8).reshape(n, 8)
    return np.frombuffer(out.tobytes(), dtype=">u4").astype(np.uint32).reshape(n, 8)


def post_epoch_state_root_np(
    arrays_np, meta: StateRootMeta, balances, effective_balance, inactivity_scores, just_np
) -> np.ndarray:
    """Host mirror of ops/state_root.post_epoch_state_root.  `arrays_np`
    is the StateRootArrays pytree as numpy; `just_np` a JustificationState
    as numpy."""
    n = meta.n_validators
    zh = zerohash_words(41)
    slot_of = {name: i for i, name in meta.dynamic_slots}
    dyn: dict[int, np.ndarray] = {}
    dyn[slot_of["validators"]] = validator_registry_root_np(
        np.asarray(arrays_np.val_node_a),
        np.asarray(arrays_np.val_node_f),
        np.asarray(arrays_np.slashed_chunk),
        np.asarray(effective_balance),
        zh,
    )
    dyn[slot_of["balances"]] = u64_list_root_np(
        np.asarray(balances), n, BALANCE_LIMIT_CHUNKS_LOG2, zh
    )
    if "inactivity_scores" in slot_of:
        dyn[slot_of["inactivity_scores"]] = u64_list_root_np(
            np.asarray(inactivity_scores), n, BALANCE_LIMIT_CHUNKS_LOG2, zh
        )
    if "previous_epoch_participation" in slot_of:
        dyn[slot_of["previous_epoch_participation"]] = u8_list_root_np(
            np.asarray(arrays_np.prev_part_flags), n, PARTICIPATION_LIMIT_CHUNKS_LOG2, zh
        )
        dyn[slot_of["current_epoch_participation"]] = _zero_u8_list_root_words(n).astype(
            np.uint32
        )
    dyn[slot_of["justification_bits"]] = bitvector4_chunk_np(
        np.asarray(just_np.justification_bits).astype(bool)
    )
    dyn[slot_of["previous_justified_checkpoint"]] = checkpoint_root_np(
        int(just_np.prev_justified_epoch), np.asarray(just_np.prev_justified_root)
    )
    dyn[slot_of["current_justified_checkpoint"]] = checkpoint_root_np(
        int(just_np.cur_justified_epoch), np.asarray(just_np.cur_justified_root)
    )
    dyn[slot_of["finalized_checkpoint"]] = checkpoint_root_np(
        int(just_np.finalized_epoch), np.asarray(just_np.finalized_root)
    )
    chunks = np.array(np.asarray(arrays_np.top_chunks), np.uint32, copy=True)
    for slot, root in dyn.items():
        chunks[slot] = root
    return tree_root_np(chunks, meta.top_depth)


def resident_root_acc_host(spec, cols, just, n_epochs: int, static) -> np.ndarray:
    """Host recompute of parallel/resident.run_epochs(..., with_root="state")
    .root_acc: the accounting advance runs through the SAME kernel jitted
    on the current (CPU-pinned) backend one epoch at a time, while every
    per-epoch state root goes through this module's native-sha tree — an
    execution path with no shared XLA graph and no shared hash engine with
    the device run being checked."""
    import jax
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops.altair_epoch import (
        AltairEpochParams,
        altair_epoch_accounting_impl,
    )

    params = AltairEpochParams.from_spec(spec)
    arrays, meta = static
    arrays_np = jax.tree_util.tree_map(np.asarray, arrays)

    @jax.jit
    def advance(cols, just):
        res = altair_epoch_accounting_impl(params, cols, just)
        cols = cols._replace(
            balance=res.balance,
            effective_balance=res.effective_balance,
            inactivity_scores=res.inactivity_scores,
        )
        just = just._replace(
            current_epoch=just.current_epoch + jnp.uint64(1),
            justification_bits=res.justification_bits,
            prev_justified_epoch=res.prev_justified_epoch,
            prev_justified_root=res.prev_justified_root,
            cur_justified_epoch=res.cur_justified_epoch,
            cur_justified_root=res.cur_justified_root,
            finalized_epoch=res.finalized_epoch,
            finalized_root=res.finalized_root,
        )
        return cols, just

    acc = np.zeros(8, np.uint32)
    for _ in range(n_epochs):
        cols, just = advance(cols, just)
        just_np = jax.tree_util.tree_map(np.asarray, just)
        acc = acc ^ post_epoch_state_root_np(
            arrays_np,
            meta,
            np.asarray(cols.balance),
            np.asarray(cols.effective_balance),
            np.asarray(cols.inactivity_scores),
            just_np,
        )
    return acc
