"""Swap-or-not shuffle in whole-permutation form.

The spec defines the shuffle per index: 90 hash-driven rounds deciding, for
each position, whether it swaps with its mirror around a per-round pivot
(reference: specs/phase0/beacon-chain.md:816-836; the reference then
LRU-caches the per-index loop, pysetup/spec_builders/phase0.py:59-88).

Inverted into whole-permutation form, each round is three vectorized steps
over ALL indices at once:
    flip  = (pivot - idx) mod n
    pos   = max(idx, flip)
    idx   = flip where bit(pos) else idx
with the decision bits gathered from one 32-byte hash per 256 positions.
That is a gather + select — exactly the shape the TPU kernel consumes. The
numpy path below is the host implementation; identity with the per-index
spec form is property-tested (tests/test_shuffle.py).
"""

from __future__ import annotations

import hashlib
from functools import lru_cache as _lru_cache

import numpy as np

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import watchdog


def shuffle_permutation(index_count: int, seed: bytes, rounds: int) -> np.ndarray:
    """perm[i] == compute_shuffled_index(i, index_count, seed) for all i."""
    if index_count == 0:
        return np.empty(0, dtype=np.int64)
    n = index_count
    idx = np.arange(n, dtype=np.int64)
    num_chunks = (n + 255) // 256
    sha = hashlib.sha256
    for rnd in range(rounds):
        rb = bytes([rnd])
        pivot = int.from_bytes(sha(seed + rb).digest()[:8], "little") % n
        # decision-bit sources: one hash per 256-position chunk
        src = np.frombuffer(
            b"".join(
                sha(seed + rb + (c).to_bytes(4, "little")).digest() for c in range(num_chunks)
            ),
            dtype=np.uint8,
        ).reshape(num_chunks, 32)
        flip = (pivot - idx) % n
        pos = np.maximum(idx, flip)
        byte_vals = src[pos // 256, (pos % 256) // 8]
        bits = (byte_vals >> (pos % 8).astype(np.uint8)) & 1
        idx = np.where(bits == 1, flip, idx)
    return idx


def shuffle_list(items: list, seed: bytes, rounds: int) -> list:
    """The shuffled sequence itself: out[i] = items[perm[i]]."""
    perm = shuffle_permutation(len(items), seed, rounds)
    return [items[int(p)] for p in perm]


# --- device kernel ---------------------------------------------------------


def _single_block_words(messages: list[bytes]) -> np.ndarray:
    """Pack sub-56-byte messages into padded single SHA-256 blocks as
    big-endian uint32[len(messages), 16]."""
    out = np.zeros((len(messages), 64), dtype=np.uint8)
    for i, m in enumerate(messages):
        out[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        out[i, len(m)] = 0x80
        bitlen = len(m) * 8
        out[i, 60:64] = np.frombuffer(np.array([bitlen], ">u4").tobytes(), np.uint8)
    return out.view(">u4").astype(np.uint32).reshape(len(messages), 16)


@_lru_cache(maxsize=None)
def _device_shuffle_kernel(n: int, rounds: int, num_chunks: int):
    """One compiled executable per (n, rounds) shape — seeds change every
    epoch, so the kernel must take (blocks, pivots) as traced arguments
    rather than closing over them (a per-seed closure would retrace)."""
    import jax
    import jax.numpy as jnp

    from .sha256 import sha256_single_block

    @jax.jit
    def run(blocks, pivots):
        digests = sha256_single_block(blocks)  # (rounds*chunks, 8) BE words
        digests = digests.reshape(rounds, num_chunks, 8)
        idx0 = jnp.arange(n, dtype=jnp.int32)

        def body(r, idx):
            pivot = pivots[r]
            flip = jnp.mod(pivot - idx, jnp.int32(n))
            pos = jnp.maximum(idx, flip)
            byte_idx = (pos % 256) // 8
            word = digests[r, pos // 256, byte_idx // 4]
            byte_val = (word >> (8 * (3 - (byte_idx % 4))).astype(jnp.uint32)) & 0xFF
            bit = (byte_val >> (pos % 8).astype(jnp.uint32)) & 1
            return jnp.where(bit == 1, flip, idx)

        # i32 loop bounds: python-int bounds widen the round counter —
        # and everything indexed by it — to i64 under the x64 flag (the
        # jaxlint x64-drift rule pins this kernel to 32-bit avals)
        return jax.lax.fori_loop(jnp.int32(0), jnp.int32(rounds), body, idx0)

    return run


def shuffle_permutation_device(index_count: int, seed: bytes, rounds: int):
    """Whole-permutation swap-or-not ON DEVICE, bit-equal to
    shuffle_permutation / compute_shuffled_index.

    The decision-bit hashes (rounds x ceil(n/256) single-block messages)
    are batched through the vectorized SHA-256 kernel; the 90 rounds of
    flip/gather/select over all n lanes run inside one jitted fori_loop —
    the reference's per-index 90-round loop
    (specs/phase0/beacon-chain.md:816-836, LRU-cached per index in
    pysetup/spec_builders/phase0.py:59-88) becomes ~90 fused gathers.
    Returns a device int32 array; np.asarray(...) for the host view."""
    if index_count == 0:
        import jax.numpy as jnp

        return jnp.empty(0, dtype=np.int32)
    n = index_count
    num_chunks = (n + 255) // 256
    sha = hashlib.sha256

    # pivots: 90 tiny host hashes (negligible; keeps uint64 mod off device)
    pivots = np.array(
        [
            int.from_bytes(sha(seed + bytes([r])).digest()[:8], "little") % n
            for r in range(rounds)
        ],
        dtype=np.int64,
    ).astype(np.int32)

    # decision-bit source blocks for every (round, chunk)
    msgs = [
        seed + bytes([r]) + c.to_bytes(4, "little")
        for r in range(rounds)
        for c in range(num_chunks)
    ]
    blocks = _single_block_words(msgs)

    # lower-bound traffic: one compression per decision hash (96 B) plus
    # the int32 index plane read+written every round
    work_bytes = 96 * rounds * num_chunks + 8 * n * rounds
    with obs.span("shuffle.permutation", work_bytes=work_bytes, lanes=n, rounds=rounds) as sp:
        sp.result = perm = _device_shuffle_kernel(n, rounds, num_chunks)(blocks, pivots)
    obs.count("shuffle.permutations", 1)
    obs.count("shuffle.lanes", n)
    obs.count("shuffle.decision_hashes", rounds * num_chunks)
    if watchdog.should_check("shuffle"):
        watchdog.check_shuffle_slice(perm, n, seed, rounds)
    return perm
