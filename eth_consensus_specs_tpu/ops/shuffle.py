"""Swap-or-not shuffle in whole-permutation form.

The spec defines the shuffle per index: 90 hash-driven rounds deciding, for
each position, whether it swaps with its mirror around a per-round pivot
(reference: specs/phase0/beacon-chain.md:816-836; the reference then
LRU-caches the per-index loop, pysetup/spec_builders/phase0.py:59-88).

Inverted into whole-permutation form, each round is three vectorized steps
over ALL indices at once:
    flip  = (pivot - idx) mod n
    pos   = max(idx, flip)
    idx   = flip where bit(pos) else idx
with the decision bits gathered from one 32-byte hash per 256 positions.
That is a gather + select — exactly the shape the TPU kernel consumes. The
numpy path below is the host implementation; identity with the per-index
spec form is property-tested (tests/test_shuffle.py).
"""

from __future__ import annotations

import hashlib

import numpy as np


def shuffle_permutation(index_count: int, seed: bytes, rounds: int) -> np.ndarray:
    """perm[i] == compute_shuffled_index(i, index_count, seed) for all i."""
    if index_count == 0:
        return np.empty(0, dtype=np.int64)
    n = index_count
    idx = np.arange(n, dtype=np.int64)
    num_chunks = (n + 255) // 256
    sha = hashlib.sha256
    for rnd in range(rounds):
        rb = bytes([rnd])
        pivot = int.from_bytes(sha(seed + rb).digest()[:8], "little") % n
        # decision-bit sources: one hash per 256-position chunk
        src = np.frombuffer(
            b"".join(
                sha(seed + rb + (c).to_bytes(4, "little")).digest() for c in range(num_chunks)
            ),
            dtype=np.uint8,
        ).reshape(num_chunks, 32)
        flip = (pivot - idx) % n
        pos = np.maximum(idx, flip)
        byte_vals = src[pos // 256, (pos % 256) // 8]
        bits = (byte_vals >> (pos % 8).astype(np.uint8)) & 1
        idx = np.where(bits == 1, flip, idx)
    return idx


def shuffle_list(items: list, seed: bytes, rounds: int) -> list:
    """The shuffled sequence itself: out[i] = items[perm[i]]."""
    perm = shuffle_permutation(len(items), seed, rounds)
    return [items[int(p)] for p in perm]
