"""Device kernels (JAX/XLA/Pallas): the TPU compute path.

Modules:
  sha256        vectorized SHA-256 compression (merkle node hashing)
  merkle        whole-subtree merkleization on device
  shuffle       swap-or-not shuffle as a whole-permutation kernel
  field         BLS12-381 base-field limb arithmetic (batched)
  state_columns columnar (struct-of-arrays) mirrors of hot state regions
"""
