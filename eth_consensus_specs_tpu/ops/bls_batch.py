"""Batched BLS verification — the device-backend slot.

The consensus workload's signature hot spot is many independent
FastAggregateVerify calls per block (<=128 attestations x committee
aggregates; reference call sites: specs/phase0/beacon-chain.md:776-792,
specs/altair/beacon-chain.md:575-650). The batching seams:

  1. aggregate pubkey sums (G1 adds) are data-parallel per attestation;
  2. random-linear-combination batching collapses N pairing checks into
     one (the algorithmic seam the reference uses for KZG batches,
     specs/deneb/polynomial-commitments.md:412-463);
  3. the final pairing runs once per batch on host.

Current state: host group arithmetic through crypto/ with the batch-RLC
structure in place; the limb-arithmetic device MSM (ops/field_limbs) slots
in underneath without changing callers. The RLC reduction itself is already
the right shape for TPU: it is exactly a (scalars x points) MSM.
"""

from __future__ import annotations

import secrets

from eth_consensus_specs_tpu.crypto import signature as _sig
from eth_consensus_specs_tpu.crypto.curve import g1_from_bytes, g1_generator, g1_infinity, g2_from_bytes
from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2
from eth_consensus_specs_tpu.crypto.pairing import pairing_check


def fast_aggregate_verify_host_pairing(pks: list[bytes], message: bytes, sig: bytes) -> bool:
    """Single FastAggregateVerify via the host pairing (device MSM slot)."""
    return _sig.fast_aggregate_verify(pks, message, sig)


def batch_verify_aggregates(items: list[tuple[list[bytes], bytes, bytes]]) -> bool:
    """Verify many (pubkeys, message, aggregate_signature) triples with ONE
    pairing check via random linear combination:

        prod_i e(r_i * aggpk_i, H(m_i)) * e(-G1, sum_i r_i * sig_i) == 1

    Sound: a forged triple passes only with probability ~1/2^64 over the
    random r_i. This is the TPU-shaped reduction: all scalar products are
    one MSM batch.
    """
    if not items:
        return True
    pairs = []
    sig_acc = None
    g1 = g1_generator()
    for pks, msg, sig_b in items:
        if len(pks) == 0:
            return False
        try:
            aggpk = g1_infinity()
            for pk in pks:
                p = g1_from_bytes(bytes(pk))
                if p.is_infinity():
                    return False
                aggpk = aggpk + p
            sig = g2_from_bytes(bytes(sig_b))
        except ValueError:
            return False
        r = secrets.randbits(64) | 1
        pairs.append((aggpk.mul(r), hash_to_g2(bytes(msg))))
        term = sig.mul(r)
        sig_acc = term if sig_acc is None else sig_acc + term
    pairs.append((-g1, sig_acc))
    return pairing_check(pairs)
