"""Batched BLS verification — the device-backend seam.

The consensus workload's signature hot spot is many independent
FastAggregateVerify calls per block (<=128 attestations x committee
aggregates; reference call sites: specs/phase0/beacon-chain.md:776-792,
specs/altair/beacon-chain.md:575-650). The batching seams:

  1. aggregate pubkey sums + RLC scalar products run as a DEVICE G1 MSM
     (ops/g1_msm limb kernel) when the tpu backend is selected;
  2. random-linear-combination batching collapses N pairing checks into
     one (the algorithmic seam the reference uses for KZG batches,
     specs/deneb/polynomial-commitments.md:412-463);
  3. the Miller accumulation and final-exponentiation membership check
     run on DEVICE too (ops/pairing_device — host prepares per-Q line
     coefficients, the device runs the batched fixed-structure loop);
     only hash-to-curve and the 64-bit G2 RLC multiplies stay host-side.

`process_operations` routes block attestations through
`batch_verify_aggregates` (one pairing per block) and falls back to
per-attestation verification only when the batch rejects, so the invalid
attestation surfaces at the exact spec assertion.
"""

from __future__ import annotations

import os
import secrets
import threading

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.analysis import lockwatch
from eth_consensus_specs_tpu.crypto.curve import (
    Point,
    g1_generator,
    g1_infinity,
    g2_from_bytes,
)
from eth_consensus_specs_tpu.crypto.hash_to_curve import DST_G2, hash_to_g2
from eth_consensus_specs_tpu.crypto.pairing import pairing_check
from eth_consensus_specs_tpu.obs import watchdog


def _use_device() -> bool:
    # snapshot the backend switch ONCE per batch (callers read it a single
    # time and thread the answer through): a concurrent use_tpu()/
    # use_pyspec() flip mid-batch must not route half a batch's items
    # through each backend
    from eth_consensus_specs_tpu.utils import bls

    return bls.backend_name() == "tpu"


# hash-to-G2 results keyed by (dst, message) — primed in one batched
# device dispatch when ETH_SPECS_TPU_DEVICE_H2C is on; host fallback per
# miss.  The dst is part of the key so a caller priming under one domain
# can never serve a point to a reader under another.  All mutation holds
# _H2G2_LOCK: the serving layer's micro-batcher verifies off-thread, and
# an unlocked evict (clear + update) racing a concurrent prime could
# publish a half-rebuilt dict.
_H2G2_CACHE: dict[tuple[bytes, bytes], object] = {}
_H2G2_LOCK = lockwatch.wrap(threading.Lock(), "ops.bls_batch._H2G2_LOCK")


def _reinit_lock_after_fork_in_child() -> None:
    # fork-safety: the serving layer's batch thread primes this cache
    # off-thread; a gen-pool fork mid-prime must not hand the child a
    # held lock (the cache contents are read-only-safe to inherit)
    global _H2G2_LOCK
    _H2G2_LOCK = lockwatch.wrap(threading.Lock(), "ops.bls_batch._H2G2_LOCK")


os.register_at_fork(after_in_child=_reinit_lock_after_fork_in_child)


def _prime_h2g2_cache(msgs: list[bytes], batch_fn, dst: bytes = DST_G2) -> None:
    # evict BEFORE deciding what to batch: clearing afterwards would drop
    # this very call's cached messages and push them onto the serial host
    # path — the opposite of what the batched dispatch is for
    keys = [(dst, m) for m in msgs]
    with _H2G2_LOCK:
        if len(_H2G2_CACHE) + len(keys) > 512:
            keep = {k: _H2G2_CACHE[k] for k in keys if k in _H2G2_CACHE}
            _H2G2_CACHE.clear()
            _H2G2_CACHE.update(keep)
        fresh = [m for m in msgs if (dst, m) not in _H2G2_CACHE]
    if not fresh:
        return
    # the batched dispatch runs OUTSIDE the lock (it can be slow; two
    # racing primes at worst both compute — idempotent, never corrupt)
    points = batch_fn(fresh, dst)
    with _H2G2_LOCK:
        # re-check the bound at insert time: N racing primes could each
        # have passed the pre-dispatch check, and unbounded overshoot
        # would defeat the cap (evicting here keeps THIS call's keys)
        if len(_H2G2_CACHE) + len(fresh) > 512:
            keep = {k: _H2G2_CACHE[k] for k in keys if k in _H2G2_CACHE}
            _H2G2_CACHE.clear()
            _H2G2_CACHE.update(keep)
        for m, p in zip(fresh, points):
            _H2G2_CACHE[(dst, m)] = p


def _h2g2(msg: bytes, dst: bytes = DST_G2):
    with _H2G2_LOCK:
        hit = _H2G2_CACHE.get((dst, msg))
    return hit if hit is not None else hash_to_g2(msg, dst)


def _pairing_check_routed(pairs, mesh=None) -> bool:
    """Device Miller loop + membership check under the tpu backend; the
    host/native pairing elsewhere. Both are bit-equivalent implementations
    of the same check (tests/test_pairing_device.py), so routing can never
    flip a verification result. Env overrides (both read per call, so a
    parent process can steer a child):

      ETH_SPECS_TPU_NO_DEVICE_PAIRING=1  force HOST pairing even under the
        tpu backend (bench's XLA:CPU fallback, where the device pairing's
        one-time compile would eat the whole section budget);
      ETH_SPECS_TPU_DEVICE_PAIRING=1     force DEVICE pairing even when the
        bls backend switch is elsewhere — the bench's hybrid mode: host C
        aggregation (one core, no dispatch round-trips) + the one batched
        Miller/final-exp on the accelerator."""
    import os

    if os.environ.get("ETH_SPECS_TPU_NO_DEVICE_PAIRING"):
        return pairing_check(pairs)
    if _use_device() or os.environ.get("ETH_SPECS_TPU_DEVICE_PAIRING"):
        from eth_consensus_specs_tpu.ops.pairing_device import pairing_check_device

        return pairing_check_device(pairs, mesh=mesh)
    return pairing_check(pairs)


def fast_aggregate_verify_device(pks: list[bytes], message: bytes, sig: bytes) -> bool:
    """FastAggregateVerify with the pubkey aggregation on device and the
    pairing on host. Semantics mirror the host path exactly (per-key
    validation rejects infinity KEYS, but an infinity AGGREGATE proceeds
    into the pairing — crypto/signature.py:115-127) so backend choice can
    never flip a verification result."""
    from eth_consensus_specs_tpu.crypto.signature import _load_pk, _load_sig
    from eth_consensus_specs_tpu.ops.g1_msm import sum_g1_device

    if len(pks) == 0:
        return False
    points = []
    for pk_b in pks:
        pk = _load_pk(bytes(pk_b))
        if pk is None:
            return False
        points.append(pk)
    sig_pt = _load_sig(bytes(sig))
    if sig_pt is None:
        return False
    with obs.span("bls.fast_aggregate_verify", pubkeys=len(pks)):
        obs.count("bls.fast_aggregate_verifies", 1)
        obs.count("bls.pubkeys_aggregated", len(pks))
        aggpk = sum_g1_device(points)
        return _pairing_check_routed(
            [(aggpk, hash_to_g2(bytes(message))), (-g1_generator(), sig_pt)]
        )


def batch_verify_aggregates(
    items: list[tuple[list[bytes], bytes, bytes]], mesh=None
) -> bool:
    """Verify many (pubkeys, message, aggregate_signature) triples with ONE
    pairing check via random linear combination:

        prod_i e(r_i * aggpk_i, H(m_i)) * e(-G1, sum_i r_i * sig_i) == 1

    Sound: a forged triple passes only with probability ~1/2^64 over the
    random r_i. With the tpu backend each item's committee pubkeys sum in
    the device pairwise-sum kernel (one dispatch per item; the compiled
    executable is shared across same-pow2 committee sizes) and the 64-bit
    r_i multiply happens host-side on the single aggregate point; the G2
    side (hash-to-curve, memoized per distinct message) and the final
    pairing are host-side.
    """
    if not items:
        return True
    with obs.span("bls.batch_verify", items=len(items)):
        obs.count("bls.batches", 1)
        obs.count("bls.batch_items", len(items))
        ok, parsed = _batch_verify_impl(items, mesh=mesh)
    # the watchdog's host-pairing recompute runs AFTER the span closes
    # (like sha256/merkle/shuffle): the probe must never be clocked as
    # kernel time — in the obs report or in bench's timed region
    if ok and parsed and watchdog.should_check("bls_batch"):
        # a True batch verdict must reproduce for any member item through
        # the plain host pairing (no device MSM, no routed pairing, no
        # h2g2 cache) — the sampled item rotates with the call counter
        points, msg, sig, _r = parsed[watchdog.call_salt("bls_batch") % len(parsed)]
        watchdog.check_bls_item(points, msg, sig, ok)
    return ok


def _parse_item(item: tuple[list[bytes], bytes, bytes]):
    """(pubkeys, message, signature) -> (points, msg, sig, r) or None on
    any malformed/empty input — the exact accept/reject rules of the
    inline parse this was extracted from."""
    from eth_consensus_specs_tpu.crypto.signature import _load_pk

    pks, msg, sig_b = item
    if len(pks) == 0:
        return None
    # _load_pk rejects malformed AND infinity keys (same outcome as the
    # previous inline parse) and caches decompression — registry keys
    # repeat every block, so steady-state parsing is dict lookups
    points = []
    for pk in pks:
        p = _load_pk(bytes(pk))
        if p is None:
            return None
        points.append(p)
    try:
        sig = g2_from_bytes(bytes(sig_b))
    except ValueError:
        return None
    r = secrets.randbits(64) | 1
    return (points, bytes(msg), sig, r)


def _batch_verify_impl(
    items: list[tuple[list[bytes], bytes, bytes]],
    mesh=None,
) -> tuple[bool, list | None]:
    parsed = []
    for item in items:
        p = _parse_item(item)
        if p is None:
            return False, None
        parsed.append(p)
    rpk = _rlc_pubkey_terms(parsed, mesh=mesh)
    return _rlc_pairing_check(parsed, rpk, mesh=mesh), parsed


def _rlc_pubkey_terms(parsed: list, mesh=None) -> list:
    """Per-item r_i * aggpk_i — independent of which subset of the batch
    a later check verifies, so verify_many's bisection computes these
    ONCE per item and re-checks subsets with only the G2 MSM + pairing."""
    if not parsed:
        return []
    if _use_device():
        from eth_consensus_specs_tpu.ops.g1_msm import sum_g1_many_device
        from eth_consensus_specs_tpu.parallel.mesh_ops import shard_count
        from eth_consensus_specs_tpu.serve import buckets

        # the scalar is uniform within an item, so r_i * aggpk_i factors
        # to r_i * sum(points): ONE batched device dispatch sums every
        # item's committee (item axis sharded over `mesh` when live),
        # and the single 64-bit host multiply per item replaces an
        # n-lane 256-bit double-and-add. The dispatch shape/key is the
        # LIVE serve key fn (serve/buckets.bls_msm_key — the same
        # callable jaxlint's recompile-surface check exercises); its
        # first sighting is the compile this process pays for that
        # (items, lanes[, mesh]) key — accounted here so serve and
        # direct callers agree.
        shards = shard_count(mesh)
        key = buckets.bls_msm_key(
            len(parsed), max(len(points) for points, _, _, _ in parsed), mesh=mesh
        )
        with buckets.first_dispatch(*key):
            sums = sum_g1_many_device(
                [points for points, _, _, _ in parsed],
                mesh=mesh if shards > 1 else None,
                pad_shape=(key[1], key[2]),
            )
        rpk = [s.mul(r) for s, (_, _, _, r) in zip(sums, parsed)]
    else:
        from eth_consensus_specs_tpu.crypto import native_bridge as nb
        from eth_consensus_specs_tpu.crypto.fields import Fq

        rpk = []
        native = nb.enabled()
        for points, _, _, r in parsed:
            if native:
                # one C call sums the committee (vs n affine adds, each a
                # field inversion round-trip through the bridge)
                raw = nb.g1_aggregate(
                    [None if p.is_infinity() else (p.x.n, p.y.n) for p in points]
                )
                aggpk = (
                    g1_infinity()
                    if raw is None
                    else Point(Fq(raw[0]), Fq(raw[1]), points[0].b)
                )
            else:
                aggpk = g1_infinity()
                for p in points:
                    aggpk = aggpk + p
            rpk.append(aggpk.mul(r))
    return rpk


def _rlc_pairing_check(parsed: list, rpk: list, mesh=None) -> bool:
    g1 = g1_generator()
    # merge same-message items into one pairing input (block attestations
    # often share AttestationData): k items with m distinct messages ->
    # m+1 pairs, one hash-to-curve per distinct message
    merged: dict[bytes, object] = {}
    for (points, msg, sig, r), rp in zip(parsed, rpk):
        merged[msg] = rp if msg not in merged else merged[msg] + rp
    # optional device hash-to-curve: one batched dispatch maps every
    # distinct message (ops/h2c_device — bit-equal to the host path, so
    # routing can never flip a result); opt-in via env because the
    # one-time compile only pays off on a real accelerator
    if os.environ.get("ETH_SPECS_TPU_DEVICE_H2C") and len(merged) > 1:
        from eth_consensus_specs_tpu.ops.h2c_device import hash_to_g2_device

        _prime_h2g2_cache(list(merged.keys()), hash_to_g2_device)
    # sum_i r_i * sig_i in ONE native Pippenger MSM (64-bit scalars are
    # always < r, so the reduced path is exact); multi_exp falls back to
    # the bit-exact per-point path without the native core
    from eth_consensus_specs_tpu.utils.bls import multi_exp

    sig_acc = multi_exp([sig for _, _, sig, _ in parsed], [r for _, _, _, r in parsed])
    pairs = [(rp, _h2g2(msg)) for msg, rp in merged.items()]
    pairs.append((-g1, sig_acc))
    obs.count("bls.pairings", 1)
    obs.count("bls.pairing_inputs", len(pairs))
    obs.count("bls.messages_distinct", len(merged))
    return _pairing_check_routed(pairs, mesh=mesh)


def verify_many(
    items: list[tuple[list[bytes], bytes, bytes]], mesh=None
) -> list[bool]:
    """Per-item verdicts for many (pubkeys, message, aggregate_signature)
    triples — the serving layer's batch entry point. Parsing and the
    per-item G1 MSM terms are computed ONCE; one RLC pairing settles an
    all-valid batch (the overwhelmingly common case), and a reject
    bisects with only the G2 MSM + pairing per subset, so each invalid
    item costs ~2*log2(n) pairings instead of n.

    With a multi-device ``mesh`` the per-item G1 terms shard their item
    axis and the device pairing's Miller chunks shard across chips; the
    terms are mesh-independent values (canonical affine points), so the
    bisection re-checks subsets with the SAME terms and verdicts stay
    bit-identical whatever the mesh shape.

    Per-item results are exactly what ``batch_verify_aggregates([item])``
    returns: a singleton RLC check is ``X^r == 1`` in the prime-order
    pairing group with odd 64-bit r, which holds iff ``X == 1`` — i.e.
    the singleton batch is deterministic, not probabilistic, so bisection
    verdicts are bit-identical to per-request direct calls."""
    if not items:
        return []
    with obs.span("bls.verify_many", items=len(items)):
        obs.count("bls.verify_many_items", len(items))
        out = [False] * len(items)
        parsed = [_parse_item(it) for it in items]
        live = [i for i, p in enumerate(parsed) if p is not None]
        if not live:
            return out
        sub = [parsed[i] for i in live]
        rpk = _rlc_pubkey_terms(sub, mesh=mesh)
        verdicts = _bisect_rlc(sub, rpk, mesh=mesh)
        for i, v in zip(live, verdicts):
            out[i] = v
    # sampled device/host coupling on the serving path too (outside the
    # span, same as batch_verify_aggregates): one item's verdict must
    # reproduce through the plain host pairing
    if live and watchdog.should_check("bls_batch"):
        k = live[watchdog.call_salt("bls_batch") % len(live)]
        points, msg, sig, _r = parsed[k]
        watchdog.check_bls_item(points, msg, sig, out[k])
    return out


def _bisect_rlc(parsed: list, rpk: list, mesh=None) -> list[bool]:
    if _rlc_pairing_check(parsed, rpk, mesh=mesh):
        return [True] * len(parsed)
    if len(parsed) == 1:
        return [False]
    mid = len(parsed) // 2
    return _bisect_rlc(parsed[:mid], rpk[:mid], mesh=mesh) + _bisect_rlc(
        parsed[mid:], rpk[mid:], mesh=mesh
    )
