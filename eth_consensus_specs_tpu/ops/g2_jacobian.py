"""Batched G2 (E'(Fq2)) Jacobian arithmetic in lazy limbs — device side.

Building block for the device hash-to-curve pipeline (ops/h2c_device) and
any future fully-device G2 walk: doubling, branchless complete addition,
the fixed [|x|]-ladder (the BLS parameter z has Hamming weight 6), the
psi endomorphism, and batched Jacobian→affine via one Fermat inversion
per lane.  All values are LF limb arrays of shape [..., 2, 15] per Fq2
coordinate; infinity is represented by Z == 0 exactly as the host's
native core does (native/bls12_381.c g2p), so results convert 1:1.

Formulas mirror native/bls12_381.c g2_dbl/g2_add so a device walk is
value-equal to the C core (and hence to crypto/curve.Point) for every
input, including the doubling and infinity edge cases, which are resolved
with lane masks instead of branches.
"""

from __future__ import annotations

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu.crypto.fields import Fq2, P as P_INT
from eth_consensus_specs_tpu.ops import fq12_tower as tw
from eth_consensus_specs_tpu.ops import lazy_limbs as lz
from eth_consensus_specs_tpu.ops.lazy_limbs import LF, lf

BLS_X_ABS = 0xD201000000010000


def _canon(x: LF) -> LF:
    """Canonical LF: normalized limbs, value < 2p (safe scan carry /
    select operand — static bounds then mean the same thing on both
    sides of a jnp.where)."""
    return lz.shrink(x)


def fq2_is_zero(a: LF):
    return tw.fq2_is_zero(a)


def _sel(mask, a: LF, b: LF) -> LF:
    """Lane-select between two Fq2 LFs; mask has the batch shape, values
    are [..., 2, 15]."""
    m = mask[..., None, None]
    return LF(jnp.where(m, a.v, b.v), max(a.max, b.max), max(a.val, b.val))


class G2J:
    """Jacobian point batch: X, Y, Z are LF of shape [..., 2, 15]."""

    __slots__ = ("x", "y", "z")

    def __init__(self, x: LF, y: LF, z: LF):
        self.x, self.y, self.z = x, y, z

    def is_inf(self):
        return fq2_is_zero(self.z)


_ONE_L = tw.fq2_to_limbs(Fq2.one())


def g2_from_affine(x: LF, y: LF, active=None) -> G2J:
    """active=False lanes become infinity (Z=0)."""
    z = lf(jnp.broadcast_to(jnp.asarray(_ONE_L), x.v.shape))
    if active is not None:
        z = _sel(active, z, LF(jnp.zeros_like(z.v), 0, 0))
    return G2J(x, y, z)


def g2_dbl(p: G2J) -> G2J:
    """2P — Jacobian doubling (a=0 curve), exact mirror of C g2_dbl.
    Y == 0 or Z == 0 lanes produce Z3 == 0 naturally (Z3 = 2YZ)."""
    A = tw.fq2_sqr(p.x)
    B = tw.fq2_sqr(p.y)
    C = tw.fq2_sqr(B)
    t = tw.fq2_sqr(tw.fq2_add(p.x, B))
    D = tw.fq2_sub(tw.fq2_sub(t, A), C)
    D = tw.fq2_add(D, D)
    E = tw.fq2_add(tw.fq2_add(A, A), A)
    F = tw.fq2_sqr(E)
    x3 = tw.fq2_sub(tw.fq2_sub(F, D), D)
    eight_c = tw.fq2_add(C, C)
    eight_c = tw.fq2_add(eight_c, eight_c)
    eight_c = tw.fq2_add(eight_c, eight_c)
    y3 = tw.fq2_sub(tw.fq2_mul(E, tw.fq2_sub(D, x3)), eight_c)
    z3 = tw.fq2_mul(p.y, p.z)
    z3 = tw.fq2_add(z3, z3)
    return G2J(x3, y3, z3)


def g2_add(p: G2J, q: G2J) -> G2J:
    """P + Q — complete branchless addition mirroring C g2_add's case
    analysis with lane masks: infinity passthroughs, doubling fallback
    when U1==U2 & S1==S2, infinity when U1==U2 & S1!=S2."""
    z1z1 = tw.fq2_sqr(p.z)
    z2z2 = tw.fq2_sqr(q.z)
    u1 = tw.fq2_mul(p.x, z2z2)
    u2 = tw.fq2_mul(q.x, z1z1)
    s1 = tw.fq2_mul(tw.fq2_mul(p.y, q.z), z2z2)
    s2 = tw.fq2_mul(tw.fq2_mul(q.y, p.z), z1z1)
    h = tw.fq2_sub(u2, u1)
    rr = tw.fq2_sub(s2, s1)
    x_eq = fq2_is_zero(h)
    y_eq = fq2_is_zero(rr)

    i = tw.fq2_sqr(tw.fq2_add(h, h))
    j = tw.fq2_mul(h, i)
    rr2 = tw.fq2_add(rr, rr)
    v = tw.fq2_mul(u1, i)
    x3 = tw.fq2_sub(tw.fq2_sub(tw.fq2_sqr(rr2), j), tw.fq2_add(v, v))
    s1j = tw.fq2_mul(s1, j)
    y3 = tw.fq2_sub(
        tw.fq2_mul(rr2, tw.fq2_sub(v, x3)), tw.fq2_add(s1j, s1j)
    )
    z3 = tw.fq2_sqr(tw.fq2_add(p.z, q.z))
    z3 = tw.fq2_sub(tw.fq2_sub(z3, z1z1), z2z2)
    z3 = tw.fq2_mul(z3, h)
    added = G2J(x3, y3, z3)

    dbl = g2_dbl(p)
    # same-x selection: doubling when y matches, infinity otherwise
    zero = LF(jnp.zeros_like(z3.v), 0, 0)
    sx = G2J(
        _sel(y_eq, dbl.x, added.x),
        _sel(y_eq, dbl.y, added.y),
        _sel(y_eq, dbl.z, zero),
    )
    out = G2J(
        _sel(x_eq, sx.x, added.x),
        _sel(x_eq, sx.y, added.y),
        _sel(x_eq, sx.z, added.z),
    )
    # infinity passthroughs
    p_inf = p.is_inf()
    q_inf = q.is_inf()
    out = G2J(
        _sel(p_inf, q.x, out.x),
        _sel(p_inf, q.y, out.y),
        _sel(p_inf, q.z, out.z),
    )
    return G2J(
        _sel(q_inf, p.x, out.x),
        _sel(q_inf, p.y, out.y),
        _sel(q_inf, p.z, out.z),
    )


def g2_neg(p: G2J) -> G2J:
    return G2J(p.x, tw.fq2_neg(p.y), p.z)


def g2_mul_z(p: G2J) -> G2J:
    """[|x|]P by the fixed double-and-add ladder (63 doublings, adds at
    the 5 set low bits) as ONE lax.scan — the step body (dbl + selected
    add) compiles once and runs 63 times, keeping the XLA graph small
    (unrolling the adds was measured to blow compile memory through the
    roof).  The carry crosses the scan boundary in canonical form (limbs
    < 2^26, value < 2p) so the re-wrap on entry tells the truth about
    static bounds.  Value-equal to the C g2_mul_z ladder."""
    add_bits = np.array(
        [(BLS_X_ABS >> bit) & 1 for bit in range(62, -1, -1)], np.uint8
    )
    base = G2J(_canon(p.x), _canon(p.y), _canon(p.z))

    def step(carry, bit):
        acc = G2J(lf(carry[0]), lf(carry[1]), lf(carry[2]))
        acc = g2_dbl(acc)
        withadd = g2_add(acc, base)
        nx = jnp.where(bit != 0, _canon(withadd.x).v, _canon(acc.x).v)
        ny = jnp.where(bit != 0, _canon(withadd.y).v, _canon(acc.y).v)
        nz = jnp.where(bit != 0, _canon(withadd.z).v, _canon(acc.z).v)
        return (nx, ny, nz), None

    init = (base.x.v, base.y.v, base.z.v)
    (ox, oy, oz), _ = lax.scan(step, init, jnp.asarray(add_bits))
    return G2J(lf(ox), lf(oy), lf(oz))


# psi endomorphism constants (same values the C core's tables hold)
def _psi_consts():
    from eth_consensus_specs_tpu.crypto.fields import Fq2, XI

    psi_x = XI.pow((P_INT - 1) // 3).inv()
    psi_y = XI.pow((P_INT - 1) // 2).inv()
    return tw.fq2_to_limbs(psi_x), tw.fq2_to_limbs(psi_y)


_PSI_X_L, _PSI_Y_L = None, None


def g2_psi(p: G2J) -> G2J:
    """psi on Jacobian coords: conj each coordinate, scale X and Y by the
    untwist-frobenius-twist constants (native/bls12_381.c g2_psi_jac)."""
    global _PSI_X_L, _PSI_Y_L
    if _PSI_X_L is None:
        _PSI_X_L, _PSI_Y_L = _psi_consts()
    px = lf(jnp.broadcast_to(jnp.asarray(_PSI_X_L), p.x.v.shape))
    py = lf(jnp.broadcast_to(jnp.asarray(_PSI_Y_L), p.y.v.shape))
    return G2J(
        tw.fq2_mul(tw.fq2_conj(p.x), px),
        tw.fq2_mul(tw.fq2_conj(p.y), py),
        tw.fq2_conj(p.z),
    )


def g2_clear_cofactor(p: G2J) -> G2J:
    """[h_eff]P via Budroni-Pintore with the shared-ladder decomposition —
    identical group element to the C core's bls_g2_clear_cofactor:
    [z^2]P + [z]P - P - psi([z+1]P) + psi^2([2]P)."""
    a = g2_mul_z(p)  # [z]P
    b = g2_mul_z(a)  # [z^2]P
    apq = g2_add(a, p)  # [z+1]P
    t = g2_psi(apq)
    acc = g2_add(b, a)
    acc = g2_add(acc, g2_neg(p))
    acc = g2_add(acc, g2_neg(t))
    p2 = g2_psi(g2_psi(g2_dbl(p)))
    return g2_add(acc, p2)


def g2_to_affine(p: G2J) -> tuple[LF, LF, "jnp.ndarray"]:
    """(x, y, inf_mask) — one Fermat Fq2 inversion per lane (batched in
    the limb lanes, so the 380-step pow scan runs once for the batch)."""
    zi = tw.fq2_inv(p.z)
    zi2 = tw.fq2_sqr(zi)
    zi3 = tw.fq2_mul(zi2, zi)
    return tw.fq2_mul(p.x, zi2), tw.fq2_mul(p.y, zi3), p.is_inf()
