"""Fixed-limb BLS12-381 base-field arithmetic for the device (uint64 lanes).

TPUs have no wide-integer units, so Fp (381-bit) elements live as 13x30-bit
limbs in uint64 lanes: a 30x30-bit partial product is <2^60 and a column of
13 such products plus carries stays under 2^64, so schoolbook accumulation
never overflows a lane. Multiplication is Montgomery (R = 2^390) in
separated (SOS) form: an unrolled pad-shift-add for the full 25-column
product (13 static rows — NOT a dot/einsum, which XLA:TPU cannot lower
for u64), then a 13-step lax.scan reduction — the graph stays ~100 HLO
ops per multiply (an unrolled CIOS was ~25x bigger and made XLA compile
times explode).

Values are kept in the REDUNDANT range [0, 2p): R > 4p, so Montgomery
outputs stay < 2p without any conditional subtraction, and only additions
pay one conditional 2p-subtraction. `from_mont_int` canonicalizes at the
host boundary.

This is the arithmetic layer the VERDICT's device-BLS step 1 calls for
(reference native analogue: the milagro/arkworks limb code behind
utils/bls.py:224-296). Host Python ints are the conversion boundary;
correctness oracles are crypto/fields.py and plain pow().
"""

from __future__ import annotations

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu.crypto.fields import P as P_INT

LIMB_BITS = 30
N_LIMBS = 13  # 13 * 30 = 390 >= 381
MASK = (1 << LIMB_BITS) - 1
R_INT = 1 << (LIMB_BITS * N_LIMBS)  # Montgomery radix 2^390 (> 4p)
# -P^-1 mod 2^30 (per-word quotient constant)
N0_INV = (-pow(P_INT, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)

U64 = jnp.uint64


def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(N_LIMBS, np.uint64)
    for i in range(N_LIMBS):
        out[i] = (x >> (LIMB_BITS * i)) & MASK
    return out


def _limbs_to_int(arr: np.ndarray) -> int:
    return sum(int(arr[i]) << (LIMB_BITS * i) for i in range(N_LIMBS))


P_LIMBS = int_to_limbs(P_INT)
P2_LIMBS = int_to_limbs(2 * P_INT)


def to_mont(x: int) -> np.ndarray:
    """Host: canonical int -> Montgomery-form limbs (x * R mod p)."""
    return int_to_limbs((x * R_INT) % P_INT)


def from_mont_int(limbs) -> int:
    """Host: (possibly redundant) Montgomery limbs -> canonical int."""
    x = _limbs_to_int(np.asarray(limbs, np.uint64))
    return (x * pow(R_INT, -1, P_INT)) % P_INT


ONE_MONT = to_mont(1)


# == device kernels (elementwise over leading batch dims) ==================

def _limb_product(a, b):
    """Full 25-column schoolbook product, columns NOT carried.
    Column magnitude <= 13 * (2^30-1)^2 + carries < 2^64.

    The anti-diagonal accumulation is an unrolled pad-shift-add (13 static
    rows), NOT a dot/einsum: XLA:TPU cannot lower u64 dot_general ("u64
    dot" hits the unimplemented X64-rewrite path at compile time), while
    elementwise u64 multiplies/adds lower fine on every backend."""
    partials = a[..., :, None] * b[..., None, :]
    batch_pad = [(0, 0)] * (partials.ndim - 2)
    out = None
    for i in range(N_LIMBS):
        row = jnp.pad(partials[..., i, :], batch_pad + [(i, N_LIMBS - 1 - i)])
        out = row if out is None else out + row
    return out


def _carry_sweep(t):
    """Normalize limbs of t[..., L] to <2^30; returns (normalized, carry)."""
    tT = jnp.moveaxis(t, -1, 0)

    def step(carry, col):
        cur = col + carry
        return cur >> jnp.uint64(LIMB_BITS), cur & jnp.uint64(MASK)

    carry, cols = lax.scan(step, jnp.zeros_like(tT[0]), tT)
    return jnp.moveaxis(cols, 0, -1), carry


def _geq(a, b):
    """Lexicographic a >= b over [..., 13] limb arrays (4-op scan body)."""
    aT = jnp.moveaxis(a, -1, 0)
    bT = jnp.moveaxis(b, -1, 0)

    def step(acc, ab):
        x, y = ab
        # scanning least-significant first: a later (more significant)
        # difference overrides the accumulated verdict
        acc = jnp.where(x == y, acc, x > y)
        return acc, None

    acc, _ = lax.scan(step, jnp.ones_like(aT[0], dtype=bool), (aT, bT))
    return acc


def _sub_limbs(a, b):
    """a - b with borrow chain, assuming a >= b (scan over limbs)."""
    aT = jnp.moveaxis(a, -1, 0)
    bT = jnp.moveaxis(b, -1, 0)

    def step(borrow, ab):
        x, y = ab
        cur = x - y - borrow
        under = cur >> jnp.uint64(63)
        return under, cur + (under << jnp.uint64(LIMB_BITS))

    _, cols = lax.scan(step, jnp.zeros_like(aT[0]), (aT, bT))
    return jnp.moveaxis(cols, 0, -1)


def _cond_sub(t, bound_limbs):
    """Subtract `bound` once when t >= bound (t < 2*bound)."""
    bound = jnp.asarray(bound_limbs)
    b = jnp.broadcast_to(bound, t.shape)
    need = _geq(t, b)
    sub = _sub_limbs(t, b)
    return jnp.where(need[..., None], sub, t)


def mont_mul(a, b):
    """Montgomery product abR^-1 mod p for a, b in [0, 2p).
    Result in [0, 2p) — no conditional subtraction needed (R > 4p)."""
    mask = jnp.uint64(MASK)
    shift = jnp.uint64(LIMB_BITS)
    n0 = jnp.uint64(N0_INV)
    p_vec = jnp.asarray(P_LIMBS)

    prod = _limb_product(a, b)  # [..., 25]
    t, carry = _carry_sweep(prod)
    t = jnp.concatenate(
        [t, carry[..., None], jnp.zeros_like(carry)[..., None]], axis=-1
    )  # [..., 27]

    def red_step(t, i):
        ti = lax.dynamic_slice_in_dim(t, i, 1, axis=-1)[..., 0]
        m = ((ti & mask) * n0) & mask
        window = lax.dynamic_slice_in_dim(t, i, N_LIMBS, axis=-1)
        window = window + m[..., None] * p_vec
        t = lax.dynamic_update_slice_in_dim(t, window, i, axis=-1)
        # fold t[i]'s (now low-zero) value up as a carry
        pair = lax.dynamic_slice_in_dim(t, i, 2, axis=-1)
        folded = jnp.stack(
            [pair[..., 0] & mask, pair[..., 1] + (pair[..., 0] >> shift)], axis=-1
        )
        return lax.dynamic_update_slice_in_dim(t, folded, i, axis=-1), None

    t, _ = lax.scan(red_step, t, jnp.arange(N_LIMBS, dtype=jnp.int32))
    res, carry = _carry_sweep(t[..., N_LIMBS : 2 * N_LIMBS + 1])  # [..., 14]
    # value < 2p < 2^382 fits in 13 limbs; top limb and carry are zero
    return res[..., :N_LIMBS]


def mont_sqr(a):
    return mont_mul(a, a)


def add_mod(a, b):
    """(a + b) kept in [0, 2p) via one conditional 2p-subtraction."""
    t, carry = _carry_sweep(a + b)
    # inputs < 2p each -> sum < 4p < 2^383: top carry lands in limb 12's
    # sweep only if limbs were lazy; with <2^30 limbs carry is 0
    return _cond_sub(t, P2_LIMBS)


def sub_mod(a, b):
    """(a - b) kept in [0, 2p): a + (2p - b), then one cond-subtraction."""
    p2 = jnp.broadcast_to(jnp.asarray(P2_LIMBS), b.shape)
    t, _ = _carry_sweep(a + _sub_limbs(p2, b))
    return _cond_sub(t, P2_LIMBS)


def is_zero(a):
    """True iff the element is 0 mod p (redundant range: 0 or p)."""
    p = jnp.broadcast_to(jnp.asarray(P_LIMBS), a.shape)
    exact_zero = jnp.all(a == 0, axis=-1)
    exact_p = jnp.all(a == p, axis=-1)
    return exact_zero | exact_p
