"""Columnar altair+ epoch accounting — ONE fused XLA computation.

Altair replaced phase0's pending-attestation reward pipeline with
participation FLAGS (reference: specs/altair/beacon-chain.md:398-486,687):
per-validator uint8 bitfields that are *already columnar in the state*.
The accounting epoch is therefore an even cleaner fusion than phase0's:

    justification/finalization  (flag-derived target balances)
    inactivity-score updates    (bias/recovery integrator per validator)
    flag-weight rewards         (3 components, sequential clamped apply)
    inactivity penalties        (score-proportional, uses UPDATED scores)
    slashings sweep             (altair multiplier)
    effective-balance hysteresis

in one jitted function over flag/score/balance columns. All control flow is
`jnp.where`; the same fusion-boundary proof as phase0 applies to
process_registry_updates (it never touches balance columns or the slashing
predicate — see ops/state_columns.py docstring).

RAW-KERNEL fork coverage: altair through electra+ semantics — the two
quotient knobs enter via the spec's fork hooks, electra's per-increment
slashing rounding via `electra_slashing`, and EIP-7251's per-validator
MaxEB as an optional column. The SPEC-LEVEL columnar wrapper
(`process_epoch_columnar`) covers altair→electra and IS the default
process_epoch: altair→deneb run the full fusion; electra runs the
TWO-PHASE split (`altair_epoch_accounting_phase_a` without the
effective-balance step, host-side pending deposit/consolidation queues in
spec order, hysteresis after — forks/electra.py process_epoch_columnar).

Sequential balance application (reward_k then clamped penalty_k, k over
src/tgt/head/inactivity) exactly mirrors the object path's delta-list
loop, so clamp-at-zero edge cases are bit-exact.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax

import eth_consensus_specs_tpu  # noqa: F401  (package import enables x64)
import jax.numpy as jnp

from .state_columns import (
    JustificationState,
    LocalReductions,
    _LOCAL,
    _total_balance,
    isqrt_u64,
    justification_update,
)


def _is_post_electra(spec) -> bool:
    from eth_consensus_specs_tpu.config import is_post_fork

    return is_post_fork(spec.fork_name, "electra")


U64 = jnp.uint64


@dataclass(frozen=True)
class AltairEpochParams:
    """Compile-time constants (static under jit). Weights in flag order
    (source, target, head) per PARTICIPATION_FLAG_WEIGHTS."""

    effective_balance_increment: int
    base_reward_factor: int
    weights: tuple  # (TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT, TIMELY_HEAD_WEIGHT)
    weight_denominator: int
    timely_head_flag_index: int
    min_epochs_to_inactivity_penalty: int
    inactivity_score_bias: int
    inactivity_score_recovery_rate: int
    inactivity_penalty_quotient: int  # fork hook value (altair/bellatrix+)
    proportional_slashing_multiplier: int  # fork hook value
    epochs_per_slashings_vector: int
    hysteresis_quotient: int
    hysteresis_downward_multiplier: int
    hysteresis_upward_multiplier: int
    max_effective_balance: int
    # [Electra:EIP7251] per-increment penalty quantum replaces altair's
    # per-validator rounding (specs/electra/beacon-chain.md:893-920); the
    # per-validator effective-balance ceiling moves into a column
    electra_slashing: bool = False

    @classmethod
    def from_spec(cls, spec) -> "AltairEpochParams":
        return cls(
            effective_balance_increment=spec.EFFECTIVE_BALANCE_INCREMENT,
            base_reward_factor=spec.BASE_REWARD_FACTOR,
            weights=tuple(int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS),
            weight_denominator=spec.WEIGHT_DENOMINATOR,
            timely_head_flag_index=spec.TIMELY_HEAD_FLAG_INDEX,
            min_epochs_to_inactivity_penalty=spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY,
            inactivity_score_bias=spec.config.INACTIVITY_SCORE_BIAS,
            inactivity_score_recovery_rate=spec.config.INACTIVITY_SCORE_RECOVERY_RATE,
            inactivity_penalty_quotient=spec.inactivity_penalty_quotient(),
            proportional_slashing_multiplier=spec.proportional_slashing_multiplier(),
            epochs_per_slashings_vector=spec.EPOCHS_PER_SLASHINGS_VECTOR,
            hysteresis_quotient=spec.HYSTERESIS_QUOTIENT,
            hysteresis_downward_multiplier=spec.HYSTERESIS_DOWNWARD_MULTIPLIER,
            hysteresis_upward_multiplier=spec.HYSTERESIS_UPWARD_MULTIPLIER,
            max_effective_balance=spec.MAX_EFFECTIVE_BALANCE,
            electra_slashing=_is_post_electra(spec),
        )


class AltairEpochColumns(NamedTuple):
    """Columnar registry + participation flags + inactivity scores."""

    effective_balance: jnp.ndarray  # u64[N]
    balance: jnp.ndarray  # u64[N]
    slashed: jnp.ndarray  # bool[N]
    activation_epoch: jnp.ndarray  # u64[N]
    exit_epoch: jnp.ndarray  # u64[N]
    withdrawable_epoch: jnp.ndarray  # u64[N]
    prev_flags: jnp.ndarray  # u8[N] previous_epoch_participation bitfield
    cur_tgt_att: jnp.ndarray  # bool[N] current-epoch TIMELY_TARGET flag
    inactivity_scores: jnp.ndarray  # u64[N]
    # [Electra:EIP7251] per-validator effective-balance ceiling (32 ETH or
    # 2048 ETH by credential type); None pre-electra -> the scalar param
    max_effective_balance: jnp.ndarray | None = None  # u64[N]


class AltairEpochResult(NamedTuple):
    balance: jnp.ndarray
    effective_balance: jnp.ndarray
    inactivity_scores: jnp.ndarray
    justification_bits: jnp.ndarray
    prev_justified_epoch: jnp.ndarray
    prev_justified_root: jnp.ndarray
    cur_justified_epoch: jnp.ndarray
    cur_justified_root: jnp.ndarray
    finalized_epoch: jnp.ndarray
    finalized_root: jnp.ndarray


def altair_epoch_accounting_impl(
    params: AltairEpochParams,
    cols: AltairEpochColumns,
    just: JustificationState,
    red: LocalReductions = _LOCAL,
    include_effective_balance: bool = True,
) -> AltairEpochResult:
    p = params
    one = jnp.asarray(1, U64)
    zero = jnp.asarray(0, U64)
    incr = jnp.asarray(p.effective_balance_increment, U64)

    cur_epoch = just.current_epoch
    prev_epoch = jnp.where(cur_epoch > 0, cur_epoch - one, zero)

    eff = cols.effective_balance
    not_slashed = ~cols.slashed
    active_cur = (cols.activation_epoch <= cur_epoch) & (cur_epoch < cols.exit_epoch)
    active_prev = (cols.activation_epoch <= prev_epoch) & (prev_epoch < cols.exit_epoch)
    eligible = active_prev | (cols.slashed & (prev_epoch + one < cols.withdrawable_epoch))

    total_active = _total_balance(active_cur, eff, incr, red)

    # unslashed participating masks per flag (previous epoch)
    flags = cols.prev_flags.astype(jnp.uint32)
    part = [
        active_prev & (((flags >> k) & 1) == 1) & not_slashed
        for k in range(len(p.weights))
    ]

    # -- justification & finalization -------------------------------------
    prev_tgt_bal = _total_balance(part[1], eff, incr, red)
    cur_tgt_bal = _total_balance(active_cur & cols.cur_tgt_att & not_slashed, eff, incr, red)
    (
        out_bits,
        out_prev_je,
        out_prev_jr,
        out_cur_je,
        out_cur_jr,
        out_fin_e,
        out_fin_r,
    ) = justification_update(just, prev_tgt_bal, cur_tgt_bal, total_active)

    finality_delay = prev_epoch - out_fin_e
    in_leak = finality_delay > jnp.asarray(p.min_epochs_to_inactivity_penalty, U64)

    # -- inactivity-score updates (uses POST-justification leak state) ----
    participating_tgt = part[1]
    score = cols.inactivity_scores
    score = jnp.where(
        eligible,
        jnp.where(
            participating_tgt,
            score - jnp.minimum(one, score),
            score + jnp.asarray(p.inactivity_score_bias, U64),
        ),
        score,
    )
    score = jnp.where(
        eligible & ~in_leak,
        score - jnp.minimum(jnp.asarray(p.inactivity_score_recovery_rate, U64), score),
        score,
    )
    do_accounting = cur_epoch > zero
    score_out = jnp.where(do_accounting, score, cols.inactivity_scores)

    # -- rewards & penalties ----------------------------------------------
    brpi = incr * jnp.asarray(p.base_reward_factor, U64) // isqrt_u64(total_active)
    base_reward = (eff // incr) * brpi
    active_increments = total_active // incr
    wd = jnp.asarray(p.weight_denominator, U64)

    bal = cols.balance
    for k, weight_int in enumerate(p.weights):
        weight = jnp.asarray(weight_int, U64)
        pk_mask = part[k]
        part_increments = _total_balance(pk_mask, eff, incr, red) // incr
        reward = base_reward * weight * part_increments // (active_increments * wd)
        r_k = jnp.where(
            do_accounting & eligible & pk_mask & ~in_leak, reward, zero
        )
        if k != p.timely_head_flag_index:
            pen_k = jnp.where(
                do_accounting & eligible & ~pk_mask, base_reward * weight // wd, zero
            )
        else:
            pen_k = jnp.zeros_like(bal)
        bal = bal + r_k
        bal = bal - jnp.minimum(bal, pen_k)

    # inactivity penalties, proportional to the UPDATED scores
    pen_inact = (
        eff
        * score_out
        // jnp.asarray(p.inactivity_score_bias * p.inactivity_penalty_quotient, U64)
    )
    p_inact = jnp.where(do_accounting & eligible & ~participating_tgt, pen_inact, zero)
    bal = bal - jnp.minimum(bal, p_inact)

    # -- slashings sweep ---------------------------------------------------
    adj_slash = jnp.minimum(
        just.slashings_sum * jnp.asarray(p.proportional_slashing_multiplier, U64),
        total_active,
    )
    half_vec = jnp.asarray(p.epochs_per_slashings_vector // 2, U64)
    slash_now = cols.slashed & (cur_epoch + half_vec == cols.withdrawable_epoch)
    if p.electra_slashing:
        # [Electra:EIP7251] shared per-increment quantum, then scale by the
        # validator's increments (different rounding from altair)
        penalty_per_increment = adj_slash // (total_active // incr)
        slash_penalty = penalty_per_increment * (eff // incr)
    else:
        slash_penalty = (eff // incr) * adj_slash // total_active * incr
    bal = bal - jnp.minimum(bal, jnp.where(slash_now, slash_penalty, zero))

    # -- effective-balance hysteresis -------------------------------------
    # electra's TWO-PHASE split runs this step host-side AFTER the pending
    # deposit/consolidation queues (spec ordering,
    # specs/electra/beacon-chain.md:943,1022) — phase A returns eff as-is
    if include_effective_balance:
        hyst = incr // jnp.asarray(p.hysteresis_quotient, U64)
        down = hyst * jnp.asarray(p.hysteresis_downward_multiplier, U64)
        up = hyst * jnp.asarray(p.hysteresis_upward_multiplier, U64)
        crossed = (bal + down < eff) | (eff + up < bal)
        eff_ceiling = (
            cols.max_effective_balance
            if cols.max_effective_balance is not None
            else jnp.asarray(p.max_effective_balance, U64)
        )
        new_eff = jnp.where(crossed, jnp.minimum(bal - bal % incr, eff_ceiling), eff)
    else:
        new_eff = eff

    return AltairEpochResult(
        balance=bal,
        effective_balance=new_eff,
        inactivity_scores=score_out,
        justification_bits=out_bits,
        prev_justified_epoch=out_prev_je,
        prev_justified_root=out_prev_jr,
        cur_justified_epoch=out_cur_je,
        cur_justified_root=out_cur_jr,
        finalized_epoch=out_fin_e,
        finalized_root=out_fin_r,
    )


altair_epoch_accounting = partial(jax.jit, static_argnums=(0,))(altair_epoch_accounting_impl)
# phase A of the electra two-phase fusion: accounting without the
# effective-balance hysteresis (that runs after the host-side queues)
altair_epoch_accounting_phase_a = partial(
    jax.jit, static_argnums=(0,), static_argnames=("include_effective_balance",)
)(altair_epoch_accounting_impl)
