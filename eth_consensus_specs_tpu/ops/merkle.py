"""Whole-subtree SSZ merkleization on device — ONE dispatch per tree.

Profiling on v5e (remote chip behind a tunnel) showed per-dispatch latency
of ~80ms dominating everything else (upload of a 32MB leaf level: 20ms;
the hashes themselves: ~milliseconds). So the whole binary reduction runs
as a single jitted call: the few widest levels unrolled with exact
shrinking shapes, then a `lax.fori_loop` over the narrow tail carrying a
fixed-width node buffer (see _unroll_levels — total work ~1.1x the exact
tree at depth 20, vs ~10x for a pure fixed-width loop), bought for a 35x
drop in dispatch count. Graph size stays a handful of compressions
(rounds unrolled on TPU, see ops/sha256.py) + the loop.

Environment note (axon tunnel, measured): device-side allocations DEGRADE
to ~1.2s/32MB after loop-heavy kernel executions (fresh-process uploads are
20ms; transfer itself is fine — it's the allocator). Consequence baked into
the design: hot state lives device-resident between calls
(ops/state_columns.py); the host-chunk entry below is for one-shot roots.

Virtual padding: SSZ pads leaf data with zero chunks up to the limit; a
subtree of zero chunks hashes to zerohashes[d], so padding the real leaf
count to 2**depth with zero chunks on device gives bit-identical roots
(cf. reference utils/merkle_minimal.py:47-91). Live nodes stay at the
front of the buffer every level, so the tail garbage (hashes of spent
positions) never reaches them.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import watchdog, xprof

from .sha256 import sha256_pair_words


def _unroll_levels(depth: int) -> int:
    """How many TOP (wide) levels to unroll with exact shrinking widths.

    The fixed-width loop costs 2^(d-1) compressions per level regardless
    of the live width, so at depth 20 a pure loop does ~10x the exact
    tree's work. Unrolling the k widest levels (each its own compression
    instance in the graph) brings total work to
    (2^d - 2^(d-k)) + (d-k)*2^(d-k-1) — 1.09x exact at d=20, k=6 — at
    the cost of k extra compression bodies (~10s one-time TPU compile
    each, persistently cached). Shallow trees keep the single-body graph:
    their absolute overhead is small and graph size stays minimal under
    big fused outer jits (parallel/resident.py fuses several trees)."""
    return min(6, max(0, depth - 8))


def tree_real_hashes(depth: int) -> int:
    """Compressions tree_root_words actually executes at `depth` — the
    honest work count for bench roofline/throughput accounting."""
    if depth == 0:
        return 0
    k = _unroll_levels(depth)
    unrolled = (1 << depth) - (1 << (depth - k))
    return unrolled + (depth - k) * (1 << max(depth - k - 1, 0))


def tree_root_words(leaves: jnp.ndarray, depth: int) -> jnp.ndarray:
    """Traceable tree reduction: uint32[2**depth, 8] -> uint32[8] root.

    Plain function so it composes under outer jits / shard_map (the
    sharded tree in parallel/merkle.py reduces local subtrees with this,
    then all-gathers the per-device roots)."""
    if depth == 0:
        return leaves[0]
    buf = leaves
    for _ in range(_unroll_levels(depth)):
        buf = sha256_pair_words(buf.reshape(buf.shape[0] // 2, 16))
    rem = depth - _unroll_levels(depth)
    if rem:
        w = buf.shape[0] // 2

        def level(_, b):
            h = sha256_pair_words(b.reshape(w, 16))
            return jnp.concatenate([h, jnp.zeros_like(h)], axis=0)

        # i32 loop bounds: python-int bounds widen the counter to i64
        # under the package-wide x64 flag — the jaxlint x64-drift rule
        # keeps this kernel's jaxpr pure 32-bit
        buf = lax.fori_loop(jnp.int32(0), jnp.int32(rem), level, buf)
    return buf[0]


_tree_root_fused = partial(jax.jit, static_argnums=(1,))(tree_root_words)


def many_tree_root_words(leaves: jnp.ndarray, depth: int) -> jnp.ndarray:
    """Batched tree reduction: uint32[B, 2**depth, 8] -> uint32[B, 8]
    roots, ONE dispatch for B independent subtrees (the serving layer's
    bucket-padded flush shape — compiles once per (B, depth))."""
    return jax.vmap(lambda level: tree_root_words(level, depth))(leaves)


_many_tree_root_fused = partial(jax.jit, static_argnums=(1,))(many_tree_root_words)


# -- mesh-sharded multi-tree dispatch: the batch (tree) axis splits over
# the serve mesh; every tree is independent, so there are NO collectives
# and the per-tree roots are trivially byte-identical to the vmapped
# single-device kernel. One jitted shard_map per (mesh, depth), the jit
# cache dedupes per batch shape.
_SHARDED_MANY: dict[tuple, object] = {}


def _many_tree_root_sharded(mesh, depth: int):
    key = (mesh, depth)
    fn = _SHARDED_MANY.get(key)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from eth_consensus_specs_tpu.parallel.mesh_ops import BATCH_AXES

    spec = P(BATCH_AXES)
    fn = jax.jit(
        shard_map(
            lambda words: many_tree_root_words(words, depth),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
            check_rep=False,
        )
    )
    _SHARDED_MANY[key] = fn
    return fn


def _clear_sharded_after_fork_in_child() -> None:
    # fork-safety: compiled executables reference the parent's devices
    _SHARDED_MANY.clear()


os.register_at_fork(after_in_child=_clear_sharded_after_fork_in_child)


def _chunks_to_words(chunks: np.ndarray, cap: int) -> np.ndarray:
    """uint8[N, 32] chunks (or pre-packed uint32[N, 8] BE words) ->
    uint32[cap, 8], zero-padded. Exposed so the service's host-prep
    stage can pack off the dispatch thread."""
    if chunks.dtype == np.uint32:
        words = np.ascontiguousarray(chunks)
    else:
        n = chunks.shape[0]
        words = np.ascontiguousarray(chunks).view(">u4").astype(np.uint32).reshape(n, 8)
    n = words.shape[0]
    assert n <= cap
    if n < cap:
        words = np.concatenate([words, np.zeros((cap - n, 8), dtype=np.uint32)], axis=0)
    return words


def merkleize_many_device(
    trees: list[np.ndarray], depth: int, pad_batch: int | None = None, mesh=None
) -> list[bytes]:
    """Merkleize many independent subtrees of one depth in a single
    dispatch. Each entry is uint8[N_i, 32] chunks (N_i <= 2**depth) or a
    pre-packed uint32[N_i, 8] word array; the batch dimension is padded
    with all-zero trees up to `pad_batch` so the compiled executable is
    shared across every flush in the same bucket. With a multi-device
    `mesh` the tree axis shards over it (pad_batch then rounds up to a
    multiple of the shard count — serve/buckets.py's mesh-aware buckets
    already are). Roots are bit-identical to per-tree
    `merkleize_subtree_device` (same kernel, vmapped) on every path."""
    from eth_consensus_specs_tpu.parallel.mesh_ops import (
        mesh_signature,
        pad_to_shards,
        shard_count,
    )

    b = len(trees)
    cap = 1 << depth
    shards = shard_count(mesh)
    if shards <= 1:
        mesh = None
    batch = pad_batch or b
    if mesh is not None:
        batch = pad_to_shards(batch, shards)
    assert b <= batch
    words = np.zeros((batch, cap, 8), np.uint32)
    for i, chunks in enumerate(trees):
        words[i] = _chunks_to_words(chunks, cap)
    real = batch * tree_real_hashes(depth)
    with obs.span(
        "merkle.many_subtree_root",
        work_bytes=96 * real,
        tree_depth=depth,
        trees=b,
        padded_trees=batch,
        mesh=mesh_signature(mesh),
        mesh_shards=shards,
        per_shard_trees=batch // shards,
    ) as sp:
        if mesh is not None:
            obs.count("mesh.dispatches", 1)
            obs.count("mesh.sharded_items", b)
            fn = _many_tree_root_sharded(mesh, depth)
            sp.result = roots = np.asarray(fn(jnp.asarray(words)))
        else:
            sp.result = roots = np.asarray(
                _many_tree_root_fused(jnp.asarray(words), depth)
            )
    obs.count("merkle.trees", b)
    obs.count("merkle.real_hashes", real)
    if xprof.enabled():
        # once per (batch, depth[, mesh shape]): what XLA compiled for
        # this bucket vs the 96 B × real-hash floor the span's roofline
        # was judged on — sharded shapes attribute per (op, mesh-shape)
        if mesh is not None:
            xprof.analyze(
                "merkle_many",
                _many_tree_root_sharded(mesh, depth),
                (jax.ShapeDtypeStruct((batch, cap, 8), jnp.uint32),),
                hand_bytes=96 * real,
                dims=(batch, depth, *(int(mesh.shape[a]) for a in mesh.axis_names)),
            )
        else:
            xprof.analyze(
                "merkle_many",
                _many_tree_root_fused,
                (jax.ShapeDtypeStruct((batch, cap, 8), jnp.uint32), depth),
                hand_bytes=96 * real,
                dims=(batch, depth),
            )
    out = [roots[i].astype(">u4", order="C").view(np.uint8).tobytes() for i in range(b)]
    if b and watchdog.should_check("merkle"):
        i = watchdog.call_salt("merkle") % b
        watchdog.check_merkle_root(words[i], depth, out[i])
    return out


def merkleize_subtree_device(chunks: np.ndarray, depth: int) -> bytes:
    """Merkleize uint8[N, 32] chunks into the root of a depth-`depth` subtree.

    N must satisfy N <= 2**depth; zero-chunk padding to 2**depth happens
    host-side. One compiled shape per depth (persistently cached, see
    utils/cache.py).
    """
    n = chunks.shape[0]
    cap = 1 << depth
    assert n <= cap
    words = np.ascontiguousarray(chunks).view(">u4").astype(np.uint32).reshape(n, 8)
    if n < cap:
        words = np.concatenate([words, np.zeros((cap - n, 8), dtype=np.uint32)], axis=0)
    real = tree_real_hashes(depth)
    with obs.span(
        "merkle.subtree_root", work_bytes=96 * real, tree_depth=depth, leaf_chunks=n
    ) as sp:
        sp.result = root_words = np.asarray(_tree_root_fused(jnp.asarray(words), depth))
    obs.count("merkle.trees", 1)
    obs.count("merkle.real_hashes", real)
    obs.count("merkle.leaf_chunks", n)
    if xprof.enabled():
        xprof.analyze(
            "merkle",
            _tree_root_fused,
            (jax.ShapeDtypeStruct((cap, 8), jnp.uint32), depth),
            hand_bytes=96 * real,
            dims=(depth,),
        )
    root = root_words.astype(">u4", order="C").view(np.uint8).tobytes()
    if watchdog.should_check("merkle"):
        watchdog.check_merkle_root(words, depth, root)
    return root


# Device/host crossover: ONE cost model shared with the serving layer's
# bucket planner (serve/buckets.py is the home; re-exported here so ops
# callers keep their import path and the two can never disagree).
from eth_consensus_specs_tpu.serve.buckets import (  # noqa: E402
    DEVICE_SUBTREE_THRESHOLD,
    device_subtree_worthwhile,
)

__all__ = [
    "DEVICE_SUBTREE_THRESHOLD",
    "device_subtree_worthwhile",
    "merkleize_many_device",
    "merkleize_subtree_device",
    "many_tree_root_words",
    "tree_real_hashes",
    "tree_root_words",
]
