"""Hierarchical committee-tree aggregation — the gossip fan-in, simulated.

A consensus slot's attestations arrive as per-committee gossip
contributions spread over attestation subnets (64 on mainnet). A real
aggregator builds the block's aggregates bottom-up:

    committee contributions          (tier 0: one ragged G2 sum per
      -> per-subnet partials          committee, ONE batched dispatch
      -> global aggregate             PER SUBNET — the fan-in unit)
                                     (tier 1: subnet partials per
                                      attestation data root, one
                                      dispatch across all subnets)
                                     (tier 2: global aggregate per
                                      root, one dispatch)

Every tier is a batched ``ops/g2_aggregate.sum_g2_many_device`` dispatch
for the signatures plus the existing mesh-sharded
``ops/g1_msm.sum_g1_many_device`` for the matching aggregate pubkeys,
keyed/accounted through the LIVE serve key fns
(``serve/buckets.g2_agg_key`` / ``bls_msm_key``) so direct pipeline
callers and the serve layer can never disagree about compile shapes.
Participation bitfields concatenate deterministically ((subnet,
committee) order within a root), so the output per attestation data
root is the (aggregate signature, aggregate pubkey, bits) triple a
block producer ships.

Correctness: :func:`aggregate_slot_host` computes the identical tiers
through ``crypto/signature``'s host fold — bit-identical Points at
every tier, which the tests and ``scripts/agg_bench.py`` enforce
before any throughput is reported. Invalid contributions (a corrupt
member signature) do not break aggregation — they surface in
:func:`verify_slot`, and :func:`isolate_invalid_subnets` feeds the
per-subnet partials through the existing mesh-sharded
``verify_many`` bisection so each bad subnet costs ~2*log2(n)
pairings instead of n.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import numpy as np

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.crypto.curve import Point, g1_to_bytes, g2_to_bytes


def subnet_count() -> int:
    """Attestation subnets the committee tree fans in over
    (env-snapshotted; mainnet's 64 by default)."""
    raw = os.environ.get("ETH_SPECS_AGG_SUBNETS", "")
    try:
        return max(int(raw), 1) if raw else 64
    except ValueError:
        return 64


@dataclass(frozen=True)
class CommitteeAttestation:
    """One committee's gossip contribution: the participating members'
    signature/pubkey points plus the participation bitfield over the
    FULL committee (len(sigs) == len(pubkeys) == popcount(bits))."""

    subnet: int
    root: bytes  # attestation data root — the signed message
    pubkeys: tuple  # participating members' G1 Points
    sigs: tuple  # matching G2 signature Points
    bits: tuple  # participation bits over the full committee


@dataclass
class SubnetAggregate:
    subnet: int
    root: bytes
    sig: Point
    pubkey: Point
    bits: np.ndarray  # committee bits concatenated in arrival order

    @property
    def sig_bytes(self) -> bytes:
        return g2_to_bytes(self.sig)

    @property
    def pubkey_bytes(self) -> bytes:
        return g1_to_bytes(self.pubkey)


@dataclass
class SlotAggregate:
    root: bytes
    sig: Point
    pubkey: Point
    bits: np.ndarray  # subnet bits concatenated in subnet order

    @property
    def sig_bytes(self) -> bytes:
        return g2_to_bytes(self.sig)

    @property
    def pubkey_bytes(self) -> bytes:
        return g1_to_bytes(self.pubkey)


def _sum_tier_device(g2_lists: list[list], g1_lists: list[list], mesh):
    """One tier's paired dispatches: the ragged G2 committee sums (lane
    axis mesh-sharded past the crossover) and the G1 pubkey sums (item
    axis mesh-sharded, the existing bls_msm seam). Keys come from the
    LIVE serve key fns and their first sightings are the compiles this
    process pays — accounted so serve and pipeline callers agree; the
    G2 first-dispatch wall also lands in ``agg.compile_ms``."""
    from eth_consensus_specs_tpu.ops.g1_msm import sum_g1_many_device
    from eth_consensus_specs_tpu.ops.g2_aggregate import sum_g2_many_device
    from eth_consensus_specs_tpu.parallel import mesh_ops
    from eth_consensus_specs_tpu.serve import buckets

    n = len(g2_lists)
    max_lanes = max((len(p) for p in g2_lists), default=1)
    # the SAME live policy fn the serve layer and front door route by
    # (pow2 lane bucket vs the crossover) — a private raw-lane rule here
    # would let pipeline and serve disagree about compile shapes for
    # raw counts just under the crossover
    sharded = mesh is not None and buckets.route_wide(
        "agg", buckets.pow2_bucket(max_lanes), n
    )
    key = buckets.g2_agg_key(n, max_lanes, mesh=mesh if sharded else None)
    t0 = time.perf_counter()
    with buckets.first_dispatch(*key) as fd:
        sigs = sum_g2_many_device(
            g2_lists, mesh=mesh if sharded else None, pad_shape=(key[1], key[2])
        )
    if fd.first:
        obs.observe("agg.compile_ms", (time.perf_counter() - t0) * 1e3)

    pk_sharded = mesh is not None and n >= mesh_ops.min_items()
    pk_key = buckets.bls_msm_key(
        n, max((len(p) for p in g1_lists), default=1),
        mesh=mesh if pk_sharded else None,
    )
    with buckets.first_dispatch(*pk_key):
        pks = sum_g1_many_device(
            g1_lists, mesh=mesh if pk_sharded else None,
            pad_shape=(pk_key[1], pk_key[2]),
        )
    return sigs, pks


def _sum_tier_host(g2_lists: list[list], g1_lists: list[list]):
    """The host oracle of one tier: ``crypto/signature``'s point folds
    (native-bridge accelerated when available), no device anywhere."""
    from eth_consensus_specs_tpu.crypto.signature import _sum_g1, _sum_g2

    return [_sum_g2(pts) for pts in g2_lists], [_sum_g1(pts) for pts in g1_lists]


def _aggregate_slot(atts: list[CommitteeAttestation], tier_fn):
    """The committee tree over ``tier_fn`` (device or host oracle —
    identical structure, so tier outputs compare 1:1)."""
    # tier 0, per subnet (the gossip fan-in unit): committee partials
    by_subnet: dict[int, list[int]] = {}
    for i, a in enumerate(atts):
        by_subnet.setdefault(int(a.subnet), []).append(i)
    csig: dict[int, Point] = {}
    cpk: dict[int, Point] = {}
    for subnet in sorted(by_subnet):
        idxs = by_subnet[subnet]
        sigs, pks = tier_fn(
            [list(atts[i].sigs) for i in idxs],
            [list(atts[i].pubkeys) for i in idxs],
        )
        for i, s, p in zip(idxs, sigs, pks):
            csig[i], cpk[i] = s, p

    # tier 1: per-(subnet, root) partials across all subnets, one call
    groups: dict[tuple[int, bytes], list[int]] = {}
    for i, a in enumerate(atts):
        groups.setdefault((int(a.subnet), bytes(a.root)), []).append(i)
    gkeys = sorted(groups)
    sigs, pks = tier_fn(
        [[csig[i] for i in groups[k]] for k in gkeys],
        [[cpk[i] for i in groups[k]] for k in gkeys],
    )
    subnet_aggs = [
        SubnetAggregate(
            subnet=k[0],
            root=k[1],
            sig=s,
            pubkey=p,
            bits=np.concatenate(
                [np.asarray(atts[i].bits, bool) for i in groups[k]]
            ),
        )
        for k, s, p in zip(gkeys, sigs, pks)
    ]

    # tier 2: global aggregate per attestation data root
    by_root: dict[bytes, list[SubnetAggregate]] = {}
    for sa in subnet_aggs:
        by_root.setdefault(sa.root, []).append(sa)
    roots = sorted(by_root)
    sigs, pks = tier_fn(
        [[sa.sig for sa in by_root[r]] for r in roots],
        [[sa.pubkey for sa in by_root[r]] for r in roots],
    )
    slot_aggs = [
        SlotAggregate(
            root=r,
            sig=s,
            pubkey=p,
            bits=np.concatenate([sa.bits for sa in by_root[r]]),
        )
        for r, s, p in zip(roots, sigs, pks)
    ]
    return slot_aggs, subnet_aggs


def aggregate_slot(
    atts: list[CommitteeAttestation], mesh=None
) -> tuple[list[SlotAggregate], list[SubnetAggregate]]:
    """Aggregate one slot's committee contributions through the
    three-tier tree on device. Returns (per-root global aggregates,
    per-(subnet, root) partials — the bisection inputs)."""
    if not atts:
        return [], []
    with obs.span("agg.slot", attestations=len(atts)):
        obs.count("agg.committees", len(atts))
        obs.count("agg.signatures", sum(len(a.sigs) for a in atts))
        slot_aggs, subnet_aggs = _aggregate_slot(
            atts, lambda g2, g1: _sum_tier_device(g2, g1, mesh)
        )
        obs.count("agg.subnet_partials", len(subnet_aggs))
        obs.count("agg.global_aggregates", len(slot_aggs))
    return slot_aggs, subnet_aggs


def aggregate_slot_host(
    atts: list[CommitteeAttestation],
) -> tuple[list[SlotAggregate], list[SubnetAggregate]]:
    """The whole-tree host oracle: identical structure and ordering, the
    ``crypto/signature`` fold at every tier — what the bench's parity
    gate (and the serve degrade ladder) compares against."""
    if not atts:
        return [], []
    return _aggregate_slot(atts, _sum_tier_host)


def verify_slot(slot_aggs: list[SlotAggregate], mesh=None) -> list[bool]:
    """Verify what was just built: FastAggregateVerify of each root's
    global aggregate against its aggregate pubkey, through the existing
    batched RLC path (ONE pairing for an all-valid slot)."""
    from eth_consensus_specs_tpu.ops.bls_batch import verify_many

    if not slot_aggs:
        return []
    items = [([sa.pubkey_bytes], sa.root, sa.sig_bytes) for sa in slot_aggs]
    return verify_many(items, mesh=mesh)


def isolate_invalid_subnets(
    subnet_aggs: list[SubnetAggregate], mesh=None
) -> list[tuple[int, bytes]]:
    """Which (subnet, root) partials are invalid? Feeds the per-subnet
    partials through ``verify_many``'s RLC bisection — an all-valid
    fan-in costs ONE pairing, each bad subnet ~2*log2(n) more — and
    returns the isolated (subnet, root) pairs."""
    from eth_consensus_specs_tpu.ops.bls_batch import verify_many

    if not subnet_aggs:
        return []
    items = [([sa.pubkey_bytes], sa.root, sa.sig_bytes) for sa in subnet_aggs]
    verdicts = verify_many(items, mesh=mesh)
    bad = [
        (sa.subnet, sa.root) for sa, ok in zip(subnet_aggs, verdicts) if not ok
    ]
    if bad:
        obs.count("agg.isolated_invalid", len(bad))
    return bad
