"""Device G1 multi-scalar multiplication over limb arithmetic.

The hot BLS reductions (aggregate-pubkey sums, KZG commitment MSMs, the
RLC batch-verification combine) are all sum_i k_i * P_i over G1. Here the
whole MSM runs on device: branchless Jacobian point arithmetic (a = 0
short-Weierstrass, infinity encoded as Z = 0, every case handled by
`where` masks so there is no data-dependent control flow), a vmapped
256-bit double-and-add per (scalar, point) lane, then a log2 pairwise
tree reduction — the same shape as the merkle tree reduce, but over
point adds (reference native analogue: arkworks `multiexp_unchecked`
behind utils/bls.py:262-296).

Doubling is dbl-2009-l (2M+5S), addition add-2007-bl (11M+5S); both are
composed from ops/field_limbs Montgomery primitives, so one MSM lane is
~20k u64 lane-multiplies per scalar bit — embarrassingly parallel across
points, which is exactly what the VPU wants.

Conversion boundary: affine crypto/curve.Point <-> Montgomery limb arrays
on host; the single final Jacobian->affine inversion also stays host-side
(one modular inverse per MSM, not worth a device Fermat chain yet).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.crypto.curve import Point, B1, g1_infinity
from eth_consensus_specs_tpu.crypto.fields import Fq, P as P_INT

from .field_limbs import (
    N_LIMBS,
    add_mod,
    from_mont_int,
    is_zero,
    mont_mul,
    mont_sqr,
    sub_mod,
    to_mont,
)

SCALAR_BITS = 256


def _dbl(X, Y, Z):
    """dbl-2009-l (a=0). Infinity (Z=0) and Y=0 both yield Z3=0."""
    A = mont_sqr(X)
    B = mont_sqr(Y)
    C = mont_sqr(B)
    t = mont_sqr(add_mod(X, B))
    D = sub_mod(sub_mod(t, A), C)
    D = add_mod(D, D)  # 2*((X+B)^2 - A - C)
    E = add_mod(add_mod(A, A), A)  # 3A
    F = mont_sqr(E)
    X3 = sub_mod(F, add_mod(D, D))
    C8 = add_mod(C, C)
    C8 = add_mod(C8, C8)
    C8 = add_mod(C8, C8)
    Y3 = sub_mod(mont_mul(E, sub_mod(D, X3)), C8)
    YZ = mont_mul(Y, Z)
    Z3 = add_mod(YZ, YZ)
    return X3, Y3, Z3


def _select(mask, a, b):
    """Per-lane select over limb arrays: mask ? a : b."""
    return jnp.where(mask[..., None], a, b)


def _add(X1, Y1, Z1, X2, Y2, Z2):
    """Complete Jacobian add via masked case analysis (add-2007-bl core)."""
    Z1Z1 = mont_sqr(Z1)
    Z2Z2 = mont_sqr(Z2)
    U1 = mont_mul(X1, Z2Z2)
    U2 = mont_mul(X2, Z1Z1)
    S1 = mont_mul(mont_mul(Y1, Z2), Z2Z2)
    S2 = mont_mul(mont_mul(Y2, Z1), Z1Z1)
    H = sub_mod(U2, U1)
    rr = sub_mod(S2, S1)
    r2 = add_mod(rr, rr)
    HH = add_mod(H, H)
    I = mont_sqr(HH)
    J = mont_mul(H, I)
    V = mont_mul(U1, I)
    X3 = sub_mod(sub_mod(mont_sqr(r2), J), add_mod(V, V))
    SJ = mont_mul(S1, J)
    Y3 = sub_mod(mont_mul(r2, sub_mod(V, X3)), add_mod(SJ, SJ))
    ZZ = sub_mod(sub_mod(mont_sqr(add_mod(Z1, Z2)), Z1Z1), Z2Z2)
    Z3 = mont_mul(ZZ, H)

    p1_inf = is_zero(Z1)
    p2_inf = is_zero(Z2)
    same_x = is_zero(H)
    same_y = is_zero(rr)

    dX, dY, dZ = _dbl(X1, Y1, Z1)

    # default: generic add; same point: double; opposite points: infinity
    outX = _select(same_x & same_y, dX, X3)
    outY = _select(same_x & same_y, dY, Y3)
    outZ = _select(same_x & same_y, dZ, _select(same_x, jnp.zeros_like(Z3), Z3))
    # either input at infinity: pass the other through
    outX = _select(p1_inf, X2, _select(p2_inf, X1, outX))
    outY = _select(p1_inf, Y2, _select(p2_inf, Y1, outY))
    outZ = _select(p1_inf, Z2, _select(p2_inf, Z1, outZ))
    return outX, outY, outZ


def _scalar_mul_lane(bits, X, Y, Z):
    """Double-and-add over MSB-first `bits` (u64[256]) for one lane; runs
    under vmap so every op broadcasts across lanes."""

    def body(i, acc):
        aX, aY, aZ = acc
        aX, aY, aZ = _dbl(aX, aY, aZ)
        sX, sY, sZ = _add(aX, aY, aZ, X, Y, Z)
        take = bits[i] != 0
        return (
            _select(take, sX, aX),
            _select(take, sY, aY),
            _select(take, sZ, aZ),
        )

    inf = (jnp.zeros_like(X), jnp.zeros_like(Y), jnp.zeros_like(Z))
    # i32 loop bounds: python-int bounds widen the bit counter to i64
    # under the package-wide x64 flag (jaxlint x64-drift)
    return lax.fori_loop(jnp.int32(0), jnp.int32(SCALAR_BITS), body, inf)


def _tree_sum(mX, mY, mZ):
    """Pairwise point-sum of N (power-of-two) Jacobian lanes."""
    n = mX.shape[0]
    while n > 1:
        half = n // 2
        mX, mY, mZ = _add(
            mX[:half], mY[:half], mZ[:half], mX[half:], mY[half:], mZ[half:]
        )
        n = half
    return mX[0], mY[0], mZ[0]


@jax.jit
def msm_kernel(bits, X, Y, Z):
    """MSM over N (power-of-two) lanes: bits u64[N,256], X/Y/Z u64[N,13]
    (Montgomery). Returns Jacobian (X,Y,Z) u64[13] of sum_i k_i * P_i."""
    mX, mY, mZ = jax.vmap(_scalar_mul_lane)(bits, X, Y, Z)
    return _tree_sum(mX, mY, mZ)


@jax.jit
def sum_kernel(X, Y, Z):
    """Plain point sum over N (power-of-two) lanes — the unit-scalar MSM
    without the 256-bit double-and-add (aggregate-pubkey fast path)."""
    return _tree_sum(X, Y, Z)


@jax.jit
def sum_many_kernel(X, Y, Z):
    """Per-item point sums over [I, L, 13] lane arrays (L a power of
    two): the batched aggregate-pubkey kernel — one dispatch sums every
    committee of a flush instead of one dispatch per item."""
    return jax.vmap(_tree_sum)(X, Y, Z)


def _msm_lanes(bits, X, Y, Z):
    """One item's MSM: vmapped double-and-add over its lanes + pairwise
    tree reduce — the shared body of msm_kernel and the batched
    per-item variant below."""
    return _tree_sum(*jax.vmap(_scalar_mul_lane)(bits, X, Y, Z))


@jax.jit
def msm_many_kernel(bits, X, Y, Z):
    """Per-item full-scalar MSMs over [I, L, ...] lane arrays (L a power
    of two): bits u64[I, L, 256], X/Y/Z u64[I, L, 13]. Returns Jacobian
    u64[I, 13] per coordinate — item i is sum_j bits[i,j] * P[i,j].

    This is the KZG batch-verification fold: one flush's RLC combine
    needs TWO independent MSMs (the proof lincomb and the commitment-
    minus-y + proof-z lincomb) and this kernel runs both in ONE
    dispatch instead of two msm_kernel round-trips."""
    return jax.vmap(_msm_lanes)(bits, X, Y, Z)


# == mesh-sharded kernels ==================================================
#
# Two shard axes, matching the two hot call patterns:
#   * ITEM axis (sum_g1_many_device): the RLC batch's per-item committee
#     sums are independent — shard items, no collectives;
#   * LANE axis (msm_g1_device): one big MSM splits its (scalar, point)
#     lanes — each shard tree-sums its lanes, then a cross-shard Jacobian
#     reduction (all_gather of the 3x13-limb partials + the same pairwise
#     tree) combines them. Jacobian addition is exact group math and the
#     final affine conversion is canonical, so any shard count returns
#     byte-identical points.


def _cross_shard_tree_sum(rX, rY, rZ, axes):
    """all_gather per-shard Jacobian partials ([..., 13] each) and
    tree-sum them over the gathered shard axis; non-pow2 shard counts
    pad with infinity lanes (Z = 0)."""
    gX = lax.all_gather(rX, axes)
    gY = lax.all_gather(rY, axes)
    gZ = lax.all_gather(rZ, axes)
    s = gX.shape[0]
    cap = 1 << max(s - 1, 0).bit_length()
    if cap != s:
        pad = ((0, cap - s),) + ((0, 0),) * (gX.ndim - 1)
        gX = jnp.pad(gX, pad)
        gY = jnp.pad(gY, pad)
        gZ = jnp.pad(gZ, pad)
    return _tree_sum(gX, gY, gZ)


_SHARDED_FNS: dict[tuple, object] = {}


def _sharded_fn(mesh: Mesh, kind: str):
    """Per-(mesh, kernel) jitted shard_map entry (cached: the jit cache
    then dedupes per input shape)."""
    key = (mesh, kind)
    fn = _SHARDED_FNS.get(key)
    if fn is not None:
        return fn
    from eth_consensus_specs_tpu.parallel.mesh_ops import BATCH_AXES

    spec = P(BATCH_AXES)
    if kind == "msm":

        def local(bits, X, Y, Z):
            mX, mY, mZ = jax.vmap(_scalar_mul_lane)(bits, X, Y, Z)
            return _cross_shard_tree_sum(*_tree_sum(mX, mY, mZ), BATCH_AXES)

        fn = jax.jit(
            shard_map(local, mesh=mesh, in_specs=spec, out_specs=P(), check_rep=False)
        )
    elif kind == "sum":

        def local(X, Y, Z):
            return _cross_shard_tree_sum(*_tree_sum(X, Y, Z), BATCH_AXES)

        fn = jax.jit(
            shard_map(local, mesh=mesh, in_specs=spec, out_specs=P(), check_rep=False)
        )
    elif kind == "msm_many":
        # per-item MSMs with the LANE axis (axis 1) sharded: each shard
        # double-and-adds + tree-sums its lane slice of every item, then
        # ONE gather combines the [I, 13] partials — the per-item sums
        # ride the same cross-shard Jacobian reduce as the single MSM,
        # so results are byte-identical at any shard count
        lane_spec = P(None, BATCH_AXES)

        def local(bits, X, Y, Z):
            pX, pY, pZ = jax.vmap(_msm_lanes)(bits, X, Y, Z)
            return _cross_shard_tree_sum(pX, pY, pZ, BATCH_AXES)

        fn = jax.jit(
            shard_map(
                local, mesh=mesh, in_specs=lane_spec, out_specs=P(),
                check_rep=False,
            )
        )
    else:  # "sum_many": item axis sharded, no collectives

        def local(X, Y, Z):
            return jax.vmap(_tree_sum)(X, Y, Z)

        fn = jax.jit(
            shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec, check_rep=False)
        )
    _SHARDED_FNS[key] = fn
    return fn


def _clear_sharded_after_fork_in_child() -> None:
    # fork-safety: compiled executables reference the parent's devices
    _SHARDED_FNS.clear()


os.register_at_fork(after_in_child=_clear_sharded_after_fork_in_child)


def mesh_lane_pad(n: int, shards: int) -> int:
    """Lane padding target under `shards`: per-shard lane counts padded
    to a power of two (the per-shard tree reduce needs pow2), total =
    shards * per-shard. For pow2 shard counts this equals the global
    pow2; for non-pow2 meshes it pads strictly less."""
    if shards <= 1:
        n = max(n, 1)
        return 1 << max(n - 1, 0).bit_length()
    per = -(-n // shards)
    per = max(per, 1)
    return shards * (1 << max(per - 1, 0).bit_length())


def many_sum_shape(n_items: int, max_lanes: int, shards: int = 1) -> tuple[int, int]:
    """(item_pad, lane_pad) the batched per-item sum kernel compiles at:
    items pad to per-shard pow2 (x shards), lanes to global pow2 — ONE
    shared shape model for the ops entry point and the serve layer's
    compile accounting, so they can never disagree."""
    return mesh_lane_pad(n_items, shards), mesh_lane_pad(max_lanes, 1)


# == host conversion boundary ==============================================


def _points_to_limbs(points: list) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = len(points)
    X = np.zeros((n, N_LIMBS), np.uint64)
    Y = np.zeros((n, N_LIMBS), np.uint64)
    Z = np.zeros((n, N_LIMBS), np.uint64)
    one = to_mont(1)
    for i, p in enumerate(points):
        if p.is_infinity():
            continue  # Z stays zero
        X[i] = to_mont(p.x.n)
        Y[i] = to_mont(p.y.n)
        Z[i] = one
    return X, Y, Z


def _scalars_to_bits(scalars: list[int]) -> np.ndarray:
    n = len(scalars)
    bits = np.zeros((n, SCALAR_BITS), np.uint64)
    for i, k in enumerate(scalars):
        k = int(k)
        assert 0 <= k < (1 << SCALAR_BITS)
        for j in range(SCALAR_BITS):
            bits[i, j] = (k >> (SCALAR_BITS - 1 - j)) & 1
    return bits


def _jacobian_to_point(X, Y, Z) -> Point:
    z = from_mont_int(np.asarray(Z))
    if z == 0:
        return g1_infinity()
    x = from_mont_int(np.asarray(X))
    y = from_mont_int(np.asarray(Y))
    zinv = pow(z, P_INT - 2, P_INT)
    zinv2 = zinv * zinv % P_INT
    return Point(Fq(x * zinv2 % P_INT), Fq(y * zinv2 % P_INT * zinv % P_INT), B1)


def _pad_lanes(arrs, n: int, cap: int):
    """Pad lane arrays to exactly `cap` lanes with infinity lanes (Z = 0,
    zero scalars)."""
    if cap == n:
        return arrs
    return [
        np.concatenate([a, np.zeros((cap - n,) + a.shape[1:], a.dtype)]) for a in arrs
    ]


def msm_g1_device(points: list, scalars: list[int], mesh: Mesh | None = None) -> Point:
    """Device MSM entry: sum_i scalars[i] * points[i] over G1. With a
    multi-device `mesh` the lanes shard over it (per-shard double-and-add
    + local tree sum, then the cross-shard Jacobian reduction) — the
    affine result is byte-identical to the single-device dispatch."""
    assert len(points) == len(scalars)
    if not points:
        return g1_infinity()
    from eth_consensus_specs_tpu.parallel.mesh_ops import shard_count

    shards = shard_count(mesh)
    if shards <= 1:
        mesh = None
    X, Y, Z = _points_to_limbs(points)
    cap = mesh_lane_pad(len(points), shards)
    if all(int(k) == 1 for k in scalars):
        # aggregate-pubkey fast path: tree sum only, no scalar loop
        X, Y, Z = _pad_lanes([X, Y, Z], len(points), cap)
        args = (jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
        if mesh is not None:
            obs.count("mesh.dispatches", 1)
            obs.count("mesh.sharded_items", len(points))
            rX, rY, rZ = _sharded_fn(mesh, "sum")(*args)
        else:
            rX, rY, rZ = sum_kernel(*args)
    else:
        bits = _scalars_to_bits(scalars)
        bits, X, Y, Z = _pad_lanes([bits, X, Y, Z], len(points), cap)
        args = (jnp.asarray(bits), jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
        if mesh is not None:
            obs.count("mesh.dispatches", 1)
            obs.count("mesh.sharded_items", len(points))
            rX, rY, rZ = _sharded_fn(mesh, "msm")(*args)
        else:
            rX, rY, rZ = msm_kernel(*args)
    return _jacobian_to_point(np.asarray(rX), np.asarray(rY), np.asarray(rZ))


def sum_g1_device(points: list, mesh: Mesh | None = None) -> Point:
    """Device point sum (unit-scalar MSM): sum_i points[i]."""
    return msm_g1_device(points, [1] * len(points), mesh=mesh)


def msm_g1_many_device(
    point_lists: list[list],
    scalar_lists: list[list[int]],
    mesh: Mesh | None = None,
    pad_shape: tuple | None = None,
) -> list[Point]:
    """Independent full-scalar MSMs for many items in ONE dispatch:
    ``[msm_g1(points, scalars) for ...]`` — the KZG RLC fold's seam.
    Lanes pad to the pow2 of the widest item (``pad_shape`` overrides:
    the serve layer passes its bucket so accounting and dispatch
    agree); a multi-device `mesh` shards the LANE axis with the
    cross-shard Jacobian combine, byte-identical to single-device.
    Each result equals ``msm_g1_device(points, scalars)``."""
    n = len(point_lists)
    assert n == len(scalar_lists)
    if n == 0:
        return []
    from eth_consensus_specs_tpu.parallel.mesh_ops import shard_count

    shards = shard_count(mesh)
    if shards <= 1:
        mesh = None
        shards = 1
    max_lanes = max(len(p) for p in point_lists)
    item_pad, lane_pad = pad_shape or (n, mesh_lane_pad(max_lanes, shards))
    assert item_pad >= n and lane_pad >= max_lanes
    bits = np.zeros((item_pad, lane_pad, SCALAR_BITS), np.uint64)
    X = np.zeros((item_pad, lane_pad, N_LIMBS), np.uint64)
    Y = np.zeros((item_pad, lane_pad, N_LIMBS), np.uint64)
    Z = np.zeros((item_pad, lane_pad, N_LIMBS), np.uint64)
    for i, (points, scalars) in enumerate(zip(point_lists, scalar_lists)):
        assert len(points) == len(scalars)
        if points:
            X[i, : len(points)], Y[i, : len(points)], Z[i, : len(points)] = (
                _points_to_limbs(points)
            )
            bits[i, : len(points)] = _scalars_to_bits([int(s) for s in scalars])
    args = (jnp.asarray(bits), jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
    if mesh is not None:
        obs.count("mesh.dispatches", 1)
        obs.count("mesh.sharded_items", n)
        rX, rY, rZ = _sharded_fn(mesh, "msm_many")(*args)
    else:
        rX, rY, rZ = msm_many_kernel(*args)
    rX, rY, rZ = np.asarray(rX), np.asarray(rY), np.asarray(rZ)
    return [_jacobian_to_point(rX[i], rY[i], rZ[i]) for i in range(n)]


def sum_g1_many_device(
    point_lists: list[list], mesh: Mesh | None = None, pad_shape: tuple | None = None
) -> list[Point]:
    """Per-item point sums for many committees in ONE dispatch:
    ``[sum(points) for points in point_lists]``. Lanes pad to the pow2 of
    the largest committee, items to the :func:`many_sum_shape` bucket
    (``pad_shape`` overrides — the serve layer passes its own bucket so
    accounting and dispatch agree); a multi-device `mesh` shards the item
    axis. Each result is byte-identical to ``sum_g1_device(points)``."""
    n = len(point_lists)
    if n == 0:
        return []
    from eth_consensus_specs_tpu.parallel.mesh_ops import shard_count

    shards = shard_count(mesh)
    if shards <= 1:
        mesh = None
    max_lanes = max(len(p) for p in point_lists)
    item_pad, lane_pad = pad_shape or many_sum_shape(n, max_lanes, shards)
    assert item_pad >= n and lane_pad >= max_lanes
    X = np.zeros((item_pad, lane_pad, N_LIMBS), np.uint64)
    Y = np.zeros((item_pad, lane_pad, N_LIMBS), np.uint64)
    Z = np.zeros((item_pad, lane_pad, N_LIMBS), np.uint64)
    one = to_mont(1)
    for i, points in enumerate(point_lists):
        for j, p in enumerate(points):
            if p.is_infinity():
                continue  # Z stays zero
            X[i, j] = to_mont(p.x.n)
            Y[i, j] = to_mont(p.y.n)
            Z[i, j] = one
    args = (jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
    if mesh is not None:
        obs.count("mesh.dispatches", 1)
        obs.count("mesh.sharded_items", n)
        rX, rY, rZ = _sharded_fn(mesh, "sum_many")(*args)
    else:
        rX, rY, rZ = sum_many_kernel(*args)
    rX, rY, rZ = np.asarray(rX), np.asarray(rY), np.asarray(rZ)
    return [_jacobian_to_point(rX[i], rY[i], rZ[i]) for i in range(n)]
