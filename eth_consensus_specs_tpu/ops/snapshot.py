"""Durable resident state: digest-verified checkpoint/restore + scrub.

PR 11 made the validator state and the merkle forest HBM-resident with
donated in-place epoch chains; a SIGKILLed owner lost all of it and the
respawn re-ingested from host columns with no integrity story in
between. The forest is self-authenticating — every internal level is a
hash of the level below — so durability can be *digest-gated* rather
than trusted:

  * :func:`checkpoint` serializes a ``StateForest`` + the resident
    columns into a content-addressed blob store (``objects/<sha256>``)
    with an atomically-committed manifest (per-tree level digests, the
    combined state root, epoch lineage). Every blob write is the
    dumper's write -> read-back -> verify -> ``os.replace`` discipline
    (gen/dumper.py, PR 2); the manifest commits after its blobs and the
    ``LATEST`` pointer commits last, so a mid-write SIGKILL leaves the
    previous checkpoint intact — never a torn current one. Incremental
    mode skips blobs whose digest already exists: unchanged subtree
    shards (the ones ``post_epoch_state_root_inc`` never dirtied)
    produce byte-identical buffers, so only dirty content hits disk,
    and a full and an incremental checkpoint of the same state agree
    on ``content_digest`` by construction.
  * :func:`restore` verifies the manifest digest, every blob digest,
    and then REFUSES to serve unless the forest re-verifies on device:
    all internal levels rebuilt from the restored leaves bit-match the
    restored buffers AND the recomputed combined state root bit-matches
    the manifest. Failures raise :class:`SnapshotError` subclasses
    carrying ``degradable = True`` — environmental damage, not logic
    errors — so ``fault.degrade("resident.restore", ...)`` falls back
    to a full host re-ingest rather than ever serving a wrong answer.
  * :func:`scrub_forest` re-hashes K randomly-salted subtrees per call
    against the resident parent nodes (one ``lax``-level kernel: gather
    leaves -> build_levels -> compare, plus the full upper region above
    the subtree cut every pass), counting
    ``resident.scrub.{checks,mismatches}``. A mismatch is silent HBM
    corruption caught in the act: the caller quarantines the tree
    (:func:`quarantine_rebuild` — recompute every internal level from
    the resident leaves) and re-verifies the root before serving again.

Fault sites (fault/sites.py): ``resident.checkpoint`` (raise/kill/
stall/corrupt at the blob-write seam), ``resident.restore`` (raise/
stall/corrupt at the blob-read seam), ``resident.scrub`` (raise, plus
corrupt on the root it reports — drives the mismatch path end to end
through the deterministic grammar).
"""

from __future__ import annotations

import hashlib
import json
import os
from functools import lru_cache, partial
from typing import NamedTuple

import numpy as np

from eth_consensus_specs_tpu import fault, obs

MANIFEST_VERSION = 1
_OBJECTS = "objects"
_LATEST = "LATEST"
_TREES = ("val_nodes", "bal_nodes", "inact_nodes")
# subtree cut depth of one scrub check: 2^5 leaves re-hashed per sample
SCRUB_SUBTREE_DEPTH = 5


class SnapshotError(RuntimeError):
    """Checkpoint/restore integrity failure. ``degradable`` marks it as
    environmental damage (torn write, bit rot, injected corruption) —
    NOT a logic error — so the fault.degrade ladder may fall back to a
    full host re-ingest instead of propagating."""

    degradable = True


class TornCheckpoint(SnapshotError):
    """A blob or manifest failed its digest check (torn/corrupt on disk
    or on the read path)."""


class RestoreMismatch(SnapshotError):
    """The restored forest failed device re-verification: rebuilt
    levels or the recomputed state root disagree with the manifest.
    The restore REFUSES to serve this state."""


# ------------------------------------------------------------- encoding --


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _arr_bytes(a) -> bytes:
    return np.ascontiguousarray(np.asarray(a)).tobytes()


def _arr_meta(a) -> dict:
    host = np.asarray(a)
    return {"dtype": host.dtype.name, "shape": list(host.shape)}


def _decode(data: bytes, meta: dict) -> np.ndarray:
    return np.frombuffer(data, dtype=np.dtype(meta["dtype"])).reshape(
        tuple(meta["shape"])
    )


def _words_bytes(words: np.ndarray) -> bytes:
    """u32[8] root words -> the canonical 32 big-endian bytes."""
    return np.asarray(words, np.uint32).astype(">u4").tobytes()


def _host_combine(shard_roots: np.ndarray) -> bytes:
    """[S, 8] per-shard root words -> the tree root bytes via the same
    log-depth pairwise combine forest_root performs on device."""
    level = [_words_bytes(shard_roots[i]) for i in range(shard_roots.shape[0])]
    while len(level) > 1:
        level = [
            hashlib.sha256(level[2 * i] + level[2 * i + 1]).digest()
            for i in range(len(level) // 2)
        ]
    return level[0]


def _level_layout(n_nodes: int) -> list[tuple[int, int]]:
    """Flat-buffer (offset, width) of every level of a tree with
    ``n_nodes = 2^(dl+1)-1`` rows — leaves first, root last (the
    merkle_inc layout: level k starts at cap2 - (cap2 >> k))."""
    cap2 = n_nodes + 1
    out = []
    k = 0
    while (cap2 >> (k + 1)) >= 1:
        out.append((cap2 - (cap2 >> k), cap2 >> (k + 1)))
        k += 1
    return out


def _tree_level_digests(nodes: np.ndarray) -> list[str]:
    """Per-level content digests over ALL shards of one forest tree —
    the manifest's self-description of the internal levels."""
    return [
        _digest(_arr_bytes(nodes[:, off : off + width, :]))
        for off, width in _level_layout(nodes.shape[-2])
    ]


# ----------------------------------------------------------- blob store --


def _objects_dir(root_dir: str) -> str:
    return os.path.join(root_dir, _OBJECTS)


def _write_verified(path: str, data: bytes, site: str, want: str) -> None:
    """ONE verified write attempt: corrupt seam -> write -> read back ->
    digest check -> atomic rename (the dumper's discipline)."""
    payload = fault.corrupt(site, data)
    tmp = f"{path}.__tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
    with open(tmp, "rb") as f:
        back = f.read()
    if _digest(back) != want:
        os.unlink(tmp)
        obs.count("resident.torn_writes", 1)
        raise TornCheckpoint(f"write verify failed for {os.path.basename(path)}")
    os.replace(tmp, path)


def _put_blob(
    root_dir: str, data: bytes, *, incremental: bool, site: str = "resident.checkpoint"
) -> tuple[str, bool]:
    """Store one content-addressed blob; returns (digest, written).
    Incremental mode trusts an existing digest file (content addressing
    makes the skip exact — same digest IS same bytes); full mode reads
    any existing blob back and re-verifies it, rewriting on damage."""
    dig = _digest(data)
    final = os.path.join(_objects_dir(root_dir), dig)
    if os.path.exists(final):
        if incremental:
            return dig, False
        try:
            with open(final, "rb") as f:
                if _digest(f.read()) == dig:
                    return dig, False
        except OSError:
            pass  # unreadable: fall through to the rewrite
    fault.retrying(
        lambda: _write_verified(final, data, site, dig),
        name="resident.checkpoint.blob",
        attempts=3,
        retry_on=(TornCheckpoint, OSError),
        base_delay=0.01,
    )
    return dig, True


def _get_blob(root_dir: str, dig: str, site: str = "resident.restore") -> bytes:
    path = os.path.join(_objects_dir(root_dir), dig)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as exc:
        raise TornCheckpoint(f"missing checkpoint blob {dig[:12]}: {exc}") from exc
    # the read-path corrupt seam: an injected flip here must be caught
    # by the digest check below, never restored into the forest
    data = fault.corrupt(site, data)
    if _digest(data) != dig:
        raise TornCheckpoint(f"checkpoint blob {dig[:12]} failed its digest check")
    return data


# ---------------------------------------------------------- checkpoints --


class CheckpointResult(NamedTuple):
    manifest: dict
    digest: str  # sha256 of the committed manifest file bytes
    path: str
    written: int  # blobs that hit disk
    reused: int  # blobs already present (the incremental savings)


def _checkpoint_tree(
    root_dir: str, nodes, *, incremental: bool
) -> tuple[dict, int, int]:
    host = np.asarray(nodes)
    shards, written, reused = [], 0, 0
    for i in range(host.shape[0]):
        dig, wrote = _put_blob(root_dir, _arr_bytes(host[i]), incremental=incremental)
        shards.append(dig)
        written += int(wrote)
        reused += int(not wrote)
    entry = {
        **_arr_meta(host),
        "shards": shards,
        "levels": _tree_level_digests(host),
        "root": _host_combine(host[:, -1, :]).hex(),
    }
    return entry, written, reused


def _checkpoint_fields(
    root_dir: str, tree, *, incremental: bool
) -> tuple[dict, int, int]:
    out, written, reused = {}, 0, 0
    for name, val in tree._asdict().items():
        if val is None:
            out[name] = None
            continue
        dig, wrote = _put_blob(root_dir, _arr_bytes(val), incremental=incremental)
        out[name] = {**_arr_meta(val), "blob": dig}
        written += int(wrote)
        reused += int(not wrote)
    return out, written, reused


def checkpoint(
    root_dir: str,
    forest,
    cols,
    just,
    *,
    epoch: int,
    plan,
    static=None,
    state_root: bytes | None = None,
    epoch0: int = 0,
    incremental: bool = True,
    extra: dict | None = None,
) -> CheckpointResult:
    """Commit one durable checkpoint of the resident state. Runs OUTSIDE
    the donated jit chain (host fetch of the forest + columns). `static`
    is the (arrays, meta) pair from ingest_full — when given and
    ``state_root`` is not, the manifest root is recomputed on device via
    the shared state_root_from_forest gate. ``extra`` is an optional
    JSON-serializable owner payload (e.g. the slot pipeline's applied-
    slot dedup window) stored INSIDE the digest-covered content — a
    flipped byte in it torns the checkpoint like any other field.
    Returns the committed manifest; crash-safe at every byte: blobs
    commit before the manifest, the manifest before LATEST, all via
    os.replace."""
    fault.check("resident.checkpoint")
    os.makedirs(_objects_dir(root_dir), exist_ok=True)
    if state_root is None and static is not None:
        state_root = state_root_bytes(static, plan, forest, just)

    written = reused = 0
    trees: dict[str, dict | None] = {}
    total_bytes = 0
    for name in _TREES:
        nodes = getattr(forest, name)
        if nodes is None:
            trees[name] = None
            continue
        entry, w, r = _checkpoint_tree(root_dir, nodes, incremental=incremental)
        trees[name] = entry
        written += w
        reused += r
        total_bytes += int(np.asarray(nodes).nbytes)
    part_dig, wrote = _put_blob(
        root_dir, _arr_bytes(forest.part_root), incremental=incremental
    )
    written += int(wrote)
    reused += int(not wrote)
    trees["part_root"] = {**_arr_meta(forest.part_root), "blob": part_dig}

    cols_entry, w, r = _checkpoint_fields(root_dir, cols, incremental=incremental)
    written += w
    reused += r
    just_entry, w, r = _checkpoint_fields(root_dir, just, incremental=incremental)
    written += w
    reused += r

    content = {
        "epoch": int(epoch),
        "state_root": state_root.hex() if state_root else None,
        "trees": trees,
        "columns": {"cols": cols_entry, "just": just_entry},
    }
    if extra is not None:
        content["extra"] = extra
    parent = None
    try:
        prev = latest(root_dir)
        if prev is not None:
            parent = prev[1]
    except TornCheckpoint:
        parent = None  # a torn predecessor never blocks a NEW checkpoint
    manifest = {
        "version": MANIFEST_VERSION,
        **content,
        "content_digest": _digest(
            json.dumps(content, sort_keys=True).encode()
        ),
        "epoch_span": [int(epoch0), int(epoch)],
        "parent": parent,
        "incremental": bool(incremental),
        "plan": list(plan),
        "counts": {"written": written, "reused": reused},
    }
    with obs.span("resident.checkpoint", work_bytes=total_bytes, epoch=int(epoch)):
        data = json.dumps(manifest, sort_keys=True).encode()
        dig = _digest(data)
        name = f"manifest-{int(epoch):08d}.json"
        path = os.path.join(root_dir, name)
        fault.retrying(
            lambda: _write_verified(path, data, "resident.checkpoint", dig),
            name="resident.checkpoint.manifest",
            attempts=3,
            retry_on=(TornCheckpoint, OSError),
            base_delay=0.01,
        )
        pointer = json.dumps({"manifest": name, "digest": dig}).encode()
        tmp = os.path.join(root_dir, f"{_LATEST}.__tmp{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(pointer)
        os.replace(tmp, os.path.join(root_dir, _LATEST))
    obs.count("resident.checkpoints", 1)
    obs.count("resident.checkpoint_blobs_written", written)
    obs.count("resident.checkpoint_blobs_reused", reused)
    return CheckpointResult(
        manifest=manifest, digest=dig, path=path, written=written, reused=reused
    )


def latest(root_dir: str) -> tuple[dict, str] | None:
    """(manifest, manifest_digest) of the committed LATEST checkpoint,
    or None when the store has none. Raises TornCheckpoint when the
    pointer names a manifest that is missing or fails its digest."""
    try:
        with open(os.path.join(root_dir, _LATEST), "rb") as f:
            pointer = json.loads(f.read())
    except (OSError, ValueError):
        return None
    name, want = pointer.get("manifest", ""), pointer.get("digest", "")
    try:
        with open(os.path.join(root_dir, name), "rb") as f:
            data = f.read()
    except OSError as exc:
        raise TornCheckpoint(f"LATEST points at missing manifest {name}") from exc
    if _digest(data) != want:
        raise TornCheckpoint(f"manifest {name} failed its digest check")
    return json.loads(data), want


# -------------------------------------------------------------- restore --


class RestoredState(NamedTuple):
    forest: object  # StateForest (device)
    cols: object  # AltairEpochColumns (device)
    just: object  # JustificationState (device)
    plan: object  # ForestPlan from the manifest
    manifest: dict
    digest: str
    epoch: int
    verdict: str  # "verified-device" | "verified-host"


@lru_cache(maxsize=None)
def _rebuild_check_kernel(n_nodes: int):
    """jit: rebuild every internal level from the restored leaf level
    and compare — ok iff the restored buffers are internally exact."""
    import jax
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops import merkle_inc

    leaves_w = (n_nodes + 1) // 2

    @jax.jit
    def run(nodes):
        rebuilt = merkle_inc.build_levels(nodes[:, :leaves_w, :])
        return jnp.all(rebuilt == nodes)

    return run


@lru_cache(maxsize=None)
def _rebuild_kernel(n_nodes: int):
    """jit (donating): recompute every internal level from the resident
    leaves — the quarantine-and-rebuild step after a scrub mismatch."""
    import jax

    from eth_consensus_specs_tpu.ops import merkle_inc

    leaves_w = (n_nodes + 1) // 2

    @partial(jax.jit, donate_argnums=(0,))
    def run(nodes):
        return merkle_inc.build_levels(nodes[:, :leaves_w, :])

    return run


@lru_cache(maxsize=None)
def _root_kernel(plan, meta):
    import jax

    from eth_consensus_specs_tpu.ops.state_root import state_root_from_forest

    @jax.jit
    def run(arrays, forest, just):
        return state_root_from_forest(arrays, meta, plan, forest, just)

    return run


def state_root_bytes(static, plan, forest, just) -> bytes:
    """The canonical combined state root of a resident forest as 32
    bytes — ONE device dispatch of the shared digest gate."""
    import jax

    from eth_consensus_specs_tpu.serve import buckets

    arrays, meta = static
    run = _root_kernel(plan, meta)
    with buckets.first_dispatch("resident_root", meta.n_validators, plan.shards):
        root = run(jax.device_put(arrays), forest, just)
    return _words_bytes(np.asarray(root))


def _restore_tree(root_dir: str, entry: dict):
    host = np.stack(
        [
            _decode(_get_blob(root_dir, dig), {**entry, "shape": entry["shape"][1:]})
            for dig in entry["shards"]
        ]
    )
    return host


def _restore_fields(root_dir: str, entry: dict, cls):
    import jax

    vals = {}
    for name, meta in entry.items():
        vals[name] = (
            None
            if meta is None
            else jax.device_put(_decode(_get_blob(root_dir, meta["blob"]), meta))
        )
    return cls(**vals)


def _host_verify_tree(name: str, host: np.ndarray, entry: dict) -> None:
    """Host re-hash of one restored tree: every internal level recomputed
    with hashlib from the level below, compared byte-for-byte — the
    device-free leg of the refusal gate (plus the level digests, which
    pin the restored bytes to the manifest's)."""
    layout = _level_layout(host.shape[-2])
    for k, dig in enumerate(entry["levels"]):
        off, width = layout[k]
        if _digest(_arr_bytes(host[:, off : off + width, :])) != dig:
            raise RestoreMismatch(f"{name}: level {k} digest mismatch after restore")
    be = host.astype(">u4")
    for k in range(len(layout) - 1):
        off, width = layout[k]
        p_off, p_width = layout[k + 1]
        child = be[:, off : off + width, :].reshape(host.shape[0], width // 2, 16)
        for s in range(host.shape[0]):
            for j in range(p_width):
                got = hashlib.sha256(child[s, j].tobytes()).digest()
                if got != be[s, p_off + j].tobytes():
                    raise RestoreMismatch(
                        f"{name}: rebuilt node ({s}, level {k + 1}, {j}) "
                        "disagrees with the restored buffer"
                    )


def restore(root_dir: str, *, static=None, verify: str = "device") -> RestoredState | None:
    """Rebuild the resident state from the LATEST checkpoint — REFUSING
    to serve unless it re-verifies. ``verify="device"`` (requires
    ``static``): every tree's internal levels are rebuilt on device
    from the restored leaves and compared, and the combined state root
    is recomputed and bit-matched against the manifest.
    ``verify="host"`` re-hashes the level chain with hashlib instead
    (no device work — the torn-checkpoint unit tests run here).
    Returns None when the store holds no checkpoint at all; raises
    TornCheckpoint / RestoreMismatch (both ``degradable``) on damage."""
    import jax

    from eth_consensus_specs_tpu.ops.altair_epoch import AltairEpochColumns
    from eth_consensus_specs_tpu.ops.state_columns import JustificationState
    from eth_consensus_specs_tpu.ops.state_root import ForestPlan, StateForest
    from eth_consensus_specs_tpu.serve import buckets

    fault.check("resident.restore")
    found = latest(root_dir)
    if found is None:
        return None
    manifest, dig = found
    plan = ForestPlan(*manifest["plan"])
    nbytes = 0
    with obs.span(
        "resident.restore", work_bytes=0, epoch=int(manifest["epoch"])
    ):
        host_trees: dict[str, np.ndarray | None] = {}
        for name in _TREES:
            entry = manifest["trees"][name]
            if entry is None:
                host_trees[name] = None
                continue
            host = _restore_tree(root_dir, entry)
            nbytes += host.nbytes
            if verify == "host":
                _host_verify_tree(name, host, entry)
            if _host_combine(host[:, -1, :]).hex() != entry["root"]:
                raise RestoreMismatch(f"{name}: restored root disagrees with manifest")
            host_trees[name] = host
        part_entry = manifest["trees"]["part_root"]
        part_root = _decode(_get_blob(root_dir, part_entry["blob"]), part_entry)
        forest = StateForest(
            val_nodes=jax.device_put(host_trees["val_nodes"]),
            bal_nodes=jax.device_put(host_trees["bal_nodes"]),
            inact_nodes=(
                None
                if host_trees["inact_nodes"] is None
                else jax.device_put(host_trees["inact_nodes"])
            ),
            part_root=jax.device_put(part_root),
        )
        cols = _restore_fields(root_dir, manifest["columns"]["cols"], AltairEpochColumns)
        just = _restore_fields(
            root_dir, manifest["columns"]["just"], JustificationState
        )
        if verify == "device":
            for name in _TREES:
                nodes = getattr(forest, name)
                if nodes is None:
                    continue
                run = _rebuild_check_kernel(nodes.shape[-2])
                with buckets.first_dispatch(
                    "resident_verify", nodes.shape[0], nodes.shape[-2]
                ):
                    ok = bool(run(nodes))
                if not ok:
                    raise RestoreMismatch(
                        f"{name}: device-rebuilt levels disagree with the "
                        "restored buffers"
                    )
            if static is not None and manifest["state_root"]:
                got = state_root_bytes(static, plan, forest, just)
                if got.hex() != manifest["state_root"]:
                    raise RestoreMismatch(
                        "recomputed state root disagrees with the manifest — "
                        "refusing to serve this checkpoint"
                    )
    obs.count("resident.restores", 1)
    return RestoredState(
        forest=forest,
        cols=cols,
        just=just,
        plan=plan,
        manifest=manifest,
        digest=dig,
        epoch=int(manifest["epoch"]),
        verdict=f"verified-{verify}",
    )


# ---------------------------------------------------------------- scrub --


class ScrubReport(NamedTuple):
    checks: int
    mismatches: int
    # tree name -> global subtree positions (shard*per_shard + pos) that
    # failed their re-hash, or -1 for an upper-region mismatch
    bad: dict[str, list[int]]
    root: bytes  # the combined val-tree root observed during the pass


@lru_cache(maxsize=None)
def _scrub_kernel(n_nodes: int, sub_depth: int, k: int):
    """jit: re-hash K subtrees of one forest tree from their resident
    leaves and compare against the resident parent row, PLUS rebuild the
    whole upper region (level sub_depth -> root — shrinking widths, a
    tiny fraction of the tree) against the stored rows. A flipped word
    anywhere ABOVE the cut is caught every pass; below it, with
    K/coverage probability per pass."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from eth_consensus_specs_tpu.ops import merkle_inc

    dl = merkle_inc.tree_depth(n_nodes)
    cap2 = n_nodes + 1
    w = 1 << sub_depth
    off_sd = cap2 - (cap2 >> sub_depth)
    n_sub = 1 << (dl - sub_depth)

    # resident rows of subtree `pos`, level-blocked in exactly the
    # layout build_levels emits (leaves first, root last): level j of
    # the tree starts at cap2 - (cap2 >> j); the subtree owns w >> j
    # consecutive rows there starting at pos * (w >> j)
    level_offs = [cap2 - (cap2 >> j) for j in range(sub_depth + 1)]
    level_widths = [w >> j for j in range(sub_depth + 1)]

    @jax.jit
    def run(nodes, sidx, pos):
        flat = nodes.reshape(-1, 8)
        base = sidx * jnp.int32(n_nodes)
        li = (base + pos * jnp.int32(w))[:, None] + jnp.arange(w, dtype=jnp.int32)
        leaves = jnp.take(flat, li.reshape(-1), axis=0).reshape(k, w, 8)
        rebuilt = merkle_inc.build_levels(leaves)  # [K, 2w-1, 8]
        parts = [
            (base + jnp.int32(off) + pos * jnp.int32(wj))[:, None]
            + jnp.arange(wj, dtype=jnp.int32)
            for off, wj in zip(level_offs, level_widths)
        ]
        si = jnp.concatenate(parts, axis=1)  # [K, 2w-1]
        stored = jnp.take(flat, si.reshape(-1), axis=0).reshape(k, 2 * w - 1, 8)
        low_bad = jnp.any(rebuilt != stored, axis=(-2, -1))
        upper = merkle_inc.build_levels(
            lax.slice_in_dim(nodes, off_sd, off_sd + n_sub, axis=1)
        )
        upper_bad = jnp.any(upper != lax.slice_in_dim(nodes, off_sd, n_nodes, axis=1))
        return low_bad, upper_bad, merkle_inc.forest_root(nodes)

    return run


def _salted_positions(salt: int, tree: str, k: int, total: int) -> list[int]:
    """K deterministic pseudo-random subtree positions for this (salt,
    tree) — sha256-derived so a chaos run and its re-run scrub the same
    subtrees (no RNG, the fault grammar's determinism rule)."""
    out = []
    for i in range(k):
        h = hashlib.sha256(f"scrub:{salt}:{tree}:{i}".encode()).digest()
        out.append(int.from_bytes(h[:8], "big") % total)
    return out


def scrub_forest(
    forest,
    *,
    k: int = 8,
    salt: int = 0,
    expect_root: bytes | None = None,
    sub_depth: int = SCRUB_SUBTREE_DEPTH,
) -> ScrubReport:
    """One scrub pass over every tree of a resident forest. Counts
    ``resident.scrub.checks`` / ``resident.scrub.mismatches``; a
    mismatch triggers a postmortem bundle (the caller quarantines via
    :func:`quarantine_rebuild`). ``expect_root`` additionally compares
    the observed val-tree root (after the ``resident.scrub`` corrupt
    seam — the chaos lever) against the last known-good root."""
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops import merkle_inc
    from eth_consensus_specs_tpu.serve import buckets

    fault.check("resident.scrub")
    checks = mismatches = 0
    bad: dict[str, list[int]] = {}
    root = b""
    nbytes = sum(
        int(np.asarray(t).nbytes)
        for t in (forest.val_nodes, forest.bal_nodes, forest.inact_nodes)
        if t is not None
    )
    with obs.span("resident.scrub", work_bytes=nbytes, k=k, salt=salt):
        for name in _TREES:
            nodes = getattr(forest, name)
            if nodes is None:
                continue
            s, m = nodes.shape[0], nodes.shape[-2]
            dl = merkle_inc.tree_depth(m)
            sd = min(sub_depth, dl)
            per_shard = 1 << (dl - sd)
            total = s * per_shard
            kk = min(k, total)
            positions = _salted_positions(salt, name, kk, total)
            sidx = jnp.asarray([p // per_shard for p in positions], jnp.int32)
            pos = jnp.asarray([p % per_shard for p in positions], jnp.int32)
            run = _scrub_kernel(m, sd, kk)
            with buckets.first_dispatch("resident_scrub", s, m, sd, kk):
                low_bad, upper_bad, tree_root = run(nodes, sidx, pos)
            low_bad = np.asarray(low_bad)
            checks += kk + 1  # +1: the always-on upper-region sweep
            tree_bad = [p for p, b in zip(positions, low_bad) if b]
            if bool(upper_bad):
                tree_bad.append(-1)
            if tree_bad:
                bad[name] = tree_bad
                mismatches += len(tree_bad)
            if name == "val_nodes":
                root = _words_bytes(np.asarray(tree_root))
    if expect_root is not None and root:
        # the chaos seam: a resident.scrub:corrupt rule flips a byte of
        # the observed root here — detected exactly like real HBM rot
        observed = fault.corrupt("resident.scrub", root)
        if observed != expect_root:
            mismatches += 1
            bad.setdefault("val_nodes", []).append(-1)
    obs.count("resident.scrub.checks", checks)
    if mismatches:
        obs.count("resident.scrub.mismatches", mismatches)
        obs.event("resident.scrub_mismatch", bad={k: v[:8] for k, v in bad.items()})
        obs.flight.trigger_dump(
            "resident.scrub", detail=",".join(sorted(bad)), extra={"bad": bad}
        )
    return ScrubReport(checks=checks, mismatches=mismatches, bad=bad, root=root)


def quarantine_rebuild(forest, tree: str):
    """Quarantine-and-rebuild one tree after a scrub mismatch: recompute
    every internal level from the RESIDENT leaves (the leaves are the
    authority; a corrupted internal node heals, a corrupted leaf
    surfaces as a root mismatch the caller must re-verify — and degrade
    to re-ingest when it persists). Donates the damaged buffers."""
    nodes = getattr(forest, tree)
    if nodes is None:
        return forest
    from eth_consensus_specs_tpu.serve import buckets

    run = _rebuild_kernel(nodes.shape[-2])
    with buckets.first_dispatch("resident_rebuild", nodes.shape[0], nodes.shape[-2]):
        rebuilt = run(nodes)
    obs.count("resident.scrub.quarantines", 1)
    obs.event("resident.quarantine_rebuild", tree=tree)
    return forest._replace(**{tree: rebuilt})


def flip_resident_word(forest, tree: str, node: int, word: int = 0):
    """Deliberately flip one u32 word of a resident tree (test/chaos
    helper — the 'silent HBM corruption' the scrub pass must catch).
    Returns the damaged forest; the original buffers are not donated."""
    import jax.numpy as jnp

    nodes = getattr(forest, tree)
    flipped = nodes.at[0, node, word].set(nodes[0, node, word] ^ jnp.uint32(0xDEADBEEF))
    return forest._replace(**{tree: flipped})


def _clear_kernels_after_fork_in_child() -> None:
    # fork-safety: cached executables reference the parent's devices
    _rebuild_check_kernel.cache_clear()
    _rebuild_kernel.cache_clear()
    _root_kernel.cache_clear()
    _scrub_kernel.cache_clear()


os.register_at_fork(after_in_child=_clear_kernels_after_fork_in_child)
