"""Batched optimal-ate pairing on the device (BLS12-381).

TPU-first split of the pairing:

* **Host "preparation"** (`prepare_g2`): the Miller loop's G2 side — the
  tangent/chord slopes and the T-point walk — depends ONLY on Q and the
  fixed BLS parameter, so the 69 affine steps run once per distinct G2
  point on host (tiny Fq2 work) and produce per-step line coefficients.
  This is the same factoring arkworks/blst call "G2Prepared"; here it is
  also the device seam.
* **Device accumulation** (`miller_from_coeffs`): the heavy part — 63
  Fq12 squarings and ~69 sparse line multiplications per pair — runs as
  ONE fixed-shape lax.scan, vmapped over all pairs of a batch in lanes.
  No inversions, no control flow, no G2 arithmetic on device.
* **Device final exponentiation**: fast cyclotomic membership check for
  `pairing_check` (5 powx scans; computes f^(3*hard) exactly like the C
  core, native/bls12_381.c:1128-1152) and the exact hard part for GT
  export parity.

Line model (identical to the host oracle and the C core, so Miller values
match crypto/pairing.py BIT-FOR-BIT): untwisted line through T, Q
evaluated at P = (px, py) is the sparse Fq12 element

    l = py + (lam*tx - ty) xi^-1 w^3 - lam*px xi^-1 w^5

with only the (lam*tx - ty)*xi^-1 and lam*xi^-1 factors precomputed on
host (Q-only data); the -px multiply happens on device.

Preconditions: G2 inputs must be in the prime-order subgroup (enforced by
crypto/curve.g2_from_bytes) — then T never meets ±Q mid-loop and no
vertical lines occur (prepare_g2 asserts this). Infinity on either side
is handled with an active-mask (e(P, O) = e(O, Q) = 1).

Reference parity surface: utils/bls.py:224-296 `pairing_check` — the one
native call every reference verification funnels into.
"""

from __future__ import annotations

import os

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp
from jax import lax

from eth_consensus_specs_tpu.crypto.fields import (
    BLS_X,
    P as P_INT,
    R as R_ORDER,
    XI,
    Fq12,
)
from eth_consensus_specs_tpu.ops import fq12_tower as tw
from eth_consensus_specs_tpu.ops import lazy_limbs as lz
from eth_consensus_specs_tpu.ops.lazy_limbs import LF, lf

N_LIMBS = lz.N_LIMBS
_XI_INV = XI.inv()
_BLS_X_ABS = -BLS_X

# Fixed step schedule: one row per Miller step; True rows square f first
# (doubling steps), False rows are the addition steps after set bits.
_SCHEDULE: list[bool] = []
for _bit in range(62, -1, -1):
    _SCHEDULE.append(True)
    if (_BLS_X_ABS >> _bit) & 1:
        _SCHEDULE.append(False)
N_STEPS = len(_SCHEDULE)
_SQR_FLAGS = np.array(_SCHEDULE, np.uint8)


# ----------------------------------------------------------- host prepare --


def prepare_g2(q) -> np.ndarray:
    """Per-step line coefficients for a (subgroup, non-infinity) G2 point:
    [N_STEPS, 2, 2, 15] Montgomery limbs of (a3, lam_xi) per step, where
    a3 = (lam*tx - ty)*xi^-1 and lam_xi = lam*xi^-1."""
    assert not q.is_infinity(), "prepare_g2: infinity handled by caller mask"
    rows = np.zeros((N_STEPS, 2, 2, N_LIMBS), np.uint64)
    t_x, t_y = q.x, q.y
    step = 0
    for bit in range(62, -1, -1):
        # doubling: tangent at T
        x_sq = t_x.square()
        lam = (x_sq + x_sq + x_sq) * (t_y + t_y).inv()
        rows[step, 0] = tw.fq2_to_limbs((lam * t_x - t_y) * _XI_INV)
        rows[step, 1] = tw.fq2_to_limbs(lam * _XI_INV)
        x3 = lam.square() - t_x - t_x
        t_y = lam * (t_x - x3) - t_y
        t_x = x3
        step += 1
        if (_BLS_X_ABS >> bit) & 1:
            # addition: chord through T and Q (never vertical for
            # subgroup Q: T = kQ with k != +-1 mod r at every add step)
            assert t_x != q.x, "vertical line in ate loop — Q not in subgroup?"
            lam = (q.y - t_y) * (q.x - t_x).inv()
            rows[step, 0] = tw.fq2_to_limbs((lam * t_x - t_y) * _XI_INV)
            rows[step, 1] = tw.fq2_to_limbs(lam * _XI_INV)
            x3 = lam.square() - t_x - q.x
            t_y = lam * (t_x - x3) - t_y
            t_x = x3
            step += 1
    assert step == N_STEPS
    return rows


def g1_affine_limbs(p) -> tuple[np.ndarray, np.ndarray]:
    """(px, py) Montgomery limbs of a non-infinity G1 point."""
    return lz.to_mont(p.x.n), lz.to_mont(p.y.n)


# ---------------------------------------------------------- device miller --


def _fq12_mul_line(f: LF, py: LF, a3: LF, a5: LF) -> LF:
    """f *= (py + a3 w^3 + a5 w^5), sparse (mirrors native fp12_mul_line).

    l.c0 = (py, 0, 0); l.c1 = (0, a3, a5). For an Fq6 half (s0, s1, s2):
    (s0,s1,s2)*(0,a3,a5) = (xi(s1 a5 + s2 a3), s0 a3 + xi s2 a5,
    s0 a5 + s1 a3). All twelve sparse Fq2 products across BOTH halves ride
    one stacked fq2_mul; the twelve py*Fq products ride one stacked mont."""
    f0, f1 = tw._part(f, 0, 3), tw._part(f, 1, 3)

    def lanes(src: LF):
        s0, s1, s2 = (tw._part(src, i, 2) for i in range(3))
        return [s1, s2, s0, s2, s0, s1], [a5, a3, a3, a5, a5, a3]

    l0, r0 = lanes(f0)
    l1, r1 = lanes(f1)
    prods = tw._unstack(
        tw.fq2_mul(tw._lane_stack(l0 + l1), tw._lane_stack(r0 + r1)), 12
    )

    def sparse6(p: list[LF]) -> LF:
        c0 = tw.fq2_mul_xi(lz.add(p[0], p[1]))
        c1 = lz.add(p[2], tw.fq2_mul_xi(p[3]))
        c2 = lz.add(p[4], p[5])
        return tw._stack([c0, c1, c2], axis=-3)

    sp0 = sparse6(prods[:6])
    sp1 = sparse6(prods[6:])

    # py * f as one 12-lane mont instance over the flattened Fq components
    comps = [
        LF(f.v[..., h, v, u, :], f.max, f.val)
        for h in range(2)
        for v in range(3)
        for u in range(2)
    ]
    scaled = tw._unstack(lz.mul(tw._lane_stack(comps), tw._lane_stack([py] * 12)), 12)

    def pyhalf(h: int) -> LF:
        return tw._stack(
            [
                tw._stack([scaled[h * 6 + v * 2 + u] for u in range(2)], axis=-2)
                for v in range(3)
            ],
            axis=-3,
        )

    c0 = lz.add(pyhalf(0), tw.fq6_mul_v(sp1))
    c1 = lz.add(pyhalf(1), sp0)
    return tw._stack([c0, c1], axis=-4)


def miller_from_coeffs(coeffs, px, py, active):
    """Batched Miller loop from prepared G2 coefficients.

    coeffs [B, N_STEPS, 2, 2, 15]; px, py [B, 15]; active [B] bool.
    Returns a normalized Fq12 limb array [B, 2, 3, 2, 15], already
    conjugated for the negative x, with inactive pairs forced to 1."""
    B = px.shape[0]
    f0 = tw.fq12_one((B,))
    neg_px = lz.sub(lz.zero_like(lf(px)), lf(px, val=P_INT - 1))
    py_l = lf(py, val=P_INT - 1)
    flags = jnp.asarray(_SQR_FLAGS)
    xs = (jnp.moveaxis(jnp.asarray(coeffs), 1, 0), flags)

    def step(f_v, x):
        row, flag = x  # row [B, 2, 2, 15]
        f = lf(f_v)
        a3 = lf(row[:, 0], val=P_INT - 1)
        a5 = tw.fq2_mul_fp(lf(row[:, 1], val=P_INT - 1), neg_px)
        sq = tw.fq12_sqr(f)
        fin = LF(
            jnp.where(flag != 0, sq.v, jnp.broadcast_to(f.v, sq.v.shape)),
            max(sq.max, f.max),
            max(sq.val, f.val),
        )
        out = _fq12_mul_line(fin, py_l, a3, a5)
        return tw._norm12(out).v, None

    f_v, _ = lax.scan(step, f0.v, xs)
    f = tw.fq12_conj(lf(f_v))  # negative BLS parameter
    one = tw.fq12_one((B,))
    sel = jnp.where(active[:, None, None, None, None], tw._norm12(f).v, one.v)
    return sel


# ------------------------------------------------------ final exponentiation


# The final-exponentiation chains are HOST-ORCHESTRATED compositions of
# small module-level jits: the powx scan — the big graph — compiles once
# per process and is REUSED six times per membership check (a single
# fused graph re-instantiated the scan per call site and took ~10 min of
# XLA time on CPU; dispatch overhead of the split is microseconds).


@jax.jit
def _powx_j(v):
    return tw._norm12(tw.fq12_powx(lf(v))).v


@jax.jit
def _mul_j(a, b):
    return tw._norm12(tw.fq12_mul(lf(a), lf(b))).v


@jax.jit
def _mul_conj_j(a, b):
    """a * conj(b), normalized."""
    return tw._norm12(tw.fq12_mul(lf(a), tw.fq12_conj(lf(b)))).v


@jax.jit
def _easy_j(v):
    """f^((p^6-1)(p^2+1)) — lands in the cyclotomic subgroup."""
    f = lf(v)
    t = tw.fq12_mul(tw.fq12_conj(f), tw.fq12_inv(f))
    return tw._norm12(tw.fq12_mul(tw.fq12_frobenius2(t), tw._norm12(t))).v


@jax.jit
def _frob1_j(v):
    return tw._norm12(tw.fq12_frobenius(lf(v))).v


@jax.jit
def _frob2_j(v):
    return tw._norm12(tw.fq12_frobenius2(lf(v))).v


@jax.jit
def _cube_j(v):
    f = lf(v)
    return tw._norm12(tw.fq12_mul(tw.fq12_sqr(f), lf(v))).v


@jax.jit
def _is_one_j(v):
    return tw.fq12_is_one(lf(v))


def final_exp_is_one(f_v):
    """True iff final_exponentiation(f) == 1, via the exact-multiple chain
    m^(3*hard) with 3H = (x-1)^2 (x+p)(x^2+p^2-1) + 3 (gcd(3, r) = 1, so
    this is 1 iff m^H is; mirrors native/bls12_381.c:1128). Takes/returns
    normalized limb arrays."""
    m = _easy_j(f_v)
    a = _mul_conj_j(_powx_j(m), m)  # m^(x-1)
    b = _mul_conj_j(_powx_j(a), a)  # m^((x-1)^2)
    c = _mul_j(_powx_j(b), _frob1_j(b))  # b^(x+p)
    d = _powx_j(_powx_j(c))  # c^(x^2)
    g = _mul_conj_j(_mul_j(d, _frob2_j(c)), c)
    return bool(_is_one_j(_mul_j(g, _cube_j(m))))


_HARD_EXP = (P_INT**4 - P_INT**2 + 1) // R_ORDER


@jax.jit
def _hard_exp_j(v):
    return tw._norm12(tw.fq12_pow_const(lf(v), _HARD_EXP)).v


def final_exponentiation(f_v):
    """Exact final exponentiation (naive hard part) — for GT export
    parity with crypto/pairing.py. Takes/returns normalized limb arrays."""
    return _hard_exp_j(_easy_j(f_v))


# ------------------------------------------------------------- public API --


# Compile units are split so each piece caches independently, and the
# Miller batch runs in FIXED-SIZE chunks: XLA compile time grows with the
# batch extent (measured: 46s at B=1, 6.4 min at B=32 on CPU), so one
# B=_CHUNK executable — compiled once per process, padded with inactive
# pairs — serves every batch size; chunk products fold through the small
# mul jit. The final-exp chain (the largest graphs) sees ONE folded
# element, so its jits also compile exactly once.
_CHUNK = 8


@jax.jit
def _miller_chunk_fold(coeffs, px, py, active):
    fs_v = miller_from_coeffs(coeffs, px, py, active)
    n = _CHUNK
    while n > 1:
        half = n // 2
        prod = tw.fq12_mul(lf(fs_v[:half]), lf(fs_v[half:n]))
        fs_v = tw._norm12(prod).v
        n = half
    return fs_v[0]


# -- mesh-sharded Miller accumulation: the chunk axis splits over the
# serve mesh; each shard scans its chunks through the SAME fixed-B=_CHUNK
# Miller body, folding a per-shard partial product, and the partials
# combine with a psum-style reduction (all_gather + Fq12 multiply — the
# reduction monoid here is multiplicative, so there is no literal psum).
# Fq12 multiplication is commutative/associative over an exact field and
# _norm12 is canonical, so the folded product — and the final membership
# verdict — is bit-identical to the sequential chunk walk.
_MILLER_SHARDED: dict[tuple, object] = {}


def _miller_sharded_fn(mesh, chunks_per_shard: int):
    key = (mesh, chunks_per_shard)
    fn = _MILLER_SHARDED.get(key)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from eth_consensus_specs_tpu.parallel.mesh_ops import BATCH_AXES

    def _fold_chunk(fs_v):
        n = _CHUNK
        while n > 1:
            half = n // 2
            fs_v = tw._norm12(tw.fq12_mul(lf(fs_v[:half]), lf(fs_v[half:n]))).v
            n = half
        return fs_v[0]

    def local(coeffs, px, py, active):
        def step(carry, x):
            co, px_, py_, act = x
            part = _fold_chunk(miller_from_coeffs(co, px_, py_, act))
            return tw._norm12(tw.fq12_mul(lf(carry), lf(part))).v, None

        init = tw.fq12_one(()).v
        part, _ = lax.scan(step, init, (coeffs, px, py, active))
        parts = jax.lax.all_gather(part, BATCH_AXES)  # [S, 2, 3, 2, 15]
        total = parts[0]
        for i in range(1, parts.shape[0]):
            total = tw._norm12(tw.fq12_mul(lf(total), lf(parts[i]))).v
        return total

    spec = P(BATCH_AXES)
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=P(),
            check_rep=False,
        )
    )
    _MILLER_SHARDED[key] = fn
    return fn


def _clear_sharded_after_fork_in_child() -> None:
    # fork-safety: compiled executables reference the parent's devices
    _MILLER_SHARDED.clear()


os.register_at_fork(after_in_child=_clear_sharded_after_fork_in_child)


def _prepare_all(pairs: list) -> None:
    """Fill _PREP_CACHE for every live G2 point in `pairs` in ONE native
    lockstep walk (bls_g2_prepare_many: Montgomery batch inversions across
    all points, limbs emitted directly in the device encoding).  Fresh Qs
    are the common case on the signature path — every distinct message is
    a fresh hash-to-curve point, and the per-point host oracle walk costs
    ~5 ms each — so this is what makes the device pairing win on fresh
    batches, not just on cache-friendly fixed-setup (KZG) workloads.
    Falls back silently to per-point prepare_g2 inside _prepared()."""
    from eth_consensus_specs_tpu.crypto import native_bridge as nb

    fresh = []
    seen = set()
    for p, q in pairs:
        if p.is_infinity() or q.is_infinity():
            continue
        key = (q.x, q.y)
        if key in _PREP_CACHE or key in seen:
            continue
        seen.add(key)
        fresh.append(q)
    if len(fresh) < 2:  # a single walk gains nothing over the oracle path
        return
    rows = nb.g2_prepare_many(
        [((q.x.c0.n, q.x.c1.n), (q.y.c0.n, q.y.c1.n)) for q in fresh]
    )
    if rows is None:
        return
    if len(_PREP_CACHE) + len(fresh) > 256:
        # evict only entries THIS batch does not need — clearing wholesale
        # would push the batch's own cached points back onto the ~5 ms
        # per-point host walk the pre-fill exists to avoid
        needed = seen | {
            (q.x, q.y) for p, q in pairs if not (p.is_infinity() or q.is_infinity())
        }
        for key in [k for k in _PREP_CACHE if k not in needed]:
            del _PREP_CACHE[key]
    for q, row in zip(fresh, rows):
        _PREP_CACHE[(q.x, q.y)] = row


def _fill_chunks(pairs: list, n_chunks: int):
    """Pack pairs into [n_chunks, _CHUNK, ...] kernel inputs; unfilled
    slots stay inactive (their Miller value folds as 1)."""
    coeffs = np.zeros((n_chunks, _CHUNK, N_STEPS, 2, 2, N_LIMBS), np.uint64)
    px = np.zeros((n_chunks, _CHUNK, N_LIMBS), np.uint64)
    py = np.zeros((n_chunks, _CHUNK, N_LIMBS), np.uint64)
    active = np.zeros((n_chunks, _CHUNK), bool)
    for i, (p, q) in enumerate(pairs):
        if p.is_infinity() or q.is_infinity():
            continue
        ci, j = divmod(i, _CHUNK)
        coeffs[ci, j] = _prepared(q)
        px[ci, j], py[ci, j] = g1_affine_limbs(p)
        active[ci, j] = True
    return coeffs, px, py, active


def _miller_product(pairs: list, mesh=None):
    """Product of Miller values over (G1, G2) pairs as a normalized limb
    array, chunked to the fixed-size kernel. With a multi-device `mesh`
    and more than one chunk of pairs, the chunk axis shards over the mesh
    (per-shard partial products, psum-style Fq12 combine)."""
    from eth_consensus_specs_tpu import obs
    from eth_consensus_specs_tpu.parallel.mesh_ops import pad_to_shards, shard_count

    _prepare_all(pairs)
    n_chunks = (len(pairs) + _CHUNK - 1) // _CHUNK
    shards = shard_count(mesh)
    if shards > 1 and n_chunks > 1:
        # one chunk gains nothing from S shards; past that, pad the
        # chunk count to the mesh and let every shard walk its share
        padded = pad_to_shards(n_chunks, shards)
        coeffs, px, py, active = _fill_chunks(pairs, padded)
        obs.count("mesh.dispatches", 1)
        obs.count("mesh.sharded_items", len(pairs))
        fn = _miller_sharded_fn(mesh, padded // shards)
        return fn(
            jnp.asarray(coeffs), jnp.asarray(px), jnp.asarray(py), jnp.asarray(active)
        )
    coeffs, px, py, active = _fill_chunks(pairs, n_chunks)
    total = None
    for ci in range(n_chunks):
        part = _miller_chunk_fold(
            jnp.asarray(coeffs[ci]),
            jnp.asarray(px[ci]),
            jnp.asarray(py[ci]),
            jnp.asarray(active[ci]),
        )
        total = part if total is None else _mul_j(total, part)
    return total


def pairing_check_device(pairs: list, mesh=None) -> bool:
    """prod e(P_i, Q_i) == 1 with the Miller accumulation and final-exp
    membership check on device. Pairs are (G1 Point, G2 Point) host
    objects (subgroup-checked at deserialization)."""
    if not pairs:
        return True
    ok = bool(final_exp_is_one(_miller_product(pairs, mesh=mesh)))
    # the bool() above materialized the device result — record the warm
    # chain for the bench's sentinel gating (utils/cache.mark_warm is a
    # no-op without the persistent cache or on cpu)
    from eth_consensus_specs_tpu.utils.cache import mark_warm

    mark_warm("pairing")
    return ok


_PREP_CACHE: dict = {}


def _prepared(q) -> np.ndarray:
    key = (q.x, q.y)
    hit = _PREP_CACHE.get(key)
    if hit is None:
        hit = prepare_g2(q)
        if len(_PREP_CACHE) > 256:
            _PREP_CACHE.clear()
        _PREP_CACHE[key] = hit
    return hit


def pairing_device(p, q) -> Fq12:
    """Exact e(P, Q) computed on device — GT element equal to
    crypto/pairing.pairing (parity/test surface; the hot path is
    pairing_check_device)."""
    if p.is_infinity() or q.is_infinity():
        return Fq12.one()
    out = final_exponentiation(_miller_product([(p, q)]))
    return tw.limbs_to_fq12(np.asarray(out))


def miller_loop_device(p, q) -> Fq12:
    """Miller value f_{|x|,Q}(P) (conjugated) — bit-exact vs
    crypto/pairing.miller_loop, for unit tests. Uses the same chunked
    kernel as the hot path (padded with inactive pairs whose f is 1, so
    the fold is exactly this pair's value)."""
    if p.is_infinity() or q.is_infinity():
        return Fq12.one()
    return tw.limbs_to_fq12(np.asarray(_miller_product([(p, q)])))
