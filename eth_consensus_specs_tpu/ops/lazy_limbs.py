"""Lazy-reduction BLS12-381 base-field limbs for the device (u64 lanes).

The first-generation Fq kernel (ops/field_limbs.py, 13x30-bit limbs)
normalizes limbs after EVERY add/sub — a ~130-node carry/borrow subgraph
per operation that made pairing-sized XLA graphs take minutes to compile
(measured: 53s for ONE Fq12 product, while a plain 400-op u64 chain
compiles in 0.8s — node count is the whole story).

This module keeps limbs LAZY, the way hand-written pairing libraries
(blst/RELIC) do, with every bound tracked STATICALLY at trace time:

* 15 x 26-bit limbs in u64 lanes; R = 2^390. Normalized limbs < 2^26
  leave 38 bits of lane headroom.
* ``add`` is ONE vector add — no carry propagation.
* ``sub`` is borrow-free: x + (F - y), where F is c*p re-encoded with
  every limb >= y's static per-limb bound (the lend trick
  f_i += k*2^26 - k preserves the value exactly); 2 vector ops.
* ``mul`` is Montgomery SOS. Preconditions checked against the STATIC
  bounds (Python ints riding along at trace time, zero graph cost):
  - product columns: N * (max_a+1) * (max_b+1) < 2^64  (lane overflow)
  - values:          val_a * val_b < p * R              (output < 2p)
  Violations auto-insert a carry sweep (``norm``) or a conditional-
  subtraction chain (``shrink``) — rare, because most tower formulas
  chain only 2-5 lazy ops between multiplies.

Every element is an ``LF`` (array + static max-limb + static value
bound). LF objects live INSIDE traced functions only; jit boundaries
pass raw arrays.
"""

from __future__ import annotations

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax.numpy as jnp

from eth_consensus_specs_tpu.crypto.fields import P as P_INT

LIMB_BITS = 26
N_LIMBS = 15  # 15 * 26 = 390 >= 381
MASK = (1 << LIMB_BITS) - 1
R_INT = 1 << (LIMB_BITS * N_LIMBS)  # 2^390
N0_INV = (-pow(P_INT, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)

NORM_MAX = MASK
P_TOP = P_INT >> (LIMB_BITS * (N_LIMBS - 1))  # top limb of p (~2^17)

_U = jnp.uint64


def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(N_LIMBS, np.uint64)
    for i in range(N_LIMBS):
        out[i] = (x >> (LIMB_BITS * i)) & MASK
    return out


def limbs_to_int(arr) -> int:
    a = np.asarray(arr, np.uint64)
    return sum(int(a[i]) << (LIMB_BITS * i) for i in range(len(a)))


def to_mont(x: int) -> np.ndarray:
    return int_to_limbs((x * R_INT) % P_INT)


def from_mont_int(limbs) -> int:
    return (limbs_to_int(limbs) * pow(R_INT, -1, P_INT)) % P_INT


P_LIMBS = int_to_limbs(P_INT)
ONE_MONT = to_mont(1)


class LF:
    """Limb array [..., 15] u64 + static (max_limb, value) bounds."""

    __slots__ = ("v", "max", "val")

    def __init__(self, v, max_limb: int, val: int):
        self.v = v
        self.max = max_limb
        self.val = val


def lf(v, val: int | None = None) -> LF:
    """Wrap a normalized-limb array. Default value bound 2p (Montgomery
    outputs live in [0, 2p); host conversions are < p)."""
    return LF(v, NORM_MAX, (2 * P_INT - 1) if val is None else val)


def zero_like(x: LF) -> LF:
    return LF(jnp.zeros_like(x.v), 0, 0)


def add(x: LF, y: LF) -> LF:
    if x.val + y.val >= R_INT // 4:
        x = shrink(x) if x.val >= y.val else x
        y = shrink(y) if x.val < y.val else y
    return LF(x.v + y.v, x.max + y.max, x.val + y.val)


def dbl(x: LF) -> LF:
    return LF(x.v + x.v, 2 * x.max, 2 * x.val)


# --- borrow-free subtraction ----------------------------------------------

_FAT_CACHE: dict[tuple[int, int], tuple[np.ndarray, int, int]] = {}


def _fat_p(limb_bound: int, top_bound: int) -> tuple[np.ndarray, int, int]:
    """c*p re-encoded with middle/low limbs >= limb_bound and the top
    limb >= top_bound; value is exactly c*p. Returns (limbs, max_limb, c)."""
    k = (limb_bound >> LIMB_BITS) + 2  # lend amount per position
    c = max((top_bound + k) // P_TOP + 1, 1)
    key = (limb_bound, top_bound)
    hit = _FAT_CACHE.get(key)
    if hit is not None:
        return hit
    while True:
        base = c * P_INT
        digits = []
        rem = base
        for _ in range(N_LIMBS):
            digits.append(rem & MASK)
            rem >>= LIMB_BITS
        if rem != 0:
            raise AssertionError("fat multiple exceeds 15 limbs — bound too large")
        f = [0] * N_LIMBS
        f[0] = digits[0] + (k << LIMB_BITS)
        for i in range(1, N_LIMBS - 1):
            f[i] = digits[i] + (k << LIMB_BITS) - k
        f[N_LIMBS - 1] = digits[N_LIMBS - 1] - k
        if f[N_LIMBS - 1] >= top_bound and all(
            f[i] >= limb_bound for i in range(N_LIMBS - 1)
        ):
            break
        c += 1
    assert sum(v << (LIMB_BITS * i) for i, v in enumerate(f)) == base
    arr = np.array(f, np.uint64)
    hit = (arr, max(f), c)
    _FAT_CACHE[key] = hit
    return hit


# Static cap on any _fat_p limb (f_i ~ y.max + 3*2^26 for every real
# call site). The rangelint lend-path Wrap declares the SAME cap
# (analysis/kernels.py), so the abstract interpreter's trusted bound for
# `fat - y` and this trace-time assertion can never drift apart; a limb
# this size leaves 2^34 of lane headroom for the subsequent add/mul
# columns (15 * 2^30 * 2^26 < 2^60).
_LEND_LIMB_CAP = 1 << 30


def sub(x: LF, y: LF) -> LF:
    """x - y (mod p), borrow-free against y's static bounds. A very lazy
    subtrahend would force a fat multiple with a huge top-limb cover
    (c ~ y_top/p_top), escalating the value bound — shrink first instead
    (the static bounds make this a rare, trace-time decision)."""
    if y.val > 16 * P_INT:
        y = shrink(y)
    if x.val > R_INT // 4:
        x = shrink(x)
    top_bound = min(y.max, y.val >> (LIMB_BITS * (N_LIMBS - 1)))
    fat, fat_max, c = _fat_p(y.max, top_bound)
    if fat_max > _LEND_LIMB_CAP:
        # a subtrahend can be lazy enough to outgrow the lend cap without
        # tripping the val-triggered shrink above (a 15-term canonical sum:
        # val = 15p < 16p, but max ~15*2^26 pushes the fat cover past 2^30)
        # — auto-insert the sweep, per the module contract, and re-cover
        y = shrink(y)
        top_bound = min(y.max, y.val >> (LIMB_BITS * (N_LIMBS - 1)))
        fat, fat_max, c = _fat_p(y.max, top_bound)
    # bound growth on the lend path: the fat limbs must respect the cap
    # the range analysis trusts, and the x + (fat - y) add must be
    # provably in-lane — neither held by construction before
    assert fat_max <= _LEND_LIMB_CAP, (
        f"_fat_p limb {fat_max} exceeds the declared lend cap "
        f"{_LEND_LIMB_CAP} even after shrink"
    )
    assert x.max + fat_max < (1 << 64), "sub: x + (fat - y) could wrap the lane"
    diff = jnp.asarray(fat) - y.v
    return LF(x.v + diff, x.max + fat_max, x.val + c * P_INT)


# --- normalization ---------------------------------------------------------


def norm(x: LF) -> LF:
    """Carry sweep to limbs < 2^26. Value must be < R (asserted
    statically) so the top carry is provably zero."""
    assert x.val < R_INT, "norm: value bound reached R — shrink first"
    if x.max <= NORM_MAX:
        return x
    out = []
    carry = None
    for i in range(N_LIMBS):
        cur = x.v[..., i] if carry is None else x.v[..., i] + carry
        out.append(cur & _U(MASK))
        carry = cur >> _U(LIMB_BITS)
    # top carry == 0 because val < 2^390
    return LF(jnp.stack(out, axis=-1), NORM_MAX, x.val)


def _geq(a, b_arr):
    acc = jnp.ones(a.shape[:-1], dtype=bool)
    for i in range(N_LIMBS):
        x, y = a[..., i], b_arr[..., i]
        acc = jnp.where(x == y, acc, x > y)
    return acc


def _sub_limbs(a, b_arr):
    out = []
    borrow = None
    for i in range(N_LIMBS):
        cur = a[..., i] - b_arr[..., i]
        if borrow is not None:
            cur = cur - borrow
        under = cur >> _U(63)
        out.append(cur + (under << _U(LIMB_BITS)))
        borrow = under
    return jnp.stack(out, axis=-1)


def shrink(x: LF) -> LF:
    """Reduce the VALUE below 2p via norm + a conditional-subtraction
    chain of power-of-two multiples of p (each halves the bound)."""
    x = norm(x)
    bound = x.val
    v = x.v
    while bound >= 2 * P_INT:
        # smallest m = 2^j * p with 2m >= bound: cond-sub leaves value < m
        q = (bound + P_INT - 1) // P_INT
        j = (q - 1).bit_length() - 1
        m = (1 << j) * P_INT
        assert 2 * m >= bound and m < bound and m < R_INT
        mb = jnp.broadcast_to(jnp.asarray(int_to_limbs(m)), v.shape)
        take = _geq(v, mb)
        v = jnp.where(take[..., None], _sub_limbs(v, mb), v)
        bound = m
    return LF(v, NORM_MAX, bound)


# --- Montgomery multiplication --------------------------------------------

_LANE_BUDGET = (1 << 64) - (1 << 40)  # carry slack


def _fix_operand(x: LF, y: LF) -> tuple[LF, LF]:
    """Insert norm/shrink so mul preconditions hold (static decision)."""
    # value precondition: val_x * val_y < p * R
    while x.val * y.val >= P_INT * R_INT:
        if x.val >= y.val:
            x = shrink(x)
        else:
            y = shrink(y)
    # lane precondition
    if N_LIMBS * (x.max + 1) * (y.max + 1) >= _LANE_BUDGET:
        if x.max >= y.max:
            x = norm(x)
        else:
            y = norm(y)
    if N_LIMBS * (x.max + 1) * (y.max + 1) >= _LANE_BUDGET:
        if x.max >= y.max:
            x = norm(x)
        else:
            y = norm(y)
    assert N_LIMBS * (x.max + 1) * (y.max + 1) < _LANE_BUDGET
    return x, y


def mul(x: LF, y: LF) -> LF:
    """Montgomery product x*y*R^-1 mod p; output normalized, < 2p.

    Same SOS algorithm with deferred carries as always, but built from
    VECTOR ops over the limb axis — one outer product plus shifted-slice
    accumulates — instead of 225 per-limb scalar multiplies.  That cuts
    the XLA graph ~4x per multiply, which is what makes the big unrolled
    curve kernels (pairing, hash-to-curve) compile in sane time; the
    arithmetic (and therefore every carry/overflow bound) is unchanged."""
    x, y = _fix_operand(x, y)
    mask = _U(MASK)
    shift = _U(LIMB_BITS)
    n0 = _U(N0_INV)
    W = 2 * N_LIMBS + 1

    def _pad_to(vrow, lo: int):
        """Place a [..., n] row at column offset `lo` of a width-W vector
        (jnp.pad, never scatter — scatter lowering dominates compile)."""
        n = vrow.shape[-1]
        return jnp.pad(vrow, [(0, 0)] * (vrow.ndim - 1) + [(lo, W - lo - n)])

    outer = x.v[..., :, None] * y.v[..., None, :]  # [..., 15, 15]
    t = _pad_to(outer[..., 0, :], 0)
    for i in range(1, N_LIMBS):
        # column k = i + j accumulates a_i * b_j: row i lands at offset i
        t = t + _pad_to(outer[..., i, :], i)

    # ONE vector carry round caps every column at mask + (budget >> 26)
    # < 2^39 — exact residue per column is preserved (value semantics),
    # and the deferred-carry folds below keep m-digit reads correct.
    t = (t & mask) + _pad_to(t[..., :-1] >> shift, 1)

    pv = jnp.asarray(P_LIMBS)
    for i in range(N_LIMBS):
        # mask BEFORE the n0 product: (t_i & mask) * n0 ≡ t_i * n0
        # (mod 2^26), so m is unchanged — but the unmasked product could
        # exceed 2^64 and leaned on silent u64 truncation for its low
        # bits; pre-masking keeps every intermediate provably in-lane
        # (rangelint lane-overflow, ~2^52 vs ~2^78)
        m = ((t[..., i] & mask) * n0) & mask
        t = t + _pad_to(m[..., None] * pv, i)
        # fold position i's full value upward before step i+1 reads i+1
        t = t + _pad_to((t[..., i] >> shift)[..., None], i + 1)

    out = []
    carry = None
    for j in range(N_LIMBS, W):
        cur = t[..., j] if carry is None else t[..., j] + carry
        if len(out) < N_LIMBS:
            out.append(cur & mask)
        carry = cur >> shift
    return LF(jnp.stack(out, axis=-1), NORM_MAX, 2 * P_INT - 1)


def is_zero(x: LF):
    """True iff x == 0 mod p, for x with value < 2p (mont outputs)."""
    assert x.val <= 2 * P_INT - 1, "is_zero expects a reduced element"
    n = norm(x)
    p_vec = jnp.asarray(P_LIMBS)
    exact_zero = jnp.all(n.v == 0, axis=-1)
    exact_p = jnp.all(n.v == jnp.broadcast_to(p_vec, n.v.shape), axis=-1)
    return exact_zero | exact_p
