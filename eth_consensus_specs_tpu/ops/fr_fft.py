"""Batched BLS-scalar-field FFT on device — the DAS recovery kernel.

The 8192-point radix-2 FFT over the 255-bit scalar field is the most
TPU-shaped math in the spec (SURVEY §2.3; reference:
specs/fulu/polynomial-commitments-sampling.md:155-209,779): thousands of
independent butterflies per stage, 13 static stages, no data-dependent
control flow.  Elements live as 9x30-bit Montgomery limbs in uint64 lanes
(ops/limb_field.py); all log2(n) stages run inside ONE jit with the
stage loop unrolled (static shapes per stage), so XLA fuses the butterfly
chain, and a leading batch axis amortizes recovery over many columns at
once.

Bit-exact with the host oracle crypto/das.fft_field (same DIT butterfly
order: both equal the textbook DFT in exact modular arithmetic)."""

from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp

from .limb_field import LimbField

# BLS12-381 scalar field (the polynomial / erasure-coding field)
BLS_MODULUS = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

FR = LimbField(BLS_MODULUS)


@lru_cache(maxsize=None)
def _bit_reversal_indices(n: int) -> np.ndarray:
    bits = n.bit_length() - 1
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = int(format(i, f"0{bits}b")[::-1], 2) if bits else 0
    return out


@lru_cache(maxsize=None)
def _stage_twiddles(roots: tuple, n: int) -> list[np.ndarray]:
    """Montgomery twiddle tables per DIT stage: stage with half-size m uses
    w[k] = roots[k * (n // (2m))] for k in range(m)."""
    tables = []
    m = 1
    while m < n:
        stride = n // (2 * m)
        tables.append(
            np.stack([FR.to_mont(roots[k * stride] % BLS_MODULUS) for k in range(m)])
        )
        m *= 2
    return tables


def fft_stages(vals, twiddles, n: int):
    """The DIT butterfly stage chain over bit-reversed input — the single
    shared kernel body (also what bench.py's chained measurement runs).

    vals: [B, n, L] Montgomery limbs; twiddles: one [m, L] table per stage."""
    out = vals
    m = 1
    for t in twiddles:
        # [B, n/(2m), 2, m, L]: axis-2 selects the (a, b) halves
        shaped = out.reshape(out.shape[0], n // (2 * m), 2, m, FR.n_limbs)
        a = shaped[:, :, 0]
        b = FR.mont_mul(shaped[:, :, 1], t)  # t broadcasts [m, L]
        merged = jnp.stack([FR.add_mod(a, b), FR.sub_mod(a, b)], axis=2)
        out = merged.reshape(out.shape[0], n, FR.n_limbs)
        m *= 2
    return out


@lru_cache(maxsize=None)
def _compiled_fft(n: int, n_stages: int):
    """One executable per size; twiddles enter as traced args so coset
    variants and inverse roots reuse the same compilation. The input
    limb array is DONATED: it is a private bit-reversed copy built in
    batch_fft_mont (never reused after the call) and its aval equals the
    output's, so XLA writes the butterfly stages back into the same
    [B, n, L] buffer — at 8192-point DAS batches that halves the
    kernel's resident footprint (the jaxlint donation-audit rule is what
    flagged the missed alias)."""

    @partial(jax.jit, donate_argnums=(0,))
    def run(vals, *twiddles):
        return fft_stages(vals, list(twiddles), n)

    return run


def batch_fft_mont(vals_mont: jnp.ndarray, roots: tuple) -> jnp.ndarray:
    """[B, n, L] Montgomery limbs -> DFT, natural order in and out."""
    n = vals_mont.shape[1]
    assert n & (n - 1) == 0 and n == len(roots)
    rev = jnp.asarray(_bit_reversal_indices(n))
    vals = jnp.take(vals_mont, rev, axis=1)
    twiddles = [jnp.asarray(t) for t in _stage_twiddles(tuple(roots), n)]
    return _compiled_fft(n, len(twiddles))(vals, *twiddles)


def batch_fft_field(batches, roots_of_unity, inv: bool = False) -> list[list[int]]:
    """Many same-length FFTs at once; bit-exact with crypto/das.fft_field
    applied row-wise (host ints in, host ints out)."""
    roots = tuple(int(r) for r in roots_of_unity)
    n = len(roots)
    arr = FR.ints_to_mont_batch([[int(x) % BLS_MODULUS for x in row] for row in batches])
    if inv:
        inv_roots = (roots[0],) + roots[:0:-1]
        out = batch_fft_mont(jnp.asarray(arr), inv_roots)
        invlen_mont = jnp.asarray(FR.to_mont(pow(n, BLS_MODULUS - 2, BLS_MODULUS)))
        out = FR.mont_mul(out, invlen_mont)
    else:
        out = batch_fft_mont(jnp.asarray(arr), roots)
    flat = FR.mont_batch_to_ints(np.asarray(out))
    b = len(batches)
    return [flat[i * n : (i + 1) * n] for i in range(b)]


def fft_field_device(vals, roots_of_unity, inv: bool = False) -> list[int]:
    """Drop-in device twin of crypto/das.fft_field (single vector)."""
    return batch_fft_field([list(vals)], roots_of_unity, inv=inv)[0]
