"""Batched BLS-scalar-field FFT on device — the DAS recovery kernel.

The 8192-point radix-2 FFT over the 255-bit scalar field is the most
TPU-shaped math in the spec (SURVEY §2.3; reference:
specs/fulu/polynomial-commitments-sampling.md:155-209,779): thousands of
independent butterflies per stage, 13 static stages, no data-dependent
control flow.  Elements live as 9x30-bit Montgomery limbs in uint64 lanes
(ops/limb_field.py); all log2(n) stages run inside ONE jit with the
stage loop unrolled (static shapes per stage), so XLA fuses the butterfly
chain, and a leading batch axis amortizes recovery over many columns at
once.

Bit-exact with the host oracle crypto/das.fft_field (same DIT butterfly
order: both equal the textbook DFT in exact modular arithmetic)."""

from __future__ import annotations

import os
from functools import lru_cache, partial

import numpy as np

import eth_consensus_specs_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .limb_field import LimbField

# BLS12-381 scalar field (the polynomial / erasure-coding field)
BLS_MODULUS = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

FR = LimbField(BLS_MODULUS)


@lru_cache(maxsize=None)
def _bit_reversal_indices(n: int) -> np.ndarray:
    bits = n.bit_length() - 1
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = int(format(i, f"0{bits}b")[::-1], 2) if bits else 0
    return out


@lru_cache(maxsize=None)
def _stage_twiddles(roots: tuple, n: int) -> list[np.ndarray]:
    """Montgomery twiddle tables per DIT stage: stage with half-size m uses
    w[k] = roots[k * (n // (2m))] for k in range(m)."""
    tables = []
    m = 1
    while m < n:
        stride = n // (2 * m)
        tables.append(
            np.stack([FR.to_mont(roots[k * stride] % BLS_MODULUS) for k in range(m)])
        )
        m *= 2
    return tables


def fft_stages(vals, twiddles, n: int):
    """The DIT butterfly stage chain over bit-reversed input — the single
    shared kernel body (also what bench.py's chained measurement runs).

    vals: [B, n, L] Montgomery limbs; twiddles: one [m, L] table per stage."""
    out = vals
    m = 1
    for t in twiddles:
        # [B, n/(2m), 2, m, L]: axis-2 selects the (a, b) halves
        shaped = out.reshape(out.shape[0], n // (2 * m), 2, m, FR.n_limbs)
        a = shaped[:, :, 0]
        b = FR.mont_mul(shaped[:, :, 1], t)  # t broadcasts [m, L]
        merged = jnp.stack([FR.add_mod(a, b), FR.sub_mod(a, b)], axis=2)
        out = merged.reshape(out.shape[0], n, FR.n_limbs)
        m *= 2
    return out


@lru_cache(maxsize=None)
def _device_twiddles(roots: tuple, n: int) -> tuple:
    """The twiddle tables as (uncommitted) device arrays, uploaded once
    per (roots, n) instead of per dispatch; their bytes are booked under
    the ``trusted_setup`` owner in the HBM residency ledger — these are
    the domain constants that live in device memory for the lifetime of
    the process."""
    tables = tuple(jnp.asarray(t) for t in _stage_twiddles(roots, n))
    try:
        from eth_consensus_specs_tpu.obs import ledger

        ledger.register(
            "trusted_setup",
            f"fft_twiddles-{n}",
            sum(int(t.nbytes) for t in tables),
        )
    except Exception:
        pass
    return tables


@lru_cache(maxsize=None)
def _compiled_fft(n: int, n_stages: int):
    """One executable per size; twiddles enter as traced args so coset
    variants and inverse roots reuse the same compilation. The input
    limb array is DONATED: it is a private bit-reversed copy built in
    batch_fft_mont (never reused after the call) and its aval equals the
    output's, so XLA writes the butterfly stages back into the same
    [B, n, L] buffer — at 8192-point DAS batches that halves the
    kernel's resident footprint (the jaxlint donation-audit rule is what
    flagged the missed alias)."""

    @partial(jax.jit, donate_argnums=(0,))
    def run(vals, *twiddles):
        return fft_stages(vals, list(twiddles), n)

    return run


# -- mesh-sharded variant: rows of a batched FFT are independent, so the
# BATCH axis shards with NO collectives (every shard runs the identical
# butterfly chain over its rows) — byte-identical to the single-device
# dispatch at any shard count. The donated vals buffer aliases per shard
# exactly like the single-device jit.
_SHARDED_FFT: dict[tuple, object] = {}


def _sharded_fft(mesh: Mesh, n: int, n_stages: int):
    key = (mesh, n, n_stages)
    fn = _SHARDED_FFT.get(key)
    if fn is not None:
        return fn
    from eth_consensus_specs_tpu.parallel.mesh_ops import BATCH_AXES

    def local(vals, *twiddles):
        return fft_stages(vals, list(twiddles), n)

    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(BATCH_AXES),) + (P(),) * n_stages,
            out_specs=P(BATCH_AXES),
            check_rep=False,
        ),
        donate_argnums=(0,),
    )
    _SHARDED_FFT[key] = fn
    return fn


def _clear_sharded_after_fork_in_child() -> None:
    # fork-safety: compiled executables (and cached device twiddle
    # uploads) reference the parent's devices
    _SHARDED_FFT.clear()
    _device_twiddles.cache_clear()


os.register_at_fork(after_in_child=_clear_sharded_after_fork_in_child)


def batch_fft_mont(
    vals_mont: jnp.ndarray, roots: tuple, mesh: Mesh | None = None
) -> jnp.ndarray:
    """[B, n, L] Montgomery limbs -> DFT, natural order in and out. With
    a multi-device `mesh` the batch axis shards (B must divide evenly —
    callers pad rows through serve/buckets.fr_fft_key, whose mesh-aware
    bucket guarantees it)."""
    n = vals_mont.shape[1]
    assert n & (n - 1) == 0 and n == len(roots)
    rev = jnp.asarray(_bit_reversal_indices(n))
    vals = jnp.take(vals_mont, rev, axis=1)
    twiddles = list(_device_twiddles(tuple(roots), n))
    from eth_consensus_specs_tpu.parallel.mesh_ops import shard_count

    if mesh is not None and shard_count(mesh) > 1:
        from eth_consensus_specs_tpu import obs

        assert vals.shape[0] % shard_count(mesh) == 0
        obs.count("mesh.dispatches", 1)
        obs.count("mesh.sharded_items", int(vals.shape[0]))
        return _sharded_fft(mesh, n, len(twiddles))(vals, *twiddles)
    return _compiled_fft(n, len(twiddles))(vals, *twiddles)


def batch_fft_field(
    batches,
    roots_of_unity,
    inv: bool = False,
    mesh: Mesh | None = None,
    pad_batch: int | None = None,
) -> list[list[int]]:
    """Many same-length FFTs at once; bit-exact with crypto/das.fft_field
    applied row-wise (host ints in, host ints out). ``pad_batch`` pads
    the batch axis with zero rows to a bucketed compile shape (the serve
    layer passes its fr_fft_key bucket so accounting and dispatch
    agree); padded rows are discarded."""
    roots = tuple(int(r) for r in roots_of_unity)
    n = len(roots)
    b = len(batches)
    rows = [[int(x) % BLS_MODULUS for x in row] for row in batches]
    if pad_batch is not None:
        assert pad_batch >= b
        rows += [[0] * n] * (pad_batch - b)
    arr = FR.ints_to_mont_batch(rows)
    if inv:
        inv_roots = (roots[0],) + roots[:0:-1]
        out = batch_fft_mont(jnp.asarray(arr), inv_roots, mesh=mesh)
        invlen_mont = jnp.asarray(FR.to_mont(pow(n, BLS_MODULUS - 2, BLS_MODULUS)))
        out = FR.mont_mul(out, invlen_mont)
    else:
        out = batch_fft_mont(jnp.asarray(arr), roots, mesh=mesh)
    flat = FR.mont_batch_to_ints(np.asarray(out)[:b])
    return [flat[i * n : (i + 1) * n] for i in range(b)]


def fft_field_device(vals, roots_of_unity, inv: bool = False) -> list[int]:
    """Drop-in device twin of crypto/das.fft_field (single vector)."""
    return batch_fft_field([list(vals)], roots_of_unity, inv=inv)[0]
