"""Million-validator aggregation-pipeline bench — one full simulated slot.

Direct mode: build an N-validator registry (``--subnets`` attestation
subnets x contiguous committees, synthetic BLS keys: validator i holds
sk = i+1, so signatures/pubkeys build incrementally without a per-
validator scalar multiply), then run the committee tree end to end —
per-subnet fan-in (tier 0), per-(subnet, root) partials (tier 1), the
global aggregate per attestation data root (tier 2) — and finally
VERIFY what was just built through the batched RLC path, with the
per-subnet partials fed to the ``verify_many`` bisection so injected
invalid committees are isolated to their (subnet, root).

Gates (direct mode):

  * bit parity vs the host oracle (``agg_tree.aggregate_slot_host``,
    the ``crypto/signature`` fold) at EVERY tier — committee, subnet
    partial, global (Points, bytes, and participation bits). A run
    that fails parity REFUSES to report throughput at all;
  * verification truth: clean roots verify True, roots holding an
    injected invalid committee verify False, and the bisection
    isolates exactly the injected (subnet, root) set;
  * zero cold compiles after the warmup pass (the warm slot run pays
    every (items, lanes[, mesh]) bucket compile; the timed reps must
    hit the jit cache only);
  * mesh parity (``--chips N``): the sharded slot's every tier
    bit-identical to the chips=1 dispatch — the chips=1-vs-N gate the
    acceptance demands.

Primary metric: **attestations aggregated + verified per second** at
registry scale (``agg.attestations_agg_per_s`` in the report's ``agg``
section, which scripts/perf_track.py ingests platform-aware).

Replicated mode (``--replicas R [--chaos]``, the agg-smoke CI job):
the committee fan-in submitted as ``aggregate`` ops through the
replicated front door (serve/frontdoor.py) — each committee's
compressed member signatures are one request, resolving to the exact
bytes ``crypto.signature.aggregate`` returns. ``--chaos`` SIGKILLs one
replica mid-fan-in (the deterministic ``frontdoor.rpc:kill`` grammar);
gates: zero lost requests, byte parity vs the host oracle on every
committee, and zero cold compiles on every replica — including the
respawned replacement, which warms from the shippable artifact.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prejax import force_virtual_chips  # noqa: E402

force_virtual_chips()

import numpy as np  # noqa: E402

from serve_bench import (  # noqa: E402
    _LOST,
    closed_loop,
    finish_report,
    wait_replicas_surveyed,
)

from eth_consensus_specs_tpu import obs  # noqa: E402
from eth_consensus_specs_tpu.crypto import signature as sig_mod  # noqa: E402
from eth_consensus_specs_tpu.crypto.curve import (  # noqa: E402
    g1_generator,
    g2_generator,
    g2_to_bytes,
)
from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2  # noqa: E402
from eth_consensus_specs_tpu.obs import export  # noqa: E402
from eth_consensus_specs_tpu.ops import agg_tree  # noqa: E402
from eth_consensus_specs_tpu.serve import buckets as serve_buckets  # noqa: E402
from eth_consensus_specs_tpu.serve.config import ServeConfig  # noqa: E402


def build_registry(
    n_validators: int,
    subnets: int,
    committee: int,
    n_roots: int = 2,
    invalid: int = 0,
    drop: int = 17,
) -> tuple[list, list]:
    """Synthesize the registry: validator i holds sk = i+1, committees
    are contiguous index ranges, attestation data roots are assigned in
    contiguous committee blocks (so per-root signature chains build by
    INCREMENTAL point addition — one scalar multiply per block start,
    one add per validator, which is what makes a million-validator
    registry constructible in minutes instead of hours). Every
    ``drop``-th validator abstains (ragged lanes + participation bits);
    the first member of each of ``invalid`` evenly-spread committees
    signs garbage. Returns (attestations, expected_bad)."""
    n_committees = max(n_validators // committee, 1)
    roots = [bytes([r + 1]) * 32 for r in range(n_roots)]
    bad_committees = {
        (i * n_committees) // invalid for i in range(invalid)
    } if invalid else set()
    G1, G2 = g1_generator(), g2_generator()
    atts, expected_bad = [], set()
    pk_run = None  # (i+1) * G1, built incrementally
    sig_run, sig_root = None, None  # (i+1) * H(root), per root block
    h_cache = {r: hash_to_g2(r) for r in roots}
    for c in range(n_committees):
        root = roots[(c * n_roots) // n_committees]
        base = h_cache[root]
        a = c * committee
        if sig_root != root:
            sig_run, sig_root = base.mul(a + 1), root
            started = a
        pks, sigs, bits = [], [], []
        for j in range(committee):
            v = a + j
            pk_run = G1 if v == 0 else pk_run + G1
            if v > started:
                sig_run = sig_run + base
            absent = drop > 0 and (v % drop) == drop - 1
            bits.append(not absent)
            if absent:
                continue
            pks.append(pk_run)
            sigs.append(sig_run)
        if c in bad_committees and sigs:
            sigs[0] = sigs[0] + G2  # a wrong signature, still on-curve
            expected_bad.add((c % subnets, root))
        atts.append(
            agg_tree.CommitteeAttestation(
                subnet=c % subnets, root=root,
                pubkeys=tuple(pks), sigs=tuple(sigs), bits=tuple(bits),
            )
        )
    return atts, sorted(expected_bad)


def _tiers_equal(a, b) -> bool:
    """(slot_aggs, subnet_aggs) equality at every tier: Points, bytes,
    participation bits — the bit-parity the gates demand."""
    slot_a, subs_a = a
    slot_b, subs_b = b
    if len(slot_a) != len(slot_b) or len(subs_a) != len(subs_b):
        return False
    for x, y in zip(subs_a, subs_b):
        if (x.subnet, x.root) != (y.subnet, y.root):
            return False
        if x.sig != y.sig or x.pubkey != y.pubkey:
            return False
        if not np.array_equal(x.bits, y.bits):
            return False
    for x, y in zip(slot_a, slot_b):
        if x.root != y.root or x.sig_bytes != y.sig_bytes:
            return False
        if x.pubkey_bytes != y.pubkey_bytes or not np.array_equal(x.bits, y.bits):
            return False
    return True


def run_direct(args) -> None:
    import jax

    from eth_consensus_specs_tpu.parallel import mesh_ops

    export.maybe_serve_http()
    platform = jax.local_devices()[0].platform
    mesh = mesh_ops.serve_mesh(args.chips) if args.chips > 1 else None
    failures: list = []

    t0 = time.time()
    atts, expected_bad = build_registry(
        args.validators, args.subnets, args.committee,
        n_roots=args.roots, invalid=args.invalid,
    )
    build_s = time.time() - t0
    obs.gauge("agg.registry_validators", args.validators)
    n_sigs = sum(len(a.sigs) for a in atts)

    # host-oracle truth at every tier (native-bridge accelerated where
    # available; pure python otherwise — untimed either way)
    t0 = time.time()
    host_tiers = agg_tree.aggregate_slot_host(atts)
    host_s = time.time() - t0

    # warmup: the warm slot run pays every bucket compile (and, via
    # ETH_SPECS_SERVE_WARMUP/--warmup-out, records the shippable keys)
    t0 = time.time()
    warm_tiers = agg_tree.aggregate_slot(atts, mesh=mesh)
    warm_verdicts = agg_tree.verify_slot(warm_tiers[0], mesh=mesh)
    warm_bad = agg_tree.isolate_invalid_subnets(warm_tiers[1], mesh=mesh)
    warmup_s = time.time() - t0
    compiles_after_warmup = obs.snapshot()["counters"].get("serve.compiles", 0)

    parity = _tiers_equal(warm_tiers, host_tiers)
    if not parity:
        failures.append("TIER PARITY FAILED: device tiers != host oracle "
                        "(throughput withheld)")

    # verification truth: clean roots True, poisoned roots False, and
    # the bisection isolates exactly the injected (subnet, root) set
    bad_roots = {root for _, root in expected_bad}
    want_verdicts = [sa.root not in bad_roots for sa in warm_tiers[0]]
    if warm_verdicts != want_verdicts:
        failures.append(
            f"verification verdicts {warm_verdicts} != expected {want_verdicts}"
        )
    if sorted(warm_bad) != expected_bad:
        failures.append(
            f"bisection isolated {sorted(warm_bad)} != injected {expected_bad}"
        )

    # timed reps: aggregate + verify, best-of-N against the jit cache
    best = None
    for _ in range(args.reps):
        t0 = time.perf_counter()
        tiers = agg_tree.aggregate_slot(atts, mesh=mesh)
        verdicts = agg_tree.verify_slot(tiers[0], mesh=mesh)
        if expected_bad:
            agg_tree.isolate_invalid_subnets(tiers[1], mesh=mesh)
        wall = time.perf_counter() - t0
        best = wall if best is None else min(best, wall)
        parity = parity and _tiers_equal(tiers, host_tiers)
        if verdicts != want_verdicts:
            failures.append("timed-rep verification verdicts diverged")
    if not parity and "TIER PARITY FAILED" not in "".join(failures):
        failures.append("TIER PARITY FAILED on a timed rep (throughput withheld)")

    # mesh parity: the chips=1-vs-N gate (single-device recompute)
    mesh_section = None
    if mesh is not None:
        single = agg_tree.aggregate_slot(atts, mesh=None)
        if not _tiers_equal(single, warm_tiers):
            failures.append("mesh parity FAILED: chips=1 tiers != sharded tiers")
        mesh_section = {
            "chips": args.chips,
            "shards": mesh_ops.shard_count(mesh),
            "signature": mesh_ops.mesh_signature(mesh),
            "parity": _tiers_equal(single, warm_tiers),
        }

    snap = obs.snapshot()
    counters = snap["counters"]
    extra = counters.get("serve.compiles", 0) - compiles_after_warmup
    if extra > 0:
        failures.append(f"{extra} compiles AFTER the warmup slot "
                        "(a shape escaped the agg buckets)")
    obs.count("serve.compiles_after_warmup", max(extra, 0))
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")

    agg_metrics = {}
    if parity:
        agg_metrics = {
            "attestations_agg_per_s": round(len(atts) / best, 2),
            "signatures_agg_per_s": round(n_sigs / best, 2),
            "slot_wall_s": round(best, 3),
        }
    report = {
        "mode": "agg-smoke" if args.smoke else "agg",
        "platform": platform,
        "validators": args.validators,
        "subnets": args.subnets,
        "committee": args.committee,
        "attestations": len(atts),
        "signatures": n_sigs,
        "roots": args.roots,
        "invalid_injected": len(expected_bad),
        "registry_build_s": round(build_s, 2),
        "host_oracle_s": round(host_s, 2),
        "warmup_s": round(warmup_s, 2),
        "parity": parity,
        "agg": agg_metrics,
        "mesh": mesh_section,
        "compiles": counters.get("serve.compiles", 0),
        "compiles_after_warmup": max(extra, 0),
        "compile_ms": snap["histograms"].get("agg.compile_ms", {}),
    }
    if args.warmup_out:
        report["warmup_artifact"] = args.warmup_out
        report["warmup_keys"] = serve_buckets.write_warmup(args.warmup_out)
    snap = obs.snapshot()
    finish_report(report, failures, args.out, "agg_bench.failure", snap)


def run_replicated(args) -> None:
    """The --replicas path: the committee fan-in as ``aggregate`` ops
    through a supervised replica fleet, optionally with a deterministic
    mid-fan-in SIGKILL."""
    from eth_consensus_specs_tpu.serve.config import FrontDoorConfig
    from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor

    export.maybe_serve_http()
    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
    if not pm_dir:
        pm_dir = os.path.join(out_dir, "postmortems")
        os.environ["ETH_SPECS_OBS_POSTMORTEM_DIR"] = pm_dir
    warmup_path = args.warmup_out or os.path.join(out_dir, "agg_warmup.jsonl")

    atts, _ = build_registry(
        args.validators, args.subnets, args.committee,
        n_roots=args.roots, invalid=0,
    )
    obs.gauge("agg.registry_validators", args.validators)
    sig_sets = [[g2_to_bytes(p) for p in a.sigs] for a in atts if a.sigs]
    # host-oracle truth per committee — the parent never touches the
    # device, so "zero cold compiles on every replica" stays honest
    direct = [sig_mod.aggregate(s) for s in sig_sets]
    # pipeline sanity on the host tiers rides along for free
    host_slot, _ = agg_tree.aggregate_slot_host(atts)
    for sa in host_slot:
        want = sig_mod.aggregate(
            [g2_to_bytes(p) for a in atts if bytes(a.root) == sa.root for p in a.sigs]
        )
        assert sa.sig_bytes == want, "host committee tree diverged from flat fold"

    # ONE flush shape: max_batch=1 makes every agg flush a single item
    # (the g2_agg item axis buckets pow2 of the LIVE flush size, so a
    # mixed-size flush stream would need one ~minute XLA:CPU scan-body
    # compile per pow2 — the budget here is chaos/parity/cold-compile
    # gates, not batching, which serve-smoke already covers)
    cfg = ServeConfig.from_env(max_batch=1, buckets=(1,))
    lane_bucket = serve_buckets.agg_lane_bucket(args.committee)
    warm_keys = [("g2_agg", 1, lane_bucket)]
    fault_spec = None
    if args.chaos:
        nth = max(len(sig_sets) // 8, 2)
        latch = os.path.join(out_dir, f"agg_kill_{os.getpid()}.latch")
        if os.path.exists(latch):
            os.unlink(latch)
        fault_spec = f"frontdoor.rpc:kill:nth={nth}:latch={latch}"

    fd = FrontDoor(
        replicas=args.replicas,
        config=cfg,
        fd_config=FrontDoorConfig.from_env(ready_timeout_s=900.0),
        warmup_path=warmup_path,
        warm_keys=warm_keys,
        replica_fault_spec=fault_spec,
        name="agg-fd",
    )
    load = [("agg", s) for s in sig_sets]
    wall_s, got, _lat = closed_loop(fd, load, args.submitters, result_timeout=600.0)
    wait_replicas_surveyed(fd)  # incl. a chaos respawn still booting
    replica_stats = fd.replica_stats()
    stats = fd.stats()
    fd.close()

    failures = []
    lost = sum(1 for r in got if r is _LOST)
    if lost:
        failures.append(f"{lost} aggregate requests lost (futures never resolved)")
    if got != direct:
        failures.append("AGG parity: replicated aggregates != host-oracle bytes")
    snap = obs.snapshot()
    counters = snap["counters"]
    replaced = counters.get("frontdoor.replicas_replaced", 0)
    if args.chaos and replaced < 1:
        failures.append("chaos run but frontdoor.replicas_replaced == 0 "
                        "(the kill never happened or was never healed)")
    cold = {
        i: s["compiles_after_ready"]
        for i, s in enumerate(replica_stats)
        if s is not None and s.get("compiles_after_ready")
    }
    if cold:
        failures.append(f"cold compiles after warmup on replicas: {cold}")
    obs.count("serve.compiles_after_warmup", sum(cold.values()))
    surveyed = sum(1 for s in replica_stats if s is not None)
    if surveyed < args.replicas:
        failures.append(
            f"only {surveyed}/{args.replicas} replicas answered a health probe"
        )
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")

    report = {
        "mode": "agg-replicated-chaos" if args.chaos else "agg-replicated",
        "replicas": args.replicas,
        "submitters": args.submitters,
        "validators": args.validators,
        "attestations": len(sig_sets),
        "agg": {
            "attestations_agg_per_s": round(len(sig_sets) / wall_s, 2)
            if got == direct else None,
        },
        "lost": lost,
        "replicas_replaced": replaced,
        "failovers": stats["failovers"],
        "hedges": stats["hedges"],
        "replica_stats": replica_stats,
        "warmup_artifact": warmup_path,
        "warmup_keys": len(serve_buckets.load_warmup(warmup_path)),
    }
    snap = obs.snapshot()
    finish_report(report, failures, args.out, "agg_bench.replicated_failure", snap)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="small-registry CI run (same hard gates)")
    ap.add_argument("--validators", type=int, default=1 << 20,
                    help="registry size (default 1Mi — the acceptance scale)")
    ap.add_argument("--subnets", type=int, default=agg_tree.subnet_count())
    ap.add_argument("--committee", type=int, default=256,
                    help="validators per committee")
    ap.add_argument("--roots", type=int, default=2,
                    help="distinct attestation data roots per slot")
    ap.add_argument("--invalid", type=int, default=2,
                    help="committees injected with a wrong member signature")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--submitters", type=int, default=16)
    ap.add_argument("--chips", type=int,
                    default=int(os.environ.get("ETH_SPECS_SERVE_CHIPS", "0") or 0))
    ap.add_argument("--replicas", type=int, default=0,
                    help="run the fan-in through an R-replica front door")
    ap.add_argument("--chaos", action="store_true",
                    help="with --replicas: SIGKILL one replica mid-fan-in")
    ap.add_argument("--out", default="BENCH_AGG.json")
    ap.add_argument("--warmup-out", default=None,
                    help="write the shippable warmup artifact here")
    args = ap.parse_args()
    if args.smoke:
        args.validators = min(args.validators, 2048)
        args.subnets = min(args.subnets, 8)
        args.committee = min(args.committee, 4)
        args.invalid = min(args.invalid, 1)
        args.reps = min(args.reps, 2)
        args.submitters = min(args.submitters, 8)
    if args.replicas > 0:
        run_replicated(args)
        return
    run_direct(args)


if __name__ == "__main__":
    main()
