"""Pre-jax-init bootstrap shared by the chips-aware CLIs.

``serve_bench.py`` and ``jaxlint.py`` both need N virtual CPU devices,
and XLA reads ``XLA_FLAGS`` exactly once — at backend init — so the
``--chips`` pre-parse must run BEFORE the first jax-touching import.
Two argv pre-parsers had already drifted (one honored
``ETH_SPECS_SERVE_CHIPS``, the other forced flags off-platform); this
module is the single copy. It deliberately imports nothing heavy: the
package ``__init__`` pulls in jax, so this must stay importable first.

Usage (from a script in scripts/):

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from prejax import force_virtual_chips
    chips = force_virtual_chips()          # serve_bench: env fallback
    chips = force_virtual_chips(default=8, env_var=None)  # jaxlint
"""

from __future__ import annotations

import os
import sys


def force_virtual_chips(
    default: int = 0, env_var: str | None = "ETH_SPECS_SERVE_CHIPS"
) -> int:
    """Pre-parse ``--chips N`` from argv (falling back to ``env_var``,
    then ``default``) and force that many virtual CPU devices via
    ``XLA_FLAGS`` — only on the cpu platform, only when the flag is not
    already set, and only for N > 1. Defaults ``JAX_PLATFORMS`` to cpu
    (real-accelerator hosts override it and are left alone). Returns
    the resolved chip count."""
    n = 0
    argv = sys.argv
    for i, a in enumerate(argv):
        if a == "--chips" and i + 1 < len(argv):
            try:
                n = int(argv[i + 1])
            except ValueError:
                pass
        elif a.startswith("--chips="):
            try:
                n = int(a.split("=", 1)[1])
            except ValueError:
                pass
    if n <= 0 and env_var:
        try:
            n = int(os.environ.get(env_var, "0") or 0)
        except ValueError:
            n = 0
    if n <= 0:
        n = default
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if (
        n > 1
        and os.environ.get("JAX_PLATFORMS") == "cpu"
        and "xla_force_host_platform_device_count" not in flags
    ):
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    return n
