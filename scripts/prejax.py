"""Pre-jax-init bootstrap shared by the chips-aware CLIs.

``serve_bench.py`` and ``jaxlint.py`` both need N virtual CPU devices,
and XLA reads ``XLA_FLAGS`` exactly once — at backend init — so the
``--chips`` pre-parse must run BEFORE the first jax-touching import.
The implementation lives in ``eth_consensus_specs_tpu/prejax.py`` (the
replica child boot shares it for its per-replica spawn env), loaded
here BY FILE PATH so the package ``__init__`` (which pulls in jax)
never executes before the flags are set. Both modules deliberately
import nothing heavy.

Usage (from a script in scripts/):

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from prejax import force_virtual_chips
    chips = force_virtual_chips()          # serve_bench: env fallback
    chips = force_virtual_chips(default=8, env_var=None)  # jaxlint
"""

from __future__ import annotations

import importlib.util
import os

_IMPL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "eth_consensus_specs_tpu",
    "prejax.py",
)
_spec = importlib.util.spec_from_file_location("_prejax_impl", _IMPL)
_impl = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_impl)

chips_xla_flags = _impl.chips_xla_flags
force_virtual_chips = _impl.force_virtual_chips
parse_chips = _impl.parse_chips
parse_chips_matrix = _impl.parse_chips_matrix
parse_replicas = _impl.parse_replicas
replica_chips_env = _impl.replica_chips_env
