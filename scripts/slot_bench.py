"""The slot machine: mainnet-shaped whole-slot replay through a fleet.

Drives ``submit_slot`` (the whole-slot state-transition pipeline,
ops/slot_pipeline.py + serve/slot.py) end to end through a supervised
replica fleet and writes a JSON report (default BENCH_SLOT.json) whose
``slot`` section feeds perf_track.py (``slots_per_s`` headline +
per-phase p99 advisories).

The load is a deterministic, seeded schedule of mainnet-SHAPED slots:
ragged committees with realistic size spread, a sync aggregate, a
configurable invalid rate (bad aggregate signatures, bad blob proofs —
sparse, like a real network), a blob-count distribution (Poisson-ish,
capped at the DAS limit), and bursty arrivals (slots between epoch
boundaries land as one burst; a boundary slot is a sync point, exactly
the chain's own commutativity: participation ORs and balance credits
commute within an epoch window, the boundary does not).

Gates — all hard, every one REFUSES the throughput number on failure:

  * **bit parity** — every slot's verdicts/aggregates/epoch, every
    boundary slot's state root, and the FINAL root must equal the
    sequential host fold of the same schedule (``host_slot_fold``).
    A parity failure fails the run; no throughput is reported.
  * **zero lost slots** — every submitted slot resolves (Overloaded is
    flow control, honored with its ``retry_after_s`` hint, not loss).
  * **zero cold compiles after warmup** — the fleet boots from explicit
    slot warm keys (the LIVE ``buckets.slot_key`` over the schedule's
    request-derived capacities) plus the shippable warmup artifact;
    after the bench's warmup burst, NO replica may compile again —
    including (``--chaos``) the respawned owner, which must come up
    clean from the artifact its predecessor enriched.
  * **chaos** (``--chaos``) — the slot OWNER (replica 0, the single
    stateful member) is SIGKILLed mid-load. The supervisor must respawn
    it, the respawn must restore the durable checkpoint, and the load
    must finish with zero lost slots and bit parity intact: a committed
    slot re-submitted after the kill must come back ``replayed`` with
    its original (oracle-identical) root. Zero lost slots, zero
    double-applies, bit-identical restored state.

``--smoke`` shrinks the schedule for CI (the slot-smoke job in
checks.yml). Exit code 0 only if every gate passes.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import signal
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prejax import force_virtual_chips  # noqa: E402

force_virtual_chips()

import numpy as np  # noqa: E402

from eth_consensus_specs_tpu import obs  # noqa: E402
from eth_consensus_specs_tpu.obs import canary as canary_mod  # noqa: E402
from eth_consensus_specs_tpu.obs import export, timeline  # noqa: E402
from eth_consensus_specs_tpu.ops import slot_pipeline as sp  # noqa: E402
from eth_consensus_specs_tpu.serve import buckets as serve_buckets  # noqa: E402
from eth_consensus_specs_tpu.serve.config import ServeConfig  # noqa: E402
from eth_consensus_specs_tpu.utils import bls  # noqa: E402

from serve_bench import (  # noqa: E402
    finish_report,
    latency_histogram,
    wait_replicas_surveyed,
)

MAX_BLOBS = 6  # the DAS per-block sidecar cap the distribution respects


# ---------------------------------------------------------- the schedule --
#
# Deterministic from --seed: the parent builds the identical schedule
# for the oracle fold and the fleet load, and a re-run reproduces a
# failure exactly. Keys are vi+1000 (attesters) / i+2000 (sync) — the
# request carries its own pubkeys, so any fixed mapping works.


def _sign_att(members, root):
    sks = [1000 + int(vi) for vi in members]
    return bytes(bls.Aggregate([bls.Sign(sk, root) for sk in sks]))


def _blob_item(rng, bad=False):
    from eth_consensus_specs_tpu.crypto import kzg

    raw = rng.integers(0, 256, size=kzg.FIELD_ELEMENTS_PER_BLOB * 32, dtype=np.uint8)
    out = []
    for j in range(kzg.FIELD_ELEMENTS_PER_BLOB):
        v = int.from_bytes(raw[j * 32 : (j + 1) * 32].tobytes(), "big")
        out.append((v % kzg.BLS_MODULUS).to_bytes(32, "big"))
    blob = b"".join(out)
    c = kzg.blob_to_kzg_commitment(blob)
    p = kzg.compute_blob_kzg_proof(blob, c)
    if bad:
        blob = blob[:-1] + bytes([blob[-1] ^ 1])
    return (blob, bytes(c), bytes(p))


def build_schedule(args) -> list[sp.SlotRequest]:
    """Mainnet-shaped slots, scaled to the registry: each slot carries
    ``--committees`` ragged committees (sizes spread around n/8), a
    sync aggregate over a fixed-size random subset, sparse invalid
    items at ``--invalid-rate``, and a capped-Poisson blob count."""
    rng = np.random.default_rng(args.seed)
    n = args.validators
    c_lo = max(n // 16, 2)
    c_hi = max(n // 6, c_lo + 1)
    sync_n = min(max(n // 8, 4), 16)
    reqs = []
    for s in range(args.slots):
        atts = []
        for c in range(args.committees):
            size = int(rng.integers(c_lo, c_hi + 1))
            members = rng.choice(n, size=size, replace=False)
            bits = rng.random(size) < 0.9
            if not bits.any():
                bits[0] = True
            root = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            signers = [int(vi) for vi, b in zip(members, bits) if b]
            sig = _sign_att(signers, root)
            if rng.random() < args.invalid_rate:
                sig = bytes(bls.Sign(9999, root))  # wrong key: bad aggregate
            atts.append(
                sp.SlotAttestation(
                    subnet=c % 8,
                    root=root,
                    committee=tuple(int(v) for v in members),
                    bits=tuple(bool(b) for b in bits),
                    pubkeys=tuple(bytes(bls.SkToPk(1000 + vi)) for vi in signers),
                    sig=sig,
                )
            )
        sync_idx = rng.choice(n, size=sync_n, replace=False)
        sync_msg = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        sync_sks = [2000 + i for i in range(sync_n)]
        sync_sig = bytes(bls.Aggregate([bls.Sign(sk, sync_msg) for sk in sync_sks]))
        if rng.random() < args.invalid_rate:
            sync_sig = bytes(bls.Sign(9999, sync_msg))
        n_blobs = min(int(rng.poisson(args.blob_rate)), MAX_BLOBS)
        blobs = tuple(
            _blob_item(rng, bad=rng.random() < args.invalid_rate)
            for _ in range(n_blobs)
        )
        reqs.append(
            sp.SlotRequest(
                slot=s,
                attestations=tuple(atts),
                sync_pubkeys=tuple(bytes(bls.SkToPk(sk)) for sk in sync_sks),
                sync_message=sync_msg,
                sync_sig=sync_sig,
                sync_indices=tuple(int(v) for v in sync_idx),
                blobs=blobs,
                epoch_boundary=(s + 1) % args.slots_per_epoch == 0,
            )
        )
    return reqs


def run_oracle(args, reqs):
    """The sequential host fold of the whole schedule — the bit truth
    every gate compares against (the exact SlotWorld world recipe)."""
    import jax

    import __graft_entry__ as graft
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_root import synthetic_static

    spec = get_spec("altair", "minimal")
    static = synthetic_static(spec, args.validators)
    cols, just = graft._example_altair_inputs(args.validators)
    cols, just = jax.device_put(cols), jax.device_put(just)
    epoch, results = 0, []
    for req in reqs:
        res, cols, just = sp.host_slot_fold(spec, static, cols, just, req, epoch)
        epoch = res.epoch
        results.append(res)
    return results


def slot_warm_keys(args, reqs) -> list[tuple]:
    """Explicit warm keys for the fleet boot: every ``slot_apply``
    bucket the schedule's request-derived capacities will hit (the LIVE
    key fn — router, dispatch, and warmup can never disagree), plus the
    blob-verification lane buckets the sidecar distribution needs."""
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_root import forest_plan, synthetic_static

    _, meta = synthetic_static(get_spec("altair", "minimal"), args.validators)
    plan = forest_plan(meta)
    keys = {serve_buckets.slot_key(args.validators, 1, 1, plan)}
    blob_counts = set()
    for req in reqs:
        flags, rewards = sp.request_capacity(req)
        keys.add(serve_buckets.slot_key(args.validators, flags, rewards, plan))
        if req.blobs:
            blob_counts.add(len(req.blobs))
    for c in blob_counts:
        keys.add(serve_buckets.kzg_msm_key(c))
    return sorted(keys)


# -------------------------------------------------------------- the load --

_LOST = object()


def submit_with_retry(fd, req, timeout_s: float, deadline_s: float):
    """One slot through the front door, honoring typed sheds (and the
    owner-down window during a chaos respawn) until the deadline."""
    from eth_consensus_specs_tpu.serve.admission import Overloaded

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            return fd.submit_slot(req).result(timeout=timeout_s)
        except Overloaded as exc:
            time.sleep(min(max(exc.retry_after_s, 0.05), 1.0))
        except Exception:
            time.sleep(0.2)
    return _LOST


def run_bench(args) -> None:
    from eth_consensus_specs_tpu.serve.config import FrontDoorConfig
    from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor

    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    os.makedirs(out_dir, exist_ok=True)
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
    if not pm_dir:
        pm_dir = os.path.join(out_dir, "postmortems")
        os.environ["ETH_SPECS_OBS_POSTMORTEM_DIR"] = pm_dir
    warmup_path = args.warmup_out or os.path.join(out_dir, "warmup_shapes.jsonl")
    ckpt_dir = args.ckpt_dir or os.path.join(out_dir, "slot_ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)

    export.maybe_serve_http()
    # fleet timeline source: stream this process's events as JSONL next
    # to the report (replicas inherit the env at spawn and re-point to
    # sibling files), so every run leaves an assemblable trace — the
    # autopsy epilogue and the CI Perfetto artifact both read it
    if not os.environ.get("ETH_SPECS_OBS_JSONL"):
        jsonl = os.path.splitext(os.path.abspath(args.out))[0] + ".events.jsonl"
        os.environ["ETH_SPECS_OBS_JSONL"] = jsonl
        obs.get_registry().configure_jsonl(jsonl)
    print(f"slot-machine: building {args.slots}-slot schedule "
          f"(n={args.validators}, invalid={args.invalid_rate}, "
          f"blobs~{args.blob_rate})", flush=True)
    reqs = build_schedule(args)
    oracle = run_oracle(args, reqs)

    cfg = ServeConfig.from_env(
        max_batch=8,
        max_wait_ms=5,
        slot_validators=args.validators,
        slot_ckpt_dir=ckpt_dir,
    )
    # continuous telemetry plane: structural detectors only (bench load
    # is not organic traffic), and a generous completion-stall horizon —
    # a single slot apply is legitimately seconds long on CPU, so the
    # default 15×200ms window would page on healthy full-scale runs
    os.environ.setdefault("ETH_SPECS_ANOM_DETECTORS", "structural")
    os.environ.setdefault("ETH_SPECS_ANOM_STALL_WINDOWS", "150")
    fd_cfg = FrontDoorConfig.from_env()
    if args.canary_ms > 0 and fd_cfg.canary_interval_ms <= 0:
        fd_cfg = dataclasses.replace(
            fd_cfg, canary_interval_ms=float(args.canary_ms))
    warm = slot_warm_keys(args, reqs)
    if fd_cfg.canary_interval_ms > 0:
        # canary compile shapes (flush-group size 1): the canary stream
        # rides the slot fleet's stateless verbs and must not cold-compile
        warm = sorted(set(warm) | set(canary_mod.warm_keys()))
    fd = FrontDoor(
        replicas=args.replicas,
        config=cfg,
        fd_config=fd_cfg,
        warmup_path=warmup_path,
        warm_keys=warm,
        name="slot-fd",
    )
    failures: list[str] = []
    try:
        _run_load(args, fd, reqs, oracle, failures, warmup_path, pm_dir)
    finally:
        fd.close()


def _windows(reqs):
    """Epoch windows: [commuting slots..., boundary sync point]."""
    win: list = []
    for req in reqs:
        win.append(req)
        if req.epoch_boundary:
            yield win
            win = []
    if win:
        yield win


def _check_slot(got, want, failures, gate_root: bool):
    if got is _LOST:
        failures.append(f"slot {want.slot}: LOST (never resolved)")
        return
    for field in ("att_verdicts", "sync_verdict", "blob_verdicts",
                  "subnet_aggregates", "epoch"):
        if getattr(got, field) != getattr(want, field):
            failures.append(
                f"slot {want.slot} parity: {field} diverged from the host fold"
            )
    if gate_root and got.state_root != want.state_root:
        failures.append(
            f"slot {want.slot} parity: root {got.state_root.hex()[:16]} != "
            f"host fold {want.state_root.hex()[:16]}"
        )


def _owner_compiles(fd) -> int:
    stats = fd.replica_stats()
    s = stats[0] if stats else None
    return int(s.get("compiles", 0)) if s else -1


def _run_load(args, fd, reqs, oracle, failures, warmup_path, pm_dir):
    want_by_slot = {r.slot: w for r, w in zip(reqs, oracle)}
    windows = list(_windows(reqs))

    # warmup burst: the first window, counted separately — after it, the
    # cold-compile gate arms (boot warm keys cover slot_apply/kzg; the
    # verify leg's bisection shapes surface here at the latest)
    latencies: list[float] = []
    results: dict[int, object] = {}
    t_start = time.perf_counter()
    chaos_done = not args.chaos
    killed_at = None
    recovery_s = None
    for wi, window in enumerate(windows):
        if wi == 1:
            wait_replicas_surveyed(fd)
            warm_compiles = _owner_compiles(fd)
        if not chaos_done and wi == max(len(windows) // 2, 1):
            # mid-load chaos: SIGKILL the OWNER — the single stateful
            # replica; the fleet has no failover for slots, only a
            # respawn-restore, which is exactly the contract under test
            proc = fd._procs[0]
            if proc is not None and proc.pid:
                print(f"chaos: SIGKILL slot owner pid={proc.pid}", flush=True)
                os.kill(proc.pid, signal.SIGKILL)
                killed_at = time.perf_counter()
            chaos_done = True
        body, boundary = window[:-1], window[-1]
        # bursty arrival: the window's slots land in bursts (they
        # commute: participation ORs + balance credits), then the
        # boundary slot is the sync point the roots are gated at
        gate_each_root = args.burst <= 1
        pending = list(body)
        while pending:
            burst, pending = pending[:args.burst], pending[args.burst:]
            t0 = time.perf_counter()
            got = [
                submit_with_retry(fd, r, args.timeout_s, args.deadline_s)
                for r in burst
            ]
            latencies.extend([(time.perf_counter() - t0) / max(len(burst), 1)] * len(burst))
            for r, g in zip(burst, got):
                results[r.slot] = g
                _check_slot(g, want_by_slot[r.slot], failures, gate_each_root)
        t0 = time.perf_counter()
        g = submit_with_retry(fd, boundary, args.timeout_s, args.deadline_s)
        latencies.append(time.perf_counter() - t0)
        results[boundary.slot] = g
        _check_slot(g, want_by_slot[boundary.slot], failures, gate_root=True)
        if killed_at is not None and recovery_s is None and g is not _LOST:
            recovery_s = time.perf_counter() - killed_at
    wall_s = time.perf_counter() - t_start

    lost = sum(1 for g in results.values() if g is _LOST)
    final_slot = reqs[-1].slot
    final = results.get(final_slot)
    if final is not _LOST and final is not None:
        if final.state_root != oracle[-1].state_root:
            failures.append("FINAL root diverged from the sequential host fold")

    # idempotent replay: a committed boundary slot re-submitted after the
    # load (post-chaos: through the RESTORED owner) must come back
    # replayed with its original, oracle-identical root — the
    # zero-double-apply proof
    replay_src = next((r for r in reqs if r.epoch_boundary), reqs[0])
    rep = submit_with_retry(fd, replay_src, args.timeout_s, args.deadline_s)
    if rep is _LOST:
        failures.append("replay probe lost")
    else:
        if not rep.replayed:
            failures.append("replay probe was re-applied, not replayed "
                            "(double-apply hazard)")
        if rep.state_root != want_by_slot[replay_src.slot].state_root:
            failures.append("replayed root != host fold root "
                            "(restored state diverged)")

    wait_replicas_surveyed(fd)
    replica_stats = fd.replica_stats()
    snap = obs.snapshot()
    counters = snap["counters"]

    if lost:
        failures.append(f"{lost} slots lost (zero-loss gate)")
    # zero cold compiles after the warmup window, fleet-wide: the
    # owner's compile counter must not move after window 0, siblings
    # must never compile after ready, and a chaos respawn must come up
    # clean from the enriched warmup artifact
    end_compiles = _owner_compiles(fd)
    owner_respawned = counters.get("frontdoor.replicas_replaced", 0) > 0
    if len(windows) > 1 and not owner_respawned:
        if end_compiles != warm_compiles:
            failures.append(
                f"cold compiles after warmup on the owner: "
                f"{warm_compiles} -> {end_compiles}"
            )
    cold = {
        i: s["compiles_after_ready"]
        for i, s in enumerate(replica_stats)
        if s is not None and i != 0 and s.get("compiles_after_ready")
    }
    if cold:
        failures.append(f"cold compiles after ready on siblings: {cold}")
    if owner_respawned:
        s0 = replica_stats[0] if replica_stats else None
        if s0 is None:
            failures.append("respawned owner never answered a health probe")
        elif s0.get("compiles_after_ready"):
            failures.append(
                f"respawned owner cold-compiled {s0['compiles_after_ready']} "
                "shapes after ready — the warmup artifact missed them"
            )
    if args.chaos and not owner_respawned:
        failures.append("chaos run but the owner was never replaced")

    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")

    # telemetry plane: canaries resolved bit-exactly through the slot
    # fleet's stateless verbs; structural detectors silent on a clean
    # run, and on a chaos run the owner kill is detected and attributed
    telemetry = fd.telemetry_report()
    can = telemetry.get("canary")
    if can is not None:
        if can.get("sent", 0) < 1:
            failures.append("no canaries sent through the slot front door")
        if can.get("parity_failures"):
            failures.append(
                f"{can['parity_failures']} canary parity failures — the fleet "
                "returned different bits than the host oracle")
    anom = telemetry.get("anomaly")
    if anom is not None:
        fires = dict(anom.get("fires") or {})
        if args.chaos:
            dead = [f for f in anom.get("fired", ())
                    if f.get("detector") == "dead_replica"]
            if not dead:
                failures.append("chaos run but the dead_replica detector "
                                "never fired on the owner kill")
            elif dead[0].get("replica") != 0 or dead[0].get("stage") != "recovery":
                failures.append(
                    f"dead_replica fired without owner attribution: {dead[0]}")
            # the owner kill legitimately trips the death/probe/stall
            # detectors (slots have no failover — completions stop until
            # the respawn-restore finishes); anything else is a lie
            unexpected = {
                k: v for k, v in fires.items()
                if k not in ("dead_replica", "probe_stall", "completion_stall")
            }
        else:
            unexpected = fires
        if unexpected:
            failures.append(f"unexpected anomaly fires: {unexpected}")

    phases = {}
    for ph in ("verify", "aggregate", "reroot"):
        h = snap["histograms"].get(f"serve.stage_ms.slot.{ph}", {})
        phases[f"{ph}_p50_ms"] = h.get("p50")
        phases[f"{ph}_p99_ms"] = h.get("p99")
        if not h.get("count"):
            failures.append(
                f"serve.stage_ms.slot.{ph} is empty — the phase waterfall "
                "never reached the parent"
            )

    slot_section = {
        "slots": len(reqs),
        "lost": lost,
        "replayed_probe_ok": rep is not _LOST and getattr(rep, "replayed", False),
        **phases,
        "host_folds": counters.get("serve.degraded_items", 0),
    }
    # the parity gate REFUSES the throughput number: a wrong-root fleet
    # has no legitimate slots/sec
    if not failures:
        slot_section["slots_per_s"] = round(len(reqs) / wall_s, 3)
        slot_section["correctness_coupled"] = True
    report = {
        "mode": "slot-chaos" if args.chaos else "slot",
        "replicas": args.replicas,
        "validators": args.validators,
        "slots_per_epoch": args.slots_per_epoch,
        "invalid_rate": args.invalid_rate,
        "blob_rate": args.blob_rate,
        "burst": args.burst,
        "seed": args.seed,
        "wall_s": round(wall_s, 3),
        "recovery_s": round(recovery_s, 3) if recovery_s is not None else None,
        "replicas_replaced": counters.get("frontdoor.replicas_replaced", 0),
        "final_root": oracle[-1].state_root.hex(),
        "latency_hist": latency_histogram(latencies),
        "replica_stats": replica_stats,
        "warmup_artifact": warmup_path,
        "warmup_keys": len(serve_buckets.load_warmup(warmup_path)),
        "slot": slot_section,
        "telemetry": telemetry,
    }
    # slot autopsy: the worst slot's critical path, from the fleet's
    # own JSONL streams under corrected clocks. On a chaos run the
    # attribution coverage GATES — a respawn whose outage doesn't land
    # in named stages means the recovery accounting broke
    jsonl = os.environ.get("ETH_SPECS_OBS_JSONL")
    if jsonl:
        autop = None
        try:
            autop = timeline.Timeline.from_path(jsonl).autopsy()
        except Exception as exc:  # noqa: BLE001 — diagnose, don't crash the bench
            failures.append(f"slot autopsy crashed: {exc!r}")
        if autop is not None:
            report["autopsy"] = autop
            print(timeline.render_autopsy(autop), flush=True)
            if args.chaos and autop["coverage"] < 0.95:
                failures.append(
                    f"autopsy attribution coverage {autop['coverage']:.3f} "
                    f"< 0.95 on the chaos run (slot {autop['slot']})"
                )
        elif args.chaos:
            failures.append("chaos run produced no autopsy (no slot "
                            "request events in the JSONL streams)")
    finish_report(report, failures, args.out, "slot_bench.failure", snap)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--validators", type=int, default=256)
    ap.add_argument("--committees", type=int, default=4)
    ap.add_argument("--slots-per-epoch", type=int, default=8)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--invalid-rate", type=float, default=0.1)
    ap.add_argument("--blob-rate", type=float, default=0.75,
                    help="mean of the capped-Poisson blob-count distribution")
    ap.add_argument("--burst", type=int, default=2,
                    help="slots per arrival burst within an epoch window; "
                    "1 additionally gates EVERY slot's root (strict order)")
    ap.add_argument("--chaos", action="store_true",
                    help="SIGKILL the slot owner mid-load")
    ap.add_argument("--smoke", action="store_true",
                    help="shrink everything for CI")
    ap.add_argument("--seed", type=int, default=20260806)
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--deadline-s", type=float, default=600.0,
                    help="per-slot overall deadline incl. retries/respawn")
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--out", default="BENCH_SLOT.json")
    ap.add_argument("--warmup-out", default="")
    ap.add_argument("--canary-ms", type=float, default=250.0,
                    help="known-answer canary interval in ms through the "
                         "fleet's stateless verbs (0 disables)")
    args = ap.parse_args()
    if args.smoke:
        args.slots = min(args.slots, 10)
        args.validators = min(args.validators, 64)
        args.committees = min(args.committees, 3)
        args.slots_per_epoch = min(args.slots_per_epoch, 5)
    args.validators = max(args.validators, 32)
    args.slots_per_epoch = max(args.slots_per_epoch, 2)
    run_bench(args)


if __name__ == "__main__":
    main()
