"""Slot autopsy CLI: why did slot N miss its budget?

Thin argparse surface over :mod:`eth_consensus_specs_tpu.obs.timeline`
(the logic lives in the package so tests exercise it directly). Three
modes, all pure-host — no accelerator, no jax import:

  * **autopsy** (default) — assemble the fleet's JSONL streams (the
    parent file plus its ``<base>.<replica>.jsonl`` siblings), correct
    per-process clocks from the recorded ``clock.sync`` pairs, and
    print the critical-path budget verdict for one slot (``--slot``),
    one trace id (``--trace``), or the worst slot in the window
    (neither). ``--events`` names the parent stream; ``--report``
    instead pulls the stream path (and budget context) from a
    slot_bench/serve_bench report JSON.
  * **--perfetto OUT** — also write the merged Perfetto trace (load it
    at ui.perfetto.dev).
  * **--diff A B** — compare two bench reports' stage histograms and
    name the stages (and replicas) a p99 regression hides in.

Exit status: 0 on success, 1 when nothing matched or (with
``--min-coverage``) the attribution coverage gate failed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from eth_consensus_specs_tpu.obs import timeline  # noqa: E402


def _load_report(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--events", help="parent JSONL stream (replica siblings found next to it)")
    ap.add_argument("--report", help="bench report JSON carrying an `events_jsonl` path")
    ap.add_argument("--slot", type=int, help="slot number to autopsy (default: worst)")
    ap.add_argument("--trace", help="trace id (or prefix) to autopsy instead of a slot")
    ap.add_argument("--budget-ms", type=float, default=None,
                    help="slot budget override (default ETH_SPECS_SLOT_BUDGET_MS)")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="also write the merged Perfetto trace here")
    ap.add_argument("--min-coverage", type=float, default=None,
                    help="fail (exit 1) when named-stage coverage is below this fraction")
    ap.add_argument("--json", action="store_true",
                    help="print the autopsy as JSON instead of the one-screen text")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="compare two bench reports' stage histograms")
    args = ap.parse_args(argv)

    if args.diff:
        d = timeline.diff_reports(_load_report(args.diff[0]), _load_report(args.diff[1]))
        print(json.dumps(d, indent=2) if args.json else timeline.render_diff(d))
        return 0

    events_path = args.events
    if args.report:
        rep = _load_report(args.report)
        events_path = events_path or rep.get("events_jsonl")
        if not events_path:
            print(f"{args.report} carries no events_jsonl path", file=sys.stderr)
            return 1
    if not events_path:
        ap.error("one of --events / --report / --diff is required")

    tl = timeline.Timeline.from_path(events_path)
    if not tl.events:
        print(f"no events found under {events_path}", file=sys.stderr)
        return 1
    if args.perfetto:
        with open(args.perfetto, "w", encoding="utf-8") as fh:
            json.dump(tl.perfetto(), fh)
        print(f"perfetto trace -> {args.perfetto}")
    rep = tl.autopsy(slot=args.slot, trace_id=args.trace, budget_ms=args.budget_ms)
    if rep is None:
        target = args.trace or args.slot
        print(f"no terminal request events matched {target!r}", file=sys.stderr)
        return 1
    print(json.dumps(rep, indent=2) if args.json else timeline.render_autopsy(rep))
    if args.min_coverage is not None and rep["coverage"] < args.min_coverage:
        print(
            f"attribution coverage {rep['coverage']:.3f} below the "
            f"{args.min_coverage} gate",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # piped into `head` and the reader hung up: that's not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        raise SystemExit(0)
