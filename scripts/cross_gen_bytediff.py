"""Cross-generator byte-diff (round-5 verdict item #6).

Strongest-possible conformance artifact for the agreed slice — every
operations handler with a direct reference sub-transition
(attestation, sync_aggregate, bls_to_execution_change, withdrawals),
sanity/blocks, sanity/slots, finality/finality, random/random and
epoch_processing/pending_deposits, over phase0 + altair + capella +
electra, minimal (the SLICE tuple below is the source of truth):

MODE A — always available (this environment has no eth2spec install and
no network): CONSUMER-SIDE REPLAY.  This framework's generator emits the
vector tree; then every emitted case is re-executed by the REFERENCE'S
OWN SPEC — the normative markdown under /root/reference/specs compiled
by specc/ (sha256-pinned against drift) — consuming the vectors exactly
as a client's reftest runner would: deserialize pre + inputs from the
.ssz_snappy bytes, run the reference's process_attestation /
state_transition, and require the serialized post-state to be
BYTE-IDENTICAL to the emitted post.ssz_snappy payload (invalid cases
must make the reference spec raise).  A divergence in enumeration,
serialization, or transition semantics fails the run.

MODE B — literal two-tree diff: where the reference's own pyspec
package (eth2spec + remerkleable/py_ecc/...) is importable (NOT in this
image, and installs are forbidden), run the reference's generator for
the same slice (`python tests/generators/main.py` filtered to the
slice) and `diff -r` the two emitted trees.  This script only REPORTS
whether that environment exists — the invocation is a documented manual
step, not an automatic one.

Usage:  python scripts/cross_gen_bytediff.py [--output DIR]
Exit 0 = every case byte-identical; nonzero otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

# this is a pure-CPU conformance artifact: the spec's columnar kernels
# must not dispatch at an experimental accelerator backend (a half-up
# tunnel turns each jit call into a stall)
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.gen.gen_from_tests import discover_test_cases
from eth_consensus_specs_tpu.gen.gen_runner import run_generator
from eth_consensus_specs_tpu.gen.snappy_codec import frame_decompress
from eth_consensus_specs_tpu.specc import compile_fork
from eth_consensus_specs_tpu.utils import bls

FORKS = ("phase0", "altair", "capella", "electra")
SLICE = (
    ("operations", "attestation"),
    ("operations", "sync_aggregate"),
    ("operations", "bls_to_execution_change"),
    ("operations", "withdrawals"),
    ("sanity", "blocks"),
    ("sanity", "slots"),
    ("finality", "finality"),
    ("random", "random"),
    # every epoch_processing handler the test corpus emits: the replay
    # dispatches process_<handler> generically
    ("epoch_processing", "*"),
)


def _in_slice(runner: str, handler: str) -> bool:
    return (runner, handler) in SLICE or (runner, "*") in SLICE

# operations handler -> (input .ssz_snappy name, SSZ type attr on the
# compiled spec, sub-transition attr).  Names follow the reference's
# vector format (tests/formats/operations/README.md there).
OP_TABLE = {
    "attestation": ("attestation", "Attestation", "process_attestation"),
    "sync_aggregate": ("sync_aggregate", "SyncAggregate", "process_sync_aggregate"),
    "bls_to_execution_change": (
        "address_change",
        "SignedBLSToExecutionChange",
        "process_bls_to_execution_change",
    ),
    "withdrawals": ("execution_payload", "ExecutionPayload", "process_withdrawals"),
}


def _read_ssz(case_dir: str, name: str) -> bytes | None:
    path = os.path.join(case_dir, f"{name}.ssz_snappy")
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return frame_decompress(f.read())


def _read_meta(case_dir: str) -> dict:
    path = os.path.join(case_dir, "meta.yaml")
    if not os.path.exists(path):
        return {}
    import yaml

    with open(path) as f:
        return yaml.safe_load(f) or {}


def _replay_case(ref, runner: str, case_dir: str, handler: str = "") -> tuple[bool, str]:
    """Replay one emitted case through the compiled reference spec.
    Returns (ok, detail).  Only the REFERENCE SPEC'S execution sits in
    the try: a harness-side failure (yaml, snappy, ssz parse) must be a
    hard failure, never mistaken for the spec rejecting an invalid
    case."""
    pre = _read_ssz(case_dir, "pre")
    if pre is None:
        return False, "missing pre"
    meta = _read_meta(case_dir)
    # honor the vector's bls_setting (reference formats/README.md): 1 =
    # signatures are load-bearing for this case, verify them; otherwise
    # the vectors were emitted without real signatures
    bls.bls_active = int(meta.get("bls_setting", 0)) == 1
    state = ssz.deserialize(ref.BeaconState, pre)
    post = _read_ssz(case_dir, "post")
    if runner == "operations":
        input_name, type_attr, fn_attr = OP_TABLE[handler]
        op_bytes = _read_ssz(case_dir, input_name)
        if op_bytes is None:
            return False, f"missing {input_name}"
        operation = ssz.deserialize(getattr(ref, type_attr), op_bytes)
        sub = getattr(ref, fn_attr)
        steps = [lambda: sub(state, operation)]
    elif runner == "epoch_processing":
        # pre is the state immediately before the named sub-transition
        sub = getattr(ref, f"process_{handler}")
        steps = [lambda: sub(state)]
    elif handler == "slots":  # sanity/slots: advance N empty slots
        target = int(state.slot) + int(meta["slots"])
        steps = [lambda: ref.process_slots(state, target)]
    else:  # blocks format (sanity/blocks, finality, random)
        signed_blocks = []
        for i in range(int(meta.get("blocks_count", 0))):
            blk = _read_ssz(case_dir, f"blocks_{i}")
            if blk is None:
                return False, f"missing blocks_{i}"
            signed_blocks.append(ssz.deserialize(ref.SignedBeaconBlock, blk))
        # the compiled markdown's state_transition mutates in place
        steps = [
            (lambda signed=signed: ref.state_transition(state, signed, True))
            for signed in signed_blocks
        ]
    try:
        for step in steps:
            step()
    except Exception as e:  # the reference spec REJECTED the input
        if post is None:
            return True, "invalid case rejected by reference spec"
        return False, f"reference spec raised on a valid case: {e!r:.120}"
    if post is None:
        return False, "reference spec ACCEPTED an invalid case"
    got = ssz.serialize(state)
    if got != post:
        return False, "post-state bytes differ"
    return True, "byte-identical post"


def _literal_tree_diff(out_ours: str) -> dict | None:
    """MODE B availability probe.  The literal diff itself is a MANUAL
    step on a machine with the reference venv (see module docstring);
    this only reports whether that environment exists."""
    try:
        import eth2spec  # noqa: F401
    except ImportError:
        return None
    return {
        "note": (
            "eth2spec importable — MANUAL step: run the reference generator "
            f"for the slice and `diff -r` its tree against {out_ours}"
        )
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--output", default=None, help="vector output dir (default: temp)")
    args = ap.parse_args()
    out = args.output or tempfile.mkdtemp(prefix="bytediff_vectors_")
    if os.path.isdir(out) and os.listdir(out):
        # stale case dirs from a previous run would be replayed and
        # counted — the gate must only ever see this run's emission
        raise SystemExit(f"output dir {out!r} is not empty; remove it first")

    # signatures: generation and replay must agree on the bls switch; the
    # compiled reference spec shares this framework's kill-switch
    bls.bls_active = False

    cases = [
        c
        for c in discover_test_cases(presets=("minimal",), forks=FORKS)
        if _in_slice(c.runner, c.handler)
    ]
    print(f"[bytediff] generating {len(cases)} cases -> {out}", file=sys.stderr)
    stats = run_generator(cases, out)
    gen_failed = int(stats.get("failed", 0))
    if gen_failed:
        # a case that failed to GENERATE must fail the gate — the replay
        # loop only walks directories that exist
        print(f"[bytediff] {gen_failed} cases failed to generate", file=sys.stderr)

    refs = {fork: compile_fork(fork, "minimal") for fork in FORKS}
    total = ok = 0
    failures: list[str] = []
    for fork in FORKS:
        fork_dir = os.path.join(out, "minimal", fork)
        if not os.path.isdir(fork_dir):
            continue
        emitted = [
            (runner, handler)
            for runner in sorted(os.listdir(fork_dir))
            for handler in sorted(os.listdir(os.path.join(fork_dir, runner)))
        ]
        for runner, handler in emitted:
            if not _in_slice(runner, handler):
                raise SystemExit(
                    f"emitted {runner}/{handler} is outside the declared slice"
                )
            base = os.path.join(fork_dir, runner, handler)
            for suite in sorted(os.listdir(base)):
                for case_name in sorted(os.listdir(os.path.join(base, suite))):
                    case_dir = os.path.join(base, suite, case_name)
                    total += 1
                    good, detail = _replay_case(refs[fork], runner, case_dir, handler)
                    if good:
                        ok += 1
                    else:
                        failures.append(f"{fork}/{runner}/{handler}/{case_name}: {detail}")

    literal = _literal_tree_diff(out)
    summary = {
        "mode": "consumer-side replay through the specc-compiled reference markdown",
        "slice": [f"{r}/{h}" for r, h in SLICE],
        "forks": list(FORKS),
        "preset": "minimal",
        "cases": total,
        "byte_identical": ok,
        "generation_failures": gen_failed,
        "failures": failures[:20],
        "literal_tree_diff": literal
        or "unavailable here: eth2spec and its deps are not installed and the "
        "environment forbids installs; MODE B is a manual step where they exist "
        "(see script docstring)",
    }
    print(json.dumps(summary, indent=1))
    return 0 if ok == total and total > 0 and gen_failed == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
