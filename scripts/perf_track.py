"""Perf-trajectory tracker: every BENCH_r*.json round on one timeline.

The bench history already burned this repo once: four rounds published
a physically impossible 878 Ghash/s because nothing compared runs to
each other. This script ingests every ``BENCH_r*.json`` (plus
``BENCH_LKG.json``), normalizes each round to (round, platform,
metrics), and emits:

  * ``PERF_TRAJECTORY.json`` — the machine-readable trajectory: one
    entry per round, the same-platform regression verdicts, and the
    last-known-good accelerator reference;
  * a markdown table (``PERF_TRAJECTORY.md`` + stdout) for humans.

**Platform awareness is the whole point.** A run that fell back to
XLA:CPU must compare against *cpu history only* — never against
``last_known_good`` TPU numbers or a TPU round, otherwise every
fallback run reads as a million-x regression (and a lucky TPU run
after a cpu round reads as a million-x win). Platform is taken from
``parsed.platform`` when present, inferred from the CPU-fallback error
marker otherwise, and defaults to the accelerator.

Regression policy: the PRIMARY metric gates (exit 1) when it drops
more than ``--threshold`` (default 30 %) against the most recent prior
round **of the same platform**; metrics whose name ends in ``_ms``/
``_s``/``_bytes`` compare in the lower-is-better direction. Secondary
metrics produce *advisories* in the JSON (and gate only under
``--strict``): they are measured with less care (single rep, shared
warmup) and a hard gate on them would make the tracker cry wolf.
Quarantined LKG sections (BENCH_LKG's round-5 revision) are reported
but never compared against.

The resident section reports BOTH merkleization paths since the
incremental forest landed: ``resident_epoch_plus_root_ms`` is the
incremental path (the headline the quarantined LKG ``resident``
section must RE-EARN on a verified accelerator run — quarantined
entries are reported, never compared, so the old acked-before-executed
numbers cannot grandfather themselves back in), with
``resident_epoch_plus_root_full_ms`` and ``incremental_root_speedup``
riding along as same-platform secondaries — a crossover regression
(speedup collapsing toward 1x) surfaces as an advisory on the same
timeline.

Rounds that carry an ``xprof`` section (bench.py runs with ambient XLA
attribution on — obs/xprof.py) also contribute per-kernel
``xprof_<kernel>_compile_ms`` and ``xprof_<kernel>_peak_bytes`` as
secondary metrics: a compile-time or executable-memory blow-up between
rounds surfaces as an advisory on the same same-platform timeline as
the throughput numbers. Like every secondary they gate only under
``--strict`` — and compile walls are noisy run-to-run, so expect
``--strict`` to flag them. Older rounds simply lack the section and
are skipped by the per-(platform, metric) comparison key.

CI runs this in the ``perf-track`` step (checks.yml) and fails only on
a same-platform primary regression.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CPU_MARKERS = ("cpu fallback", "xla:cpu", "cpu-fallback")


def _lower_is_better(metric: str) -> bool:
    # speedup FACTORS (e.g. incremental_root_speedup, mesh scaling) are
    # higher-is-better regardless of any suffix a later rename gives them
    if metric.endswith("_speedup") or "_speedup_" in metric:
        return False
    # RATES are higher-is-better even though "_per_s" textually ends in
    # "_s" — without this carve-out the agg bench's primary
    # (attestations_agg_per_s) would gate in the WRONG direction and a
    # throughput improvement would read as a wall-time regression
    if metric.endswith(("_per_s", "_rps")):
        return False
    # the SLO burn-rate fraction (obs/slo.py burn_rate) carries no unit
    # suffix but regresses UPWARD: more windows out of budget is worse
    if metric.endswith("_burn_rate"):
        return True
    # telemetry-plane counts (canary parity failures, anomaly detector
    # fires) also regress UPWARD: any increase is worse
    if metric.endswith(("_failures", "_fires")):
        return True
    return metric.endswith(("_ms", "_s", "_bytes"))


def infer_platform(parsed: dict) -> str:
    plat = str(parsed.get("platform", "")).lower()
    if plat:
        return "cpu" if "cpu" in plat else plat
    err = str(parsed.get("error", "")).lower()
    if any(m in err for m in _CPU_MARKERS):
        return "cpu"
    return "tpu"


def load_rounds(repo_dir: str) -> list[dict]:
    entries = []
    for path in sorted(glob.glob(os.path.join(repo_dir, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m is None:
            continue
        try:
            raw = json.load(open(path))
        except (OSError, json.JSONDecodeError) as exc:
            entries.append({"round": int(m.group(1)), "file": os.path.basename(path),
                            "status": "unreadable", "error": str(exc)})
            continue
        parsed = raw.get("parsed")
        entry = {
            "round": int(m.group(1)),
            "file": os.path.basename(path),
            "rc": raw.get("rc"),
        }
        if not parsed or not isinstance(parsed, dict) or "value" not in parsed:
            entry["status"] = "no-data"  # e.g. r01: backend died before measuring
            entries.append(entry)
            continue
        metrics = {parsed["metric"]: parsed["value"]}
        for name, value in (parsed.get("secondary") or {}).items():
            if isinstance(value, (int, float)):
                metrics[name] = value
        # XLA-derived attribution (per-kernel compile_ms / peak_bytes,
        # obs/xprof.py): secondary metrics — never a round's primary, so
        # by default they land in the advisory list (gating only under
        # --strict, like every secondary)
        for name, value in (parsed.get("xprof") or {}).items():
            if isinstance(value, (int, float)):
                metrics[f"xprof_{name}"] = value
        # mesh-sharded dispatch (serve_bench --chips): the per-chip
        # scaling factors ride the same platform-keyed timeline — a cpu
        # virtual-mesh factor never compares against an accelerator's —
        # and, as secondaries, regress to advisories, not gates
        for name, value in (parsed.get("mesh") or {}).items():
            if isinstance(value, (int, float)) and (
                name.endswith("_scaling") or name == "chip_scaling"
            ):
                metrics[f"mesh_{name}"] = value
        # aggregation pipeline (scripts/agg_bench.py): the committee-tree
        # throughput numbers (higher-is-better ``*_per_s`` rates plus the
        # best slot wall) ride the same platform-keyed timeline as
        # secondaries — a cpu smoke never compares against an
        # accelerator slot, and regressions are advisories unless the
        # round's PRIMARY is the agg metric itself
        for name, value in (parsed.get("agg") or {}).items():
            if isinstance(value, (int, float)):
                metrics[f"agg_{name}" if not name.startswith("agg_") else name] = value
        # DAS/KZG blob verification (scripts/das_bench.py): blob
        # throughput rates (higher-is-better ``*_per_s`` plus the best
        # flush wall) ride the same platform-keyed timeline as
        # secondaries — the bench only EMITS them on a parity-coupled
        # run, and the LKG re-earn rule below refuses anything else.
        # Bools (the correctness_coupled flag) are not metrics.
        for name, value in (parsed.get("das") or {}).items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                metrics[f"das_{name}" if not name.startswith("das_") else name] = value
        # two-tier fleet matrix (serve_bench --replicas R --chips-matrix):
        # per-cell rps and per-effective-chip scaling factors, platform-
        # keyed like the mesh factors — secondaries, so regressions are
        # advisories (a cpu cell never gates an accelerator round)
        for name, value in (parsed.get("fleet") or {}).items():
            if isinstance(value, (int, float)) and (
                name.endswith("_scaling") or name.endswith("_rps")
            ):
                metrics[f"fleet_{name}"] = value
        # whole-slot pipeline (scripts/slot_bench.py): the slot-machine
        # headline (higher-is-better ``slots_per_s``) plus the per-phase
        # p50/p99 walls (``verify``/``aggregate``/``reroot``) on the same
        # platform-keyed timeline. The bench REFUSES to emit
        # ``slots_per_s`` on a parity failure, so every ingested rate is
        # correctness-coupled by construction; the re-earn rule below
        # holds any LKG ``slot`` section to the same standard. Bools
        # (the coupling flag) are not metrics.
        for name, value in (parsed.get("slot") or {}).items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                metrics[f"slot_{name}" if not name.startswith("slot_") else name] = value
        # request waterfall (serve_bench's waterfall section,
        # obs/waterfall.py): per-stage p50/p99 milliseconds ride the same
        # platform-keyed timeline as secondaries — a stage-attribution
        # blow-up (queue p99 doubling, device p99 creeping) surfaces as
        # an advisory without crying wolf on every noisy CI box. Only the
        # flat ``*_ms`` keys are metrics; the nested device/hbm dicts and
        # coverage ratios are report structure, not timeline points.
        # SLO burn-rate advisory (obs/slo.py burn_rate, recorded by the
        # bench epilogue): the fraction of supervision windows spent out
        # of the wait-p99 budget. A run-wide p99 that still passes can
        # hide a long stretch of breaching windows — the burn rate is
        # the secondary that surfaces it. Never a primary, never gates
        # outside --strict; the raw window counts are context, not
        # timeline points.
        for name, value in (parsed.get("slo") or {}).items():
            if name == "burn_rate" and isinstance(value, (int, float)) \
                    and not isinstance(value, bool):
                metrics["slo_burn_rate"] = value
        # continuous-telemetry advisories (the plane the benches run:
        # obs/canary.py + obs/anomaly.py). Canary pass rate is
        # higher-is-better; parity failures and per-detector fire
        # counts regress upward (_lower_is_better suffix rule). The
        # benches already hard-gate parity == 0 and zero clean-run
        # fires, so these timeline points exist to surface slow erosion
        # — a detector that starts firing once per round — not to gate.
        tele = parsed.get("telemetry") or {}
        can = tele.get("canary") or {}
        if isinstance(can.get("pass_rate"), (int, float)) \
                and not isinstance(can.get("pass_rate"), bool):
            metrics["canary_pass_rate"] = can["pass_rate"]
        if isinstance(can.get("parity_failures"), int) \
                and not isinstance(can.get("parity_failures"), bool):
            metrics["canary_parity_failures"] = can["parity_failures"]
        for det, n in ((tele.get("anomaly") or {}).get("fires") or {}).items():
            if isinstance(n, (int, float)) and not isinstance(n, bool):
                metrics[f"anomaly_{det}_fires"] = n
        for name, value in (parsed.get("waterfall") or {}).items():
            if (
                isinstance(value, (int, float))
                and not isinstance(value, bool)
                and name.endswith("_ms")
            ):
                metrics[f"stage_{name}"] = value
        entry.update(
            status="ok",
            platform=infer_platform(parsed),
            primary=parsed["metric"],
            metrics=metrics,
            method=parsed.get("method"),
        )
        entries.append(entry)
    return entries


def load_lkg(repo_dir: str) -> dict:
    path = os.path.join(repo_dir, "BENCH_LKG.json")
    try:
        raw = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return {"present": False}
    return {
        "present": True,
        "sections": raw.get("sections") or {},
        "quarantined": sorted((raw.get("quarantined") or {}).get("sections", {})),
    }


# sections the round-5 quarantine burned: their numbers were recorded
# without correctness-coupled timing and may NEVER grandfather back in —
# a fresh entry must come from a run that proved device/host parity
_REEARN_ONLY = ("das", "tree", "epoch", "resident", "slot")


def reearn_violations(lkg: dict) -> list[str]:
    """The re-earn-never-grandfather rule (test-pinned): a usable LKG
    section that shares a name with a quarantined entry — or with any
    once-quarantined section — is only legitimate when its run declared
    its device/host coupling: ``correctness_coupled: true``
    (scripts/das_bench.py's flag) or ``verified: true`` (bench.py's
    ``_store_lkg`` form — the literal ``True``, not the "same-backend"
    CPU-lane string). Both emitters set their flag ONLY on runs whose
    device result matched a host recompute; copying the quarantined
    numbers into ``sections`` without one fails the tracker."""
    out = []
    quarantined = set(lkg.get("quarantined") or ())
    for name, section in (lkg.get("sections") or {}).items():
        if name not in quarantined and name not in _REEARN_ONLY:
            continue
        coupled = isinstance(section, dict) and (
            section.get("correctness_coupled") is True
            or section.get("verified") is True
        )
        if not coupled:
            out.append(name)
    return sorted(out)


def compare(entries: list[dict], threshold: float, strict: bool) -> tuple[list, list]:
    """Same-platform metric comparisons. Returns (regressions,
    advisories): regressions gate, advisories inform. Each record:
    {round, vs_round, platform, metric, prev, value, change}."""
    regressions, advisories = [], []
    # last seen value per (platform, metric) — a cpu round can never be
    # compared against a tpu round by construction of this key
    last: dict[tuple, tuple] = {}
    for e in entries:
        if e.get("status") != "ok":
            continue
        for metric, value in e["metrics"].items():
            key = (e["platform"], metric)
            prev = last.get(key)
            last[key] = (e["round"], value)
            if prev is None or not value or not prev[1]:
                continue
            prev_round, prev_value = prev
            if _lower_is_better(metric):
                change = value / prev_value - 1.0  # positive = slower
                regressed = change > threshold
            else:
                change = 1.0 - value / prev_value  # positive = slower
                regressed = change > threshold
            if not regressed:
                continue
            rec = {
                "round": e["round"],
                "vs_round": prev_round,
                "platform": e["platform"],
                "metric": metric,
                "prev": prev_value,
                "value": value,
                "change_pct": round(change * 100.0, 1),
                "gates": strict or metric == e["primary"],
            }
            (regressions if rec["gates"] else advisories).append(rec)
    return regressions, advisories


def _fmt_val(v: float) -> str:
    if v is None:
        return "—"
    if abs(v) >= 1e6:
        return f"{v:.3g}"
    return f"{v:g}"


def markdown_table(entries: list[dict], regressions: list, advisories: list,
                   lkg: dict) -> str:
    lines = [
        "# Perf trajectory",
        "",
        "| round | platform | primary metric | value | status |",
        "|---|---|---|---|---|",
    ]
    flagged = {(r["round"], r["metric"]) for r in regressions}
    for e in entries:
        if e.get("status") != "ok":
            lines.append(
                f"| r{e['round']:02d} | — | — | — | {e.get('status')} (rc={e.get('rc')}) |"
            )
            continue
        mark = "**REGRESSED**" if (e["round"], e["primary"]) in flagged else "ok"
        lines.append(
            f"| r{e['round']:02d} | {e['platform']} | {e['primary']} "
            f"| {_fmt_val(e['metrics'][e['primary']])} | {mark} |"
        )
    if lkg.get("present"):
        usable = sorted(lkg.get("sections", {}))
        lines += [
            "",
            f"Last-known-good accelerator sections: {usable or 'none'} "
            f"(quarantined: {lkg.get('quarantined') or 'none'}). "
            "LKG numbers are an accelerator reference only — cpu-fallback "
            "rounds are never compared against them.",
        ]
    if regressions:
        lines += ["", "## Same-platform regressions", ""]
        for r in regressions:
            lines.append(
                f"- r{r['round']:02d} vs r{r['vs_round']:02d} [{r['platform']}] "
                f"{r['metric']}: {_fmt_val(r['prev'])} → {_fmt_val(r['value'])} "
                f"({r['change_pct']:+.1f}% slower)"
            )
    if advisories:
        lines += ["", "## Advisories (secondary metrics, non-gating)", ""]
        for r in advisories:
            lines.append(
                f"- r{r['round']:02d} vs r{r['vs_round']:02d} [{r['platform']}] "
                f"{r['metric']}: {_fmt_val(r['prev'])} → {_fmt_val(r['value'])} "
                f"({r['change_pct']:+.1f}% slower)"
            )
    return "\n".join(lines) + "\n"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo-dir", default=REPO)
    ap.add_argument("--threshold", type=float, default=0.30,
                    help="same-platform fractional drop that flags a regression")
    ap.add_argument("--strict", action="store_true",
                    help="secondary-metric regressions gate too")
    ap.add_argument("--out", default=None, help="default <repo>/PERF_TRAJECTORY.json")
    ap.add_argument("--md", default=None, help="default <repo>/PERF_TRAJECTORY.md")
    args = ap.parse_args()

    entries = load_rounds(args.repo_dir)
    if not entries:
        print("no BENCH_r*.json found", file=sys.stderr)
        raise SystemExit(2)
    lkg = load_lkg(args.repo_dir)
    grandfathered = reearn_violations(lkg)
    if grandfathered:
        print(
            "FAILED: quarantined LKG section(s) re-entered without a "
            f"correctness-coupled run (re-earn, never grandfather): {grandfathered}",
            file=sys.stderr,
        )
        raise SystemExit(1)

    # TPU rounds may also be checked against the (non-quarantined) LKG
    # sections by seeding the comparison history with a pseudo-round 0
    seeded = []
    if lkg.get("present") and lkg.get("sections"):
        metrics = {}
        for section in lkg["sections"].values():
            for k, v in section.items():
                if isinstance(v, (int, float)):
                    metrics[k] = v
        if metrics:
            seeded.append({
                "round": 0, "file": "BENCH_LKG.json", "status": "ok",
                "platform": "tpu", "primary": next(iter(metrics)),
                "metrics": metrics,
            })
    regressions, advisories = compare(seeded + entries, args.threshold, args.strict)
    regressions = [r for r in regressions if r["round"] != 0]

    out = args.out or os.path.join(args.repo_dir, "PERF_TRAJECTORY.json")
    md_path = args.md or os.path.join(args.repo_dir, "PERF_TRAJECTORY.md")
    trajectory = {
        "threshold": args.threshold,
        "strict": args.strict,
        "rounds": entries,
        "regressions": regressions,
        "advisories": advisories,
        "last_known_good": lkg,
    }
    with open(out, "w") as fh:
        json.dump(trajectory, fh, indent=1, sort_keys=True)
        fh.write("\n")
    md = markdown_table(entries, regressions, advisories, lkg)
    with open(md_path, "w") as fh:
        fh.write(md)
    print(md)
    print(f"wrote {out} and {md_path}", file=sys.stderr)
    if regressions:
        print("FAILED: same-platform regression(s):", file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
