#!/usr/bin/env python
"""Generate docs/env-reference.md from the env registry (envreg.py).

The registry is the single source of truth for every ``ETH_SPECS_*``
knob (the ``env-registry`` speclint rule enforces declared == read);
this script renders it into the one docs table the subsystem pages link
to. Modes:

    python scripts/gen_env_docs.py           # rewrite docs/env-reference.md
    python scripts/gen_env_docs.py --check   # exit 1 if committed != generated

CI's ``static-analysis`` job runs ``--check`` so the committed table
literally cannot drift from the code.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from eth_consensus_specs_tpu import envreg  # noqa: E402

OUT = os.path.join(REPO_ROOT, "docs", "env-reference.md")

HEADER = """\
# Environment variable reference

<!-- GENERATED FILE — do not edit. Regenerate with:
         python scripts/gen_env_docs.py
     Source of truth: eth_consensus_specs_tpu/envreg.py (the env
     registry; the `env-registry` speclint rule keeps it in lockstep
     with every os.environ read). CI diffs this file against a fresh
     generation. -->

Every `ETH_SPECS_*` knob in one table, generated from the
[env registry](analysis.md#env-registry). The *details* column links to
the subsystem page whose prose explains the knob in context.

"""


def render() -> str:
    return HEADER + envreg.markdown_table()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="diff generated vs committed; exit 1 on drift")
    args = ap.parse_args()
    text = render()
    if args.check:
        try:
            with open(OUT, encoding="utf-8") as fh:
                committed = fh.read()
        except OSError:
            committed = ""
        if committed != text:
            sys.stderr.write(
                "docs/env-reference.md is stale — run "
                "`python scripts/gen_env_docs.py` and commit the result\n"
            )
            return 1
        print(f"docs/env-reference.md up to date ({len(envreg.ENV_VARS)} vars)")
        return 0
    with open(OUT, "w", encoding="utf-8") as fh:
        fh.write(text)
    print(f"wrote {OUT} ({len(envreg.ENV_VARS)} vars)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
