#!/usr/bin/env python
"""speclint CLI — run the project-native static analysis and gate on it.

Usage:
    python scripts/speclint.py                 # lint the package, human output
    python scripts/speclint.py --json r.json   # machine-readable report
    python scripts/speclint.py --update-baseline
    python scripts/speclint.py --rules lock-order,fork-safety path/to/file.py

Exit codes: 0 clean (every finding baselined), 1 usage/ratchet error,
2 non-baselined findings. CI's ``static-analysis`` job runs ``--json``
over the tree and fails on exit != 0; ``make lint`` chains it after
ruff. The baseline (speclint_baseline.json) may only shrink — see
analysis/lint.py's module docs for the ratchet contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from eth_consensus_specs_tpu.analysis import lint  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="files/dirs to lint (default: the package)")
    ap.add_argument("--json", dest="json_out", help="write a JSON report here")
    ap.add_argument("--rules", help="comma-separated rule subset (default: all)")
    ap.add_argument(
        "--baseline",
        default=os.path.join(REPO_ROOT, "speclint_baseline.json"),
        help="baseline path (default: speclint_baseline.json at the repo root)",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from current findings (ratchet: a rule's "
             "count may only decrease; --force overrides for bootstrap)",
    )
    ap.add_argument("--force", action="store_true", help="override the ratchet")
    args = ap.parse_args()

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(lint.ALL_RULES)
        if unknown:
            print(f"unknown rules: {sorted(unknown)} (have {lint.ALL_RULES})")
            return 1

    paths = [os.path.abspath(p) for p in args.paths] or None
    findings = lint.run(REPO_ROOT, paths=paths, rules=rules,
                        project_checks=paths is None)

    if args.update_baseline:
        try:
            payload = lint.write_baseline(args.baseline, findings, force=args.force)
        except ValueError as exc:
            print(f"REFUSED: {exc}")
            return 1
        print(f"baseline updated: {len(payload['findings'])} fingerprints")
        return 0

    baseline = lint.load_baseline(args.baseline)
    diff = lint.baseline_diff(findings, baseline)
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1

    report = {
        "findings": [f.to_dict() for f in findings],
        "counts_by_rule": dict(sorted(by_rule.items())),
        "total": len(findings),
        "baselined": len(findings) - len(diff["new"]),
        "new": [f.to_dict() for f in diff["new"]],
        "stale_baseline_entries": diff["stale"],
    }
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")

    for f in diff["new"]:
        print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
    if diff["stale"]:
        print(
            f"note: {len(diff['stale'])} stale baseline entr"
            f"{'y' if len(diff['stale']) == 1 else 'ies'} (fixed findings) — "
            "run --update-baseline to ratchet them out"
        )
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items())) or "clean"
    print(
        f"speclint: {len(findings)} finding(s) ({summary}); "
        f"{len(diff['new'])} non-baselined"
    )
    return 2 if diff["new"] else 0


if __name__ == "__main__":
    sys.exit(main())
