#!/usr/bin/env python
"""speclint CLI — run the project-native static analysis and gate on it.

Usage:
    python scripts/speclint.py                 # lint the package, human output
    python scripts/speclint.py --json r.json   # machine-readable report
    python scripts/speclint.py --write-baseline
    python scripts/speclint.py --rules lock-order,fork-safety path/to/file.py

Exit codes: 0 clean (every finding baselined), 1 usage/ratchet error,
2 non-baselined findings. CI's ``static-analysis`` job runs ``--json``
over the tree and fails on exit != 0; ``make lint`` chains it after
ruff. The baseline (speclint_baseline.json) may only shrink — see
analysis/lint.py's module docs for the ratchet contract. The flag set
and exit protocol are shared with the trace-level tool
(scripts/jaxlint.py) through analysis/cli.py, so the two CLIs cannot
drift.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from eth_consensus_specs_tpu.analysis import cli, lint  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="files/dirs to lint (default: the package)")
    cli.add_common_args(
        ap,
        default_baseline=os.path.join(REPO_ROOT, "speclint_baseline.json"),
        all_rules=lint.ALL_RULES,
    )
    args = ap.parse_args()

    try:
        rules = cli.parse_rules(args, lint.ALL_RULES)
    except ValueError as exc:
        print(exc)
        return 1

    paths = [os.path.abspath(p) for p in args.paths] or None
    findings = lint.run(REPO_ROOT, paths=paths, rules=rules,
                        project_checks=paths is None)
    return cli.finish(args, findings, tool="speclint")


if __name__ == "__main__":
    sys.exit(main())
