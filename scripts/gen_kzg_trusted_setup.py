#!/usr/bin/env python
"""Regenerate the INSECURE testing trusted setup (reference analogue:
scripts/gen_kzg_trusted_setups.py).

The output file documents its own provenance: its first JSON key is the
``provenance`` string from ``crypto/kzg_setup.PROVENANCE`` stating that
tau is derived from a public tag (the trapdoor discrete log is public —
anyone can forge proofs), so a copied artifact still announces it is
test-only. ``tests/test_kzg_ceremony_setup.py`` round-trips the
generated setup: a known blob must verify against BOTH the host oracle
(``crypto/kzg.py``) and the device path (``ops/kzg_batch.py``).

Usage: python scripts/gen_kzg_trusted_setup.py [--g1 4096]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--g1", type=int, default=4096, help="G1 monomial/lagrange count")
    args = parser.parse_args()

    from eth_consensus_specs_tpu.crypto import kzg_setup

    path = kzg_setup.write_setup(n=args.g1)
    print(f"trusted setup written to {path}")
    print(f"provenance (embedded in the file): {kzg_setup.PROVENANCE}")


if __name__ == "__main__":
    main()
