#!/usr/bin/env python
"""Regenerate the INSECURE testing trusted setup (reference analogue:
scripts/gen_kzg_trusted_setups.py).

Usage: python scripts/gen_kzg_trusted_setup.py [--g1 4096]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--g1", type=int, default=4096, help="G1 monomial/lagrange count")
    args = parser.parse_args()

    from eth_consensus_specs_tpu.crypto import kzg_setup

    print(f"trusted setup written to {kzg_setup.write_setup(n=args.g1)}")


if __name__ == "__main__":
    main()
