"""Resident-loop incremental-merkleization bench — the resident-smoke gate.

Measures `parallel/resident.run_epochs` with the FULL per-epoch state
recompute (``with_root="state"``) against the incremental merkle_inc
forest (``with_root="state_inc"``) on the same synthetic registry, and
gates the contract the incremental path ships under:

  1. **bit parity** — the incremental xor-chain ``root_acc`` equals the
     full recompute's on every timed repeat (same salted columns);
  2. **mesh parity** — with ``--chips N`` the forest's leaf axes shard
     over the (dp, sp) mesh and the sharded ``root_acc`` must equal the
     single-device one bit for bit;
  3. **zero cold compiles after warmup** — every runner/forest shape is
     compiled in the warmup phase (``serve.compiles`` via the resident
     first_dispatch keys); a timed dispatch that compiles fails the run;
  4. **speedup** — incremental beats the full recompute by at least
     ``--speedup-min`` (``ETH_SPECS_INC_SPEEDUP_MIN``; interleaved
     best-of-N so host-load noise hits both paths alike).

The report JSON lands in ``--out`` (plus a validated Prometheus
textfile next to it) and carries a ``resident`` section shaped like the
bench driver's, so perf_track-style tooling can ingest either. CI runs
``--smoke --chips 8`` under forced 8-virtual-device XLA (the
resident-smoke job in checks.yml) and uploads both artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prejax import force_virtual_chips  # noqa: E402

force_virtual_chips()

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from eth_consensus_specs_tpu import obs  # noqa: E402
from eth_consensus_specs_tpu.obs import export, flight  # noqa: E402


def _root_bytes(acc) -> bytes:
    return np.asarray(acc).astype(">u4", order="C").view(np.uint8).tobytes()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="small CI run")
    ap.add_argument("--n", type=int, default=1 << 16, help="validator count")
    ap.add_argument("--epochs", type=int, default=2, help="chained epochs per run")
    ap.add_argument("--reps", type=int, default=3, help="timed repeats (best-of)")
    ap.add_argument("--chips", type=int,
                    default=int(os.environ.get("ETH_SPECS_SERVE_CHIPS", "0") or 0),
                    help="also run the mesh-sharded forest on N chips")
    ap.add_argument("--speedup-min", type=float,
                    default=float(os.environ.get("ETH_SPECS_INC_SPEEDUP_MIN", "2.0")
                                  or 2.0),
                    help="minimum incremental-vs-full speedup factor")
    ap.add_argument("--out", default="BENCH_RESIDENT.json")
    args = ap.parse_args()
    if args.smoke:
        args.n = min(args.n, 1 << 12)
        args.reps = min(args.reps, 3)

    import __graft_entry__ as graft
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_root import synthetic_static
    from eth_consensus_specs_tpu.parallel import resident
    from eth_consensus_specs_tpu.parallel.mesh_ops import mesh_signature, serve_mesh

    export.maybe_serve_http()
    n, epochs = args.n, args.epochs
    spec = get_spec("deneb", "mainnet")
    cols, just = graft._example_altair_inputs(n)
    cols = jax.device_put(cols)
    just = jax.device_put(just)
    static = synthetic_static(spec, n)
    plan1 = resident.forest_plan_for(static)
    mesh = serve_mesh(args.chips) if args.chips > 1 else None
    planN = resident.forest_plan_for(static, mesh=mesh) if mesh is not None else None

    failures: list[str] = []

    def run_full(c):
        return resident.run_epochs(spec, c, just, epochs, with_root="state",
                                   static=static)

    def run_inc(c, m=None):
        forest, _ = resident.build_state_forest_device(static, c, mesh=m)
        jax.block_until_ready(forest)  # ingest is setup, not timed work
        t0 = time.perf_counter()
        carry = resident.run_epochs(spec, c, just, epochs, with_root="state_inc",
                                    static=static, forest=forest, mesh=m)
        jax.block_until_ready(carry.root_acc)
        return carry, time.perf_counter() - t0

    # --- warmup: every executable compiles here, none in the timed phase
    warm_full = run_full(cols)
    jax.block_until_ready(warm_full.root_acc)
    warm_inc, _ = run_inc(cols)
    if _root_bytes(warm_inc.root_acc) != _root_bytes(warm_full.root_acc):
        failures.append("warmup: incremental root_acc != full recompute root_acc")
    mesh_section = {"chips": args.chips, "shards": 0, "signature": ""}
    if mesh is not None:
        warm_mesh, _ = run_inc(cols, mesh)
        mesh_section = {
            "chips": args.chips,
            "shards": planN.shards,
            "signature": mesh_signature(mesh),
            "parity": _root_bytes(warm_mesh.root_acc) == _root_bytes(warm_inc.root_acc),
        }
        if planN.shards <= 1:
            failures.append(
                f"--chips {args.chips} requested but the forest plan fell back "
                f"to 1 shard (devices: {len(jax.local_devices())})"
            )
        if not mesh_section["parity"]:
            failures.append(
                f"mesh parity: {planN.shards}-shard incremental root_acc != "
                "single-device root_acc"
            )
    compiles_after_warmup = obs.snapshot()["counters"].get("serve.compiles", 0)

    # --- timed phase: interleaved best-of-N, fresh salted columns ---------
    salt_fn = jax.jit(lambda c, s: c._replace(balance=c.balance + s))
    best_full = best_inc = best_mesh = float("inf")
    for i in range(args.reps):
        fresh = salt_fn(cols, jnp.uint64(i + 1))
        jax.block_until_ready(fresh)
        t0 = time.perf_counter()
        full = run_full(fresh)
        jax.block_until_ready(full.root_acc)
        best_full = min(best_full, time.perf_counter() - t0)
        inc, t_inc = run_inc(fresh)
        best_inc = min(best_inc, t_inc)
        if _root_bytes(inc.root_acc) != _root_bytes(full.root_acc):
            failures.append(f"rep {i}: incremental root_acc != full root_acc")
        if mesh is not None:
            incN, t_incN = run_inc(fresh, mesh)
            if _root_bytes(incN.root_acc) != _root_bytes(inc.root_acc):
                failures.append(f"rep {i}: mesh root_acc != single-device root_acc")
            # same best-of-N discipline as the single-device timings —
            # a last-rep host-load spike must not be the reported number
            best_mesh = min(best_mesh, t_incN)
            mesh_section["inc_ms_per_epoch"] = round(best_mesh / epochs * 1e3, 2)

    speedup = best_full / best_inc if best_inc else 0.0
    if speedup < args.speedup_min:
        failures.append(
            f"incremental speedup {speedup:.2f}x < gate {args.speedup_min}x "
            f"(full {best_full/epochs*1e3:.1f} ms/epoch vs "
            f"inc {best_inc/epochs*1e3:.1f} ms/epoch)"
        )

    # --- zero cold compiles after warmup ---------------------------------
    snap = obs.snapshot()
    extra = snap["counters"].get("serve.compiles", 0) - compiles_after_warmup
    if extra > 0:
        failures.append(
            f"{extra} compiles AFTER warmup (a resident shape escaped the "
            "warmup phase's first dispatches)"
        )
    obs.count("serve.compiles_after_warmup", max(extra, 0))
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")

    snap = obs.snapshot()
    report = {
        "mode": "resident-smoke" if args.smoke else "resident",
        "n": n,
        "epochs": epochs,
        "reps": args.reps,
        "platform": jax.default_backend(),
        "resident": {
            "epoch_plus_root_full_ms": round(best_full / epochs * 1e3, 3),
            "epoch_plus_root_ms": round(best_inc / epochs * 1e3, 3),
            "incremental_root_speedup": round(speedup, 2),
        },
        "plan": plan1._asdict(),
        "mesh": mesh_section,
        "speedup_min": args.speedup_min,
        "compiles": snap["counters"].get("serve.compiles", 0),
        "compiles_after_warmup": max(extra, 0),
        "inc_roots": snap["counters"].get("state_root.inc_roots", 0),
        "watchdog": snap["watchdog"],
        "failures": failures,
    }
    prom_path = os.environ.get("ETH_SPECS_OBS_PROM") or (
        os.path.splitext(args.out)[0] + ".prom"
    )
    export.write_textfile(prom_path, snap=snap)
    try:
        export.validate_text(open(prom_path).read())
    except ValueError as exc:
        failures.append(f"prometheus exposition invalid: {exc}")
    report["prometheus_textfile"] = prom_path
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report, sort_keys=True))
    if failures:
        flight.trigger_dump("resident_bench.failure", detail="; ".join(failures)[:300])
        print("FAILED:", *failures, sep="\n  ", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
