"""Blob-scale DAS/KZG verification bench — the workload that re-earns
the quarantined ``das`` LKG section honestly.

Direct mode: synthesize ``--blobs`` full-size (4096-field-element)
blobs from sparse monomial polynomials — commitment and proof are then
K-lane MSMs over the monomial setup points instead of 4096-lane ones,
which is what makes blob-scale registry construction take seconds
instead of hours, while the VERIFIER still does the full 4096-point
work on every item — inject ``--invalid`` tampered proofs, and run the
device pipeline end to end: ONE batched inverse FFT (challenge
evaluation), ONE RLC-combined multi-MSM, one pairing check, with the
injected invalid blobs isolated via RLC bisection.

Gates (direct mode) — a run that fails ANY of them REFUSES to report
throughput at all:

  * per-item verdicts bit-identical to the ``crypto/kzg.py`` host
    oracle (clean blobs True, tampered blobs False, the bisection
    isolating exactly the injected set), and the device batch verdict
    equal to ``verify_blob_kzg_proof_batch`` on the clean subset;
  * zero cold compiles after the warmup pass (the warm flush pays
    every fr_fft / kzg bucket compile; timed reps hit the jit cache);
  * mesh parity (``--chips N``): the sharded flush's verdicts — and
    the isolated invalid set — bit-identical to the chips=1 dispatch;
  * zero watchdog divergences (the sampled host recompute agreed).

Primary metric: **blobs verified per second** (``das.blobs_per_s``;
``ffts_per_s`` rides along — one 4096-point inverse FFT row per blob).
The report's ``das`` section carries ``correctness_coupled: true``
exactly when the parity gates passed — scripts/perf_track.py refuses
to let a das LKG section replace the quarantined entry without it
(re-earn, never grandfather).

Replicated mode (``--replicas R [--chaos]``, the das-smoke CI job):
every blob rides a ``kzg`` op through the replicated front door.
``--chaos`` SIGKILLs one replica mid-flush AND corrupts two
``frontdoor.rpc`` frames (the deterministic fault grammar); gates:
zero lost requests, verdict parity vs the host oracle on every blob,
``frontdoor.replicas_replaced > 0``, corrupt frames detected (never
silently accepted), and zero cold compiles on every replica —
including the respawned replacement, which warms from the shippable
artifact.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prejax import force_virtual_chips  # noqa: E402

force_virtual_chips()

from serve_bench import (  # noqa: E402
    _LOST,
    closed_loop,
    finish_report,
    wait_replicas_surveyed,
)

from eth_consensus_specs_tpu import obs  # noqa: E402
from eth_consensus_specs_tpu.crypto import kzg  # noqa: E402
from eth_consensus_specs_tpu.obs import export  # noqa: E402
from eth_consensus_specs_tpu.serve import buckets as serve_buckets  # noqa: E402
from eth_consensus_specs_tpu.serve.config import ServeConfig  # noqa: E402


def build_blobs(n: int, degree: int, invalid: int) -> tuple[list, set]:
    """n (blob, commitment, proof) triples (the shared sparse-monomial
    construction — test_infra/blob.py); ``invalid`` evenly spread items
    get a tampered (still on-curve, still subgroup) proof. Returns
    (items, expected_invalid_indices)."""
    from eth_consensus_specs_tpu.test_infra.blob import sparse_blob_triple

    bad = {(i * n) // invalid for i in range(invalid)} if invalid else set()
    return [
        sparse_blob_triple(i, degree=degree, tamper=i in bad) for i in range(n)
    ], bad


def run_direct(args) -> None:
    import jax

    from eth_consensus_specs_tpu.ops import kzg_batch
    from eth_consensus_specs_tpu.parallel import mesh_ops

    export.maybe_serve_http()
    platform = jax.local_devices()[0].platform
    mesh = mesh_ops.serve_mesh(args.chips) if args.chips > 1 else None
    failures: list = []

    t0 = time.time()
    items, expected_bad = build_blobs(args.blobs, args.degree, args.invalid)
    build_s = time.time() - t0
    obs.gauge("das.blobs", len(items))
    want = [i not in expected_bad for i in range(len(items))]
    clean = [it for i, it in enumerate(items) if i not in expected_bad]

    # host-oracle truth per item (pure crypto/kzg.py path — untimed)
    t0 = time.time()
    host = [kzg_batch.verify_blob_host(*it) for it in items]
    # zip(*[]) expands to zero args: an all-invalid run still exercises
    # the empty-batch contract (True by spec) instead of crashing
    host_batch = kzg.verify_blob_kzg_proof_batch(
        *(map(list, zip(*clean)) if clean else ([], [], []))
    )
    host_s = time.time() - t0
    if host != want:
        failures.append("host oracle disagrees with the injected-invalid plan "
                        "(bench construction broken)")
    if not host_batch:
        failures.append("host batch verifier rejected the clean subset")

    # warmup: pays every fr_fft / kzg bucket compile (and records the
    # shippable keys via ETH_SPECS_SERVE_WARMUP / --warmup-out). The
    # chips=1 parity recompute runs INSIDE the warmup window too — its
    # unsharded kernel compiles are warmup cost, not escaped shapes.
    t0 = time.time()
    obs.count("das.flushes", 1)
    warm = kzg_batch.verify_many_blobs(items, mesh=mesh)
    warm_batch = kzg_batch.verify_blob_kzg_proof_batch_device(
        *(map(list, zip(*clean)) if clean else ([], [], [])), mesh=mesh
    )
    single = kzg_batch.verify_many_blobs(items, mesh=None) if mesh is not None else None
    warmup_s = time.time() - t0
    compiles_after_warmup = obs.snapshot()["counters"].get("serve.compiles", 0)

    parity = warm == host and warm_batch == host_batch
    if warm != host:
        failures.append("PARITY FAILED: device verdicts != host oracle "
                        "(throughput withheld)")
    if warm_batch != host_batch:
        failures.append("PARITY FAILED: device batch verdict != host batch "
                        "(throughput withheld)")
    isolated = {i for i, v in enumerate(warm) if not v}
    if isolated != expected_bad:
        failures.append(
            f"bisection isolated {sorted(isolated)} != injected {sorted(expected_bad)}"
        )

    # timed reps: the all-valid flush (ONE FFT + ONE MSM + one pairing),
    # best-of-N against the jit cache
    best = None
    for _ in range(args.reps):
        obs.count("das.flushes", 1)
        t0 = time.perf_counter()
        verdicts = kzg_batch.verify_many_blobs(clean, mesh=mesh)
        wall = time.perf_counter() - t0
        best = wall if best is None else min(best, wall)
        if verdicts != [True] * len(clean):
            failures.append("timed-rep verdicts diverged on the clean flush")
            parity = False

    # mesh parity: the chips=1-vs-N gate (recomputed during warmup)
    mesh_section = None
    if mesh is not None:
        if single != warm:
            failures.append("mesh parity FAILED: chips=1 verdicts != sharded")
            parity = False
        mesh_section = {
            "chips": args.chips,
            "shards": mesh_ops.shard_count(mesh),
            "signature": mesh_ops.mesh_signature(mesh),
            "parity": single == warm,
        }

    snap = obs.snapshot()
    counters = snap["counters"]
    extra = counters.get("serve.compiles", 0) - compiles_after_warmup
    if extra > 0:
        failures.append(f"{extra} compiles AFTER the warmup flush "
                        "(a shape escaped the kzg/fr_fft buckets)")
    obs.count("serve.compiles_after_warmup", max(extra, 0))
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")

    das_metrics = {}
    if parity and not failures:
        das_metrics = {
            "blobs_per_s": round(len(clean) / best, 2),
            "ffts_per_s": round(len(clean) / best, 2),
            "flush_wall_s": round(best, 4),
            "correctness_coupled": True,
        }
    report = {
        "mode": "das-smoke" if args.smoke else "das",
        "platform": platform,
        "blobs": len(items),
        "degree": args.degree,
        "invalid_injected": len(expected_bad),
        "registry_build_s": round(build_s, 2),
        "host_oracle_s": round(host_s, 2),
        "warmup_s": round(warmup_s, 2),
        "parity": parity,
        "das": das_metrics,
        "mesh": mesh_section,
        "compiles": counters.get("serve.compiles", 0),
        "compiles_after_warmup": max(extra, 0),
    }
    if args.warmup_out:
        report["warmup_artifact"] = args.warmup_out
        report["warmup_keys"] = serve_buckets.write_warmup(args.warmup_out)
    snap = obs.snapshot()
    finish_report(report, failures, args.out, "das_bench.failure", snap)


def run_replicated(args) -> None:
    """The --replicas path: every blob as a ``kzg`` op through a
    supervised replica fleet, optionally with a deterministic mid-flush
    SIGKILL plus wire corruption."""
    from eth_consensus_specs_tpu.serve.config import FrontDoorConfig
    from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor

    export.maybe_serve_http()
    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
    if not pm_dir:
        pm_dir = os.path.join(out_dir, "postmortems")
        os.environ["ETH_SPECS_OBS_POSTMORTEM_DIR"] = pm_dir
    warmup_path = args.warmup_out or os.path.join(out_dir, "das_warmup.jsonl")

    items, expected_bad = build_blobs(args.blobs, args.degree, args.invalid)
    obs.gauge("das.blobs", len(items))
    from eth_consensus_specs_tpu.ops.kzg_batch import verify_blob_host

    # host-oracle truth per blob — the parent never touches the device,
    # so "zero cold compiles on every replica" stays honest
    direct = [verify_blob_host(*it) for it in items]

    # ONE flush shape: max_batch=1 makes every kzg flush a single item
    # (the budget here is chaos/parity/cold-compile gates, not
    # batching, which direct mode covers) — so the warm keys are the
    # singleton fr_fft batch + the singleton RLC lane bucket
    cfg = ServeConfig.from_env(max_batch=1, buckets=(1,))
    warm_keys = [
        ("fr_fft", 1, kzg.FIELD_ELEMENTS_PER_BLOB),
        ("kzg", serve_buckets.kzg_lane_bucket(1)),
    ]
    fault_spec = None
    if args.chaos:
        nth = max(len(items) // 4, 2)
        latch = os.path.join(out_dir, f"das_kill_{os.getpid()}.latch")
        if os.path.exists(latch):
            os.unlink(latch)
        fault_spec = (
            f"frontdoor.rpc:kill:nth={nth}:latch={latch};"
            f"frontdoor.rpc:corrupt:nth=2:times=2"
        )

    fd = FrontDoor(
        replicas=args.replicas,
        config=cfg,
        fd_config=FrontDoorConfig.from_env(ready_timeout_s=900.0),
        warmup_path=warmup_path,
        warm_keys=warm_keys,
        replica_fault_spec=fault_spec,
        name="das-fd",
    )
    load = [("kzg", it) for it in items]
    wall_s, got, _lat = closed_loop(fd, load, args.submitters, result_timeout=600.0)
    # the cold-compile gate must survey EVERY replica — including a
    # chaos respawn whose boot (artifact replay = the kzg + fr_fft
    # compiles) can outlive a small flush on a slow box
    wait_replicas_surveyed(fd)
    replica_stats = fd.replica_stats()
    stats = fd.stats()
    fd.close()

    failures = []
    lost = sum(1 for r in got if r is _LOST)
    if lost:
        failures.append(f"{lost} kzg requests lost (futures never resolved)")
    if got != direct:
        failures.append("KZG parity: replicated verdicts != host-oracle bools")
    snap = obs.snapshot()
    counters = snap["counters"]
    replaced = counters.get("frontdoor.replicas_replaced", 0)
    if args.chaos and replaced < 1:
        failures.append("chaos run but frontdoor.replicas_replaced == 0 "
                        "(the kill never happened or was never healed)")
    if args.chaos and counters.get("frontdoor.corrupt_frames", 0) < 1:
        failures.append("chaos run but zero corrupt frames detected "
                        "(the corruption rule never fired or was silently "
                        "accepted)")
    cold = {
        i: s["compiles_after_ready"]
        for i, s in enumerate(replica_stats)
        if s is not None and s.get("compiles_after_ready")
    }
    if cold:
        failures.append(f"cold compiles after warmup on replicas: {cold}")
    obs.count("serve.compiles_after_warmup", sum(cold.values()))
    surveyed = sum(1 for s in replica_stats if s is not None)
    if surveyed < args.replicas:
        failures.append(
            f"only {surveyed}/{args.replicas} replicas answered a health probe"
        )
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")

    report = {
        "mode": "das-replicated-chaos" if args.chaos else "das-replicated",
        "replicas": args.replicas,
        "submitters": args.submitters,
        "blobs": len(items),
        "invalid_injected": len(expected_bad),
        "das": {
            "blobs_per_s": round(len(items) / wall_s, 2)
            if got == direct else None,
        },
        "lost": lost,
        "replicas_replaced": replaced,
        "failovers": stats["failovers"],
        "hedges": stats["hedges"],
        "corrupt_frames": counters.get("frontdoor.corrupt_frames", 0),
        "replica_stats": replica_stats,
        "warmup_artifact": warmup_path,
        "warmup_keys": len(serve_buckets.load_warmup(warmup_path)),
    }
    snap = obs.snapshot()
    finish_report(report, failures, args.out, "das_bench.replicated_failure", snap)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="small-flush CI run (same hard gates)")
    ap.add_argument("--blobs", type=int, default=64,
                    help="blobs per flush (full 4096-element blobs)")
    ap.add_argument("--degree", type=int, default=8,
                    help="sparse monomial degree of the synthesized blobs "
                    "(construction cost only; the verifier always does the "
                    "full 4096-point work)")
    ap.add_argument("--invalid", type=int, default=2,
                    help="blobs injected with a tampered proof")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions, floored at 1 (the throughput "
                    "metric needs at least one measured flush)")
    ap.add_argument("--submitters", type=int, default=8)
    ap.add_argument("--chips", type=int,
                    default=int(os.environ.get("ETH_SPECS_SERVE_CHIPS", "0") or 0))
    ap.add_argument("--replicas", type=int, default=0,
                    help="run the flush through an R-replica front door")
    ap.add_argument("--chaos", action="store_true",
                    help="with --replicas: SIGKILL one replica mid-flush and "
                    "corrupt frontdoor.rpc frames")
    ap.add_argument("--out", default="BENCH_DAS.json")
    ap.add_argument("--warmup-out", default=None,
                    help="write the shippable warmup artifact here")
    args = ap.parse_args()
    args.reps = max(args.reps, 1)
    if args.smoke:
        args.blobs = min(args.blobs, 8)
        args.invalid = min(args.invalid, 1)
        args.reps = min(args.reps, 2)
        args.submitters = min(args.submitters, 4)
    if args.replicas > 0:
        run_replicated(args)
        return
    run_direct(args)


if __name__ == "__main__":
    main()
