#!/usr/bin/env python
"""rangelint CLI — value-range static analysis of the registered kernels.

Usage:
    python scripts/rangelint.py                  # every family, 8 virtual chips
    python scripts/rangelint.py --chips 1        # single-device variants only
    python scripts/rangelint.py --json r.json    # machine-readable report
    python scripts/rangelint.py --rules lane-overflow,lazy-bound-audit
    python scripts/rangelint.py --only pairing,g1_msm
    python scripts/rangelint.py --write-baseline

Interval abstract interpretation over ``jax.make_jaxpr`` output only —
nothing executes, nothing compiles. Input intervals come from the
domains each registry variant declares; sanctioned wraparound comes from
each family's per-primitive-site ``Wrap`` declarations. ``--chips N``
forces N virtual CPU devices BEFORE jax initializes (the serve_bench
idiom) so the four mesh-sharded variants are analyzable on any dev box.

Exit codes (shared with speclint/jaxlint via analysis/cli.py): 0 clean,
1 usage/ratchet error, 2 non-baselined findings. The baseline
(rangelint_baseline.json) ships EMPTY and may only shrink; CI
additionally asserts lane-overflow findings are NEVER baselined — a
possible silent wraparound is a wrong pairing verdict, not tech debt.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
# the --chips pre-parse must run before the first jax import (XLA reads
# XLA_FLAGS once, at backend init); ONE copy shared with serve_bench.py
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prejax import force_virtual_chips  # noqa: E402


def main() -> int:
    chips = force_virtual_chips(default=8, env_var=None)

    from eth_consensus_specs_tpu.analysis import cli, rangelint

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--chips",
        type=int,
        default=8,
        help="virtual device count for the mesh variants (forced before "
        "jax init on cpu; 1 = single-device variants only; default 8)",
    )
    ap.add_argument(
        "--only", help="comma-separated kernel-family subset (default: all)"
    )
    cli.add_common_args(
        ap,
        default_baseline=os.path.join(REPO_ROOT, "rangelint_baseline.json"),
        all_rules=rangelint.ALL_RULES,
    )
    args = ap.parse_args()

    try:
        rules = cli.parse_rules(args, rangelint.ALL_RULES)
    except ValueError as exc:
        print(exc)
        return 1
    only = (
        {k.strip() for k in args.only.split(",") if k.strip()} if args.only else None
    )
    if only:
        from eth_consensus_specs_tpu.analysis import kernels

        unknown = only - set(kernels.by_name()) - {"lazy_limbs"}
        if unknown:
            # a silently-ignored family would let the mesh-smoke gate
            # pass green while proving nothing — fail loudly
            print(
                f"unknown kernel families: {sorted(unknown)} "
                f"(have {sorted(kernels.by_name())} + lazy_limbs)"
            )
            return 1

    from eth_consensus_specs_tpu.parallel.mesh_ops import mesh_signature, serve_mesh

    mesh = serve_mesh(chips) if chips > 1 else None
    findings, stats = rangelint.analyze(mesh=mesh, rules=rules, only=only)
    stats["mesh"] = mesh_signature(mesh)
    print(
        f"rangelint: {stats['kernels']} kernel families, {stats['variants']} "
        f"variants ({stats['mesh_variants']} mesh @ {stats['mesh'] or 'none'}), "
        f"{stats['eqns']} eqns interpreted, {stats['wrap_hits']} sanctioned "
        f"wrap hits, {stats.get('lf_chains', 0)} LF chains audited"
    )
    return cli.finish(args, findings, tool="rangelint", extra=stats)


if __name__ == "__main__":
    sys.exit(main())
