"""CI recovery smoke for durable resident state (checks.yml `recovery-smoke`).

One resident replica behind the front door is SIGKILLed mid-advance by a
deterministic fault rule (``resident.checkpoint:kill`` — the chaos fires
at the checkpoint commit seam, after the chunk's epochs ran on device
but before a single byte of the commit lands, so the previous LATEST
must survive intact), and the durable-state contract is gated end to
end:

  * **zero lost requests** — the client retries every advance until
    acked; every in-flight RPC across the kill fails DETECTABLY
    (connection error or honest busy), never silently; the world
    converges on exactly the target epoch;
  * **the kill happened AND was healed** — frontdoor.replicas_replaced
    >= 1 and a frontdoor.replica_lost postmortem bundle on disk;
  * **restore-then-replay, not cold start** — the respawned replica's
    lineage verdict is ``restored`` and its final root is BIT-IDENTICAL
    to an uninterrupted in-process control run of the same
    deterministic world (the recovery parity gate of ops/snapshot.py);
  * **recovery is a first-class waterfall stage** —
    ``serve.stage_ms.recovery`` (death -> replacement ready) is
    non-empty in the parent's merged registry and carries the restore
    lineage in its frontdoor.replica_recovered event;
  * **honest busy while restoring** — every overloaded / restoring
    reply observed mid-boot carried ``retry_after_s > 0`` (the measured
    restore wall, never a blackhole);
  * **zero cold compiles after ready** on the replacement — the
    resident prewarm covered the epoch runner, the root gate, and the
    scrub kernel;
  * **a clean post-recovery scrub** — K salted subtrees re-hash against
    the restored parents with zero mismatches.

Exit code 0 on success; prints a one-line JSON summary; dumps a
postmortem bundle (flight recorder) when any gate fails.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def control_root(validators: int, epochs: int) -> bytes:
    """Uninterrupted in-process truth: the SAME deterministic world the
    replica builds (seeded columns + synthetic static), advanced
    ``epochs`` with no checkpoints. Replicas are spawned with fresh
    runtimes, so parent-side work cannot pre-warm them — the replica's
    zero-cold-compile gate stays honest."""
    import jax

    import __graft_entry__ as graft
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_root import synthetic_static
    from eth_consensus_specs_tpu.parallel import resident

    spec = get_spec("altair", "minimal")
    cols, just = graft._example_altair_inputs(validators)
    static = synthetic_static(spec, validators)
    _, root, _ = resident.run_epochs_checkpointed(
        spec, jax.device_put(cols), jax.device_put(just), epochs, static=static
    )
    return root


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--validators", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=6, help="target epoch")
    ap.add_argument("--interval", type=int, default=2, help="checkpoint interval")
    ap.add_argument("--out", default="recovery_smoke.json")
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from eth_consensus_specs_tpu import obs
    from eth_consensus_specs_tpu.obs import flight
    from eth_consensus_specs_tpu.serve.config import ServeConfig
    from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor

    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
    if not pm_dir:
        pm_dir = os.path.join(out_dir, "postmortems")
        os.environ["ETH_SPECS_OBS_POSTMORTEM_DIR"] = pm_dir

    t0 = time.perf_counter()
    ctl = control_root(args.validators, args.epochs)
    control_s = time.perf_counter() - t0

    base = tempfile.mkdtemp(prefix="recovery_smoke_")
    ckpt_dir = os.path.join(base, "ckpt")
    # hit 1 of the site is the boot checkpoint (establishes LATEST);
    # hit 2 is the first advance's commit -> SIGKILL mid-request. The
    # latch arbitrates ACROSS process lives: the respawned replica
    # reinstalls the same rule with fresh counters, but the latch file
    # already exists, so the replacement is never re-killed.
    latch = os.path.join(base, "kill.latch")
    fault_spec = f"resident.checkpoint:kill:nth=2:latch={latch}"
    cfg = ServeConfig.from_env(
        resident_ckpt_dir=ckpt_dir,
        resident_validators=args.validators,
        resident_ckpt_interval=args.interval,
        resident_restore="prefer",
    )

    t0 = time.perf_counter()
    fd = FrontDoor(
        replicas=1, config=cfg, replica_fault_spec=fault_spec, name="recovery-fd"
    )

    target = args.epochs
    issued = acked = detected = busy_seen = 0
    dishonest: list = []
    scrub_rep: dict | None = None
    final: dict | None = None
    deadline = time.monotonic() + args.timeout

    def backoff(reply: dict) -> None:
        nonlocal busy_seen
        busy_seen += 1
        ra = reply.get("retry_after_s")
        if not isinstance(ra, (int, float)) or ra <= 0:
            dishonest.append(reply)
        time.sleep(min(float(ra or 0.5), 2.0))

    while time.monotonic() < deadline:
        try:
            st = fd._rpc_admin(0, {"op": "resident.status"}, 30.0)
        except Exception:  # noqa: BLE001 — dead/respawning slot: detected, retried
            detected += 1
            time.sleep(0.5)
            continue
        if not st.get("ok"):
            time.sleep(0.5)
            continue
        if st.get("restoring"):
            backoff(st)
            continue
        epoch = int(st.get("epoch", 0))
        if epoch >= target:
            final = st
            break
        issued += 1
        try:
            r = fd._rpc_admin(
                0,
                {"op": "resident.epochs", "n": min(args.interval, target - epoch)},
                300.0,
            )
        except Exception:  # noqa: BLE001 — the kill lands HERE: the in-flight
            detected += 1  # advance dies with its replica; retried, never silent
            time.sleep(0.5)
            continue
        if r.get("ok"):
            acked += 1
        elif r.get("err") == "overloaded":
            backoff(r)
        else:
            raise SystemExit(f"unexpected resident.epochs reply: {r}")

    # post-recovery scrub: K salted subtrees vs the restored parents
    if final is not None:
        try:
            scrub_rep = fd._rpc_admin(0, {"op": "resident.scrub"}, 120.0)
        except Exception:  # noqa: BLE001 — gated below as a failure
            scrub_rep = None

    # the replacement's OWN health stats (the supervisor clears the dead
    # predecessor's snapshot on death — never read its numbers)
    surveyed_by = time.monotonic() + 120.0
    while time.monotonic() < surveyed_by:
        stats = fd.replica_stats()
        if stats and stats[0] is not None:
            break
        time.sleep(0.5)
    replica_stats = fd.replica_stats()
    fd.close()
    chaos_s = time.perf_counter() - t0

    snap = obs.snapshot()
    counters = snap["counters"]
    failures: list[str] = []

    if final is None:
        failures.append(
            f"never converged on epoch {target} within {args.timeout}s "
            f"(issued={issued} acked={acked} detected={detected})"
        )
    else:
        if int(final.get("epoch", -1)) != target:
            failures.append(f"converged on wrong epoch: {final.get('epoch')}")
        if final.get("root") != ctl.hex():
            failures.append(
                "restored root differs from uninterrupted control run: "
                f"{final.get('root')} != {ctl.hex()}"
            )
        lineage = final.get("lineage") or {}
        if lineage.get("verdict") != "restored":
            failures.append(
                f"replacement did not restore-then-replay: lineage={lineage}"
            )
        if (lineage.get("epoch_span") or [None, None])[1] != target:
            failures.append(f"LATEST lineage not at target epoch: {lineage}")
    if detected < 1:
        failures.append("no RPC ever failed: the kill never hit an in-flight request")
    replaced = counters.get("frontdoor.replicas_replaced", 0)
    if replaced < 1:
        failures.append("frontdoor.replicas_replaced == 0 (kill never happened "
                        "or was never healed)")
    rec_hist = snap["histograms"].get("serve.stage_ms.recovery", {})
    if not rec_hist.get("count"):
        failures.append("serve.stage_ms.recovery is empty — the recovery stage "
                        "never reached the merged waterfall")
    if dishonest:
        failures.append(
            f"{len(dishonest)} busy replies without honest retry_after_s: "
            f"{dishonest[:3]}"
        )
    if scrub_rep is None or not scrub_rep.get("ok"):
        failures.append(f"post-recovery scrub failed: {scrub_rep}")
    elif scrub_rep.get("mismatches") or not scrub_rep.get("checks"):
        failures.append(f"post-recovery scrub not clean: {scrub_rep}")
    if not replica_stats or replica_stats[0] is None:
        failures.append("replacement never answered a health probe")
    else:
        cold = replica_stats[0].get("compiles_after_ready")
        if cold:
            failures.append(f"{cold} cold compiles after ready on the replacement")
        resident_health = (replica_stats[0].get("resident") or {}).get("lineage") or {}
        if not resident_health.get("manifest"):
            failures.append(
                f"no checkpoint lineage in health: {replica_stats[0]}"
            )
    bundles = []
    if os.path.isdir(pm_dir):
        bundles = [
            os.path.join(pm_dir, n)
            for n in sorted(os.listdir(pm_dir))
            if n.startswith("postmortem-") and "frontdoor-replica-lost" in n
        ]
    if not bundles:
        failures.append(f"no frontdoor.replica_lost postmortem bundle in {pm_dir}")

    report = {
        "ok": not failures,
        "failures": failures,
        "target_epoch": target,
        "validators": args.validators,
        "root": ctl.hex(),
        "advances": {"issued": issued, "acked": acked, "detected_failures": detected,
                     "busy_replies": busy_seen},
        "replicas_replaced": replaced,
        "recovery_ms": rec_hist,
        "lineage": (final or {}).get("lineage"),
        "scrub": scrub_rep,
        "postmortem_bundles": bundles,
        "control_s": round(control_s, 3),
        "chaos_s": round(chaos_s, 3),
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps({k: report[k] for k in
                      ("ok", "advances", "replicas_replaced", "lineage")}))
    if failures:
        flight.trigger_dump(
            "recovery_smoke.gate", detail="; ".join(failures)[:500],
            extra={"failures": failures, "report": report},
        )
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
