"""One-screen fleet scoreboard: render the continuous-telemetry plane.

Reads the scoreboard JSON a running FrontDoor writes atomically when
``ETH_SPECS_OBS_SCOREBOARD`` is set (``--watch`` tails it live, top(1)
style), or digs the embedded ``telemetry.scoreboard`` section out of a
bench report JSON (serve_bench/slot_bench ``--out`` files) for a
post-hoc snapshot — the CI artifact path.

The screen, top to bottom:

  * header — fleet name, snapshot age, SLO burn rate over the last
    minute, admission queue depth vs the effective cap;
  * canary line — known-answer pass rate plus sent/ok/parity/error
    counts (a parity failure renders as PAGE: the fleet returned wrong
    bits for a request with a precomputed host-oracle answer);
  * one row per replica — alive/restarting/dead glyph, probe health,
    router EWMA latency and pick share;
  * sparklines — requests/sec, wait p99, per-stage p99s, canary pass
    rate over the series ring's last 48 telemetry windows;
  * active anomalies — detector fires within the last minute, with
    replica/stage attribution and the exemplar bundle path when one
    was captured.

Plain ASCII + the eight-step block glyphs; no curses, no deps — CI
logs and terminals render it identically.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BLOCKS = "▁▂▃▄▅▆▇█"


def spark(values, width: int = 48) -> str:
    """Eight-level unicode sparkline of the last ``width`` values."""
    vals = [v for v in values if v is not None][-width:]
    if not vals:
        return "(no data)"
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return BLOCKS[0] * len(vals)
    return "".join(
        BLOCKS[min(int((v - lo) / (hi - lo) * (len(BLOCKS) - 1)), len(BLOCKS) - 1)]
        for v in vals
    )


def _fmt(v, suffix: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.2f}{suffix}"
    return f"{v}{suffix}"


def load_scoreboard(path: str) -> dict:
    """A scoreboard file, or a bench report carrying one inside its
    telemetry section."""
    with open(path) as f:
        doc = json.load(f)
    if "replicas" in doc and "unix_time" in doc:
        return doc
    tele = doc.get("telemetry") or {}
    board = tele.get("scoreboard")
    if board is None and (tele.get("canary") or tele.get("anomaly")):
        # in-process bench reports carry canary/anomaly but no fleet
        # scoreboard — synthesize a board so the snapshot still renders
        anom = tele.get("anomaly") or {}
        board = {
            "name": doc.get("mode", "bench"),
            "replicas": [],
            "canary": tele.get("canary"),
            "anomalies": anom.get("fired", []),
            "anomaly_fires": anom.get("fires", {}),
        }
    if board is None:
        raise SystemExit(
            f"{path}: neither a scoreboard file nor a bench report with a "
            "telemetry section"
        )
    return board


def render(board: dict) -> str:
    lines = []
    burn = board.get("burn") or {}
    head = f"== {board.get('name', 'fleet')}"
    if board.get("unix_time"):
        head += f" | snapshot {time.time() - board['unix_time']:.1f}s ago"
    if burn:
        head += (f" | burn {_fmt(burn.get('burn_rate'))}"
                 f" over {_fmt(burn.get('window_s'), 's')}")
    if "queue_depth" in board:
        head += (f" | queue {board['queue_depth']}"
                 f"/{board.get('effective_max_queue', '-')}")
    lines.append(head)

    can = board.get("canary")
    if can:
        rate = can.get("pass_rate")
        flag = " PAGE: parity failure" if can.get("parity_failures") else ""
        lines.append(
            f"canary  pass {_fmt(rate)}  sent {can.get('sent', 0)} "
            f"ok {can.get('ok', 0)} parity {can.get('parity_failures', 0)} "
            f"err {can.get('errors', 0)}  shapes {','.join(can.get('shapes', []))}"
            f"{flag}"
        )

    for rep in board.get("replicas", []):
        glyph = ("~" if rep.get("restarting")
                 else "*" if rep.get("alive") else "X")
        router = rep.get("router") or {}
        health = rep.get("health")
        if isinstance(health, dict):
            health = (f"q{health.get('queue_depth', '-')}"
                      f" c{health.get('compiles', '-')}"
                      f"+{health.get('compiles_after_ready', '-')}")
        lines.append(
            f"  [{glyph}] replica {rep.get('replica')}  "
            f"health {_fmt(health)}  "
            f"ewma {_fmt(router.get('ewma_ms'), 'ms')}  "
            f"picks {router.get('picks', 0)}  "
            f"failures {router.get('failures', 0)}"
        )

    series = board.get("series")
    if series:
        lines.append(f"-- series (last {len(series.get('rps', []))} windows, "
                     f"{board.get('span_s', 0)}s span)")
        lines.append(f"  rps        {spark(series.get('rps', []))}")
        lines.append(f"  wait p99   {spark(series.get('wait_p99_ms', []))}")
        for st, vals in (series.get("stage_p99_ms") or {}).items():
            if any(v is not None for v in vals):
                lines.append(f"  {st:<10} {spark(vals)}")
        cpr = series.get("canary_pass_rate")
        if cpr:
            lines.append(f"  canary     {spark(cpr)}")

    active = board.get("anomalies") or []
    fires = board.get("anomaly_fires") or {}
    if active:
        lines.append("-- ACTIVE ANOMALIES")
        for a in active:
            where = []
            if a.get("replica") is not None:
                where.append(f"replica {a['replica']}")
            if a.get("stage"):
                where.append(f"stage {a['stage']}")
            loc = f" [{', '.join(where)}]" if where else ""
            lines.append(f"  ! {a.get('detector')}{loc}: {a.get('detail')}")
            if a.get("bundle"):
                lines.append(f"      exemplar: {a['bundle']}")
    elif fires:
        lines.append(f"-- past anomaly fires: {fires}")
    else:
        lines.append("-- no anomalies")
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="scoreboard JSON (ETH_SPECS_OBS_SCOREBOARD "
                                 "file) or a bench report with a telemetry "
                                 "section")
    ap.add_argument("--watch", action="store_true",
                    help="re-render on every file change, top(1) style")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="watch poll interval in seconds")
    args = ap.parse_args()

    if not args.watch:
        print(render(load_scoreboard(args.path)))
        return
    last_mtime = 0.0
    try:
        while True:
            try:
                mtime = os.path.getmtime(args.path)
            except OSError:
                time.sleep(args.interval)
                continue
            if mtime != last_mtime:
                last_mtime = mtime
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                print(render(load_scoreboard(args.path)), flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
