"""Postmortem bundle inspector: pretty-print and diff flight-recorder
dumps (obs/flight.py).

A bundle is one JSON file written into ``ETH_SPECS_OBS_POSTMORTEM_DIR``
when a trigger fired (watchdog divergence, fault.degrade fallback, SLO
breach, lost gen worker, pytest failure, explicit API). This CLI is the
reading side:

    python scripts/postmortem.py                      # latest bundle in
                                                      # $ETH_SPECS_OBS_POSTMORTEM_DIR
                                                      # (./postmortems fallback)
    python scripts/postmortem.py --dir DIR            # latest bundle in DIR
    python scripts/postmortem.py BUNDLE.json          # that bundle
    python scripts/postmortem.py A.json B.json        # diff two bundles
    python scripts/postmortem.py --json [BUNDLE]      # re-emit canonical JSON
                                                      # (round-trip safe)
    python scripts/postmortem.py --list [--dir DIR]   # inventory, newest first

``make postmortem`` is the one-keystroke form of the first invocation.

Exit codes: 0 on success, 2 when no bundle is found / unreadable —
scripting-friendly (CI can probe "did anything dump?" cheaply).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

_DEFAULT_DIR = "postmortems"
_RING_TAIL = 25


def bundle_dir(explicit: str | None = None) -> str:
    return (
        explicit
        or os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
        or _DEFAULT_DIR
    )


def list_bundles(dir_path: str) -> list[str]:
    """Bundle paths in ``dir_path``, newest first (mtime, then name)."""
    paths = glob.glob(os.path.join(dir_path, "postmortem-*.json"))
    return sorted(paths, key=lambda p: (os.path.getmtime(p), p), reverse=True)


def latest_bundle(dir_path: str) -> str | None:
    paths = list_bundles(dir_path)
    return paths[0] if paths else None


def load_bundle(path: str) -> dict:
    """Load + sanity-check one bundle; raises ValueError on alien JSON."""
    with open(path) as fh:
        bundle = json.load(fh)
    if not isinstance(bundle, dict) or bundle.get("bundle") != "eth-specs-postmortem":
        raise ValueError(f"{path}: not an eth-specs postmortem bundle")
    return bundle


def _fmt_time(unix: float | None) -> str:
    if not unix:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime(unix))


def _fmt_event(e: dict) -> str:
    head = f"  #{e.get('seq', '?'):>5}  {e.get('kind', '?')}"
    bits = []
    for k in ("name", "site", "kernel", "reason", "op", "case", "trigger"):
        if k in e:
            bits.append(f"{k}={e[k]}")
    if "s" in e:
        bits.append(f"{e['s'] * 1e3:.3f}ms")
    if "n" in e:
        bits.append(f"n={e['n']}")
    if e.get("trace_id"):
        bits.append(f"trace={e['trace_id'][:8]}…/{e.get('span_id', '')[:8]}")
    if "thread" in e:
        bits.append(f"[{e['thread']}]")
    return head + ("  " + " ".join(bits) if bits else "")


def _top_counters(counters: dict, n: int = 12) -> list[tuple[str, float]]:
    return sorted(counters.items(), key=lambda kv: -abs(kv[1]))[:n]


_STAGE_PREFIX = "serve.stage_ms."


def _stage_rows(reg: dict) -> list[tuple[str, dict]]:
    """(stage, histogram) rows from a bundle registry, waterfall order:
    the named stages as obs/waterfall.py emits them, then other/total."""
    hists = reg.get("histograms", {})
    rows = {
        name[len(_STAGE_PREFIX):]: h
        for name, h in hists.items()
        if name.startswith(_STAGE_PREFIX)
    }
    order = ["admit", "queue", "prep", "handoff", "dispatch_wait",
             "device", "resolve", "wire", "recovery", "other", "total"]
    ordered = [(s, rows.pop(s)) for s in order if s in rows]
    return ordered + sorted(rows.items())


def _waterfall_lines(bundle: dict) -> list[str]:
    """The waterfall view: per-stage p50/p99 table plus the HBM ledger's
    resident/high-water marks — empty if the bundle predates either."""
    reg = bundle.get("registry", {})
    lines = []
    rows = _stage_rows(reg)
    if rows:
        lines.append("  waterfall (serve.stage_ms):")
        lines.append(f"    {'stage':<14} {'count':>7} {'p50_ms':>10} {'p99_ms':>10}")
        for stage, h in rows:
            p50, p99 = h.get("p50"), h.get("p99")
            lines.append(
                f"    {stage:<14} {h.get('count', 0):>7} "
                f"{p50 if p50 is None else f'{p50:.3f}':>10} "
                f"{p99 if p99 is None else f'{p99:.3f}':>10}"
            )
    dev = sorted(
        (name[len("device.exec_ms."):], h)
        for name, h in reg.get("histograms", {}).items()
        if name.startswith("device.exec_ms.")
    )
    if dev:
        lines.append("  device time (device.exec_ms):")
        for kern, h in dev:
            p50 = h.get("p50")
            lines.append(
                f"    {kern:<14} {h.get('count', 0):>7} runs, "
                f"p50 {p50 if p50 is None else f'{p50:.3f}'} ms"
            )
    hbm = bundle.get("hbm")
    if hbm:
        lines.append(
            f"  hbm ledger: resident {hbm.get('resident_total_bytes', 0):,} B, "
            f"high water {hbm.get('high_water_bytes', 0):,} B"
        )
        for owner, nbytes in sorted((hbm.get("owners") or {}).items()):
            lines.append(f"    {owner:<24} {nbytes:>14,} B")
        for ent in hbm.get("top_entries", []) or []:
            lines.append(
                f"      {ent.get('owner')}/{ent.get('name')}: {ent.get('bytes', 0):,} B"
            )
    return lines


def summarize(bundle: dict, path: str | None = None, ring_tail: int = _RING_TAIL) -> str:
    """Human-readable one-screen account of a bundle."""
    plat = bundle.get("platform", {})
    reg = bundle.get("registry", {})
    counters = reg.get("counters", {})
    ring = bundle.get("ring", [])
    lines = [
        f"postmortem bundle{f' {path}' if path else ''}",
        f"  trigger : {bundle.get('trigger')}"
        + (f" ({bundle['detail']})" if bundle.get("detail") else ""),
        f"  time    : {_fmt_time(bundle.get('unix_time'))}   pid {bundle.get('pid')}",
        f"  platform: {plat.get('system')}/{plat.get('machine')} "
        f"python {plat.get('python')} jax {plat.get('jax_version', '—')} "
        f"backend {plat.get('jax_backend', '—')}",
        f"  argv    : {' '.join(bundle.get('argv', []))[:120]}",
    ]
    wd = reg.get("watchdog", {})
    if wd:
        lines.append(
            f"  watchdog: {wd.get('checks', 0)} checks, "
            f"{wd.get('divergences', 0)} divergences"
        )
    ckpt = bundle.get("checkpoint")
    if ckpt:
        bits = [f"verdict {ckpt.get('verdict')}"]
        if ckpt.get("manifest"):
            bits.insert(0, f"manifest {str(ckpt['manifest'])[:16]}")
        span = ckpt.get("epoch_span")
        if span:
            bits.append(f"epochs {span[0]}..{span[1]}")
        if ckpt.get("restore_ms") is not None:
            bits.append(f"restore {ckpt['restore_ms']:.0f} ms")
        lines.append("  checkpoint lineage: " + ", ".join(bits))
    if counters:
        lines.append("  top counters:")
        for name, val in _top_counters(counters):
            lines.append(f"    {name:<44} {val:g}")
    lines += _waterfall_lines(bundle)
    extra = bundle.get("extra")
    if extra:
        worker_ring = extra.get("worker_ring")
        shown = {k: v for k, v in extra.items() if k != "worker_ring"}
        if shown:
            lines.append(f"  extra   : {json.dumps(shown, sort_keys=True, default=str)[:300]}")
        if worker_ring is not None:
            lines.append(f"  dead worker's ring (last {min(len(worker_ring), ring_tail)} "
                         f"of {len(worker_ring)}):")
            lines += [_fmt_event(e) for e in worker_ring[-ring_tail:]]
    lines.append(f"  flight ring (last {min(len(ring), ring_tail)} of {len(ring)}):")
    lines += [_fmt_event(e) for e in ring[-ring_tail:]]
    return "\n".join(lines)


def diff_bundles(a: dict, b: dict, a_name: str = "A", b_name: str = "B") -> str:
    """What changed between two bundles: counter deltas, env drift, and
    each side's ring tail beyond the common prefix (same-process bundles
    share seq numbering; cross-process rings just print both tails)."""
    lines = [f"postmortem diff: {a_name} ({a.get('trigger')} @ "
             f"{_fmt_time(a.get('unix_time'))}) → {b_name} "
             f"({b.get('trigger')} @ {_fmt_time(b.get('unix_time'))})"]
    ca = a.get("registry", {}).get("counters", {})
    cb = b.get("registry", {}).get("counters", {})
    deltas = []
    for name in sorted(set(ca) | set(cb)):
        d = cb.get(name, 0) - ca.get(name, 0)
        if d:
            deltas.append((name, ca.get(name, 0), cb.get(name, 0), d))
    if deltas:
        lines.append("  counter deltas:")
        for name, va, vb, d in sorted(deltas, key=lambda r: -abs(r[3]))[:30]:
            lines.append(f"    {name:<44} {va:g} → {vb:g} ({'+' if d > 0 else ''}{d:g})")
    else:
        lines.append("  counters: identical")
    ha = a.get("registry", {}).get("histograms", {})
    hb = b.get("registry", {}).get("histograms", {})
    stage_deltas = []
    for name in sorted(set(ha) | set(hb)):
        if not name.startswith(_STAGE_PREFIX):
            continue
        pa = (ha.get(name) or {}).get("p99")
        pb = (hb.get(name) or {}).get("p99")
        if pa != pb:
            stage_deltas.append((name[len(_STAGE_PREFIX):], pa, pb))
    if stage_deltas:
        lines.append("  stage p99 deltas (serve.stage_ms):")
        for stage, pa, pb in stage_deltas:
            fa = "—" if pa is None else f"{pa:.3f}"
            fb = "—" if pb is None else f"{pb:.3f}"
            d = "" if pa is None or pb is None else f" ({pb - pa:+.3f})"
            lines.append(f"    {stage:<14} {fa} → {fb} ms{d}")
    wa = (a.get("hbm") or {}).get("high_water_bytes")
    wb = (b.get("hbm") or {}).get("high_water_bytes")
    if wa != wb:
        lines.append(f"  hbm high water: {wa} → {wb} bytes")
    ka, kb = a.get("checkpoint") or {}, b.get("checkpoint") or {}
    if ka != kb:
        lines.append("  checkpoint lineage:")
        for key in sorted(set(ka) | set(kb)):
            va, vb = ka.get(key), kb.get(key)
            if va != vb:
                lines.append(f"    {key:<12} {va} → {vb}")
    ea, eb = a.get("env", {}), b.get("env", {})
    env_drift = {
        k: (ea.get(k), eb.get(k))
        for k in sorted(set(ea) | set(eb))
        if ea.get(k) != eb.get(k)
    }
    if env_drift:
        lines.append("  env drift:")
        for k, (va, vb) in env_drift.items():
            lines.append(f"    {k}: {va!r} → {vb!r}")
    seqs_a = {e.get("seq") for e in a.get("ring", [])}
    new_in_b = [e for e in b.get("ring", []) if e.get("seq") not in seqs_a]
    same_pid = a.get("pid") == b.get("pid")
    if same_pid and new_in_b:
        lines.append(f"  ring events only in {b_name} (last {min(len(new_in_b), _RING_TAIL)}):")
        lines += [_fmt_event(e) for e in new_in_b[-_RING_TAIL:]]
    elif not same_pid:
        lines.append("  rings are from different processes; tails:")
        for name, bundle in ((a_name, a), (b_name, b)):
            lines.append(f"  {name}:")
            lines += [_fmt_event(e) for e in bundle.get("ring", [])[-5:]]
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("bundles", nargs="*",
                    help="0 paths: latest in --dir; 1: print it; 2: diff them")
    ap.add_argument("--dir", default=None,
                    help="bundle directory (default $ETH_SPECS_OBS_POSTMORTEM_DIR "
                         f"or ./{_DEFAULT_DIR})")
    ap.add_argument("--list", action="store_true", help="inventory, newest first")
    ap.add_argument("--json", action="store_true",
                    help="re-emit the bundle as canonical JSON instead of prose")
    ap.add_argument("--ring", type=int, default=_RING_TAIL,
                    help=f"ring tail length to print (default {_RING_TAIL})")
    args = ap.parse_args(argv)

    d = bundle_dir(args.dir)
    if args.list:
        paths = list_bundles(d)
        if not paths:
            print(f"no bundles under {d}", file=sys.stderr)
            return 2
        for p in paths:
            try:
                b = load_bundle(p)
                print(f"{p}  {b.get('trigger'):<24} {_fmt_time(b.get('unix_time'))} "
                      f"pid={b.get('pid')}")
            except (ValueError, OSError, json.JSONDecodeError) as exc:
                print(f"{p}  UNREADABLE ({exc})")
        return 0

    if len(args.bundles) == 2:
        try:
            a, b = (load_bundle(p) for p in args.bundles)
        except (ValueError, OSError, json.JSONDecodeError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        print(diff_bundles(a, b, *(os.path.basename(p) for p in args.bundles)))
        return 0

    path = args.bundles[0] if args.bundles else latest_bundle(d)
    if path is None:
        print(f"no bundles under {d}", file=sys.stderr)
        return 2
    try:
        bundle = load_bundle(path)
    except (ValueError, OSError, json.JSONDecodeError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.json:
        # canonical re-emission: json.loads(output) == the bundle on disk
        print(json.dumps(bundle, indent=1, sort_keys=True))
    else:
        print(summarize(bundle, path=path, ring_tail=args.ring))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
