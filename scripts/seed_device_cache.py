"""Warm the device crypto kernels into the persistent compile cache.

Run this ONCE on a machine with a live accelerator (it is a no-op on
XLA:CPU — the cache helper refuses cpu backends).  After it completes,
the compiled pairing and hash-to-G2 chains sit in `.jax_cache` with warm
sentinels next to them, and `bench.py`'s hybrid BLS section will use the
device stages instead of falling back to host-native.

    python scripts/seed_device_cache.py           # both stages
    python scripts/seed_device_cache.py pairing   # just the Miller chain
    python scripts/seed_device_cache.py h2c       # just hash-to-G2

The first compile of each chain is expensive (minutes — it is exactly
the cost this script exists to pay once); subsequent processes load from
the cache in seconds.

NOTE: backend init blocks while the accelerator tunnel is unreachable —
run under `timeout(1)` if the tunnel's health is unknown (the bench
itself never calls this; its subprocess budgets make it unstrandable).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    stages = sys.argv[1:] or ["pairing", "h2c"]
    unknown = set(stages) - {"pairing", "h2c"}
    if unknown:
        print(f"unknown stage(s): {sorted(unknown)} — valid: pairing, h2c")
        return 2
    from eth_consensus_specs_tpu.utils.cache import enable_persistent_cache

    cache = enable_persistent_cache()
    if cache is None:
        print("no accelerator backend (or init failed) — nothing to seed")
        return 1
    print(f"persistent cache: {cache}")

    if "pairing" in stages:
        from eth_consensus_specs_tpu.crypto.curve import g1_generator, g2_generator
        from eth_consensus_specs_tpu.ops.pairing_device import pairing_check_device

        g1, g2 = g1_generator(), g2_generator()
        pairs = [(g1.mul(6), g2), (g1.mul(2).mul(3), -g2)]
        t0 = time.perf_counter()
        ok = pairing_check_device(pairs)
        print(f"pairing chain: ok={ok} in {time.perf_counter() - t0:.1f}s")
        if not ok:
            return 1

    if "h2c" in stages:
        from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2
        from eth_consensus_specs_tpu.ops.h2c_device import hash_to_g2_device

        msgs = [b"seed-0", b"seed-1"]
        t0 = time.perf_counter()
        got = hash_to_g2_device(msgs)
        assert all(g == hash_to_g2(m) for g, m in zip(got, msgs))
        print(f"h2c chain: bit-exact in {time.perf_counter() - t0:.1f}s")

    return 0


if __name__ == "__main__":
    raise SystemExit(main())
