#!/usr/bin/env python
"""Report the implemented spec surface: forks, features, per-fork method
counts, and test-function counts (reference analogue: the docs indices
scripts/gen_spec_indices.py builds).

Usage: python scripts/spec_coverage.py
"""

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def main() -> None:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from eth_consensus_specs_tpu.forks import available_forks, get_spec
    from eth_consensus_specs_tpu.forks.features import available_features, get_feature_spec

    print(f"{'fork':<12} {'spec methods':>12} {'containers':>11}")
    for fork in available_forks():
        spec = get_spec(fork, "minimal")
        methods = [n for n in dir(spec) if callable(getattr(spec, n)) and not n.startswith("_")]
        containers = [
            n for n in vars(spec) if isinstance(getattr(spec, n), type)
        ]
        print(f"{fork:<12} {len(methods):>12} {len(containers):>11}")
    for feat in available_features():
        spec = get_feature_spec(feat, "minimal")
        methods = [n for n in dir(spec) if callable(getattr(spec, n)) and not n.startswith("_")]
        print(f"{feat:<12} {len(methods):>12}")

    n_tests = 0
    for path in (ROOT / "tests").rglob("test_*.py"):
        n_tests += sum(
            1 for line in path.read_text().splitlines() if line.startswith("def test_")
        )
    print(f"\ntest functions: {n_tests}")


if __name__ == "__main__":
    main()
