"""Closed-loop load generator for the serve/ verification service.

Measures requests/sec of the batched async service against sequential
per-request ops calls on the SAME payloads, with bit-exact result
parity enforced, and writes a JSON report (default BENCH_SERVE.json)
including a request-latency histogram.

Phases:

  1. direct sequential baseline (one thread, per-request ops calls);
  2. service warmup: ``precompile()`` every (batch-bucket, depth) shape,
     snapshot the ``serve.compiles`` counter;
  3. trickle: one submitter, spaced submits — must produce a DEADLINE
     flush (low-load latency bound);
  4. load: N closed-loop submitters (each waits for its future before
     submitting the next) — must produce a SIZE flush and the headline
     throughput;
  5. gates: zero watchdog divergences, zero compiles after warmup
     (so total compiles <= len(buckets) per depth), serve.compile_ms
     histogram count == serve.compiles (every first dispatch left its
     compile wall time; p50/p99 land in the report), declarative SLOs
     (obs/slo.py: wait p99 bound, degraded rate, divergences,
     compiles-after-warmup) evaluated from the registry snapshot, and —
     full mode — batched BLS throughput >= 2x sequential.

Run-level wait p50/p99 come from the mergeable ``serve.wait_ms``
log-bucket histogram (every wait of the run — no reservoir
truncation), and the full registry snapshot is emitted as a Prometheus
textfile next to the JSON report (``<out>.prom``, overridable via
``ETH_SPECS_OBS_PROM``) and validated before the script exits.

``--smoke`` shrinks everything for CI (the serve-smoke job in
checks.yml) and skips the 2x gate; correctness/flush/compile/SLO gates
always apply. Exit code 0 only if every gate passes.

Replicated mode (``--replicas R``, the serve-replica-chaos CI job):
boots a supervised R-replica front door (serve/frontdoor.py), runs the
same closed-loop load THROUGH the socket boundary, and gates the
distributed-systems contract instead of the batching contract:

  * zero lost requests — every submitted future resolves;
  * byte parity with the clean single-process direct run;
  * ``--chaos``: one replica SIGKILLs itself mid-load
    (``frontdoor.rpc:kill`` + latch, the deterministic fault grammar),
    and the run must additionally show ``frontdoor.replicas_replaced
    > 0``, a ``frontdoor.replica_lost`` postmortem bundle from the
    parent, zero host-oracle degrades (the fleet absorbed the kill),
    and zero compiles-after-warmup on every surviving replica (the
    shippable warmup artifact did its job — including for the
    respawned replacement);
  * wait-p99 SLO evaluated from the MERGED cross-process histogram
    (replica deltas folded into the parent registry via health probes).

``--warmup-out`` writes the shippable warmup artifact (every compiled
shape key) for CI to upload; replicated runs also boot FROM it.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from eth_consensus_specs_tpu import obs, serve  # noqa: E402
from eth_consensus_specs_tpu.analysis import lint, lockwatch  # noqa: E402
from eth_consensus_specs_tpu.obs import export, slo  # noqa: E402
from eth_consensus_specs_tpu.ops import bls_batch  # noqa: E402
from eth_consensus_specs_tpu.ops.merkle import merkleize_subtree_device  # noqa: E402
from eth_consensus_specs_tpu.serve import buckets as serve_buckets  # noqa: E402
from eth_consensus_specs_tpu.serve.config import ServeConfig  # noqa: E402
from eth_consensus_specs_tpu.utils import bls  # noqa: E402


def build_bls_items(n: int, committee: int, distinct_msgs: int) -> list[tuple]:
    sks = list(range(1, committee + 1))
    pks = [bls.SkToPk(sk) for sk in sks]
    msgs = [bytes([i + 1]) * 32 for i in range(distinct_msgs)]
    items = []
    for i in range(n):
        m = msgs[i % distinct_msgs]
        sig = bls.Aggregate([bls.Sign(sk, m) for sk in sks])
        if i % 64 == 7:  # sparse invalid items keep bisection honest
            sig = b"\x01" + bytes(sig)[1:]
        items.append((pks, m, sig))
    return items


def build_trees(n: int, depth: int, seed: int = 0) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    cap = 1 << depth
    lo = cap // 2 + 1
    return [
        rng.integers(0, 256, size=(int(rng.integers(lo, cap + 1)), 32)).astype(np.uint8)
        for _ in range(n)
    ]


_LOST = object()  # sentinel: a future that never resolved (a LOST request)


def closed_loop(
    svc, payloads: list[tuple], submitters: int, result_timeout: float = 300.0
) -> tuple[float, list, list]:
    """Each submitter thread works through its share, one outstanding
    request at a time (closed loop). Returns (seconds, results in
    payload order, per-request latencies seconds). A future that fails
    or times out leaves the ``_LOST`` sentinel — the replicated gates
    assert none exist."""
    results: list = [_LOST] * len(payloads)
    latencies: list = [0.0] * len(payloads)
    shards = [list(range(i, len(payloads), submitters)) for i in range(submitters)]
    start = threading.Barrier(submitters + 1)

    def run(shard):
        start.wait()
        for idx in shard:
            kind, payload = payloads[idx]
            t0 = time.perf_counter()
            while True:
                try:
                    if kind == "bls":
                        fut = svc.submit_bls_aggregate(*payload)
                    else:
                        fut = svc.submit_hash_tree_root(payload)
                except serve.Overloaded as exc:
                    time.sleep(exc.retry_after_s)  # closed loop honors the shed hint
                    continue
                try:
                    results[idx] = fut.result(timeout=result_timeout)
                except serve.Overloaded as exc:
                    # the front door resolved the future with a typed
                    # shed (every replica overloaded): flow control, not
                    # loss — back off and resubmit like any other shed
                    time.sleep(exc.retry_after_s)
                    continue
                except Exception:  # noqa: BLE001 — recorded as lost, gated below
                    pass
                break
            latencies[idx] = time.perf_counter() - t0

    threads = [threading.Thread(target=run, args=(s,), daemon=True) for s in shards]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    return time.perf_counter() - t0, results, latencies


def latency_histogram(latencies_s: list[float]) -> dict:
    """Log2 millisecond buckets: {"<=1ms": n, "<=2ms": n, ...}."""
    hist: dict[str, int] = {}
    for lat in latencies_s:
        ms = lat * 1000.0
        edge = 1 << max(math.ceil(math.log2(max(ms, 0.001))), 0)
        hist[f"<={edge}ms"] = hist.get(f"<={edge}ms", 0) + 1
    return dict(sorted(hist.items(), key=lambda kv: int(kv[0][2:-2])))


def finish_report(report: dict, failures: list, out: str, trigger: str, snap: dict) -> None:
    """Shared epilogue of both bench modes: validated Prometheus
    textfile of the final snapshot, report JSON + stdout line, and — on
    any gate failure — a flight-recorder bundle plus exit code 1."""
    prom_path = os.environ.get("ETH_SPECS_OBS_PROM") or (
        os.path.splitext(out)[0] + ".prom"
    )
    if lockwatch.enabled():
        # runtime lock-order gate (ETH_SPECS_ANALYSIS_LOCKWATCH=1, the
        # CI serve-smoke configuration): zero inversions observed live,
        # and the union of the static lock graph with the orders this
        # run actually exercised stays acyclic (docs/analysis.md)
        lockwatch.publish()
        snap = obs.snapshot()  # re-snapshot WITH the published gauges
        lw = lockwatch.report()
        static = lint.build_lock_graph(lint.collect_modules(REPO))
        agreement = lockwatch.check_against_static(static["edges"])
        lw["static_agreement"] = agreement
        report["lockwatch"] = lw
        if lw["inversions"]:
            failures.append(f"lock-order inversions observed live: {lw['inversions']}")
        if not agreement["ok"]:
            failures.append(
                f"static/runtime lock graphs disagree (union has a cycle): "
                f"{agreement['cycles']}"
            )
    export.write_textfile(prom_path, snap=snap)
    try:
        export.validate_text(open(prom_path).read())
    except ValueError as exc:
        failures.append(f"prometheus exposition invalid: {exc}")
    report["prometheus_textfile"] = prom_path
    report["failures"] = failures
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report, sort_keys=True))
    if failures:
        # any gate failure is an incident: leave a flight-recorder
        # bundle for the CI `if: failure()` artifact (no-op without a
        # postmortem dir)
        obs.flight.trigger_dump(trigger, detail="; ".join(failures)[:300])
        print("FAILED:", *failures, sep="\n  ", file=sys.stderr)
        raise SystemExit(1)


def run_replicated(args) -> None:
    """The --replicas path: closed-loop load through a supervised
    replica fleet, optionally with a deterministic mid-load SIGKILL."""
    from eth_consensus_specs_tpu.obs import slo as slo_mod
    from eth_consensus_specs_tpu.serve.config import FrontDoorConfig
    from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor

    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
    if not pm_dir:
        pm_dir = os.path.join(out_dir, "postmortems")
        os.environ["ETH_SPECS_OBS_POSTMORTEM_DIR"] = pm_dir
    warmup_path = args.warmup_out or os.path.join(out_dir, "warmup_shapes.jsonl")

    export.maybe_serve_http()
    cfg = ServeConfig.from_env(max_batch=min(max(args.submitters // 2, 1), 32))
    fault_spec = None
    if args.chaos:
        # deterministic mid-load kill: exactly ONE replica (the latch
        # arbitrates) SIGKILLs itself on its Nth request RPC
        nth = max(args.requests // 8, 2)
        latch = os.path.join(out_dir, f"chaos_kill_{os.getpid()}.latch")
        if os.path.exists(latch):
            os.unlink(latch)
        fault_spec = f"frontdoor.rpc:kill:nth={nth}:latch={latch}"

    fd = FrontDoor(
        replicas=args.replicas,
        config=cfg,
        fd_config=FrontDoorConfig.from_env(),
        warmup_path=warmup_path,
        # the bls_msm key matters on device backends (the MSM kernel
        # compiles per pow2 committee size; precompile skips it when
        # _use_device() is off) — without it the bls home replica's
        # first dispatch would be a cold compile after mark_ready and
        # fail this run's own compiles_after_ready gate
        warm_keys=[("merkle_many", b, args.tree_depth) for b in cfg.buckets]
        + [("bls_msm", serve_buckets.pow2_bucket(args.committee))],
        replica_fault_spec=fault_spec,
        name="bench-fd",
    )

    # clean single-process truth on the SAME payloads (replicas are
    # spawned with fresh runtimes, so parent-side work can't pre-warm
    # them — the zero-cold-compile gate stays honest)
    bls_items = build_bls_items(args.requests, args.committee, distinct_msgs=4)
    trees = build_trees(args.requests, args.tree_depth)
    direct_bls = [bls_batch.batch_verify_aggregates([it]) for it in bls_items]
    direct_roots = [merkleize_subtree_device(t, args.tree_depth) for t in trees]

    load = [("bls", it) for it in bls_items] + [("htr", t) for t in trees]
    wall_s, got, _lat = closed_loop(fd, load, args.submitters)
    time.sleep(max(fd.fdcfg.probe_interval_s * 3, 0.5))  # one last probe round
    stats = fd.stats()
    replica_stats = fd.replica_stats()
    fd.close()  # merges each survivor's final obs delta

    failures = []
    lost = sum(1 for r in got if r is _LOST)
    if lost:
        failures.append(f"{lost} requests lost (futures never resolved)")
    if got[: len(bls_items)] != direct_bls:
        failures.append("BLS parity: replicated results != direct ops results")
    if got[len(bls_items):] != direct_roots:
        failures.append("HTR parity: replicated roots != direct ops roots")

    snap = obs.snapshot()
    counters = snap["counters"]
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")
    replaced = counters.get("frontdoor.replicas_replaced", 0)
    degraded_host = counters.get("frontdoor.degraded_to_host", 0)
    bundles = []
    if os.path.isdir(pm_dir):
        for name in sorted(os.listdir(pm_dir)):
            if name.startswith("postmortem-") and "frontdoor-replica-lost" in name:
                bundles.append(os.path.join(pm_dir, name))
    if args.chaos:
        if replaced < 1:
            failures.append("chaos run but frontdoor.replicas_replaced == 0 "
                            "(the kill never happened or was never healed)")
        if not bundles:
            failures.append(f"no frontdoor.replica_lost postmortem bundle in {pm_dir}")
        if degraded_host:
            failures.append(
                f"{degraded_host} host-oracle degrades: the fleet did NOT absorb "
                "the kill (siblings should have served every failover)"
            )
    # zero cold compiles on every replica that answered its last probe:
    # survivors AND the respawned replacement warmed from the artifact
    cold = {
        i: s["compiles_after_ready"]
        for i, s in enumerate(replica_stats)
        if s is not None and s.get("compiles_after_ready")
    }
    if cold:
        failures.append(f"cold compiles after warmup on replicas: {cold}")
    surveyed = sum(1 for s in replica_stats if s is not None)
    if surveyed < args.replicas:
        failures.append(
            f"only {surveyed}/{args.replicas} replicas answered a health probe"
        )
    obs.count("serve.compiles_after_warmup", sum(cold.values()))

    # the wait-p99 SLO over the MERGED cross-process histogram (replica
    # deltas folded in via health probes + the final close() probe)
    snap = obs.snapshot()
    wait_hist = snap["histograms"].get("serve.wait_ms", {})
    if not wait_hist.get("count"):
        failures.append("merged serve.wait_ms histogram is empty — replica "
                        "telemetry never reached the parent")
    slo_results = slo_mod.evaluate(snap)
    for r in slo_results:
        if not r.ok:
            failures.append(
                f"SLO {r.name}: observed {r.observed} > bound {r.bound} ({r.detail})"
            )

    report = {
        "mode": "replicated-chaos" if args.chaos else "replicated",
        "replicas": args.replicas,
        "submitters": args.submitters,
        "requests": len(load),
        "rps": round(len(load) / wall_s, 2),
        "lost": lost,
        "replicas_replaced": replaced,
        "postmortem_bundles": bundles,
        "degraded_to_host": degraded_host,
        "hedges": stats["hedges"],
        "hedge_wins": stats["hedge_wins"],
        "failovers": stats["failovers"],
        "corrupt_frames": stats["corrupt_frames"],
        "route_affinity": counters.get("frontdoor.route.affinity", 0),
        "route_fallback": counters.get("frontdoor.route.fallback", 0),
        "replica_stats": replica_stats,
        "warmup_artifact": warmup_path,
        "warmup_keys": len(serve_buckets.load_warmup(warmup_path)),
        "wait_ms": {
            "samples": wait_hist.get("count", 0),
            "p50": wait_hist.get("p50"),
            "p99": wait_hist.get("p99"),
        },
        "slo": slo_mod.report(slo_results),
    }

    finish_report(report, failures, args.out, "serve_bench.replicated_failure", snap)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="small CI run, skip the 2x gate")
    ap.add_argument("--submitters", type=int, default=64)
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--tree-depth", type=int, default=10)
    ap.add_argument("--committee", type=int, default=3)
    ap.add_argument("--out", default="BENCH_SERVE.json")
    ap.add_argument("--replicas", type=int, default=0,
                    help="run the load through an R-replica front door")
    ap.add_argument("--chaos", action="store_true",
                    help="with --replicas: SIGKILL one replica mid-load")
    ap.add_argument("--warmup-out", default=None,
                    help="write the shippable warmup artifact here")
    args = ap.parse_args()
    if args.smoke:
        args.submitters = min(args.submitters, 16)
        args.requests = min(args.requests, 64)
        args.tree_depth = min(args.tree_depth, 6)
    if args.replicas > 0:
        run_replicated(args)
        return

    export.maybe_serve_http()  # scrapeable while the bench runs (env-gated)
    # max_batch strictly below the submitter count guarantees full (size-
    # flushed) buckets at steady state instead of racing the deadline
    cfg = ServeConfig.from_env(max_batch=min(max(args.submitters // 2, 1), 32))
    bls_items = build_bls_items(args.requests, args.committee, distinct_msgs=4)
    trees = build_trees(args.requests, args.tree_depth)

    # --- phase 1: sequential per-request direct ops baseline ------------
    bls_batch.batch_verify_aggregates([bls_items[0]])  # warm parse/h2g2 caches
    merkleize_subtree_device(trees[0], args.tree_depth)  # pay the direct compile
    t0 = time.perf_counter()
    direct_bls = [bls_batch.batch_verify_aggregates([it]) for it in bls_items]
    seq_bls_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    direct_roots = [merkleize_subtree_device(t, args.tree_depth) for t in trees]
    seq_htr_s = time.perf_counter() - t0

    # --- phase 2: service + bucket warmup -------------------------------
    svc = serve.VerifyService(cfg, name="bench")
    warm_keys = [("merkle_many", b, args.tree_depth) for b in cfg.buckets]
    svc.precompile(warm_keys)
    compiles_after_warmup = obs.snapshot()["counters"].get("serve.compiles", 0)

    # --- phase 3: trickle (deadline flushes) ----------------------------
    for it in bls_items[:3]:
        assert svc.submit_bls_aggregate(*it).result() == bls_batch.batch_verify_aggregates([it])
        time.sleep(cfg.max_wait_s * 2)

    # --- phase 4: closed-loop load --------------------------------------
    load_bls = [("bls", it) for it in bls_items]
    svc_bls_s, got_bls, lat_bls = closed_loop(svc, load_bls, args.submitters)
    load_htr = [("htr", t) for t in trees]
    svc_htr_s, got_roots, lat_htr = closed_loop(svc, load_htr, args.submitters)
    svc.close()

    # --- phase 5: gates --------------------------------------------------
    failures = []
    if got_bls != direct_bls:
        failures.append("BLS parity: service results != direct ops results")
    if got_roots != direct_roots:
        failures.append("HTR parity: service roots != direct ops roots")
    snap = obs.snapshot()
    counters = snap["counters"]
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")
    if counters.get("serve.flush.deadline", 0) < 1:
        failures.append("no deadline flush observed (trickle phase)")
    if counters.get("serve.flush.size", 0) < 1:
        failures.append("no size flush observed (load phase)")
    extra = counters.get("serve.compiles", 0) - compiles_after_warmup
    if extra > 0:
        failures.append(f"{extra} compiles AFTER warmup (shape escaped the buckets)")
    # every first-dispatch compile must have left its wall time in the
    # serve.compile_ms histogram — count in lockstep with the counter
    compile_hist = snap["histograms"].get("serve.compile_ms", {})
    if compile_hist.get("count", 0) != counters.get("serve.compiles", 0):
        failures.append(
            f"serve.compile_ms count {compile_hist.get('count', 0)} != "
            f"serve.compiles {counters.get('serve.compiles', 0)} "
            "(a first dispatch escaped the timed wrapper)"
        )
    # feed the declarative SLO set (obs/slo.py): the counter is the
    # snapshot-visible form of the "zero compiles after warmup" contract
    obs.count("serve.compiles_after_warmup", max(extra, 0))
    snap = obs.snapshot()
    counters = snap["counters"]
    slo_results = slo.evaluate(snap)
    for r in slo_results:
        if not r.ok:
            failures.append(
                f"SLO {r.name}: observed {r.observed} > bound {r.bound} ({r.detail})"
            )

    # run-level wait quantiles: bucket quantiles over EVERY wait of the
    # run (the old 4096-sample reservoir is gone)
    wait_hist = snap["histograms"].get("serve.wait_ms", {})

    speedup_bls = (args.requests / svc_bls_s) / (args.requests / seq_bls_s)
    speedup_htr = (args.requests / svc_htr_s) / (args.requests / seq_htr_s)
    if not args.smoke and speedup_bls < 2.0:
        failures.append(f"BLS speedup {speedup_bls:.2f}x < 2x over sequential ops calls")

    report = {
        "mode": "smoke" if args.smoke else "full",
        "submitters": args.submitters,
        "requests": args.requests,
        "bls": {
            "sequential_rps": round(args.requests / seq_bls_s, 2),
            "service_rps": round(args.requests / svc_bls_s, 2),
            "speedup": round(speedup_bls, 3),
            "latency_ms_histogram": latency_histogram(lat_bls),
        },
        "htr": {
            "tree_depth": args.tree_depth,
            "sequential_rps": round(args.requests / seq_htr_s, 2),
            "service_rps": round(args.requests / svc_htr_s, 2),
            "speedup": round(speedup_htr, 3),
            "latency_ms_histogram": latency_histogram(lat_htr),
        },
        "flushes": {
            r: counters.get(f"serve.flush.{r}", 0)
            for r in ("size", "deadline", "pressure", "close")
        },
        "compiles": counters.get("serve.compiles", 0),
        "compiles_after_warmup": max(extra, 0),
        # first-dispatch compile walls (p50/p99 from the mergeable
        # histogram; count == compiles is gated above)
        "compile_ms": {
            "count": compile_hist.get("count", 0),
            "p50": compile_hist.get("p50"),
            "p99": compile_hist.get("p99"),
        },
        "buckets": list(cfg.buckets),
        "rejected": counters.get("serve.rejected", 0),
        "watchdog": snap["watchdog"],
        "queue_depth_max": snap["gauges"].get("serve.queue_depth", {}).get("max", 0),
        "wait_ms": {
            "samples": wait_hist.get("count", 0),
            "p50": wait_hist.get("p50"),
            "p99": wait_hist.get("p99"),
        },
        "slo": slo.report(slo_results),
    }

    if args.warmup_out:
        # the shippable warmup artifact: every shape this run compiled,
        # for CI to upload and later boots (replicas!) to replay
        report["warmup_artifact"] = args.warmup_out
        report["warmup_keys"] = serve_buckets.write_warmup(args.warmup_out)
    finish_report(report, failures, args.out, "serve_bench.failure", snap)


if __name__ == "__main__":
    main()
