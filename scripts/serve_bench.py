"""Closed-loop load generator for the serve/ verification service.

Measures requests/sec of the batched async service against sequential
per-request ops calls on the SAME payloads, with bit-exact result
parity enforced, and writes a JSON report (default BENCH_SERVE.json)
including a request-latency histogram.

Phases:

  1. direct sequential baseline (one thread, per-request ops calls);
  2. service warmup: ``precompile()`` every (batch-bucket, depth) shape,
     snapshot the ``serve.compiles`` counter;
  3. trickle: one submitter, spaced submits — must produce a DEADLINE
     flush (low-load latency bound);
  4. load: N closed-loop submitters (each waits for its future before
     submitting the next) — must produce a SIZE flush and the headline
     throughput;
  5. gates: zero watchdog divergences, zero compiles after warmup
     (so total compiles <= len(buckets) per depth), serve.compile_ms
     histogram count == serve.compiles (every first dispatch left its
     compile wall time; p50/p99 land in the report), declarative SLOs
     (obs/slo.py: wait p99 bound, degraded rate, divergences,
     compiles-after-warmup) evaluated from the registry snapshot, and —
     full mode — batched BLS throughput >= 2x sequential.

Run-level wait p50/p99 come from the mergeable ``serve.wait_ms``
log-bucket histogram (every wait of the run — no reservoir
truncation), and the full registry snapshot is emitted as a Prometheus
textfile next to the JSON report (``<out>.prom``, overridable via
``ETH_SPECS_OBS_PROM``) and validated before the script exits.

``--smoke`` shrinks everything for CI (the serve-smoke job in
checks.yml) and skips the 2x gate; correctness/flush/compile/SLO gates
always apply. Exit code 0 only if every gate passes.

Replicated mode (``--replicas R``, the serve-replica-chaos CI job):
boots a supervised R-replica front door (serve/frontdoor.py), runs the
same closed-loop load THROUGH the socket boundary, and gates the
distributed-systems contract instead of the batching contract:

  * zero lost requests — every submitted future resolves;
  * byte parity with the clean single-process direct run;
  * ``--chaos``: one replica SIGKILLs itself mid-load
    (``frontdoor.rpc:kill`` + latch, the deterministic fault grammar),
    and the run must additionally show ``frontdoor.replicas_replaced
    > 0``, a ``frontdoor.replica_lost`` postmortem bundle from the
    parent, zero host-oracle degrades (the fleet absorbed the kill),
    and zero compiles-after-warmup on every surviving replica (the
    shippable warmup artifact did its job — including for the
    respawned replacement);
  * wait-p99 SLO evaluated from the MERGED cross-process histogram
    (replica deltas folded into the parent registry via health probes).

``--warmup-out`` writes the shippable warmup artifact (every compiled
shape key) for CI to upload; replicated runs also boot FROM it.

Fleet-matrix mode (``--replicas R --chips-matrix 1,8``, the fleet-smoke
CI job): the two-tier scale-out surface measured as a replicas×chips
grid. Every cell (r, c) boots a homogeneous fleet of r replicas × c
virtual chips each and runs the same closed-loop big-tree load;
throughput is measured interleaved against a live 1×1 base fleet (the
PR 11 noisy-neighbor lesson: pair the two measurements inside ONE
noise window, alternate their order each round, and gate on the MEDIAN
within-round ratio — a best base wall from a quiet window must never
divide a cell wall from a throttled one) with every replica boot
blocked on — and all replicas probe-confirmed — BEFORE the timer
starts. Gates per cell: byte parity with the parent's direct ops calls
(a cell that fails parity REFUSES to report throughput at all) and
``compiles_after_ready == 0`` on every replica; across cells, the BEST
wide (c > 1) per-effective-chip scaling must clear ``--scaling-min``
(run_mesh's best-of-sections discipline — per-cell factors are all
reported so a host's oversubscription cliff stays visible), where
effective chips = min(r*c, cores - 1) on the virtual CPU mesh (the
closed-loop client burns a core) and r*c on accelerators. A final HETEROGENEOUS
phase boots the mixed fleet (chips cycled from the matrix), routes a
mixed toy/big/bls load through the signature-aware router, SIGKILLs one
replica mid-load (``--chaos``), and drives the SLO autoscaler through a
forced breach and an idle window — gating zero lost requests, parity,
zero cold compiles fleet-wide (respawned replacement included), p99
within the DEFAULT SLO, and the autoscaler observably growing AND
retiring a replica. The report's ``fleet`` section feeds perf_track.py
as platform-aware secondary metrics.

Mesh mode (``--chips N``, the mesh-smoke CI job): forces N virtual CPU
devices (``--xla_force_host_platform_device_count``; real devices on
accelerators), then measures every hot kernel chips=1 vs chips=N in one
process — merkleization through a 1-chip and an N-chip VerifyService
(mesh-aware buckets, signed warmup keys), the G1 MSM as a direct kernel
loop, and the sharded RLC pairing when the backend affords the Miller
compile (``--mesh-pairing`` opts the CPU mesh in). Gates: byte parity
on every sharded result, zero cold compiles after the mesh-aware warmup
replay, zero watchdog divergences, and best per-effective-chip scaling
>= ``--scaling-min`` (effective chips = min(chips, cores) on the
virtual CPU mesh — 8 virtual devices on 2 cores cannot honestly beat
2x). The report's ``mesh`` section feeds perf_track.py as
platform-aware secondary metrics.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# --chips / ETH_SPECS_SERVE_CHIPS need N virtual devices forced BEFORE
# the XLA backend initializes; the pre-parse lives in scripts/prejax.py
# (ONE copy, shared with scripts/jaxlint.py — the two had started to
# drift) and also defaults JAX_PLATFORMS to cpu
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prejax import force_virtual_chips  # noqa: E402

force_virtual_chips()

import numpy as np  # noqa: E402

from eth_consensus_specs_tpu import obs, serve  # noqa: E402
from eth_consensus_specs_tpu.analysis import lint, lockwatch  # noqa: E402
from eth_consensus_specs_tpu.obs import anomaly as anomaly_mod  # noqa: E402
from eth_consensus_specs_tpu.obs import canary as canary_mod  # noqa: E402
from eth_consensus_specs_tpu.obs import export, slo, timeline  # noqa: E402
from eth_consensus_specs_tpu.obs import tsdb as tsdb_mod  # noqa: E402
from eth_consensus_specs_tpu.ops import bls_batch  # noqa: E402
from eth_consensus_specs_tpu.ops.merkle import merkleize_subtree_device  # noqa: E402
from eth_consensus_specs_tpu.serve import buckets as serve_buckets  # noqa: E402
from eth_consensus_specs_tpu.serve.config import ServeConfig  # noqa: E402
from eth_consensus_specs_tpu.utils import bls  # noqa: E402


def build_bls_items(n: int, committee: int, distinct_msgs: int) -> list[tuple]:
    sks = list(range(1, committee + 1))
    pks = [bls.SkToPk(sk) for sk in sks]
    msgs = [bytes([i + 1]) * 32 for i in range(distinct_msgs)]
    items = []
    for i in range(n):
        m = msgs[i % distinct_msgs]
        sig = bls.Aggregate([bls.Sign(sk, m) for sk in sks])
        if i % 64 == 7:  # sparse invalid items keep bisection honest
            sig = b"\x01" + bytes(sig)[1:]
        items.append((pks, m, sig))
    return items


def build_trees(n: int, depth: int, seed: int = 0) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    cap = 1 << depth
    lo = cap // 2 + 1
    return [
        rng.integers(0, 256, size=(int(rng.integers(lo, cap + 1)), 32)).astype(np.uint8)
        for _ in range(n)
    ]


_LOST = object()  # sentinel: a future that never resolved (a LOST request)


def closed_loop(
    svc, payloads: list[tuple], submitters: int, result_timeout: float = 300.0
) -> tuple[float, list, list]:
    """Each submitter thread works through its share, one outstanding
    request at a time (closed loop). Returns (seconds, results in
    payload order, per-request latencies seconds). A future that fails
    or times out leaves the ``_LOST`` sentinel — the replicated gates
    assert none exist."""
    results: list = [_LOST] * len(payloads)
    latencies: list = [0.0] * len(payloads)
    shards = [list(range(i, len(payloads), submitters)) for i in range(submitters)]
    start = threading.Barrier(submitters + 1)

    def run(shard):
        start.wait()
        for idx in shard:
            kind, payload = payloads[idx]
            t0 = time.perf_counter()
            while True:
                try:
                    if kind == "bls":
                        fut = svc.submit_bls_aggregate(*payload)
                    elif kind == "agg":
                        fut = svc.submit_aggregate(payload)
                    elif kind == "kzg":
                        fut = svc.submit_blob_verify(*payload)
                    else:
                        fut = svc.submit_hash_tree_root(payload)
                except serve.Overloaded as exc:
                    time.sleep(exc.retry_after_s)  # closed loop honors the shed hint
                    continue
                try:
                    results[idx] = fut.result(timeout=result_timeout)
                except serve.Overloaded as exc:
                    # the front door resolved the future with a typed
                    # shed (every replica overloaded): flow control, not
                    # loss — back off and resubmit like any other shed
                    time.sleep(exc.retry_after_s)
                    continue
                except Exception:  # noqa: BLE001 — recorded as lost, gated below
                    pass
                break
            latencies[idx] = time.perf_counter() - t0

    threads = [threading.Thread(target=run, args=(s,), daemon=True) for s in shards]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    return time.perf_counter() - t0, results, latencies


def wait_replicas_surveyed(fd, timeout_s: float = 600.0) -> None:
    """Block until every live replica slot has answered a health probe
    since its CURRENT process came up. A chaos respawn's boot (the
    warmup-artifact replay — real compile time) can outlive a small
    load phase, and the supervisor clears a dead replica's health
    snapshot on death, so the cold-compile gate must wait for the
    replacement's OWN stats rather than read its predecessor's.
    Bounded: a respawn that never comes up leaves its slot None and
    the surveyed gate fails exactly as before."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        time.sleep(max(fd.fdcfg.probe_interval_s * 2, 0.5))
        # live slots FIRST, stats second: an autoscaler grow landing
        # between the two calls may add a slot the stats snapshot does
        # not cover yet — that slot is simply not-yet-surveyed, not an
        # index error
        live = getattr(fd, "live_replicas", None)
        stats = fd.replica_stats()
        idxs = live() if live is not None else range(len(stats))
        if all(i < len(stats) and stats[i] is not None for i in idxs):
            return


def latency_histogram(latencies_s: list[float]) -> dict:
    """Log2 millisecond buckets: {"<=1ms": n, "<=2ms": n, ...}."""
    hist: dict[str, int] = {}
    for lat in latencies_s:
        ms = lat * 1000.0
        edge = 1 << max(math.ceil(math.log2(max(ms, 0.001))), 0)
        hist[f"<={edge}ms"] = hist.get(f"<={edge}ms", 0) + 1
    return dict(sorted(hist.items(), key=lambda kv: int(kv[0][2:-2])))


class BenchTelemetry:
    """The continuous-telemetry plane for the in-process bench mode: a
    tsdb sampler feeding the STRUCTURAL anomaly detectors plus a
    known-answer canary stream through the same client the load uses.

    Structural detectors only: the statistical set (latency step/drift,
    rate spike/stall) assumes organic traffic, and a bench sweeps load
    shapes by design — trickle then closed-loop IS a rate spike. The
    structural detectors (dead replica, probe/completion stall, dark
    stage) must stay silent on any clean run regardless of load shape,
    which is exactly what the bench gates."""

    def __init__(self, client, source: str, canary_ms: float, shapes=None):
        cfg = anomaly_mod.AnomalyConfig.from_env()
        self.sampler = tsdb_mod.Sampler(tsdb_mod.ring_capacity_from_env())
        self.engine = anomaly_mod.Engine(
            cfg,
            detectors=anomaly_mod.default_detectors(
                cfg, source, anomaly_mod.STRUCTURAL),
            source=source,
        )
        self.canary = canary_mod.CanaryScheduler(
            client, interval_s=canary_ms / 1000.0, shapes=shapes)
        self._stop = threading.Event()
        self._last_sample = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, name="bench-telemetry", daemon=True)

    def start(self) -> "BenchTelemetry":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            self.canary.pump(now)
            if now - self._last_sample >= 0.25:
                self._last_sample = now
                self.sampler.sample(now)
                self.engine.step(self.sampler.ring)
            self._stop.wait(0.05)

    def stop(self) -> None:
        """Call BEFORE closing the service: the drain needs the serving
        path alive to resolve the in-flight canary."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        self.canary.drain(timeout_s=10.0)
        self.sampler.sample()  # fold the tail window
        self.engine.step(self.sampler.ring)

    def section(self) -> dict:
        return {
            "canary": self.canary.stats(),
            "anomaly": self.engine.report(),
            "series_span_s": round(self.sampler.ring.span_s(), 1),
        }

    def gate(self, failures: list) -> None:
        st = self.canary.stats()
        if st["sent"] < 1:
            failures.append("no canaries sent (the scheduler never fired)")
        if st["parity_failures"]:
            failures.append(
                f"{st['parity_failures']} canary parity failures — the serving "
                "path returned different bits than the host oracle")
        fires = self.engine.fire_counts()
        if fires:
            failures.append(f"anomaly fires on a clean run: {fires}")


def finish_report(report: dict, failures: list, out: str, trigger: str, snap: dict) -> None:
    """Shared epilogue of both bench modes: validated Prometheus
    textfile of the final snapshot, report JSON + stdout line, and — on
    any gate failure — a flight-recorder bundle plus exit code 1."""
    prom_path = os.environ.get("ETH_SPECS_OBS_PROM") or (
        os.path.splitext(out)[0] + ".prom"
    )
    if lockwatch.enabled():
        # runtime lock-order gate (ETH_SPECS_ANALYSIS_LOCKWATCH=1, the
        # CI serve-smoke configuration): zero inversions observed live,
        # and the union of the static lock graph with the orders this
        # run actually exercised stays acyclic (docs/analysis.md)
        lockwatch.publish()
        snap = obs.snapshot()  # re-snapshot WITH the published gauges
        lw = lockwatch.report()
        static = lint.build_lock_graph(lint.collect_modules(REPO))
        agreement = lockwatch.check_against_static(static["edges"])
        lw["static_agreement"] = agreement
        report["lockwatch"] = lw
        if lw["inversions"]:
            failures.append(f"lock-order inversions observed live: {lw['inversions']}")
        if not agreement["ok"]:
            failures.append(
                f"static/runtime lock graphs disagree (union has a cycle): "
                f"{agreement['cycles']}"
            )
    export.write_textfile(prom_path, snap=snap)
    try:
        export.validate_text(open(prom_path).read())
    except ValueError as exc:
        failures.append(f"prometheus exposition invalid: {exc}")
    report["prometheus_textfile"] = prom_path
    # stage histogram snapshots: slot_autopsy --diff compares two runs'
    # per-stage p99s from exactly these (full mergeable snapshots, not
    # pre-reduced quantiles — the diff picks its own quantile)
    stage_hist = {
        name: h for name, h in snap.get("histograms", {}).items()
        if name.startswith("serve.stage_ms.") and h.get("count")
    }
    if stage_hist:
        report["stage_hist"] = stage_hist
    # SLO burn-rate advisory (obs/slo.py): fraction of supervision
    # windows spent out of the wait-p99 budget. Non-gating — perf_track
    # ingests it as a secondary
    burn = slo.burn_rate(snap)
    if burn is not None:
        report["slo"] = burn
    # fleet timeline: when this run streamed JSONL events, assemble the
    # parent + replica sibling streams into ONE Perfetto trace next to
    # the report (the CI artifact; ui.perfetto.dev loads it directly)
    jsonl = os.environ.get("ETH_SPECS_OBS_JSONL")
    if jsonl:
        report["events_jsonl"] = jsonl
        try:
            summary = timeline.assemble_to_file(
                jsonl, os.path.splitext(out)[0] + ".trace.json"
            )
        except Exception as exc:  # noqa: BLE001 — the trace is an artifact,
            # never a reason to fail an otherwise-green bench
            summary = None
            print(f"trace assembly failed: {exc}", file=sys.stderr)
        if summary is not None:
            report["trace"] = summary
    report["failures"] = failures
    with open(out, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(json.dumps(report, sort_keys=True))
    if failures:
        # any gate failure is an incident: leave a flight-recorder
        # bundle for the CI `if: failure()` artifact (no-op without a
        # postmortem dir)
        obs.flight.trigger_dump(trigger, detail="; ".join(failures)[:300])
        print("FAILED:", *failures, sep="\n  ", file=sys.stderr)
        raise SystemExit(1)


def waterfall_section(
    failures: list,
    out: str,
    require_kernels: tuple = ("merkle_many", "bls_msm"),
    require_resident: bool = True,
) -> dict:
    """The request-waterfall report section (obs/waterfall.py), shared by
    the default, replicated and fleet modes, with its CI gates:

      * per-stage p50/p99 from the ``serve.stage_ms.*`` histograms (flat
        ``<stage>_p50_ms``/``<stage>_p99_ms`` keys — perf_track.py
        ingests every numeric ``*_ms`` key as a secondary advisory);
      * coverage: named-stage milliseconds must tile >= 95% of the
        measured e2e wall (``total``), and the first-class ``other``
        stage must stay under 20% of the e2e p50 — unattributed time is
        reported, never silent, but it must not dominate;
      * ``device.exec_ms.<kernel>`` populated for the headline kernel
        families (the dispatch seams actually measured device time) with
        zero roofline violations from MEASURED seconds;
      * a forced postmortem bundle whose ``hbm`` section carries a
        positive resident total — the HBM residency ledger is live and
        rides every black box.

    In replicated/fleet modes the stage and device histograms arrive via
    the replicas' obs deltas (obs/delta.py) — this reads the MERGED
    parent registry, the same fleet-wide view an operator would.
    """
    from eth_consensus_specs_tpu.obs import ledger, waterfall

    snap = obs.snapshot()
    wf = waterfall.report(snap)
    section: dict = {}
    for name, st in sorted(wf["stages"].items()):
        section[f"{name}_p50_ms"] = st["p50_ms"]
        section[f"{name}_p99_ms"] = st["p99_ms"]
    section["coverage"] = wf["coverage"]
    section["other_share_p50"] = wf["other_share_p50"]

    cov = wf["coverage"]
    if cov is None:
        failures.append(
            "waterfall: no stage histograms recorded (serve.stage_ms.total empty)"
        )
    elif cov < 0.95:
        failures.append(
            f"waterfall: named stages cover {cov:.3f} < 0.95 of measured e2e wall"
        )
    share = wf["other_share_p50"]
    if share is not None and share >= 0.20:
        failures.append(
            f"waterfall: 'other' (unattributed) stage is {share:.1%} of e2e p50"
        )

    hists = snap["histograms"]
    counters = snap["counters"]
    device: dict = {}
    for name, h in sorted(hists.items()):
        if name.startswith("device.exec_ms."):
            kern = name[len("device.exec_ms."):]
            device[kern] = {
                "count": h.get("count", 0),
                "p50_ms": h.get("p50"),
                "p99_ms": h.get("p99"),
                "roofline_violations": counters.get(
                    f"device.roofline_violations.{kern}", 0
                ),
            }
    section["device"] = device
    for kern in require_kernels:
        if not device.get(kern, {}).get("count"):
            failures.append(
                f"waterfall: device.exec_ms.{kern} is empty — the dispatch seam "
                "never measured device time for that family"
            )
    if counters.get("device.roofline_violations", 0):
        failures.append(
            "waterfall: measured device seconds violate the declared byte model "
            f"({counters['device.roofline_violations']} roofline violations)"
        )

    # the HBM residency ledger must ride the black box: force one bundle
    # (explicit out_dir — the default smoke sets no postmortem env) and
    # read its hbm section back
    out_dir = os.path.dirname(os.path.abspath(out)) or "."
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR") or os.path.join(
        out_dir, "postmortems"
    )
    path = obs.flight.dump("serve-bench-waterfall", out_dir=pm_dir)
    section["hbm"] = ledger.postmortem_section(top=5)
    section["postmortem_bundle"] = path
    if path is None:
        failures.append("waterfall: forced postmortem bundle failed to write")
    elif require_resident:
        # replicated/fleet parents hold no device buffers themselves (the
        # replicas own them), so residency is gated in the default mode only
        with open(path) as fh:
            hbm = (json.load(fh).get("hbm")) or {}
        if not hbm.get("resident_total_bytes", 0) > 0:
            failures.append(
                "waterfall: postmortem bundle hbm.resident_total_bytes is not "
                "positive — the residency ledger saw no device buffers"
            )
    return section


def run_replicated(args) -> None:
    """The --replicas path: closed-loop load through a supervised
    replica fleet, optionally with a deterministic mid-load SIGKILL."""
    from eth_consensus_specs_tpu.obs import slo as slo_mod
    from eth_consensus_specs_tpu.serve.config import FrontDoorConfig
    from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor

    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
    if not pm_dir:
        pm_dir = os.path.join(out_dir, "postmortems")
        os.environ["ETH_SPECS_OBS_POSTMORTEM_DIR"] = pm_dir
    warmup_path = args.warmup_out or os.path.join(out_dir, "warmup_shapes.jsonl")

    export.maybe_serve_http()
    cfg = ServeConfig.from_env(max_batch=min(max(args.submitters // 2, 1), 32))
    # continuous telemetry plane: structural detectors only (the
    # statistical set assumes organic traffic — a bench sweeps load
    # shapes by design) unless the caller pinned their own detector
    # set; canaries ride the supervisor tick at --canary-ms
    os.environ.setdefault("ETH_SPECS_ANOM_DETECTORS", "structural")
    fd_cfg = FrontDoorConfig.from_env()
    if args.canary_ms > 0 and fd_cfg.canary_interval_ms <= 0:
        fd_cfg = dataclasses.replace(
            fd_cfg, canary_interval_ms=float(args.canary_ms))
    fault_spec = None
    if args.chaos:
        # deterministic mid-load kill: exactly ONE replica (the latch
        # arbitrates) SIGKILLs itself on its Nth request RPC
        nth = max(args.requests // 8, 2)
        latch = os.path.join(out_dir, f"chaos_kill_{os.getpid()}.latch")
        if os.path.exists(latch):
            os.unlink(latch)
        fault_spec = f"frontdoor.rpc:kill:nth={nth}:latch={latch}"

    fd = FrontDoor(
        replicas=args.replicas,
        config=cfg,
        fd_config=fd_cfg,
        warmup_path=warmup_path,
        # the bls_msm keys matter on device backends (the batched G1
        # many-sum kernel compiles per (flush-items, committee-lanes)
        # bucket; precompile skips them when _use_device() is off) —
        # without them the bls home replica's first dispatch would be a
        # cold compile after mark_ready and fail this run's own
        # compiles_after_ready gate
        warm_keys=[("merkle_many", b, args.tree_depth) for b in cfg.buckets]
        + [
            ("bls_msm", b, serve_buckets.pow2_bucket(args.committee))
            for b in cfg.buckets
        ]
        # canary compile shapes (flush-group size 1), so the canary
        # stream can't trip a replica's compiles_after_ready gate
        + (canary_mod.warm_keys() if fd_cfg.canary_interval_ms > 0 else []),
        replica_fault_spec=fault_spec,
        name="bench-fd",
    )

    # clean single-process truth on the SAME payloads (replicas are
    # spawned with fresh runtimes, so parent-side work can't pre-warm
    # them — the zero-cold-compile gate stays honest)
    bls_items = build_bls_items(args.requests, args.committee, distinct_msgs=4)
    trees = build_trees(args.requests, args.tree_depth)
    direct_bls = [bls_batch.batch_verify_aggregates([it]) for it in bls_items]
    direct_roots = [merkleize_subtree_device(t, args.tree_depth) for t in trees]

    load = [("bls", it) for it in bls_items] + [("htr", t) for t in trees]
    wall_s, got, _lat = closed_loop(fd, load, args.submitters)
    wait_replicas_surveyed(fd)  # incl. a chaos respawn still booting
    stats = fd.stats()
    replica_stats = fd.replica_stats()
    fd.close()  # merges each survivor's final obs delta
    telemetry = fd.telemetry_report()  # close() took the final window

    failures = []
    lost = sum(1 for r in got if r is _LOST)
    if lost:
        failures.append(f"{lost} requests lost (futures never resolved)")
    if got[: len(bls_items)] != direct_bls:
        failures.append("BLS parity: replicated results != direct ops results")
    if got[len(bls_items):] != direct_roots:
        failures.append("HTR parity: replicated roots != direct ops roots")

    snap = obs.snapshot()
    counters = snap["counters"]
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")
    replaced = counters.get("frontdoor.replicas_replaced", 0)
    degraded_host = counters.get("frontdoor.degraded_to_host", 0)
    bundles = []
    if os.path.isdir(pm_dir):
        for name in sorted(os.listdir(pm_dir)):
            if name.startswith("postmortem-") and "frontdoor-replica-lost" in name:
                bundles.append(os.path.join(pm_dir, name))
    if args.chaos:
        if replaced < 1:
            failures.append("chaos run but frontdoor.replicas_replaced == 0 "
                            "(the kill never happened or was never healed)")
        if not bundles:
            failures.append(f"no frontdoor.replica_lost postmortem bundle in {pm_dir}")
        if degraded_host:
            failures.append(
                f"{degraded_host} host-oracle degrades: the fleet did NOT absorb "
                "the kill (siblings should have served every failover)"
            )
    # zero cold compiles on every replica that answered its last probe:
    # survivors AND the respawned replacement warmed from the artifact
    cold = {
        i: s["compiles_after_ready"]
        for i, s in enumerate(replica_stats)
        if s is not None and s.get("compiles_after_ready")
    }
    if cold:
        failures.append(f"cold compiles after warmup on replicas: {cold}")
    surveyed = sum(1 for s in replica_stats if s is not None)
    if surveyed < args.replicas:
        failures.append(
            f"only {surveyed}/{args.replicas} replicas answered a health probe"
        )
    obs.count("serve.compiles_after_warmup", sum(cold.values()))

    # the wait-p99 SLO over the MERGED cross-process histogram (replica
    # deltas folded in via health probes + the final close() probe)
    snap = obs.snapshot()
    wait_hist = snap["histograms"].get("serve.wait_ms", {})
    if not wait_hist.get("count"):
        failures.append("merged serve.wait_ms histogram is empty — replica "
                        "telemetry never reached the parent")
    slo_results = slo_mod.evaluate(snap)
    for r in slo_results:
        if not r.ok:
            failures.append(
                f"SLO {r.name}: observed {r.observed} > bound {r.bound} ({r.detail})"
            )

    # telemetry-plane gates: canaries resolved bit-exactly through the
    # fleet, and the anomaly engine told the truth — silent on a clean
    # run, attributing the kill on a chaos run
    can = telemetry.get("canary")
    if fd_cfg.canary_interval_ms > 0 and can is not None:
        if can.get("sent", 0) < 1:
            failures.append("no canaries sent through the front door")
        if can.get("parity_failures"):
            failures.append(
                f"{can['parity_failures']} canary parity failures — the fleet "
                "returned different bits than the host oracle for a "
                "known-answer request")
    anom = telemetry.get("anomaly")
    if anom is not None:
        fires = dict(anom.get("fires") or {})
        if args.chaos:
            dead = [f for f in anom.get("fired", ())
                    if f.get("detector") == "dead_replica"]
            if not dead:
                failures.append(
                    "chaos run but the dead_replica detector never fired — "
                    "the kill went undetected by the telemetry plane")
            else:
                rec = dead[0]
                if rec.get("replica") is None or rec.get("stage") != "recovery":
                    failures.append(
                        f"dead_replica fired without attribution: {rec}")
                if rec.get("windows", 99) > 2:
                    failures.append(
                        f"dead_replica detection took {rec['windows']} probe "
                        "windows (documented horizon is 2)")
                if not rec.get("bundle"):
                    failures.append(
                        "dead_replica fired without an exemplar bundle "
                        f"(ETH_SPECS_OBS_POSTMORTEM_DIR={pm_dir})")
            # the kill legitimately trips the death + probe detectors;
            # anything else firing is a telemetry false positive
            unexpected = {k: v for k, v in fires.items()
                          if k not in ("dead_replica", "probe_stall")}
        else:
            unexpected = fires
        if unexpected:
            failures.append(f"unexpected anomaly fires: {unexpected}")

    report = {
        "mode": "replicated-chaos" if args.chaos else "replicated",
        "replicas": args.replicas,
        "submitters": args.submitters,
        "requests": len(load),
        "rps": round(len(load) / wall_s, 2),
        "lost": lost,
        "replicas_replaced": replaced,
        "postmortem_bundles": bundles,
        "degraded_to_host": degraded_host,
        "hedges": stats["hedges"],
        "hedge_wins": stats["hedge_wins"],
        "failovers": stats["failovers"],
        "corrupt_frames": stats["corrupt_frames"],
        "route_affinity": counters.get("frontdoor.route.affinity", 0),
        "route_fallback": counters.get("frontdoor.route.fallback", 0),
        "replica_stats": replica_stats,
        "warmup_artifact": warmup_path,
        "warmup_keys": len(serve_buckets.load_warmup(warmup_path)),
        "wait_ms": {
            "samples": wait_hist.get("count", 0),
            "p50": wait_hist.get("p50"),
            "p99": wait_hist.get("p99"),
        },
        "slo": slo_mod.report(slo_results),
        "telemetry": telemetry,
        "waterfall": waterfall_section(failures, args.out, require_resident=False),
    }

    finish_report(report, failures, args.out, "serve_bench.replicated_failure", snap)


def _fleet_ready(fd, replicas: int, timeout_s: float = 30.0) -> bool:
    """Block until every replica of the fleet has answered a health
    probe — the 'async setup blocked on before the timer starts' bench
    discipline: FrontDoor.__init__ already joins the boot threads, this
    additionally proves the supervision loop sees every replica alive."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if sum(1 for s in fd.replica_stats() if s is not None) >= replicas:
            return True
        time.sleep(fd.fdcfg.probe_interval_s)
    return False


def run_fleet_matrix(args) -> None:
    """The --chips-matrix mode: the replicas×chips scaling grid plus the
    heterogeneous chaos/autoscale phase (module docstring, fleet-matrix
    mode)."""
    from eth_consensus_specs_tpu.obs import slo as slo_mod
    from eth_consensus_specs_tpu.serve.config import FrontDoorConfig
    from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor

    out_dir = os.path.dirname(os.path.abspath(args.out)) or "."
    pm_dir = os.environ.get("ETH_SPECS_OBS_POSTMORTEM_DIR")
    if not pm_dir:
        pm_dir = os.path.join(out_dir, "postmortems")
        os.environ["ETH_SPECS_OBS_POSTMORTEM_DIR"] = pm_dir
    warmup_path = args.warmup_out or os.path.join(out_dir, "fleet_warmup.jsonl")
    export.maybe_serve_http()

    # bench fleets run structural detectors only (statistical ones
    # assume organic traffic; the matrix sweeps load shapes by design)
    os.environ.setdefault("ETH_SPECS_ANOM_DETECTORS", "structural")
    matrix = tuple(args.chips_matrix) or (1,)
    R = max(args.replicas, 1)
    reps_list = sorted({1, R}) if args.smoke else list(range(1, R + 1))
    chips_vals = sorted(set(matrix))
    cores = os.cpu_count() or 1
    import jax

    platform = jax.local_devices()[0].platform
    n_rounds = 5  # odd: the gate reads the MEDIAN paired round ratio

    # small bucket set bounds the per-replica warm compile count; the
    # WIDE depths clear the mesh crossover at any flush >= min-items so
    # the wide cells genuinely shard (and route_wide classifies them
    # wide) — depth 9 would be RPC/prep-bound on 2 cores and show no
    # mesh advantage at all (measured: 1.05x vs 1.8x at depth 11).
    # TWO wide depths, not one: shape affinity sends one shape to ONE
    # home replica, so a single-shape load would leave every sibling of
    # a multi-replica cell idle by design
    cfg = ServeConfig.from_env(
        max_batch=min(max(args.submitters // 2, 2), 8), buckets=(1, 4, 8)
    )
    # depth 11/12 trees: device-dominant even through the socket path
    # (measured: depth 9/10 loads are RPC/prep-bound on 2 cores and the
    # 1.8x kernel-level mesh win disappears end-to-end)
    wide_depths = (11, 12)
    toy_depth = min(args.tree_depth, 6)
    big_trees, direct_big = [], []
    for j, d in enumerate(wide_depths):
        per = build_trees(args.requests // len(wide_depths), d, seed=3 + j)
        big_trees += [(t, d) for t in per]
        direct_big += [merkleize_subtree_device(t, d) for t in per]
    load_big = [("htr", t) for t, _ in big_trees]
    warm = [("merkle_many", b, d) for d in wide_depths for b in cfg.buckets]

    failures: list = []
    cells: list = []
    fleet_metrics: dict = {}

    # the interleave partner: one 1-replica×1-chip fleet, alive for the
    # whole matrix, re-measured inside every cell's window
    base_fd = FrontDoor(
        replicas=1, chips=[1], config=cfg,
        fd_config=FrontDoorConfig.from_env(slo_shedding=False),
        warmup_path=warmup_path, warm_keys=warm, name="fleet-base",
    )
    if not _fleet_ready(base_fd, 1):
        failures.append("base fleet never confirmed ready")

    def _measure_cell(r: int, c: int) -> dict:
        # effective chips on cpu: the closed-loop client + supervisor
        # burn roughly ONE core end-to-end (unlike the in-process mesh
        # bench, where min(chips, cores) is the whole story), so the
        # fleet's replicas share cores-1 — measured on the 2-core box:
        # a 4-virtual-chip replica shows its 1.8x kernel-level mesh win
        # as ~0.8-1.1x through the socket path because it never sees a
        # second core. Accelerator fleets keep effective = r*c.
        cell = {"replicas": r, "chips": c, "effective":
                min(r * c, max(cores - 1, 1)) if platform == "cpu" else r * c}
        if (r, c) == (1, 1):
            fd = base_fd
        else:
            fd = FrontDoor(
                replicas=r, chips=[c] * r, config=cfg,
                fd_config=FrontDoorConfig.from_env(slo_shedding=False),
                warmup_path=None, warm_keys=warm, name=f"fleet-r{r}x{c}",
            )
        try:
            if not _fleet_ready(fd, r):
                cell["ready"] = False
                failures.append(f"cell ({r},{c}): fleet never confirmed ready")
                return cell
            # untimed warm pass: client connections, first flush shapes
            _, got, _ = closed_loop(fd, load_big, args.submitters)
            parity = got == direct_big
            ratios, best_cell, best_base = [], None, None
            for k in range(n_rounds):
                # one round = one paired A/B inside one noise window:
                # the host is shares-throttled, so comparing a best base
                # wall from a quiet window against a cell wall from a
                # throttled one would be fiction — only the WITHIN-round
                # ratio is honest, and the order alternates so a
                # decaying noisy neighbor can't favor one side
                order = [("base", base_fd), ("cell", fd)]
                if k % 2:
                    order.reverse()
                walls = {}
                for side, target in order:
                    w, got_s, _ = closed_loop(target, load_big, args.submitters)
                    parity = parity and got_s == direct_big
                    walls[side] = w
                if not parity:
                    break
                ratios.append(walls["base"] / walls["cell"])
                best_base = (
                    walls["base"] if best_base is None
                    else min(best_base, walls["base"])
                )
                best_cell = (
                    walls["cell"] if best_cell is None
                    else min(best_cell, walls["cell"])
                )
            cell["parity"] = parity
            if not parity:
                # a cell that failed parity reports NO throughput: a
                # wrong-answer cell must never look like a fast cell
                failures.append(f"cell ({r},{c}): byte parity FAILED")
                return cell
            wait_replicas_surveyed(fd)
            cold = {
                i: s["compiles_after_ready"]
                for i, s in enumerate(fd.replica_stats())
                if s is not None and s.get("compiles_after_ready")
            }
            if cold:
                failures.append(f"cell ({r},{c}): cold compiles {cold}")
            cell["cold_compiles"] = sum(cold.values())
            speedup = sorted(ratios)[len(ratios) // 2]  # median round ratio
            cell.update(
                rps=round(len(load_big) / best_cell, 2),
                base_rps=round(len(load_big) / best_base, 2),
                round_ratios=[round(x, 3) for x in ratios],
                speedup=round(speedup, 3),
                scaling_factor=round(speedup / cell["effective"], 3),
            )
            fleet_metrics[f"r{r}x{c}_rps"] = cell["rps"]
            fleet_metrics[f"r{r}x{c}_scaling"] = cell["scaling_factor"]
            return cell
        finally:
            if fd is not base_fd:
                fd.close()

    for r in reps_list:
        for c in chips_vals:
            cells.append(_measure_cell(r, c))
    base_fd.close()

    het = _run_het_phase(
        args, cfg, matrix, R, warm, warmup_path, pm_dir, wide_depths[0], toy_depth,
        failures, slo_mod, FrontDoorConfig, FrontDoor,
    )
    snap = obs.snapshot()
    counters = snap["counters"]
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")
    fleet_metrics["grown"] = counters.get("frontdoor.replicas_grown", 0)
    fleet_metrics["retired"] = counters.get("frontdoor.replicas_retired", 0)
    # the wide-cell scaling gate reads the BEST wide cell — the same
    # discipline run_mesh applies across its sections: on a 2-core box
    # an 8-virtual-device replica sits past the oversubscription cliff
    # (measured (1,8) ~0.44 while (2,8) clears 0.97), and the grid's
    # job is to RECORD that cliff per cell, not to pretend a throttled
    # host refutes the mesh. Parity and cold-compile gates still apply
    # to every cell individually.
    wide_factors = [
        c["scaling_factor"] for c in cells
        if c.get("chips", 1) > 1 and "scaling_factor" in c
    ]
    if wide_factors:
        fleet_metrics["wide_scaling"] = max(wide_factors)
        if max(wide_factors) < args.scaling_min:
            failures.append(
                f"best wide-cell per-effective-chip scaling "
                f"{max(wide_factors)} < {args.scaling_min} "
                f"(all wide cells: {wide_factors})"
            )
    elif any(c > 1 for c in chips_vals):
        failures.append("no wide cell produced a scaling factor")

    report = {
        "mode": "fleet-matrix-smoke" if args.smoke else "fleet-matrix",
        "platform": platform,
        "requests": args.requests,
        "submitters": args.submitters,
        "replicas": R,
        "chips_matrix": list(matrix),
        "interleaved_rounds": n_rounds,
        "cells": cells,
        "het": het,
        "fleet": fleet_metrics,
        "scaling_min": args.scaling_min,
        "warmup_artifact": warmup_path,
        "warmup_keys": len(serve_buckets.load_warmup(warmup_path)),
        "waterfall": waterfall_section(failures, args.out, require_resident=False),
    }
    finish_report(report, failures, args.out, "serve_bench.fleet_failure", snap)


def _run_het_phase(
    args, cfg, matrix, R, warm, warmup_path, pm_dir, wide_depth, toy_depth,
    failures, slo_mod, FrontDoorConfig, FrontDoor,
) -> dict:
    """The heterogeneous chaos/autoscale phase: mixed tiers in one
    fleet, signature-aware routing under a mid-load SIGKILL, then the
    SLO autoscaler driven through one grow (forced breach) and one
    retire (idle)."""
    het_chips = [matrix[i % len(matrix)] for i in range(R)]
    fault_spec = None
    if args.chaos:
        nth = max(args.requests // 8, 2)
        latch = os.path.join(os.path.dirname(warmup_path) or ".",
                             f"fleet_kill_{os.getpid()}.latch")
        if os.path.exists(latch):
            os.unlink(latch)
        fault_spec = f"frontdoor.rpc:kill:nth={nth}:latch={latch}"
    fd_cfg = FrontDoorConfig.from_env(
        probe_interval_ms=120.0,
        autoscale=True,
        min_replicas=R,
        max_replicas=R + 1,
        grow_windows=2,
        retire_windows=4,
        scale_cooldown_s=1.0,
    )
    # every tier's warm keys: toy + wide merkle depths, plus the bls_msm
    # shapes (device backends; precompile skips them on host bls)
    warm_het = warm + [("merkle_many", b, toy_depth) for b in cfg.buckets] + [
        ("bls_msm", b, serve_buckets.pow2_bucket(args.committee))
        for b in cfg.buckets
    ]
    n_each = max(args.requests // 4, 8)
    toy_trees = build_trees(n_each, toy_depth, seed=5)
    big_trees = build_trees(n_each, wide_depth, seed=7)
    bls_items = build_bls_items(n_each, args.committee, distinct_msgs=2)
    direct = (
        [merkleize_subtree_device(t, toy_depth) for t in toy_trees]
        + [merkleize_subtree_device(t, wide_depth) for t in big_trees]
        + [bls_batch.batch_verify_aggregates([it]) for it in bls_items]
    )
    load = (
        [("htr", t) for t in toy_trees]
        + [("htr", t) for t in big_trees]
        + [("bls", it) for it in bls_items]
    )

    from eth_consensus_specs_tpu.obs.delta import DeltaShipper

    old_bound = os.environ.get("ETH_SPECS_SLO_WAIT_P99_MS")
    fd = FrontDoor(
        replicas=R, chips=het_chips, config=cfg, fd_config=fd_cfg,
        warmup_path=warmup_path, warm_keys=warm_het,
        replica_fault_spec=fault_spec, name="fleet-het",
    )
    try:
        if not _fleet_ready(fd, R):
            failures.append("het fleet never confirmed ready")
        # the CHAOS window: the SIGKILL load runs under the DEFAULT SLO
        # bounds and is the window the p99 gate reads — the deliberate
        # breach that drives the autoscaler comes AFTER, in its own
        # phase, so "p99 held under the kill" is not polluted by "we
        # then overloaded it on purpose" (nor by the matrix cells)
        chaos_ship = DeltaShipper()
        wall_s, got, _ = closed_loop(fd, load, args.submitters)
        time.sleep(max(fd_cfg.probe_interval_s * 3, 0.5))  # ship the last deltas
        chaos_window = chaos_ship.delta()

        def _counter(name):
            return obs.snapshot()["counters"].get(name, 0)

        # autoscale demo, actuator 1 of 2 (grow): force the breach —
        # ANY observed wait violates a 0.001ms p99 objective
        os.environ["ETH_SPECS_SLO_WAIT_P99_MS"] = "0.001"
        deadline = time.monotonic() + 60
        while _counter("frontdoor.replicas_grown") < 1 and time.monotonic() < deadline:
            try:
                # keep breach windows flowing while the grow boots
                fd.submit_hash_tree_root(toy_trees[0]).result(timeout=30)
            except serve.Overloaded as exc:
                time.sleep(exc.retry_after_s)  # the shed actuator is live too
            time.sleep(fd_cfg.probe_interval_s)
        if old_bound is None:
            os.environ.pop("ETH_SPECS_SLO_WAIT_P99_MS", None)
        else:
            os.environ["ETH_SPECS_SLO_WAIT_P99_MS"] = old_bound
        # actuator 2 of 2 (retire): sustained idle
        deadline = time.monotonic() + 60
        while _counter("frontdoor.replicas_retired") < 1 and time.monotonic() < deadline:
            time.sleep(fd_cfg.probe_interval_s)  # idle: no traffic at all
        wait_replicas_surveyed(fd)
        replica_stats = fd.replica_stats()
        profiles = fd.replica_profiles()
        stats = fd.stats()
    finally:
        if old_bound is None:
            os.environ.pop("ETH_SPECS_SLO_WAIT_P99_MS", None)
        else:
            os.environ["ETH_SPECS_SLO_WAIT_P99_MS"] = old_bound
        fd.close()

    lost = sum(1 for x in got if x is _LOST)
    if lost:
        failures.append(f"het: {lost} requests lost")
    if got != direct:
        failures.append("het: byte parity FAILED vs direct ops results")
    snap = obs.snapshot()
    counters = snap["counters"]
    grown = counters.get("frontdoor.replicas_grown", 0)
    retired = counters.get("frontdoor.replicas_retired", 0)
    if grown < 1:
        failures.append("autoscaler never grew a replica (forced breach)")
    if retired < 1:
        failures.append("autoscaler never retired a replica (idle window)")
    if counters.get("frontdoor.route.mesh_affinity", 0) < 1:
        failures.append("het: no mesh-tier affinity hits recorded")
    replaced = counters.get("frontdoor.replicas_replaced", 0)
    if args.chaos and replaced < 1:
        failures.append("het chaos: the SIGKILL never happened or never healed")
    if args.chaos and counters.get("frontdoor.degraded_to_host", 0):
        failures.append("het chaos: host-oracle degrades (fleet didn't absorb)")
    cold = {
        i: s["compiles_after_ready"]
        for i, s in enumerate(replica_stats)
        if s is not None and s.get("compiles_after_ready")
    }
    if cold:
        failures.append(f"het: cold compiles after ready: {cold}")
    # respawned/grown replicas replay ONLY their own mesh's keys
    for i, p in enumerate(profiles):
        if not p:
            continue
        own = p.get("signature", "")
        alien = [
            k for k in p.get("warm_keys") or []
            if any(isinstance(d, str) for d in k[1:])
            and not any(d == own for d in k[1:] if isinstance(d, str))
        ]
        if alien:
            failures.append(f"het: replica {i} warmed alien-signed keys {alien[:3]}")
    # p99 under the DEFAULT SLO bounds over the CHAOS window's merged
    # cross-process histogram (replica deltas folded in via probes);
    # window quantiles come from the bucket deltas — the snapshot's
    # derived p50/p99 fields are run-global and would smear the cells
    # and the deliberate-breach phase into the kill window
    from eth_consensus_specs_tpu.obs.histogram import Histogram

    wait_hist = dict(chaos_window["histograms"].get("serve.wait_ms", {}))
    if not wait_hist.get("count"):
        failures.append("het: merged serve.wait_ms histogram is empty for the "
                        "chaos window — replica telemetry never reached the parent")
    else:
        h = Histogram.from_snapshot(wait_hist)
        wait_hist["p50"] = round(h.quantile(0.5), 3)
        wait_hist["p99"] = round(h.quantile(0.99), 3)
    slo_results = slo_mod.evaluate(
        {"counters": chaos_window["counters"],
         "histograms": chaos_window["histograms"]}
    )
    for r_ in slo_results:
        if not r_.ok:
            failures.append(
                f"chaos-window SLO {r_.name}: observed {r_.observed} > "
                f"bound {r_.bound} ({r_.detail})"
            )
    return {
        "chips": het_chips,
        "requests": len(load),
        "rps": round(len(load) / wall_s, 2),
        "lost": lost,
        "replicas_grown": grown,
        "replicas_retired": retired,
        "replicas_replaced": replaced,
        "route_affinity": counters.get("frontdoor.route.affinity", 0),
        "route_mesh_affinity": counters.get("frontdoor.route.mesh_affinity", 0),
        "route_warm": counters.get("frontdoor.route.warm", 0),
        "replica_stats": replica_stats,
        "router": stats["replicas"],
        "wait_ms": {
            "samples": wait_hist.get("count", 0),
            "p50": wait_hist.get("p50"),
            "p99": wait_hist.get("p99"),
        },
        "slo": slo_mod.report(slo_results),
    }


def _timed_reps(fn, reps: int) -> float:
    """Median-free simple wall: one warm call (pays any compile), then
    `reps` timed calls; returns seconds per call."""
    fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def run_mesh(args) -> None:
    """The --chips N closed-loop mode: every kernel measured chips=1 vs
    chips=N IN ONE PROCESS (a 1-device mesh service vs an N-device mesh
    service; direct kernel loops for MSM/pairing), gating

      * byte parity — every sharded result identical to the
        single-device path (and to the direct per-request ops calls);
      * zero cold compiles after the mesh-aware warmup replay;
      * zero watchdog divergences;
      * scaling: best per-effective-chip factor >= --scaling-min, where
        effective chips = min(chips, cpu cores) on the virtual CPU mesh
        (8 virtual devices on 2 cores cannot beat 2x — gating against
        physical parallelism is what keeps this honest) and = chips on
        real accelerators.

    The report's ``mesh`` section is what perf_track.py ingests as
    platform-aware secondary metrics (``mesh_*``)."""
    import jax

    from eth_consensus_specs_tpu.crypto.curve import g1_generator
    from eth_consensus_specs_tpu.crypto.msm import msm_g1
    from eth_consensus_specs_tpu.ops.g1_msm import (
        msm_g1_device,
        sum_g1_device,
        sum_g1_many_device,
    )
    from eth_consensus_specs_tpu.parallel import mesh_ops

    export.maybe_serve_http()
    chips = args.chips
    devices = jax.local_devices()
    platform = devices[0].platform
    mesh = mesh_ops.serve_mesh(chips)
    shards = mesh_ops.shard_count(mesh)
    sig = mesh_ops.mesh_signature(mesh)
    failures = []
    if shards < 2:
        failures.append(
            f"--chips {chips} but only {len(devices)} {platform} devices — no mesh"
        )
    cores = os.cpu_count() or 1
    effective = min(chips, cores, max(shards, 1)) if platform == "cpu" else chips
    reps = 2 if args.smoke else 4
    sections: dict = {}

    # --- merkle: through the REAL serve path, 1-chip vs N-chip service --
    # The serve leg uses trees big enough to clear the mesh crossover
    # (depth >= 9: a max_batch flush of 512-chunk trees passes
    # MESH_SUBTREE_THRESHOLD) so the N-chip service genuinely shards —
    # a smoke that only ever took the single-device fallback would gate
    # nothing about the mesh routing or the signed warmup keys.
    serve_depth = max(args.tree_depth, 9)
    cfg1 = ServeConfig.from_env(
        max_batch=min(max(args.submitters // 2, 1), 32), mesh_chips=1
    )
    cfgN = ServeConfig.from_env(max_batch=cfg1.max_batch, mesh_chips=chips)
    trees = build_trees(args.requests, serve_depth)
    direct_roots = [merkleize_subtree_device(t, serve_depth) for t in trees]
    warm = [("merkle_many", b, serve_depth) for b in cfg1.buckets]
    if mesh is not None:
        # signed keys only for the flush sizes the service will actually
        # shard (the mesh crossover keeps toy flushes single-device)
        warm += [
            ("merkle_many", pad, serve_depth, sig)
            for pad in sorted(
                {
                    serve_buckets.mesh_batch_bucket(n, shards, cfgN.buckets)
                    for n in range(1, cfgN.max_batch + 1)
                    if n >= mesh_ops.min_items()
                    and serve_buckets.mesh_dispatch_worthwhile(1 << serve_depth, n)
                }
            )
        ]
    if args.mesh_pairing or platform != "cpu":
        # the pairing section's verify_many pays the batched G1 many-sum
        # compile under the device bls backend — warm its exact
        # many_sum_shape keys (unsigned + signed) or those dispatches
        # would land AFTER the compile snapshot and fail the gate (a
        # parse-rejected item can shrink the live count across a pow2
        # boundary, so the n-1 shapes are warmed too)
        from eth_consensus_specs_tpu.ops.bls_batch import _use_device
        from eth_consensus_specs_tpu.ops.g1_msm import many_sum_shape

        if _use_device():
            n_p = max(args.requests // 8, 8)
            pair_shapes = {many_sum_shape(n, args.committee, 1) for n in (n_p, n_p - 1)}
            warm += [("bls_msm", *shape) for shape in sorted(pair_shapes)]
            if mesh is not None:
                mesh_shapes = {
                    many_sum_shape(n, args.committee, shards) for n in (n_p, n_p - 1)
                }
                warm += [("bls_msm", *shape, sig) for shape in sorted(mesh_shapes)]
    serve_buckets.precompile(warm, chips=chips)
    compiles_after_warmup = obs.snapshot()["counters"].get("serve.compiles", 0)

    load_htr = [("htr", t) for t in trees]
    svc1 = serve.VerifyService(cfg1, name="mesh1")
    s1_wall, got1, _ = closed_loop(svc1, load_htr, args.submitters)
    svc1.close()
    svcN = serve.VerifyService(cfgN, name=f"mesh{chips}")
    sN_wall, gotN, _ = closed_loop(svcN, load_htr, args.submitters)
    svcN.close()
    if got1 != direct_roots:
        failures.append("merkle parity: 1-chip service roots != direct ops roots")
    if gotN != direct_roots:
        failures.append(f"merkle parity: {chips}-chip service roots != direct ops roots")
    sections["merkle_serve"] = {
        "rps_1chip": round(len(load_htr) / s1_wall, 2),
        "rps_nchip": round(len(load_htr) / sN_wall, 2),
        "speedup": round(s1_wall / sN_wall, 3),
        "parity": got1 == direct_roots and gotN == direct_roots,
    }

    # --- merkle kernel scaling: bucket-sized trees, direct dispatch -----
    # The serve smoke runs toy depths for the parity/compile gates; the
    # SCALING measurement needs real bucket sizes (a depth-6 tree is 64
    # hashes — pure dispatch overhead, which an 8-shard mesh can only
    # lose on). Depth 10-12 x 64 trees is the beacon-state subtree
    # regime the sharded path exists for.
    from eth_consensus_specs_tpu.ops.merkle import merkleize_many_device

    rng = np.random.default_rng(7)
    scale_depth = 10 if args.smoke else 12
    scale_b = 64
    big = [
        rng.integers(0, 256, size=(1 << scale_depth, 32)).astype(np.uint8)
        for _ in range(scale_b)
    ]
    roots_1 = merkleize_many_device(big, scale_depth, pad_batch=scale_b)
    roots_n = merkleize_many_device(big, scale_depth, pad_batch=scale_b, mesh=mesh)
    if roots_1 != roots_n:
        failures.append("merkle parity: sharded kernel roots != single-device roots")
    t1 = _timed_reps(
        lambda: merkleize_many_device(big, scale_depth, pad_batch=scale_b), reps
    )
    tn = _timed_reps(
        lambda: merkleize_many_device(big, scale_depth, pad_batch=scale_b, mesh=mesh),
        reps,
    )
    speedup = t1 / tn
    sections["merkle"] = {
        "depth": scale_depth,
        "trees": scale_b,
        "rps_1chip": round(scale_b / t1, 2),
        "rps_nchip": round(scale_b / tn, 2),
        "speedup": round(speedup, 3),
        "scaling_factor": round(speedup / effective, 3),
        "parity": roots_1 == roots_n,
    }

    # --- G1 MSM: direct kernel loop, batched many-sum + scalar MSM ------
    # End-to-end walls include the host limb packing both paths share
    # (the service overlaps that prep with dispatch, a kernel loop
    # cannot), so this section's factor understates the device scaling —
    # reported, and gated only through best-of-kernels.
    G = g1_generator()
    lanes = 32 if args.smoke else 64
    items = 32 if args.smoke else 64
    lists = [
        [G.mul(1 + ((7 * i + j) % 961)) for j in range(lanes)] for i in range(items)
    ]
    per_item = [sum_g1_device(pts) for pts in lists]
    sums_1 = sum_g1_many_device(lists)
    sums_n = sum_g1_many_device(lists, mesh=mesh)
    if not (sums_1 == per_item and sums_n == per_item):
        failures.append("msm parity: sharded/batched committee sums diverge")
    t1 = _timed_reps(lambda: sum_g1_many_device(lists), reps)
    tn = _timed_reps(lambda: sum_g1_many_device(lists, mesh=mesh), reps)
    msm_speedup = t1 / tn
    sections["msm"] = {
        "items": items,
        "lanes": lanes,
        "rps_1chip": round(items / t1, 2),
        "rps_nchip": round(items / tn, 2),
        "speedup": round(msm_speedup, 3),
        "scaling_factor": round(msm_speedup / effective, 3),
        "parity": sums_1 == per_item and sums_n == per_item,
    }
    if not args.smoke:
        # scalar-MSM parity (the 256-bit double-and-add lanes + the
        # cross-shard Jacobian reduction); compile-heavy, full mode only
        pts = [G.mul(i + 3) for i in range(lanes)]
        ks = [(1 << 62) + 977 * i for i in range(lanes)]
        if not (msm_g1_device(pts, ks, mesh=mesh) == msm_g1_device(pts, ks) == msm_g1(pts, ks)):
            failures.append("msm parity: sharded scalar MSM != single-device != host")

    # --- RLC pairing: device Miller chunks sharded over the mesh --------
    # The Miller scan's one-time XLA:CPU compile is minutes — the virtual
    # CPU mesh runs it only on request (--mesh-pairing); accelerator
    # backends always do. Bit-parity incl. the bisection invalid-item
    # path is covered on the CPU mesh by tests/test_mesh_ops.py.
    if args.mesh_pairing or platform != "cpu":
        os.environ["ETH_SPECS_TPU_DEVICE_PAIRING"] = "1"
        items_p = build_bls_items(max(args.requests // 8, 8), args.committee, 4)
        v1 = bls_batch.verify_many(items_p)
        vn = bls_batch.verify_many(items_p, mesh=mesh)
        if v1 != vn:
            failures.append("pairing parity: sharded verify_many verdicts diverge")
        tp1 = _timed_reps(lambda: bls_batch.verify_many(items_p), 1)
        tpn = _timed_reps(lambda: bls_batch.verify_many(items_p, mesh=mesh), 1)
        p_speedup = tp1 / tpn
        sections["pairing"] = {
            "items": len(items_p),
            "speedup": round(p_speedup, 3),
            "scaling_factor": round(p_speedup / effective, 3),
            "parity": v1 == vn,
        }
    else:
        sections["pairing"] = {"skipped": "cpu Miller compile is minutes; "
                               "run with --mesh-pairing to include it"}

    # --- gates -----------------------------------------------------------
    snap = obs.snapshot()
    counters = snap["counters"]
    extra = counters.get("serve.compiles", 0) - compiles_after_warmup
    if extra > 0:
        failures.append(
            f"{extra} compiles AFTER the mesh-aware warmup replay "
            "(a shape escaped the mesh buckets or the signature)"
        )
    obs.count("serve.compiles_after_warmup", max(extra, 0))
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")
    factors = [
        s["scaling_factor"] for s in sections.values() if "scaling_factor" in s
    ]
    best = max(factors) if factors else 0.0
    if best < args.scaling_min:
        failures.append(
            f"best per-effective-chip scaling {best} < {args.scaling_min} "
            f"(chips={chips}, effective={effective}, platform={platform})"
        )
    snap = obs.snapshot()

    report = {
        "mode": "mesh-smoke" if args.smoke else "mesh",
        "platform": platform,
        "requests": args.requests,
        "submitters": args.submitters,
        "mesh": {
            "chips": chips,
            "devices": len(devices),
            "shards": shards,
            "signature": sig,
            "effective_parallelism": effective,
            "chip_scaling": best,
            "merkle_scaling": sections["merkle"]["scaling_factor"],
            "msm_scaling": sections["msm"]["scaling_factor"],
        },
        "sections": sections,
        "compiles": counters.get("serve.compiles", 0),
        "compiles_after_warmup": max(extra, 0),
        "mesh_dispatches": counters.get("mesh.dispatches", 0),
        "watchdog": snap["watchdog"],
        "scaling_min": args.scaling_min,
    }
    if args.warmup_out:
        report["warmup_artifact"] = args.warmup_out
        report["warmup_keys"] = serve_buckets.write_warmup(args.warmup_out)
    finish_report(report, failures, args.out, "serve_bench.mesh_failure", snap)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="small CI run, skip the 2x gate")
    ap.add_argument("--submitters", type=int, default=64)
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--tree-depth", type=int, default=10)
    ap.add_argument("--committee", type=int, default=3)
    ap.add_argument("--out", default="BENCH_SERVE.json")
    ap.add_argument("--replicas", type=int, default=0,
                    help="run the load through an R-replica front door")
    ap.add_argument("--chaos", action="store_true",
                    help="with --replicas: SIGKILL one replica mid-load")
    ap.add_argument("--warmup-out", default=None,
                    help="write the shippable warmup artifact here")
    ap.add_argument("--chips", type=int,
                    default=int(os.environ.get("ETH_SPECS_SERVE_CHIPS", "0") or 0),
                    help="mesh mode: gate chips=1 -> N scaling (virtual CPU "
                         "devices locally, real devices on accelerators)")
    ap.add_argument("--chips-matrix", type=lambda s: tuple(
                        int(x) for x in s.split(",") if x.strip()),
                    default=(),
                    help="with --replicas: the fleet-matrix mode — "
                         "replicas×chips scaling grid plus the heterogeneous "
                         "chaos/autoscale phase (chips cycle, e.g. 1,8)")
    ap.add_argument("--scaling-min", type=float,
                    default=float(os.environ.get("ETH_SPECS_MESH_SCALING_MIN", "0.7")
                                  or 0.7),
                    help="minimum per-effective-chip scaling factor")
    ap.add_argument("--mesh-pairing", action="store_true",
                    help="include the sharded device pairing on the CPU mesh "
                         "(one-time Miller compile is minutes)")
    ap.add_argument("--canary-ms", type=float, default=150.0,
                    help="known-answer canary interval in ms (0 disables the "
                         "telemetry plane; shapes via ETH_SPECS_CANARY_SHAPES)")
    args = ap.parse_args()
    if args.smoke:
        args.submitters = min(args.submitters, 16)
        args.requests = min(args.requests, 64)
        args.tree_depth = min(args.tree_depth, 6)
    if args.replicas > 0 and args.chips_matrix:
        if args.smoke:
            args.requests = min(args.requests, 48)
        run_fleet_matrix(args)
        return
    if args.chips > 1:
        run_mesh(args)
        return
    if args.replicas > 0:
        run_replicated(args)
        return

    export.maybe_serve_http()  # scrapeable while the bench runs (env-gated)
    # max_batch strictly below the submitter count guarantees full (size-
    # flushed) buckets at steady state instead of racing the deadline
    cfg = ServeConfig.from_env(max_batch=min(max(args.submitters // 2, 1), 32))
    bls_items = build_bls_items(args.requests, args.committee, distinct_msgs=4)
    trees = build_trees(args.requests, args.tree_depth)

    # --- phase 1: sequential per-request direct ops baseline ------------
    bls_batch.batch_verify_aggregates([bls_items[0]])  # warm parse/h2g2 caches
    merkleize_subtree_device(trees[0], args.tree_depth)  # pay the direct compile
    t0 = time.perf_counter()
    direct_bls = [bls_batch.batch_verify_aggregates([it]) for it in bls_items]
    seq_bls_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    direct_roots = [merkleize_subtree_device(t, args.tree_depth) for t in trees]
    seq_htr_s = time.perf_counter() - t0

    # --- phase 2: service + bucket warmup -------------------------------
    svc = serve.VerifyService(cfg, name="bench")
    warm_keys = [("merkle_many", b, args.tree_depth) for b in cfg.buckets]
    if args.canary_ms > 0:
        # the canary stream's own compile shapes (flush-group size is
        # always 1) — warmed here so injecting canaries through the
        # load phase cannot trip the zero-cold-compile gate below
        warm_keys += canary_mod.warm_keys()
    svc.precompile(warm_keys)

    # --- state_root mini-phase (warm): one post-epoch state root through
    # the service. Exercises the state_root devprof seam end to end
    # (device.exec_ms.state_root) and — via synthetic_static's
    # creation-site registration — puts a genuinely resident device tree
    # on the HBM ledger for the waterfall section's residency gate. Runs
    # BEFORE the compile snapshot: its first dispatch is a legitimate
    # warm-phase compile.
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_columns import JustificationState
    from eth_consensus_specs_tpu.ops.state_root import (
        post_epoch_state_root,
        synthetic_static,
    )

    spec_min = get_spec("altair", "minimal")
    sr_arrays, sr_meta = synthetic_static(spec_min, 64, seed=11)
    sr_rng = np.random.default_rng(11)
    sr_bal = jnp.asarray(sr_rng.integers(16, 64, size=64, dtype=np.uint64) * 10**9)
    sr_eff = jnp.asarray(np.full(64, 32 * 10**9, np.uint64))
    sr_inact = jnp.asarray(sr_rng.integers(0, 4, size=64, dtype=np.uint64))
    zero_root = jnp.zeros(32, jnp.uint8)
    sr_just = JustificationState(
        current_epoch=jnp.uint64(5),
        justification_bits=jnp.asarray([True, False, True, False]),
        prev_justified_epoch=jnp.uint64(3),
        prev_justified_root=zero_root,
        cur_justified_epoch=jnp.uint64(4),
        cur_justified_root=zero_root,
        finalized_epoch=jnp.uint64(3),
        finalized_root=zero_root,
        block_root_prev=zero_root,
        block_root_cur=zero_root,
        slashings_sum=jnp.uint64(0),
    )
    direct_sr = np.asarray(
        post_epoch_state_root(sr_arrays, sr_meta, sr_bal, sr_eff, sr_inact, sr_just)
    )
    got_sr = np.asarray(
        svc.submit_state_root(
            sr_arrays, sr_meta, sr_bal, sr_eff, sr_inact, sr_just
        ).result(timeout=120)
    )
    sr_parity = bool(np.array_equal(got_sr, direct_sr))

    compiles_after_warmup = obs.snapshot()["counters"].get("serve.compiles", 0)

    # continuous telemetry plane: known-answer canaries + structural
    # anomaly detectors ride the whole trickle/load run. Starts AFTER
    # the compile snapshot (its shapes are pre-warmed above); stopped
    # and drained before svc.close() so every canary resolves
    tele = None
    if args.canary_ms > 0:
        tele = BenchTelemetry(svc, source="service",
                              canary_ms=args.canary_ms).start()

    # --- phase 3: trickle (deadline flushes) ----------------------------
    for it in bls_items[:3]:
        assert svc.submit_bls_aggregate(*it).result() == bls_batch.batch_verify_aggregates([it])
        time.sleep(cfg.max_wait_s * 2)

    # --- phase 4: closed-loop load --------------------------------------
    load_bls = [("bls", it) for it in bls_items]
    svc_bls_s, got_bls, lat_bls = closed_loop(svc, load_bls, args.submitters)
    load_htr = [("htr", t) for t in trees]
    svc_htr_s, got_roots, lat_htr = closed_loop(svc, load_htr, args.submitters)
    if tele is not None:
        tele.stop()
    svc.close()

    # --- phase 5: gates --------------------------------------------------
    failures = []
    if got_bls != direct_bls:
        failures.append("BLS parity: service results != direct ops results")
    if got_roots != direct_roots:
        failures.append("HTR parity: service roots != direct ops roots")
    if not sr_parity:
        failures.append("state_root parity: service root != direct ops root")
    snap = obs.snapshot()
    counters = snap["counters"]
    if snap["watchdog"]["divergences"] != 0:
        failures.append(f"watchdog divergences: {snap['watchdog']}")
    if counters.get("serve.flush.deadline", 0) < 1:
        failures.append("no deadline flush observed (trickle phase)")
    if counters.get("serve.flush.size", 0) < 1:
        failures.append("no size flush observed (load phase)")
    extra = counters.get("serve.compiles", 0) - compiles_after_warmup
    if extra > 0:
        failures.append(f"{extra} compiles AFTER warmup (shape escaped the buckets)")
    # every first-dispatch compile must have left its wall time in the
    # serve.compile_ms histogram — count in lockstep with the counter
    compile_hist = snap["histograms"].get("serve.compile_ms", {})
    if compile_hist.get("count", 0) != counters.get("serve.compiles", 0):
        failures.append(
            f"serve.compile_ms count {compile_hist.get('count', 0)} != "
            f"serve.compiles {counters.get('serve.compiles', 0)} "
            "(a first dispatch escaped the timed wrapper)"
        )
    # feed the declarative SLO set (obs/slo.py): the counter is the
    # snapshot-visible form of the "zero compiles after warmup" contract
    obs.count("serve.compiles_after_warmup", max(extra, 0))
    snap = obs.snapshot()
    counters = snap["counters"]
    slo_results = slo.evaluate(snap)
    for r in slo_results:
        if not r.ok:
            failures.append(
                f"SLO {r.name}: observed {r.observed} > bound {r.bound} ({r.detail})"
            )
    if tele is not None:
        # the telemetry contract on a clean run: every canary resolved
        # with the oracle's exact bits, zero structural anomaly fires
        tele.gate(failures)

    # run-level wait quantiles: bucket quantiles over EVERY wait of the
    # run (the old 4096-sample reservoir is gone)
    wait_hist = snap["histograms"].get("serve.wait_ms", {})

    speedup_bls = (args.requests / svc_bls_s) / (args.requests / seq_bls_s)
    speedup_htr = (args.requests / svc_htr_s) / (args.requests / seq_htr_s)
    if not args.smoke and speedup_bls < 2.0:
        failures.append(f"BLS speedup {speedup_bls:.2f}x < 2x over sequential ops calls")

    report = {
        "mode": "smoke" if args.smoke else "full",
        "submitters": args.submitters,
        "requests": args.requests,
        "bls": {
            "sequential_rps": round(args.requests / seq_bls_s, 2),
            "service_rps": round(args.requests / svc_bls_s, 2),
            "speedup": round(speedup_bls, 3),
            "latency_ms_histogram": latency_histogram(lat_bls),
        },
        "htr": {
            "tree_depth": args.tree_depth,
            "sequential_rps": round(args.requests / seq_htr_s, 2),
            "service_rps": round(args.requests / svc_htr_s, 2),
            "speedup": round(speedup_htr, 3),
            "latency_ms_histogram": latency_histogram(lat_htr),
        },
        "flushes": {
            r: counters.get(f"serve.flush.{r}", 0)
            for r in ("size", "deadline", "pressure", "close")
        },
        "compiles": counters.get("serve.compiles", 0),
        "compiles_after_warmup": max(extra, 0),
        # first-dispatch compile walls (p50/p99 from the mergeable
        # histogram; count == compiles is gated above)
        "compile_ms": {
            "count": compile_hist.get("count", 0),
            "p50": compile_hist.get("p50"),
            "p99": compile_hist.get("p99"),
        },
        "buckets": list(cfg.buckets),
        "rejected": counters.get("serve.rejected", 0),
        "watchdog": snap["watchdog"],
        "queue_depth_max": snap["gauges"].get("serve.queue_depth", {}).get("max", 0),
        "wait_ms": {
            "samples": wait_hist.get("count", 0),
            "p50": wait_hist.get("p50"),
            "p99": wait_hist.get("p99"),
        },
        "slo": slo.report(slo_results),
        "waterfall": waterfall_section(failures, args.out),
    }
    if tele is not None:
        report["telemetry"] = tele.section()

    if args.warmup_out:
        # the shippable warmup artifact: every shape this run compiled,
        # for CI to upload and later boots (replicas!) to replay
        report["warmup_artifact"] = args.warmup_out
        report["warmup_keys"] = serve_buckets.write_warmup(args.warmup_out)
    finish_report(report, failures, args.out, "serve_bench.failure", snap)


if __name__ == "__main__":
    main()
