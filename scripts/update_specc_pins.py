#!/usr/bin/env python
"""Regenerate specc/pins.json — the sha256 manifest of every reference file
the spec-oracle compiler is allowed to exec code from.

Run after auditing a reference-tree change. The compiler refuses unpinned
or hash-mismatching files (specc/compiler.py:_read_pinned)."""

import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from eth_consensus_specs_tpu.specc import compiler as c


def main() -> None:
    paths: set[str] = set()
    for fork in c.DOC_SETS:
        names = list(c.DOC_SETS[fork]) + list(c.FC_DOCS.get(fork, []))
        for name in names:
            p = os.path.join(c.REFERENCE_SPECS, "specs", fork, name)
            if os.path.exists(p):
                paths.add(p)
    for preset in ("minimal", "mainnet"):
        ts = os.path.join(
            c.REFERENCE_SPECS, "presets", preset, "trusted_setups", "trusted_setup_4096.json"
        )
        if os.path.exists(ts):
            paths.add(ts)
    pins = {}
    for p in sorted(paths):
        with open(p, "rb") as fh:
            pins[os.path.relpath(p, c.REFERENCE_SPECS)] = hashlib.sha256(fh.read()).hexdigest()
    with open(c._PINS_PATH, "w") as fh:
        json.dump(pins, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(f"pinned {len(pins)} files -> {c._PINS_PATH}")


if __name__ == "__main__":
    main()
