"""CI chaos smoke for the fault-tolerant generator (checks.yml `chaos-gen`).

Runs a small pool generation twice — once clean, once with a worker
SIGKILL and a stall-past-deadline injected (ETH_SPECS_FAULT) — and
asserts the recovery contract:

  * written == the clean run's written count (nothing silently lost);
  * gen.workers_replaced > 0 (the kill actually happened and was healed);
  * fault-injected part digests == clean part digests (byte-identical
    vectors, from the run manifests alone);
  * zero torn files: every emitted `.ssz_snappy` snappy-decodes.

Exit code 0 on success; prints a one-line JSON summary.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_gen(out_dir: str, fault_spec: str, extra_args: tuple = ()) -> dict:
    env = dict(os.environ, ETH_SPECS_FAULT=fault_spec, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable, "-m", "eth_consensus_specs_tpu.gen",
        "--output", out_dir,
        "--presets", "minimal", "--forks", "phase0", "--runners", "operations",
        "--workers", "2",
        *extra_args,
    ]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=1800, cwd=REPO)
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr, file=sys.stderr)
        raise SystemExit(f"generator exited rc={proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    sys.path.insert(0, REPO)
    from eth_consensus_specs_tpu.gen.manifest import load_manifest, manifest_path
    from eth_consensus_specs_tpu.gen.snappy_codec import frame_decompress

    base = tempfile.mkdtemp(prefix="chaos_gen_")
    clean_dir = os.path.join(base, "clean")
    chaos_dir = os.path.join(base, "chaos")

    clean = run_gen(clean_dir, "")
    assert clean["failed"] == 0, f"clean run failed cases: {clean}"
    assert clean["written"] > 0, f"clean run wrote nothing: {clean}"

    kill_latch = os.path.join(base, "kill.latch")
    stall_latch = os.path.join(base, "stall.latch")
    fault_spec = (
        f"gen.case:kill:nth=3:latch={kill_latch};"
        f"gen.case:stall:nth=5:delay=60:latch={stall_latch}"
    )
    chaos = run_gen(
        chaos_dir, fault_spec, extra_args=("--case-timeout", "20", "--case-retries", "3")
    )

    assert chaos["written"] == clean["written"], f"lost vectors: {clean} vs {chaos}"
    assert chaos["failed"] == 0, f"unrecovered failures: {chaos}"
    counters = chaos["counters"]
    assert counters.get("gen.workers_replaced", 0) > 0, f"no worker was replaced: {counters}"
    assert counters.get("gen.cases_retried", 0) > 0, f"no case was retried: {counters}"

    digests = lambda d: {  # noqa: E731
        "/".join(k): r["parts"] for k, r in load_manifest(manifest_path(d)).items()
    }
    clean_digests, chaos_digests = digests(clean_dir), digests(chaos_dir)
    assert clean_digests == chaos_digests, "fault-injected digests differ from clean run"

    torn_checked = 0
    for root, _dirs, files in os.walk(chaos_dir):
        for name in files:
            if name.endswith(".ssz_snappy"):
                with open(os.path.join(root, name), "rb") as f:
                    frame_decompress(f.read())  # raises on a torn file
                torn_checked += 1
            assert not name.endswith(".tmp"), f"stray tmp file: {os.path.join(root, name)}"
    assert torn_checked > 0, "no parts to verify"

    print(json.dumps({
        "written": chaos["written"],
        "parts_decoded": torn_checked,
        "workers_replaced": counters.get("gen.workers_replaced"),
        "cases_retried": counters.get("gen.cases_retried"),
        "cases_timeout": counters.get("gen.cases_timeout", 0),
    }))


if __name__ == "__main__":
    main()
