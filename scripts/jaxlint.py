#!/usr/bin/env python
"""jaxlint CLI — trace-level static analysis of the registered kernels.

Usage:
    python scripts/jaxlint.py                    # every family, 8 virtual chips
    python scripts/jaxlint.py --chips 1          # single-device variants only
    python scripts/jaxlint.py --json r.json      # machine-readable report
    python scripts/jaxlint.py --rules x64-drift,donation-audit
    python scripts/jaxlint.py --only merkle_many,bls_msm
    python scripts/jaxlint.py --write-baseline

Abstract evaluation only (jax.make_jaxpr) — nothing executes, nothing
compiles. ``--chips N`` forces N virtual CPU devices BEFORE jax
initializes (the serve_bench idiom) so the mesh-sharded kernel variants
are analyzable on any dev box; on a real accelerator host the live
devices are used as-is. Defaults to 8 so `make jaxlint` always covers
the mesh variants.

Exit codes (shared with speclint via analysis/cli.py): 0 clean,
1 usage/ratchet error, 2 non-baselined findings. The baseline
(jaxlint_baseline.json) ships EMPTY and may only shrink; CI additionally
asserts transfer-free/collective-audit findings are NEVER baselined.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
# the --chips pre-parse must run before the first jax import (XLA reads
# XLA_FLAGS once, at backend init); ONE copy shared with serve_bench.py
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prejax import force_virtual_chips  # noqa: E402


def main() -> int:
    # no env fallback: the analysis grid is a CLI decision, and the
    # argparse default below must agree with what was forced here
    chips = force_virtual_chips(default=8, env_var=None)

    from eth_consensus_specs_tpu.analysis import cli, jaxlint

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--chips",
        type=int,
        default=8,
        help="virtual device count for the mesh variants (forced before "
        "jax init on cpu; 1 = single-device variants only; default 8)",
    )
    ap.add_argument(
        "--only", help="comma-separated kernel-family subset (default: all)"
    )
    cli.add_common_args(
        ap,
        default_baseline=os.path.join(REPO_ROOT, "jaxlint_baseline.json"),
        all_rules=jaxlint.ALL_RULES,
    )
    args = ap.parse_args()

    try:
        rules = cli.parse_rules(args, jaxlint.ALL_RULES)
    except ValueError as exc:
        print(exc)
        return 1
    only = (
        {k.strip() for k in args.only.split(",") if k.strip()} if args.only else None
    )
    if only:
        from eth_consensus_specs_tpu.analysis import kernels

        unknown = only - set(kernels.by_name())
        if unknown:
            # a silently-ignored family name would let the mesh-smoke CI
            # gate pass green while analyzing nothing — fail loudly, like
            # --rules does
            print(
                f"unknown kernel families: {sorted(unknown)} "
                f"(have {sorted(kernels.by_name())})"
            )
            return 1

    from eth_consensus_specs_tpu.parallel.mesh_ops import mesh_signature, serve_mesh

    mesh = serve_mesh(chips) if chips > 1 else None
    findings, stats = jaxlint.analyze(mesh=mesh, rules=rules, only=only)
    stats["mesh"] = mesh_signature(mesh)
    print(
        f"jaxlint: {stats['kernels']} kernel families, {stats['variants']} "
        f"variants ({stats['mesh_variants']} mesh @ {stats['mesh'] or 'none'}), "
        f"{stats['keys']} bucket keys checked"
    )
    return cli.finish(args, findings, tool="jaxlint", extra=stats)


if __name__ == "__main__":
    sys.exit(main())
