"""Driver benchmark — ONE JSON line on stdout.

Primary metric: SSZ merkleization throughput (device tree kernel,
ops/merkle.py) over a 2**21-chunk leaf level — the size class of a
~1M-validator registry's balance/leaf levels, the reference's #1 hot spot
(hash_tree_root(state) twice per slot; reference:
specs/phase0/beacon-chain.md:1383-1393 via utils/hash_function.py).

Baseline: the reference's exact host path — one hashlib.sha256 call per
tree node (reference: utils/merkle_minimal.py:47-91 hashes pairwise per
level) — measured on a 2**16 subtree and scaled per-hash (hashlib cost is
size-independent per 64B message).

vs_baseline is the speedup of the device tree over that host loop (>1 is
faster than the reference path). Secondary numbers go to stderr.
"""

from __future__ import annotations

import hashlib
import json
import sys
import time

import numpy as np


def host_hashes_per_sec(n_pairs: int = 1 << 16) -> float:
    rng = np.random.default_rng(0)
    pairs = rng.integers(0, 256, size=(n_pairs, 64), dtype=np.uint8)
    blobs = [p.tobytes() for p in pairs]
    sha = hashlib.sha256
    t0 = time.perf_counter()
    for b in blobs:
        sha(b).digest()
    dt = time.perf_counter() - t0
    return n_pairs / dt


def device_tree_hashes_per_sec(depth: int = 21, repeats: int = 3) -> tuple[float, float]:
    import jax
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused

    rng = np.random.default_rng(1)
    leaves = jnp.asarray(
        rng.integers(0, 2**32, size=(1 << depth, 8), dtype=np.uint64).astype(np.uint32)
    )
    leaves = jax.device_put(leaves)
    # warmup/compile
    jax.block_until_ready(_tree_root_fused(leaves, depth))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(_tree_root_fused(leaves, depth))
        best = min(best, time.perf_counter() - t0)
    n_hashes = (1 << depth) - 1  # logical tree nodes
    return n_hashes / best, best


def bench_epoch_accounting(n_validators: int = 1_000_000) -> float:
    """Secondary: fused 1M-validator accounting epoch, seconds/epoch."""
    import jax

    import __graft_entry__ as graft
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_columns import EpochParams, epoch_accounting

    params = EpochParams.from_spec(get_spec("phase0", "mainnet"))
    cols, just = graft._example_inputs(n_validators)
    cols = jax.device_put(cols)
    just = jax.device_put(just)
    jax.block_until_ready(epoch_accounting(params, cols, just))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(epoch_accounting(params, cols, just))
        best = min(best, time.perf_counter() - t0)
    return best


def _probe_accelerator(retries: int = 2) -> bool:
    """Check in a subprocess whether the accelerator backend can initialize.

    A failed in-process init can leave jax's backend registry poisoned, so
    the probe must not run in this interpreter. Retries cover transient
    tunnel hiccups."""
    import subprocess

    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.default_backend())"],
                capture_output=True,
                timeout=180,
                text=True,
            )
            backend = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
            if out.returncode == 0 and backend and backend != "cpu":
                return True
            print(
                f"[bench] accelerator probe {attempt+1}/{retries}: rc={out.returncode} "
                f"backend={backend!r}",
                file=sys.stderr,
            )
        except Exception as e:
            print(f"[bench] accelerator probe {attempt+1}/{retries} failed: {e}", file=sys.stderr)
        time.sleep(2)
    return False


def main() -> None:
    import os

    error = None
    dev_hps = 0.0
    host_hps = host_hashes_per_sec()
    print(f"[bench] host hashlib: {host_hps/1e6:.2f} Mhash/s", file=sys.stderr)

    on_accelerator = _probe_accelerator()
    if not on_accelerator:
        # Backend is gone — fall back to XLA:CPU so the benchmark still
        # produces a real measured number instead of a crash. Must happen
        # before the first in-process backend init; the sitecustomize pins
        # the platform programmatically, so force the config too.
        error = "accelerator backend unavailable; measured on XLA:CPU fallback"
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from eth_consensus_specs_tpu.utils.cache import enable_persistent_cache

        enable_persistent_cache()

    try:
        dev_hps, tree_s = device_tree_hashes_per_sec()
        print(
            f"[bench] device tree (2^21 chunks): {dev_hps/1e9:.3f} Ghash/s, "
            f"{tree_s*1e3:.1f} ms/tree",
            file=sys.stderr,
        )
    except Exception as e:
        error = f"device tree bench failed: {e!r}"
        print(f"[bench] {error}", file=sys.stderr)

    try:
        epoch_s = bench_epoch_accounting()
        print(f"[bench] fused epoch @1M validators: {epoch_s*1e3:.1f} ms", file=sys.stderr)
    except Exception as e:  # secondary metric must not sink the primary
        print(f"[bench] epoch accounting skipped: {e}", file=sys.stderr)

    result = {
        "metric": "ssz_merkle_tree_hashes_per_sec",
        "value": round(dev_hps, 0),
        "unit": "hash/s",
        "vs_baseline": round(dev_hps / host_hps, 2) if host_hps else 0.0,
    }
    if error is not None:
        result["error"] = error
    print(json.dumps(result))


if __name__ == "__main__":
    main()
