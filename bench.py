"""Driver benchmark — ONE JSON line on stdout.

Primary metric: SSZ merkleization throughput (device tree kernel,
ops/merkle.py) over a 2**21-chunk leaf level — the size class of a
~1M-validator registry's balance/leaf levels, the reference's #1 hot spot
(hash_tree_root(state) twice per slot; reference:
specs/phase0/beacon-chain.md:1383-1393 via utils/hash_function.py).

Baseline: the reference's exact host path — one hashlib.sha256 call per
tree node (reference: utils/merkle_minimal.py:47-91 hashes pairwise per
level) — measured on a 2**16 subtree and scaled per-hash (hashlib cost is
size-independent per 64B message).

vs_baseline is the speedup of the device tree over that host loop (>1 is
faster than the reference path). Secondary numbers go to stderr and into
the JSON payload's "secondary" object.

Methodology (round-3 fix): every device section uses CHAINED-DEPENDENCY
timing — K iterations inside one jit where iteration i+1 consumes
iteration i's output — so the number is sustained throughput; a lone
dispatch's apparently-instant completion (round-2 verdict: ~7x inflation)
cannot leak in.
"""

from __future__ import annotations

import hashlib
import json
import sys
import time

import numpy as np


def host_hashes_per_sec(n_pairs: int = 1 << 16) -> float:
    rng = np.random.default_rng(0)
    pairs = rng.integers(0, 256, size=(n_pairs, 64), dtype=np.uint8)
    blobs = [p.tobytes() for p in pairs]
    sha = hashlib.sha256
    t0 = time.perf_counter()
    for b in blobs:
        sha(b).digest()
    dt = time.perf_counter() - t0
    return n_pairs / dt


def device_tree_hashes_per_sec(
    depth: int = 21, chain: int = 16, repeats: int = 3
) -> tuple[float, float]:
    """Sustained per-tree time via CHAINED-DEPENDENCY timing: `chain` trees
    run inside one jit, each tree's leaves XORed with the previous tree's
    root, so no tree can start before the previous one finishes and a lone
    dispatch's apparent completion cannot deflate the number (round-2
    verdict: single-call block_until_ready under-measured ~7x on this
    platform).  Inputs are re-salted between repeats to defeat any
    (executable, input) result caching."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused

    rng = np.random.default_rng(1)
    base = jax.device_put(
        jnp.asarray(
            rng.integers(0, 2**32, size=(1 << depth, 8), dtype=np.uint64).astype(np.uint32)
        )
    )

    @jax.jit
    def run(lv, acc0):
        def body(_, carry):
            lv, acc = carry
            fresh = lv ^ acc  # (N, 8) ^ (8,): every leaf depends on the prior root
            return lv, _tree_root_fused(fresh, depth)

        return lax.fori_loop(0, chain, body, (lv, acc0))[1]

    warm = jnp.zeros(8, jnp.uint32)
    jax.block_until_ready(run(base, warm))  # compile + warm
    best = float("inf")
    for i in range(repeats):
        salt = jnp.full(8, np.uint32(i + 1), jnp.uint32)
        t0 = time.perf_counter()
        jax.block_until_ready(run(base, salt))
        best = min(best, time.perf_counter() - t0)
    per_tree = best / chain
    n_hashes = (1 << depth) - 1  # logical tree nodes
    return n_hashes / per_tree, per_tree


def bench_epoch_accounting(n_validators: int = 1_000_000, chain: int = 8) -> float:
    """Secondary: fused 1M-validator accounting epoch, sustained
    seconds/epoch via chained-dependency timing (each epoch consumes the
    previous epoch's balances inside one jit)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    import __graft_entry__ as graft
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_columns import EpochParams, epoch_accounting

    params = EpochParams.from_spec(get_spec("phase0", "mainnet"))
    cols, just = graft._example_inputs(n_validators)
    cols = jax.device_put(cols)
    just = jax.device_put(just)

    @jax.jit
    def run(cols, just):
        def body(_, c):
            res = epoch_accounting(params, c, just)
            return c._replace(
                balance=res.balance, effective_balance=res.effective_balance
            )

        return lax.fori_loop(0, chain, body, cols).balance

    salt_fn = jax.jit(lambda c, s: c._replace(balance=c.balance + s))
    jax.block_until_ready(run(cols, just))
    best = float("inf")
    for i in range(3):
        fresh = salt_fn(cols, jnp.uint64(i + 1))  # defeat result caching
        jax.block_until_ready(fresh)
        t0 = time.perf_counter()
        jax.block_until_ready(run(fresh, just))
        best = min(best, time.perf_counter() - t0)
    return best / chain


def bench_device_resident_epochs(
    n_validators: int = 1 << 20, epochs: int = 8
) -> tuple[float, float]:
    """The BASELINE.json stepping stone: accounting epoch + balance-column
    SSZ subtree root at ~1M validators, state DEVICE-RESIDENT across
    epochs through the PUBLIC framework API (parallel/resident.py
    run_epochs — not bench-local code).  Chained-dependency by
    construction: each epoch consumes the previous epoch's balances and
    the per-epoch root xor-chains into the carry.  Returns
    (seconds_per_epoch_with_root, seconds_total)."""
    import jax
    import jax.numpy as jnp

    import __graft_entry__ as graft
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.parallel import resident

    spec = get_spec("deneb", "mainnet")
    cols, just = graft._example_altair_inputs(n_validators)
    cols = jax.device_put(cols)
    just = jax.device_put(just)

    salt_fn = jax.jit(lambda c, s: c._replace(balance=c.balance + s))
    jax.block_until_ready(
        resident.run_epochs(spec, cols, just, epochs).root_acc
    )  # compile + warm
    best = float("inf")
    for i in range(3):
        fresh = salt_fn(cols, jnp.uint64(i + 1))  # defeat result caching
        jax.block_until_ready(fresh)
        t0 = time.perf_counter()
        jax.block_until_ready(resident.run_epochs(spec, fresh, just, epochs).root_acc)
        best = min(best, time.perf_counter() - t0)
    return best / epochs, best


def bench_das_fft(batch: int = 16, n: int = 8192, chain: int = 8) -> tuple[float, float]:
    """Secondary: batched 8192-point BLS-scalar-field FFT (the DAS erasure
    recovery kernel, ops/fr_fft.py), chained-dependency timed: K rounds
    inside one jit, each round re-transforming its own output.  Returns
    (ffts_per_sec, seconds_per_round_of_batch)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from eth_consensus_specs_tpu.crypto.kzg import compute_roots_of_unity
    from eth_consensus_specs_tpu.ops import fr_fft
    from eth_consensus_specs_tpu.ops.fr_fft import FR

    roots = tuple(compute_roots_of_unity(n))
    rev = jnp.asarray(fr_fft._bit_reversal_indices(n))
    twiddles = [jnp.asarray(t) for t in fr_fft._stage_twiddles(roots, n)]

    rng = np.random.default_rng(7)
    vals = FR.ints_to_mont_batch(
        rng.integers(1, 1 << 62, size=(batch, n), dtype=np.int64)
    )

    @jax.jit
    def run(v):
        def body(_, v):
            # the SAME kernel body the DAS path runs (fr_fft.fft_stages),
            # re-transforming its own output for the dependency chain
            return fr_fft.fft_stages(jnp.take(v, rev, axis=1), twiddles, n)

        return lax.fori_loop(0, chain, body, v)

    dev = jax.device_put(jnp.asarray(vals))
    jax.block_until_ready(run(dev))  # compile + warm
    best = float("inf")
    for i in range(2):
        salted = dev + jnp.uint64(0)  # fresh buffer identity
        t0 = time.perf_counter()
        jax.block_until_ready(run(salted))
        best = min(best, time.perf_counter() - t0)
    per_round = best / chain
    return batch / per_round, per_round


def bench_batch_verify(n_aggregates: int = 16, committee: int = 8) -> tuple[float, float]:
    """Secondary: aggregate-signature batch verification throughput under
    the tpu backend (device G1 MSM for the RLC combine, one host pairing
    per batch). Returns (aggregates_per_sec, seconds_per_batch)."""
    from eth_consensus_specs_tpu.crypto import signature as sig_mod
    from eth_consensus_specs_tpu.ops.bls_batch import batch_verify_aggregates
    from eth_consensus_specs_tpu.utils import bls

    items = []
    sk = 1
    for i in range(n_aggregates):
        msg = i.to_bytes(32, "big")
        group = list(range(sk, sk + committee))
        sk += committee
        pks = [sig_mod.sk_to_pk(k) for k in group]
        sigs = [sig_mod.sign(k, msg) for k in group]
        items.append((pks, msg, sig_mod.aggregate(sigs)))

    bls.use_tpu()
    try:
        if not batch_verify_aggregates(items):  # warm (compiles the MSM)
            raise RuntimeError("batch verification rejected valid signatures")
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            ok = batch_verify_aggregates(items)
            best = min(best, time.perf_counter() - t0)
            if not ok:
                raise RuntimeError("batch verification rejected valid signatures")
    finally:
        bls.use_pyspec()
    return n_aggregates / best, best


def _probe_accelerator(retries: int = 2) -> bool:
    """Check in a subprocess whether the accelerator backend can initialize.

    A failed in-process init can leave jax's backend registry poisoned, so
    the probe must not run in this interpreter. Retries cover transient
    tunnel hiccups."""
    import subprocess

    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", "import jax; print(jax.default_backend())"],
                capture_output=True,
                timeout=120,
                text=True,
            )
            backend = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
            if out.returncode == 0 and backend and backend != "cpu":
                return True
            print(
                f"[bench] accelerator probe {attempt+1}/{retries}: rc={out.returncode} "
                f"backend={backend!r}",
                file=sys.stderr,
            )
        except Exception as e:
            print(f"[bench] accelerator probe {attempt+1}/{retries} failed: {e}", file=sys.stderr)
        time.sleep(2)
    return False


def _run_section(section: str, on_cpu: bool) -> None:
    """Child mode: run one device-bench section, print a JSON fragment."""
    if on_cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from eth_consensus_specs_tpu.utils.cache import enable_persistent_cache

        enable_persistent_cache()

    # CPU fallback exists to produce *a* real measured number when the
    # accelerator is gone — scale the work to what XLA:CPU finishes fast
    if section == "tree":
        depth = 16 if on_cpu else 21
        hps, tree_s = device_tree_hashes_per_sec(depth=depth)
        print(json.dumps({"hps": hps, "tree_s": tree_s, "depth": depth}))
    elif section == "epoch":
        n = 1 << 16 if on_cpu else 1_000_000
        epoch_s = bench_epoch_accounting(n_validators=n)
        print(json.dumps({"epoch_s": epoch_s, "n": n}))
    elif section == "resident":
        n = 1 << 16 if on_cpu else 1 << 20
        epochs = 4 if on_cpu else 8
        per_epoch_s, total_s = bench_device_resident_epochs(n_validators=n, epochs=epochs)
        print(json.dumps({"per_epoch_s": per_epoch_s, "total_s": total_s, "n": n, "epochs": epochs}))
    elif section == "bls":
        n = 4 if on_cpu else 16
        aggs_per_sec, batch_s = bench_batch_verify(n_aggregates=n)
        print(json.dumps({"aggs_per_sec": aggs_per_sec, "batch_s": batch_s, "n": n}))
    elif section == "das":
        batch = 2 if on_cpu else 16
        n = 1024 if on_cpu else 8192
        ffts_per_sec, round_s = bench_das_fft(batch=batch, n=n)
        print(json.dumps({"ffts_per_sec": ffts_per_sec, "round_s": round_s, "batch": batch, "n": n}))
    else:
        raise SystemExit(f"unknown section {section}")


def _section_in_subprocess(section: str, on_cpu: bool, timeout_s: int) -> dict | None:
    """Run a bench section in its own process with a hard timeout — a hung
    device tunnel must never prevent the final JSON line."""
    import subprocess

    cmd = [sys.executable, __file__, "--section", section]
    if on_cpu:
        cmd.append("--cpu")
    try:
        out = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"[bench] section {section}: timed out after {timeout_s}s", file=sys.stderr)
        return None
    sys.stderr.write(out.stderr)
    if out.returncode != 0 or not out.stdout.strip():
        print(f"[bench] section {section}: rc={out.returncode}", file=sys.stderr)
        return None
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except json.JSONDecodeError:
        return None


def main() -> None:
    if "--section" in sys.argv:
        idx = sys.argv.index("--section")
        _run_section(sys.argv[idx + 1], on_cpu="--cpu" in sys.argv)
        return

    error = None
    dev_hps = 0.0
    host_hps = host_hashes_per_sec()
    print(f"[bench] host hashlib: {host_hps/1e6:.2f} Mhash/s", file=sys.stderr)

    on_cpu = not _probe_accelerator()
    if on_cpu:
        error = "accelerator backend unavailable; measured on XLA:CPU fallback"
        print(f"[bench] {error}", file=sys.stderr)

    tree = _section_in_subprocess("tree", on_cpu, timeout_s=480)
    if tree is not None:
        dev_hps, tree_s = tree["hps"], tree["tree_s"]
        print(
            f"[bench] device tree (2^{tree['depth']} chunks): {dev_hps/1e9:.3f} Ghash/s, "
            f"{tree_s*1e3:.1f} ms/tree",
            file=sys.stderr,
        )
    elif error is None:
        error = "device tree bench failed or timed out"

    epoch = _section_in_subprocess("epoch", on_cpu, timeout_s=300)
    if epoch is not None:
        print(
            f"[bench] fused epoch @{epoch['n']} validators: {epoch['epoch_s']*1e3:.1f} ms",
            file=sys.stderr,
        )

    resident = _section_in_subprocess("resident", on_cpu, timeout_s=480)
    if resident is not None:
        print(
            f"[bench] device-resident epoch+root @{resident['n']} validators: "
            f"{resident['per_epoch_s']*1e3:.2f} ms/epoch "
            f"({resident['epochs']} epochs chained: {resident['total_s']*1e3:.1f} ms)",
            file=sys.stderr,
        )

    bls_res = _section_in_subprocess("bls", on_cpu, timeout_s=480)
    if bls_res is not None:
        print(
            f"[bench] RLC batch verify ({bls_res['n']} aggregates): "
            f"{bls_res['aggs_per_sec']:.1f} aggregates/s "
            f"({bls_res['batch_s']*1e3:.0f} ms/batch, one pairing)",
            file=sys.stderr,
        )

    das_res = _section_in_subprocess("das", on_cpu, timeout_s=480)
    if das_res is not None:
        print(
            f"[bench] DAS field FFT ({das_res['batch']}x{das_res['n']}-point batch): "
            f"{das_res['ffts_per_sec']:.1f} FFTs/s "
            f"({das_res['round_s']*1e3:.1f} ms/batch-round)",
            file=sys.stderr,
        )

    result = {
        "metric": "ssz_merkle_tree_hashes_per_sec",
        "value": round(dev_hps, 0),
        "unit": "hash/s",
        "vs_baseline": round(dev_hps / host_hps, 2) if host_hps else 0.0,
        "method": (
            "chained-dependency timing: K data-dependent iterations inside one "
            "jit, wall-clock/K (sustained, not single-dispatch latency)"
        ),
        "secondary": {
            "host_hashlib_hashes_per_sec": round(host_hps, 0),
            "bls_aggregates_per_sec": (
                round(bls_res["aggs_per_sec"], 1) if bls_res else None
            ),
            "resident_epoch_plus_root_ms": (
                round(resident["per_epoch_s"] * 1e3, 3) if resident else None
            ),
            "fused_epoch_ms": round(epoch["epoch_s"] * 1e3, 3) if epoch else None,
            "das_ffts_per_sec": round(das_res["ffts_per_sec"], 1) if das_res else None,
        },
    }
    if error is not None:
        result["error"] = error
    print(json.dumps(result))


if __name__ == "__main__":
    main()
